"""Evaluation metrics.

Capability parity with reference ``disco_theque/metrics.py`` (snr:9,
delta_snr:25, sd:46, fw_snr:63, seg_snr:131, reverb_ratios:176, fw_sd:211,
ci_wp:283, si_bss:291, si_sdr:342).  Metrics are *evaluation-time* quantities:
the reference computes them in float64 NumPy (``metrics.py:376-377`` asserts
f64) and SDR parity against it is the acceptance bar, so the canonical
implementations here are host-side float64 NumPy as well.  ``si_sdr_jax`` is
the on-device batched variant for use inside jitted eval loops.

The reference's ``seg_snr`` is dead code (imports a nonexistent
``sliding_window`` / ``db_utils.frame_vad``, metrics.py:144-145); here the
evident intent is implemented and working (see ``disco_tpu.core.sigproc`` for
the two helpers).
"""
from __future__ import annotations

import functools

import numpy as np
import jax.numpy as jnp

from disco_tpu.core.sigproc import (
    band_importance,
    sliding_window,
    frame_vad,
    third_octave_filterbank,
)

__all__ = [
    "snr",
    "delta_snr",
    "sd",
    "fw_snr",
    "seg_snr",
    "reverb_ratios",
    "fw_sd",
    "ci_wp",
    "si_bss",
    "si_sdr",
    "si_sdr_jax",
]


def _nz_var(x, sel=None):
    """Variance over nonzero samples (or over ``sel != 0``) — the reference's
    convention for ignoring zero-padded segments (metrics.py:21,59)."""
    x = np.asarray(x)
    m = (x != 0) if sel is None else (np.asarray(sel) != 0)
    return np.var(x[m])


def snr(s, n, db=True):
    """Broadband SNR over nonzero segments (metrics.py:9-22)."""
    r = _nz_var(s) / _nz_var(n)
    return 10 * np.log10(r) if db else r


def delta_snr(s_out, n_out, s_in, n_in, db=True):
    """Output-minus-input SNR (metrics.py:25-43)."""
    d = snr(s_out, n_out, True) - snr(s_in, n_in, True)
    return d if db else 10 ** (d / 10)


def sd(s_out, s_in, db=True):
    """Speech distortion var(s_in)/var(s_out) over nonzero segments
    (metrics.py:46-60)."""
    r = _nz_var(s_in) / _nz_var(s_out)
    return 10 * np.log10(r) if db else r


def _fw_banded(a, b_coefs, a_coefs, sel_vad=None):
    """Per-band dB power of ``a`` filtered through each bandpass filter."""
    import scipy.signal

    out = np.zeros(b_coefs.shape[0])
    for i in range(b_coefs.shape[0]):
        f = scipy.signal.lfilter(b_coefs[i], a_coefs[i], a, axis=0)
        out[i] = 10 * np.log10(_nz_var(f, sel=sel_vad if sel_vad is not None else f))
    return out


@functools.lru_cache(maxsize=8)
def _band_design(fs, order=4):
    """Cached (I, F, b, a) band-importance weights + third-octave Butterworth
    coefficients — pure functions of (fs, order), but the scipy filter DESIGN
    (butter → lp2bp_zpk → poly per band) was measured re-running on every
    fw_snr/fw_sd call, ~10% of per-RIR scoring cost.  Arrays are returned
    read-only (shared across calls)."""
    I, F = band_importance(fs)
    b, a = third_octave_filterbank(F, fs, order=order)
    for arr in (I, F, b, a):
        np.asarray(arr).setflags(write=False)
    return I, F, b, a


def fw_snr(s, n, fs, vad_tar=None, vad_noi=None, clipping=1, db=True):
    """Frequency-weighted (band-importance) SNR, ANSI/Pavlovic weights
    (metrics.py:63-128, duplicate sigproc_utils.py:120-190).

    Returns (per-band weighted SNR, scalar mean, center frequencies).
    """
    I, F, b, a = _band_design(fs)
    F = F.copy()  # callers historically received a writable array
    s_p = _fw_banded(s, b, a, vad_tar)
    n_p = _fw_banded(n, b, a, vad_noi)
    snr_var = s_p - n_p
    if clipping:
        snr_var = np.clip(snr_var, -15, 25)
    fqwt = I / np.sum(I) * snr_var
    mean = np.sum(fqwt)
    if not db:
        fqwt, mean = 10 ** (fqwt / 10), 10 ** (mean / 10)
    return fqwt, mean, F


def fw_sd(s_out, s_in, fs, clipping=1, db=True):
    """Frequency-weighted speech distortion (metrics.py:211-279): per-band
    in-minus-out dB power, clipped to [0, 25], band-importance-averaged."""
    I, F, b, a = _band_design(fs)
    F = F.copy()  # callers historically received a writable array
    out_p = _fw_banded(s_out, b, a)
    in_p = _fw_banded(s_in, b, a)
    sd_var = in_p - out_p
    if clipping:
        sd_var = np.clip(sd_var, 0, 25)
    fqwt = I / np.sum(I) * sd_var
    mean = np.sum(fqwt)
    if not db:
        fqwt, mean = 10 ** (fqwt / 10), 10 ** (mean / 10)
    return fqwt, mean, F


def seg_snr(s, n, win_len, win_hop, vad=None, axis=-1):
    """Segmental SNR in dB, VAD-gated, per-window SNR clipped to [-15, 25]
    (working implementation of the intent of metrics.py:131-173)."""
    eps = np.finfo(np.float64).eps
    s = np.asarray(s, np.float64)
    n = np.asarray(n, np.float64)
    if len(s) != len(n):
        pad_s = max(len(n) - len(s), 0)
        pad_n = max(len(s) - len(n), 0)
        s = np.pad(s, (0, pad_s), mode="reflect")
        n = np.pad(n, (0, pad_n), mode="reflect")
    sw = sliding_window(s, win_len, win_hop, axis=axis)
    nw = sliding_window(n, win_len, win_hop, axis=axis)
    sw_var = np.maximum(np.var(sw, axis=-1), eps)
    nw_var = np.maximum(np.var(nw, axis=-1), eps)
    if vad is None:
        batch_vad = np.ones(sw_var.shape)
    else:
        batch_vad = frame_vad(vad, win_len, win_hop)[: sw_var.shape[0]]
    per_win = batch_vad * np.clip(10 * np.log10(sw_var / nw_var), -15, 25)
    return np.sum(per_win) / np.sum(batch_vad)


def reverb_ratios(x, rir, reverb_start=20, fs=16000):
    """Direct-to-reverberant and signal-to-reverberation ratios in dB
    (metrics.py:176-208): split the RIR at ``argmax + reverb_start`` ms."""
    rir = np.asarray(rir)
    i_peak = int(np.argmax(rir))
    n_d = int(1e-3 * reverb_start * fs)
    h_d, h_r = rir[: i_peak + n_d], rir[i_peak + n_d :]
    drr = 10 * np.log10(np.sum(h_d**2) / np.sum(h_r**2))
    x_d = np.convolve(x, h_d)
    x_r = np.convolve(x, h_r)
    srr = 10 * np.log10(np.sum(x_d**2) / np.sum(x_r**2))
    return drr, srr


def ci_wp(x, axis=0):
    """95% normal-approximation confidence half-interval (metrics.py:283-288)."""
    return 1.96 * np.nanstd(x, axis=axis) / np.sqrt(np.shape(x)[axis])


def si_bss(estimated_signal, targets, j, scaling=True):
    """Scale-invariant SDR / SIR / SAR of ``estimated_signal`` against source
    ``j`` of ``targets`` (n_samples, n_src) — Le Roux et al. 2019 decomposition
    (metrics.py:291-339)."""
    targets = np.asarray(targets, np.float64)
    est = np.asarray(estimated_signal, np.float64)
    Rss = targets.T @ targets
    this_s = targets[:, j]
    a = (this_s @ est) / Rss[j, j] if scaling else 1.0
    e_true = a * this_s
    e_res = est - e_true
    Sss = np.sum(e_true**2)
    b = np.linalg.solve(Rss, targets.T @ e_res)
    e_interf = targets @ b
    e_artif = e_res - e_interf
    sisdr = 10 * np.log10(Sss / np.sum(e_res**2))
    sisir = 10 * np.log10(Sss / np.sum(e_interf**2))
    sisar = 10 * np.log10(Sss / np.sum(e_artif**2))
    return sisdr, sisir, sisar


def si_sdr(reference, estimation):
    """Scale-invariant SDR, float64, batched over leading axes
    (metrics.py:342-392; doctest values preserved).

    >>> rng = np.random.RandomState(0)
    >>> ref = rng.randn(100)
    >>> bool(np.isinf(si_sdr(ref, ref)))
    True
    >>> round(float(si_sdr(ref, np.flip(ref))), 12)
    -25.127672346461
    >>> round(float(si_sdr(ref, ref + np.flip(ref))), 12)
    0.481070445786
    >>> round(float(si_sdr(ref, ref + 0.5)), 12)
    6.370460603258
    """
    estimation, reference = np.broadcast_arrays(
        np.asarray(estimation, np.float64), np.asarray(reference, np.float64)
    )
    ref_energy = np.sum(reference**2, axis=-1, keepdims=True)
    alpha = np.sum(reference * estimation, axis=-1, keepdims=True) / ref_energy
    projection = alpha * reference
    noise = estimation - projection
    # A perfect estimate has zero residual: the ratio is +inf by design (see
    # the doctest), so the final divide/log are silenced (this also covers
    # the -inf of a zero projection) — an all-zero reference still warns on
    # the alpha division above.
    with np.errstate(divide="ignore"):
        ratio = np.sum(projection**2, axis=-1) / np.sum(noise**2, axis=-1)
        return 10 * np.log10(ratio)


def si_sdr_jax(reference: jnp.ndarray, estimation: jnp.ndarray) -> jnp.ndarray:
    """On-device SI-SDR for jitted eval loops — same math as :func:`si_sdr`,
    batched over leading axes, in the ambient JAX precision."""
    ref_energy = jnp.sum(reference**2, axis=-1, keepdims=True)
    alpha = jnp.sum(reference * estimation, axis=-1, keepdims=True) / ref_energy
    projection = alpha * reference
    noise = estimation - projection
    ratio = jnp.sum(projection**2, axis=-1) / jnp.sum(noise**2, axis=-1)
    return 10.0 * jnp.log10(ratio)


# --------------------------------------------------------------------- STOI
# The reference evaluates intelligibility with pystoi (tango.py:569-578).
# pystoi is a CPython/NumPy package; here the algorithm (Taal et al., "An
# Algorithm for Intelligibility Prediction of Time-Frequency Weighted Noisy
# Speech", IEEE TASLP 2011) is implemented natively so the framework owns
# the capability without the undeclared dependency.

_STOI_FS = 10000  # internal rate
_STOI_NFFT = 512
_STOI_WIN = 256
_STOI_HOP = 128
_STOI_NBANDS = 15
_STOI_MINFREQ = 150.0
_STOI_SEG = 30  # analysis segment: 30 frames = 384 ms
_STOI_BETA = -15.0  # clipping SDR bound, dB
_STOI_DYN = 40.0  # silent-frame energy range, dB


def _stoi_third_octaves(fs=_STOI_FS, nfft=_STOI_NFFT, n_bands=_STOI_NBANDS, min_freq=_STOI_MINFREQ):
    """Rectangular one-third-octave band matrix (n_bands, nfft//2+1)."""
    f = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    k = np.arange(n_bands, dtype=np.float64)
    cf = 2.0 ** (k / 3.0) * min_freq
    lo = cf * 2.0 ** (-1.0 / 6.0)
    hi = cf * 2.0 ** (1.0 / 6.0)
    obm = np.zeros((n_bands, len(f)))
    for i in range(n_bands):
        lo_i = int(np.argmin((f - lo[i]) ** 2))
        hi_i = int(np.argmin((f - hi[i]) ** 2))
        obm[i, lo_i:hi_i] = 1.0
    return obm


def _stoi_frames(x, win=_STOI_WIN, hop=_STOI_HOP):
    if len(x) < win:  # shorter than one frame: no frames (stoi -> nan)
        return np.zeros((0, win))
    n = 1 + (len(x) - win) // hop
    idx = np.arange(win)[None, :] + hop * np.arange(n)[:, None]
    return x[idx] * np.hanning(win + 2)[1:-1]


def _remove_silent_frames(x, y, dyn_range=_STOI_DYN, win=_STOI_WIN, hop=_STOI_HOP):
    """Drop frames of x whose energy is > dyn_range dB below the loudest
    frame; apply the same selection to y; overlap-add back to time."""
    xf, yf = _stoi_frames(x, win, hop), _stoi_frames(y, win, hop)
    if not len(xf):
        return np.zeros(0), np.zeros(0)
    energies = 20 * np.log10(np.linalg.norm(xf, axis=1) + np.finfo(np.float64).eps)
    keep = energies > (np.max(energies) - dyn_range)
    xf, yf = xf[keep], yf[keep]
    n_kept = xf.shape[0]
    out_len = (n_kept - 1) * hop + win if n_kept else 0
    # vectorized overlap-add: scatter every (frame, tap) into its output
    # position in one ufunc pass (the per-frame Python loop was a measured
    # corpus-scoring hot spot)
    idx = (hop * np.arange(n_kept)[:, None] + np.arange(win)[None, :]).ravel()
    w = np.hanning(win + 2)[1:-1]
    xs, ys, wsum = np.zeros(out_len), np.zeros(out_len), np.zeros(out_len)
    np.add.at(xs, idx, xf.ravel())
    np.add.at(ys, idx, yf.ravel())
    np.add.at(wsum, idx, np.broadcast_to(w, (n_kept, win)).ravel())
    wsum[wsum == 0] = 1.0
    return xs / wsum, ys / wsum


def _resample_to_10k(x, fs):
    from scipy.signal import resample_poly

    if fs == _STOI_FS:
        return np.asarray(x, np.float64)
    g = np.gcd(int(fs), _STOI_FS)
    return resample_poly(np.asarray(x, np.float64), _STOI_FS // g, int(fs) // g)


def stoi(x, y, fs_sig, extended: bool = False):
    """Short-Time Objective Intelligibility of degraded signal ``y`` against
    clean ``x`` (Taal et al. 2011), in [~0, 1].  Drop-in for
    ``pystoi.stoi`` as the reference uses it (tango.py:569-574)."""
    x, y = np.asarray(x, np.float64), np.asarray(y, np.float64)
    assert x.shape == y.shape, "x and y must have the same length"
    x, y = _resample_to_10k(x, fs_sig), _resample_to_10k(y, fs_sig)
    x, y = _remove_silent_frames(x, y)
    if len(x) < _STOI_WIN:
        return np.nan

    xf, yf = _stoi_frames(x), _stoi_frames(y)
    X = np.fft.rfft(xf, _STOI_NFFT, axis=1)
    Y = np.fft.rfft(yf, _STOI_NFFT, axis=1)
    obm = _stoi_third_octaves()
    # (frames, bands) band magnitudes
    Xb = np.sqrt(np.maximum(np.abs(X) ** 2 @ obm.T, 0.0)).T
    Yb = np.sqrt(np.maximum(np.abs(Y) ** 2 @ obm.T, 0.0)).T
    n_frames = Xb.shape[1]
    if n_frames < _STOI_SEG:
        return np.nan

    eps = np.finfo(np.float64).eps
    if extended:
        d_sum, n_seg = 0.0, 0
        for m in range(_STOI_SEG, n_frames + 1):
            Xs = Xb[:, m - _STOI_SEG : m]
            Ys = Yb[:, m - _STOI_SEG : m]
            Xs = (Xs - Xs.mean(axis=1, keepdims=True)) / (np.linalg.norm(Xs - Xs.mean(axis=1, keepdims=True), axis=1, keepdims=True) + eps)
            Ys = (Ys - Ys.mean(axis=1, keepdims=True)) / (np.linalg.norm(Ys - Ys.mean(axis=1, keepdims=True), axis=1, keepdims=True) + eps)
            Xs = (Xs - Xs.mean(axis=0, keepdims=True)) / (np.linalg.norm(Xs - Xs.mean(axis=0, keepdims=True), axis=0, keepdims=True) + eps)
            Ys = (Ys - Ys.mean(axis=0, keepdims=True)) / (np.linalg.norm(Ys - Ys.mean(axis=0, keepdims=True), axis=0, keepdims=True) + eps)
            d_sum += np.sum(Xs * Ys) / _STOI_SEG
            n_seg += 1
        return d_sum / n_seg

    d_sum, n_seg = _stoi_corr_sum(Xb, Yb)
    return d_sum / (n_seg * _STOI_NBANDS)


def _stoi_corr_sum(Xb, Yb):
    """Sum over sliding 30-frame segments of the per-band clipped envelope
    correlations (the inner loop of Taal et al. 2011, eqs. 4-6), given the
    (bands, frames) third-octave envelope matrices.

    Factored out so the correlation machinery can be anchored analytically
    on hand-built envelopes (tests/test_analytic_anchors.py) independent of
    the framing/FFT front end.  Returns (d_sum, n_segments)."""
    eps = np.finfo(np.float64).eps
    n_frames = Xb.shape[1]
    beta_clip = 10.0 ** (-_STOI_BETA / 20.0)
    d_sum, n_seg = 0.0, 0
    for m in range(_STOI_SEG, n_frames + 1):
        Xs = Xb[:, m - _STOI_SEG : m]
        Ys = Yb[:, m - _STOI_SEG : m]
        alpha = np.linalg.norm(Xs, axis=1, keepdims=True) / (np.linalg.norm(Ys, axis=1, keepdims=True) + eps)
        Yp = np.minimum(Ys * alpha, Xs * (1.0 + beta_clip))
        xm = Xs - Xs.mean(axis=1, keepdims=True)
        ym = Yp - Yp.mean(axis=1, keepdims=True)
        corr = np.sum(xm * ym, axis=1) / (np.linalg.norm(xm, axis=1) * np.linalg.norm(ym, axis=1) + eps)
        d_sum += corr.sum()
        n_seg += 1
    return d_sum, n_seg
