"""Scalar / array math utilities.

Capability parity with the reference's ``disco_theque/math_utils.py`` (see
/root/reference/disco_theque/math_utils.py:4-233), re-expressed as jit-friendly
JAX functions.  Everything here is shape-polymorphic, dtype-preserving and safe
to call under ``jax.jit`` / ``jax.vmap`` (the Welford accumulator is a pytree
of arrays updated functionally).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

# float64 machine epsilon — the reference's ``sys.float_info.epsilon``
# (sigproc_utils.py:74, internal_formulas.py:6); shared across the package.
FLOAT64_EPS = 2.220446049250313e-16


def floor_to_multiple(num, div):
    """Largest multiple of ``div`` that is <= ``num`` (math_utils.py:4-21)."""
    return int(num - (num % div))


def round_to_base(x, base=1):
    """Round ``x`` to the nearest multiple of ``base`` (math_utils.py:24-43)."""
    return base * jnp.round(jnp.asarray(x) / base)


def db2lin(x, exp=1):
    """dB -> linear. ``exp=1`` for power, ``exp=2`` for magnitude (math_utils.py:46-62)."""
    return 10.0 ** (jnp.asarray(x) / (10.0 * exp))


def lin2db(x):
    """Linear power -> dB (math_utils.py:65-75)."""
    return 10.0 * jnp.log10(jnp.asarray(x))


def cart2pol(x, y):
    """Cartesian -> polar, angle in radians (math_utils.py:78-97).

    XLA's ``arctan2`` returns NaN when BOTH arguments are f32 denormals
    (numpy gives the true angle); such points are numerically at the
    origin, so the angle falls back to the ``arctan2(0, 0) = 0``
    convention instead of poisoning downstream geometry."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    phi = jnp.arctan2(y, x)
    return jnp.sqrt(x**2 + y**2), jnp.where(jnp.isnan(phi), 0.0, phi)


def pol2cart(r, theta):
    """Polar -> cartesian (math_utils.py:100-115)."""
    r = jnp.asarray(r)
    theta = jnp.asarray(theta)
    return r * jnp.cos(theta), r * jnp.sin(theta)


def my_mse(x, y):
    """Mean of squared differences, reduced over the last axis then the rest
    (math_utils.py:118-131)."""
    return jnp.mean(jnp.mean((jnp.asarray(x) - jnp.asarray(y)) ** 2, axis=-1))


def next_pow_2(x):
    """Smallest power of two >= ``x`` (math_utils.py:155-165). Host-side int."""
    return int(2 ** int(np.ceil(np.log2(x))))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class WelfordState:
    """Functional state for Welford's online mean/variance over 2-D data
    (feature_dim x n_frames), the streaming-statistics capability of
    math_utils.py:168-232."""

    mean: jnp.ndarray
    m2: jnp.ndarray
    count: jnp.ndarray

    def tree_flatten(self):
        return (self.mean, self.m2, self.count), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def std(self):
        return jnp.sqrt(self.m2 / jnp.maximum(self.count, 1))


def welford_init(feature_dim: int, dtype=jnp.float32) -> WelfordState:
    """Zeroed Welford running-stats state for ``feature_dim`` features."""
    return WelfordState(
        mean=jnp.zeros(feature_dim, dtype),
        m2=jnp.zeros(feature_dim, dtype),
        count=jnp.zeros((), jnp.int32),
    )


@jax.jit
def welford_update(state: WelfordState, data: jnp.ndarray) -> WelfordState:
    """Vectorized chunk update (the ``quick_update`` semantics of
    math_utils.py:214-232): one pass over a (feature_dim x n_frames) block."""
    delta = data - state.mean[:, None]
    count = state.count + data.shape[-1]
    mean = state.mean + delta.sum(axis=-1) / count
    delta2 = data - mean[:, None]
    m2 = state.m2 + jnp.sum(delta2 * delta, axis=-1)
    return WelfordState(mean=mean, m2=m2, count=count)


class WelfordsOnlineAlgorithm:
    """Stateful convenience wrapper around the functional Welford kernel,
    exposing the reference's attribute surface (mean/std/m2/count)."""

    def __init__(self, feature_dim: int, dtype=jnp.float32):
        self.feature_dim = feature_dim
        self._state = welford_init(feature_dim, dtype)

    def update_stats(self, data):
        self.quick_update(data)

    def quick_update(self, data):
        data = jnp.asarray(data)
        assert data.shape[0] == self.feature_dim, (
            f"`data` should have {self.feature_dim} features, got {data.shape[0]}"
        )
        self._state = welford_update(self._state, data)

    @property
    def mean(self):
        return self._state.mean

    @property
    def std(self):
        return self._state.std

    @property
    def m2(self):
        return self._state.m2

    @property
    def count(self):
        return int(self._state.count)
