"""Time-frequency masks and the oracle power VAD.

Capability parity with reference ``disco_theque/sigproc_utils.py:12-86``
(``vad_oracle_batch``, ``tf_mask``) and its duplicate ``dnn/utils.py:44-71``,
re-expressed as loop-free jitted JAX ops so a whole (rooms, nodes, channels)
batch of spectrograms is masked in one fused kernel.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from disco_tpu.core.mathx import db2lin, FLOAT64_EPS as _EPS


@partial(jax.jit, static_argnames=("mask_type",))
def tf_mask(s: jnp.ndarray, n: jnp.ndarray, mask_type: str = "irm1", bin_thr: float = 0.0):
    """Ideal TF mask from target/noise spectrograms (sigproc_utils.py:58-86).

    ``mask_type`` is 'irmX' (Wiener-like ratio mask), 'ibmX' (binary) or
    'iamX' (amplitude mask), X the integer power applied to the magnitude
    ratio.  Shapes broadcast; output matches ``s``.
    """
    power = int(mask_type[-1])
    family = mask_type[:-1]
    if family == "irm":
        xi = (jnp.abs(s) / jnp.maximum(jnp.abs(n), _EPS)) ** power
        return xi / (1.0 + xi)
    if family == "ibm":
        xi = (jnp.abs(s) / jnp.maximum(jnp.abs(n), _EPS)) ** power
        return (xi >= db2lin(bin_thr)).astype(s.real.dtype)
    if family == "iam":
        # eps floor: all-silent bins (|s+n| = 0, e.g. zero-padded frames)
        # must yield 0, not 0/0 = NaN
        return (jnp.abs(s) / jnp.maximum(jnp.abs(s + n), _EPS)) ** power
    raise ValueError('Unknown mask type. Should be "irmX", "ibmX" or "iamX"')


@partial(jax.jit, static_argnames=("mask_type",))
def tf_mask_mag(mag_s: jnp.ndarray, mag_n: jnp.ndarray, mask_type: str = "irm1",
                bin_thr: float = 0.0):
    """:func:`tf_mask` from MAGNITUDE spectrograms — the consumer of the
    fused STFT's magnitude output (``ops.stft_ops.stft_with_mag``), so the
    irm/ibm mask families never recompute ``abs`` over the complex spectra
    (same formulas as sigproc_utils.py:58-86; identical bits when
    ``mag == abs(spec)``).  The iam family needs ``|s + n|`` — not
    derivable from the two magnitudes — and keeps the complex entry point.
    """
    power = int(mask_type[-1])
    family = mask_type[:-1]
    if family == "irm":
        xi = (mag_s / jnp.maximum(mag_n, _EPS)) ** power
        return xi / (1.0 + xi)
    if family == "ibm":
        xi = (mag_s / jnp.maximum(mag_n, _EPS)) ** power
        return (xi >= db2lin(bin_thr)).astype(mag_s.dtype)
    raise ValueError(
        'tf_mask_mag supports "irmX" and "ibmX" (iam needs the complex sum '
        "— use tf_mask)"
    )


@partial(jax.jit, static_argnames=("win_len", "win_hop", "rat"))
def vad_oracle_batch(
    x: jnp.ndarray,
    win_len: int = 512,
    win_hop: int = 256,
    thr: float = 0.001,
    rat: int = 2,
) -> jnp.ndarray:
    """Oracle power-threshold VAD (sigproc_utils.py:12-55).

    A window is voice-active when more than ``len(window)/rat`` of its samples
    have instantaneous power above ``thr * q99(power)``; active windows paint
    1s over the samples they cover (overlapping windows OR together).

    Args:
      x: waveform, shape (length,).

    Returns:
      float32 0/1 vector, same length as ``x``.
    """
    x = jnp.asarray(x)
    length = x.shape[-1]
    x2 = jnp.abs((x - jnp.mean(x)) ** 2)
    thr_ = thr * jnp.quantile(x2, 0.99)

    n_win = -(-(length - win_len) // win_hop) + 1  # ceil((L - w)/h) + 1
    if n_win <= 0:
        # Shorter than one window: the reference evaluates zero windows and
        # returns an all-zero VAD (sigproc_utils.py:48).
        return jnp.zeros(length, jnp.float32)
    starts = jnp.arange(n_win) * win_hop
    offs = jnp.arange(win_len)
    idx = starts[:, None] + offs[None, :]  # (n_win, win_len)
    valid = idx < length
    idx_c = jnp.minimum(idx, length - 1)
    above = (x2[idx_c] > thr_) & valid
    n_above = jnp.sum(above, axis=-1)
    n_samples = jnp.sum(valid, axis=-1)
    active = n_above >= (n_samples // rat)  # int(N/rat) of the reference

    # Scatter-OR each active window back onto its samples.
    vad = jnp.zeros(length, jnp.float32)
    contrib = (active[:, None] & valid).astype(jnp.float32)
    vad = vad.at[idx_c.reshape(-1)].max(contrib.reshape(-1))
    return vad


def vad_to_mask(vad: jnp.ndarray, n_freq: int, n_frames: int, hop: int = 256) -> jnp.ndarray:
    """Spread a sample-level VAD across frequencies as a mask-like STFT matrix
    (the 'ivad' branch of reference tango.py:216-221: subsample every ``hop``
    samples, tile over ``n_freq`` rows, zero-pad trailing frames)."""
    v = vad[::hop]
    v = jnp.pad(v, (0, max(0, n_frames - v.shape[0])))[:n_frames]
    return jnp.tile(v[None, :], (n_freq, 1))
