"""Seeded fault injection at the z-exchange seam.

:func:`plan_faults` expands a declarative :class:`~disco_tpu.fault.spec.FaultSpec`
into a concrete :class:`FaultPlan` for one (K nodes, B blocks) run: a
``(K, B)`` per-source availability matrix, per-node NaN-corruption flags,
and a host-side list of every injected fault (the ``fault`` events that
:meth:`FaultPlan.record` emits through ``disco_tpu.obs``).

The plan is what the pipeline actually consumes:

* offline ``tango``: ``plan.avail_offline`` (``(K,)`` — a stream counts as
  available only if delivered in *every* block, since the offline
  frame-mean covariance spans the whole clip) and ``plan.z_nan`` (real NaN
  injection, detected and excluded by the finiteness guard at the
  exchange).
* ``streaming_tango``: ``plan.avail_streaming`` (``(K, B)`` — lost/stale
  blocks are bridged by the last-good-z hold policy; NaN corruption folds
  into unavailability because a single NaN would poison the recursive
  covariances forever).

Determinism contract (tests/test_fault.py): all randomness comes from
``np.random.default_rng(spec.seed)`` with draws in a fixed order —
dropout ``(K,)``, link loss ``(K, B)``, stale ``(K, B)``, nan ``(K,)`` —
drawn unconditionally so toggling one probability never reshuffles the
others' streams.

No reference counterpart: the reference assumes a perfect in-process
z-exchange; fault injection exists only in this rebuild.
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A spec expanded against concrete (K, B) dimensions.  All arrays are
    host numpy — the plan is built before any device work and is what the
    telemetry describes."""

    spec: "FaultSpec"
    n_nodes: int
    n_blocks: int
    avail: np.ndarray  # (K, B) float32: 1 = z_k delivered in block b
    z_nan: np.ndarray  # (K,) bool: NaN-corrupt node k's exchanged streams
    faults: tuple[dict, ...]  # host-side description of every injected fault

    @property
    def avail_offline(self) -> np.ndarray:
        """(K,) availability for the offline pipeline: the frame-mean
        covariances span the whole clip, so a partially-delivered stream is
        conservatively excluded (available only if delivered every block)."""
        return self.avail.min(axis=1)

    @property
    def avail_streaming(self) -> np.ndarray:
        """(K, B) availability for the streaming pipeline, with NaN-corrupted
        nodes folded in as unavailable (the hold policy bridges them; real
        NaNs would poison the recursive covariance state forever)."""
        return self.avail * (~self.z_nan[:, None]).astype(self.avail.dtype)

    def any_fault(self) -> bool:
        return bool(self.faults)

    def n_unavailable_offline(self) -> int:
        return int((self.avail_offline < 1.0).sum())

    def record(self, mode: str | None = None) -> None:
        """Emit one ``fault`` event per injected fault plus the injection
        counters through ``disco_tpu.obs`` (no-op while recording is
        disabled, like every obs producer)."""
        from disco_tpu.obs import events as obs_events
        from disco_tpu.obs.metrics import REGISTRY

        REGISTRY.counter("faults_injected").inc(len(self.faults))
        n_lost = int((self.avail < 1.0).sum())
        if n_lost:
            REGISTRY.counter("fault_blocks_lost").inc(n_lost)
        if not obs_events.enabled():
            return
        for f in self.faults:
            attrs = {k: v for k, v in f.items() if k != "fault"}
            if mode is not None:
                attrs["mode"] = mode
            obs_events.record("fault", stage="inject", fault=f["fault"], **attrs)


def plan_faults(spec, n_nodes: int, n_blocks: int = 1) -> FaultPlan:
    """Expand ``spec`` into a :class:`FaultPlan` for ``n_nodes`` sources and
    ``n_blocks`` exchange blocks (offline callers pass ``n_blocks=1``)."""
    from disco_tpu.fault.spec import load_fault_spec

    spec = load_fault_spec(spec)
    spec.validate_for(n_nodes)
    K, B = int(n_nodes), max(int(n_blocks), 1)
    rng = np.random.default_rng(spec.seed)
    avail = np.ones((K, B), np.float32)
    z_nan = np.zeros(K, bool)
    faults: list[dict] = []

    # Fixed draw order (module docstring): dropout, link loss, stale, nan.
    drop_draw = rng.random(K)
    link_draw = rng.random((K, B))
    stale_draw = rng.random((K, B))
    nan_draw = rng.random(K)

    dropped = set(spec.node_dropout)
    for k in range(K):
        if k not in dropped and drop_draw[k] < spec.dropout_prob:
            dropped.add(k)
    for k in sorted(dropped):
        avail[k, :] = 0.0
        faults.append({"fault": "node_dropout", "node": k})

    link_nodes = set(spec.link_loss_nodes) if spec.link_loss_nodes is not None else set(range(K))
    for k in range(K):
        if k in dropped:
            continue
        lost = np.zeros(B, bool)
        if k in link_nodes and spec.link_loss_prob:
            lost |= link_draw[k] < spec.link_loss_prob
        stale = stale_draw[k] < spec.stale_prob if spec.stale_prob else np.zeros(B, bool)
        stale &= ~lost
        if lost.any():
            avail[k, lost] = 0.0
            faults.append(
                {"fault": "link_loss", "node": k, "n_blocks": int(lost.sum()),
                 "blocks": np.flatnonzero(lost).tolist()}
            )
        if stale.any():
            avail[k, stale] = 0.0
            faults.append(
                {"fault": "stale_delivery", "node": k, "n_blocks": int(stale.sum()),
                 "blocks": np.flatnonzero(stale).tolist()}
            )

    nan_nodes = set(spec.nan_z)
    for k in range(K):
        if k not in nan_nodes and nan_draw[k] < spec.nan_prob:
            nan_nodes.add(k)
    for k in sorted(nan_nodes):
        if k in dropped:
            continue  # a dropped node's z never arrives; nothing to corrupt
        z_nan[k] = True
        faults.append({"fault": "nan_z", "node": k})

    return FaultPlan(
        spec=spec, n_nodes=K, n_blocks=B, avail=avail, z_nan=z_nan, faults=tuple(faults)
    )
