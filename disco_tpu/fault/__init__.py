"""disco_tpu.fault — declarative fault injection and degraded-mode support.

The DANSE-style z-exchange is the pipeline's only network seam: node k's
step-2 MWF consumes the K-1 compressed streams of every other node.  This
package makes that seam fault-tolerant end-to-end:

* :mod:`disco_tpu.fault.spec`   — :class:`FaultSpec`, the declarative,
  seeded fault scenario (node dropout, per-block link loss, stale delivery,
  NaN-corrupted z) loadable from YAML/JSON via ``--fault-spec``.
* :mod:`disco_tpu.fault.inject` — :func:`plan_faults` expands a spec into a
  concrete :class:`FaultPlan` (``(K, B)`` availability + NaN flags + the
  ``fault`` telemetry events).
* :mod:`disco_tpu.fault.check`  — the ``make fault-check`` CPU smoke: inject
  a dropout and a NaN z, assert finite outputs and the expected obs events.

Consumers: ``enhance/tango.py`` (``z_mask``/``z_nan`` channel masking with
covariance regularization, degrading to local-only beamforming), ``enhance/
streaming.py`` (``(K, B)`` availability + last-good-z hold),
``disco_tpu.parallel`` (the mask rides the z-exchange all_gather),
``enhance/driver.py`` / ``cli/tango.py`` (``fault_spec`` wiring), and
``utils/resilience.py`` (bounded retry around the flaky-tunnel side).
"""
from disco_tpu.fault.inject import FaultPlan, plan_faults
from disco_tpu.fault.spec import FaultSpec, load_fault_spec

__all__ = ["FaultPlan", "FaultSpec", "load_fault_spec", "plan_faults"]
