"""Declarative, seeded fault specifications for the TANGO network seams.

The reference pipeline — and our port until this module — assumes every
node's compressed signal ``z_k`` arrives intact at every other node
(``tango_step2`` hard-concatenates all K-1 exchanged streams).  A real
ad-hoc wireless acoustic sensor network loses nodes, drops links for a few
blocks, and occasionally delivers corrupted or stale packets.  A
:class:`FaultSpec` names those scenarios declaratively; the injector
(``disco_tpu.fault.inject``) turns one into a concrete, seeded
:class:`~disco_tpu.fault.inject.FaultPlan` that the pipeline consumes as a
``(K,)``/``(K, B)`` availability mask plus per-node NaN-corruption flags.

No reference counterpart: the reference has no fault model at all (its
"network" is ``np.concatenate``, tango.py:142-155).  The spec format is the
one documented in ``doc/source/robustness.rst``.

Fault kinds:

* ``node_dropout`` / ``dropout_prob`` — a node's z never arrives anywhere
  (listed node ids, plus an optional per-node Bernoulli).
* ``link_loss_prob`` (optionally restricted to ``link_loss_nodes``) — a
  node's z is lost for individual blocks of ``update_every`` frames: the
  per-(node, block) Bernoulli of intermittent radio loss.
* ``stale_prob`` — a block's z arrives too late to use; the streaming
  consumer reuses the previous block's z (mechanically identical to a
  per-block loss under the last-good-z hold policy, tracked as its own
  fault kind in telemetry).
* ``nan_z`` / ``nan_prob`` — a node's exchanged streams are corrupted to
  NaN.  The offline pipeline *injects real NaNs* and relies on the
  finiteness guard at the z-exchange seam to detect and exclude them; the
  streaming pipeline (whose recursive covariances a single NaN would poison
  forever) realizes corruption as unavailability.

Every random draw comes from ``np.random.default_rng(seed)`` in a fixed
documented order, so the same (spec, seed, K, B) always yields the same
plan — the determinism contract pinned by tests/test_fault.py.
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path

_FAULT_FIELDS = (
    "seed",
    "node_dropout",
    "dropout_prob",
    "link_loss_prob",
    "link_loss_nodes",
    "stale_prob",
    "nan_z",
    "nan_prob",
)


def _as_node_tuple(v, field: str) -> tuple[int, ...]:
    if v is None:
        return ()
    # bool is an int subclass: 'node_dropout: true' would otherwise silently
    # become node id 1 — reject it as the malformed spec it is
    if isinstance(v, bool):
        raise ValueError(f"fault spec {field!r}: expected a list of node ids, got {v!r}")
    if isinstance(v, (int,)):
        return (int(v),)
    try:
        if any(isinstance(x, bool) for x in v):
            raise ValueError
        nodes = tuple(int(x) for x in v)
    except (TypeError, ValueError):
        raise ValueError(f"fault spec {field!r}: expected a list of node ids, got {v!r}") from None
    if any(n < 0 for n in nodes):
        raise ValueError(f"fault spec {field!r}: node ids must be >= 0, got {nodes}")
    return nodes


def _as_prob(v, field: str) -> float:
    try:
        p = float(v)
    except (TypeError, ValueError):
        raise ValueError(f"fault spec {field!r}: expected a probability, got {v!r}") from None
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"fault spec {field!r}: probability must be in [0, 1], got {p}")
    return p


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One declarative fault scenario (see module docstring for semantics).

    Immutable and hashable so it can ride through functools caches and be
    embedded in run manifests; ``to_dict``/``from_dict`` round-trip the
    YAML/JSON file format consumed by ``--fault-spec``.
    """

    seed: int = 0
    node_dropout: tuple[int, ...] = ()
    dropout_prob: float = 0.0
    link_loss_prob: float = 0.0
    link_loss_nodes: tuple[int, ...] | None = None
    stale_prob: float = 0.0
    nan_z: tuple[int, ...] = ()
    nan_prob: float = 0.0

    def __post_init__(self):
        try:
            object.__setattr__(self, "seed", int(self.seed))
        except (TypeError, ValueError):
            raise ValueError(
                f"fault spec 'seed': expected an integer, got {self.seed!r}"
            ) from None
        object.__setattr__(self, "node_dropout", _as_node_tuple(self.node_dropout, "node_dropout"))
        object.__setattr__(self, "dropout_prob", _as_prob(self.dropout_prob, "dropout_prob"))
        object.__setattr__(self, "link_loss_prob", _as_prob(self.link_loss_prob, "link_loss_prob"))
        if self.link_loss_nodes is not None:
            object.__setattr__(
                self, "link_loss_nodes", _as_node_tuple(self.link_loss_nodes, "link_loss_nodes")
            )
        object.__setattr__(self, "stale_prob", _as_prob(self.stale_prob, "stale_prob"))
        object.__setattr__(self, "nan_z", _as_node_tuple(self.nan_z, "nan_z"))
        object.__setattr__(self, "nan_prob", _as_prob(self.nan_prob, "nan_prob"))

    def any_fault(self) -> bool:
        """True when this spec can inject anything at all (an all-defaults
        spec is the explicit 'no faults' scenario)."""
        return bool(
            self.node_dropout
            or self.nan_z
            or self.dropout_prob
            or self.link_loss_prob
            or self.stale_prob
            or self.nan_prob
        )

    def validate_for(self, n_nodes: int) -> None:
        """Raise ``ValueError`` if the spec names nodes outside ``[0, K)``."""
        for field in ("node_dropout", "nan_z", "link_loss_nodes"):
            nodes = getattr(self, field) or ()
            bad = [n for n in nodes if n >= n_nodes]
            if bad:
                raise ValueError(
                    f"fault spec {field!r} names node(s) {bad} but the array has "
                    f"{n_nodes} nodes (ids 0..{n_nodes - 1})"
                )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["node_dropout"] = list(d["node_dropout"])
        d["nan_z"] = list(d["nan_z"])
        if d["link_loss_nodes"] is not None:
            d["link_loss_nodes"] = list(d["link_loss_nodes"])
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        if not isinstance(d, dict):
            raise ValueError(f"fault spec: expected a mapping, got {type(d).__name__}")
        unknown = sorted(set(d) - set(_FAULT_FIELDS))
        if unknown:
            raise ValueError(
                f"fault spec: unknown field(s) {unknown}; known fields: {list(_FAULT_FIELDS)}"
            )
        return cls(**d)


def load_fault_spec(source) -> FaultSpec:
    """Load a :class:`FaultSpec` from a dict, a YAML/JSON file path, or an
    existing spec (pass-through) — the ``--fault-spec`` entry point.

    YAML files use the same keys as :meth:`FaultSpec.to_dict`; a JSON file
    is just YAML that happens to use braces.
    """
    if isinstance(source, FaultSpec):
        return source
    if isinstance(source, dict):
        return FaultSpec.from_dict(source)
    path = Path(source)
    text = path.read_text()
    try:
        d = json.loads(text)
    except json.JSONDecodeError:
        import yaml

        try:
            d = yaml.safe_load(text)
        except yaml.YAMLError as e:
            # ValueError so CLI-level handlers (cli/tango.resolve_fault_spec)
            # render it as a clean error naming the file, not a traceback
            raise ValueError(f"{path}: not valid YAML/JSON: {e}") from None
    if d is None:
        d = {}
    if not isinstance(d, dict):
        raise ValueError(f"{path}: fault spec must be a mapping of fields, got {type(d).__name__}")
    return FaultSpec.from_dict(d)
