"""``make fault-check`` — the CPU fault-tolerance smoke gate.

Builds a tiny synthetic scene, injects a node dropout plus a NaN-corrupted
z through the declarative spec machinery, runs the full two-step TANGO in
degraded mode with obs recording on, and asserts the robustness contract:

* every surviving consumer's enhanced output is finite (the dropped and
  corrupted streams were excluded, not propagated);
* the fault-free run of the SAME scene is finite too and differs from the
  degraded one (the injection demonstrably reached the pipeline);
* the event log carries the expected ``fault`` events (one
  ``node_dropout``, one ``nan_z``) and a ``degraded`` entry, and the
  counters snapshot shows the injections.

Runs on the CPU backend in a few seconds (no dataset, no TPU) — wired into
``make test`` alongside ``obs-check`` so fault-handling drift fails CI.

No reference counterpart: the reference models no comms faults.
"""
from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path


def main(argv=None) -> int:
    """Run the fault-tolerance gate (``make fault-check``); exit 1 on failure."""
    import numpy as np

    from disco_tpu import obs
    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.tango import oracle_masks, tango
    from disco_tpu.fault import FaultSpec, plan_faults
    from disco_tpu.milestones import _scene

    K, C, L = 4, 2, 8192
    y, s, n = _scene(K, C, L, seed=11)  # the shared synthetic-scene recipe
    Y, S, N = stft(y), stft(s), stft(n)
    masks = oracle_masks(S, N, "irm1")

    spec = FaultSpec(seed=0, node_dropout=(1,), nan_z=(2,))
    plan = plan_faults(spec, n_nodes=K, n_blocks=1)

    with tempfile.TemporaryDirectory() as tmp:
        log = Path(tmp) / "fault_check.jsonl"
        with obs.recording(log):
            obs.write_manifest(config=spec.to_dict(), tool="fault-check")
            plan.record(mode="offline")
            obs.record("degraded", stage="mwf", mode="offline",
                       n_streams_excluded=plan.n_unavailable_offline(),
                       nodes=np.flatnonzero(plan.avail_offline < 1).tolist())
            res = tango(Y, S, N, masks, masks, policy="local",
                        z_mask=plan.avail_offline, z_nan=plan.z_nan)
            yf = np.asarray(res.yf)
            obs.record("counters", **obs.REGISTRY.snapshot())
        events = obs.read_events(log)  # schema-validating read

    failures = []
    if not np.isfinite(yf).all():
        bad = [k for k in range(K) if not np.isfinite(yf[k]).all()]
        failures.append(f"non-finite degraded-mode output at node(s) {bad}")

    res_clean = tango(Y, S, N, masks, masks, policy="local")
    yf_clean = np.asarray(res_clean.yf)
    if not np.isfinite(yf_clean).all():
        failures.append("non-finite fault-free output (scene itself is broken)")
    if np.allclose(yf, yf_clean):
        failures.append("degraded output identical to fault-free output — "
                        "the injection never reached the pipeline")

    faults = {e["attrs"].get("fault") for e in events if e["kind"] == "fault"}
    for want in ("node_dropout", "nan_z"):
        if want not in faults:
            failures.append(f"event log missing the injected {want!r} fault event")
    if not any(e["kind"] == "degraded" for e in events):
        failures.append("event log missing the degraded-mode entry")
    counters = next(
        (e["attrs"] for e in reversed(events) if e["kind"] == "counters"), {}
    )
    if int(counters.get("counters", {}).get("faults_injected", 0)) < 2:
        failures.append(f"faults_injected counter below 2 in snapshot: {counters}")

    if failures:
        for f in failures:
            print(f"fault-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "fault_check": "ok",
        "n_fault_events": sum(1 for e in events if e["kind"] == "fault"),
        "excluded_nodes": np.flatnonzero(plan.avail_offline < 1).tolist(),
        "nan_nodes": np.flatnonzero(plan.z_nan).tolist(),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
