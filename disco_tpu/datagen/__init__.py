from disco_tpu.datagen.disco import (
    generate_disco_rirs,
    generate_disco_rirs_batched,
    reverb_other_noises,
    simulate_scene,
    snr_at_mics,
)
from disco_tpu.datagen.meetit import (
    check_sir_validity,
    get_masks,
    get_value_range,
    simulate_meetit_room,
    sir_at_node,
)
from disco_tpu.datagen.postgen import PostGenerator

__all__ = [
    "simulate_meetit_room",
    "sir_at_node",
    "check_sir_validity",
    "get_value_range",
    "get_masks",
    "simulate_scene",
    "snr_at_mics",
    "reverb_other_noises",
    "generate_disco_rirs",
    "generate_disco_rirs_batched",
    "PostGenerator",
]
