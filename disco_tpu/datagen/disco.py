"""DISCO dataset generation: room simulation + convolution + SNR gating.

Capability parity with reference ``dataset_generation/gen_disco/
convolve_signals.py`` (mix_signals:84, get_convolved_vads:102,
reverb_other_noises:118, snr_at_mics:170, simulate_room:216, save_data:285,
__main__:329), re-designed TPU-first:

* RIRs come from the batched XLA image-source kernel
  (``disco_tpu.sim.shoebox_rirs``) instead of pyroomacoustics' libroom,
* all source->mic convolutions are ONE batched FFT-convolve on device
  instead of ``room.simulate`` + per-channel ``np.convolve`` loops,
* geometry/SNR rejection sampling stays host-side (data-dependent control
  flow, SURVEY.md §7 hard-part 5), with the reference's sentinel protocol
  ("redraw_source_signal" / "redraw_room_setup") and bounded retries,
* per-RIR idempotency guards and deterministic per-file reseeding keep the
  corpus-scale jobs restartable and process-parallel (SURVEY.md §5.2-5.3).

The reference's ``simulate_room`` calls ``signal_setup.get_signal(n_type=
"SSN", ...)`` which does not exist on SpeechAndNoiseSetup (its method is
``get_noise_segment``, SURVEY.md §7 defect list) — the evident intent is
implemented here.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from disco_tpu.core.masks import vad_oracle_batch
from disco_tpu.core.metrics import fw_snr
from disco_tpu.core.sigproc import increase_to_snr
from disco_tpu.io import DatasetLayout
from disco_tpu.io.atomic import atomic_write, probe_npy, save_npy_atomic, write_wav_atomic
from disco_tpu.sim import (
    RoomSetup,
    fft_convolve,
    rir_bucket,
    shoebox_rirs,
    shoebox_rirs_batched,
)


@dataclasses.dataclass
class SimulatedScene:
    """One simulated room: everything the mixing/saving passes need."""

    setup: RoomSetup
    rirs: np.ndarray  # (n_sources, n_mics, rir_len)
    sources: np.ndarray  # list of dry source signals (object array / list)
    images: np.ndarray  # (n_sources, n_mics, T) reverberated per-source images
    target_vad: np.ndarray  # dry-target VAD
    image_vads: np.ndarray  # (n_mics, T) VADs of the target images
    snr_images: np.ndarray  # per-mic fw-SNR


def get_convolved_vads(x: np.ndarray) -> np.ndarray:
    """Oracle VAD per image channel (convolve_signals.py:102-115)."""
    return np.stack(
        [np.asarray(vad_oracle_batch(np.asarray(x[i], np.float32), thr=0.001)) for i in range(x.shape[0])]
    )


def snr_at_mics(s, n, mics_per_node, fs=16000, vad_s=None, vad_n=None):
    """Per-mic fw-SNR, per-node means, min inter-node |ΔSNR|
    (convolve_signals.py:170-213)."""
    n_mic = s.shape[0]
    bounds = np.concatenate([[0], np.cumsum(mics_per_node)])
    n_nodes = len(mics_per_node)
    snrs = np.zeros(n_mic)
    for i in range(n_mic):
        vs = None if vad_s is None else vad_s[i]
        vn = None if vad_n is None else vad_n[i]
        snrs[i] = fw_snr(s[i], n[i], fs=fs, vad_tar=vs, vad_noi=vn)[1]
    nodes_snr = np.array([np.mean(snrs[bounds[k] : bounds[k + 1]]) for k in range(n_nodes)])
    deltas = [
        nodes_snr[i] - nodes_snr[j] for i in range(n_nodes) for j in range(i + 1, n_nodes)
    ]
    return snrs, nodes_snr, np.min(np.abs(deltas))


def simulate_scene(
    room_cfg: RoomSetup,
    signal_setup,
    i_target_file: int,
    dset: str,
    mics_per_node,
    max_order: int = 20,
    fs: int = 16000,
):
    """Simulate one two-source scene (target + SSN noise source)
    (convolve_signals.py:216-282).

    Returns a :class:`SimulatedScene`, or the sentinel strings
    "redraw_source_signal" / "redraw_room_setup".
    """
    target_file = signal_setup.target_list[i_target_file]
    target, target_vad, fs_t = signal_setup.get_target_segment(target_file)
    if target is None:
        return "redraw_source_signal"

    noise, _, _, noise_vad, _ = signal_setup.get_noise_segment("SSN", signal_setup.target_duration)
    noise = increase_to_snr(
        target, noise, signal_setup.source_snr[0],
        weight=True, vad_tar=target_vad, vad_noi=noise_vad, fs=fs,
    )

    # RIRs for both sources to all mics: one batched device launch.  The
    # bucket comes from the ONE canonical policy (sim.ism.rir_bucket), so
    # the per-scene and batched paths can never disagree on sizing.
    max_order, rir_len = rir_bucket(room_cfg.beta, room_cfg.room_dim,
                                    max_order=max_order, fs=fs)
    srcs = np.asarray(room_cfg.source_positions[:2], np.float32)
    mics = np.asarray(room_cfg.mic_positions.T, np.float32)  # (M, 3)
    rirs = np.asarray(
        shoebox_rirs(
            np.asarray(room_cfg.room_dim, np.float32), srcs, mics,
            float(room_cfg.alpha), max_order=max_order, rir_len=rir_len, fs=fs,
        )
    )

    # Per-source images: broadcast each dry signal over its (M, R) RIRs.
    L = len(target)
    sig_stack = np.zeros((2, L), np.float32)
    sig_stack[0] = target
    sig_stack[1, : len(noise)] = noise[:L]
    images = np.asarray(
        fft_convolve(sig_stack[:, None, :], rirs, out_len=L)
    )  # (2, M, L)

    image_vads = get_convolved_vads(images[0])
    snr_images, snr_nodes, snr_diff = snr_at_mics(
        images[0], images[1], mics_per_node, fs, vad_s=image_vads
    )

    lo, hi = signal_setup.snr_cnv_range
    if not (np.all(lo < snr_nodes) and np.all(snr_nodes < hi) and signal_setup.min_delta_snr < snr_diff):
        return "redraw_room_setup"

    if dset == "train":
        # Pad/truncate train clips to the fixed corpus length
        # (convolve_signals.py:275-279).
        len_max = int((signal_setup.duration_range[-1] + 1) * fs)
        pad = max(len_max - images.shape[-1], 0)
        images = np.pad(images, ((0, 0), (0, 0), (0, pad)))[:, :, :len_max]

    return SimulatedScene(
        setup=room_cfg,
        rirs=rirs,
        sources=sig_stack,
        images=images,
        target_vad=target_vad,
        image_vads=image_vads,
        snr_images=snr_images,
    )


def reverb_other_noises(scene: SimulatedScene, signal_setup, dset="train", fs=16000, max_snr_err=1.0):
    """Convolve additional noise types (freesound / interferent talker) with
    the noise-source RIRs already computed (convolve_signals.py:118-167),
    with the fw-SNR-checked retry loop.

    Returns (dry noises, reverberated noises (n_noi, M, T), files, starts).
    """
    noise_names = [k for k in signal_setup.noises_dict.keys()]
    target = scene.sources[0]
    target_duration = len(target) / fs
    if dset in ("train", "val"):
        len_max = int((signal_setup.duration_range[-1] + 1) * fs)
    else:
        len_max = scene.image_vads.shape[-1]

    n_noi = len(noise_names)
    M = scene.rirs.shape[1]
    dry = np.zeros((n_noi, len(target)))
    reverbed = np.zeros((n_noi, M, len_max), np.float32)
    files, starts = [], np.zeros(n_noi)

    for i, name in enumerate(noise_names):
        for _ in range(100):
            n, n_file, n_start, vad_n, _ = signal_setup.get_noise_segment(name, target_duration)
            n = increase_to_snr(
                target, n, signal_setup.source_snr[0],
                weight=True, vad_tar=scene.target_vad, vad_noi=vad_n, fs=fs,
            )
            snr_check = fw_snr(target, n, fs, vad_tar=scene.target_vad, vad_noi=vad_n, clipping=True)[1]
            if abs(snr_check - signal_setup.source_snr[0]) < max_snr_err:
                break
        dry[i, : len(n)] = n
        out = np.asarray(
            fft_convolve(
                np.asarray(n, np.float32)[None, :], scene.rirs[1], out_len=min(len_max, len(n))
            )
        )
        reverbed[i, :, : out.shape[-1]] = out[:, :len_max]
        files.append(n_file)
        starts[i] = -1 if n_start is None else n_start
    return dry, reverbed, files, starts


# File-name tags per noise type (convolve_signals.py:306 uses positional
# ['', '_ssn', '_it', '_fs']; deriving from the type name is robust to the
# dict ordering).
_NOISE_TAGS = {"ssn": "_ssn", "interferent_talker": "_it", "it": "_it", "freesound": "_fs", "fs": "_fs"}


def noise_tag(name: str) -> str:
    """Canonical filename tag for a noise kind."""
    return _NOISE_TAGS.get(name.lower(), f"_{name.lower()}")


def save_scene(
    scene: SimulatedScene, extra_dry, extra_reverbed, infos, rir_id,
    layout: DatasetLayout, fs=16000, extra_names=(),
):
    """Write the per-RIR corpus files in the reference layout
    (convolve_signals.py:285-326): dry sources, convolved images, extra
    noises, infos log.

    All writes are atomic (``disco_tpu.io.atomic``) and the infos log —
    the scene's completion marker, written LAST — lands only after every
    wav it describes, so the validated idempotency guard in
    :func:`generate_disco_rirs` can trust a complete infos file.

    Returns the list of written artifact paths (what a run ledger digests
    into the scene's ``done`` record)."""
    tags = [None, "ssn"] + [noise_tag(n).lstrip("_") for n in extra_names]
    kinds = ["target", "noise"]
    written = []
    # Dry sources (target, SSN)
    for i_s, sig in enumerate(scene.sources):
        p = layout.dry_source(kinds[i_s], rir_id, i_s + 1, noise=tags[i_s])
        written.append(write_wav_atomic(p, np.asarray(sig, np.float32), fs))
    # Extra dry noises (S-2 with their tag)
    for i_n in range(len(extra_dry)):
        p = layout.dry_source("noise", rir_id, 2, noise=tags[i_n + 2])
        written.append(write_wav_atomic(p, np.asarray(extra_dry[i_n], np.float32), fs))
    # Convolved images
    for i_s in range(len(scene.images)):
        for ch in range(scene.images.shape[1]):
            p = layout.cnv_image(kinds[i_s], rir_id, i_s + 1, ch + 1, noise=tags[i_s])
            written.append(write_wav_atomic(p, scene.images[i_s, ch], fs))
    for i_n in range(len(extra_reverbed)):
        for ch in range(extra_reverbed.shape[1]):
            p = layout.cnv_image("noise", rir_id, 2, ch + 1, noise=tags[i_n + 2])
            written.append(write_wav_atomic(p, extra_reverbed[i_n, ch], fs))
    written.append(save_npy_atomic(layout.infos(rir_id), infos, allow_pickle=True))
    return written


def generate_disco_rirs(
    scenario: str,
    dset: str,
    rir_start: int,
    n_rirs: int,
    signal_setup,
    layout: DatasetLayout,
    rng=None,
    max_order: int = 20,
    fs: int = 16000,
    max_redraws: int = 50,
    ledger=None,
    resume: bool = False,
):
    """The per-RIR-range generation driver (convolve_signals.py:418-448):
    idempotent, restartable, sentinel-driven redraw loop.

    Crash safety (``disco_tpu.runs``): every scene artifact is written
    atomically with the infos log last, and the idempotency guard
    *validates* the infos file (integrity probe) instead of trusting bare
    existence — a scene whose datagen run crashed mid-save is regenerated.
    ``ledger``/``resume`` add per-scene digest records with verified
    resume; a graceful SIGTERM/SIGINT stop finishes the current scene and
    returns early, resumable.

    Returns the list of RIR ids actually generated (existing ones skipped).
    """
    from disco_tpu.runs import chaos as run_chaos
    from disco_tpu.runs import interrupt as run_interrupt
    from disco_tpu.runs.ledger import RunLedger, unit_scene
    from disco_tpu.sim import make_setup
    from disco_tpu.sim.defaults import RoomDefaults

    rng = np.random.default_rng() if rng is None else rng
    defaults = RoomDefaults()
    room_sampler = make_setup(scenario, rng=rng)
    generated = []
    i_file = (rir_start - 1) * 2  # distinct talker per RIR, with margin (convolve_signals.py:373)

    if ledger is not None and not isinstance(ledger, RunLedger):
        ledger = RunLedger(ledger)
    if resume:
        from disco_tpu.io.atomic import remove_tmp_litter

        litter = remove_tmp_litter(layout.base)
        if litter:
            from disco_tpu.obs import events as _ev

            _ev.record("warning", stage="resume",
                       reason=f"removed {len(litter)} abandoned temp file(s) "
                              f"from a crashed writer", files=litter[:20])
    ledger_done: set = set()
    requeued_units: set = set()
    if ledger is not None and resume:
        from disco_tpu.obs import events as obs_events

        ledger_done, requeued = ledger.verified_done()
        requeued_units = set(requeued)
        obs_events.record(
            "run_resume", stage="datagen", ledger=str(ledger.path),
            n_done=len(ledger_done), n_requeued=len(requeued),
            requeued=sorted(requeued),
        )

    for rir_id in range(rir_start, rir_start + n_rirs):
        if run_interrupt.stop_requested():
            break  # graceful stop between scenes: everything saved, resumable
        if unit_scene(rir_id) in ledger_done:
            continue
        if unit_scene(rir_id) not in requeued_units and probe_npy(layout.infos(rir_id)):
            # validated idempotency guard (SURVEY.md §5.3): the infos log is
            # written LAST and atomically, so a complete one certifies the
            # scene; a truncated one (pre-atomic-era crash) is regenerated.
            # A unit the verified resume just requeued (digest-level damage
            # the infos probe cannot see) bypasses this skip and is redone.
            continue
        if ledger is not None:
            ledger.mark_in_flight(unit_scene(rir_id))
        signal_setup.get_random_dry_snr()
        scene = None
        for _ in range(max_redraws):
            cfg = room_sampler.create_room_setup()
            result = simulate_scene(
                cfg, signal_setup, i_file % len(signal_setup.target_list), dset,
                defaults.n_sensors_per_node, max_order=max_order, fs=fs,
            )
            if result == "redraw_source_signal":
                i_file += 1
                continue
            if result == "redraw_room_setup":
                continue
            scene = result
            break
        if scene is None:
            raise RuntimeError(f"RIR {rir_id}: no valid configuration after {max_redraws} redraws")
        extra_dry, extra_rev, files, starts = reverb_other_noises(scene, signal_setup, dset, fs)
        # Keys follow the reference infos contract (convolve_signals.py:438-446)
        # so plot_conf and reference-side tooling read these files unchanged.
        dims = np.asarray(scene.setup.room_dim)
        infos = {
            "room": {
                "length": float(dims[0]),
                "width": float(dims[1]),
                "height": float(dims[2]),
                "alpha": scene.setup.alpha,
                "rt60": scene.setup.beta,
            },
            "mics": np.asarray(scene.setup.mic_positions),
            "sources": np.asarray(scene.setup.source_positions),
            "nodes_centers": scene.setup.nodes_centers,
            "rirs": scene.rirs,
            "snr_images": scene.snr_images,
            "noise_files": files,
            "noise_starts": starts,
        }
        written = save_scene(
            scene, extra_dry, extra_rev, infos, rir_id, layout, fs,
            extra_names=list(signal_setup.noises_dict.keys()),
        )
        if ledger is not None:
            ledger.mark_done(unit_scene(rir_id), written)
        generated.append(rir_id)
        run_chaos.tick("between_scenes", rir=rir_id)
        i_file += 1
    return generated


def _draw_dry_pair(signal_setup, i_file: int, fs: int):
    """The dry-signal preamble of :func:`simulate_scene` (target + SSN
    noise, convolve_signals.py:216-240), factored so the batched driver can
    draw signals for a whole chunk before its one RIR dispatch.

    Returns ``(sig_stack (2, L), target_vad)`` or None (unusable target
    file — the caller advances ``i_file``, the "redraw_source_signal"
    protocol)."""
    target_file = signal_setup.target_list[i_file % len(signal_setup.target_list)]
    target, target_vad, _fs_t = signal_setup.get_target_segment(target_file)
    if target is None:
        return None
    noise, _, _, noise_vad, _ = signal_setup.get_noise_segment(
        "SSN", signal_setup.target_duration)
    noise = increase_to_snr(
        target, noise, signal_setup.source_snr[0],
        weight=True, vad_tar=target_vad, vad_noi=noise_vad, fs=fs,
    )
    L = len(target)
    sig_stack = np.zeros((2, L), np.float32)
    sig_stack[0] = target
    sig_stack[1, : len(noise)] = noise[:L]
    return sig_stack, target_vad


def _simulate_scenes_batched(cfgs, sig_stacks, target_vads, dset, signal_setup,
                             mics_per_node, max_order, fs):
    """Simulate a list of scenes with ONE RIR-engine dispatch.

    The batched twin of :func:`simulate_scene`'s device half
    (convolve_signals.py:216-282): all rooms' RIRs come from one
    ``shoebox_rirs_batched`` launch in the chunk's shared
    ``scenes.batched`` bucket, all dry→wet convolutions from one padded
    batched FFT convolve, and the results cross the tunnel in one batched
    readback.  SNR gating stays host-side per scene — a scene failing the
    node-SNR window returns None in its slot ("redraw_room_setup").

    Returns a list of :class:`SimulatedScene` or None per slot."""
    from disco_tpu.scenes.batched import BATCH_QUANTUM
    from disco_tpu.utils.transfer import device_get_tree

    B = len(cfgs)
    rir_len = 0
    for cfg in cfgs:
        _, n = rir_bucket(cfg.beta, cfg.room_dim, max_order=max_order, fs=fs,
                          quantum=BATCH_QUANTUM)
        rir_len = max(rir_len, n)
    dims = np.stack([np.asarray(c.room_dim, np.float32) for c in cfgs])
    srcs = np.stack([np.asarray(c.source_positions[:2], np.float32) for c in cfgs])
    mics = np.stack([np.asarray(c.mic_positions.T, np.float32) for c in cfgs])
    alphas = np.asarray([c.alpha for c in cfgs], np.float32)

    lens = [s.shape[-1] for s in sig_stacks]
    L_max = max(lens)
    dry = np.zeros((B, 2, L_max), np.float32)
    for b, s in enumerate(sig_stacks):
        dry[b, :, : s.shape[-1]] = s

    rirs_d = shoebox_rirs_batched(dims, srcs, mics, alphas,
                                  max_order=max_order, rir_len=rir_len, fs=fs)
    images_d = fft_convolve(dry[:, :, None, :], rirs_d, out_len=L_max)
    got = device_get_tree({"rirs": rirs_d, "images": images_d})

    scenes = []
    for b, cfg in enumerate(cfgs):
        images = got["images"][b][:, :, : lens[b]]
        image_vads = get_convolved_vads(images[0])
        snr_images, snr_nodes, snr_diff = snr_at_mics(
            images[0], images[1], mics_per_node, fs, vad_s=image_vads)
        lo, hi = signal_setup.snr_cnv_range
        if not (np.all(lo < snr_nodes) and np.all(snr_nodes < hi)
                and signal_setup.min_delta_snr < snr_diff):
            scenes.append(None)  # redraw_room_setup
            continue
        if dset == "train":
            len_max = int((signal_setup.duration_range[-1] + 1) * fs)
            pad = max(len_max - images.shape[-1], 0)
            images = np.pad(images, ((0, 0), (0, 0), (0, pad)))[:, :, :len_max]
        scenes.append(SimulatedScene(
            setup=cfg, rirs=got["rirs"][b], sources=sig_stacks[b],
            images=images, target_vad=target_vads[b],
            image_vads=image_vads, snr_images=snr_images,
        ))
    return scenes


def generate_disco_rirs_batched(
    scenario: str,
    dset: str,
    rir_start: int,
    n_rirs: int,
    signal_setup,
    layout: DatasetLayout,
    rng=None,
    max_order: int = 20,
    fs: int = 16000,
    max_redraws: int = 50,
    ledger=None,
    resume: bool = False,
    batch: int = 8,
    seed: int | None = None,
):
    """The batched generation driver (``disco-gen --batched``): same
    idempotency, ledger and redraw semantics as :func:`generate_disco_rirs`,
    but the RIR engine runs once per chunk of ``batch`` scenes instead of
    once per scene — on the tunneled attachment that turns B×~80 ms of
    dispatch RPC into one.

    Redraw protocol per chunk: every pending scene draws its dry signals
    up front (unusable targets advance the talker index, bounded); then
    redraw ROUNDS run — each round simulates all still-unsatisfied scenes
    in one dispatch and host-gates their node SNRs, failed scenes drawing
    a fresh room next round (the "redraw_room_setup" sentinel, amortized).
    Saving, ledger units (``scene:<id>``), the infos completion marker and
    the ``between_scenes`` chaos seam are IDENTICAL to the per-scene
    driver, so a batched corpus resumes (and chaos-drills) exactly like a
    per-scene one; the chunk boundary adds the ``between_scene_batches``
    seam.

    Unlike the per-scene driver — whose rng state at scene N depends on
    every draw scenes 1..N-1 consumed — the batched driver reseeds the
    samplers deterministically PER SCENE from ``(seed, rir_id, stream)``
    (the SURVEY §5.2 per-file reseeding discipline): scene ``rir_id``
    produces identical bytes whether it runs in a fresh run, a resumed
    run, or a different chunk split, which is what lets ``make
    scene-check`` assert byte-identical crash-and-resume trees.  ``seed``
    defaults to one integer drawn from ``rng`` (pass it explicitly — the
    CLI passes ``--seed`` — for cross-run reproducibility).

    Returns the list of RIR ids actually generated.
    """
    from disco_tpu.obs import events as obs_events
    from disco_tpu.runs import chaos as run_chaos
    from disco_tpu.runs import interrupt as run_interrupt
    from disco_tpu.runs.ledger import RunLedger, unit_scene
    from disco_tpu.sim import make_setup
    from disco_tpu.sim.defaults import RoomDefaults

    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    rng = np.random.default_rng() if rng is None else rng
    if seed is None:
        seed = int(rng.integers(2**31 - 1))
    defaults = RoomDefaults()
    room_sampler = make_setup(scenario, rng=rng)
    generated = []

    if ledger is not None and not isinstance(ledger, RunLedger):
        ledger = RunLedger(ledger)
    if resume:
        from disco_tpu.io.atomic import remove_tmp_litter

        litter = remove_tmp_litter(layout.base)
        if litter:
            obs_events.record("warning", stage="resume",
                              reason=f"removed {len(litter)} abandoned temp file(s) "
                                     f"from a crashed writer", files=litter[:20])
    ledger_done: set = set()
    requeued_units: set = set()
    if ledger is not None and resume:
        ledger_done, requeued = ledger.verified_done()
        requeued_units = set(requeued)
        obs_events.record(
            "run_resume", stage="datagen", ledger=str(ledger.path),
            n_done=len(ledger_done), n_requeued=len(requeued),
            requeued=sorted(requeued),
        )

    # Pending ids under the same skip rules as the per-scene driver: ledger
    # done, or a validated infos completion marker (unless requeued).
    pending = []
    for rir_id in range(rir_start, rir_start + n_rirs):
        if unit_scene(rir_id) in ledger_done:
            continue
        if unit_scene(rir_id) not in requeued_units and probe_npy(layout.infos(rir_id)):
            continue
        pending.append(rir_id)

    for c0 in range(0, len(pending), batch):
        if run_interrupt.stop_requested():
            break  # graceful stop between chunks: everything saved, resumable
        chunk = pending[c0 : c0 + batch]
        if ledger is not None:
            for rir_id in chunk:
                ledger.mark_in_flight(unit_scene(rir_id))
        # Dry signals per scene, drawn up front (redraw_source_signal
        # advances the talker index, bounded like the per-scene loop).
        # Stream 0 of the per-scene reseeding: scene rir_id's signal
        # draws never depend on what other scenes consumed.
        sig_stacks, target_vads = [], []
        for rir_id in chunk:
            signal_setup.rng = np.random.default_rng([seed, rir_id, 0])
            signal_setup.get_random_dry_snr()
            i_file = (rir_id - 1) * 2  # per-scene driver's talker convention
            pair = None
            for _ in range(max_redraws):
                pair = _draw_dry_pair(signal_setup, i_file, fs)
                if pair is not None:
                    break
                i_file += 1
            if pair is None:
                raise RuntimeError(
                    f"no usable target signal after {max_redraws} files")
            sig_stacks.append(pair[0])
            target_vads.append(pair[1])
        # Redraw rounds: one RIR dispatch per round over the unsatisfied
        # slots, until every scene passes its SNR gate.  Stream 1000+round
        # per scene: a scene's round-r room draw is a pure function of
        # (seed, rir_id, r), so resumed runs redraw identical rooms.
        scenes: list = [None] * len(chunk)
        active = list(range(len(chunk)))
        for _round in range(max_redraws):
            cfgs = []
            for slot in active:
                room_sampler.rng = np.random.default_rng(
                    [seed, chunk[slot], 1000 + _round])
                cfgs.append(room_sampler.create_room_setup())
            results = _simulate_scenes_batched(
                cfgs, [sig_stacks[i] for i in active],
                [target_vads[i] for i in active], dset, signal_setup,
                defaults.n_sensors_per_node, max_order, fs)
            still = []
            for slot, scene in zip(active, results):
                if scene is None:
                    still.append(slot)
                else:
                    scenes[slot] = scene
            active = still
            if not active:
                break
        if active:
            raise RuntimeError(
                f"RIRs {[chunk[i] for i in active]}: no valid configuration "
                f"after {max_redraws} batched redraw rounds")
        obs_events.record("scene", stage="datagen", n_scenes=len(chunk),
                          rir_start=int(chunk[0]), rir_end=int(chunk[-1]),
                          scenario=scenario)
        for rir_id, scene in zip(chunk, scenes):
            # Stream 1: extra-noise reverb draws, reseeded per scene.
            signal_setup.rng = np.random.default_rng([seed, rir_id, 1])
            extra_dry, extra_rev, files, starts = reverb_other_noises(
                scene, signal_setup, dset, fs)
            dims = np.asarray(scene.setup.room_dim)
            infos = {
                "room": {
                    "length": float(dims[0]),
                    "width": float(dims[1]),
                    "height": float(dims[2]),
                    "alpha": scene.setup.alpha,
                    "rt60": scene.setup.beta,
                },
                "mics": np.asarray(scene.setup.mic_positions),
                "sources": np.asarray(scene.setup.source_positions),
                "nodes_centers": scene.setup.nodes_centers,
                "rirs": scene.rirs,
                "snr_images": scene.snr_images,
                "noise_files": files,
                "noise_starts": starts,
            }
            written = save_scene(
                scene, extra_dry, extra_rev, infos, rir_id, layout, fs,
                extra_names=list(signal_setup.noises_dict.keys()),
            )
            if ledger is not None:
                ledger.mark_done(unit_scene(rir_id), written)
            generated.append(rir_id)
            run_chaos.tick("between_scenes", rir=rir_id)
        run_chaos.tick("between_scene_batches", rir_start=int(chunk[0]),
                       rir_end=int(chunk[-1]))
    return generated


def get_wavs_list(librispeech_root, freesound_root=None, dset="train", cache_dir=None, seed=30):
    """Deterministically shuffled corpus file lists (convolve_signals.py:32-81):
    train targets from train-clean-100, SSN talkers from train-clean-360,
    test targets from test-clean; optional freesound noise files.  The fixed
    seed makes every parallel process see the same order (SURVEY.md §5.2);
    lists are cached as txt so restarts and job arrays agree.

    Returns (target_list, talkers_list, noises_dict).
    """
    import glob
    import os

    def listing(name, subdir):
        if cache_dir is not None:
            cache = os.path.join(cache_dir, f"{name}.txt")
            if os.path.isfile(cache):
                with open(cache) as fh:
                    return [ln.strip() for ln in fh if ln.strip()]
        pats = ("*.wav", "*.flac")
        files = sorted(
            f for pat in pats for f in glob.glob(os.path.join(subdir, "**", pat), recursive=True)
        )
        np.random.default_rng(seed).shuffle(files)
        if cache_dir is not None and files:
            os.makedirs(cache_dir, exist_ok=True)
            # atomic: a half-written listing cache READS clean (every prefix
            # of a line list parses), so a torn write would silently shrink
            # the corpus on the next run instead of erroring
            with atomic_write(os.path.join(cache_dir, f"{name}.txt"), "w") as fh:
                fh.write("\n".join(files))
        return files

    if dset in ("train", "val"):  # val RIRs live inside the train corpus
        targets = listing("train_targets", os.path.join(librispeech_root, "train-clean-100"))
        talkers = listing("train_talkers", os.path.join(librispeech_root, "train-clean-360"))
    else:
        targets = listing("test_targets", os.path.join(librispeech_root, "test-clean"))
        talkers = listing("test_talkers", os.path.join(librispeech_root, "train-clean-360"))
    if not targets:  # flat directory fallback (synthetic/test corpora)
        targets = listing("targets_flat", str(librispeech_root))
    talkers = talkers or targets  # SSN needs talker material even without train-clean-360
    noises = {}
    if freesound_root is not None:
        fs_files = listing("freesound", str(freesound_root))
        if fs_files:
            noises["fs"] = fs_files
    return targets, talkers, noises
