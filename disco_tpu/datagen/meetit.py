"""MEETIT dataset generation: N interfering speakers facing N nodes
(source separation, ICASSP 2021 setup).

Capability parity with reference ``dataset_generation/gen_meetit/
convolve_signals.py`` (get_value_range:43, sir_at_node:81,
check_sir_validity:94, simulate_room:114, get_masks:166, save_data:191,
__main__:210), TPU-first: RIRs from the batched ISM kernel, all
source×mic convolutions one device launch, per-node per-source IRMs one
batched mask computation (the reference's ``get_masks`` uses the broken
``my_stft`` — implemented working here).

SIR accounting: the reference measures SIR with mir_eval's bss_eval on
(mixture, mixture) estimates, which reduces to the energy ratio of the
projections; here the SIR at a node is the scale-invariant SIR of the
mixture against the local target (``si_bss``), averaged over the node's
mics — same quantity, owned implementation.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from disco_tpu.utils import to_host

from disco_tpu.core.dsp import stft
from disco_tpu.core.masks import tf_mask
from disco_tpu.core.metrics import si_bss
from disco_tpu.io import DatasetLayout, write_wav
from disco_tpu.io.atomic import save_npy_atomic
from disco_tpu.sim import RoomSetup, fft_convolve, rir_length_for, shoebox_rirs


def get_value_range(i_rir, n_rirs, vmin=0, vmax=20, n_bins=5):
    """Linear bin of the value range for this RIR index (gen_meetit:43-57)."""
    i_bin = i_rir // (n_rirs / n_bins)
    d = vmax - vmin
    return np.array([vmin + i_bin * d / n_bins, vmin + (i_bin + 1) * d / n_bins])


def sir_at_node(s, n):
    """Mean over the node's mics of the mixture's SIR against the local
    target (gen_meetit:81-92)."""
    sirs = np.zeros(s.shape[0])
    for i in range(s.shape[0]):
        m = s[i] + n[i]
        _, sir, _ = si_bss(m, np.stack([s[i], n[i]], axis=1), 0)
        sirs[i] = sir
    return np.mean(sirs)


def sir_histogram(past_sirs_first, n_classes=4, vmin=2, vmax=14):
    """Counts per SIR class over the first-node SIRs of past rooms (the
    plt.hist trick of gen_meetit:107, without matplotlib)."""
    edges = np.linspace(vmin, vmax, n_classes + 1)
    return np.histogram(np.asarray(past_sirs_first), bins=edges)[0], edges


def check_sir_validity(current_sirs, past_sirs, bin_level, delta_sir=2, n_classes=4, vmin=2, vmax=14):
    """Balance the SIR histogram across classes and reject inter-node SIR
    spreads above ``delta_sir`` (gen_meetit:94-111)."""
    current_sirs = np.asarray(current_sirs)
    for shift in range(1, len(current_sirs)):
        if np.any((current_sirs - np.roll(current_sirs, shift)) > delta_sir):
            return False
    if current_sirs[0] < vmin or current_sirs[0] > vmax:
        return False
    counts, edges = sir_histogram([p[0] for p in past_sirs] if past_sirs else [], n_classes, vmin, vmax)
    bin_index = min(int(np.searchsorted(edges, current_sirs[0], side="right")) - 1, n_classes - 1)
    return counts[bin_index] < bin_level


@dataclasses.dataclass
class MeetitScene:
    setup: RoomSetup
    rirs: np.ndarray  # (n_sources, n_mics, R)
    sources: np.ndarray  # (n_sources, L) dry
    images: np.ndarray  # (n_sources, n_mics, L)
    sirs: np.ndarray  # (n_nodes,)


def simulate_meetit_room(
    room_cfg: RoomSetup,
    signal_setup,
    dset: str,
    mics_per_node,
    past_sirs,
    n_rirs_per_proc: int,
    max_order: int = 20,
    fs: int = 16000,
    rng=None,
    sir_vmin: float = 2.0,
    sir_vmax: float = 14.0,
    n_sir_classes: int = 4,
):
    """One meeting room with n_sources == n_nodes interfering speakers
    (gen_meetit:114-163).  Returns a MeetitScene or "redraw_room_setup".

    The default SIR gate reproduces the reference's four 3-dB classes
    2-5 / 5-8 / 8-11 / 11-14 (gen_meetit:150-152)."""
    rng = np.random.default_rng() if rng is None else rng
    n_sources = len(room_cfg.source_positions)
    rnd_dur = signal_setup.duration_range[0] + (
        signal_setup.duration_range[1] - signal_setup.duration_range[0]
    ) * rng.random()

    signal_setup.reset()
    sigs = []
    for _ in range(n_sources):
        sig, _vad = signal_setup.get_signal(duration=rnd_dur)
        sigs.append(sig)
    L = min(len(s) for s in sigs)
    sources = np.stack([np.asarray(s[:L], np.float32) for s in sigs])

    rir_len = rir_length_for(room_cfg.beta, fs=fs)
    rirs = np.asarray(
        shoebox_rirs(
            np.asarray(room_cfg.room_dim, np.float32),
            np.asarray(room_cfg.source_positions, np.float32),
            np.asarray(room_cfg.mic_positions.T, np.float32),
            float(room_cfg.alpha), max_order=max_order, rir_len=rir_len, fs=fs,
        )
    )
    images = np.asarray(fft_convolve(sources[:, None, :], rirs, out_len=L))  # (S, M, L)

    bounds = node_channel_bounds(mics_per_node)
    sirs = np.zeros(len(mics_per_node))
    for src in range(n_sources):
        local_target = images[src, bounds[src] : bounds[src + 1]]
        others = [j for j in range(n_sources) if j != src]
        local_noise = images[others, bounds[src] : bounds[src + 1]].sum(0)
        sirs[src] = sir_at_node(local_target, local_noise)

    bin_level = int(np.ceil(n_rirs_per_proc / n_sir_classes))
    if not check_sir_validity(
        sirs, past_sirs, bin_level, n_classes=n_sir_classes, vmin=sir_vmin, vmax=sir_vmax
    ):
        return "redraw_room_setup"

    if dset in ("train", "val"):
        len_max = int(signal_setup.duration_range[-1] * fs)
        pad = max(len_max - images.shape[-1], 0)
        images = np.pad(images, ((0, 0), (0, 0), (0, pad)))[:, :, :len_max]

    return MeetitScene(setup=room_cfg, rirs=rirs, sources=sources, images=images, sirs=sirs)


def get_masks(images, mics_per_node):
    """Per-node mixtures and per-source IRMs at every channel
    (gen_meetit:166-189), batched: one STFT over all (sources, mics).

    Returns (mix_stfts (M, F, T), masks (n_sources, M, F, T))."""
    S = to_host(stft(images))  # (n_src, M, F, T)
    mix = S.sum(0)  # (M, F, T)
    n_src = S.shape[0]
    masks = np.stack(
        [np.asarray(tf_mask(S[s], mix - S[s], "irm1")) for s in range(n_src)]
    )
    return mix, masks


def save_meetit_scene(scene: MeetitScene, infos, rir_id, layout: DatasetLayout, fs=16000):
    """wav/clean/{dry,cnv} layout of the MEETIT corpus (gen_meetit:191-207)."""
    base = layout.base
    for i_s in range(len(scene.sources)):
        p = base / "wav" / "clean" / "dry" / f"{rir_id}_S-{i_s + 1}.wav"
        layout.ensure_dir(p)
        write_wav(p, scene.sources[i_s], fs)
        for ch in range(scene.images.shape[1]):
            p = base / "wav" / "clean" / "cnv" / f"{rir_id}_S-{i_s + 1}_Ch-{ch + 1}.wav"
            layout.ensure_dir(p)
            write_wav(p, scene.images[i_s, ch], fs)
    # infos is written LAST: it doubles as the idempotency marker, so a
    # crash mid-save leaves a restartable (not silently-skipped) RIR.
    info_path = layout.infos(rir_id)
    layout.ensure_dir(info_path)
    # atomic: a crash mid-save must leave the marker absent, not truncated
    save_npy_atomic(info_path, infos, allow_pickle=True)


def generate_meetit_rirs(
    n_sources: int,
    dset: str,
    rir_start: int,
    n_rirs: int,
    signal_setup,
    layout: DatasetLayout,
    rng=None,
    max_order: int = 20,
    fs: int = 16000,
    max_redraws: int = 200,
):
    """The per-RIR-range MEETIT generation driver (gen_meetit:210-302):
    idempotent per RIR, SIR-histogram-balanced redraw loop, node count ==
    source count.  Returns the list of RIR ids actually generated."""
    from disco_tpu.sim import make_setup

    rng = np.random.default_rng() if rng is None else rng
    mics_per_node = [4] * n_sources
    sampler = make_setup("meetit", rng=rng, n_sensors_per_node=tuple(mics_per_node), n_sources=n_sources)
    generated, past_sirs = [], []

    for rir_id in range(rir_start, rir_start + n_rirs):
        if layout.infos(rir_id).exists():
            continue  # idempotency guard (gen_meetit:272, SURVEY.md §5.3)
        scene = None
        for _ in range(max_redraws):
            cfg = sampler.create_room_setup()
            out = simulate_meetit_room(
                cfg, signal_setup, dset, mics_per_node,
                past_sirs=past_sirs, n_rirs_per_proc=n_rirs,
                max_order=max_order, fs=fs, rng=rng,
            )
            if out == "redraw_room_setup":
                continue
            scene = out
            break
        if scene is None:
            raise RuntimeError(f"RIR {rir_id}: no valid room after {max_redraws} redraws")
        past_sirs.append(scene.sirs)
        infos = {
            "room": {
                "length": float(scene.setup.room_dim[0]),
                "width": float(scene.setup.room_dim[1]),
                "height": float(scene.setup.room_dim[2]),
                "alpha": scene.setup.alpha,
                "rt60": scene.setup.beta,
            },
            "mics": np.asarray(scene.setup.mic_positions),
            "sources": np.asarray(scene.setup.source_positions),
            "sirs": scene.sirs,
        }
        # masks/STFTs first, then save_meetit_scene (whose infos write is the
        # idempotency marker) — a crash mid-RIR stays restartable.
        mix, masks = get_masks(scene.images, mics_per_node)
        for ch in range(mix.shape[0]):
            p = layout.base / "stft" / "mix" / f"{rir_id}_Ch-{ch + 1}.npy"
            layout.ensure_dir(p)
            save_npy_atomic(p, mix[ch].astype("complex64"))
            for i_s in range(masks.shape[0]):
                p = layout.base / "mask" / f"{rir_id}_S-{i_s + 1}_Ch-{ch + 1}.npy"
                layout.ensure_dir(p)
                save_npy_atomic(p, masks[i_s, ch].astype("float32"))
        save_meetit_scene(scene, infos, rir_id, layout, fs=fs)
        generated.append(rir_id)
    return generated


def node_channel_bounds(mics_per_node) -> np.ndarray:
    """Cumulative channel offsets per node: node k's channels are
    ``bounds[k]..bounds[k+1]-1`` (1-based file channel = offset + 1), and its
    reference mic is the first — THE mapping shared by the sample loader and
    every consumer scoring against per-channel artifacts."""
    return np.concatenate([[0], np.cumsum(mics_per_node)])


def load_meetit_sample(layout: DatasetLayout, rir_id: int, mics_per_node):
    """Load one generated MEETIT sample back from disk: the per-channel
    mixture STFTs and per-source IRMs written by :func:`generate_meetit_rirs`,
    shaped for :func:`disco_tpu.enhance.separate_with_masks`.

    Returns (Y (K, C, F, T) complex64 node-major mixture STFTs,
             masks (n_src, K, F, T) float32 at each node's reference mic).
    """
    base = layout.base
    M = int(np.sum(mics_per_node))
    mix = np.stack([np.load(base / "stft" / "mix" / f"{rir_id}_Ch-{ch + 1}.npy") for ch in range(M)])
    n_src = len(mics_per_node)
    bounds = node_channel_bounds(mics_per_node)
    K = len(mics_per_node)
    Y = np.stack([mix[bounds[k] : bounds[k + 1]] for k in range(K)])  # (K, C, F, T)
    masks = np.stack(
        [
            np.stack([np.load(base / "mask" / f"{rir_id}_S-{s + 1}_Ch-{bounds[k] + 1}.npy") for k in range(K)])
            for s in range(n_src)
        ]
    )  # (n_src, K, F, T) — ref mic of each node
    return Y.astype("complex64"), masks.astype("float32")
