"""Corpus acquisition — the pre_generation subsystem.

Capability parity with reference ``dataset_generation/pre_generation/``
(download_freesound_queries.py:44-338, clean_audio_info.py:19-115,
utils.py:5-35): typed download config, a Freesound inquirer with paginated
query search and 200-id batched id search, a per-minute rate limiter, serial/
multiprocess execution, csv bookkeeping with dedup, csv<->disk reconciliation
and the structured logging setup.

Network-free by construction: the inquirer takes any *client* object exposing
``text_search(**kwargs)`` (the freesound-python API surface).  In the
zero-egress build/test environment a fake client drives every code path; in
production the real ``freesound.FreesoundClient`` plugs straight in.  The
LibriSpeech / Zenodo fetches are plain URL lists for the host's own
downloader (reference download_librispeech.sh / download_noises_from_zenodo.sh).
"""
from __future__ import annotations

import csv as _csv
import functools
import glob
import logging
import os
import sys
import time
from collections import namedtuple
from multiprocessing import Pool

import numpy as np
import yaml

from disco_tpu.io.atomic import atomic_write

# The published corpus sources (download_librispeech.sh:1-21,
# download_noises_from_zenodo.sh:1-14).
LIBRISPEECH_URLS = [
    "https://www.openslr.org/resources/12/test-clean.tar.gz",
    "https://www.openslr.org/resources/12/train-clean-100.tar.gz",
    "https://www.openslr.org/resources/12/train-clean-360.tar.gz",
]
ZENODO_DISCO_NOISE_URL = "https://zenodo.org/record/4019030/files/noises.zip"


def set_up_log(logfile: str = "", level: int = 0) -> logging.Logger:
    """Root-logger setup (reference pre_generation/utils.py:5-35):
    level 0 = warnings, 1 = info, else debug; file or stderr."""
    formatter = logging.Formatter(
        "[%(levelname)s] %(asctime)s %(funcName)s: %(message)s", "%Y-%m-%d %H:%M:%S"
    )
    if logfile:
        os.makedirs(os.path.dirname(logfile) or ".", exist_ok=True)
        handler: logging.Handler = logging.FileHandler(logfile)
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(formatter)
    logger = logging.getLogger()
    logger.handlers = [handler]
    logger.setLevel(logging.WARNING if level == 0 else logging.INFO if level == 1 else logging.DEBUG)
    return logger


class DownloadConfig(namedtuple("DownloadConfig", "queries, id_file, fields_to_save, min_duration")):
    """Freesound download configuration (download_freesound_queries.py:111-154):
    category->queries mapping and/or an id csv, with string queries promoted
    to single-element lists."""

    def __new__(cls, queries=None, id_file=None, fields_to_save=(), min_duration=5.5):
        queries = dict(queries or {})
        if not queries and not id_file:
            raise ValueError('At least one of "queries" and "id_file" must be non-empty')
        for key, value in queries.items():
            if isinstance(value, str):
                queries[key] = [value]
        return super().__new__(cls, queries, id_file, tuple(fields_to_save), min_duration)

    @classmethod
    def from_yaml(cls, config_file):
        with open(config_file) as fh:
            return cls(**(yaml.safe_load(fh) or {}))


class FreesoundInquirer:
    """Paginated / id-batched search over a Freesound-API-like client
    (download_freesound_queries.py:157-217).

    Args:
      client: object with ``text_search(query=..., filter=..., sort=...,
        fields=..., page_size=..., page=...)`` returning result pages whose
        ``as_dict()`` has a ``"next"`` key (freesound-python semantics).
    """

    ID_BATCH = 200  # Freesound encodes the query in the URL (ref :209)
    PAGE_SIZE = 150  # API maximum (ref :191)

    def __init__(self, client):
        self.client = client

    @classmethod
    def from_token(cls, token, authentication_method="oauth"):
        """Production constructor over the real freesound-python client."""
        import freesound  # pragma: no cover - not in the build image

        client = freesound.FreesoundClient()
        client.set_token(token, auth_type=authentication_method)
        return cls(client)

    def _paginate(self, **search_kwargs):
        """Yield every page of one search.  The reference breaks on
        next==None BEFORE yielding (download_freesound_queries.py:194-197),
        silently dropping the final page of every query — the evident intent
        (all pages) is implemented here instead (SURVEY.md §7 policy)."""
        page = 1
        while True:
            results = self.client.text_search(page_size=self.PAGE_SIZE, page=page, **search_kwargs)
            yield results
            if results.as_dict()["next"] is None:
                return
            page += 1

    def queries_to_files(self, queries, fields_to_save, min_duration=5.5):
        """Yield result pages for every query until the API reports no next
        page (ref :174-198)."""
        for query in queries:
            yield from self._paginate(
                query=query,
                filter=f"duration:[{min_duration} TO *]",
                sort="score",
                fields=",".join(fields_to_save),
            )

    def ids_to_files(self, ids, fields_to_save, min_duration=5.5):
        """Yield result pages for explicit ids, 200 per request, each batch
        paginated (the reference's single unpaginated call, ref :200-217,
        would only ever see the API's default first page)."""
        ids = list(ids)
        for i in range(int(np.ceil(len(ids) / self.ID_BATCH))):
            batch = ids[i * self.ID_BATCH : (i + 1) * self.ID_BATCH]
            yield from self._paginate(
                query="",
                filter=f'duration:[{min_duration} TO *] id:({" OR ".join(batch)})',
                sort="score",
                fields=",".join(fields_to_save),
            )


def extract_category_ids(id_file):
    """category -> id list from the labelled csv (ref :219-232,
    ids_per_category.csv layout: index column + one column per category)."""
    with open(id_file, newline="") as fh:
        rows = list(_csv.reader(fh))
    header = rows[0][1:]  # skip index column
    out = {cat: [] for cat in header}
    for row in rows[1:]:
        vals = row[1:]
        if len(vals) < len(header) or any(v == "" for v in vals[: len(header)]):
            continue  # dropna semantics: only fully-labelled rows
        for cat, v in zip(header, vals):
            out[cat].append(v)
    return out


def serial_exec(func, iterable):
    """(ref :250-257)"""
    return [func(*val) for val in iterable]


def parallel_exec(func, iterable, num_proc):
    """multiprocessing starmap execution (ref :234-246)."""
    with Pool(processes=num_proc) as pool:
        return list(pool.starmap(func, iterable))


def update_csv(data: dict, file_path, sort_label: str = "", sep: str = ","):
    """Merge ``data`` (dict of equal-length lists) into the csv, dropping
    duplicate rows, optionally mergesort-stable-sorted (ref :260-283)."""
    header = list(data.keys())
    new_rows = [list(map(str, row)) for row in zip(*data.values())]
    rows = []
    if os.path.isfile(file_path):
        with open(file_path, newline="") as fh:
            old = list(_csv.reader(fh, delimiter=sep))
        if old:
            header = old[0]
            rows = old[1:]
    rows += new_rows
    seen, dedup = set(), []
    for row in rows:
        key = tuple(row)
        if key not in seen:
            seen.add(key)
            dedup.append(row)
    if sort_label and sort_label in header:
        col = header.index(sort_label)
        dedup.sort(key=lambda r: r[col])  # python sort IS mergesort-stable
    os.makedirs(os.path.dirname(file_path) or ".", exist_ok=True)
    # atomic: the CSV is the download ledger a resumed run trusts — a torn
    # rewrite would re-download (or worse, skip) half the corpus
    with atomic_write(file_path, "w", newline="") as fh:
        w = _csv.writer(fh, delimiter=sep)
        w.writerow(header)
        w.writerows(dedup)


def limit_exec(function=None, *, max_per_minute=50, sleep=time.sleep, clock=time.time):
    """Rate-limit decorator: after ``max_per_minute`` calls inside a minute,
    sleep out the remainder (ref :285-317).  ``sleep``/``clock`` injectable
    for tests."""

    def arg_wrapper(func):
        @functools.wraps(func)
        def limited(*args, **kwargs):
            if limited.num_exec == 0:
                limited.start = clock()
            res = func(*args, **kwargs)
            limited.num_exec += 1
            if limited.num_exec == max_per_minute:
                remaining = 60 - (clock() - limited.start)
                if remaining > 0:
                    sleep(remaining)
                limited.num_exec = 0
            return res

        limited.num_exec = 0
        return limited

    return arg_wrapper if function is None else arg_wrapper(function)


def _plain_download(file, filename, output_dir):
    """One download; ``file`` is a Freesound sound object exposing
    ``retrieve(dir, name=...)`` (ref :320-333).  Picklable for Pool workers;
    rate limiting happens in the dispatcher (see download_freesound)."""
    logger = logging.getLogger(__name__)
    logger.info(f"downloading: {filename}")
    try:
        file.retrieve(output_dir, name=filename)
    except Exception:
        logger.warning(f"Error while downloading {filename}")


#: Rate-limited single-process variant (the reference's decorated form,
#: ref :320-333) for direct use outside the batched dispatcher.
limited_download = limit_exec(_plain_download)


# ------------------------------------------------- csv <-> disk reconciliation
def get_missing(csv_path, label="id", sep="\t"):
    """Audio files on disk (same dir as the csv) whose id is absent from the
    csv (reference clean_audio_info.py:62-84)."""
    folder = os.path.dirname(csv_path)
    with open(csv_path, newline="") as fh:
        rows = list(_csv.reader(fh, delimiter=sep))
    if not rows:
        return []
    ids = {row[rows[0].index(label)] for row in rows[1:] if row}
    missing = []
    for f in sorted(glob.glob(os.path.join(folder, "*"))):
        base = os.path.basename(f)
        if base.endswith(".csv"):
            continue
        file_id = base.split("_")[0].split(".")[0]
        if file_id not in ids:
            missing.append(base)
    return missing


def clean_info(csv_path, label="id", sep="\t"):
    """Drop csv rows whose audio file no longer exists on disk and rewrite
    (reference clean_audio_info.py:87-115)."""
    folder = os.path.dirname(csv_path)
    on_disk = set()
    for f in glob.glob(os.path.join(folder, "*")):
        base = os.path.basename(f)
        if not base.endswith(".csv"):
            on_disk.add(base.split("_")[0].split(".")[0])
    with open(csv_path, newline="") as fh:
        rows = list(_csv.reader(fh, delimiter=sep))
    if not rows:
        return 0
    header, body = rows[0], rows[1:]
    col = header.index(label)
    kept = [row for row in body if row and row[col] in on_disk]
    with atomic_write(csv_path, "w", newline="") as fh:
        w = _csv.writer(fh, delimiter=sep)
        w.writerow(header)
        w.writerows(kept)
    return len(body) - len(kept)


def download_freesound(
    config: DownloadConfig,
    inquirer: FreesoundInquirer,
    out_root,
    num_jobs: int = 1,
    max_per_minute: int = 50,
    sleep=time.sleep,
    clock=time.time,
):
    """The downloader main (ref :44-78): for each category, query (or id-list)
    search -> rate-limited downloads -> per-category csv of saved fields.

    Rate limiting is enforced in the DISPATCHING process (batches of
    ``max_per_minute`` per minute): with a worker pool, per-worker limiter
    state would multiply the effective request rate by ``num_jobs`` past the
    API quota (a latent flaw of the reference's in-worker decorator)."""
    logger = logging.getLogger(__name__)
    exec_fn = (
        functools.partial(parallel_exec, num_proc=num_jobs) if num_jobs > 1 else serial_exec
    )
    categories = (
        extract_category_ids(config.id_file) if config.id_file else config.queries
    )

    def dispatch(tasks):
        for i in range(0, len(tasks), max_per_minute):
            start = clock()
            exec_fn(_plain_download, tasks[i : i + max_per_minute])
            if i + max_per_minute < len(tasks):
                remaining = 60 - (clock() - start)
                if remaining > 0:
                    sleep(remaining)

    n_files = 0
    for category, spec in categories.items():
        out_dir = os.path.join(out_root, category)
        os.makedirs(out_dir, exist_ok=True)
        pages = (
            inquirer.ids_to_files(spec, config.fields_to_save, config.min_duration)
            if config.id_file
            else inquirer.queries_to_files(spec, config.fields_to_save, config.min_duration)
        )
        for results in pages:
            sounds = list(results)
            logger.info(f"{category}: {len(sounds)} files")
            dispatch([(s, f"{s.id}.wav", out_dir) for s in sounds])
            info = {field: [getattr(s, field) for s in sounds] for field in config.fields_to_save}
            if info:
                update_csv(info, os.path.join(out_dir, f"{category}.csv"), sort_label="id", sep="\t")
            n_files += len(sounds)
    return n_files
