"""The mixing pass: target + noise at random SNR -> STFTs, ideal masks,
saved representations.

Capability parity with reference ``dataset_utils/post_generator.py``
(``PostGenerator:9``), with the per-channel librosa/mask loops replaced by
one batched jitted STFT + mask computation over all 16 channels.
"""
from __future__ import annotations

import glob

import numpy as np

from disco_tpu.utils import to_host

from disco_tpu.core.dsp import stft
from disco_tpu.core.masks import tf_mask
from disco_tpu.io import DatasetLayout, read_wav, write_wav
from disco_tpu.io.atomic import save_npy_atomic
from disco_tpu.io.layout import case_of_rir, snr_dirname


class PostGenerator:
    """Mix, transform and save one RIR range (post_generator.py:9-166)."""

    def __init__(
        self,
        rir_start,
        nb_rir,
        scene,
        noise,
        snr_range,
        path_to_dataset,
        n_fft=512,
        n_hop=256,
        mask_type="irm1",
        save_target=True,
        n_samples=None,
        rng=None,
    ):
        self.rir_start = rir_start
        self.nb_rir = nb_rir
        self.save_target = save_target
        self.scene = scene
        self.noise = noise
        self.snr_range = np.asarray(snr_range)
        self.path_dataset = path_to_dataset
        self.n_fft = n_fft
        self.n_hop = n_hop
        self.mask_type = mask_type
        self.snr_out = np.zeros((nb_rir, 1))
        self.n_samples = n_samples if n_samples is not None else (10000, 1000, 1000)
        self.case = self._get_dset()
        self.rng = np.random.default_rng() if rng is None else rng
        # Hard-coded corpus constants (post_generator.py:52-56)
        self.fs = 16000
        self.ch_per_node = [4, 4, 4, 4]
        self.n_ch = sum(self.ch_per_node)
        self.n_nodes = len(self.ch_per_node)
        self.layout = DatasetLayout(path_to_dataset, scene, self.case)

    def _get_dset(self):
        """train/val/test from the RIR range; both ends must fall in the same
        split (post_generator.py:58-64)."""
        first = case_of_rir(self.rir_start, self.n_samples)
        last = case_of_rir(self.rir_start + self.nb_rir - 1, self.n_samples)
        assert first == last, "First and last RIRs do not belong to the same set."
        return first

    @property
    def snr_dir(self):
        return snr_dirname(self.snr_range)

    def post_process(self):
        """Idempotent per-RIR mixing pass (post_generator.py:70-84)."""
        done = []
        for rir in range(self.rir_start, self.rir_start + self.nb_rir):
            if self.layout.snr_log(self.snr_range, rir, self.noise).exists():
                continue
            tar_list, noi_list = self.get_sig_lists(rir)
            tars, nois, mixs, snr = self.mix_sigs(tar_list, noi_list)
            self.snr_out[rir - self.rir_start, 0] = snr
            # to_host: the tunneled TPU attachment cannot transfer complex
            # dtypes in one copy (see utils.transfer)
            tars_stft = to_host(stft(tars, self.n_fft, self.n_hop))
            nois_stft = to_host(stft(nois, self.n_fft, self.n_hop))
            mixs_stft = to_host(stft(mixs, self.n_fft, self.n_hop))
            masks = np.asarray(tf_mask(tars_stft, nois_stft, self.mask_type))
            self.save_data(tars, nois, mixs, tars_stft, nois_stft, mixs_stft, masks, rir)
            done.append(rir)
        return done

    def get_sig_lists(self, rir):
        """Channel-sorted convolved-wav lists for one RIR
        (post_generator.py:86-97)."""
        base = self.layout.base / "wav_original" / "cnv"
        tar = sorted(
            glob.glob(str(base / "target" / f"{rir}_S-1_Ch-*.wav")),
            key=lambda p: int(p.split("_Ch-")[-1].split(".wav")[0]),
        )
        noi = sorted(
            glob.glob(str(base / "noise" / f"{rir}_S-2_{self.noise}_Ch-*.wav")),
            key=lambda p: int(p.split("_Ch-")[-1].split(".wav")[0]),
        )
        return tar, [noi]

    def mix_sigs(self, tar_list, noi_list):
        """One random SNR for all channels and noises (post_generator.py:99-115)."""
        snr = self.snr_range[0] + (self.snr_range[1] - self.snr_range[0]) * self.rng.random()
        tars, nois, mixs = [], [], []
        for ch in range(self.n_ch):
            tar, _ = read_wav(tar_list[ch])
            noi_sum = np.zeros(len(tar))
            for group in noi_list:
                noi, _ = read_wav(group[ch])
                noi_sum[: len(noi)] += noi * 10 ** (-snr / 20)
            tars.append(tar)
            nois.append(noi_sum)
            mixs.append(tar + noi_sum)
        return np.array(tars, np.float32), np.array(nois, np.float32), np.array(mixs, np.float32), snr

    def save_data(self, s, n, m, ss, ns, ms, masks, rir):
        """Write wav_processed / stft_processed{raw, normed/abs} /
        mask_processed / snr log (post_generator.py:133-166)."""
        lay = self.layout
        for ch in range(s.shape[0]):
            c = ch + 1
            if self.save_target:
                p = lay.wav_processed(self.snr_range, "target", rir, c)
                lay.ensure_dir(p)
                write_wav(p, s[ch], self.fs)
            for kind, sig in (("noise", n[ch]), ("mixture", m[ch])):
                p = lay.wav_processed(self.snr_range, kind, rir, c, noise=self.noise)
                lay.ensure_dir(p)
                write_wav(p, sig, self.fs)
            if self.save_target:
                p = lay.stft_processed(self.snr_range, "target", rir, c)
                lay.ensure_dir(p)
                save_npy_atomic(p, ss[ch])
            for kind, spec in (("noise", ns[ch]), ("mixture", ms[ch])):
                p = lay.stft_processed(self.snr_range, kind, rir, c, noise=self.noise)
                lay.ensure_dir(p)
                save_npy_atomic(p, spec)
            p = lay.stft_processed(self.snr_range, "mixture", rir, c, noise=self.noise, normed=True)
            lay.ensure_dir(p)
            save_npy_atomic(p, np.abs(ms[ch]))
            p = lay.mask_processed(self.snr_range, rir, c, self.noise)
            lay.ensure_dir(p)
            save_npy_atomic(p, masks[ch])
        p = lay.snr_log(self.snr_range, rir, self.noise)
        lay.ensure_dir(p)
        save_npy_atomic(p, self.snr_out[rir - self.rir_start])
