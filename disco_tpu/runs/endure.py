"""``make endure-check`` — the continuous-flywheel endurance gate
(sixteenth gate).

Runs the WHOLE closed loop in one process, hermetically (CPU backend
forced by the Makefile, loopback sockets only, ONE jax process, compile
cache off, zero SIGKILLs): a serve scheduler delivering model-mask blocks,
the corpus tap spooling every delivered block to training shards, the
co-resident trainer (:class:`~disco_tpu.flywheel.resident.ResidentTrainer`)
consuming those shards in step slices interleaved on the dispatch thread,
publishing a generation per epoch, and the promotion controller rolling
each one out canary → gate → promote — through **at least
:data:`MIN_GENERATIONS` full generations**, while every component is
crash-drilled at its seams:

* ``mid_epoch`` — the trainer dies at an epoch boundary with the train
  pass done and nothing persisted; the restart re-enters the epoch, every
  consumed shard unit verifies and is skipped, and the epoch closes with
  **zero re-consumed shard units**.
* ``pre_publish`` — the trainer dies with the checkpoint and epoch record
  durable but the generation not staged; the restart drains the
  interrupted ``publish:<e>`` unit first and re-stages idempotently.
* ``between_generations`` — a clean boundary death right after a
  generation lands; the store holds only complete, digest-verified
  generations and training resumes at the next epoch.
* ``pre_swap`` — the serve dispatch thread dies mid-rollout; the
  interrupted rollout is rolled back from the ledger on restart.
* ``mid_canary`` — the controller thread alone dies mid-gate; the server
  keeps delivering bit-exact, and a fresh controller's replay rolls the
  orphaned rollout back (a demoted candidate is never resurrected).

Standing asserts, every leg: every delivered frame **bit-exact** against
the per-generation offline oracle (block-wise
:func:`~disco_tpu.promote.lane.block_masks` under each block's recorded
generation, chained through ``streaming_tango``); recovery within a
**paced-round bound** (tick-based, never wall-clock); ``disco-obs slo``
green while training runs.  Campaign-end asserts: monotone promoted-serial
lineage ending at ``ACTIVE``, every generation digest-verifies, the tap
manifest replays with zero digest drift, the trainer ledger shows every
shard-epoch unit consumed exactly once, and the summary line is
byte-stable (constants of the seeded campaign only).

No reference counterpart: the reference trains once, offline, and serves
nothing (SURVEY.md §5.1) — there is no live loop to endure.
"""
from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

K, C, U = 4, 2, 4
BLOCK = 2 * U
WIN = BLOCK // 2
WINDOW = 2            #: canary window (blocks) per rollout
LONG = 49152          #: clip length: 24 paced blocks per leg

#: promoted generations the campaign must reach (the ISSUE floor)
MIN_GENERATIONS = 3
#: paced-round bound on post-restart recovery: a fresh promotion must land
#: within this many delivered blocks of a leg's start (tick-based — the
#: clock never judges recovery)
REC_ROUNDS = 16
#: trainer epoch budget added per leg (bounds the generation count)
EPOCHS_PER_LEG = 3

#: SLO targets for the hermetic gate: the wall-clock latency legs are
#: relaxed (cold-jit frames poison a cumulative p95 on a slow host, and
#: host speed must never decide this gate — paced-round bounds do) while
#: the host-independent RATE legs keep their production targets
SLO_TARGETS = {"serve_p95_ms": 60000.0, "queue_wait_p95_ms": 60000.0}

#: the crash schedule: one leg per seam, one component each —
#: trainer (first three), serve dispatch, controller; the final ``None``
#: leg runs clean to the generation floor
SEAM_LEGS = ("mid_epoch", "pre_publish", "between_generations",
             "pre_swap", "mid_canary", None)


def _scene(seed, L=LONG):
    import numpy as np

    from disco_tpu.core.dsp import stft

    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    T = Y.shape[-1] - (Y.shape[-1] % BLOCK)   # whole blocks only
    return Y[..., :T]


def _config(F):
    from disco_tpu.serve import SessionConfig

    return SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                         block_frames=BLOCK, update_every=U, masks="model")


def _arch(n_freq: int) -> dict:
    """The gate's tiny CRNN (promote-check's shape): milliseconds to jit,
    real enough to exercise the whole mask + training lane."""
    return dict(n_ch=1, win_len=WIN, n_freq=n_freq,
                cnn_filters=(4,), pool_kernels=((1, 4),),
                conv_padding=((0, 1),), rnn_units=(16,),
                ff_units=(n_freq,), rnn_dropouts=0.0)


def _seed_variables(arch: dict, seed: int) -> dict:
    import numpy as np

    from disco_tpu.nn.crnn import build_crnn
    from disco_tpu.nn.training import create_train_state

    model, tx = build_crnn(**arch)
    x0 = np.zeros((1, arch["n_ch"], WIN, arch["n_freq"]), np.float32)
    state = create_train_state(model, tx, x0, seed=seed)
    return {"params": state.params, "batch_stats": state.batch_stats}


def _offline(Y, m):
    import numpy as np

    from disco_tpu.enhance.streaming import streaming_tango

    return np.asarray(
        streaming_tango(Y, m, m, update_every=U, policy="local")["yf"])


def _gen_oracle(Y, gens, store):
    """Offline replay: per-block masks under each block's recorded
    generation (store-loaded, digest-verified — loading doubles as the
    no-torn-file check), chained through the server's streaming carry."""
    import numpy as np

    from disco_tpu.promote.lane import block_masks
    from disco_tpu.promote.store import model_for_arch

    cache: dict = {}
    ms = []
    for i, g in enumerate(gens):
        if g not in cache:
            gen = store.get(g)
            cache[g] = (model_for_arch(gen.arch), store.load(g)[1])
        model, variables = cache[g]
        lo = i * BLOCK
        ms.append(block_masks(Y[..., lo:lo + BLOCK], model, variables))
    m = np.concatenate(ms, axis=-1)
    return _offline(Y[..., :len(gens) * BLOCK], m)


def _assert_stream(failures, label, delivered, gen_of, Y, store):
    """Stitch one leg's delivered frames and compare bit-for-bit against
    the per-generation oracle."""
    import numpy as np

    n = max(delivered) + 1 if delivered else 0
    if sorted(delivered) != list(range(n)):
        failures.append(f"{label}: delivered seqs have holes "
                        f"({sorted(delivered)})")
        return
    if n == 0:
        return
    gens = [gen_of.get(i) for i in range(n)]
    if None in gens:
        failures.append(f"{label}: frames missing generation tags at seqs "
                        f"{[i for i, g in enumerate(gens) if g is None]}")
        return
    got = np.concatenate([delivered[i] for i in range(n)], axis=-1)
    ref = _gen_oracle(Y, gens, store)
    if not np.array_equal(got, ref):
        failures.append(
            f"{label}: stream not bit-exact vs the per-generation offline "
            f"oracle (max abs diff {np.abs(got - ref).max():g})")


def _done_rollouts(store):
    """[(t, gen_id)] of decided-done rollouts, promotion order."""
    out = []
    for unit, rec in store.rollout_ledger().replay().items():
        if unit.startswith("rollout:") and rec["state"] == "done":
            out.append((rec["t"], unit.split(":", 1)[1]))
    return sorted(out)


def _raw_done_counts(led_path: Path, prefix: str) -> dict:
    """{unit: #done-appends} over the raw ledger file — the
    zero-re-consumed-units contract counts appends, not latest state."""
    counts: dict = {}
    if not led_path.is_file():
        return counts
    for line in led_path.read_text().splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("unit", "").startswith(prefix) and rec.get("state") == "done":
            counts[rec["unit"]] = counts.get(rec["unit"], 0) + 1
    return counts


def _trainer_ckpt_intact(failures, label, train_dir: Path) -> None:
    """No torn trainer checkpoint: the rolling file must match the digest
    recorded by the LATEST done epoch (the checkpoint is always saved
    before its epoch record, and every drilled seam lands outside that
    pair)."""
    from disco_tpu.flywheel.resident import CKPT_NAME, LEDGER_NAME
    from disco_tpu.io.atomic import file_digest
    from disco_tpu.runs.ledger import RunLedger

    led = train_dir / LEDGER_NAME
    if not led.is_file():
        return
    done = [(int(u.split(":", 1)[1]), rec)
            for u, rec in RunLedger(led).replay().items()
            if u.startswith("epoch:") and rec["state"] == "done"]
    if not done:
        return
    want = (max(done)[1].get("attrs") or {}).get("ckpt_digest")
    ckpt = train_dir / CKPT_NAME
    if not ckpt.is_file():
        failures.append(f"{label}: epochs are done but the rolling "
                        "checkpoint is missing")
    elif want and file_digest(ckpt) != want:
        failures.append(f"{label}: rolling checkpoint digest drifted from "
                        "the latest done epoch's record (torn checkpoint)")


def _no_litter(failures, label, *dirs) -> None:
    from disco_tpu.io.atomic import TMP_SUFFIX

    litter = [str(p) for d in dirs if Path(d).is_dir()
              for p in Path(d).rglob(f"*{TMP_SUFFIX}.*")]
    if litter:
        failures.append(f"{label}: atomic-write temp litter: {litter}")


def _campaign(failures: list, tmp: Path) -> dict:
    from disco_tpu.flywheel import CorpusTap
    from disco_tpu.flywheel.resident import ResidentTrainer
    from disco_tpu.promote.controller import PromotionController, rollout_unit
    from disco_tpu.promote.store import GenerationStore, PublishRefused
    from disco_tpu.runs import chaos
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError
    from disco_tpu.serve.status import evaluate_slo, status_payload

    tap_dir, train_dir = tmp / "tap", tmp / "train"
    state_dir, store = tmp / "state", GenerationStore(tmp / "promote")
    clip0 = _scene(130)
    F = clip0.shape[-2]
    n_blocks = clip0.shape[-1] // BLOCK
    arch = _arch(F)
    gen0 = store.stage_variables(_seed_variables(arch, seed=6), arch=arch,
                                 source="endure-gen0")
    store.set_active(gen0.gen_id)

    crashes = 0
    slo_breaches, slo_samples = 0, 0
    interrupted_swap = [None]   # pre_swap leg's orphaned rollout gen

    for leg, seam in enumerate(SEAM_LEGS):
        clip = _scene(131 + leg)
        tap = CorpusTap(tap_dir, records_per_shard=2)
        ctl = PromotionController(store, canary_frac=1.0, sdr_gate_db=None,
                                  slo_gate=True, slo_targets=SLO_TARGETS,
                                  window_blocks=WINDOW,
                                  gate_timeout_s=60.0, poll_s=0.01)
        tr = ResidentTrainer(tap_dir, train_dir, promote_dir=store.root,
                             arch=arch, batch_size=4, steps_per_tick=4,
                             publish="always", publish_every=1,
                             max_epochs=EPOCHS_PER_LEG * (leg + 1),
                             recent_shards=6)
        srv = EnhanceServer(max_sessions=4, tap=tap, promote=ctl,
                            resident=tr, state_dir=state_dir)
        addr = srv.start()

        if interrupted_swap[0] is not None:
            # the pre_swap leg's mid-rollout death: the restart's ledger
            # replay must have rolled the orphan back before serving
            rec = store.rollout_ledger().replay().get(
                rollout_unit(interrupted_swap[0]))
            if rec is None or rec["state"] != "failed":
                failures.append(
                    f"leg {leg}: the pre_swap-interrupted rollout is "
                    f"{None if rec is None else rec['state']!r} after "
                    "restart, expected failed (rolled back from the ledger)")
            interrupted_swap[0] = None

        cl = ServeClient(addr)
        cl.open(_config(F), session_id=f"e{leg}")
        delivered: dict = {}
        cursors = [0]

        def pace() -> bool:
            """One paced round; False once the server is gone or the clip
            is spent.  Every swap lands between rounds, so each block runs
            under exactly one generation.  The receive pumps in short
            slices watching ``srv.crashed`` — a dispatch-thread death must
            end the round in about a second, not after a full client
            timeout (the injected crash kills the dispatch loop, not the
            I/O loop, so the socket stays open and silent)."""
            i = cursors[0]
            if i >= n_blocks or srv.crashed is not None:
                return False
            try:
                cl.send_block(clip[..., i * BLOCK:(i + 1) * BLOCK])
            except ServeError:
                return False
            deadline = time.monotonic() + 120.0
            while True:
                try:
                    delivered[i] = cl.recv_enhanced(i, timeout_s=1.0)
                    break
                except ServeError:
                    if srv.crashed is not None or time.monotonic() > deadline:
                        return False
            cursors[0] = i + 1
            return True

        # -- phase 1: recover + promote within the round bound ---------------
        before = len(_done_rollouts(store))
        promoted_round = None
        for r in range(REC_ROUNDS):
            if not pace():
                break
            if len(_done_rollouts(store)) > before:
                promoted_round = r
                break
            if tr.stats()["steps_total"] or tr.stats()["epochs_done"]:
                # the trainer is live again; SLO must hold while it trains
                slo = evaluate_slo(status_payload(srv.scheduler), SLO_TARGETS)
                slo_samples += 1
                slo_breaches += 0 if slo["verdict"] == "OK" else 1
        if promoted_round is None:
            rolls = {u: r["state"] for u, r in
                     store.rollout_ledger().replay().items()}
            failures.append(
                f"leg {leg} ({seam or 'final'}): no promotion within "
                f"{REC_ROUNDS} paced rounds of the restart — recovery "
                f"missed the tick bound (trainer: {tr.stats()}, "
                f"ctl phase={ctl._phase} crashed={ctl.crashed!r}, "
                f"rollouts={rolls}, store={store.list_ids()})")

        if leg > 0 and SEAM_LEGS[leg - 1] == "pre_publish":
            # the previous leg died at pre_publish: THIS leg's trainer must
            # have drained the interrupted publish unit from the ledger
            from disco_tpu.flywheel.resident import LEDGER_NAME
            from disco_tpu.runs.ledger import RunLedger

            pubs = [rec for u, rec in
                    RunLedger(train_dir / LEDGER_NAME).replay().items()
                    if u.startswith("publish:") and rec["state"] == "done"
                    and (rec.get("attrs") or {}).get("resumed")]
            if not pubs:
                failures.append(
                    "leg %d: no publish unit carries resumed=True after the "
                    "pre_publish crash — the interrupted publish was not "
                    "drained from the ledger" % leg)

        # -- phase 2: crash the leg's component at its seam -------------------
        if seam is None:
            while (len(_done_rollouts(store)) < MIN_GENERATIONS
                   and cursors[0] < n_blocks):
                if not pace():
                    break
                slo = evaluate_slo(status_payload(srv.scheduler), SLO_TARGETS)
                slo_samples += 1
                slo_breaches += 0 if slo["verdict"] == "OK" else 1
            if len(_done_rollouts(store)) < MIN_GENERATIONS:
                failures.append(
                    f"final leg: only {len(_done_rollouts(store))} "
                    f"generations promoted within the clip budget, need "
                    f">= {MIN_GENERATIONS}")
            cl.close()
            cl.shutdown()
            srv.stop(timeout_s=120)
            tap.close()
        elif seam == "mid_canary":
            # controller-thread death: the server must keep serving
            chaos.configure(seam, after=1)
            try:
                while ctl.crashed is None and cursors[0] < n_blocks - 3:
                    if not pace():
                        break
            finally:
                chaos.disable()
            if not isinstance(ctl.crashed, chaos.ChaosCrash):
                failures.append(f"leg {leg}: mid_canary crash never fired "
                                f"(crashed={ctl.crashed!r})")
            else:
                crashes += 1
            orphan = ctl.current_candidate()
            for _ in range(2):        # a dead controller degrades, never
                pace()                # corrupts — frames keep flowing
            cl.close()
            cl.shutdown()
            srv.stop(timeout_s=120)
            tap.close()
            if orphan is not None:
                ctl_r = PromotionController(store, poll_s=0.01)
                ctl_r.start()
                ctl_r.stop()
                ctl_r.wait()
                rec = store.rollout_ledger().replay().get(rollout_unit(orphan))
                if rec is None or rec["state"] != "failed":
                    failures.append(
                        f"leg {leg}: ledger replay left the orphaned rollout "
                        f"{None if rec is None else rec['state']!r}, "
                        "expected failed")
        else:
            # dispatch-thread seams: trainer (mid_epoch / pre_publish /
            # between_generations) and serve (pre_swap) — the whole
            # process 'dies'
            chaos.configure(seam, after=1)
            fired = False
            try:
                while cursors[0] < n_blocks:
                    if not pace():
                        fired = srv.crashed is not None
                        break
                else:
                    failures.append(f"leg {leg}: {seam} crash never fired "
                                    f"within {n_blocks} paced rounds")
            finally:
                chaos.disable()
            if fired:
                try:
                    srv.wait(timeout_s=60)
                    failures.append(f"leg {leg}: dispatch thread survived "
                                    f"the {seam} crash")
                except chaos.ChaosCrash:
                    crashes += 1
            else:
                # the seam never fired (already a failure above): close the
                # healthy server so the campaign can still report everything
                try:
                    srv.stop(timeout_s=120)
                except chaos.ChaosCrash:
                    crashes += 1
            # complete the simulated process death: a real one takes the
            # controller thread with it, and a zombie controller would keep
            # judging rollouts against the SHARED ledger (its zero-traffic
            # gate timeout demotes candidates of later legs)
            ctl.stop()
            ctl.wait(timeout_s=30)
            cl.shutdown()
            tap.close()
            if seam == "pre_swap":
                interrupted_swap[0] = ctl.current_candidate()

        # -- standing post-leg asserts ---------------------------------------
        _assert_stream(failures, f"leg {leg} ({seam or 'final'})", delivered,
                       cl.gen_of, clip, store)
        for gen_id in store.list_ids():
            try:
                store.load(gen_id)
            except PublishRefused as e:
                failures.append(f"leg {leg}: generation {gen_id} torn: {e}")
        _trainer_ckpt_intact(failures, f"leg {leg}", train_dir)
        _no_litter(failures, f"leg {leg}", store.root, train_dir, tap_dir,
                   state_dir)

    return {"crashes": crashes, "promoted": _done_rollouts(store),
            "slo_breaches": slo_breaches, "slo_samples": slo_samples,
            "store": store, "train_dir": train_dir, "tap_dir": tap_dir}


def _campaign_end_asserts(failures: list, stats: dict) -> None:
    from disco_tpu.flywheel.resident import LEDGER_NAME
    from disco_tpu.runs.ledger import RunLedger

    store = stats["store"]
    promoted = stats["promoted"]
    if len(promoted) < MIN_GENERATIONS:
        failures.append(f"campaign promoted {len(promoted)} generations, "
                        f"need >= {MIN_GENERATIONS}")
    serials = [store.get(g).serial for _, g in promoted]
    if serials != sorted(serials) or len(set(serials)) != len(serials):
        failures.append(f"promotion lineage is not strictly monotone by "
                        f"serial: {serials}")
    if promoted and store.active() != promoted[-1][1]:
        failures.append(f"ACTIVE is {store.active()}, expected the last "
                        f"promoted generation {promoted[-1][1]}")

    # zero re-consumed shard-epoch units, over the RAW trainer ledger
    dupes = {u: n for u, n in _raw_done_counts(
        stats["train_dir"] / LEDGER_NAME, "shard:").items() if n != 1}
    if dupes:
        failures.append(f"shard units consumed more than once: {dupes}")

    # the tap manifest survives every restart with zero digest drift (the
    # shard-numbering resume contract)
    done, requeued = RunLedger(
        stats["tap_dir"] / "manifest.jsonl").verified_done(requeue=False)
    if requeued:
        failures.append(f"tap manifest re-queued {len(requeued)} shards — "
                        "a restarted tap overwrote or tore a shard")

    if stats["slo_samples"] == 0:
        failures.append("slo was never sampled while the trainer ran")
    elif stats["slo_breaches"]:
        failures.append(f"slo breached in {stats['slo_breaches']}/"
                        f"{stats['slo_samples']} samples while training ran")


def main(argv=None) -> int:
    """Run the endurance gate (``make endure-check``); exit 1 on failure.

    No reference counterpart (module docstring)."""
    import os

    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    from disco_tpu import obs

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        obs_log = tmp / "endure_check.jsonl"
        with obs.recording(obs_log):
            obs.write_manifest(tool="endure-check")
            stats = _campaign(failures, tmp)
            _campaign_end_asserts(failures, stats)
            obs.record("counters", **obs.REGISTRY.snapshot())
        events = obs.read_events(obs_log)   # schema-validating read

        def count(kind, action=None):
            return sum(1 for e in events if e["kind"] == kind
                       and (action is None
                            or e["attrs"].get("action") == action))

        if count("generation", "published") < MIN_GENERATIONS:
            failures.append(
                f"event log carries {count('generation', 'published')} "
                f"generation-published events, need >= {MIN_GENERATIONS}")
        if count("promotion", "promoted") < MIN_GENERATIONS:
            failures.append(
                f"event log carries {count('promotion', 'promoted')} "
                f"promoted events, need >= {MIN_GENERATIONS}")
        if count("run_resume") < 1:
            failures.append("event log missing the trainer's run_resume "
                            "event (ledger resume never happened)")
        n_crash_ev = sum(1 for e in events if e["kind"] == "fault"
                         and e["attrs"].get("fault") == "chaos_crash")
        if n_crash_ev != stats["crashes"]:
            failures.append(f"event log carries {n_crash_ev} chaos_crash "
                            f"events, expected {stats['crashes']}")

    if failures:
        for f in failures:
            print(f"endure-check FAIL: {f}", file=sys.stderr)
        return 1
    # byte-stable by construction: constants of the seeded campaign only —
    # no host-speed-dependent counts
    print(json.dumps({
        "endure_check": "ok",
        "legs": len(SEAM_LEGS),
        "crash_seams": [s for s in SEAM_LEGS if s],
        "crashes_injected": len(SEAM_LEGS) - 1,
        "min_generations": MIN_GENERATIONS,
        "canary_window": WINDOW,
        "jax_processes": 1,
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
