"""``make soak-check`` — the chaos-soak gate of the serving survival layer.

The serve subsystem's other gates each prove ONE failure mode in isolation
(serve-check: crash/drain; fault-check: z-exchange faults; chaos-check:
artifact atomicity).  A production outage is never that polite: a client
disconnects while another truncates a frame mid-block while the tunnel
throws a transport error burst.  ``disco-soak`` composes the EXISTING
fault primitives — the chaos seams (:mod:`disco_tpu.runs.chaos`), protocol
truncation, hard connection drops, slow clients, and injected
``TRANSPORT_ERRORS`` through the scheduler's fakeable dispatch hook
(:func:`disco_tpu.serve.scheduler.set_dispatch_fault_injector`) — into K
seeded randomized multi-fault campaigns against a loopback server on CPU,
and asserts the survival invariants after every run:

1. **no torn artifact or shard** — every session checkpoint in the state
   dir passes ``probe_session_state``; every flywheel tap shard passes
   ``probe_shard``.
2. **no delivered frame lost or duplicated** — each client's log of
   received ``enhanced`` seqs is exactly ``0..n_blocks-1``, once each,
   across every drop/park/reattach.
3. **bit-exact reattach** — every session's stitched output equals the
   offline ``streaming_tango`` run of the same clip, byte for byte.
4. **bounded recovery** — after the last injected fault the server drains
   the remaining work within :func:`recovery_tick_bound` scheduler ticks
   (a load-scaled budget: base + per-block slack for the campaign's size).
5. **byte-stable ledger** — the per-seed event summary (planned faults +
   deterministic survival counts distilled from the obs JSONL ledger) is
   byte-identical across runs of the same seed (asserted by literally
   running the first seed twice).

The final schedule adds the crash leg: a parked session's park-checkpoint
must survive a :class:`~disco_tpu.runs.chaos.ChaosCrash` server death and
resume bit-exact on a FRESH server via its resume token — parking is what
turns "the server died" into "the client reattaches somewhere else".

Hermetic like the other gates: CPU backend, loopback sockets only, compile
cache off, ONE jax process (clients are numpy threads), zero SIGKILLs.

No reference counterpart: the reference has no serving layer to soak.
"""
from __future__ import annotations

import json
import socket
import sys
import tempfile
import threading
import time
from pathlib import Path

#: the seeded campaign roster (>= 5 schedules; acceptance criterion)
SEEDS = (201, 202, 203, 204, 205)

#: declared recovery bound: scheduler ticks between the last injected fault
#: and full drain of the remaining work.  The bound is LOAD-SCALED, not a
#: single constant: a seeded campaign draws 2-3 sessions with seed-dependent
#: clip lengths, so the drain tail after the last fault is proportional to
#: the blocks still in flight — a fixed ceiling sized for the smallest draw
#: flaked on the largest one (the eleventh-gate slow-host flake), while one
#: sized for the largest stops binding on the smallest.  A wedged server
#: still blows the scaled bound by orders of magnitude, which is the point.
RECOVERY_TICK_BOUND_BASE = 3000
RECOVERY_TICKS_PER_BLOCK = 50


def recovery_tick_bound(total_blocks: int) -> int:
    """Ticks allowed between the last injected fault and full drain for a
    campaign carrying ``total_blocks`` client blocks.

    No reference counterpart: the reference has no serving layer to soak.
    """
    return RECOVERY_TICK_BOUND_BASE + RECOVERY_TICKS_PER_BLOCK * total_blocks

K, C, U = 4, 2, 4
BLOCK = 2 * U


def _scene(seed, L=16000):
    import numpy as np

    from disco_tpu.core.dsp import stft

    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    # whole blocks only: a ragged final block would compile a third program
    # shape mid-campaign, and XLA compile time mid-soak reads as a fault
    T -= T % BLOCK
    return Y[..., :T], m[..., :T]


def _warm(F: int, n_super: int) -> None:
    """Pre-compile the serve-shaped programs through the production
    scheduler path (one scan group + one per-block dispatch on a throwaway
    scheduler).  Serving fleets warm before taking traffic for the same
    reason this gate does: the first dispatch of a cold program pays
    seconds of XLA compile, which is start-up cost, not a fault — unwarmed
    it would dominate the campaign's queue waits and the first run of a
    seed would not match the second (byte-stability).

    No reference counterpart (module docstring)."""
    import numpy as np

    from disco_tpu.serve import Scheduler

    cap = max(2 * n_super, 2)
    sched = Scheduler(max_sessions=1, max_queue_blocks=cap,
                      max_blocks_per_tick=cap,
                      blocks_per_super_tick=n_super)
    s = sched.open_session(_config(F), session_id="warm")
    Y = np.zeros((K, C, F, BLOCK), np.complex64)
    m = np.ones((K, F, BLOCK), np.float32)
    for i in range(n_super):
        sched.push_block(s, i, Y, m, m)
    sched.tick()                      # the (scan or per-block) program
    if n_super > 1:
        sched.push_block(s, n_super, Y, m, m)
        sched.tick()                  # the per-block tail program
    sched.tick()                      # flush the overlap buffer


def _offline(Y, m):
    import numpy as np

    from disco_tpu.enhance.streaming import streaming_tango

    return np.asarray(
        streaming_tango(Y, m, m, update_every=U, policy="local")["yf"])


def _config(F):
    from disco_tpu.serve import SessionConfig

    return SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                         block_frames=BLOCK, update_every=U)


def plan_campaign(seed: int) -> dict:
    """Expand one seed into a deterministic multi-fault schedule.

    Per session: one connection fault (``drop`` — hard socket kill after a
    drawn delivery, ``truncate`` — a partial frame then EOF mid-stream, or
    ``none``) plus an optional slow-reader delay; per run: a seeded set of
    dispatch-attempt indices that raise an injected transport error (single
    indices retry in place; a consecutive triple exhausts the retry budget
    and exercises quarantine).  Same seed, same plan, same summary —
    ``plan_faults``'s determinism contract applied to the serving layer.

    No reference counterpart (module docstring)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n_sessions = int(2 + rng.integers(0, 2))          # 2-3 clients
    kinds = ["drop", "truncate", "none"]
    faults = [kinds[int(rng.integers(0, len(kinds)))] for _ in range(n_sessions)]
    if all(f == "none" for f in faults):
        faults[0] = "drop"                            # every run multi-faults
    plan = {
        "seed": seed,
        "super_tick": 2 if seed % 2 == 0 else 1,
        "sessions": [
            {
                "sid": f"soak{seed}-{i}",
                "scene_seed": seed * 100 + i,
                "fault": faults[i],
                "drop_after": int(rng.integers(1, 4)),  # deliveries before it
                "slow_ms": int(rng.integers(0, 2)) * 5,  # 0 or 5 ms per block
            }
            for i in range(n_sessions)
        ],
        "crash_leg": seed == SEEDS[-1],
    }
    # transport bursts only on per-block schedules (attempt indices map 1:1
    # to blocks there, so consumption is deterministic); one lone transient
    # plus one exhausting triple
    if plan["super_tick"] == 1 and not plan["crash_leg"]:
        lone = int(rng.integers(2, 6))
        burst = int(rng.integers(8, 11))
        plan["transport_attempts"] = sorted({lone, burst, burst + 1, burst + 2})
    else:
        plan["transport_attempts"] = []
    return plan


class _LoggingClient:
    """A ServeClient + a log of every received ``enhanced`` seq (duplicate
    and loss detection across reattaches).  Built lazily so the module
    imports without the serve package loaded."""

    def __new__(cls, *args, **kwargs):
        from disco_tpu.serve import ServeClient

        class LoggingClient(ServeClient):
            def __init__(self, *a, **k):
                self.seq_log: list[int] = []
                super().__init__(*a, **k)

            def _fold(self, frame):
                if frame.get("type") == "enhanced":
                    self.seq_log.append(int(frame["seq"]))
                super()._fold(frame)

        return LoggingClient(*args, **kwargs)


def _make_injector(attempt_indices):
    """The transport-fault injector: raises ``TimeoutError`` (a
    ``TRANSPORT_ERRORS`` member with no jax dependency) on the planned
    dispatch-attempt indices.  Counts every attempt, including retries —
    which is what makes a consecutive index triple hit one block's whole
    retry chain and exhaust it.

    No reference counterpart (module docstring)."""
    planned = set(attempt_indices)
    state = {"n": 0, "injected": 0, "last_wall": 0.0}

    def injector(_sid, _seqs):
        state["n"] += 1
        if state["n"] - 1 in planned:
            state["injected"] += 1
            state["last_wall"] = time.monotonic()
            raise TimeoutError(
                f"soak: injected transport fault at dispatch attempt "
                f"{state['n'] - 1}")

    return injector, state


def _client_worker(plan_s, addr, Y, m, results, errors, i):
    """One streaming client thread executing its session's fault script."""
    import numpy as np

    cl = _LoggingClient(addr, timeout_s=120.0, reattach_timeout_s=10.0,
                        retry_seed=plan_s["scene_seed"])
    try:
        F = Y.shape[-2]
        cl.open(_config(F), session_id=plan_s["sid"])
        fired = [False]

        def on_block(seq, _yf):
            if plan_s["slow_ms"]:
                time.sleep(plan_s["slow_ms"] / 1e3)
            if fired[0] or seq + 1 != plan_s["drop_after"]:
                return
            fired[0] = True
            if plan_s["fault"] == "drop":
                # a hard network drop: both directions die mid-stream
                cl._sock.shutdown(socket.SHUT_RDWR)
            elif plan_s["fault"] == "truncate":
                # a partial frame then EOF: the server must park the
                # session (nothing reached push_block), never corrupt it
                from disco_tpu.serve import protocol

                frame = protocol.pack_frame({"type": "close"})
                cl._sock.sendall(frame[: max(1, len(frame) // 2)])
                cl._sock.shutdown(socket.SHUT_WR)

        yf = cl.enhance_clip(Y, m, m, on_block=on_block)
        cl.close()
        results[i] = (yf, list(cl.seq_log), cl.reattaches)
    except Exception as e:
        errors.append(f"client {plan_s['sid']}: {type(e).__name__}: {e}")
    finally:
        cl.shutdown()


def run_soak(seed: int, tmp: Path, failures: list) -> dict:
    """One seeded soak campaign; returns the canonical per-seed summary
    dict (deterministic — the byte-stability invariant hashes its JSON).

    No reference counterpart (module docstring)."""
    import numpy as np

    from disco_tpu import obs
    from disco_tpu.flywheel import CorpusTap, list_shards, probe_shard
    from disco_tpu.serve import EnhanceServer, set_dispatch_fault_injector
    from disco_tpu.serve.session import probe_session_state

    plan = plan_campaign(seed)
    scenes = [_scene(s["scene_seed"]) for s in plan["sessions"]]
    refs = [_offline(Y, m) for (Y, m) in scenes]
    n_blocks = [-(-Y.shape[-1] // BLOCK) for (Y, _m) in scenes]
    _warm(scenes[0][0].shape[-2], plan["super_tick"])

    run_dir = tmp / f"seed{seed}"
    run_dir.mkdir(parents=True, exist_ok=True)
    obs_log = run_dir / "events.jsonl"
    tap = CorpusTap(run_dir / "tap", records_per_shard=8)
    injector, inj_state = _make_injector(plan["transport_attempts"])

    summary: dict = {"seed": seed, "plan": plan}
    with obs.recording(obs_log):
        srv = EnhanceServer(
            max_sessions=8, state_dir=run_dir / "state", tap=tap,
            blocks_per_super_tick=plan["super_tick"],
            park_ttl_s=60.0, quarantine_ticks=5, tick_deadline_s=10.0,
            dispatch_retries=2, retry_seed=seed, ladder=True,
        )
        srv.scheduler.dispatch_retry_base_s = 0.002
        set_dispatch_fault_injector(injector)
        try:
            addr = srv.start()
            results: list = [None] * len(scenes)
            errors: list = []
            threads = [
                threading.Thread(
                    target=_client_worker,
                    args=(plan["sessions"][i], addr, scenes[i][0], scenes[i][1],
                          results, errors, i))
                for i in range(len(scenes))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            recovery_start_tick = srv.scheduler.tick_no
            failures.extend(f"seed {seed}: {e}" for e in errors)
            srv.stop(timeout_s=120)
            if plan["crash_leg"]:
                # runs with the campaign server fully stopped: the chaos
                # seam is process-global, and exactly ONE server must be
                # ticking when it fires
                summary["crash_leg"] = _crash_leg(seed, run_dir, failures)
        finally:
            set_dispatch_fault_injector(None)
            tap_stats = tap.close()

        # invariant 2 + 3: per-session loss/duplication and bit-exactness
        reattaches_total = 0
        for i, s in enumerate(plan["sessions"]):
            if results[i] is None:
                failures.append(f"seed {seed}: session {s['sid']} returned nothing")
                continue
            yf, seq_log, reattaches = results[i]
            reattaches_total += reattaches
            if sorted(seq_log) != list(range(n_blocks[i])):
                dup = sorted({q for q in seq_log if seq_log.count(q) > 1})
                missing = sorted(set(range(n_blocks[i])) - set(seq_log))
                failures.append(
                    f"seed {seed}: session {s['sid']} delivered frames "
                    f"lost={missing} duplicated={dup}"
                )
            if not np.array_equal(yf, refs[i]):
                failures.append(
                    f"seed {seed}: session {s['sid']} stitched output is not "
                    f"bit-exact vs offline streaming_tango (max abs diff "
                    f"{np.abs(yf - refs[i]).max():g})"
                )
        # invariant 4: bounded recovery — every block was already delivered
        # when the clients joined; the tick budget bounds how long the tail
        # (reattach + quarantine release + drain) took after the LAST fault
        ticks_total = srv.scheduler.tick_no
        tick_bound = recovery_tick_bound(sum(n_blocks))
        if ticks_total - recovery_start_tick > tick_bound:
            failures.append(
                f"seed {seed}: drain took {ticks_total - recovery_start_tick} "
                f"ticks after the campaign (> {tick_bound} for "
                f"{sum(n_blocks)} blocks)"
            )

        # invariant 1: no torn artifact or shard
        state_dir = run_dir / "state"
        checkpoints = sorted(state_dir.glob("*.msgpack")) if state_dir.is_dir() else []
        for p in checkpoints:
            if not probe_session_state(p):
                failures.append(f"seed {seed}: torn session checkpoint {p}")
        shards = list_shards(run_dir / "tap")
        for p in shards:
            if not probe_shard(p):
                failures.append(f"seed {seed}: torn tap shard {p}")
        if tap_stats["blocks_dropped"]:
            failures.append(
                f"seed {seed}: tap dropped {tap_stats['blocks_dropped']} "
                "blocks at soak load")

    # invariant 5: the byte-stable ledger — the plan plus deterministic
    # survival facts distilled from the validated event log.  Counts whose
    # value depends on scheduling races (exact park/reattach totals — a
    # drop can surface once on the read path or twice via read+send,
    # whether a park checkpoint landed before the reattach, shard rotation
    # timing) are asserted as INVARIANTS below but summarized as booleans;
    # wall times and tick counts never enter the summary at all.
    events = obs.read_events(obs_log)
    campaign_ids = {s["sid"] for s in plan["sessions"]}
    acts = [e["attrs"].get("action") for e in events
            if e["kind"] == "session"
            and e["attrs"].get("session") in campaign_ids]
    n_faults = sum(1 for s in plan["sessions"] if s["fault"] != "none")
    parks, reatt = acts.count("park"), acts.count("reattach")
    spurious_degrades = sum(
        1 for e in events if e["kind"] == "degraded"
        and e["attrs"].get("controller") == "ladder")
    summary.update({
        "sessions": len(plan["sessions"]),
        "blocks": n_blocks,
        "connection_faults": n_faults,
        "transport_faults_planned": len(plan["transport_attempts"]),
        "transport_faults_injected": inj_state["injected"],
        "quarantines": acts.count("quarantine"),
        "evictions": acts.count("evict"),
        "all_parks_reattached": parks == reatt and parks >= n_faults,
        "spurious_ladder_degrades": spurious_degrades,
        "torn_artifacts": 0,   # any torn probe above is a failure + exit 1
    })
    if summary["transport_faults_injected"] != len(plan["transport_attempts"]):
        failures.append(
            f"seed {seed}: injected {summary['transport_faults_injected']} "
            f"transport faults, planned {len(plan['transport_attempts'])}"
        )
    if not summary["all_parks_reattached"]:
        failures.append(
            f"seed {seed}: {n_faults} connection fault(s), {parks} park(s), "
            f"{reatt} reattach(es) — a park never reattached (or a fault "
            f"evicted instead of parking)"
        )
    if summary["evictions"]:
        failures.append(
            f"seed {seed}: {summary['evictions']} eviction(s) during the "
            "soak — every faulted session must park and reattach")
    if spurious_degrades:
        failures.append(
            f"seed {seed}: the ladder degraded {spurious_degrades}x during "
            "a light-load soak — outage latency is leaking into the "
            "ladder's queue-wait p95")
    return summary


def _crash_leg(seed: int, run_dir: Path, failures: list) -> dict:
    """The crash schedule of the final seed: a parked session's checkpoint
    survives a ChaosCrash server death and resumes bit-exact on a fresh
    server via the resume token.

    No reference counterpart (module docstring)."""
    import numpy as np

    from disco_tpu.runs import chaos
    from disco_tpu.serve import EnhanceServer, ServeClient, ServeError

    Y, m = _scene(seed * 100 + 77)
    F, T = Y.shape[-2:]
    ref = _offline(Y, m)
    n_blocks = -(-T // BLOCK)
    half = max(1, n_blocks // 2)
    state_dir = run_dir / "crash_state"

    srv = EnhanceServer(max_sessions=4, state_dir=state_dir, park_ttl_s=60.0)
    addr = srv.start()
    cl = ServeClient(addr, reattach_retries=0)
    cl.open(_config(F), session_id="crashee")
    outs = {}
    for i in range(half):
        lo, hi = i * BLOCK, (i + 1) * BLOCK
        cl.send_block(Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
        outs[i] = cl.recv_enhanced(i, timeout_s=60)
    cl.shutdown()           # deliberate disconnect: the session PARKS
    ckpt = state_dir / "session_crashee.state.msgpack"
    deadline = time.monotonic() + 30.0
    while not ckpt.is_file() and time.monotonic() < deadline:
        time.sleep(0.01)    # the park checkpoint lands on the next tick
    if not ckpt.is_file():
        failures.append(f"seed {seed}: park checkpoint never written")
    # now the server dies mid-tick, like a process death: arm the seam and
    # WAIT for the dispatch loop to hit it (calling stop() here would win
    # the race — the drain path exits after a single tick)
    chaos.configure("serve_tick", after=3)
    crashed = False
    try:
        deadline = time.monotonic() + 30.0
        while srv.crashed is None and time.monotonic() < deadline:
            time.sleep(0.005)
        try:
            srv.wait(timeout_s=30)
        except chaos.ChaosCrash:
            crashed = True
    finally:
        chaos.disable()
    if not crashed:
        failures.append(f"seed {seed}: chaos serve_tick crash never fired")
    from disco_tpu.serve.session import probe_session_state

    if not probe_session_state(ckpt):
        failures.append(f"seed {seed}: park checkpoint torn by the crash")

    # a FRESH server: the resume token reattaches through the checkpoint
    srv2 = EnhanceServer(max_sessions=4, state_dir=state_dir)
    addr2 = srv2.start()
    try:
        cl2 = ServeClient(addr2)
        cl2.open(_config(F), resume="crashee")
        if cl2.blocks_done != half:
            failures.append(
                f"seed {seed}: crash-resume started at {cl2.blocks_done}, "
                f"expected {half}")
        rest = cl2.enhance_clip(Y, m, m)
        cl2.close()
        cl2.shutdown()
    finally:
        srv2.stop(timeout_s=120)
    full = np.concatenate(
        [np.concatenate([outs[i] for i in range(half)], axis=-1), rest],
        axis=-1)
    if not np.array_equal(full, ref):
        failures.append(
            f"seed {seed}: crash-resume stitch is not bit-exact "
            f"(max abs diff {np.abs(full - ref).max():g})")
    return {"blocks_before_park": half, "blocks_total": n_blocks,
            "crash_injected": crashed}


def main(argv=None) -> int:
    """Run the chaos-soak gate (``make soak-check``); exit 1 on failure.

    No reference counterpart (module docstring)."""
    import os

    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    failures: list[str] = []
    summaries = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        for seed in SEEDS:
            summaries.append(run_soak(seed, tmp / "a", failures))
        # the byte-stability invariant, asserted literally: rerun the first
        # seed in a fresh directory and compare summaries byte for byte
        rerun = run_soak(SEEDS[0], tmp / "b", failures)
        first = json.dumps(summaries[0], sort_keys=True).encode()
        again = json.dumps(rerun, sort_keys=True).encode()
        if first != again:
            failures.append(
                f"seed {SEEDS[0]}: event summary is not byte-stable across "
                f"runs:\n  {first.decode()}\n  {again.decode()}"
            )

    if failures:
        for f in failures:
            print(f"soak-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "soak_check": "ok",
        "schedules": len(SEEDS),
        "connection_faults": sum(s["connection_faults"] for s in summaries),
        "transport_faults": sum(s["transport_faults_injected"] for s in summaries),
        "all_parks_reattached": all(s["all_parks_reattached"] for s in summaries),
        "quarantines": sum(s["quarantines"] for s in summaries),
        "crash_legs": sum(1 for s in summaries if "crash_leg" in s),
        "byte_stable_seeds": 1,
        "recovery_tick_bound_base": RECOVERY_TICK_BOUND_BASE,
        "recovery_ticks_per_block": RECOVERY_TICKS_PER_BLOCK,
        "jax_processes": 1,
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
