"""Graceful interruption: SIGTERM/SIGINT → finish the unit, flush, exit
resumable.

The environment contract (CLAUDE.md) forbids SIGKILL on a TPU-holding
process — a killed holder wedges the remote chip claim for hours.  The
operational consequence: the ONLY way to stop a long run is to ask it
nicely, so stopping nicely must actually work.  :class:`GracefulInterrupt`
is that story:

* the **first** SIGTERM/SIGINT sets flags and returns — nothing else: no
  exception into the pipeline, and no telemetry from handler context (the
  ``interrupted`` obs event is emitted by the next poll; the handler is
  flag-only by the disco-race DR003 contract), so the in-flight fenced
  dispatch drains normally and the current work unit completes and
  persists (atomically, ``disco_tpu.io.atomic``);
* the long-running loops (batched enhancement chunks, datagen scenes,
  training epochs) poll :func:`stop_requested` between units and wind down:
  flush the run ledger, record final counters, return partial results —
  the run is then resumable with ``--resume``;
* a **second** SIGINT raises ``KeyboardInterrupt`` — the operator insists,
  and an in-process unwind is still contract-safe (``utils.resilience``
  never catches it).

SIGTERM matters as much as Ctrl-C: it is what schedulers and container
runtimes send before escalating, and handling it is what keeps the
escalation (SIGKILL) from ever happening.

Handlers install only in the main thread (Python's signal rule); from
worker threads :class:`GracefulInterrupt` degrades to a pure poll flag that
:func:`request_stop` can set programmatically (used by tests and the chaos
harness).

No reference counterpart: the reference's runs are short enough to simply
restart from scratch.
"""
from __future__ import annotations

import contextlib
import signal
import threading

_lock = threading.Lock()
_active: list["GracefulInterrupt"] = []


def stop_requested() -> bool:
    """True once a graceful stop was requested anywhere in the process.
    The poll the long-running loops call between work units; False when no
    :class:`GracefulInterrupt` scope is active.  Polling also flushes any
    telemetry a signal handler deferred (see :meth:`GracefulInterrupt.
    _flush_telemetry`)."""
    with _lock:
        scopes = list(_active)
    for g in scopes:
        g._flush_telemetry()
    return any(g.stopped for g in scopes)


def request_stop(reason: str = "programmatic") -> bool:
    """Programmatically request a graceful stop on the innermost active
    scope (tests, chaos harness, in-process embedders).  Returns False when
    no scope is active."""
    with _lock:
        if not _active:
            return False
        scope = _active[-1]
    scope._trip(reason)
    return True


class GracefulInterrupt(contextlib.AbstractContextManager):
    """Scoped SIGTERM/SIGINT handler implementing the drain-and-exit
    protocol.

    >>> with GracefulInterrupt() as stop:
    ...     for unit in work:
    ...         if stop():          # or runs.interrupt.stop_requested()
    ...             break           # ledger flushed by the caller; resumable
    ...         process(unit)

    ``as``-binds a zero-argument callable returning the stop flag, so deep
    call sites can also poll the module-level :func:`stop_requested`.
    """

    def __init__(self, signals=(signal.SIGINT, signal.SIGTERM)):
        self.signals = tuple(signals)
        self.stopped = False
        self.reason: str | None = None
        self._prev: dict[int, object] = {}
        self._installed = False
        self._sigint_count = 0
        self._telemetry_sent = False

    # -- signal plumbing ----------------------------------------------------
    def _trip(self, reason: str) -> None:
        """Programmatic stop (``request_stop``, tests, chaos harness):
        set the flags and emit immediately — normal code, locks allowed."""
        # disco-race: disable=DR007 -- monotonic one-way flag: _trip (main) and the handler both only ever store True; a racing pair of stores is idempotent
        self.stopped = True
        self.reason = self.reason or reason  # disco-race: disable=DR007 -- first-writer-wins string; both writers guard with `or`, and a torn outcome only affects the human-readable reason label
        self._flush_telemetry()

    def _flush_telemetry(self) -> None:
        # Unlocked fast path first: both flags are monotonic, so the
        # common not-stopped/already-sent poll (this is the prefetch
        # loader's per-iteration stop callback) pays no lock.  The SENT
        # transition is lock-guarded: stop_requested() polls from ANY
        # thread, and two pollers racing an unguarded check would both
        # emit the one-shot `interrupted` event.  The emission itself
        # happens OUTSIDE the lock — obs takes its own non-reentrant locks
        # (disco-race DR004: never block or nest under a held lock).
        if not self.stopped or self._telemetry_sent:
            return
        with _lock:
            if self._telemetry_sent:
                return
            self._telemetry_sent = True
        from disco_tpu.obs import events as _events
        from disco_tpu.obs.metrics import REGISTRY as _REGISTRY

        _REGISTRY.counter("interrupts").inc()
        _events.record("interrupted", reason=self.reason)

    def _handler(self, signum, frame):
        # FLAG-ONLY by contract (disco-race DR003, the PR 3 bug class): a
        # signal handler runs on the main thread at an arbitrary bytecode
        # boundary — possibly INSIDE obs's non-reentrant locks
        # (Recorder._lock, Counter._lock) or our own module _lock.
        # Emitting telemetry or taking ANY lock here could self-deadlock
        # the interrupted frame, so the handler stores the stop flags and
        # returns; the next stop_requested() poll (normal code) emits.
        # tests/test_race.py pins this shape from both sides: the live
        # handler passes the gate, and a revert fixture that re-inlines
        # the telemetry emission fails it.
        name = signal.Signals(signum).name
        if signum == signal.SIGINT:
            self._sigint_count += 1
            if self._sigint_count >= 2:
                # the operator insists: in-process unwind (contract-safe —
                # never SIGKILL; resilience never catches KeyboardInterrupt)
                raise KeyboardInterrupt(f"second {name}")
        self.stopped = True
        self.reason = self.reason or name

    # -- context protocol ---------------------------------------------------
    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for sig in self.signals:
                self._prev[sig] = signal.getsignal(sig)
                signal.signal(sig, self._handler)
            self._installed = True
        with _lock:
            _active.append(self)

        def stopped():
            self._flush_telemetry()
            return self.stopped

        return stopped

    def __exit__(self, *exc):
        with _lock:
            with contextlib.suppress(ValueError):
                _active.remove(self)
        if self._installed:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)  # disco-race: disable=DR001 -- restores the handler SAVED at __enter__ (whatever was installed before this scope); there is no static target to register
            self._installed = False
        self._flush_telemetry()  # a trip no poll observed still records
        return False
