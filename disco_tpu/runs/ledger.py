"""Append-only run ledger: per-work-unit state with verified resume.

The long-haul jobs — corpus enhancement (thousands of RIRs), dataset
generation, multi-hour CRNN training — need a restart story stronger than
"does the output file exist".  The ledger is an append-only JSONL record of
per-unit state transitions::

    {"t": <unix>, "unit": "rir:11001:ssn", "state": "in_flight", "attrs": {...}}
    {"t": <unix>, "unit": "rir:11001:ssn", "state": "done",
     "artifacts": {"results/.../results_mwf_11001_ssn.p": "sha256:..."},
     "attrs": {...}}

States: ``pending`` → ``in_flight`` → ``done`` | ``failed``; a ``requeued``
record (appended by verification) voids an earlier ``done``.  Appends are
single ``write`` calls of one line, flushed and fsynced per transition —
crash-durable, and a torn final line (the one crash artifact an append-only
log can have) is detected and skipped on replay.

**Verified resume** is the point: :meth:`RunLedger.verified_done` replays
the log and re-checks every done unit against its recorded artifacts —
digest match (:func:`disco_tpu.io.atomic.file_digest`) when recorded,
integrity probe otherwise.  A unit whose artifacts are missing or corrupt
is *requeued* (a ``requeued`` line is appended, the ``units_requeued``
counter ticks, a ``warning`` obs event fires) and reported as not-done, so
the driver re-runs it.  Truncated files are never trusted — the failure
mode of the existence-only guards this replaces (pre-PR-3
``enhance/driver.py:378/626``).

No reference counterpart: the reference's restartability is existence
checks per output file (SURVEY.md §5.3).
"""
from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

from disco_tpu.io.atomic import file_digest, probe_artifact, verify_digest

#: Legal ledger states, in lifecycle order.
LEDGER_STATES = ("pending", "in_flight", "done", "failed", "requeued")


def unit_rir(rir, noise: str) -> str:
    """Work-unit id of one enhanced RIR (``enhance_rir(s_batched)``)."""
    return f"rir:{rir}:{noise}"


def unit_scene(rir_id) -> str:
    """Work-unit id of one generated datagen scene."""
    return f"scene:{rir_id}"


def unit_epoch(epoch) -> str:
    """Work-unit id of one training epoch."""
    return f"epoch:{epoch}"


def digest_artifacts(paths) -> dict:
    """{str(path): sha256 digest} over finished artifact files — the
    payload of a ``done`` record.

    Paths that do not exist are OMITTED rather than raised on: the ledger
    catch-up path records clips whose completion markers are intact but
    whose secondary artifacts may have been cleaned up (a pre-ledger corpus
    where only the OIM pickles feed aggregation is a normal sight), and a
    done record must certify what is there, not crash the resume that is
    trying to recover.  Files present at record time remain fully verified
    on every later resume."""
    return {str(p): file_digest(p) for p in paths if Path(p).is_file()}


class RunLedger:
    """Append-only JSONL state ledger for one run directory.

    Thread-safe (the batched driver marks units done from scoring worker
    threads).  The file handle opens lazily in append mode, so constructing
    a ledger for a path never truncates an existing log — resume appends to
    the same history.
    """

    def __init__(self, path):
        self.path = Path(path)
        self._fh = None
        self._lock = threading.Lock()

    # -- append side --------------------------------------------------------
    def record(self, unit: str, state: str, artifacts: dict | None = None, **attrs):
        """Append one state transition, flushed + fsynced (a transition that
        was reported must survive the very next crash)."""
        if state not in LEDGER_STATES:
            raise ValueError(f"unknown ledger state {state!r} (known: {LEDGER_STATES})")
        line = json.dumps(
            {"t": time.time(), "unit": unit, "state": state,
             "artifacts": artifacts, "attrs": attrs},
            default=str,
        )
        with self._lock:
            if self._fh is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(line + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def mark_in_flight(self, unit: str, **attrs):
        self.record(unit, "in_flight", **attrs)

    def mark_done(self, unit: str, artifact_paths=(), **attrs):
        """Record completion WITH the artifact digests that make the claim
        verifiable on resume."""
        self.record(unit, "done", artifacts=digest_artifacts(artifact_paths), **attrs)

    def mark_failed(self, unit: str, error: str = "", **attrs):
        self.record(unit, "failed", error=error, **attrs)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- replay side --------------------------------------------------------
    def replay(self) -> dict:
        """{unit: latest record dict} from the log.  A torn final line
        (crash mid-append) is skipped; a torn line anywhere else is treated
        the same — every line is independent, so one bad line never poisons
        the rest of the history."""
        state: dict[str, dict] = {}
        if not self.path.exists():
            return state
        with open(self.path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn append — the crash artifact replay expects
                if not isinstance(rec, dict) or "unit" not in rec or "state" not in rec:
                    continue
                state[rec["unit"]] = rec
        return state

    def verified_done(self, requeue: bool = True) -> tuple[set, dict]:
        """Replay and VERIFY: returns ``(done_units, requeued)``.

        A unit counts as done only if its latest state is ``done`` AND every
        recorded artifact checks out — digest match when the record carries
        one, format probe (:func:`probe_artifact`) when it does not.  Units
        that fail verification are returned in ``requeued`` ({unit: reason})
        and, when ``requeue`` is true, get a ``requeued`` line appended (so
        the next replay doesn't re-hash them), a ``units_requeued`` counter
        tick and a ``warning`` obs event — corrupt partials are loud, never
        silently trusted.
        """
        from disco_tpu.obs import events as _events
        from disco_tpu.obs.metrics import REGISTRY as _REGISTRY

        done: set = set()
        requeued: dict[str, str] = {}
        for unit, rec in self.replay().items():
            if rec["state"] != "done":
                continue
            reason = None
            for pathstr, digest in (rec.get("artifacts") or {}).items():
                if digest:
                    if not verify_digest(pathstr, digest):
                        reason = (f"artifact {pathstr} "
                                  + ("missing" if not Path(pathstr).exists() else "digest mismatch"))
                        break
                elif not probe_artifact(pathstr):
                    reason = f"artifact {pathstr} missing or failed its integrity probe"
                    break
            if reason is None:
                done.add(unit)
            else:
                requeued[unit] = reason
                if requeue:
                    self.record(unit, "requeued", reason=reason)
                _REGISTRY.counter("units_requeued").inc()
                _events.record("warning", stage="resume", unit=unit, reason=reason)
        return done, requeued
