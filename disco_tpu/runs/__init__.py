"""disco_tpu.runs — crash-safe run management for the long-haul entry points.

The process-layer complement to ``disco_tpu.fault`` (which made the
*logical* comms layer fault-tolerant in PR 2): corpus enhancement, dataset
generation and CRNN training are hours-long batch jobs on hardware where a
process must never be SIGKILLed (CLAUDE.md), so crashes, preemptions and
operator stops have to be survivable by construction:

* :mod:`disco_tpu.runs.ledger`    — append-only JSONL per-work-unit state
  with **verified resume**: done entries are re-checked against their
  artifact digests and corrupt/missing units are requeued.
* :mod:`disco_tpu.runs.interrupt` — graceful SIGTERM/SIGINT handling:
  finish the in-flight unit, flush ledger + obs, exit resumable.
* :mod:`disco_tpu.runs.chaos`     — deterministic in-process crash
  injection at named seams, driving the ``make chaos-check`` gate
  (:mod:`disco_tpu.runs.check`): interrupt a miniature corpus run, resume
  it, assert the artifact tree is byte-identical to an uninterrupted run.

Atomic artifact writes and integrity probes live in
:mod:`disco_tpu.io.atomic`; preflight device health lives in
:func:`disco_tpu.utils.resilience.preflight_probe`.
"""
from disco_tpu.runs.chaos import ChaosCrash
from disco_tpu.runs.interrupt import GracefulInterrupt, request_stop, stop_requested
from disco_tpu.runs.ledger import (
    RunLedger,
    digest_artifacts,
    unit_epoch,
    unit_rir,
    unit_scene,
)

__all__ = [
    "ChaosCrash",
    "GracefulInterrupt",
    "RunLedger",
    "digest_artifacts",
    "request_stop",
    "stop_requested",
    "unit_epoch",
    "unit_rir",
    "unit_scene",
]
