"""Deterministic crash injection at configurable seams (the chaos harness).

A crash-safety claim is untestable without crashes, and real crashes are
forbidden here: the environment contract bans SIGKILL on a TPU-holding
process (a killed holder wedges the remote claim for hours).  So the chaos
harness *simulates* the crash in-process: production code calls
:func:`tick` at its crash seams, and when a seam is armed the Nth hit
raises :class:`ChaosCrash` — a ``BaseException`` subclass, so it unwinds
through every ``except Exception`` guard exactly like a process death
would, without ever touching the chip claim.

Seams wired through the pipeline (each a named :func:`tick` call):

* ``mid_write``      — inside :func:`disco_tpu.io.atomic.atomic_write`,
  after the payload bytes but before the atomic rename (the classic
  truncated-artifact window).
* ``between_clips``  — after one RIR's artifacts are fully persisted
  (``enhance/driver.py``).
* ``mid_epoch``      — inside the training epoch loop, after the train
  pass but before validation/checkpointing (``nn/training.py``).
* ``between_scenes`` — after one generated scene is saved
  (``datagen/disco.py``).
* ``pre_fence``      — immediately before a fenced device readback
  (``milestones._fence_readback``), the seam where a tunnel drop kills an
  unprepared run.
* ``pre_dispatch``   — before a batched chunk is dispatched to the device
  (``enhance/driver.py``), i.e. crash with work enqueued but unscored.
* ``chunk_load``     — at the start of a corpus chunk's wav ingest, right
  after its ledger ``in_flight`` marks (``enhance/driver.py``).  Under the
  pipelined engine this seam runs on the PREFETCH thread — the injected
  ``ChaosCrash`` is re-delivered at the consuming dispatch loop
  (``enhance/pipeline.ChunkPrefetcher``), so a crash during background
  loading still kills the run like a process death would.
* ``serve_tick``     — at the top of every online-serving scheduler tick
  (``serve/scheduler.py``), on the dispatch thread: the injected crash
  kills the server mid-stream (connections drop, nothing more is
  written), which is what lets ``make serve-check`` prove no client ever
  observes a truncated frame and every checkpoint survives intact.
* ``pre_swap``       — inside the promotion rollout, on the DISPATCH
  thread (``serve/scheduler.py``): a swap has been decided (recorded
  in the promote ledger) but no session has been moved yet — a crash
  here kills the server mid-rollout and must leave every session on
  the incumbent with the rollout resumable from the ledger.
* ``mid_canary``     — during an open canary window, after canary
  sessions are live on the candidate but before the gate has enough
  scores: a crash here must leave each session on exactly ONE intact
  generation, and a restart re-adopts or rolls back from the ledger.
* ``post_gate``      — after the gate verdict (promote or demote) is
  computed but before the ledger records it: the classic
  decided-but-not-durable window.
* ``pre_publish``    — inside the resident trainer
  (``flywheel/resident.py``): an epoch's checkpoint is durable and its
  ledger unit done, but the generation has not been staged yet — a crash
  here must resume WITHOUT re-consuming the epoch's shards and the
  restart must still publish the checkpoint (no lost generation).
* ``between_generations`` — after one generation is fully published
  (``flywheel/resident.py``): the clean boundary between two
  generations — a crash here must leave the store with only complete,
  digest-verified generations and the trainer resumable at the next
  epoch.
* ``between_scene_batches`` — after one simulated scene batch's training
  windows are fully yielded (``scenes/stream.py``) and after one batched
  datagen round is fully saved (``datagen/disco.py``): the scenario
  factory's clean boundary — a crash here must leave only complete,
  ledger-done scene batches, with the resumed run skipping them
  byte-identically (``make scene-check``'s crash-and-resume leg).

Injection is armed either programmatically (:func:`configure`) or via the
``DISCO_TPU_CHAOS`` environment variable (``"seam"`` or ``"seam:N"`` —
crash at the Nth hit, default 1), read once at first :func:`tick`.  The
plan is deterministic: same seam, same N, same run → same crash point,
which is what lets ``make chaos-check`` assert byte-identical recovery.

Disabled cost: one module-level ``is None`` check per tick — the seams are
free in production.

No reference counterpart: the reference cannot resume, so it has nothing
to chaos-test.
"""
from __future__ import annotations

import os
import threading


#: The closed set of production crash seams (each documented in the module
#: docstring above).  ``disco-lint`` rule DL010 checks every
#: ``tick("<seam>")`` string literal in the pipeline against this registry —
#: a typo'd seam name would otherwise arm nothing and a chaos experiment
#: would silently test nothing.  Runtime stays permissive (tests arm
#: synthetic seams); registration is a lint-time contract.
SEAMS = frozenset(
    {
        "mid_write",       # io.atomic, between payload bytes and rename
        "between_clips",   # enhance/driver.py, after one RIR persisted
        "mid_epoch",       # nn/training.py, post-train pre-checkpoint
        "between_scenes",  # datagen/disco.py, after one scene saved
        "pre_fence",       # milestones._fence_readback
        "pre_dispatch",    # enhance/driver.py, chunk about to dispatch
        "chunk_load",      # enhance/driver.py, on the prefetch thread
        "between_blocks",  # enhance/streaming.py, streaming block loop
        "serve_tick",      # serve/scheduler.py, top of a scheduler tick
        "pre_swap",        # serve/scheduler.py, swap decided but not yet applied
        "mid_canary",      # promote/controller.py, canary window open, scores partial
        "post_gate",       # promote/controller.py, verdict reached, ledger not yet final
        "pre_publish",     # flywheel/resident.py, checkpoint done, generation not staged
        "between_generations",  # flywheel/resident.py, one generation fully published
        "between_scene_batches",  # scenes/stream.py + datagen/disco.py, one scene batch complete
    }
)


class ChaosCrash(BaseException):
    """An injected crash.  Inherits ``BaseException`` (like
    ``KeyboardInterrupt``) so pipeline-internal ``except Exception``
    recovery — retry wrappers, best-effort plotting — cannot swallow it:
    a simulated process death must kill the run, that is its job."""

    def __init__(self, seam: str, hit: int):
        super().__init__(f"injected chaos crash at seam {seam!r} (hit {hit})")
        self.seam = seam
        self.hit = hit


class _Plan:
    __slots__ = ("seam", "after", "hits", "lock")

    def __init__(self, seam: str, after: int):
        if after < 1:
            raise ValueError(f"chaos 'after' must be >= 1, got {after}")
        self.seam = seam
        self.after = after
        self.hits = 0
        self.lock = threading.Lock()


_PLAN: _Plan | None = None
_ENV_READ = False

#: Environment switch: ``DISCO_TPU_CHAOS="between_clips"`` or
#: ``DISCO_TPU_CHAOS="mid_write:3"`` (crash at the 3rd hit).
ENV_VAR = "DISCO_TPU_CHAOS"


def configure(seam: str, after: int = 1) -> None:
    """Arm the chaos plan: raise :class:`ChaosCrash` at the ``after``-th
    :func:`tick` of ``seam``.  One seam at a time — chaos engineering is
    about one controlled failure per experiment."""
    global _PLAN, _ENV_READ
    _PLAN = _Plan(seam, after)
    _ENV_READ = True  # explicit configuration wins over the env var


def disable() -> None:
    """Disarm injection (the resume half of an interrupt-resume test)."""
    global _PLAN, _ENV_READ
    _PLAN = None
    _ENV_READ = True


def active() -> bool:
    """True when a chaos plan is armed."""
    return _PLAN is not None


def _maybe_read_env() -> None:
    global _ENV_READ, _PLAN
    if _ENV_READ:
        return
    _ENV_READ = True
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return
    seam, _, n = spec.partition(":")
    _PLAN = _Plan(seam.strip(), int(n) if n else 1)


def tick(seam: str, **attrs) -> None:
    """Production crash seam: no-op unless chaos is armed for ``seam``, in
    which case the configured hit raises :class:`ChaosCrash` after
    recording a ``fault`` obs event (kind ``chaos_crash``) — the injected
    death is first-class telemetry like every other fault."""
    if _PLAN is None:
        _maybe_read_env()
        if _PLAN is None:
            return
    plan = _PLAN
    if plan is None or plan.seam != seam:
        return
    with plan.lock:
        plan.hits += 1
        hit = plan.hits
    if hit != plan.after:
        return
    from disco_tpu.obs import events as _events
    from disco_tpu.obs import flight as _flight
    from disco_tpu.obs.metrics import REGISTRY as _REGISTRY

    _REGISTRY.counter("chaos_crashes").inc()
    _events.record("fault", stage=seam, fault="chaos_crash", hit=hit, **attrs)
    # the flight ring's last act before the simulated death: dump what led
    # here (no-op unless armed; the dump is atomic, so even this crash
    # cannot leave a torn post-mortem)
    _flight.auto_dump("chaos_crash", reason=f"seam {seam!r} hit {hit}")
    raise ChaosCrash(seam, hit)


def _reset_for_tests() -> None:
    """Re-arm env reading (test isolation; never called in production)."""
    global _PLAN, _ENV_READ
    _PLAN = None
    _ENV_READ = False
