"""disco_tpu.analysis.race — static thread-contract analysis.

The paper's "distributed" arrays were simulated in one single-threaded
process (SURVEY §0: inter-node communication is ``np.concatenate``), but
this rebuild made concurrency real: the serve stack alone runs an asyncio
I/O thread against a single jax dispatch thread, with prefetch loaders,
the corpus-tap writer, watchdog timers, client readers and signal handlers
around it — and every invariant that keeps those from deadlocking ("ONE
jax thread per the chip-claim contract", "handlers only set flags", "never
block a tick holding the registry lock") lived only in docstrings until
this package.  ``disco-race`` turns them into whole-program checks over a
statically built call graph, gated in CI as ``make race-check`` — the
thirteenth gate, right after ``trace-check``.

Like :mod:`disco_tpu.analysis` (disco-lint) the analyzer is stdlib-only:
no jax import anywhere under ``race/`` (pinned by test), so the gate is
hermetic and never touches the tunneled chip claim.

* :mod:`.roles`      — the declared thread-role registry (every spawn site
  must resolve into it) + the explicit dynamic-dispatch fallbacks
* :mod:`.registries` — the named-lock registry (every ``threading.Lock``
  must be a registered module- or instance-level attribute)
* :mod:`.callgraph`  — AST index + module-qualified call resolution
* :mod:`.checks`     — the DRnnn contract checks (catalog in its docstring)
* :mod:`.manifest`   — the committed concurrency manifest
  (``analysis/golden/threads.json``) and its drift diff
* :mod:`.runner`     — the whole-program engine (:func:`analyze`)
* :mod:`.cli`        — the ``disco-race`` console entry

Suppressions reuse the shared machinery of
:mod:`disco_tpu.analysis.suppressions` with the ``disco-race`` marker::

    self.expired = True  # disco-race: disable=DR007 -- single bool store

No reference counterpart: the reference repo is single-threaded end to end
and has no static analysis of any kind.
"""
from disco_tpu.analysis.race.runner import RaceResult, analyze

__all__ = ["RaceResult", "analyze"]
