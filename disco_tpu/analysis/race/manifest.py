"""The committed concurrency manifest: the repo's threading topology as a
reviewable artifact.

``analysis/golden/threads.json`` records, per role, the entry points and
the registered locks its reachable code acquires, plus the global spawn
map and the lock-order edges — a pure function of the program model
(canonical JSON: sorted keys, fixed indent, trailing newline), so
rebuilding unchanged code reproduces it bit-identically, exactly like the
trace goldens.  Any drift — a new thread, a role acquiring a lock it
never held, a new lock-order edge — fails DR008 until ``disco-race
--update`` regenerates the file and the diff is reviewed in the PR.

Deliberately NOT in the manifest: line numbers, reachable-function counts,
source text — anything that churns under refactors that do not change the
threading topology.

No reference counterpart: the reference repo is single-threaded.
"""
from __future__ import annotations

import json

from disco_tpu.analysis.findings import Finding
from disco_tpu.analysis.race.callgraph import attr_chain
from disco_tpu.analysis.race.checks import CHECKS, Analysis, lock_order_edges

#: bump on incompatible schema change — a mismatch reports "regenerate
#: with --update", not a topology drift
VERSION = 1

#: repo-relative home of the committed manifest
GOLDEN_REL = "disco_tpu/analysis/golden/threads.json"


def build(an: Analysis) -> dict:
    """The manifest dict (module docstring) from one analysis."""
    roles = {}
    for name, role in an.roles.items():
        locks = set()
        for qual in an.reach[name]:
            fn = an.index.functions[qual]
            locks.update(a.lock for a in fn.acquires if a.lock is not None)
        roles[name] = {
            "entry_points": sorted(role.entry_points),
            "jax_ok": role.jax_ok,
            "flag_only": role.flag_only,
            "locks_held": sorted(locks),
        }
    entry_roles = {}
    for name, role in an.roles.items():
        for ep in role.entry_points:
            entry_roles[ep] = name
    spawns: dict = {}
    for qual, fn in an.index.functions.items():
        for spawn in fn.spawns:
            chain = attr_chain(spawn.target) if spawn.target is not None else None
            resolved = an.index.resolve_callable(chain, fn) or ()
            for target in resolved:
                spawns[target] = {
                    "kind": spawn.kind,
                    "role": entry_roles.get(target, "<unregistered>"),
                }
    return {
        "version": VERSION,
        "roles": roles,
        "locks": sorted(an.index.locks),
        "lock_order": sorted(f"{a} -> {b}" for a, b in lock_order_edges(an)),
        "spawns": spawns,
    }


def dumps(manifest: dict) -> str:
    """Canonical JSON text (the committed byte format)."""
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"


def diff(golden: dict, current: dict) -> list:
    """Readable drift messages, empty when identical."""
    out: list = []
    if golden.get("version") != current.get("version"):
        return [f"manifest schema version {golden.get('version')} != "
                f"{current.get('version')}: regenerate with "
                "`disco-race --update`"]
    gr, cr = golden.get("roles", {}), current.get("roles", {})
    for name in sorted(set(gr) | set(cr)):
        if name not in cr:
            out.append(f"role '{name}' disappeared")
            continue
        if name not in gr:
            out.append(f"new role '{name}'")
            continue
        for key in ("entry_points", "jax_ok", "flag_only", "locks_held"):
            if gr[name].get(key) != cr[name].get(key):
                out.append(f"role '{name}' {key}: {gr[name].get(key)} -> "
                           f"{cr[name].get(key)}")
    for key in ("locks", "lock_order"):
        a, b = golden.get(key, []), current.get(key, [])
        if a != b:
            gone = sorted(set(a) - set(b))
            new = sorted(set(b) - set(a))
            out.append(f"{key}: {'removed ' + str(gone) if gone else ''}"
                       f"{' ' if gone and new else ''}"
                       f"{'added ' + str(new) if new else ''}".strip()
                       or f"{key} reordered")
    gs, cs = golden.get("spawns", {}), current.get("spawns", {})
    for target in sorted(set(gs) | set(cs)):
        if gs.get(target) != cs.get(target):
            out.append(f"spawn '{target}': {gs.get(target)} -> "
                       f"{cs.get(target)}")
    return out


def drift_findings(golden: dict | None, current: dict) -> list:
    """DR008 findings anchored at the committed golden."""
    if golden is None:
        return [Finding(
            path=GOLDEN_REL, line=1, col=0, rule="DR008",
            name=CHECKS["DR008"][0],
            message="no committed concurrency manifest — run "
                    "`disco-race --update` and commit the result")]
    return [
        Finding(path=GOLDEN_REL, line=1, col=0, rule="DR008",
                name=CHECKS["DR008"][0],
                message=f"concurrency manifest drift: {msg} — review the "
                        "change, then `disco-race --update`")
        for msg in diff(golden, current)
    ]
