"""The race engine: collect sources, build the model, run checks, apply
suppressions.

Whole-program by design (a thread contract is a statement about what a
role can REACH, not about one file), which is the one structural
difference from the per-file disco-lint engine; everything else — finding
shape, suppression syntax, JSON schema — is shared with
:mod:`disco_tpu.analysis`.

No reference counterpart: the reference repo has no static analysis.
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from disco_tpu.analysis import suppressions as sup
from disco_tpu.analysis.findings import Finding
from disco_tpu.analysis.race import manifest as manifest_mod
from disco_tpu.analysis.race import roles as race_roles
from disco_tpu.analysis.race.callgraph import Index
from disco_tpu.analysis.race.checks import CHECKS, HYGIENE_RULE, Analysis, run_checks
from disco_tpu.analysis.runner import collect_files, repo_root


def known_check_ids() -> frozenset:
    """Every id a ``# disco-race:`` suppression may name."""
    return frozenset(CHECKS) | {HYGIENE_RULE[0]}


@dataclasses.dataclass
class RaceResult:
    """Everything one race-analysis run produced (the JSON reporter of
    :mod:`disco_tpu.analysis.report` renders this shape directly — same
    machine contract as disco-lint)."""

    findings: list
    suppressed: list     # (Finding, justification)
    n_files: int
    manifest: dict
    outside: list = dataclasses.field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings


def collect_sources(root=None, overrides=None) -> list:
    """``[(rel, source), ...]`` over the repo's contract surface (the
    disco-lint DEFAULT_TARGETS).  ``overrides`` maps rel -> replacement
    source — the revert-fixture seam: tests re-analyze the repo with ONE
    file mutated back to a buggy shape without touching the checkout."""
    root = Path(root) if root is not None else repo_root()
    overrides = dict(overrides or {})
    out = []
    seen = set()
    for path, rel in collect_files(None, root=root):
        seen.add(rel)
        if rel in overrides:
            out.append((rel, overrides.pop(rel)))
        else:
            out.append((rel, path.read_text()))
    out.extend(sorted(overrides.items()))   # synthetic extra files
    return out


def analyze(
    root=None,
    *,
    files=None,
    overrides=None,
    roles=None,
    locks=None,
    dynamic_calls=None,
    attr_types=None,
    use_suppressions: bool = True,
    golden=None,
) -> RaceResult:
    """Run the full analysis.

    Defaults analyze the real repo against the shipped registries and the
    committed manifest.  Tests inject miniature programs via ``files``
    (``[(rel, source), ...]``) with their own ``roles``/``locks``/
    ``dynamic_calls``/``attr_types``, and ``golden=False`` skips the
    manifest diff (``golden`` may also be a dict to diff against).

    No reference counterpart (module docstring).
    """
    if files is None:
        files = collect_sources(root, overrides=overrides)
    index = Index()
    if locks is not None:
        index.locks = dict(locks)
    if dynamic_calls is not None:
        index.dynamic_calls = dict(dynamic_calls)
    if attr_types is not None:
        index.attr_types = dict(attr_types)
    findings: list = []
    parsed_files: list = []
    for rel, source in files:
        try:
            index.add_module(rel, source)
            parsed_files.append((rel, source))
        except SyntaxError as e:
            findings.append(Finding(
                path=rel, line=e.lineno or 1, col=e.offset or 0,
                rule=HYGIENE_RULE[0], name=HYGIENE_RULE[1],
                message=f"file does not parse: {e.msg}"))
    an = Analysis(index, roles if roles is not None else race_roles.ROLES)
    findings.extend(run_checks(an))
    built = manifest_mod.build(an)
    if golden is not False:
        committed = golden
        if committed is None:
            committed = load_golden(root)
        findings.extend(manifest_mod.drift_findings(committed, built))
    findings.sort()
    if not use_suppressions:
        return RaceResult(findings=findings, suppressed=[],
                          n_files=len(files), manifest=built)
    return _apply_suppressions(findings, parsed_files, built)


def load_golden(root=None):
    """The committed manifest, or None when absent."""
    root = Path(root) if root is not None else repo_root()
    path = root / manifest_mod.GOLDEN_REL
    if not path.exists():
        return None
    return json.loads(path.read_text())


def _apply_suppressions(findings, files, built) -> RaceResult:
    kept: list = []
    suppressed: list = []
    by_path: dict = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    sources = dict(files)
    known = known_check_ids()
    handled = set()
    for rel, source in sources.items():
        handled.add(rel)
        sups, problems = sup.parse(rel, source, known, tool="disco-race",
                                   hygiene_rule=HYGIENE_RULE)
        file_kept, file_sup = sup.apply(by_path.get(rel, []), sups)
        kept.extend(file_kept)
        kept.extend(problems)
        kept.extend(sup.unused_problems(rel, sups, hygiene_rule=HYGIENE_RULE))
        suppressed.extend(file_sup)
    for rel, fs in by_path.items():
        if rel not in handled:   # findings on non-source paths (golden)
            kept.extend(fs)
    return RaceResult(findings=sorted(kept), suppressed=suppressed,
                      n_files=len(sources), manifest=built)


def update_golden(root=None, use_suppressions: bool = True):
    """Rebuild and write the committed manifest (``disco-race --update``).
    Returns ``(path, result)`` — the one analysis both produced the
    manifest and judged the findings, so the CLI never runs it twice."""
    root = Path(root) if root is not None else repo_root()
    result = analyze(root, golden=False, use_suppressions=use_suppressions)
    path = root / manifest_mod.GOLDEN_REL
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(manifest_mod.dumps(result.manifest))
    return path, result
