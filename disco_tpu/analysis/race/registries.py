"""The named-lock registry: every lock in the repo, by declaration.

Lock-order analysis needs stable lock *identities*: a deadlock cycle is a
statement about specific locks, so every ``threading.Lock``/``RLock``/
``Condition`` must be a named module- or instance-level attribute
registered here.  The id format is

* ``module::name``        — module-level lock (``disco_tpu.runs.interrupt::_lock``)
* ``module:Class::attr``  — instance lock assigned ``self.attr = Lock()``
  (``disco_tpu.flywheel.tap:CorpusTap::_lock``)

The value is the one-line statement of WHAT the lock guards — reviewers
review that sentence when a new lock lands, exactly like the obs
``EVENT_KINDS`` / chaos ``SEAMS`` registries.  A ``Lock()`` created
outside this table is a DR005 finding (and DL015 at lint time): an
anonymous lock cannot participate in the order analysis, so it is an
unreviewed deadlock surface.

Per-instance locks (one lock object per Counter/Session instance) are
registered once by their attribute id: the order analysis is about the
*classes* of locks code acquires, not object identity — two instances of
the same attr id never nest in the repo's designs, and if a design ever
needs that, the registry comment is where it gets said.

No reference counterpart: the reference repo has no locks at all.
"""
from __future__ import annotations

#: lock id -> what it guards (the reviewed contract line)
LOCKS = {
    "disco_tpu.utils.compile_cache::_lock":
        "the on-disk compile-cache manifest during read-modify-write",
    "disco_tpu.io.fastwav::_lock":
        "lazy one-time dlopen of libfastwav",
    "disco_tpu.nn.fastload::_lock":
        "lazy one-time dlopen of libfastloader",
    "disco_tpu.enhance.driver::_FIG_LOCK":
        "matplotlib's non-thread-safe figure state across scoring workers",
    "disco_tpu.nn.training::_STEP_FNS_LOCK":
        "the lazily-built jitted train/eval step cache",
    "disco_tpu.serve.scheduler::_STEP_LOCK":
        "the lazily-resolved serve step-callable cache",
    "disco_tpu.runs.interrupt::_lock":
        "the active GracefulInterrupt scope stack",
    "disco_tpu.runs.chaos:_Plan::lock":
        "a chaos plan's hit counter (ticked from any seam's thread)",
    "disco_tpu.runs.ledger:RunLedger::_lock":
        "ledger append + in-memory state (dispatch loop vs tap writer)",
    "disco_tpu.flywheel.tap:CorpusTap::_lock":
        "tap writer-thread lifecycle (start-once) and the ChaosCrash "
        "stash handoff between the writer and close()",
    "disco_tpu.serve.session:Session::_lock":
        "one session's queue/state (I/O thread pushes, dispatch pops)",
    "disco_tpu.serve.scheduler:Scheduler::_lock":
        "the session registry; NEVER held across device work "
        "(Scheduler docstring)",
    "disco_tpu.serve.server:EnhanceServer::_conns_lock":
        "the live-connection set (asyncio thread vs drain)",
    "disco_tpu.obs.events:Recorder::_lock":
        "the JSONL sink file handle + rotation state",
    "disco_tpu.obs.trace:Tracer::_lock":
        "the in-flight span table",
    "disco_tpu.obs.flight:FlightRecorder::_lock":
        "the per-subsystem rings + dump bookkeeping",
    "disco_tpu.obs.metrics:Counter::_lock":
        "one counter's value (scoring workers vs main)",
    "disco_tpu.obs.metrics:Histogram::_lock":
        "one histogram's reservoir",
    "disco_tpu.obs.metrics:Registry::_lock":
        "the instrument name tables (get-or-create)",
    "disco_tpu.promote.controller:PromotionController::_lock":
        "the rollout state machine (phase/candidate/pending/swapped/"
        "scores: controller thread steps it, dispatch thread reports "
        "swaps, I/O thread offers scores); NEVER held across store I/O "
        "or a model load",
    "disco_tpu.promote.store::_MODEL_CACHE_LOCK":
        "the per-architecture flax module cache (model_for_arch "
        "get-or-create: dispatch thread vs controller)",
}


#: Functions that ASSUME a lock is already held by their caller (the
#: ``_locked`` suffix convention) — the analyzer seeds their held set so
#: writes inside them are judged as lock-guarded.  Each entry is a
#: reviewed contract: "every caller of this function holds that lock".
ASSUMED_LOCKS = {
    "disco_tpu.obs.events:Recorder._rotate_locked": (
        "disco_tpu.obs.events:Recorder::_lock",
    ),
}


def lock_id(module: str, cls, attr: str) -> str:
    """The registry id for a lock assigned at ``module`` level (``cls``
    None) or as ``self.attr`` inside ``cls`` — the ONE id-construction
    rule shared by DL015 (rules/threads.py) and this registry's readers."""
    return f"{module}:{cls}::{attr}" if cls else f"{module}::{attr}"


def is_registered(lid: str) -> bool:
    """Whether a lock id is in the registry (the DL015-side membership
    check; the race engine consults its injectable ``Index.locks`` copy)."""
    return lid in LOCKS
