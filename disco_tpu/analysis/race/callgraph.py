"""AST index + module-qualified call resolution for the race analyzer.

The analyzer is whole-program: checks are statements about what a THREAD
can reach, not about one file.  This module builds the program model —
every function indexed by ``module:dotted.qualname`` (class and enclosing
-function names dotted in, no ``<locals>`` marker), with per-function
facts collected under a tracked held-lock set:

* **calls**       — every call site, its attribute chain, and the locks
  held around it (``with``-statement nesting, resolved against the lock
  registry);
* **spawns**      — ``threading.Thread``/``Timer``, ``executor.submit``,
  ``loop.run_in_executor`` and ``signal.signal`` sites with their target
  expressions (DR001 resolves these against the role registry);
* **acquires**    — lock acquisitions with the set held *before* each
  (the edges of the lock-order graph);
* **creations**   — ``threading.Lock()/RLock()/Condition()`` assignment
  sites with their derived registry ids (DR005);
* **writes**      — ``self.attr`` mutations with held locks (DR007).

Resolution is module-qualified and deliberately conservative: bare names
resolve through nested defs, module scope and imports; ``self.m()``
through the enclosing class (then same-module bases); ``self.attr.m()``
and ``local.m()`` through inferred or declared attribute/local types;
everything else is unresolved UNLESS an explicit
:data:`~disco_tpu.analysis.race.roles.DYNAMIC_CALLS` entry declares the
targets — dynamic dispatch is modeled by declaration, never by guessing
(a name-match fallback would flood the jax-reachability check with false
edges).

Stdlib-only by the same constraint as disco-lint: no jax import, no
production ``disco_tpu`` module import — the model is built by parsing.

No reference counterpart: the reference repo is single-threaded and has
no static analysis.
"""
from __future__ import annotations

import ast
import dataclasses

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.race import registries as race_registries
from disco_tpu.analysis.race import roles as race_roles

#: with-item context names treated as lock-ish even when unresolved (an
#: unregistered lock must surface as DR005, not silently drop out of the
#: order analysis)
_LOCKISH = ("lock", "_lock")

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})


def module_of(rel: str) -> str:
    """Repo-relative path -> import path (``disco_tpu/serve/server.py`` ->
    ``disco_tpu.serve.server``; ``bench.py`` -> ``bench``)."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""

    chain: tuple | None      # ("self", "tap", "offer") or None (computed)
    node: ast.Call
    held: frozenset          # lock ids held around the call
    n_args: int
    keywords: tuple          # keyword names (None for **kw)


@dataclasses.dataclass
class SpawnSite:
    """One thread/timer/executor/signal-handler registration site."""

    kind: str                # "thread" | "timer" | "executor" | "signal"
    target: ast.expr | None  # the callable expression (None: not given)
    node: ast.Call
    held: frozenset


@dataclasses.dataclass
class LockUse:
    """One ``with``-acquisition of a (possibly unresolved) lock."""

    lock: str | None         # registry id, or None when unresolvable
    text: str                # source text of the context expr (reports)
    node: ast.expr
    held_before: frozenset


@dataclasses.dataclass
class LockCreation:
    """One ``threading.Lock()``-family constructor assignment."""

    lock: str | None         # derived registry id, or None (anonymous)
    node: ast.expr


@dataclasses.dataclass
class AttrWrite:
    """One ``self.attr = ...`` / ``self.attr op= ...`` mutation."""

    attr: str
    held: frozenset
    node: ast.stmt


@dataclasses.dataclass
class FunctionInfo:
    """Everything the checks ask about one function."""

    qual: str                # "disco_tpu.flywheel.tap:CorpusTap._run"
    module: str
    cls: str | None          # nearest enclosing class name, or None
    rel: str
    node: ast.AST
    calls: list = dataclasses.field(default_factory=list)
    spawns: list = dataclasses.field(default_factory=list)
    acquires: list = dataclasses.field(default_factory=list)
    creations: list = dataclasses.field(default_factory=list)
    writes: list = dataclasses.field(default_factory=list)
    local_types: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClassInfo:
    """One class: its methods, bases and inferred attribute types."""

    qual: str                # "disco_tpu.flywheel.tap:CorpusTap"
    name: str
    module: str
    methods: set = dataclasses.field(default_factory=set)
    bases: list = dataclasses.field(default_factory=list)   # attr chains
    attr_types: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleInfo:
    """One source module: imports, classes, module-level functions."""

    name: str
    rel: str
    imports: dict = dataclasses.field(default_factory=dict)  # alias -> path
    classes: dict = dataclasses.field(default_factory=dict)  # name -> ClassInfo
    functions: set = dataclasses.field(default_factory=set)  # module-level defs
    #: module-global name -> class qual, inferred from constructor assigns
    #: at module level or under a ``global`` declaration (``_PLAN =
    #: _Plan(...)``, ``_FLIGHT = FlightRecorder()``)
    var_types: dict = dataclasses.field(default_factory=dict)


class Index:
    """The whole-program model: modules + functions + resolution."""

    def __init__(self):
        self.modules: dict = {}    # module name -> ModuleInfo
        self.functions: dict = {}  # qual -> FunctionInfo
        self.classes: dict = {}    # "module:Class" -> ClassInfo
        #: explicit dynamic-dispatch fallbacks (roles.DYNAMIC_CALLS by
        #: default; tests inject their own)
        self.dynamic_calls: dict = dict(race_roles.DYNAMIC_CALLS)
        self.attr_types: dict = dict(race_roles.ATTR_TYPES)
        self.locks: dict = dict(race_registries.LOCKS)
        self.assumed_locks: dict = dict(race_registries.ASSUMED_LOCKS)

    # -- construction --------------------------------------------------------
    def add_module(self, rel: str, source: str) -> None:
        """Parse one file into the model."""
        mod = module_of(rel)
        tree = ast.parse(source)
        info = ModuleInfo(name=mod, rel=rel)
        self.modules[mod] = info
        _collect_imports(tree, info.imports)
        _Builder(self, info, rel).visit_module(tree)

    # -- lookups -------------------------------------------------------------
    def function(self, qual: str):
        return self.functions.get(qual)

    def class_info(self, qual: str):
        return self.classes.get(qual)

    def import_root(self, module: str, alias: str) -> str | None:
        """What ``alias`` refers to in ``module`` (an import path), or
        None for plain locals/builtins."""
        info = self.modules.get(module)
        return info.imports.get(alias) if info else None

    def is_jax_name(self, module: str, chain: tuple) -> bool:
        """Whether a call chain is rooted in a jax import (``jax.x``,
        ``jnp.y`` via ``import jax.numpy as jnp``, a bare name imported
        ``from jax import ...``) — the chip-claim surface of DR002."""
        path = self.import_root(module, chain[0])
        return bool(path) and (path == "jax" or path.startswith("jax."))

    # -- call resolution -----------------------------------------------------
    def resolve_callable(self, expr_chain: tuple | None, func: FunctionInfo):
        """Resolve a callable expression (a spawn target or a call's
        ``func``) to function quals.  Returns a tuple of quals (possibly
        empty: declared-dead dynamic site) or None (unresolvable)."""
        if expr_chain is None:
            return None
        key = f"{func.qual}::{'.'.join(expr_chain)}"
        if key in self.dynamic_calls:
            return tuple(self.dynamic_calls[key])
        mod = func.module
        if len(expr_chain) == 1:
            return self._resolve_name(expr_chain[0], func)
        head, rest = expr_chain[0], expr_chain[1:]
        if head in ("self", "cls") and func.cls is not None:
            cqual = f"{mod}:{func.cls}"
            if len(rest) == 1:
                return self._resolve_method(cqual, rest[0])
            # self.attr.m(): declared or inferred attribute type
            tqual = self._attr_type(cqual, rest[0])
            if tqual is not None and len(rest) == 2:
                return self._resolve_method(tqual, rest[1])
            return None
        # local variable with an inferred type: x = ClassName(...)
        tqual = func.local_types.get(head)
        if tqual is None:
            # module global with an inferred type (_FLIGHT, _PLAN)
            minfo = self.modules.get(mod)
            tqual = minfo.var_types.get(head) if minfo else None
        if tqual is not None and len(rest) == 1:
            return self._resolve_method(tqual, rest[0])
        # module alias: obs_events.record(...), disco-style imports
        path = self.import_root(mod, head)
        if path is not None:
            return self._resolve_dotted(path, rest)
        return None

    def _resolve_name(self, name: str, func: FunctionInfo):
        # nested def of this function, then enclosing functions outward
        scope = func.qual
        while True:
            cand = f"{scope}.{name}"
            if cand in self.functions:
                return (cand,)
            if "." not in scope.split(":", 1)[1]:
                break
            scope = scope.rsplit(".", 1)[0]
        minfo = self.modules.get(func.module)
        if minfo is None:
            return None
        if name in minfo.functions:
            return (f"{func.module}:{name}",)
        if name in minfo.classes:
            return self._resolve_method(f"{func.module}:{name}", "__init__")
        path = self.import_root(func.module, name)
        if path is not None:
            return self._resolve_dotted_symbol(path)
        return None

    def _resolve_method(self, class_qual: str, meth: str):
        cinfo = self.classes.get(class_qual)
        if cinfo is None:
            return None
        if meth in cinfo.methods:
            return (f"{class_qual}.{meth}",)
        # single-level base walk (same module or imported repo class)
        for base_chain in cinfo.bases:
            bqual = self._resolve_class_ref(cinfo.module, base_chain)
            if bqual is not None:
                got = self._resolve_method(bqual, meth)
                if got is not None:
                    return got
        return None

    def _resolve_class_ref(self, module: str, chain: tuple):
        if len(chain) == 1:
            minfo = self.modules.get(module)
            if minfo and chain[0] in minfo.classes:
                return f"{module}:{chain[0]}"
            path = self.import_root(module, chain[0])
            if path is not None:
                m, _, c = path.rpartition(".")
                if m in self.modules and c in self.modules[m].classes:
                    return f"{m}:{c}"
        elif len(chain) == 2:
            path = self.import_root(module, chain[0])
            if path in self.modules and chain[1] in self.modules[path].classes:
                return f"{path}:{chain[1]}"
        return None

    def _attr_type(self, class_qual: str, attr: str):
        declared = self.attr_types.get(f"{class_qual}.{attr}")
        if declared is not None:
            return declared
        cinfo = self.classes.get(class_qual)
        return cinfo.attr_types.get(attr) if cinfo else None

    def _resolve_dotted(self, path: str, rest: tuple):
        """``path`` is an import target; ``rest`` the remaining chain.
        Try ever-longer module prefixes (``pkg.sub`` imports)."""
        for i in range(len(rest), -1, -1):
            mod = ".".join((path, *rest[:i])) if i else path
            if mod in self.modules:
                tail = rest[i:]
                if len(tail) == 1:
                    minfo = self.modules[mod]
                    if tail[0] in minfo.functions:
                        return (f"{mod}:{tail[0]}",)
                    if tail[0] in minfo.classes:
                        return self._resolve_method(f"{mod}:{tail[0]}", "__init__")
                if len(tail) == 2 and tail[0] in self.modules[mod].classes:
                    return self._resolve_method(f"{mod}:{tail[0]}", tail[1])
                return None
        return self._resolve_dotted_symbol(path, rest)

    def _resolve_dotted_symbol(self, path: str, rest: tuple = ()):
        """``from m import f`` gives alias path ``m.f``: split the symbol
        off the tail and resolve inside module ``m``."""
        mod, _, sym = path.rpartition(".")
        if mod in self.modules and not rest:
            minfo = self.modules[mod]
            if sym in minfo.functions:
                return (f"{mod}:{sym}",)
            if sym in minfo.classes:
                return self._resolve_method(f"{mod}:{sym}", "__init__")
        if mod in self.modules and len(rest) == 1 and sym in self.modules[mod].classes:
            return self._resolve_method(f"{mod}:{sym}", rest[0])
        return None

    def resolve_lock(self, expr: ast.expr, func: FunctionInfo):
        """Resolve a ``with`` context expression to a registered lock id.
        Returns ``(lock_id_or_None, is_lockish)`` — ``is_lockish`` marks
        names that LOOK like locks so unregistered ones surface (DR005)."""
        chain = attr_chain(expr)
        if chain is None:
            return None, False
        leaf = chain[-1]
        lockish = (leaf.lower() in _LOCKISH or leaf.lower().endswith("_lock")
                   or "LOCK" in leaf)
        cand = None
        mod = func.module
        if len(chain) == 1:
            cand = f"{mod}::{leaf}"
        elif chain[0] in ("self", "cls") and func.cls is not None:
            if len(chain) == 2:
                cand = f"{mod}:{func.cls}::{leaf}"
            elif len(chain) == 3:
                tqual = self._attr_type(f"{mod}:{func.cls}", chain[1])
                if tqual is not None:
                    cand = f"{tqual}::{leaf}"
        elif len(chain) == 2:
            tqual = func.local_types.get(chain[0])
            if tqual is None:
                minfo = self.modules.get(mod)
                tqual = minfo.var_types.get(chain[0]) if minfo else None
            if tqual is not None:
                cand = f"{tqual}::{leaf}"
            else:
                path = self.import_root(mod, chain[0])
                if path in self.modules:
                    cand = f"{path}::{leaf}"
        if cand is not None and cand in self.locks:
            return cand, lockish
        return None, lockish


def _collect_imports(tree: ast.AST, out: dict) -> None:
    """alias -> import path, over the whole module INCLUDING function-local
    imports (the repo's lazy-jax idiom makes those the ones that matter)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.partition(".")[0]] = (
                    alias.name if alias.asname else alias.name.partition(".")[0]
                )
                if alias.asname:
                    out[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                out[alias.asname or alias.name] = f"{node.module}.{alias.name}"


class _Builder:
    """Walk one module: register classes/functions, then collect each
    function's facts under tracked held-lock sets."""

    def __init__(self, index: Index, minfo: ModuleInfo, rel: str):
        self.index = index
        self.minfo = minfo
        self.rel = rel

    # -- declaration pass ----------------------------------------------------
    def visit_module(self, tree: ast.Module) -> None:
        self._declare(tree.body, scope=(), cls=None)
        self._infer_attr_types()
        self._infer_module_var_types(tree)
        for qual, fn in list(self.index.functions.items()):
            if fn.module == self.minfo.name and fn.rel == self.rel:
                self._analyze_function(fn)
        # module-level lock creations (the registry id has no class part)
        mod_fn = self._module_body_fn(tree)
        self._analyze_function(mod_fn)

    def _module_body_fn(self, tree: ast.Module) -> FunctionInfo:
        """A synthetic function for module-level statements (import-time
        code: lock creations, module-level spawns)."""
        qual = f"{self.minfo.name}:<module>"
        fn = FunctionInfo(qual=qual, module=self.minfo.name, cls=None,
                          rel=self.rel, node=tree)
        self.index.functions[qual] = fn
        return fn

    def _declare(self, body, scope: tuple, cls: str | None) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                path = (*scope, node.name)
                qual = f"{self.minfo.name}:{'.'.join(path)}"
                self.index.functions[qual] = FunctionInfo(
                    qual=qual, module=self.minfo.name, cls=cls,
                    rel=self.rel, node=node,
                )
                if not scope:
                    self.minfo.functions.add(node.name)
                if cls is not None and len(scope) == 1:
                    self.index.classes[f"{self.minfo.name}:{cls}"].methods.add(
                        node.name)
                self._declare(node.body, path, cls)
            elif isinstance(node, ast.ClassDef):
                if not scope:   # nested classes: not modeled
                    cqual = f"{self.minfo.name}:{node.name}"
                    cinfo = ClassInfo(qual=cqual, name=node.name,
                                      module=self.minfo.name)
                    cinfo.bases = [c for c in map(attr_chain, node.bases) if c]
                    self.index.classes[cqual] = cinfo
                    self.minfo.classes[node.name] = cinfo
                    self._declare(node.body, (node.name,), node.name)
            else:
                # descend EVERY nested statement list (if/try AND
                # with/for/while): a def declared inside a with or loop
                # body must enter the model, or code reached through it
                # would silently escape every reachability check
                for block in _stmt_blocks(node):
                    self._declare(block, scope, cls)

    def _infer_attr_types(self) -> None:
        """``self.attr = ClassName(...)`` anywhere in a class (including
        behind ``x or ClassName(...)``) types the attribute."""
        for cinfo in self.minfo.classes.values():
            for meth in cinfo.methods:
                fn = self.index.functions.get(f"{cinfo.qual}.{meth}")
                if fn is None:
                    continue
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for tgt in node.targets:
                        chain = attr_chain(tgt)
                        if not (chain and len(chain) == 2 and chain[0] == "self"):
                            continue
                        tq = self._ctor_type(node.value, fn)
                        if tq is not None:
                            cinfo.attr_types.setdefault(chain[1], tq)

    def _infer_module_var_types(self, tree: ast.Module) -> None:
        """Type module globals from constructor assignments: at module
        level, and inside functions that declare the name ``global`` (the
        repo's ``configure()``-style rebinding idiom)."""
        probe = FunctionInfo(qual=f"{self.minfo.name}:<module>",
                             module=self.minfo.name, cls=None,
                             rel=self.rel, node=tree)
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tq = self._ctor_type(node.value, probe)
                if tq is not None:
                    self.minfo.var_types.setdefault(node.targets[0].id, tq)
        for fn in self.index.functions.values():
            if fn.module != self.minfo.name or isinstance(fn.node, ast.Module):
                continue
            globals_here = {
                n for sub in ast.walk(fn.node)
                if isinstance(sub, ast.Global) for n in sub.names
            }
            if not globals_here:
                continue
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and sub.targets[0].id in globals_here:
                    tq = self._ctor_type(sub.value, fn)
                    if tq is not None:
                        self.minfo.var_types.setdefault(sub.targets[0].id, tq)

    def _ctor_type(self, value: ast.expr, fn: FunctionInfo):
        """The class qual a value expression constructs, if inferable."""
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                got = self._ctor_type(v, fn)
                if got is not None:
                    return got
            return None
        if not isinstance(value, ast.Call):
            return None
        chain = attr_chain(value.func)
        if chain is None:
            return None
        # bare, module-alias (mod.Class(...)) or imported constructor
        got = self.index._resolve_class_ref(self.minfo.name, chain)
        if got is not None:
            return got
        # external marker for the one stdlib type spawn sites care about
        if chain[-1] == "ThreadPoolExecutor":
            return "<ThreadPoolExecutor>"
        return None

    # -- fact-collection pass ------------------------------------------------
    def _analyze_function(self, fn: FunctionInfo) -> None:
        body = fn.node.body if not isinstance(fn.node, ast.Module) else [
            n for n in fn.node.body
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef))
        ]
        # closures see the enclosing function's locals: seed nested defs
        # with the parent's inferred types (declaration order guarantees
        # the parent was analyzed first)
        tail = fn.qual.split(":", 1)[1]
        if "." in tail:
            parent = self.index.functions.get(fn.qual.rsplit(".", 1)[0])
            if parent is not None:
                fn.local_types.update(parent.local_types)
        self._infer_local_types(fn, body)
        # the _locked-suffix contract: registered helpers run with their
        # caller's lock held (registries.ASSUMED_LOCKS)
        self._walk_stmts(fn, body,
                         frozenset(self.index.assumed_locks.get(fn.qual, ())))

    def _infer_local_types(self, fn: FunctionInfo, body) -> None:
        if fn.cls is not None:
            fn.local_types["self"] = f"{fn.module}:{fn.cls}"
            fn.local_types["cls"] = f"{fn.module}:{fn.cls}"
        for node in body:
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                    if isinstance(tgt, ast.Name):
                        tq = self._ctor_type(sub.value, fn)
                        if tq is None and isinstance(sub.value, ast.Name):
                            # alias of a typed module global (plan = _PLAN)
                            tq = self.minfo.var_types.get(sub.value.id)
                        if tq is not None:
                            fn.local_types.setdefault(tgt.id, tq)
                elif isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        if isinstance(item.optional_vars, ast.Name):
                            tq = self._ctor_type(item.context_expr, fn)
                            if tq is not None:
                                fn.local_types.setdefault(
                                    item.optional_vars.id, tq)

    def _walk_stmts(self, fn: FunctionInfo, stmts, held: frozenset) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # separate FunctionInfo / not modeled
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    self._visit_expr(fn, item.context_expr, held)
                    lid, lockish = self.index.resolve_lock(
                        item.context_expr, fn)
                    if lid is not None or lockish:
                        text = ".".join(attr_chain(item.context_expr) or ("?",))
                        fn.acquires.append(LockUse(
                            lock=lid, text=text, node=item.context_expr,
                            held_before=inner))
                        inner = inner | {lid or f"<unregistered:{text}>"}
                self._walk_stmts(fn, stmt.body, inner)
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                self._note_writes(fn, stmt, held)
            for expr in _stmt_exprs(stmt):
                self._visit_expr(fn, expr, held)
            for block in _stmt_blocks(stmt):
                self._walk_stmts(fn, block, held)

    def _note_writes(self, fn: FunctionInfo, stmt, held: frozenset) -> None:
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for tgt in targets:
            elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
            for t in elts:
                chain = attr_chain(t)
                if chain and len(chain) == 2 and chain[0] == "self":
                    fn.writes.append(AttrWrite(attr=chain[1], held=held,
                                               node=stmt))
        # lock creations: X = threading.Lock() / self._x = Lock()
        value = getattr(stmt, "value", None)
        if isinstance(value, ast.Call):
            cchain = attr_chain(value.func)
            if cchain and self._is_lock_ctor(cchain):
                tgt0 = targets[0] if targets else None
                tchain = attr_chain(tgt0) if tgt0 is not None else None
                lid = None
                if tchain is not None and len(tchain) == 1:
                    if fn.qual.endswith(":<module>"):
                        lid = f"{fn.module}::{tchain[0]}"
                elif (tchain is not None and len(tchain) == 2
                      and tchain[0] == "self" and fn.cls is not None):
                    lid = f"{fn.module}:{fn.cls}::{tchain[1]}"
                fn.creations.append(LockCreation(lock=lid, node=value))

    def _is_lock_ctor(self, chain: tuple) -> bool:
        if len(chain) == 2 and chain[1] in _LOCK_CTORS:
            return self.index.import_root(self.minfo.name, chain[0]) == "threading"
        if len(chain) == 1 and chain[0] in _LOCK_CTORS:
            path = self.index.import_root(self.minfo.name, chain[0])
            return bool(path) and path.startswith("threading.")
        return False

    def _visit_expr(self, fn: FunctionInfo, expr, held: frozenset) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._note_call(fn, node, held)

    def _note_call(self, fn: FunctionInfo, node: ast.Call, held) -> None:
        chain = attr_chain(node.func)
        fn.calls.append(CallSite(
            chain=chain, node=node, held=held, n_args=len(node.args),
            keywords=tuple(k.arg for k in node.keywords),
        ))
        if chain is None:
            return
        spawn = self._spawn_kind(fn, chain, node)
        if spawn is not None:
            kind, target = spawn
            fn.spawns.append(SpawnSite(kind=kind, target=target, node=node,
                                       held=held))

    def _spawn_kind(self, fn: FunctionInfo, chain: tuple, node: ast.Call):
        def kwarg(name):
            for k in node.keywords:
                if k.arg == name:
                    return k.value
            return None

        leaf = chain[-1]
        root_path = self.index.import_root(self.minfo.name, chain[0])
        if leaf == "Thread" and (
            (len(chain) == 2 and root_path == "threading")
            or (len(chain) == 1 and root_path == "threading.Thread")
        ):
            return "thread", kwarg("target")
        if leaf == "Timer" and (
            (len(chain) == 2 and root_path == "threading")
            or (len(chain) == 1 and root_path == "threading.Timer")
        ):
            target = node.args[1] if len(node.args) > 1 else kwarg("function")
            return "timer", target
        if leaf == "signal" and len(chain) == 2 and root_path == "signal":
            return "signal", (node.args[1] if len(node.args) > 1
                              else kwarg("handler"))
        if leaf == "submit" and len(chain) == 2:
            if fn.local_types.get(chain[0]) == "<ThreadPoolExecutor>":
                return "executor", (node.args[0] if node.args else None)
        if leaf == "run_in_executor" and len(chain) >= 2:
            return "executor", (node.args[1] if len(node.args) > 1 else None)
        return None


def _stmt_blocks(stmt) -> list:
    """The nested statement lists of one statement (bodies re-walked by
    the caller with the right held set)."""
    out = []
    for name in ("body", "orelse", "finalbody"):
        block = getattr(stmt, name, None)
        if isinstance(block, list) and block and isinstance(block[0], ast.stmt):
            out.append(block)
    for h in getattr(stmt, "handlers", ()):
        out.append(h.body)
    return out


def _stmt_exprs(stmt) -> list:
    """The expression children of one statement (bodies excluded)."""
    out = []
    for name, value in ast.iter_fields(stmt):
        if name in ("body", "orelse", "finalbody", "handlers"):
            continue
        vals = value if isinstance(value, list) else [value]
        out.extend(v for v in vals if isinstance(v, ast.expr))
    return out


def build_index(files) -> Index:
    """Build the program model from ``[(rel, source), ...]``."""
    index = Index()
    for rel, source in files:
        index.add_module(rel, source)
    return index
