"""The thread-role registry: who runs on which thread, by declaration.

A **role** names one kind of thread the repo deliberately runs, rooted at
the exact functions those threads execute (entry points are
``module:dotted.qualname`` — class and enclosing-function names dotted in,
no ``<locals>`` marker).  The race analyzer builds the call graph from
these roots; every ``threading.Thread(target=...)``, ``threading.Timer``,
``executor.submit`` and ``signal.signal`` site in the repo must resolve to
a registered entry point or is itself a finding (DR001) — an unregistered
thread is an unreviewed concurrency surface.

Role policy is part of the declaration:

* ``jax_ok`` — only ``dispatch`` and ``main`` may reach jax-touching code
  (the single-chip-claim contract of CLAUDE.md: every process claims the
  tunneled chip at first jax use, so a second jax-entering thread contends
  for the one claim; until this gate the contract was enforced by
  convention plus DL005's narrow client/protocol carve-out);
* ``flag_only`` — the ``signal_handler`` role runs at an arbitrary
  bytecode boundary of the main thread, possibly INSIDE a non-reentrant
  lock of the interrupted frame; its reachable code may only set flags
  (no lock acquisition, no obs emission, no I/O — the PR 3 bug class,
  checked structurally by DR003).

No reference counterpart: the reference repo is single-threaded end to
end (SURVEY §0).
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Role:
    """One declared thread role (module docstring)."""

    name: str
    #: ``module:dotted.qualname`` roots the threads of this role execute
    entry_points: tuple
    #: may code reachable from this role enter jax? (chip-claim contract)
    jax_ok: bool = False
    #: restricted to the flag-set allowlist (signal handlers)
    flag_only: bool = False
    summary: str = ""


#: name -> Role.  Every spawn site in the repo resolves into this table.
ROLES = {
    r.name: r
    for r in (
        Role(
            "main",
            entry_points=(
                # operational entry points that run on the caller's thread
                # CONCURRENTLY with the worker roles below: CLI mains, the
                # gate harness mains, the server/tap/prefetcher lifecycle
                # methods an embedding caller drives.
                "bench:main",
                "__graft_entry__:entry",
                "__graft_entry__:dryrun_multichip",
                "disco_tpu.serve.server:EnhanceServer.start",
                "disco_tpu.serve.server:EnhanceServer.stop",
                "disco_tpu.serve.server:EnhanceServer.wait",
                "disco_tpu.serve.server:EnhanceServer.serve_forever",
                "disco_tpu.flywheel.tap:CorpusTap.start",
                "disco_tpu.flywheel.tap:CorpusTap.close",
                "disco_tpu.flywheel.tap:CorpusTap.stats",
                "disco_tpu.enhance.pipeline:ChunkPrefetcher.__iter__",
                "disco_tpu.enhance.pipeline:ChunkPrefetcher.close",
                "disco_tpu.utils.resilience:DispatchDeadline.__enter__",
                "disco_tpu.utils.resilience:DispatchDeadline.__exit__",
                "disco_tpu.runs.interrupt:GracefulInterrupt.__enter__",
                "disco_tpu.runs.interrupt:GracefulInterrupt.__exit__",
                "disco_tpu.runs.interrupt:request_stop",
                "disco_tpu.runs.interrupt:stop_requested",
                "disco_tpu.enhance.driver:enhance_rirs_batched",
                "disco_tpu.serve.check:main",
                "disco_tpu.flywheel.check:main",
                "disco_tpu.promote.check:main",
                "disco_tpu.obs.scope:main",
                "disco_tpu.runs.soak:main",
                "disco_tpu.runs.endure:main",
            ),
            jax_ok=True,
            summary="the process main thread: CLI/check mains + the "
                    "lifecycle methods embedding callers drive",
        ),
        Role(
            "dispatch",
            entry_points=("disco_tpu.serve.server:EnhanceServer._dispatch_loop",),
            jax_ok=True,
            summary="the single jax dispatch thread of the serve stack "
                    "(the ONLY non-main thread allowed to enter jax)",
        ),
        Role(
            "asyncio_io",
            entry_points=(
                "disco_tpu.serve.server:EnhanceServer.start._run",
                "disco_tpu.serve.server:EnhanceServer._handle",
            ),
            summary="the serve event-loop thread: socket framing only, "
                    "host-side, never jax",
        ),
        Role(
            "prefetch_loader",
            entry_points=(
                "disco_tpu.enhance.pipeline:ChunkPrefetcher._run",
                "disco_tpu.utils.transfer:prefetch_to_device.feeder",
            ),
            summary="background chunk/batch loaders: disk + numpy work "
                    "overlapping device compute, never jax",
        ),
        Role(
            "tap_writer",
            entry_points=("disco_tpu.flywheel.tap:CorpusTap._run",),
            summary="the corpus-tap shard writer: msgpack + io.atomic, "
                    "never jax (DL005 pins the module; DR002 pins the role)",
        ),
        Role(
            "watchdog_timer",
            entry_points=(
                "disco_tpu.utils.resilience:DispatchDeadline._fire",
                "bench:_start_watchdog.fire",
            ),
            summary="watchdog timer threads: host-only telemetry, never "
                    "interrupt or kill anything",
        ),
        Role(
            "signal_handler",
            entry_points=("disco_tpu.runs.interrupt:GracefulInterrupt._handler",),
            flag_only=True,
            summary="SIGTERM/SIGINT handlers: flag-set allowlist only "
                    "(runs inside an arbitrary interrupted frame)",
        ),
        Role(
            "promote_controller",
            entry_points=(
                "disco_tpu.promote.controller:PromotionController._run",
            ),
            # NOT jax_ok by design: the controller only REQUESTS swaps
            # (pending map) and reads ledgers/stores; the dispatch thread
            # loads weights and executes every swap (the single-chip-claim
            # contract — a second jax-entering thread would contend for
            # the one tunneled claim)
            summary="the promotion-rollout controller thread: watch-dir "
                    "scans, canary bookkeeping, gate verdicts, ledger "
                    "writes — never jax",
        ),
        Role(
            "client_reader",
            entry_points=("disco_tpu.serve.client:ServeClient._read_loop",),
            summary="the numpy-only serve client's socket reader thread",
        ),
        Role(
            "harness_worker",
            entry_points=(
                "disco_tpu.serve.check:_check_parity.worker",
                "disco_tpu.serve.check:_check_overload.worker",
                "disco_tpu.obs.scope:_check_chains_and_status.worker",
                "disco_tpu.flywheel.check:_check_tap_serve.worker",
                "disco_tpu.runs.soak:_client_worker",
                "bench:bench_serve.worker",
            ),
            summary="gate-harness loopback clients: concurrent numpy-only "
                    "ServeClient drivers, never jax",
        ),
        Role(
            "score_worker",
            entry_points=(
                "disco_tpu.enhance.driver:enhance_rirs_batched.score_unit",
            ),
            # jax_ok is DELIBERATE: in the pipelined default the workers
            # score host arrays fetched by ONE batched readback and never
            # enter jax, but the sequential escape hatch (--no-pipeline)
            # still pays the per-clip ISTFT + device_get_tree ON the
            # worker (_persist_and_score's time_domain=None branch).
            # Threads share the process's single chip claim (CLAUDE.md
            # forbids a second PROCESS, not a second thread), so this is
            # contention, not a claim violation — tighten to jax_ok=False
            # if the sequential path ever drops its device work.
            jax_ok=True,
            summary="corpus scoring pool workers: host-side in the "
                    "pipelined default; the sequential escape hatch still "
                    "does per-clip ISTFT+readback on the worker",
        ),
    )
}


#: Explicit dynamic-dispatch fallbacks: call sites the module-qualified
#: resolver cannot see through (callables stored on ``self``, callback
#: parameters) mapped to their real targets BY DECLARATION, so the call
#: graph stays complete without guessing.  Key: ``caller_qual::callee
#: text`` exactly as written at the site; value: tuple of function quals.
#: An entry here is a reviewed statement of "this indirect call can only
#: ever land on these functions" — extend it when a new callback seam
#: appears (the manifest diff will prompt you).
DYNAMIC_CALLS = {
    # ChunkPrefetcher's injected loader: the corpus driver's chunk loader
    # and the training batch feed's identity loader
    "disco_tpu.enhance.pipeline:ChunkPrefetcher._run::self._load": (
        "disco_tpu.enhance.driver:enhance_rirs_batched.load_chunk",
    ),
    # ChunkPrefetcher's injected stop poll (runs.interrupt.stop_requested)
    "disco_tpu.enhance.pipeline:ChunkPrefetcher._run::self._stop_requested": (
        "disco_tpu.runs.interrupt:stop_requested",
    ),
    # DispatchDeadline's on_expire callback: no in-repo caller passes one
    # today (the scheduler polls .expired after the window instead); the
    # empty tuple DECLARES that, and a future callback must be added here
    "disco_tpu.utils.resilience:DispatchDeadline._fire::self.on_expire": (),
    # the scoring pool's partial(_persist_and_score, ...) thunk
    "disco_tpu.enhance.driver:enhance_rirs_batched.score_unit::score_fn": (
        "disco_tpu.enhance.driver:_persist_and_score",
    ),
    # the GracefulInterrupt scope stack: scopes popped off module-level
    # ``_active`` lose their static type, but every element is a
    # GracefulInterrupt by construction
    "disco_tpu.runs.interrupt:request_stop::scope._trip": (
        "disco_tpu.runs.interrupt:GracefulInterrupt._trip",
    ),
    "disco_tpu.runs.interrupt:stop_requested::g._flush_telemetry": (
        "disco_tpu.runs.interrupt:GracefulInterrupt._flush_telemetry",
    ),
}


#: Declared instance-attribute types the resolver cannot infer from a
#: constructor assignment (the attribute is bound from a parameter).
#: ``"module:Class.attr" -> "module:Class"`` — lets ``self.tap.offer(...)``
#: resolve through the declared type.
ATTR_TYPES = {
    "disco_tpu.serve.scheduler:Scheduler.tap": "disco_tpu.flywheel.tap:CorpusTap",
    "disco_tpu.serve.server:EnhanceServer.scheduler": "disco_tpu.serve.scheduler:Scheduler",
    "disco_tpu.serve.server:EnhanceServer.tap": "disco_tpu.flywheel.tap:CorpusTap",
    "disco_tpu.serve.scheduler:Scheduler.promote":
        "disco_tpu.promote.controller:PromotionController",
    "disco_tpu.serve.server:EnhanceServer.promote":
        "disco_tpu.promote.controller:PromotionController",
    "disco_tpu.promote.controller:PromotionController.store":
        "disco_tpu.promote.store:GenerationStore",
    # the co-resident trainer: driven by the dispatch thread between
    # ticks (scheduler.resident.step), lifecycle by main (server start/
    # stop) — both roles are jax_ok, which is what makes a trainer ON the
    # dispatch thread legal under the single-chip-claim contract
    "disco_tpu.serve.scheduler:Scheduler.resident":
        "disco_tpu.flywheel.resident:ResidentTrainer",
    "disco_tpu.serve.server:EnhanceServer.resident":
        "disco_tpu.flywheel.resident:ResidentTrainer",
}


def entry_point_index() -> dict:
    """``entry qual -> role name`` over every registered role."""
    out = {}
    for role in ROLES.values():
        for ep in role.entry_points:
            out[ep] = role.name
    return out


def entry_point_leaves() -> frozenset:
    """The last dotted component of every registered entry point — the
    lexical surface DL015 (bare-thread lint rule) checks spawn targets
    against without building the call graph."""
    return frozenset(ep.rpartition(":")[2].rpartition(".")[2]
                     for ep in entry_point_index())
