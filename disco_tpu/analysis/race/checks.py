"""The DRnnn thread-contract checks over the race call graph.

Check catalog (ids are stable; DR000 is the engine's suppression-hygiene
pseudo-rule, shared machinery with disco-lint's DL000):

* DR001 ``unregistered-thread``   — every ``threading.Thread``/``Timer``,
  ``executor.submit``/``run_in_executor`` and ``signal.signal`` site must
  resolve to an entry point of a registered role (roles.py); an
  unresolvable target is a finding too (register it, declare a
  DYNAMIC_CALLS fallback, or justify a suppression).
* DR002 ``jax-outside-dispatch``  — jax-touching calls reachable ONLY
  from roles declared ``jax_ok`` in roles.py: the single-chip-claim
  contract, structural instead of conventional.
* DR003 ``signal-handler-unsafe`` — code reachable from a ``flag_only``
  role may not acquire locks, block, emit telemetry (``disco_tpu.obs``)
  or do I/O (``disco_tpu.io``, ``open``/``print``) — the PR 3
  handler-self-deadlock bug class.
* DR004 ``blocking-under-lock``   — no blocking call (zero-timeout
  ``join``/``get``/``put``/``wait``/``result``, ``recv``/``accept``/
  ``select``, ``time.sleep``) while ANY registered lock is held, directly
  or through the call graph.
* DR005 ``unregistered-lock``     — every ``Lock``/``RLock``/
  ``Condition`` creation must land on a registered id (registries.py);
  lock-looking ``with`` targets that resolve to nothing are findings, and
  so are registry entries with no surviving creation site (dead entries
  hide drift exactly like dead suppressions).
* DR006 ``lock-order-cycle``      — the global lock-acquisition graph
  (``with`` nesting propagated through calls) must be acyclic; a self-
  edge is a non-reentrant re-acquisition (instant deadlock).
* DR007 ``unlocked-shared-write`` — an instance attribute written from
  functions reachable from >= 2 roles needs one common lock held at every
  write site (``__init__`` writes are excluded: construction
  happens-before thread start).
* DR008 ``manifest-drift``        — the computed concurrency manifest
  must match the committed ``analysis/golden/threads.json``
  (:mod:`disco_tpu.analysis.race.manifest`).

No reference counterpart: the reference repo is single-threaded.
"""
from __future__ import annotations

from disco_tpu.analysis.findings import Finding
from disco_tpu.analysis.race.callgraph import Index, attr_chain

#: id -> (name, one-line summary) — the ``--list-checks`` catalog
CHECKS = {
    "DR001": ("unregistered-thread",
              "thread/timer/executor/signal targets must resolve to a "
              "registered role entry point"),
    "DR002": ("jax-outside-dispatch",
              "jax-touching code reachable only from jax_ok roles "
              "(race/roles.py: dispatch/main + declared exceptions) — "
              "the single-chip-claim contract"),
    "DR003": ("signal-handler-unsafe",
              "signal-handler-reachable code is flag-set only: no locks, "
              "no blocking, no obs, no I/O"),
    "DR004": ("blocking-under-lock",
              "no blocking call while holding a registered lock "
              "(directly or through the call graph)"),
    "DR005": ("unregistered-lock",
              "every Lock/RLock/Condition is a registered named attribute "
              "(race/registries.py)"),
    "DR006": ("lock-order-cycle",
              "the global lock-acquisition graph is acyclic "
              "(self-edge = non-reentrant re-acquire)"),
    "DR007": ("unlocked-shared-write",
              "attributes written from >= 2 roles need a common lock at "
              "every write site"),
    "DR008": ("manifest-drift",
              "the concurrency manifest matches the committed "
              "golden/threads.json"),
}

#: the engine's suppression-hygiene pseudo-rule (cannot be suppressed)
HYGIENE_RULE = ("DR000", "race-suppression")

#: where registry-level findings (stale entry points, dead lock entries)
#: anchor — the registries are source files too
ROLES_REL = "disco_tpu/analysis/race/roles.py"
LOCKS_REL = "disco_tpu/analysis/race/registries.py"

#: modules forbidden from signal handlers (telemetry + I/O layers)
_HANDLER_FORBIDDEN_MODULES = ("disco_tpu.obs", "disco_tpu.io")


def _finding(check_id: str, rel: str, node, message: str) -> Finding:
    return Finding(
        path=rel,
        line=getattr(node, "lineno", 1) if node is not None else 1,
        col=getattr(node, "col_offset", 0) if node is not None else 0,
        rule=check_id,
        name=CHECKS[check_id][0],
        message=message,
    )


def blocking_desc(site) -> str | None:
    """Classify one call site as a blocking primitive (DR003/DR004), or
    None.  Timeouts make a call bounded: ``q.get(timeout=0.05)`` and
    ``thread.join(t)`` pass; zero-argument forms block forever."""
    chain = site.chain
    if chain is None:
        return None
    leaf = chain[-1]
    kw = set(site.keywords)
    if leaf == "sleep" and chain[0] == "time":
        return "time.sleep"
    if leaf in ("recv", "accept", "select") and len(chain) >= 2:
        return f".{leaf}()"
    if leaf == "join" and site.n_args == 0 and not kw:
        return ".join() without timeout"
    if leaf == "get" and site.n_args == 0 and "timeout" not in kw:
        return ".get() without timeout"
    if (leaf == "put" and site.n_args == 1
            and not kw.intersection({"timeout", "block"})):
        return ".put() without timeout"
    if leaf == "wait" and site.n_args == 0 and "timeout" not in kw:
        return ".wait() without timeout"
    if leaf == "result" and site.n_args == 0 and "timeout" not in kw:
        return ".result() without timeout"
    return None


class Analysis:
    """Resolved call graph + role reachability, shared by the checks and
    the manifest builder."""

    def __init__(self, index: Index, roles: dict):
        self.index = index
        self.roles = roles
        #: qual -> tuple of resolved target quals per call site (parallel
        #: to FunctionInfo.calls; None = unresolvable)
        self.call_targets: dict = {}
        #: qual -> set of callee quals
        self.edges: dict = {}
        for qual, fn in index.functions.items():
            targets = []
            out = set()
            for site in fn.calls:
                resolved = index.resolve_callable(site.chain, fn)
                targets.append(resolved)
                if resolved:
                    out.update(t for t in resolved if t in index.functions)
            self.call_targets[qual] = targets
            self.edges[qual] = out
        self.reach: dict = {}       # role -> {qual: parent qual or None}
        self.stale_entries: list = []
        for name, role in roles.items():
            tree: dict = {}
            queue = []
            for ep in role.entry_points:
                if ep in index.functions:
                    tree[ep] = None
                    queue.append(ep)
                else:
                    self.stale_entries.append((name, ep))
            while queue:
                cur = queue.pop()
                for nxt in self.edges.get(cur, ()):
                    if nxt not in tree:
                        tree[nxt] = cur
                        queue.append(nxt)
            self.reach[name] = tree

    def roles_reaching(self, qual: str) -> frozenset:
        return frozenset(n for n, tree in self.reach.items() if qual in tree)

    def path_to(self, role: str, qual: str) -> list:
        """Entry-point-to-function witness chain for one role."""
        tree = self.reach.get(role, {})
        out, cur = [], qual
        while cur is not None:
            out.append(cur)
            cur = tree.get(cur)
        return list(reversed(out))


# -- DR001 --------------------------------------------------------------------
def check_spawns(an: Analysis) -> list:
    """DR001: every spawn site resolves to a registered role entry point.

    No reference counterpart (module docstring)."""
    index, out = an.index, []
    entry_roles = {}
    for name, role in an.roles.items():
        for ep in role.entry_points:
            entry_roles[ep] = name
    for fn in index.functions.values():
        for spawn in fn.spawns:
            if spawn.target is None:
                out.append(_finding(
                    "DR001", fn.rel, spawn.node,
                    f"{spawn.kind} spawn without an explicit target "
                    "callable — the role cannot be inferred"))
                continue
            chain = attr_chain(spawn.target)
            resolved = index.resolve_callable(chain, fn)
            if not resolved:
                text = ".".join(chain) if chain else "<computed>"
                out.append(_finding(
                    "DR001", fn.rel, spawn.node,
                    f"{spawn.kind} target '{text}' does not resolve to a "
                    "known function — register the real target as a role "
                    "entry point (race/roles.py) or declare a "
                    "DYNAMIC_CALLS fallback"))
                continue
            for target in resolved:
                if target not in entry_roles:
                    out.append(_finding(
                        "DR001", fn.rel, spawn.node,
                        f"{spawn.kind} target '{target}' is not a "
                        "registered role entry point (race/roles.py) — "
                        "an unregistered thread is an unreviewed "
                        "concurrency surface"))
    for role_name, ep in an.stale_entries:
        out.append(_finding(
            "DR001", ROLES_REL, None,
            f"role '{role_name}' entry point '{ep}' not found in the "
            "program model — the function moved or was renamed; update "
            "race/roles.py"))
    return out


# -- DR002 --------------------------------------------------------------------
def check_jax_reachability(an: Analysis) -> list:
    """DR002: jax-touching calls reachable only from jax_ok roles.

    No reference counterpart (module docstring)."""
    index, out = an.index, []
    for role_name, role in an.roles.items():
        if role.jax_ok:
            continue
        for qual in an.reach[role_name]:
            fn = index.functions[qual]
            for site in fn.calls:
                if site.chain is None:
                    continue
                if index.is_jax_name(fn.module, site.chain):
                    path = " -> ".join(an.path_to(role_name, qual))
                    out.append(_finding(
                        "DR002", fn.rel, site.node,
                        f"jax call '{'.'.join(site.chain)}' is reachable "
                        f"from role '{role_name}' ({path}) — only jax_ok "
                        "roles (race/roles.py) may enter jax (single-chip-"
                        "claim contract, CLAUDE.md)"))
    return out


# -- DR003 --------------------------------------------------------------------
def check_signal_safety(an: Analysis) -> list:
    """DR003: flag_only roles may not lock, block, emit obs or do I/O.

    No reference counterpart (module docstring)."""
    index, out = an.index, []
    for role_name, role in an.roles.items():
        if not role.flag_only:
            continue
        for qual in an.reach[role_name]:
            fn = index.functions[qual]
            via = " -> ".join(an.path_to(role_name, qual))
            for acq in fn.acquires:
                out.append(_finding(
                    "DR003", fn.rel, acq.node,
                    f"lock acquisition '{acq.text}' reachable from "
                    f"signal handler ({via}) — a handler interrupting the "
                    "lock's own holder self-deadlocks; handlers only set "
                    "flags"))
            for site, targets in zip(fn.calls, an.call_targets[qual]):
                desc = blocking_desc(site)
                if desc is not None:
                    out.append(_finding(
                        "DR003", fn.rel, site.node,
                        f"blocking call {desc} reachable from signal "
                        f"handler ({via})"))
                    continue
                if site.chain and site.chain[-1] in ("open", "print"):
                    out.append(_finding(
                        "DR003", fn.rel, site.node,
                        f"I/O call '{'.'.join(site.chain)}' reachable "
                        f"from signal handler ({via})"))
                    continue
                for target in targets or ():
                    tmod = target.partition(":")[0]
                    if tmod.startswith(_HANDLER_FORBIDDEN_MODULES):
                        out.append(_finding(
                            "DR003", fn.rel, site.node,
                            f"call into '{target}' reachable from signal "
                            f"handler ({via}) — telemetry/I-O layers "
                            "acquire non-reentrant locks (the PR 3 bug "
                            "class); set a flag and emit from the next "
                            "poll instead"))
    return out


# -- DR004 --------------------------------------------------------------------
def check_blocking_under_lock(an: Analysis) -> list:
    """DR004: no blocking call while any registered lock is held.

    No reference counterpart (module docstring)."""
    index, out = an.index, []
    # transitive may-block, with one witness description per function
    witness: dict = {}
    for qual, fn in index.functions.items():
        for site in fn.calls:
            desc = blocking_desc(site)
            if desc is not None:
                witness.setdefault(qual, f"{desc} at {fn.rel}:{site.node.lineno}")
    changed = True
    while changed:
        changed = False
        for qual in index.functions:
            if qual in witness:
                continue
            for callee in an.edges.get(qual, ()):
                if callee in witness:
                    witness[qual] = f"via {callee} ({witness[callee]})"
                    changed = True
                    break
    for qual, fn in index.functions.items():
        for site, targets in zip(fn.calls, an.call_targets[qual]):
            if not site.held:
                continue
            held = ", ".join(sorted(site.held))
            desc = blocking_desc(site)
            if desc is not None:
                out.append(_finding(
                    "DR004", fn.rel, site.node,
                    f"blocking call {desc} while holding {held} — a "
                    "stalled peer wedges every thread contending for the "
                    "lock"))
                continue
            for target in targets or ():
                if target in witness:
                    out.append(_finding(
                        "DR004", fn.rel, site.node,
                        f"call to '{target}' may block ({witness[target]}) "
                        f"while holding {held}"))
                    break
    return out


# -- DR005 --------------------------------------------------------------------
def check_lock_registry(an: Analysis) -> list:
    """DR005: every lock creation lands on a registered id, and every
    registered id still has a creation site.

    No reference counterpart (module docstring)."""
    index, out = an.index, []
    created = set()
    for fn in index.functions.values():
        for creation in fn.creations:
            if creation.lock is None:
                out.append(_finding(
                    "DR005", fn.rel, creation.node,
                    "anonymous lock creation (not a module- or "
                    "instance-level named attribute) — it cannot "
                    "participate in the lock-order analysis"))
            elif creation.lock not in index.locks:
                out.append(_finding(
                    "DR005", fn.rel, creation.node,
                    f"lock '{creation.lock}' is not registered in "
                    "race/registries.py — register it with a one-line "
                    "statement of what it guards"))
            else:
                created.add(creation.lock)
        for acq in fn.acquires:
            if acq.lock is None:
                out.append(_finding(
                    "DR005", fn.rel, acq.node,
                    f"acquisition of unregistered/unresolvable lock "
                    f"'{acq.text}' — the order analysis cannot see it"))
    for lid in sorted(index.locks):
        if lid not in created:
            out.append(_finding(
                "DR005", LOCKS_REL, None,
                f"registered lock '{lid}' has no creation site in the "
                "program model — the lock moved or died; update "
                "race/registries.py"))
    return out


# -- DR006 --------------------------------------------------------------------
def lock_order_edges(an: Analysis) -> dict:
    """``(lockA, lockB) -> witness`` — A held while B is (transitively)
    acquired."""
    index = an.index
    # transitive lock-acquisition sets per function
    acq: dict = {q: {a.lock for a in fn.acquires if a.lock is not None}
                 for q, fn in index.functions.items()}
    changed = True
    while changed:
        changed = False
        for qual in index.functions:
            mine = acq[qual]
            before = len(mine)
            for callee in an.edges.get(qual, ()):
                mine |= acq[callee]
            if len(mine) != before:
                changed = True
    edges: dict = {}
    for qual, fn in index.functions.items():
        for a in fn.acquires:
            if a.lock is None:
                continue
            for h in a.held_before:
                edges.setdefault((h, a.lock),
                                 f"{fn.rel}:{a.node.lineno}")
        for site, targets in zip(fn.calls, an.call_targets[qual]):
            if not site.held:
                continue
            for target in targets or ():
                for t in acq.get(target, ()):
                    for h in site.held:
                        edges.setdefault(
                            (h, t),
                            f"{fn.rel}:{site.node.lineno} via {target}")
    return edges


def check_lock_order(an: Analysis) -> list:
    """DR006: the global lock-acquisition graph is acyclic.

    No reference counterpart (module docstring)."""
    edges = lock_order_edges(an)
    out = []
    adj: dict = {}
    for (a, b), wit in edges.items():
        if a == b:
            out.append(Finding(
                path=LOCKS_REL, line=1, col=0, rule="DR006",
                name=CHECKS["DR006"][0],
                message=f"non-reentrant re-acquisition of '{a}' ({wit}) — "
                        "instant self-deadlock"))
            continue
        adj.setdefault(a, set()).add(b)
    # cycle detection: iterative DFS with color marking
    color: dict = {}
    stack_path: list = []

    def visit(node):
        color[node] = 1
        stack_path.append(node)
        for nxt in sorted(adj.get(node, ())):
            if color.get(nxt, 0) == 1:
                cycle = stack_path[stack_path.index(nxt):] + [nxt]
                wits = "; ".join(
                    edges.get((cycle[i], cycle[i + 1]), "?")
                    for i in range(len(cycle) - 1))
                out.append(Finding(
                    path=LOCKS_REL, line=1, col=0, rule="DR006",
                    name=CHECKS["DR006"][0],
                    message=("lock-order cycle "
                             + " -> ".join(cycle)
                             + f" (witnesses: {wits}) — two threads taking "
                               "the cycle from different ends deadlock")))
            elif color.get(nxt, 0) == 0:
                visit(nxt)
        stack_path.pop()
        color[node] = 2

    for node in sorted(adj):
        if color.get(node, 0) == 0:
            visit(node)
    return out


# -- DR007 --------------------------------------------------------------------
def check_shared_writes(an: Analysis) -> list:
    """DR007: cross-role attribute writes need one common lock.

    No reference counterpart (module docstring)."""
    index, out = an.index, []
    grouped: dict = {}   # (class qual, attr) -> [(fn, write, roles)]
    for qual, fn in index.functions.items():
        if fn.cls is None or qual.endswith(".__init__"):
            continue
        roles = an.roles_reaching(qual)
        if not roles:
            continue
        for w in fn.writes:
            grouped.setdefault((f"{fn.module}:{fn.cls}", w.attr),
                               []).append((fn, w, roles))
    for (cqual, attr), sites in sorted(grouped.items()):
        all_roles = frozenset().union(*(r for _, _, r in sites))
        if len(all_roles) < 2:
            continue
        common = frozenset.intersection(
            *(frozenset(w.held) for _, w, _ in sites))
        if common:
            continue
        sites = sorted(sites, key=lambda s: (s[0].rel, s[1].node.lineno))
        where = ", ".join(f"{fn.rel}:{w.node.lineno}" for fn, w, _ in sites)
        # anchor at the first UNGUARDED site — that is where a fix (or a
        # justified suppression) belongs
        fn0, w0, _ = next(
            (s for s in sites if not s[1].held), sites[0])
        out.append(_finding(
            "DR007", fn0.rel, w0.node,
            f"'{cqual}.{attr}' is written from roles "
            f"{{{', '.join(sorted(all_roles))}}} with no common lock "
            f"(write sites: {where}) — guard it, or justify why the "
            "stores cannot race"))
    return out


def run_checks(an: Analysis) -> list:
    """All graph checks (DR008 manifest drift lives in
    :mod:`disco_tpu.analysis.race.manifest`)."""
    out = []
    out.extend(check_spawns(an))
    out.extend(check_jax_reachability(an))
    out.extend(check_signal_safety(an))
    out.extend(check_blocking_under_lock(an))
    out.extend(check_lock_registry(an))
    out.extend(check_lock_order(an))
    out.extend(check_shared_writes(an))
    return out
