"""``disco-race`` — the thread-contract analyzer's command line.

Exit codes mirror ``disco-lint``: 0 clean, 1 unsuppressed findings, 2
usage error.  Hermetic by construction: stdlib + ``disco_tpu.analysis``
only, no jax import anywhere (pinned by test) — safe to run while another
process holds the chip, which is what lets ``make race-check`` gate every
``make test``.

``--update`` regenerates the committed concurrency manifest
(``analysis/golden/threads.json``) after an *intended* topology change —
a new thread, a role acquiring a new lock; commit the diff with a message
saying WHAT changed in the threading topology and why
(doc/source/static_analysis.rst, "Thread contracts").

No reference counterpart: the reference repo has no static analysis.
"""
from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    """The disco-race argument parser (no reference counterpart)."""
    p = argparse.ArgumentParser(
        prog="disco-race",
        description=(
            "Static thread-contract analyzer: role-rooted call graph, "
            "jax-reachability, signal-handler safety, lock order and the "
            "committed concurrency manifest.  Targets: disco_tpu/, "
            "bench.py, __graft_entry__.py (whole-program — no path "
            "arguments)."
        ),
    )
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (json is the machine contract, "
                        "same key shape as disco-lint)")
    p.add_argument("--update", action="store_true",
                   help="regenerate analysis/golden/threads.json instead "
                        "of diffing against it; commit the result")
    p.add_argument("--no-suppressions", action="store_true",
                   help="ignore suppression comments and report everything "
                        "(audit mode: shows what the shipped waivers hold "
                        "back)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="text format: also list justified suppressions")
    p.add_argument("--list-checks", action="store_true",
                   help="print the check catalog and exit")
    return p


def main(argv=None) -> int:
    """Entry point (console script ``disco-race`` / ``python -m
    disco_tpu.analysis.race.cli``).  No reference counterpart."""
    args = build_parser().parse_args(argv)
    from disco_tpu.analysis import report
    from disco_tpu.analysis.race import runner
    from disco_tpu.analysis.race.checks import CHECKS, HYGIENE_RULE

    if args.list_checks:
        print(f"{HYGIENE_RULE[0]} {HYGIENE_RULE[1]:<24} "
              "malformed/unjustified/unused suppression comments "
              "(engine rule)")
        for cid, (name, summary) in sorted(CHECKS.items()):
            print(f"{cid} {name:<24} {summary}")
        return 0

    if args.update:
        # ONE analysis both writes the manifest and judges the findings
        # (everything except drift, which --update just redefined)
        path, result = runner.update_golden(
            use_suppressions=not args.no_suppressions)
        print(f"disco-race: wrote {path}")
    else:
        result = runner.analyze(use_suppressions=not args.no_suppressions)

    if args.format == "json":
        print(report.format_json(result))
    else:
        print(_format_text(report, result, args.show_suppressed))
    return 0 if result.clean else 1


def _format_text(report, result, verbose) -> str:
    """The disco-lint text format with the tool name corrected in the
    summary line."""
    text = report.format_text(result, verbose_suppressed=verbose)
    head, sep, tail = text.rpartition("disco-lint:")
    return f"{head}disco-race:{tail}" if sep else text


if __name__ == "__main__":
    sys.exit(main())
