"""AST extraction of the repo's in-code string registries.

The registry rules (DL009 obs event kinds, DL010 chaos seams, DL014 span
stages / status sections) check string literals at call sites against the
closed sets declared in ``disco_tpu/obs/events.py`` (``EVENT_KINDS``),
``disco_tpu/runs/chaos.py`` (``SEAMS``), ``disco_tpu/obs/trace.py``
(``SPAN_STAGES``) and ``disco_tpu/serve/status.py`` (``STATUS_SECTIONS``).
The sets are read by PARSING those files, not importing them: the linter
must stay importable with no jax (or any production dependency) in the
process — ``make lint-check`` is a hermetic CPU gate.

No reference counterpart: the reference repo has neither telemetry kinds
nor chaos seams to register.
"""
from __future__ import annotations

import ast
from pathlib import Path

#: repo-relative file and assigned name per registry
REGISTRY_SOURCES = {
    "event_kinds": ("disco_tpu/obs/events.py", "EVENT_KINDS"),
    "chaos_seams": ("disco_tpu/runs/chaos.py", "SEAMS"),
    "span_stages": ("disco_tpu/obs/trace.py", "SPAN_STAGES"),
    "status_sections": ("disco_tpu/serve/status.py", "STATUS_SECTIONS"),
}

_cache: dict = {}


class RegistryExtractionError(RuntimeError):
    """The declared registry could not be located/parsed — the registry
    moved or changed shape, and the lint rule would otherwise silently
    check nothing."""


def _extract_string_set(path: Path, name: str) -> frozenset:
    tree = ast.parse(path.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            strings = {
                c.value
                for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            }
            if strings:
                return frozenset(strings)
    raise RegistryExtractionError(
        f"could not extract {name} from {path} — if the registry moved, "
        f"update disco_tpu.analysis.registries.REGISTRY_SOURCES"
    )


def load(root, which: str) -> frozenset:
    """The named registry's string set, parsed from the repo at ``root``
    (cached per (root, registry))."""
    rel, name = REGISTRY_SOURCES[which]
    key = (str(root), which)
    if key not in _cache:
        _cache[key] = _extract_string_set(Path(root) / rel, name)
    return _cache[key]


def event_kinds(root) -> frozenset:
    """``EVENT_KINDS`` as declared in ``disco_tpu/obs/events.py``."""
    return load(root, "event_kinds")


def chaos_seams(root) -> frozenset:
    """``SEAMS`` as declared in ``disco_tpu/runs/chaos.py``."""
    return load(root, "chaos_seams")


def span_stages(root) -> frozenset:
    """``SPAN_STAGES`` as declared in ``disco_tpu/obs/trace.py``."""
    return load(root, "span_stages")


def status_sections(root) -> frozenset:
    """``STATUS_SECTIONS`` as declared in ``disco_tpu/serve/status.py``."""
    return load(root, "status_sections")
