"""Suppression comments: per-line and per-file, justification REQUIRED.

Syntax (the ``--`` separator and a non-empty justification are mandatory —
an unexplained suppression is itself a finding, ``DL000``)::

    x = risky()  # disco-lint: disable=DL004 -- why this one is safe
    # disco-lint: disable=DL002,DL003 -- applies to the NEXT line
    # disco-lint: file-disable=DL001 -- whole-file waiver, stated once

A trailing comment suppresses findings reported on its own line; a comment
on a line of its own suppresses the next line (for calls too long to share
a line).  ``file-disable`` waives the rule for the whole file.  Unknown
rule ids and suppressions that no finding actually needed are reported as
``DL000`` — dead waivers hide regressions exactly like dead code.

No reference counterpart: the reference repo has no static analysis; the
syntax follows the ``# noqa``/``# pylint: disable`` lineage with the
justification made load-bearing instead of optional.

The machinery is shared by every analyzer in this package: ``tool``
selects the comment marker (``disco-lint`` by default; ``disco-race``
passes its own name and hygiene rule id), so the race analyzer's waivers
carry exactly the same syntax, the same mandatory justification and the
same dead-waiver policing without a second implementation.
"""
from __future__ import annotations

import dataclasses
import io
import re
import tokenize

from disco_tpu.analysis.findings import Finding
from disco_tpu.analysis.registry import SUPPRESSION_RULE_ID, SUPPRESSION_RULE_NAME


def _pattern(tool: str):
    return re.compile(
        rf"#\s*{re.escape(tool)}:\s*(?P<kind>file-disable|disable)\s*=\s*"
        r"(?P<ids>[A-Za-z0-9_,\s-]*?)\s*(?:--\s*(?P<just>.*))?$"
    )


def _marker(tool: str):
    return re.compile(rf"#\s*{re.escape(tool)}\b")


@dataclasses.dataclass
class Suppression:
    """One parsed waiver (line=None for file-wide)."""

    rule_id: str
    line: int | None     # the line findings must sit on; None = whole file
    comment_line: int    # where the comment itself lives (for DL000 reports)
    justification: str
    used: bool = False


def _hygiene(path, line, message, hygiene_rule=None) -> Finding:
    rid, name = hygiene_rule or (SUPPRESSION_RULE_ID, SUPPRESSION_RULE_NAME)
    return Finding(path=path, line=line, col=0, rule=rid,
                   name=name, message=message)


def parse(rel: str, source: str, known_ids: frozenset,
          tool: str = "disco-lint", hygiene_rule=None):
    """Extract suppressions from ``source``.

    Returns ``(suppressions, problems)`` — ``problems`` are hygiene-rule
    findings (DL000 for disco-lint, DR000 for disco-race) for malformed
    comments (bad syntax, unknown rule id, missing justification).  A
    malformed comment suppresses nothing: failing open would let a typo
    silently waive a rule.  ``tool`` selects the comment marker
    (``# <tool>: disable=...``); ``hygiene_rule`` is the ``(id, name)``
    pair the problems are reported under.
    """
    sups: list = []
    problems: list = []
    code_lines = set()     # lines carrying non-comment tokens
    comments = []          # (line, text)
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.string))
            elif tok.type not in (
                tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                tokenize.DEDENT, tokenize.ENDMARKER,
            ):
                code_lines.add(tok.start[0])
    except tokenize.TokenError:
        # ast.parse succeeded upstream, so this should be unreachable;
        # degrade to "no suppressions" rather than crash the linter.
        return [], []

    hyg_id = (hygiene_rule or (SUPPRESSION_RULE_ID, SUPPRESSION_RULE_NAME))[0]
    marker, pattern = _marker(tool), _pattern(tool)
    sample = "DLnnn" if tool == "disco-lint" else "DRnnn"
    for line, text in comments:
        if not marker.search(text):
            continue
        m = pattern.search(text)
        if not m:
            problems.append(_hygiene(
                rel, line,
                f"malformed {tool} comment (expected "
                f"'# {tool}: disable={sample}[,{sample}] -- justification')",
                hygiene_rule,
            ))
            continue
        ids = [s.strip() for s in m.group("ids").split(",") if s.strip()]
        just = (m.group("just") or "").strip()
        ok = True
        if not ids:
            problems.append(_hygiene(rel, line, "suppression names no rule ids",
                                     hygiene_rule))
            ok = False
        for rid in ids:
            if rid not in known_ids:
                problems.append(_hygiene(
                    rel, line, f"suppression names unknown rule id {rid!r}",
                    hygiene_rule))
                ok = False
            elif rid == hyg_id:
                problems.append(_hygiene(
                    rel, line, f"{hyg_id} (suppression hygiene) cannot be suppressed",
                    hygiene_rule))
                ok = False
        if not just:
            problems.append(_hygiene(
                rel, line,
                "suppression carries no justification (policy: every waiver "
                "states WHY the flagged code honors the contract anyway)",
                hygiene_rule,
            ))
            ok = False
        if not ok:
            continue
        if m.group("kind") == "file-disable":
            target = None
        else:
            # trailing comment -> this line; standalone comment -> next line
            target = line if line in code_lines else line + 1
        for rid in ids:
            sups.append(Suppression(rule_id=rid, line=target,
                                    comment_line=line, justification=just))
    return sups, problems


def apply(findings, suppressions):
    """Partition ``findings`` into (kept, suppressed-with-justification).

    Marks each matching suppression ``used``; call :func:`unused_problems`
    afterwards for the dead-waiver findings.
    """
    kept, suppressed = [], []
    for f in findings:
        hit = None
        for s in suppressions:
            if s.rule_id == f.rule and (s.line is None or s.line == f.line):
                hit = s
                s.used = True
                break
        if hit is None:
            kept.append(f)
        else:
            suppressed.append((f, hit.justification))
    return kept, suppressed


def unused_problems(rel: str, suppressions, hygiene_rule=None) -> list:
    """Hygiene findings for waivers that matched nothing."""
    return [
        _hygiene(
            rel, s.comment_line,
            f"unused suppression of {s.rule_id} (no finding on "
            f"{'this file' if s.line is None else f'line {s.line}'}): "
            "remove it, or the contract it waives has silently drifted",
            hygiene_rule,
        )
        for s in suppressions
        if not s.used
    ]
