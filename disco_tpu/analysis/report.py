"""Text and JSON reporters for lint results.

No reference counterpart: the reference repo has no static analysis.  The
JSON document is the machine contract (``disco-lint --format json``) — its
top-level keys (``findings``/``suppressed``/``counts``/``clean``) are
consumed by CI tooling and pinned by tests; stdout stays exactly one
document per run in either format.
"""
from __future__ import annotations

import json


def format_text(result, verbose_suppressed: bool = False) -> str:
    """Human-readable report: one ``path:line:col: DLnnn [...]`` line per
    finding plus a one-line summary (and, optionally, the justified
    waivers)."""
    lines = [f.render() for f in result.findings]
    if verbose_suppressed and result.suppressed:
        lines.append("suppressed (justified):")
        lines.extend(
            f"  {f.render()}  -- {just}" for f, just in result.suppressed
        )
    lines.append(
        f"disco-lint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, {result.n_files} file(s) scanned"
    )
    return "\n".join(lines)


def format_json(result) -> str:
    """Machine-readable report (one JSON document)."""
    per_rule: dict = {}
    for f in result.findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    return json.dumps(
        {
            "clean": result.clean,
            "counts": {
                "findings": len(result.findings),
                "suppressed": len(result.suppressed),
                "files": result.n_files,
                "by_rule": per_rule,
            },
            "findings": [f.to_dict() for f in result.findings],
            "suppressed": [
                {**f.to_dict(), "justification": just}
                for f, just in result.suppressed
            ],
        },
        indent=2,
    )
