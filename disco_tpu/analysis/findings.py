"""Finding: one rule violation at one source location.

No reference counterpart: the reference repo has no static analysis; the
shape (rule id + location + message, machine- and human-renderable) follows
the convention of production linters (flake8/ruff diagnostics).
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One violation of one rule at one location.

    ``path`` is repo-relative POSIX (stable across checkouts — the JSON
    reporter is consumed by CI); ``line``/``col`` are 1-/0-based like every
    other python linter.  Ordering is (path, line, col, rule) so reports are
    deterministic without a separate sort key.
    """

    path: str
    line: int
    col: int
    rule: str       # "DL004"
    name: str       # "atomic-write"
    message: str

    def render(self) -> str:
        """``path:line:col: DLnnn [name] message`` — the text-reporter line."""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} [{self.name}] {self.message}"

    def to_dict(self) -> dict:
        """JSON-reporter payload (field names are the public schema)."""
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "name": self.name,
            "message": self.message,
        }
