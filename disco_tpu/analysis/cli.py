"""``disco-lint`` — the AST invariant checker's command line.

Exit codes: 0 clean, 1 unsuppressed findings, 2 usage error.  Hermetic by
construction: the linter imports nothing outside the stdlib and
``disco_tpu.analysis`` (no jax — safe to run while another process holds
the chip), which is what lets ``make lint-check`` gate every ``make test``.

No reference counterpart: the reference repo has no static analysis.
"""
from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    """The disco-lint argument parser (no reference counterpart)."""
    p = argparse.ArgumentParser(
        prog="disco-lint",
        description=(
            "AST invariant checker for the disco_tpu tunnel/fence/atomicity "
            "contracts.  Default targets: disco_tpu/, bench.py, "
            "__graft_entry__.py (repo-root relative)."
        ),
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint (default: the repo's "
                        "contract surface)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (json is the machine contract)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--no-suppressions", action="store_true",
                   help="ignore suppression comments and report everything "
                        "(audit mode: shows what the shipped waivers hold back)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="text format: also list justified suppressions")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    """Entry point (console script ``disco-lint`` / ``python -m
    disco_tpu.analysis.cli``).  No reference counterpart."""
    args = build_parser().parse_args(argv)
    from disco_tpu.analysis import report, runner
    from disco_tpu.analysis.registry import (
        SUPPRESSION_RULE_ID,
        SUPPRESSION_RULE_NAME,
        get_rules,
    )

    if args.list_rules:
        print(f"{SUPPRESSION_RULE_ID} {SUPPRESSION_RULE_NAME:<22} "
              "malformed/unjustified/unused suppression comments (engine rule)")
        for rid, rule in sorted(get_rules().items()):
            print(f"{rid} {rule.name:<22} {rule.summary}")
        return 0

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(get_rules()) - {SUPPRESSION_RULE_ID}
        if unknown:
            print(f"disco-lint: unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2

    try:
        result = runner.lint_paths(
            paths=args.paths or None,
            rules=rules,
            use_suppressions=not args.no_suppressions,
        )
    except FileNotFoundError as e:
        print(f"disco-lint: {e}", file=sys.stderr)
        return 2

    if result.outside:
        print(
            f"disco-lint: warning: {len(result.outside)} target(s) outside "
            f"the repo root ({', '.join(result.outside[:3])}"
            f"{', ...' if len(result.outside) > 3 else ''}) — repo-path-"
            "scoped rules (readbacks/atomic-writes/purity/citations) do not "
            "apply to them",
            file=sys.stderr,
        )
    if args.format == "json":
        print(report.format_json(result))
    else:
        print(report.format_text(result,
                                 verbose_suppressed=args.show_suppressed))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
