"""Rule base class and the process-global rule registry.

No reference counterpart: the reference repo has no static analysis.  The
decorator-registry shape mirrors the repo's other closed registries (obs
``EVENT_KINDS``, chaos ``SEAMS``): a rule is registered once at import of
:mod:`disco_tpu.analysis.rules` and addressed by a stable ``DLnnn`` id.
"""
from __future__ import annotations


class Rule:
    """One invariant checker.

    Subclasses set ``id`` ("DL004"), ``name`` (kebab-case slug), ``summary``
    (one line for ``--list-rules`` and the docs), and implement
    :meth:`check` yielding :class:`~disco_tpu.analysis.findings.Finding`.
    ``applies`` pre-filters by file so ``check`` can assume its scope.
    """

    id: str = ""
    name: str = ""
    summary: str = ""

    def applies(self, ctx) -> bool:
        """Whether this rule runs on ``ctx`` at all (default: every file)."""
        return True

    def check(self, ctx):
        """Yield findings for one :class:`FileContext`."""
        raise NotImplementedError

    def finding(self, ctx, node, message):
        """Build a Finding anchored at an AST node of ``ctx``."""
        from disco_tpu.analysis.findings import Finding

        return Finding(
            path=ctx.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            rule=self.id,
            name=self.name,
            message=message,
        )


#: id -> Rule instance, in registration (= documentation) order.
RULES: dict = {}

#: The engine-level suppression-hygiene pseudo-rule id (emitted by the
#: runner, not a Rule subclass; it cannot itself be suppressed).
SUPPRESSION_RULE_ID = "DL000"
SUPPRESSION_RULE_NAME = "lint-suppression"


def register(cls):
    """Class decorator: instantiate and register a :class:`Rule`."""
    inst = cls()
    if not inst.id or not inst.name:
        raise ValueError(f"rule {cls.__name__} must set id and name")
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


def get_rules() -> dict:
    """The populated registry (importing the rule modules on first use)."""
    import disco_tpu.analysis.rules  # noqa: F401  (registers on import)

    return RULES


def known_rule_ids() -> frozenset:
    """Every id a suppression comment may name (rules + DL000)."""
    return frozenset(get_rules()) | {SUPPRESSION_RULE_ID}
