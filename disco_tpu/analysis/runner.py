"""The lint engine: collect files, run rules, apply suppressions.

No reference counterpart: the reference repo has no static analysis.  The
engine is deliberately import-light — stdlib only, no jax and no production
``disco_tpu`` modules — so ``make lint-check`` runs hermetically on any
host without touching the chip claim.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path

from disco_tpu.analysis import suppressions as sup
from disco_tpu.analysis.context import FileContext
from disco_tpu.analysis.findings import Finding
from disco_tpu.analysis.registry import get_rules, known_rule_ids

#: what ``disco-lint`` (and ``make lint-check``) scans by default,
#: repo-root relative — the jitted pipeline, the bench harness, and the
#: driver entry (ISSUE: the contract surface, not the tests).
DEFAULT_TARGETS = ("disco_tpu", "bench.py", "__graft_entry__.py")


def repo_root() -> Path:
    """The checkout root: the directory containing the ``disco_tpu``
    package this module was imported from."""
    return Path(__file__).resolve().parents[2]


@dataclasses.dataclass
class LintResult:
    """Everything one lint run produced.

    ``findings`` are the unsuppressed (gate-failing) ones; ``suppressed``
    pairs each waived finding with its justification; ``n_files`` is the
    scan breadth for the summary line.
    """

    findings: list
    suppressed: list   # (Finding, justification)
    n_files: int
    #: targets that resolved OUTSIDE the repo root: they are linted, but
    #: the repo-path-scoped rules (DL002/DL004/DL005/DL006 scoping) cannot
    #: apply to them — the CLI warns so a "clean" result is not misread.
    #: Use :func:`lint_source` with a synthetic ``rel`` to lint a snippet
    #: "as" an in-repo path.
    outside: list = dataclasses.field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings


def collect_files(paths=None, root: Path | None = None) -> list:
    """Expand targets to ``(abspath, rel)`` pairs, sorted for determinism.

    ``paths`` defaults to :data:`DEFAULT_TARGETS` resolved against the repo
    root; directories are walked for ``*.py``.  A default target that does
    not exist (an installed package without the bench harness) is skipped;
    an explicitly named missing path raises.
    """
    root = Path(root) if root is not None else repo_root()
    explicit = paths is not None
    out = []
    for target in paths if explicit else DEFAULT_TARGETS:
        p = Path(target)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            out.extend((f, _rel(f, root)) for f in sorted(p.rglob("*.py")))
        elif p.is_file():
            out.append((p, _rel(p, root)))
        elif explicit:
            raise FileNotFoundError(f"lint target does not exist: {target}")
    return sorted(set(out), key=lambda pair: pair[1])


def _rel(path: Path, root: Path) -> str:
    """Repo-relative POSIX path, or the bare name for files outside the
    root (rules scoped to repo paths then cannot match — the runner records
    such targets in ``LintResult.outside`` and the CLI warns)."""
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.name


def _is_outside(path: Path, root: Path) -> bool:
    try:
        path.resolve().relative_to(root.resolve())
        return False
    except ValueError:
        return True


def lint_source(
    source: str,
    rel: str,
    root: Path | None = None,
    rules=None,
    use_suppressions: bool = True,
) -> LintResult:
    """Lint one in-memory source blob as if it lived at ``rel``.

    The test-fixture entry point: rules scope by repo-relative path, so a
    snippet can be checked "as" ``disco_tpu/enhance/x.py``.  ``rules``
    optionally restricts to a set of rule ids; ``use_suppressions=False``
    reports everything (how the tests prove the shipped suppression sets
    are load-bearing).
    """
    root = Path(root) if root is not None else repo_root()
    ctx = FileContext(rel, source, root)
    active = [
        r for rid, r in get_rules().items() if (rules is None or rid in rules)
    ]
    found = []
    for rule in active:
        if rule.applies(ctx):
            found.extend(rule.check(ctx))
    if not use_suppressions:
        return LintResult(findings=sorted(found), suppressed=[], n_files=1)
    sups, problems = sup.parse(rel, source, known_rule_ids())
    kept, suppressed = sup.apply(found, sups)
    # Malformed waivers are ALWAYS reported (they suppress nothing, under
    # any filter), but a waiver only counts as "unused" if its rule
    # actually RAN — otherwise a focused `--rules DL005` run would flag
    # every other rule's shipped suppressions as dead and fail a clean repo.
    kept.extend(problems)
    active_ids = {r.id for r in active}
    kept.extend(sup.unused_problems(
        rel, [s for s in sups if s.rule_id in active_ids]))
    return LintResult(findings=sorted(kept), suppressed=suppressed, n_files=1)


def lint_paths(
    paths=None,
    root: Path | None = None,
    rules=None,
    use_suppressions: bool = True,
) -> LintResult:
    """Lint files/directories (default: the repo's contract surface,
    :data:`DEFAULT_TARGETS`).  Returns a merged :class:`LintResult`."""
    root = Path(root) if root is not None else repo_root()
    findings: list = []
    suppressed: list = []
    files = collect_files(paths, root=root)
    outside = [rel for path, rel in files if _is_outside(path, root)]
    for path, rel in files:
        try:
            source = path.read_text()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(
                path=rel, line=1, col=0, rule="DL000", name="lint-suppression",
                message=f"unreadable source file: {e}"))
            continue
        try:
            res = lint_source(source, rel, root=root, rules=rules,
                              use_suppressions=use_suppressions)
        except SyntaxError as e:
            findings.append(Finding(
                path=rel, line=e.lineno or 1, col=e.offset or 0,
                rule="DL000", name="lint-suppression",
                message=f"file does not parse: {e.msg}"))
            continue
        findings.extend(res.findings)
        suppressed.extend(res.suppressed)
    return LintResult(findings=sorted(findings), suppressed=suppressed,
                      n_files=len(files), outside=outside)
