"""DL004 — run-critical artifacts are written atomically, or not at all.

Crash-safe resume (PR 3) stands on one invariant: a final artifact path is
either complete or absent, never truncated.  ``disco_tpu.io.atomic``
(tmp + fsync + rename) is the only writer allowed to produce final paths in
the run-critical packages (enhance / datagen / nn / runs / serve); raw
truncate-mode ``open``, ``np.save``/``savez``, ``pickle.dump``,
``soundfile.write`` and ``Path.write_text``/``write_bytes`` all leave the
torn-write window the verified-resume probes cannot see past.  Append mode
("a") is allowed: the run ledger's append-only JSONL with per-line fsync is
itself the crash-safe protocol.

No reference counterpart: the reference writes artifacts raw and cannot
resume (SURVEY.md §5).
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain, str_literal
from disco_tpu.analysis.registry import Rule, register

_SCOPE = (
    "disco_tpu/enhance", "disco_tpu/datagen", "disco_tpu/nn",
    "disco_tpu/runs", "disco_tpu/serve", "disco_tpu/flywheel",
)
_NP_WRITERS = {"save", "savez", "savez_compressed"}
_NP_BASES = {"np", "numpy"}
_SF_BASES = {"sf", "soundfile"}
_PATH_WRITERS = {"write_text", "write_bytes"}
_HINT = ("route it through disco_tpu.io.atomic (atomic_write / "
         "write_bytes_atomic / save_npy_atomic / savez_atomic / "
         "dump_pickle_atomic / write_wav_atomic) so a crash cannot leave a "
         "truncated final artifact")


#: modules whose ``X.open(path, mode)`` has the BUILTIN signature (mode at
#: position 1), unlike ``Path.open(mode)`` (mode at position 0)
_OPEN_MODULES = {"io", "gzip", "bz2", "lzma", "codecs", "tarfile", "zipfile"}


def _write_mode(mode: str | None) -> bool:
    """True for truncate/create modes; read ("r") and append ("a") pass."""
    return mode is not None and any(c in mode for c in "wx+")


def _open_mode(call: ast.Call, base: str | None) -> str | None:
    """The literal mode of an ``open``-shaped call, or None (default 'r' or
    non-literal — non-literal modes are skipped, not guessed).  ``base``
    distinguishes builtin-signature variants (``open``/``gzip.open``/... —
    mode at position 1) from method form (``path.open(mode)`` — position 0)."""
    pos = 1 if (base is None or base in _OPEN_MODULES) else 0
    for kw in call.keywords:
        if kw.arg == "mode":
            return str_literal(kw.value)
    if len(call.args) > pos:
        return str_literal(call.args[pos])
    return None


@register
class AtomicWrite(Rule):
    id = "DL004"
    name = "atomic-write"
    summary = ("raw write (open('w') / np.save / pickle.dump / soundfile / "
               "write_text) in a run-critical package — final artifacts must "
               "go through io.atomic")

    def applies(self, ctx) -> bool:
        return ctx.in_dir(*_SCOPE)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            name = chain[-1]
            base = chain[0] if len(chain) > 1 else None
            if name == "open":
                mode = _open_mode(node, base)
                if _write_mode(mode):
                    yield self.finding(
                        ctx, node,
                        f"raw open(..., {mode!r}) in a run-critical module; {_HINT}")
            elif name in _NP_WRITERS and base in _NP_BASES:
                yield self.finding(
                    ctx, node, f"raw np.{name} in a run-critical module; {_HINT}")
            elif name == "dump" and base in ("pickle", "cPickle"):
                yield self.finding(
                    ctx, node, f"raw pickle.dump in a run-critical module; {_HINT}")
            elif name == "write" and base in _SF_BASES:
                yield self.finding(
                    ctx, node, f"raw soundfile write in a run-critical module; {_HINT}")
            elif name in _PATH_WRITERS:
                yield self.finding(
                    ctx, node, f"raw .{name}() in a run-critical module; {_HINT}")
