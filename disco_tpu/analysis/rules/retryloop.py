"""DL013 — transport retries go through ``utils.resilience``, nowhere else.

PR 2 centralized transient-failure recovery in
:func:`disco_tpu.utils.resilience.call_with_retries`: bounded attempts, a
wall deadline, seeded-jitter backoff, and first-class telemetry (``fault``
events per failed attempt, ``recovery`` on a late success, the
``retries``/``retry_giveups`` counters).  An ad-hoc ``try/except
OSError``-and-go-again loop around a tunnel crossing has none of that — it
retries forever (or a magic number of times), sleeps however it likes,
desynchronizes with nothing and tells the obs log nothing — so any loop
that swallows a transport-layer error type and keeps looping is a finding.

The shape flagged: a ``while`` loop (or a ``for`` over ``range(...)`` — an
attempt counter) containing a ``try`` whose handler catches a transport
error type (``OSError``/``ConnectionError``/``TimeoutError`` or their
subclasses/aliases, or ``socket.error``) and then lets the loop continue —
no ``raise``, ``return`` or ``break`` anywhere in the handler.  A handler
that re-raises (fail-fast), returns a fallback or breaks out is not a
retry loop and is not flagged; neither is a loop *inside* a ``try`` (one
attempt, not a retry), nor a ``for`` over distinct items that skips a
failed item and moves on (the next iteration is different work, not a
re-attempt of the same crossing).

Allowed files: ``utils/resilience.py`` (the one implementation), and the
DL005 numpy-only client files (``serve/client.py``/``protocol.py`` and the
flywheel host side) — the import-purity contract bars them from
``utils.resilience``, whose transport-error table imports jax, so their
*client-socket* retries (connect backoff, reattach) are documented inline
stdlib implementations; the client socket is not the tunnel.

No reference counterpart: the reference never leaves one host process.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.registry import Rule, register
from disco_tpu.analysis.rules.purity import CLIENT_FILES

#: transport-layer exception names (the retry_on set of the wired seams,
#: plus the OSError subclasses a socket/tunnel failure commonly surfaces as)
_TRANSPORT_NAMES = frozenset({
    "OSError", "IOError", "EnvironmentError",
    "ConnectionError", "ConnectionRefusedError", "ConnectionResetError",
    "ConnectionAbortedError", "BrokenPipeError",
    "TimeoutError", "InterruptedError",
})

_ALLOWED_FILES = ("disco_tpu/utils/resilience.py",) + CLIENT_FILES


def _is_transport_type(node) -> bool:
    """True when an except-clause type expression names a transport error
    (a bare name, ``socket.error``/``socket.timeout``, or a tuple holding
    at least one of them)."""
    if node is None:
        return False
    if isinstance(node, ast.Tuple):
        return any(_is_transport_type(e) for e in node.elts)
    chain = attr_chain(node)
    if not chain:
        return False
    if len(chain) == 1:
        return chain[0] in _TRANSPORT_NAMES
    return chain[0] == "socket" and chain[-1] in ("error", "timeout")


def _handler_exits(handler: ast.ExceptHandler) -> bool:
    """True when the handler body can leave the loop / unwind (raise,
    return, break anywhere inside — conservative: a conditional re-raise
    still counts as an exit path, the DL013 concern is handlers with NO
    exit at all)."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
    return False


@register
class AdHocTransportRetryLoop(Rule):
    id = "DL013"
    name = "adhoc-transport-retry"
    summary = ("try/except swallowing a transport error type inside a loop "
               "outside utils.resilience — transport retries go through "
               "call_with_retries (bounded, jittered, telemetered)")

    def applies(self, ctx) -> bool:
        return not ctx.is_file(*_ALLOWED_FILES)

    @staticmethod
    def _is_retry_shaped(loop) -> bool:
        """``while`` loops and ``for`` over ``range(...)`` re-attempt the
        SAME work each iteration; a ``for`` over items does different work
        (skipping a failed item is not a retry)."""
        if isinstance(loop, ast.While):
            return True
        if isinstance(loop, (ast.For, ast.AsyncFor)):
            it = loop.iter
            return (isinstance(it, ast.Call)
                    and attr_chain(it.func) in (("range",), ("itertools", "count")))
        return False

    def check(self, ctx):
        for loop in ast.walk(ctx.tree):
            if not self._is_retry_shaped(loop):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if _is_transport_type(handler.type) and not _handler_exits(handler):
                        yield self.finding(
                            ctx, handler,
                            "ad-hoc transport retry loop: this handler "
                            "swallows a transport error type and loops "
                            "again — unbounded, unjittered and invisible "
                            "to obs; route the retry through utils."
                            "resilience.call_with_retries (retry_on="
                            "TRANSPORT_ERRORS) instead",
                        )
