"""DL007 — traced-float seams take float literals, never ints.

``lambda_cor`` and ``mu`` are traced floats through every jitted streaming/
serve entry point: jit folds the OMITTED default at trace time, but a
passed value becomes a traced argument typed by what was passed.  A literal
``mu=1`` therefore traces a third, int-typed program per shape bucket
instead of reusing the float one — the msgpack ``mu=1`` retrace trap that
``SessionConfig`` now coerces at the wire (CHANGES.md PR 6).  This rule
catches the same trap at every in-repo call site: int literals for these
keywords must be written as floats (``mu=1.0``).

No reference counterpart: the reference has no jit and no retrace hazard.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.registry import Rule, register

#: the keyword seams with traced-float calling conventions
_SEAMS = ("lambda_cor", "mu")


@register
class TracedFloatLiteral(Rule):
    id = "DL007"
    name = "traced-float-literal"
    summary = ("literal int passed for lambda_cor=/mu= — traces an extra "
               "int-typed jit program per shape bucket; write it as a float")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                # bool is an int subclass: True/False literals trip the same
                # retrace and are flagged too
                if (
                    kw.arg in _SEAMS
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, int)
                ):
                    yield self.finding(
                        ctx, kw.value,
                        f"literal {kw.value.value!r} for traced-float seam "
                        f"'{kw.arg}=': jit folds the omitted default but "
                        "traces a distinct int-typed program for a passed "
                        f"int — write {kw.arg}={float(kw.value.value)} "
                        "(the mu=1 retrace trap, CHANGES.md PR 6)",
                    )
