"""DL001 — fence discipline: no bare ``block_until_ready`` in pipeline code.

On the tunneled TPU attachment ``jax.block_until_ready`` returns without
waiting (~20 us — CLAUDE.md), so code that uses it as a fence measures
nothing and synchronizes nothing.  The sanctioned fences are the 1-element
readback in ``disco_tpu.milestones._fence`` / ``_fence_readback`` and
``disco_tpu.utils.resilience.resilient_fence``; the obs package may touch
``block_until_ready`` because it implements the accounting around those.

No reference counterpart: the reference never leaves one host process.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.registry import Rule, register

#: modules allowed to reference block_until_ready
_ALLOWED_DIRS = ("disco_tpu/obs",)
_ALLOWED_FILES = ("disco_tpu/milestones.py", "disco_tpu/milestones_corpus.py")


@register
class FenceDiscipline(Rule):
    id = "DL001"
    name = "fence-discipline"
    summary = ("bare jax.block_until_ready outside obs/milestones — it returns "
               "without waiting on the tunnel; fence with a 1-element readback")

    def applies(self, ctx) -> bool:
        return not (ctx.in_dir(*_ALLOWED_DIRS) or ctx.is_file(*_ALLOWED_FILES))

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Attribute, ast.Name)):
                chain = attr_chain(node)
                if chain and chain[-1] == "block_until_ready":
                    # report the outermost reference once, not its Name child
                    if isinstance(node, ast.Name) and chain != ("block_until_ready",):
                        continue
                    yield self.finding(
                        ctx, node,
                        "bare block_until_ready: on the tunneled attachment it "
                        "returns without waiting (CLAUDE.md) — fence with "
                        "milestones._fence / utils.resilience.resilient_fence "
                        "(1-element readback) instead",
                    )
