"""DL011 — ``lax.scan`` in bit-exactness-gated modules must pass ``unroll=``.

The PR-6 rolled-scan trap: a ROLLED ``lax.scan`` body compiles with
different FMA/fusion choices than the standalone per-block program
(measured ~2e-6 step-1 drift on CPU, amplified to ~3e-2 through the warm-up
GEVD + ffill hold), while unrolled bodies compile exactly like the
standalone program.  In the modules whose outputs are gated bit-exact
against a per-block reference (``enhance/streaming.py`` — the
``streaming_tango_scan`` super-tick driver and every scan inside the traced
per-block body — and the serve scheduler that dispatches them), the unroll
choice is therefore load-bearing and must be EXPLICIT: ``unroll=N`` where
the scan must compile like the per-block program, ``unroll=1`` where the
rolled form is the deliberate choice (intra-program recursions that exist
in both the scanned and per-block paths and so cancel in the parity
comparison).  An omitted ``unroll=`` is indistinguishable from "nobody
thought about it" — exactly how the PR-6 divergence shipped.

The jaxpr-level twin of this rule is the golden-fingerprint gate
(``disco_tpu.analysis.trace``), which records every scan's ``unroll``
parameter in the traced program and fails CI when it drifts; this AST rule
catches the same trap at review time, before anything is traced.

No reference counterpart: the reference has no jit, no scan and no
bit-exactness gate.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.registry import Rule, register

#: the modules whose scans are bit-exactness-gated (make stream-check /
#: make serve-check compare their outputs bit-for-bit against a per-block
#: reference)
_GATED_FILES = (
    "disco_tpu/enhance/streaming.py",
    "disco_tpu/serve/scheduler.py",
    # the dynamic-scene blend: scene-check's crash-and-resume leg compares
    # artifact trees byte-for-byte, so its scan order is load-bearing too
    "disco_tpu/scenes/dynamic.py",
)


@register
class ScanUnroll(Rule):
    id = "DL011"
    name = "scan-unroll"
    summary = ("lax.scan without an explicit unroll= in a bit-exactness-"
               "gated module — the PR-6 rolled-scan FMA-drift trap; state "
               "the unroll choice")

    def applies(self, ctx) -> bool:
        return ctx.is_file(*_GATED_FILES)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "scan":
                continue
            # jax.lax.scan / lax.scan / a bare `scan` from-import; leave
            # other .scan callees (e.g. a dataframe API) alone
            if len(chain) > 1 and chain[-2] != "lax":
                continue
            if any(kw.arg == "unroll" for kw in node.keywords):
                continue
            yield self.finding(
                ctx, node,
                "lax.scan without an explicit unroll= in a bit-exactness-"
                "gated module: a rolled scan body compiles with different "
                "FMA/fusion choices than the standalone per-block program "
                "(the PR-6 ~2e-6 step-1 drift, amplified ~3e-2 through the "
                "warm-up GEVD) — pass unroll=N to compile like the "
                "per-block program, or unroll=1 to state that the rolled "
                "form is deliberate",
            )
