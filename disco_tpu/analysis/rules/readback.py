"""DL002 — no per-item host readbacks inside loop bodies in hot packages.

Every device->host crossing on the tunneled attachment is one fenced
~80 ms RPC, so a readback inside a loop multiplies the fixed cost by the
trip count — the exact anti-pattern behind the pre-engine driver's
K x n_real per-chunk crossings (CHANGES.md PR 4).  The sanctioned shape is
ONE batched :func:`disco_tpu.utils.transfer.device_get_tree` call before or
after the loop.  ``np.asarray`` is included as a heuristic: on a device
array it IS the raw crossing; in-loop uses on host arrays get a per-line
suppression stating that.

No reference counterpart: the reference never crosses a device boundary.
"""
from __future__ import annotations

from disco_tpu.analysis.context import callee_name
from disco_tpu.analysis.registry import Rule, register

_SCOPE = ("disco_tpu/enhance", "disco_tpu/serve", "disco_tpu/nn")
_READBACK = {"to_host", "resilient_to_host", "device_get"}
_HEURISTIC = {"asarray"}


@register
class HostReadbackInLoop(Rule):
    id = "DL002"
    name = "host-readback-in-loop"
    summary = ("device->host readback (to_host/device_get/np.asarray) inside a "
               "loop body in enhance/serve/nn — each is a fenced ~80 ms RPC; "
               "batch with ONE device_get_tree")

    def applies(self, ctx) -> bool:
        return ctx.in_dir(*_SCOPE)

    def check(self, ctx):
        for call, depth in ctx.calls_with_loop_depth():
            if not depth:
                continue
            name = callee_name(call)
            if name in _READBACK:
                yield self.finding(
                    ctx, call,
                    f"{name}() inside a loop body: each crossing is a fenced "
                    "~80 ms tunnel RPC — queue the per-item work on device and "
                    "read it back in ONE batched utils.transfer.device_get_tree",
                )
            elif name in _HEURISTIC:
                yield self.finding(
                    ctx, call,
                    f"{name}() inside a loop body: on a device array this is a "
                    "raw per-item crossing (~80 ms fenced RPC each) — batch via "
                    "device_get_tree, or suppress stating the operand is "
                    "host-resident",
                )
