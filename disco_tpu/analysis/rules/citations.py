"""DL006 — every public function documents its reference counterpart.

The repo convention (CLAUDE.md): every public function cites its reference
counterpart (``file:line``) in the docstring, or states that it has none
and why.  The rule checks every public module-level function under
``disco_tpu/`` for (a) a docstring at all and (b) a citation — a
``file:line`` pattern or an explicit reference-counterpart statement —
in the function docstring or, for infrastructure modules whose whole file
shares one provenance, in the module docstring.

No reference counterpart: the reference repo does not document one.
"""
from __future__ import annotations

import ast
import re

from disco_tpu.analysis.registry import Rule, register

#: "tango.py:189-225", "main:497", "SURVEY.md §5.1" all count as citations
_CITE = re.compile(r"[\w./-]+\.\w+:\d+|\bmain:\d+")
_MENTION = re.compile(r"\breference\b|\bSURVEY\.md\b|\bPARITY\.md\b", re.I)


def _cited(doc: str) -> bool:
    return bool(_CITE.search(doc) or _MENTION.search(doc))


@register
class ReferenceCitation(Rule):
    id = "DL006"
    name = "reference-citation"
    summary = ("public function without a docstring, or whose docstring (and "
               "module docstring) never cites a reference counterpart")

    def applies(self, ctx) -> bool:
        return ctx.in_dir("disco_tpu")

    def check(self, ctx):
        module_ok = _cited(ctx.module_docstring())
        for node in ctx.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name.startswith("_"):
                continue
            doc = ast.get_docstring(node)
            if doc is None:
                yield self.finding(
                    ctx, node,
                    f"public function {node.name!r} has no docstring "
                    "(CLAUDE.md: every public function cites its reference "
                    "counterpart)",
                )
            elif not (_cited(doc) or module_ok):
                yield self.finding(
                    ctx, node,
                    f"docstring of {node.name!r} cites no reference "
                    "counterpart — add 'reference <file>:<line>' or state "
                    "'No reference counterpart: <why>' (function or module "
                    "docstring)",
                )
