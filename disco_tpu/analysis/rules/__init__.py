"""Rule modules — importing this package populates the registry.

Rule catalog (one module per contract; ids are stable, documentation order
is registration order):

* DL001 ``fence-discipline``      — :mod:`.fence`
* DL002 ``host-readback-in-loop`` — :mod:`.readback`
* DL003 ``raw-tunnel-transfer``   — :mod:`.transfer`
* DL004 ``atomic-write``          — :mod:`.atomicio`
* DL005 ``import-purity``         — :mod:`.purity`
* DL006 ``reference-citation``    — :mod:`.citations`
* DL007 ``traced-float-literal``  — :mod:`.tracedfloat`
* DL008 ``never-sigkill``         — :mod:`.sigkill`
* DL009 ``obs-event-kind``        — :mod:`.registered`
* DL010 ``chaos-seam``            — :mod:`.registered`
* DL011 ``scan-unroll``           — :mod:`.scanunroll`
* DL012 ``fused-magnitude-precision`` — :mod:`.magnitude`
* DL013 ``adhoc-transport-retry`` — :mod:`.retryloop`
* DL014 ``span-stage-status-section`` — :mod:`.registered`
* DL015 ``bare-thread-primitive``  — :mod:`.threads`
* DL016 ``fused-solver-selection`` — :mod:`.fusedsolver`

(DL000 ``lint-suppression`` is the engine's own hygiene rule — see
:mod:`disco_tpu.analysis.suppressions`.)

No reference counterpart: the reference repo has no static analysis.
"""
from disco_tpu.analysis.rules import (  # noqa: F401  (import = register)
    atomicio,
    citations,
    fence,
    fusedsolver,
    magnitude,
    purity,
    readback,
    registered,
    retryloop,
    scanunroll,
    sigkill,
    threads,
    tracedfloat,
    transfer,
)
