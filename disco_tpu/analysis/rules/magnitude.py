"""DL012 — magnitude recomputation and precision-cast literals stay in ops/.

The hot-path fusion round gave the analysis stage a fused spec+magnitude
STFT (``ops.stft_ops.stft_with_mag``: the magnitude is computed in VMEM
while the re/im tiles are resident) and a ``precision=`` compute-lane seam
(``ops.resolve``: 'f32'/'bf16', canonicalized once, threaded as a static
argument through tango/streaming/driver).  Two call-site shapes silently
undo those seams:

* ``jnp.abs(stft(...))`` — recomputing the magnitude from a fresh STFT is
  exactly the separate abs-pass-over-HBM the fused kernel deletes, and it
  bypasses the ``stft_impl`` resolution (the caller gets whatever ``stft``
  alone resolves to, with a second read of the spec).
* dtype-cast literals (``x.astype("bfloat16")``, ``dtype=jnp.bfloat16``) —
  a hand-rolled precision change outside ops/ creates a lane the
  ``precision=`` seam doesn't know about: it escapes the oracle-tolerance
  gates, and as a non-canonical static value it is the string-typed twin
  of the PR-6 ``mu=1`` retrace trap.

Inside ``disco_tpu/ops/`` both shapes are the implementation itself (the
'xla' lane of ``stft_with_mag`` IS ``abs(stft(...))``; the bf16 casts live
in the kernels) — the rule exempts it.

No reference counterpart: the reference has one STFT path and one dtype
(float64 numpy).
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.registry import Rule, register

#: callables whose result is a complex spectrogram the fused kernel already
#: pairs with a magnitude
_STFT_NAMES = ("stft", "_stft_rfft", "stft_matmul", "stft_pallas",
               "stft_fused", "stft_with_mag")

#: the magnitude callables the recomputation shape goes through
_ABS_NAMES = ("abs", "absolute")


def _is_bf16_literal(node) -> bool:
    """True for the literal spellings of a bfloat16 dtype: the string
    ``"bfloat16"``/``"bf16"`` or an attribute chain ending in ``bfloat16``
    (``jnp.bfloat16``, ``np.bfloat16``, ...).

    No reference counterpart (module docstring)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip().lower() in ("bfloat16", "bf16")
    chain = attr_chain(node)
    return bool(chain) and chain[-1] == "bfloat16"


@register
class MagnitudePrecisionSeam(Rule):
    id = "DL012"
    name = "fused-magnitude-precision"
    summary = ("jnp.abs(stft(...)) magnitude recomputation or a bfloat16 "
               "cast literal outside ops/ — use the fused spec+mag STFT "
               "and the precision= seam")

    def applies(self, ctx) -> bool:
        return not ctx.in_dir("disco_tpu/ops")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain and chain[-1] in _ABS_NAMES and node.args:
                inner = node.args[0]
                if isinstance(inner, ast.Call):
                    ichain = attr_chain(inner.func)
                    if ichain and ichain[-1] in _STFT_NAMES:
                        yield self.finding(
                            ctx, node,
                            "magnitude recomputed as abs(stft(...)): the "
                            "fused spec+magnitude kernel "
                            "(ops.stft_ops.stft_with_mag) already emits it "
                            "in the same pass — a separate abs is one more "
                            "HBM read of the full spec and bypasses the "
                            "stft_impl seam",
                        )
            if chain and chain[-1] == "astype" and node.args \
                    and _is_bf16_literal(node.args[0]):
                yield self.finding(
                    ctx, node,
                    "bfloat16 cast literal outside ops/: precision changes "
                    "go through the precision= seam (ops.resolve) so the "
                    "lane stays oracle-gated and canonical — a hand-rolled "
                    "cast is the string-typed mu=1 retrace trap",
                )
            for kw in node.keywords:
                if kw.arg == "dtype" and _is_bf16_literal(kw.value):
                    yield self.finding(
                        ctx, node,
                        "bfloat16 dtype literal outside ops/: request the "
                        "lane through the precision= seam (ops.resolve) "
                        "instead of constructing bf16 values directly",
                    )
