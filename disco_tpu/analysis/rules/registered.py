"""DL009/DL010/DL014 — registered telemetry strings: event kinds, chaos
seams, span stages, status sections.

The obs event log, the chaos harness, the causal tracer and the serve
status surface are all keyed by bare strings at the call site
(``record("clip", ...)``, ``chaos.tick("mid_write")``,
``span("dispatch", ctx)``, ``status_section(payload, "counters")``).  A
typo'd kind crashes only when the schema-validating reader runs; a typo'd
seam is worse — it arms NOTHING and the chaos test silently tests
nothing; a typo'd span stage breaks every chain reconstruction that
expects the canonical hop names, and a typo'd status section renders
blanks in ``disco-obs top``.  These rules check every string literal at
those call sites against the declared registries (``EVENT_KINDS`` in
``obs/events.py``, ``SEAMS`` in ``runs/chaos.py``, ``SPAN_STAGES`` in
``obs/trace.py``, ``STATUS_SECTIONS`` in ``serve/status.py``), parsed
from source so the linter stays hermetic (no production import, no jax).

No reference counterpart: the reference has neither telemetry nor chaos.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis import registries
from disco_tpu.analysis.context import attr_chain, str_literal
from disco_tpu.analysis.registry import Rule, register

#: receiver aliases under which obs.events' record() is called in-repo
_OBS_ALIASES = {"obs", "_obs", "obs_events", "events", "_events", "ev", "_ev"}


def _record_calls(ctx):
    """Calls that are (by alias convention) obs.events.record invocations."""
    bare_record = any(
        isinstance(node, ast.ImportFrom)
        and (node.module or "").startswith("disco_tpu.obs")
        and any(a.name == "record" for a in node.names)
        for node in ast.walk(ctx.tree)
    )
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain:
            continue
        if (len(chain) >= 2 and chain[-1] == "record" and chain[0] in _OBS_ALIASES) or (
            chain == ("record",) and bare_record
        ):
            yield node


@register
class ObsEventKind(Rule):
    id = "DL009"
    name = "obs-event-kind"
    summary = ("obs record() called with an event kind missing from "
               "EVENT_KINDS — the schema-validating reader would reject the "
               "log it produces")

    def check(self, ctx):
        kinds = registries.event_kinds(ctx.root)
        for call in _record_calls(ctx):
            kind = str_literal(call.args[0]) if call.args else None
            if kind is not None and kind not in kinds:
                yield self.finding(
                    ctx, call,
                    f"event kind {kind!r} is not in obs.events.EVENT_KINDS — "
                    "register it there (and teach disco-obs report about it) "
                    "or fix the typo; the JSONL reader rejects unknown kinds",
                )


@register
class ChaosSeamName(Rule):
    id = "DL010"
    name = "chaos-seam"
    summary = ("chaos tick() called with a seam missing from runs.chaos.SEAMS "
               "— a typo'd seam arms nothing and the chaos gate silently "
               "tests nothing")

    def check(self, ctx):
        seams = registries.chaos_seams(ctx.root)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "tick":
                continue
            seam = str_literal(node.args[0]) if node.args else None
            if seam is not None and seam not in seams:
                yield self.finding(
                    ctx, node,
                    f"chaos seam {seam!r} is not in runs.chaos.SEAMS — "
                    "register the seam (and document it in the chaos module "
                    "docstring) or fix the typo; an unknown seam never arms",
                )


#: receiver aliases under which obs.trace's span()/root() are called
_TRACE_ALIASES = {"trace", "_trace", "obs_trace", "tracer"}


def _span_stage_literal(call: ast.Call):
    """The stage string literal of a span()/root() call: first positional
    arg, or the ``stage=`` keyword (root's signature)."""
    for kw in call.keywords:
        if kw.arg == "stage":
            return str_literal(kw.value)
    if call.args:
        return str_literal(call.args[0])
    return None


@register
class SpanStageStatusSection(Rule):
    id = "DL014"
    name = "span-stage-status-section"
    summary = ("span()/root() called with a stage missing from SPAN_STAGES, "
               "or status_section() with a section missing from "
               "STATUS_SECTIONS — a typo'd hop breaks chain reconstruction, "
               "a typo'd section renders blanks")

    def check(self, ctx):
        stages = registries.span_stages(ctx.root)
        sections = registries.status_sections(ctx.root)
        bare_span = any(
            isinstance(node, ast.ImportFrom)
            and (node.module or "").startswith("disco_tpu.obs")
            and any(a.name in ("span", "root", "record_span")
                    for a in node.names)
            for node in ast.walk(ctx.tree)
        )
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            name = chain[-1]
            if name in ("span", "root", "record_span") and (
                (len(chain) >= 2 and chain[0] in _TRACE_ALIASES)
                or (len(chain) == 1 and bare_span)
            ):
                stage = _span_stage_literal(node)
                if stage is not None and stage not in stages:
                    yield self.finding(
                        ctx, node,
                        f"span stage {stage!r} is not in obs.trace."
                        "SPAN_STAGES — register the hop (and teach the "
                        "waterfall/STAGE_ORDER about it) or fix the typo; "
                        "chain reconstruction expects the canonical names",
                    )
            elif name == "status_section":
                section = (str_literal(node.args[1])
                           if len(node.args) > 1 else None)
                if section is not None and section not in sections:
                    yield self.finding(
                        ctx, node,
                        f"status section {section!r} is not in serve.status."
                        "STATUS_SECTIONS — register the section in the "
                        "payload builder or fix the typo; an unknown section "
                        "raises KeyError at render time",
                    )
