"""DL009/DL010 — telemetry kinds and chaos seams come from their registries.

Both the obs event log and the chaos harness are keyed by bare strings at
the call site (``record("clip", ...)``, ``chaos.tick("mid_write")``).  A
typo'd kind crashes only when the schema-validating reader runs; a typo'd
seam is worse — it arms NOTHING and the chaos test silently tests nothing.
These rules check every string literal at those call sites against the
declared registries (``EVENT_KINDS`` in ``obs/events.py``, ``SEAMS`` in
``runs/chaos.py``), parsed from source so the linter stays hermetic (no
production import, no jax).

No reference counterpart: the reference has neither telemetry nor chaos.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis import registries
from disco_tpu.analysis.context import attr_chain, str_literal
from disco_tpu.analysis.registry import Rule, register

#: receiver aliases under which obs.events' record() is called in-repo
_OBS_ALIASES = {"obs", "_obs", "obs_events", "events", "_events", "ev", "_ev"}


def _record_calls(ctx):
    """Calls that are (by alias convention) obs.events.record invocations."""
    bare_record = any(
        isinstance(node, ast.ImportFrom)
        and (node.module or "").startswith("disco_tpu.obs")
        and any(a.name == "record" for a in node.names)
        for node in ast.walk(ctx.tree)
    )
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain:
            continue
        if (len(chain) >= 2 and chain[-1] == "record" and chain[0] in _OBS_ALIASES) or (
            chain == ("record",) and bare_record
        ):
            yield node


@register
class ObsEventKind(Rule):
    id = "DL009"
    name = "obs-event-kind"
    summary = ("obs record() called with an event kind missing from "
               "EVENT_KINDS — the schema-validating reader would reject the "
               "log it produces")

    def check(self, ctx):
        kinds = registries.event_kinds(ctx.root)
        for call in _record_calls(ctx):
            kind = str_literal(call.args[0]) if call.args else None
            if kind is not None and kind not in kinds:
                yield self.finding(
                    ctx, call,
                    f"event kind {kind!r} is not in obs.events.EVENT_KINDS — "
                    "register it there (and teach disco-obs report about it) "
                    "or fix the typo; the JSONL reader rejects unknown kinds",
                )


@register
class ChaosSeamName(Rule):
    id = "DL010"
    name = "chaos-seam"
    summary = ("chaos tick() called with a seam missing from runs.chaos.SEAMS "
               "— a typo'd seam arms nothing and the chaos gate silently "
               "tests nothing")

    def check(self, ctx):
        seams = registries.chaos_seams(ctx.root)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "tick":
                continue
            seam = str_literal(node.args[0]) if node.args else None
            if seam is not None and seam not in seams:
                yield self.finding(
                    ctx, node,
                    f"chaos seam {seam!r} is not in runs.chaos.SEAMS — "
                    "register the seam (and document it in the chaos module "
                    "docstring) or fix the typo; an unknown seam never arms",
                )
