"""DL016 — fused-solver selection goes through the dispatch seams.

The solve-fusion round gave the pipeline a fused rank-1 GEVD-MWF solve
(``ops/mwf_ops.py``) selected by the ``solver='fused'``/``'fused-xla'``/
``'fused-pallas'`` specs of THE dispatch table
(``beam.filters.rank1_gevd`` via ``parse_solver_spec``) and resolved per
backend by the shared ``ops.resolve`` policy
(``mwf_ops.resolve_mwf_impl``, ``DISCO_TPU_MWF_IMPL``).  Two call-site
shapes silently bypass those seams:

* calling the fused ops directly (``rank1_gevd_fused`` /
  ``fused_mwf_xla`` / ``fused_mwf_pallas`` / ``resolve_mwf_impl``)
  outside ops/ and the dispatch table — the caller picks a kernel without
  the grammar validation, the env escape hatch, or the sanitize policy the
  dispatch owns, and the bench provenance (``solver_lanes``) stops
  describing what actually ran;
* branching on ``'fused'``-family string literals (``solver == "fused"``,
  ``base in ("fused", ...)``, and — since the step-1 fusion round —
  prefix probes like ``solver.startswith("fused")``) — ad-hoc grammar
  re-implementation, the same drift hazard ``parse_solver_spec`` exists
  to prevent (a call site that spells the family check itself will miss
  the next spec added to the table).

Passing a fused spec AS DATA (``solver="fused"`` into ``rank1_gevd``/
``tango``/the CLI) is the sanctioned path and stays legal — the rule
targets selection LOGIC, not spec strings.  Call sites that genuinely
need the family decision (the step-1 K×F pencil batching in
``enhance.tango``, the chained-clip program in ``enhance.fused``) route
it through ``solver_spec.is_fused_spec`` — a function call, not a
comparison, so it stays legal everywhere by construction.  Inside
``disco_tpu/ops/``, ``disco_tpu/beam/filters.py`` (the dispatch table)
and ``disco_tpu/solver_spec.py`` (the grammar itself) both shapes ARE
the implementation — exempt.

No reference counterpart: the reference solves every pencil one way only
(``scipy.linalg.eig``, internal_formulas.py:56-73).
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.registry import Rule, register

#: the fused-solve entry points owned by the dispatch seams
_FUSED_CALLS = ("rank1_gevd_fused", "fused_mwf_xla", "fused_mwf_pallas",
                "resolve_mwf_impl")

#: the spec bases of the fused solver family (beam.filters._FUSED_IMPLS)
_FUSED_BASES = ("fused", "fused-xla", "fused-pallas")


def _fused_literal(node) -> bool:
    """True for a string constant of the fused solver family (optionally
    with a ``:N`` suffix), or a tuple/list/set display containing one.

    No reference counterpart (module docstring)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.partition(":")[0] in _FUSED_BASES
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(_fused_literal(el) for el in node.elts)
    return False


@register
class FusedSolverSeam(Rule):
    id = "DL016"
    name = "fused-solver-selection"
    summary = ("fused-solve selection bypassing parse_solver_spec / "
               "ops.resolve — direct fused-op calls or 'fused' literal "
               "comparisons outside the dispatch seams")

    def applies(self, ctx) -> bool:
        return not (ctx.in_dir("disco_tpu/ops")
                    or ctx.is_file("disco_tpu/beam/filters.py")
                    or ctx.is_file("disco_tpu/solver_spec.py"))

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain and chain[-1] in _FUSED_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"direct call to {chain[-1]} outside ops/ and the "
                        "rank1_gevd dispatch table: select the fused solve "
                        "with a solver spec ('fused[:N]'/'fused-xla'/"
                        "'fused-pallas') through parse_solver_spec so the "
                        "grammar, the DISCO_TPU_MWF_IMPL resolution and the "
                        "sanitize policy stay owned by the seams",
                    )
                elif (chain and chain[-1] == "startswith"
                        and any(_fused_literal(a) for a in node.args)):
                    # solver.startswith("fused") — the prefix spelling of
                    # the same ad-hoc family check (a "fused-xla" spec
                    # matches it by accident, the next family member by
                    # luck only); the sanctioned predicate is
                    # solver_spec.is_fused_spec
                    yield self.finding(
                        ctx, node,
                        "'fused' family probe via startswith: solver-family "
                        "branching belongs behind solver_spec.is_fused_spec "
                        "/ parse_solver_spec — a prefix check drifts the "
                        "moment the spec grammar grows",
                    )
            elif isinstance(node, ast.Compare):
                operands = [node.left, *node.comparators]
                if any(_fused_literal(op) for op in operands):
                    yield self.finding(
                        ctx, node,
                        "comparison against a 'fused' solver literal: "
                        "solver-family branching belongs behind "
                        "parse_solver_spec / the rank1_gevd dispatch table "
                        "(beam/filters.py) — an ad-hoc family check drifts "
                        "the moment the spec grammar grows",
                    )
