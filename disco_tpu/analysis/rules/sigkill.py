"""DL008 — the never-SIGKILL contract, statically.

A SIGKILLed TPU-holding process wedges the remote chip claim for hours
(CLAUDE.md), so nothing in this repo may hard-kill a process: no
``SIGKILL`` reference, no ``os.kill``, no ``Popen.kill()``/``terminate()``.
The sanctioned stop path is ``disco_tpu.runs.interrupt`` (signal a graceful
flag, drain between units, exit resumable) and, for subprocess tests, a
SIGINT + wait.  Legitimate exceptions (there are currently none in
production code) must carry a suppression explaining why the target can
never be the chip holder.

No reference counterpart: the reference has no process management at all.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.registry import Rule, register


@register
class NeverSigkill(Rule):
    id = "DL008"
    name = "never-sigkill"
    summary = ("SIGKILL reference or os.kill/.kill()/.terminate() call — a "
               "killed chip holder wedges the remote claim; use "
               "runs.interrupt graceful stops")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Attribute, ast.Name)):
                chain = attr_chain(node)
                if chain and chain[-1] == "SIGKILL" and (
                    not isinstance(node, ast.Name) or len(chain) == 1
                ):
                    yield self.finding(
                        ctx, node,
                        "SIGKILL referenced: the environment contract forbids "
                        "hard-killing a (potential) chip holder — a killed "
                        "holder wedges the remote claim for hours",
                    )
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if not chain:
                    continue
                if chain == ("os", "kill"):
                    yield self.finding(
                        ctx, node,
                        "os.kill(): signal delivery to another process risks "
                        "the never-SIGKILL contract — use runs.interrupt "
                        "(graceful flag + drain) or justify why the target "
                        "can never hold the chip",
                    )
                elif len(chain) > 1 and chain[-1] in ("kill", "terminate"):
                    yield self.finding(
                        ctx, node,
                        f".{chain[-1]}() on a process object: Popen.kill is "
                        "SIGKILL and terminate skips the graceful drain — "
                        "send SIGINT and wait for the resumable exit instead",
                    )
