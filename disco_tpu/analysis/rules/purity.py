"""DL005 — import purity: serve clients, the flywheel host side and CLI
wiring stay jax-free.

The environment contract allows ONE chip-claiming process, so the
numpy+stdlib serve client (``serve/client.py`` + ``serve/protocol.py``)
must be importable with no jax anywhere — not even lazily, since any call
path that reaches jax would claim (or block on) the chip from the client
process.  The flywheel's host side (``flywheel/tap.py`` writer thread,
``flywheel/shards.py`` codec, ``flywheel/dataset.py`` reader) carries the
same contract for a different reason: its tap thread runs INSIDE the one
chip-claiming server process, where a second thread entering jax would
contend for the single dispatch thread's claim.  The CLI modules may use
jax, but only INSIDE ``main``-path functions: a module-level import would
claim the chip at ``--help`` time and break the jax-free gates that shell
out to argparse.

Generalizes the bespoke AST walk formerly in ``tests/test_serve.py`` (the
client purity contract now has exactly one implementation — this rule).

No reference counterpart: the reference has no serve client.
"""
from __future__ import annotations

from disco_tpu.analysis.context import imports_module
from disco_tpu.analysis.registry import Rule, register

_BANNED = ("jax", "jaxlib", "torch")
#: no jax/torch ANYWHERE (module or function level): the numpy-only serve
#: client plus the flywheel host side (the tap's writer thread must never
#: import jax — it shares a process with the one chip claim)
CLIENT_FILES = (
    "disco_tpu/serve/client.py",
    "disco_tpu/serve/protocol.py",
    "disco_tpu/flywheel/__init__.py",
    "disco_tpu/flywheel/tap.py",
    "disco_tpu/flywheel/shards.py",
    "disco_tpu/flywheel/dataset.py",
)
#: no jax/torch at MODULE level (lazy in-function imports are the idiom)
_CLI_DIR = "disco_tpu/cli"


@register
class ImportPurity(Rule):
    id = "DL005"
    name = "import-purity"
    summary = ("jax/torch imported in the numpy-only serve client (anywhere) "
               "or at module level in cli arg-parsing modules")

    def applies(self, ctx) -> bool:
        return ctx.is_file(*CLIENT_FILES) or ctx.in_dir(_CLI_DIR)

    def check(self, ctx):
        if ctx.is_file(*CLIENT_FILES):
            import ast

            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.Import, ast.ImportFrom)) and imports_module(
                    node, *_BANNED
                ):
                    yield self.finding(
                        ctx, node,
                        "jax/torch import in a numpy-only module (serve "
                        "client / flywheel host side): it must be importable "
                        "and runnable without ever touching the chip claim "
                        "(one-process contract; the tap's writer thread "
                        "shares the server process)",
                    )
        else:
            for node in ctx.module_level_imports():
                if imports_module(node, *_BANNED):
                    yield self.finding(
                        ctx, node,
                        "module-level jax/torch import in a CLI module claims "
                        "the chip at --help time — import lazily inside the "
                        "function that needs it",
                    )
