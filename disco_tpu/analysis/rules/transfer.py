"""DL003 — raw transfer primitives stay inside ``utils.transfer``.

Complex dtypes cannot cross the tunnel (environment contract, CLAUDE.md):
a raw ``jax.device_get``/``jax.device_put`` on complex data wedges or
corrupts the transfer, and whether an array is complex is invisible at most
call sites.  So the raw primitives are confined to
``disco_tpu/utils/transfer.py``, whose ``to_host`` / ``to_device`` /
``device_get_tree`` split complex arrays into two real transfers; everyone
else calls those.

No reference counterpart: the reference never crosses a device boundary.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain, imports_module
from disco_tpu.analysis.registry import Rule, register

_RAW = {"device_get", "device_put"}
_ALLOWED_FILE = "disco_tpu/utils/transfer.py"


@register
class RawTunnelTransfer(Rule):
    id = "DL003"
    name = "raw-tunnel-transfer"
    summary = ("direct jax.device_get/device_put outside utils.transfer — raw "
               "transfers are not complex-safe on the tunnel; use "
               "to_host/to_device/device_get_tree")

    def applies(self, ctx) -> bool:
        return not ctx.is_file(_ALLOWED_FILE)

    def check(self, ctx):
        # bare names count only when actually imported from jax
        bare = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and imports_module(node, "jax"):
                bare.update(a.asname or a.name for a in node.names if a.name in _RAW)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            raw = (len(chain) >= 2 and chain[0] == "jax" and chain[-1] in _RAW) or (
                len(chain) == 1 and chain[0] in bare
            )
            if raw:
                yield self.finding(
                    ctx, node,
                    f"raw jax.{chain[-1]}: complex dtypes cannot cross the "
                    "tunnel (environment contract) — use utils.transfer."
                    "to_host/to_device/device_get_tree, which split complex "
                    "arrays into two real transfers",
                )
