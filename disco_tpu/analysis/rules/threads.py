"""DL015: bare thread-primitive creation outside the race registries.

The race analyzer (``disco-race``, :mod:`disco_tpu.analysis.race`) models
the repo's concurrency from two declared registries: thread roles
(``race/roles.py``) and named locks (``race/registries.py``).  The model
is only as complete as the registries, so this rule closes the loop at
LINT time, per file and purely lexically:

* a ``threading.Thread(target=...)`` / ``threading.Timer(...)`` whose
  target's final name is not the leaf of any registered role entry point
  is a finding — the thread would run code no role declares;
* a ``threading.Lock()``/``RLock()``/``Condition()`` assigned to a name
  that is not a registered lock attribute for its module/class — or not
  assigned to a name at all — is a finding: an anonymous lock cannot
  participate in the lock-order analysis.

The deep, call-graph-accurate version of both checks is disco-race's
DR001/DR005 (which resolves targets module-qualified instead of by leaf
name); DL015 is the cheap per-file tripwire that fires inside the same
gate run as every other lint rule, exactly like DL009/DL010 police the
obs/chaos string registries.  The registries are imported directly:
:mod:`disco_tpu.analysis.race` is stdlib-only by construction (pinned by
test), so the linter stays jax-free.

No reference counterpart: the reference repo is single-threaded.
"""
from __future__ import annotations

import ast

from disco_tpu.analysis.context import attr_chain
from disco_tpu.analysis.registry import Rule, register

_LOCK_CTORS = ("Lock", "RLock", "Condition")
_SPAWN_CTORS = ("Thread", "Timer")


def _threading_names(ctx) -> dict:
    """Map of local alias -> threading member name for this file
    (``threading.Thread`` and ``from threading import Thread`` forms)."""
    out = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "threading":
                    out[alias.asname or "threading"] = "*"
        elif isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                out[alias.asname or alias.name] = alias.name
    return out


@register
class BareThreadPrimitive(Rule):
    """DL015 (module docstring)."""

    id = "DL015"
    name = "bare-thread-primitive"
    summary = (
        "threading.Thread/Timer targets must be registered race-role "
        "entry points and Lock/RLock/Condition must land on registered "
        "lock attributes (disco_tpu/analysis/race registries)"
    )

    def check(self, ctx):
        from disco_tpu.analysis.race.callgraph import module_of
        from disco_tpu.analysis.race.roles import entry_point_leaves

        aliases = _threading_names(ctx)
        if not aliases:
            return
        leaves = entry_point_leaves()
        module = module_of(ctx.rel)
        lock_assigns = set()    # Call node ids consumed by a named assign
        yield from self._check_lock_assigns(ctx, aliases, module,
                                            lock_assigns)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            member = self._threading_member(node, aliases)
            if member in _SPAWN_CTORS:
                yield from self._check_spawn(ctx, node, member, leaves)
            elif member in _LOCK_CTORS and id(node) not in lock_assigns:
                yield self.finding(
                    ctx, node,
                    f"threading.{member}() not assigned to a named "
                    "module- or instance-level attribute — an anonymous "
                    "lock cannot be registered in race/registries.py",
                )

    def _threading_member(self, call: ast.Call, aliases: dict):
        chain = attr_chain(call.func)
        if chain is None:
            return None
        if len(chain) == 2 and aliases.get(chain[0]) == "*":
            return chain[1]
        if len(chain) == 1:
            member = aliases.get(chain[0])
            return member if member != "*" else None
        return None

    def _check_spawn(self, ctx, node: ast.Call, member: str, leaves):
        target = None
        if member == "Thread":
            target = next((k.value for k in node.keywords
                           if k.arg == "target"), None)
        else:   # Timer(interval, function, ...)
            target = (node.args[1] if len(node.args) > 1 else
                      next((k.value for k in node.keywords
                            if k.arg == "function"), None))
        if target is None:
            yield self.finding(
                ctx, node,
                f"threading.{member} without an explicit target callable "
                "— the race role cannot be checked")
            return
        chain = attr_chain(target)
        leaf = chain[-1] if chain else None
        if leaf is None or leaf not in leaves:
            shown = ".".join(chain) if chain else "<computed>"
            yield self.finding(
                ctx, node,
                f"threading.{member} target '{shown}' is not a registered "
                "race-role entry point — declare the thread's role in "
                "disco_tpu/analysis/race/roles.py (disco-race DR001 is "
                "the call-graph-accurate twin of this check)")

    def _check_lock_assigns(self, ctx, aliases, module, consumed):
        """Walk assignments with class scope tracked; mark named lock
        constructor calls consumed and judge their registry ids."""
        from disco_tpu.analysis.race.registries import is_registered, lock_id

        def walk(body, cls):
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    yield from walk(stmt.body, stmt.name if cls is None else cls)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from walk(stmt.body, cls)
                    continue
                if isinstance(stmt, (ast.If, ast.Try, ast.With,
                                     ast.For, ast.While)):
                    for name in ("body", "orelse", "finalbody"):
                        yield from walk(getattr(stmt, name, []) or [], cls)
                    for h in getattr(stmt, "handlers", ()):
                        yield from walk(h.body, cls)
                    continue
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                value = stmt.value
                if not isinstance(value, ast.Call):
                    continue
                member = self._threading_member(value, aliases)
                if member not in _LOCK_CTORS:
                    continue
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                tchain = attr_chain(targets[0]) if targets else None
                lid = None
                if tchain and len(tchain) == 1 and cls is None:
                    lid = lock_id(module, None, tchain[0])
                elif (tchain and len(tchain) == 2 and tchain[0] == "self"
                      and cls is not None):
                    lid = lock_id(module, cls, tchain[1])
                consumed.add(id(value))
                if lid is None:
                    yield self.finding(
                        ctx, value,
                        f"threading.{member}() assigned to an expression "
                        "that is not a module-level name or self "
                        "attribute — it cannot carry a registry id")
                elif not is_registered(lid):
                    yield self.finding(
                        ctx, value,
                        f"lock '{lid}' is not registered in "
                        "disco_tpu/analysis/race/registries.py — register "
                        "it with a one-line statement of what it guards")

        yield from walk(ctx.tree.body, None)
