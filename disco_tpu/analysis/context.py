"""Per-file analysis context: parsed AST plus the shared helpers rules need.

No reference counterpart: the reference repo has no static analysis.  The
helpers here are the whole vocabulary of the rule set — attribute-chain
resolution, loop-depth-aware call iteration, module-level import listing —
kept in one place so every rule reads the tree the same way.
"""
from __future__ import annotations

import ast
from pathlib import Path, PurePosixPath


class FileContext:
    """Everything a rule may ask about one source file.

    ``rel`` is the repo-relative POSIX path ("disco_tpu/enhance/driver.py")
    — rules scope themselves by it, and tests inject synthetic ones via
    :func:`disco_tpu.analysis.runner.lint_source`.  ``root`` is the repo
    root (where ``disco_tpu/`` lives), used by rules that consult the
    in-repo registries (obs event kinds, chaos seams).
    """

    def __init__(self, rel: str, source: str, root: Path):
        self.rel = str(PurePosixPath(rel))
        self.source = source
        self.root = Path(root)
        self.tree = ast.parse(source)

    # -- path predicates ----------------------------------------------------
    def in_dir(self, *dirs: str) -> bool:
        """True when the file lives under any of the given repo-relative
        directories (e.g. ``in_dir("disco_tpu/enhance", "disco_tpu/nn")``)."""
        return any(self.rel == d or self.rel.startswith(d.rstrip("/") + "/") for d in dirs)

    def is_file(self, *rels: str) -> bool:
        """Exact repo-relative path match."""
        return self.rel in rels

    # -- AST helpers --------------------------------------------------------
    def module_docstring(self) -> str:
        return ast.get_docstring(self.tree) or ""

    def module_level_imports(self):
        """Yield the Import/ImportFrom nodes executed at module import time
        (direct module body plus ``if``/``try`` blocks at top level — the
        compat-guard idiom — but NOT function/class bodies)."""
        def _walk(body):
            for node in body:
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    yield node
                elif isinstance(node, (ast.If, ast.Try)):
                    for block in _blocks(node):
                        yield from _walk(block)

        yield from _walk(self.tree.body)

    def calls_with_loop_depth(self):
        """Yield ``(Call, depth)`` for every call, where ``depth`` counts the
        enclosing per-iteration scopes (for/while bodies, comprehension
        elements).  A ``for`` statement's iterable — and a comprehension's
        FIRST generator iterable — runs once and is NOT in-loop; a
        ``while`` test re-runs every iteration and is."""
        yield from _calls(self.tree, 0)


def _blocks(node):
    if isinstance(node, ast.If):
        return [node.body, node.orelse]
    if isinstance(node, ast.Try):
        out = [node.body, node.orelse, node.finalbody]
        out.extend(h.body for h in node.handlers)
        return out
    return []


def _calls(node, depth):
    if isinstance(node, ast.Call):
        yield node, depth
    if isinstance(node, (ast.For, ast.AsyncFor)):
        yield from _calls_children((node.target, node.iter), depth)
        for child in (*node.body, *node.orelse):
            yield from _calls(child, depth + 1)
        return
    if isinstance(node, ast.While):
        # the test expression re-evaluates each iteration: in-loop
        for child in (node.test, *node.body, *node.orelse):
            yield from _calls(child, depth + 1)
        return
    if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
        # the FIRST generator's iterable evaluates once (same as a for
        # statement's); the element expr, conditions and inner generators
        # run per iteration
        first = node.generators[0]
        yield from _calls(first.iter, depth)
        for sub in (first.target, *first.ifs):
            yield from _calls(sub, depth + 1)
        for gen in node.generators[1:]:
            for sub in (gen.target, gen.iter, *gen.ifs):
                yield from _calls(sub, depth + 1)
        elts = (node.key, node.value) if isinstance(node, ast.DictComp) else (node.elt,)
        for sub in elts:
            yield from _calls(sub, depth + 1)
        return
    for child in ast.iter_child_nodes(node):
        yield from _calls(child, depth)


def _calls_children(nodes, depth):
    for n in nodes:
        yield from _calls(n, depth)


def attr_chain(node) -> tuple | None:
    """``jax.tree_util.tree_map`` -> ("jax", "tree_util", "tree_map");
    a bare name -> ("name",); anything rooted in a non-Name expression
    (calls, subscripts) -> None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def callee_name(call: ast.Call) -> str | None:
    """The final name of the called expression ("tick" for both ``tick(..)``
    and ``chaos.tick(..)``), or None for computed callees."""
    chain = attr_chain(call.func)
    return chain[-1] if chain else None


def str_literal(node) -> str | None:
    """The value of a string-literal expression node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def import_names(node) -> list:
    """The imported module names of an Import/ImportFrom ("jax.numpy" for
    ``import jax.numpy``; "jax" for ``from jax import x``; relative imports
    yield their (possibly empty) module text)."""
    if isinstance(node, ast.Import):
        return [alias.name for alias in node.names]
    if isinstance(node, ast.ImportFrom):
        return [node.module or ""]
    return []


def imports_module(node, *roots: str) -> bool:
    """True when an Import/ImportFrom pulls in any of the ``roots`` packages
    (exact name or a submodule of it)."""
    for name in import_names(node):
        for root in roots:
            if name == root or name.startswith(root + "."):
                return True
    return False
