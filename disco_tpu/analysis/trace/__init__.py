"""disco-trace — jaxpr-level program contracts (the eighth CI gate).

The repo's worst bug class lives below the AST: "same value, different
program" retraces (PR 5's traced-float convention, PR 6's msgpack
``mu=1``), the rolled-scan FMA drift that broke bit-exactness, and
donation that jax can silently drop.  ``disco-lint`` cannot see any of it
— those are properties of the *traced jaxpr and lowered executable*, not
the source text.  This package makes them mechanical:

* **golden fingerprints** (:mod:`.fingerprint`, :mod:`.programs`): the
  canonical hot-path programs traced on declared abstract inputs, reduced
  to a stable structural fingerprint (primitive multiset + sequence hash,
  avals, scan ``unroll`` parameters, host-callback presence, dtype
  hygiene) and diffed against goldens committed under
  ``disco_tpu/analysis/golden/`` — an unexplained diff fails CI with a
  primitive-level report; ``disco-trace --update`` regenerates after an
  intended change,
* **retrace budgets** (:mod:`.budgets`): a miniature workload with cold
  caches, every ``counted_jit`` label held to an exact per-label program
  count — the next ``mu=1``-shaped trap fails here whatever its source
  shape,
* **donation + dtype audits** (:mod:`.audits`): declared
  ``donate_argnums``/``donate_argnames`` verified to survive into the
  lowered module's input-output aliasing, float64 leaks and weak-type
  ``convert_element_type`` churn rejected inside jitted hot paths,
* the gate itself (:mod:`.check`, ``make trace-check``) and the
  ``disco-trace`` CLI (:mod:`.cli`, JSON reporter mirroring
  ``disco-lint``'s contract).

No reference counterpart: the reference repo has no traced programs.
"""
from disco_tpu.analysis.trace.check import (  # noqa: F401
    TraceResult,
    run_checks,
)
from disco_tpu.analysis.trace.fingerprint import (  # noqa: F401
    diff_fingerprints,
    fingerprint_fn,
    fingerprint_jaxpr,
)
