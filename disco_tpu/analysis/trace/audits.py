"""Donation and dtype audits over the lowered hot-path programs.

**Donation**: ``donate_argnums``/``donate_argnames`` are metadata — jax can
silently drop them (shape-mismatched outputs, backends without aliasing
support) and the program still runs, just with double the HBM footprint the
donation was supposed to save.  The audit lowers each program WITH its
declared donation and counts what survived into the StableHLO module:
``tf.aliasing_output`` (donated input aliased to an output buffer — the
donation is real) vs ``jax.buffer_donor`` (donated, left for XLA to maybe
use).  A spec with ``must_alias`` hard-fails when fewer than
``min_aliased`` donated leaves alias; otherwise the result is report-only
(the per-backend report the gate prints).

**Dtype**: the pipeline is complex64/float32 end to end, pinned against
float64 NumPy oracles host-side only (CLAUDE.md conventions).  A float64 or
complex128 aval inside a jitted hot path means an accidental x64 promotion
(2x memory, different numerics than validated); a dtype-preserving
``convert_element_type`` is weak-type churn — each one marks a spot where
a passed-vs-folded constant changes the traced program (the PR-5
convention).  Both are extracted by the fingerprint walk; this module
turns them into gate verdicts.

No reference counterpart: the reference has no jit, no donation and a
float64-everywhere numpy pipeline.
"""
from __future__ import annotations

import re
import warnings

#: weak-type-churn ceiling per program: the count is recorded in the golden
#: (so ANY drift fails the fingerprint diff); this absolute bound
#: additionally fails a --update that tries to commit a churn explosion
CONVERT_CHURN_MAX = 60


def donated_lowering(spec):
    """Lower ``spec``'s program with its declared donation; return
    ``(stablehlo_text, args)``.  The jit is built here (not taken from the
    production module) because the production call sites enable donation
    off-CPU only — the audit checks the *declared* contract on the current
    backend.  ``args`` are returned so the caller can count the declared
    leaves without a second ``spec.build()``.

    No reference counterpart (module docstring).
    """
    import jax

    fn, args, kwargs = spec.build()
    don = dict(spec.donate or {})
    jit_kw = {}
    if "argnums" in don:
        jit_kw["donate_argnums"] = tuple(don["argnums"])
    if "argnames" in don:
        # donate_argnames needs named parameters: bind the args by position
        # is fine — jax resolves names against the signature
        jit_kw["donate_argnames"] = tuple(don["argnames"])
    with warnings.catch_warnings():
        # "Some donated buffers were not usable" is exactly what the audit
        # quantifies — keep it out of the gate's stdout
        warnings.simplefilter("ignore")
        lowered = jax.jit(
            lambda *a: fn(*a, **kwargs), **_positional(jit_kw, fn, args)
        ).lower(*args)
    return lowered.as_text(), args


def _positional(jit_kw: dict, fn, args):
    """``donate_argnames`` against a ``lambda *a`` wrapper cannot resolve —
    rewrite it to the positional index of the named parameter in ``fn``'s
    signature (the wrapper passes everything positionally).

    No reference counterpart (module docstring).
    """
    if "donate_argnames" not in jit_kw:
        return jit_kw
    import inspect

    params = list(inspect.signature(fn).parameters)
    unresolved = [name for name in jit_kw["donate_argnames"]
                  if name not in params or params.index(name) >= len(args)]
    if unresolved:
        # a declared name that does not resolve must FAIL the audit, not
        # silently lower an undonated program and report it green
        raise ValueError(
            f"donate_argnames {unresolved} do not resolve against the "
            f"program's positional signature {params[:len(args)]} — fix the "
            "ProgramSpec donation declaration"
        )
    nums = tuple(params.index(name) for name in jit_kw["donate_argnames"])
    out = dict(jit_kw)
    del out["donate_argnames"]
    out["donate_argnums"] = tuple(out.get("donate_argnums", ())) + nums
    return out


def audit_donation(spec) -> dict:
    """One program's donation verdict: ``{declared, aliased, donor_only,
    ok, note}``.

    No reference counterpart (module docstring).
    """
    import jax

    don = spec.donate or {}
    text, args = donated_lowering(spec)
    aliased = len(re.findall(r"tf\.aliasing_output", text))
    donor_only = len(re.findall(r"jax\.buffer_donor", text))
    declared = _declared_leaves(don, args)
    ok = (not don.get("must_alias")) or aliased >= int(don.get("min_aliased", 1))
    return {
        "program": spec.name,
        "backend": jax.default_backend(),
        "declared_leaves": declared,
        "aliased": aliased,
        "donor_only": donor_only,
        "ok": ok,
        "must_alias": bool(don.get("must_alias")),
        "min_aliased": int(don.get("min_aliased", 1)),
        "note": don.get("note", ""),
    }


def _declared_leaves(don: dict, args) -> int:
    import jax

    n = 0
    for i in don.get("argnums", ()):
        n += len(jax.tree_util.tree_leaves(args[i]))
    if don.get("argnames"):
        # by construction the named args are the trailing entries of the
        # spec's positional args (see ProgramSpec.build contracts)
        n += len(jax.tree_util.tree_leaves(args[-len(don["argnames"]):]))
    return n


def audit_dtypes(fp: dict) -> list:
    """Gate findings from one fingerprint's dtype fields (empty = clean).

    No reference counterpart (module docstring).
    """
    out = []
    if fp.get("f64"):
        out.append(
            "float64/complex128 leak inside a jitted hot path: "
            + "; ".join(fp["f64"][:5])
            + (" ..." if len(fp["f64"]) > 5 else "")
        )
    churn = int(fp.get("convert_churn", 0))
    if churn > CONVERT_CHURN_MAX:
        out.append(
            f"{churn} dtype-preserving convert_element_type equations "
            f"(> {CONVERT_CHURN_MAX}): weak-type churn exploded — check the "
            "traced-float calling convention (streaming.DEFAULT_LAMBDA_COR)"
        )
    return out
