"""The canonical hot-path program catalog the trace gate fingerprints.

One :class:`ProgramSpec` per program the repo actually ships: the offline
two-step TANGO units (``tango_step1``/``tango_step2``), the streaming
per-block body via its public ``streaming_tango`` entry (warm-start AND
continuation-state variants — the continuation program is what the serve
scheduler dispatches every tick), the scanned super-tick driver
(``streaming_tango_scan``), and the corpus driver's per-chunk batch
programs (``run_batch``/``run_batch_with_masks``, built through the SAME
:func:`disco_tpu.enhance.driver.make_batch_runners` factory the driver
uses).  Declared ``ShapeDtypeStruct`` inputs keep tracing abstract: no
FLOP runs, no device buffer is allocated, no chip claim is needed beyond
the jax import itself (the check forces the CPU backend first —
:mod:`disco_tpu.analysis.trace.check`).

The shapes are deliberately tiny (they only need to be *structurally*
representative: K nodes exchanging z, refresh-aligned blocks, a batch
axis); the fingerprint records primitives and parameters, not work sizes.
Statics are pinned (``solver="power"``, ``cov_impl="xla"``) so the traced
program is identical on every backend — ``cov_impl="auto"`` resolves per
backend and would make the golden depend on where it was generated.

No reference counterpart: the reference repo has no traced programs.
"""
from __future__ import annotations

import dataclasses

# -- canonical abstract shapes (structural, not workload-sized) -------------
K = 2          #: nodes
C = 2          #: mics per node
F = 5          #: frequency bins
T = 8          #: frames per block (a multiple of UPDATE_EVERY)
B = 2          #: clip batch of the corpus runners
UPDATE_EVERY = 4
BLOCKS_PER_DISPATCH = 2  #: super-tick width of the scanned program

#: statics pinned backend-independent (module docstring)
SOLVER = "power"
COV_IMPL = "xla"

#: time-domain lengths of the chained-clip programs (disco-chain round).
#: The STFT grid is fixed (n_fft 512 → F = 257, hop 256), so the chained
#: programs cannot use the tiny canonical F — only their clip lengths are
#: shrunk: CLIP_L gives 1 + 1024//256 = 5 frames, WINDOW_L gives the
#: 8 frames of one BLOCKS_PER_DISPATCH × UPDATE_EVERY super-tick window.
CLIP_L = 1024
WINDOW_L = (BLOCKS_PER_DISPATCH * UPDATE_EVERY - 1) * 256
STFT_F = 257


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One fingerprinted program: a ``build()`` returning ``(fn, args,
    kwargs)`` with ``args`` a tuple of ``ShapeDtypeStruct`` pytrees (traced
    positionally) and ``kwargs`` the pinned statics, plus the declared
    donation contract for the audit pass.

    ``donate``: ``None`` or a dict with ``argnums``/``argnames`` (the
    declaration the production call site uses off-CPU), ``min_aliased``
    (how many donated leaves must survive to input-output aliasing in the
    lowered module) and ``must_alias`` (hard-fail when aliasing is absent
    vs. report-only on backends known to drop it).
    """

    name: str
    summary: str
    build: callable
    donate: dict | None = None


def _c64(*shape):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.complex64)


def _f32(*shape):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _state_structs():
    """The streaming continuation carry as a ShapeDtypeStruct pytree —
    exactly ``initial_stream_state``'s structure (the serve session carry).

    No reference counterpart (module docstring)."""
    import jax

    from disco_tpu.enhance.streaming import initial_stream_state

    state = initial_stream_state(K, C, F, update_every=UPDATE_EVERY)
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )


def _build_tango_step1():
    from disco_tpu.enhance.tango import tango_step1

    args = (_c64(C, F, T), _c64(C, F, T), _c64(C, F, T), _f32(F, T))
    return tango_step1, args, {"solver": SOLVER, "cov_impl": COV_IMPL}


def _build_tango_step2():
    import jax
    import jax.numpy as jnp

    from disco_tpu.enhance.tango import tango_step2

    all_z = {key: _c64(K, F, T)
             for key in ("z_y", "z_s", "z_n", "zn", "z_t1_s", "z_t1_n")}
    args = (
        _c64(C, F, T), _c64(C, F, T), _c64(C, F, T), _f32(F, T),
        jax.ShapeDtypeStruct((), jnp.int32),          # traced node index k
        all_z, _f32(K, F, T), _c64(K, F, T), _c64(K, F, T),
    )
    return tango_step2, args, {
        "policy": "local", "solver": SOLVER, "cov_impl": COV_IMPL,
    }


def _build_tango_step2_fused():
    """The solve-fusion round's step-2 chain: same unit as
    :func:`_build_tango_step2` with the fused rank-1 GEVD-MWF solver —
    pinned to the 'fused-xla' lane so the golden is backend-independent
    (plain 'fused' resolves per backend, and the pallas lane's interpret
    flag differs off-TPU).  The contract the golden holds (beyond the
    fingerprint): the whole chain is ONE traced program whose outputs are
    (F, T) filtered streams only — no (F, D, D) pencil-shaped intermediate
    escapes to the output avals (pinned by tests/test_trace.py).

    No reference counterpart (module docstring)."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.enhance.tango import tango_step2

    all_z = {key: _c64(K, F, T)
             for key in ("z_y", "z_s", "z_n", "zn", "z_t1_s", "z_t1_n")}
    args = (
        _c64(C, F, T), _c64(C, F, T), _c64(C, F, T), _f32(F, T),
        jax.ShapeDtypeStruct((), jnp.int32),          # traced node index k
        all_z, _f32(K, F, T), _c64(K, F, T), _c64(K, F, T),
    )
    return tango_step2, args, {
        "policy": "local", "solver": "fused-xla", "cov_impl": COV_IMPL,
    }


def _build_tango_step2_eigh():
    """The separate-stage eigh baseline of the step-2 chain: identical
    unit to :func:`_build_tango_step2_fused` with the classic
    materialize-pencils-then-eigh solver.  It exists for the meter gate's
    cross-program budget (analysis/meter/budgets.py): the fused solve's
    modeled HBM traffic must stay strictly below THIS program's — the
    solve-fusion round's thesis as a hard assertion.

    No reference counterpart (module docstring)."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.enhance.tango import tango_step2

    all_z = {key: _c64(K, F, T)
             for key in ("z_y", "z_s", "z_n", "zn", "z_t1_s", "z_t1_n")}
    args = (
        _c64(C, F, T), _c64(C, F, T), _c64(C, F, T), _f32(F, T),
        jax.ShapeDtypeStruct((), jnp.int32),          # traced node index k
        all_z, _f32(K, F, T), _c64(K, F, T), _c64(K, F, T),
    )
    return tango_step2, args, {
        "policy": "local", "solver": "eigh", "cov_impl": COV_IMPL,
    }


def _build_tango_step1_fused():
    """The disco-chain round's step-1: ALL K×F local-MWF pencils as ONE
    batch-in-lanes fused solve through ``compute_z_signals``'s solver spec
    ('fused-xla' pinned backend-independent, like the step-2 twin).  The
    contract the golden holds: one traced program over the whole K-node
    stack — K is a batch lane of the solve, not a vmap of K per-node
    programs — whose outputs are the (K, F, T) z streams only.

    No reference counterpart (module docstring)."""
    from disco_tpu.enhance.zexport import compute_z_signals

    def step1_all(Y, S, N, m):
        return compute_z_signals(None, None, None, Y=Y, S=S, N=N,
                                 masks_z=m, solver="fused-xla",
                                 cov_impl=COV_IMPL)

    args = (_c64(K, C, F, T), _c64(K, C, F, T), _c64(K, C, F, T),
            _f32(K, F, T))
    return step1_all, args, {}


def _build_tango_step1_eigh():
    """The K-vmapped separate-stage eigh baseline of the step-1 chain —
    the meter cross-budget's larger side: the fused step-1 must model
    strictly fewer HBM bytes than THIS program (analysis/meter/budgets.py).

    No reference counterpart (module docstring)."""
    from disco_tpu.enhance.zexport import compute_z_signals

    def step1_all(Y, S, N, m):
        return compute_z_signals(None, None, None, Y=Y, S=S, N=N,
                                 masks_z=m, solver="eigh",
                                 cov_impl=COV_IMPL)

    args = (_c64(K, C, F, T), _c64(K, C, F, T), _c64(K, C, F, T),
            _f32(K, F, T))
    return step1_all, args, {}


def _build_tango_clip_fused():
    """The whole-clip chained program (enhance/fused.py): time-domain
    (K, C, L) in, the enhanced (K, L) signal out, every former stage seam
    (STFT → masks → step-1 → z-exchange → step-2 → ISTFT) inside ONE
    trace.  The contract the golden holds (pinned by tests/test_trace.py):
    no (·, 257, ·) spectrogram-shaped intermediate escapes to the output
    avals.  Statics pinned backend-independent ('fused-xla'/'xla').

    No reference counterpart (module docstring)."""
    from disco_tpu.enhance.fused import tango_clip_fused

    args = (_f32(K, C, CLIP_L), _f32(K, C, CLIP_L), _f32(K, C, CLIP_L))
    return tango_clip_fused.__wrapped__, args, {
        "solver": "fused-xla", "cov_impl": COV_IMPL, "stft_impl": "xla",
    }


def _build_streaming_clip_fused():
    """The streaming chained super-tick (enhance/fused.py): one window's
    time-domain samples + its (K, F, T) masks in, the enhanced window and
    the continuation state out — the program the serve scheduler's
    time-domain sessions dispatch.  Masks ride as program inputs (the
    serve wire contract is client masks); statics pinned
    backend-independent.

    No reference counterpart (module docstring)."""
    from disco_tpu.enhance.fused import streaming_clip_fused

    t = BLOCKS_PER_DISPATCH * UPDATE_EVERY

    def fn(y, mz, mw):
        return streaming_clip_fused.__wrapped__(
            y, None, None, mz, mw, update_every=UPDATE_EVERY,
            blocks_per_dispatch=BLOCKS_PER_DISPATCH, solver="fused-xla",
            stft_impl="xla")

    args = (_f32(K, C, WINDOW_L), _f32(K, STFT_F, t), _f32(K, STFT_F, t))
    return fn, args, {}


def _streaming_args():
    return (_c64(K, C, F, T), _f32(K, F, T), _f32(K, F, T))


def _build_streaming_tango():
    from disco_tpu.enhance import streaming

    return (streaming.streaming_tango.__wrapped__, _streaming_args(),
            {"update_every": UPDATE_EVERY, "solver": "eigh"})


def _build_streaming_tango_state():
    from disco_tpu.enhance import streaming

    def with_state(Y, mz, mw, state):
        return streaming.streaming_tango.__wrapped__(
            Y, mz, mw, update_every=UPDATE_EVERY, solver="eigh", state=state
        )

    return with_state, (*_streaming_args(), _state_structs()), {}


def _build_streaming_tango_scan():
    from disco_tpu.enhance import streaming

    n = BLOCKS_PER_DISPATCH
    args = (_c64(K, C, F, n * T), _f32(K, F, n * T), _f32(K, F, n * T))
    return (streaming.streaming_tango_scan.__wrapped__, args,
            {"update_every": UPDATE_EVERY, "solver": "eigh",
             "blocks_per_dispatch": n})


def _batch_args(with_masks: bool):
    stack = (_c64(B, K, C, F, T),) * 3
    return stack + ((_f32(B, K, F, T), _f32(B, K, F, T)) if with_masks else ())


# -- the batched scenario factory (disco-scenes round) -----------------------
#: tiny scene-batch statics (structural, not workload sized: 2 scenes ×
#: 2 sources × 3 mics, a 512-tap RIR bucket at order 2, 1024-sample dry
#: clips — the full factory shape of batched ISM → convolve → mix → STFT
#: magnitudes → IRM mask in one program)
SCENE_B, SCENE_S, SCENE_M = 2, 2, 3
SCENE_RIR_LEN, SCENE_ORDER, SCENE_L = 512, 2, 1024


def _build_scene_batch():
    from disco_tpu.scenes.batched import _scene_batch_program

    args = (
        _f32(SCENE_B, 3),                    # room_dims
        _f32(SCENE_B, SCENE_S, 3),           # sources
        _f32(SCENE_B, SCENE_M, 3),           # mics
        _f32(SCENE_B),                       # alphas
        _f32(SCENE_B, SCENE_S, SCENE_L),     # dry
        _f32(SCENE_B),                       # noise_gains
    )
    return _scene_batch_program.__wrapped__, args, {
        "max_order": SCENE_ORDER, "rir_len": SCENE_RIR_LEN, "fs": 16000,
    }


# -- the flywheel training step (sharded data-parallel lane) -----------------
#: tiny CRNN the train_step golden is traced on (structural, not workload
#: sized: one conv layer, one GRU, sigmoid FF — the full step shape of
#: value_and_grad + optax apply + batch-stats mutation + dropout split)
TRAIN_WIN = 5
TRAIN_FREQ = 8
TRAIN_BATCH = 4


def _train_model():
    from disco_tpu.nn.crnn import build_crnn

    return build_crnn(
        n_ch=1, win_len=TRAIN_WIN, n_freq=TRAIN_FREQ,
        cnn_filters=(2,), pool_kernels=((1, 2),), conv_padding=((0, 1),),
        rnn_units=(4,), ff_units=(TRAIN_FREQ,), rnn_dropouts=0.0,
    )


def _train_mesh():
    """A 1-device ('batch', 'node') mesh: the golden must fingerprint the
    SAME program under the trace CLI (1 CPU device) and the 8-virtual-
    device test conftest, so the spec always takes exactly one device.

    No reference counterpart (module docstring)."""
    import jax
    import numpy as np

    from disco_tpu.parallel.mesh import make_mesh

    return make_mesh(n_node=1, n_batch=1, devices=np.array(jax.devices()[:1]))


def _build_train_step():
    import jax

    from disco_tpu.nn.training import create_train_state, make_step_fns

    model, tx = _train_model()
    train_step, _eval_step = make_step_fns(model, "all", mesh=_train_mesh())
    # abstract TrainState: eval_shape runs init/opt-init without a single
    # real FLOP, keeping the gate's no-device-work property
    import numpy as np

    sample = np.zeros((1, TRAIN_WIN, TRAIN_FREQ), np.float32)
    state = jax.eval_shape(lambda: create_train_state(model, tx, sample, seed=0))
    args = (state, _f32(TRAIN_BATCH, TRAIN_WIN, TRAIN_FREQ),
            _f32(TRAIN_BATCH, TRAIN_WIN, TRAIN_FREQ))
    return train_step.__wrapped__, args, {}


def _build_run_batch():
    from disco_tpu.enhance.driver import make_batch_runners

    run_batch, _ = make_batch_runners(
        mask_type="irm1", mu=1.0, policy="local", solver=SOLVER,
        cov_impl=COV_IMPL, n_nodes=K,
    )
    return run_batch.__wrapped__, _batch_args(with_masks=False), {}


def _build_run_batch_with_masks():
    from disco_tpu.enhance.driver import make_batch_runners

    _, run_batch_with_masks = make_batch_runners(
        mask_type="irm1", mu=1.0, policy="local", solver=SOLVER,
        cov_impl=COV_IMPL, n_nodes=K,
    )
    return run_batch_with_masks.__wrapped__, _batch_args(with_masks=True), {}


#: name -> ProgramSpec, in documentation order (the golden catalog)
PROGRAMS: dict = {
    spec.name: spec
    for spec in (
        ProgramSpec(
            "tango_step1",
            "offline step-1 local MWF at one node (enhance/tango.py)",
            _build_tango_step1,
        ),
        ProgramSpec(
            "tango_step2",
            "offline step-2 global MWF on [y_k ‖ z_j≠k] (enhance/tango.py)",
            _build_tango_step2,
        ),
        ProgramSpec(
            "tango_step2_fused",
            "offline step-2 with the fused rank-1 GEVD-MWF solve "
            "(ops/mwf_ops.py; 'fused-xla' lane pinned backend-independent) "
            "— one program, no pencil-shaped output escapes",
            _build_tango_step2_fused,
        ),
        ProgramSpec(
            "tango_step2_eigh",
            "offline step-2 with the separate-stage eigh solver — the "
            "fused solve's HBM-traffic baseline (meter cross-budget)",
            _build_tango_step2_eigh,
        ),
        ProgramSpec(
            "tango_step1_fused",
            "step-1 local MWF over ALL K nodes as one batch-in-lanes fused "
            "solve (enhance/zexport.py compute_z_signals, 'fused-xla' "
            "pinned) — the disco-chain step-1 fusion",
            _build_tango_step1_fused,
        ),
        ProgramSpec(
            "tango_step1_eigh",
            "step-1 local MWF, K-vmapped separate-stage eigh — the fused "
            "step-1's HBM-traffic baseline (meter cross-budget)",
            _build_tango_step1_eigh,
        ),
        ProgramSpec(
            "tango_clip_fused",
            "whole offline clip as ONE program: STFT → masks → both MWF "
            "steps → ISTFT (enhance/fused.py) — no spectrogram-shaped "
            "output escapes",
            _build_tango_clip_fused,
        ),
        ProgramSpec(
            "streaming_clip_fused",
            "streaming chained super-tick: time-domain window + masks in, "
            "enhanced window + continuation state out (enhance/fused.py) — "
            "the serve time-domain session program",
            _build_streaming_clip_fused,
        ),
        ProgramSpec(
            "streaming_tango",
            "per-block streaming body, warm start (enhance/streaming.py)",
            _build_streaming_tango,
        ),
        ProgramSpec(
            "streaming_tango_state",
            "per-block streaming body with continuation state — the program "
            "the serve scheduler dispatches every tick",
            _build_streaming_tango_state,
            donate={
                "argnames": ("state",),
                # the 6 step1/step2 covariance+filter leaves alias in place;
                # the 4 fault-hold leaves are dead without z_avail and
                # legitimately cannot alias
                "min_aliased": 6,
                "must_alias": True,
                "note": "serve _resolve_step donates the session carry "
                        "off-CPU (scheduler.py); aliasing holds on CPU too",
            },
        ),
        ProgramSpec(
            "streaming_tango_scan",
            f"scanned super-tick driver, N={BLOCKS_PER_DISPATCH} "
            "(enhance/streaming.py) — the unroll=N contract",
            _build_streaming_tango_scan,
        ),
        ProgramSpec(
            "scene_batch",
            "batched scenario factory: B scenes' ISM RIRs → dry→wet FFT "
            "convolve → SNR mix → reference-mic STFT magnitudes + IRM mask "
            "as ONE program (scenes/batched.py) — one dispatch per batch",
            _build_scene_batch,
        ),
        ProgramSpec(
            "train_step",
            "flywheel data-parallel CRNN train step on a 1-device mesh "
            "(nn/training.make_step_fns: batch sharded P('batch'), "
            "replicated params, donated TrainState)",
            _build_train_step,
            donate={
                "argnames": ("state",),
                # the sharded lane donates the whole TrainState carry; on
                # CPU XLA aliases the optimizer/params buffers it can —
                # require at least the bulk of the float leaves to alias
                "min_aliased": 4,
                "must_alias": True,
                "note": "make_step_fns donates the TrainState on the mesh "
                        "lane (fit always rebinds)",
            },
        ),
        ProgramSpec(
            "run_batch",
            "corpus per-chunk batch program, oracle masks (enhance/driver.py "
            "make_batch_runners)",
            _build_run_batch,
            donate={
                "argnums": (0, 1, 2),
                # the (Yb, Sb, Nb) stacks donate whole buffers; XLA aliases
                # what it can and keeps the rest as donor hints — presence
                # is report-only (CPU and some backends drop donation)
                "min_aliased": 0,
                "must_alias": False,
                "note": "driver donates the STFT stacks off-CPU "
                        "(make_batch_runners)",
            },
        ),
        ProgramSpec(
            "run_batch_with_masks",
            "corpus per-chunk batch program, masks passed in "
            "(enhance/driver.py make_batch_runners)",
            _build_run_batch_with_masks,
            donate={
                "argnums": (0, 1, 2),
                "min_aliased": 0,
                "must_alias": False,
                "note": "driver donates the STFT stacks off-CPU "
                        "(make_batch_runners)",
            },
        ),
    )
}
