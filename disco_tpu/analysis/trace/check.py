"""The trace-contract gate: fingerprints vs goldens + budgets + audits.

``make trace-check`` runs :func:`main` (the eighth hermetic gate, right
after ``lint-check``): every canonical hot-path program
(:data:`~disco_tpu.analysis.trace.programs.PROGRAMS`) is traced on declared
abstract inputs and its structural fingerprint diffed against the golden
committed under ``disco_tpu/analysis/golden/``; the retrace-budget workload
runs with cold caches and every ``counted_jit`` label is held to its
declared budget; donation and dtype audits run over the same programs; and
the serve scheduler's CPU step is asserted to BE the offline entry point
(``_resolve_step`` identity — "the program I ship is the program I
validated", made mechanical).

Hermetic by construction: the checker forces the CPU backend before any
device use (:func:`ensure_cpu` — the conftest trick), so it never touches
the tunneled chip claim, needs no network, and runs in one JAX process
like every other gate (environment contract).

No reference counterpart: the reference repo has no traced programs and no
CI gates.
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path

#: where the golden fingerprints live (committed, one JSON per program)
GOLDEN_DIR = Path(__file__).resolve().parents[1] / "golden"


def ensure_cpu() -> None:
    """Force the CPU backend (the conftest path) or refuse to run.

    Every python process claims the tunneled chip at first jax use and
    blocks while another holds it (CLAUDE.md) — a contract checker must
    never be the process that does that.

    No reference counterpart (module docstring).
    """
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:  # backend already initialised: verify, don't fight
        pass
    if jax.default_backend() != "cpu":
        raise SystemExit(
            f"disco-trace: refusing to run on backend "
            f"{jax.default_backend()!r} — the gate is CPU-only by contract "
            "(run via `make trace-check`, which forces JAX_PLATFORMS=cpu)"
        )


@dataclasses.dataclass
class TraceResult:
    """Everything one gate run produced (the JSON reporter's payload).

    ``findings`` are gate-failing: dicts with ``program`` (or ``-`` for
    process-wide checks), ``check`` (``fingerprint``/``budget``/
    ``donation``/``dtype``/``identity``/``golden``) and ``message`` —
    the same shape contract as ``disco-lint``'s findings list.

    No reference counterpart (module docstring).
    """

    findings: list
    fingerprints: dict
    donation: list
    budgets: dict
    n_programs: int
    updated: list

    @property
    def clean(self) -> bool:
        return not self.findings


def _finding(program: str, check: str, message: str) -> dict:
    return {"program": program, "check": check, "message": message}


def golden_path(name: str) -> Path:
    """The committed golden file of one program.

    No reference counterpart (module docstring)."""
    return GOLDEN_DIR / f"{name}.json"


def load_golden(name: str) -> dict | None:
    """Read one committed golden fingerprint (None when absent).

    No reference counterpart (module docstring)."""
    path = golden_path(name)
    if not path.is_file():
        return None
    return json.loads(path.read_text())


def run_checks(update: bool = False, programs=None, budgets: bool = True,
               budget_extra=None) -> TraceResult:
    """Run the gate.  ``update=True`` regenerates the goldens instead of
    diffing (audits still run: a golden with a dtype leak or a dead
    donation must not be committable).  ``programs`` optionally restricts
    the fingerprint/audit passes; ``budgets=False`` skips the workload
    (the fingerprint-only mode tests use).  ``budget_extra`` is threaded to
    :func:`~disco_tpu.analysis.trace.budgets.run_workload` (test fixtures).

    No reference counterpart (module docstring).
    """
    ensure_cpu()

    from disco_tpu.analysis.trace import audits, fingerprint
    from disco_tpu.analysis.trace.programs import PROGRAMS

    findings: list = []
    fps: dict = {}
    donation: list = []
    updated: list = []

    selected = {
        name: spec for name, spec in PROGRAMS.items()
        if programs is None or name in programs
    }
    for name in (programs or ()):
        if name not in PROGRAMS:
            raise KeyError(f"unknown program {name!r}; known: {sorted(PROGRAMS)}")

    for name, spec in selected.items():
        fn, args, kwargs = spec.build()
        fp = fingerprint.fingerprint_fn(fn, args, kwargs)
        fps[name] = fp
        dtype_msgs = audits.audit_dtypes(fp)
        for msg in dtype_msgs:
            findings.append(_finding(name, "dtype", msg))
        if update:
            if dtype_msgs:
                # a golden with a dtype leak must not be committable: the
                # finding fails the run AND the bad fingerprint never
                # reaches disk, so `git add golden/` cannot smuggle it in
                findings.append(_finding(
                    name, "golden",
                    "refusing to write a golden whose fingerprint fails "
                    "the dtype audit (fix the program, then --update)",
                ))
            else:
                GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
                golden_path(name).write_text(fingerprint.dumps(fp))
                updated.append(name)
        else:
            golden = load_golden(name)
            if golden is None:
                findings.append(_finding(
                    name, "golden",
                    f"no committed golden at {golden_path(name)} — generate "
                    "one with `disco-trace --update` and commit it",
                ))
            else:
                for line in fingerprint.diff_fingerprints(golden, fp):
                    findings.append(_finding(name, "fingerprint", line))
        if spec.donate is not None:
            rep = audits.audit_donation(spec)
            donation.append(rep)
            if not rep["ok"]:
                findings.append(_finding(
                    name, "donation",
                    f"declared donation did not survive lowering: "
                    f"{rep['aliased']} aliased < min {rep['min_aliased']} "
                    f"(of {rep['declared_leaves']} donated leaves, "
                    f"{rep['donor_only']} left as donor hints) on backend "
                    f"{rep['backend']} — {rep['note']}",
                ))

    # ship-what-you-validate: on CPU the serve scheduler's step IS the
    # offline jitted entry point (object identity, not equivalence)
    if programs is None:
        from disco_tpu.enhance import streaming
        from disco_tpu.serve import scheduler

        pairs = (
            (scheduler._serve_step(), streaming.streaming_tango, "serve_step"),
            (scheduler._serve_scan_step(), streaming.streaming_tango_scan,
             "serve_scan_step"),
        )
        for got, want, label in pairs:
            if got is not want:
                findings.append(_finding(
                    label, "identity",
                    "scheduler._resolve_step no longer returns the offline "
                    "jitted entry point on CPU — serve parity is only true "
                    "by construction when the program object is shared "
                    "(scheduler.py)",
                ))

    budget_counts: dict = {}
    if budgets and not update:
        from disco_tpu.analysis.trace import budgets as budgets_mod

        lines, budget_counts = budgets_mod.check_budgets(extra=budget_extra)
        for line in lines:
            findings.append(_finding("-", "budget", line))

    return TraceResult(
        findings=findings, fingerprints=fps, donation=donation,
        budgets=budget_counts, n_programs=len(selected), updated=updated,
    )


def format_text(result: TraceResult) -> str:
    """Human-readable gate report (one line per program + findings).

    No reference counterpart (module docstring)."""
    lines = []
    # DRIFT marks fingerprint/golden problems only — a donation or dtype
    # finding on a program whose fingerprint matched must not steer the
    # reader toward --update
    bad = {f["program"] for f in result.findings
           if f["check"] in ("fingerprint", "golden")}
    for name, fp in result.fingerprints.items():
        status = "DRIFT" if name in bad else "ok"
        scans = ",".join(f"unroll={s['unroll']}" for s in fp["scans"]) or "-"
        lines.append(
            f"fingerprint {name:<24} {status:>5}  "
            f"{fp['n_eqns']:>4} eqns  scans[{scans}]  "
            f"churn={fp['convert_churn']}"
        )
    for rep in result.donation:
        lines.append(
            f"donation    {rep['program']:<24} "
            f"{'ok' if rep['ok'] else 'FAIL':>5}  "
            f"{rep['aliased']}/{rep['declared_leaves']} leaves aliased "
            f"({rep['donor_only']} donor-only) on {rep['backend']}"
        )
    if result.budgets:
        from disco_tpu.analysis.trace.budgets import BUDGETS

        lines.append("budgets: " + "  ".join(
            f"{label}={n}/{BUDGETS[label]}"
            for label, n in sorted(result.budgets.items())
        ))
    if result.updated:
        lines.append("updated goldens: " + ", ".join(result.updated))
    for f in result.findings:
        lines.append(f"FINDING [{f['check']}] {f['program']}: {f['message']}")
    lines.append(
        f"disco-trace: {len(result.findings)} finding(s), "
        f"{result.n_programs} program(s) checked"
    )
    return "\n".join(lines)


def format_json(result: TraceResult) -> str:
    """Machine-readable report — the ``disco-lint --format json`` contract
    shape (``clean``/``counts``/``findings`` top-level keys) extended with
    the per-program payloads.

    No reference counterpart (module docstring)."""
    per_check: dict = {}
    for f in result.findings:
        per_check[f["check"]] = per_check.get(f["check"], 0) + 1
    return json.dumps(
        {
            "clean": result.clean,
            "counts": {
                "findings": len(result.findings),
                "programs": result.n_programs,
                "by_check": per_check,
            },
            "findings": result.findings,
            "fingerprints": result.fingerprints,
            "donation": result.donation,
            "budgets": result.budgets,
            "updated": result.updated,
        },
        indent=2,
    )


def main(argv=None) -> int:
    """``python -m disco_tpu.analysis.trace.check`` — the ``make
    trace-check`` entry: full gate, text report, exit 1 on findings.

    No reference counterpart (module docstring)."""
    result = run_checks()
    print(format_text(result))
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
