"""Retrace budgets: a declarative per-label bound on traced programs.

The trap this gate exists for is PR 6's ``mu=1``: a wire-decoded *int* for
a traced-float keyword traced a third, int-typed program per shape bucket —
same value, different program, compile tax and a parity hazard at every new
call site.  ``disco-lint`` DL007 catches the int *literal* at the call
site; this gate catches the behavior, whatever the source shape: it runs a
miniature representative workload through the jitted entry points with
cold caches and fails when any ``counted_jit`` label traces more programs
than its declared budget (the per-label ``jit_recompiles{label}`` counters,
:func:`disco_tpu.obs.accounting.recompile_count`).

Budgets are EXACT expectations, not loose ceilings: the workload is fixed,
so the trace count per label is deterministic — one more program than
declared means a new retrace seam leaked in (the gate's report names the
label), one fewer means the workload stopped exercising the entry point
and the budget is dead (also a failure: a gate that runs nothing gates
nothing).

Labels not listed here are covered elsewhere: the ``serve_step``/
``serve_scan_step`` labels exist off-CPU only and dispatch the SAME
programs as ``streaming_tango``/``streaming_tango_scan`` (scheduler
``_resolve_step``); ``train_step``/``eval_step`` recompile drift is
reported per epoch by ``nn.training.fit``'s epoch events.

No reference counterpart: the reference repo has no jit and no retraces.
"""
from __future__ import annotations

from disco_tpu.analysis.trace.programs import (
    B,
    BLOCKS_PER_DISPATCH,
    C,
    COV_IMPL,
    F,
    K,
    SOLVER,
    T,
    UPDATE_EVERY,
)

#: label -> exact number of programs the miniature workload traces.
#: streaming_tango: the warm-start program + the continuation-state program
#: (a different carry pytree IS a different program) + exactly ONE bf16-lane
#: program; repeat calls, floats passed equal to the defaults, and the
#: precision token passed equal to (or as a non-canonical spelling of) the
#: 'f32' default must NOT add a fourth — that fourth program is precisely
#: the mu=1 trap, in its float and string forms.  streaming_step1 is driven
#: directly with the warm/continuation variants (inside streaming_tango it
#: runs under the outer trace, where the inner jit compiles nothing and its
#: cache-size counter legitimately stays flat).  The scan driver and the
#: two corpus runners trace once each.
#: train_step / eval_step: one program per LANE — f32 single-device, f32 on
#: the 1-device mesh (sharding constraints are a different program), and
#: the bf16 mixed-precision lane (exactly ONE extra program: the budget is
#: what pins "one lane, one program").  Repeat steps on an evolving
#: TrainState, fresh factory calls with the same key, and precision passed
#: as a non-canonical spelling (' F32 ') must all add NOTHING — the step
#: factory memoizes on the canonicalized key (nn.training.make_step_fns),
#: so a spelling variant reaching jit as a distinct static is impossible
#: by construction.
#: tango_clip_fused: the deployment program ((K, L) out) + exactly ONE
#: export-payload program (export=True is a static) — repeat calls and mu
#: passed equal to the 1.0 default must not add a third.
#: streaming_clip_fused: the warm-start super-tick + the continuation-state
#: program (the carry pytree is a new input structure), like
#: streaming_tango minus its bf16 lane (the chained lane rides the shared
#: precision seam; its bf16 program is not part of this workload).
#: run_batch_chained: the chained corpus runner traces once.
BUDGETS: dict = {
    "streaming_tango": 3,
    "streaming_step1": 2,
    "streaming_tango_scan": 1,
    "run_batch": 1,
    "run_batch_with_masks": 1,
    "run_batch_chained": 1,
    "tango_clip_fused": 2,
    "streaming_clip_fused": 2,
    "train_step": 3,
    "eval_step": 3,
}


def _inputs(rng, t):
    import numpy as np

    Y = (rng.standard_normal((K, C, F, t)) +
         1j * rng.standard_normal((K, C, F, t))).astype(np.complex64)
    mz = rng.uniform(0.1, 0.9, (K, F, t)).astype(np.float32)
    mw = rng.uniform(0.1, 0.9, (K, F, t)).astype(np.float32)
    return Y, mz, mw


def run_workload(extra=None) -> None:
    """The miniature representative workload (cold caches, CPU-sized).

    ``extra``: optional callable run after the canonical calls — the
    deliberate-retrace test fixtures push one more call through a fresh
    call site (e.g. an int-typed ``mu``) and assert the gate fails.

    No reference counterpart (module docstring).
    """
    import numpy as np

    from disco_tpu.enhance import streaming
    from disco_tpu.enhance.driver import make_batch_runners

    for entry in (streaming.streaming_tango, streaming.streaming_step1,
                  streaming.streaming_tango_scan):
        if entry.clear_cache is None:
            # counted_jit resolves clear_cache per jax version; without it
            # a second same-process workload would count 0 fresh programs
            # and misread as "workload no longer exercises the label" —
            # fail self-diagnosing instead
            raise RuntimeError(
                "budget workload needs cold caches but this jax version "
                "exposes no clear_cache on the jitted entry points "
                "(obs.accounting.counted_jit) — update the cache-clearing "
                "seam in budgets.run_workload"
            )
        entry.clear_cache()

    rng = np.random.default_rng(0)
    Y, mz, mw = _inputs(rng, T)

    out = streaming.streaming_tango(Y, mz, mw, update_every=UPDATE_EVERY)
    # cache hit: same shapes
    streaming.streaming_tango(Y, mz, mw, update_every=UPDATE_EVERY)
    # cache hit: floats passed EQUAL to the defaults are stripped by the
    # canonical _float_kw convention — passing them must not retrace
    streaming.streaming_tango(Y, mz, mw, update_every=UPDATE_EVERY,
                              lambda_cor=0.99, mu=1.0)
    # continuation program: the carry pytree is a new input structure
    streaming.streaming_tango(Y, mz, mw, update_every=UPDATE_EVERY,
                              state=out["state"])
    # cache hits: the precision token passed EQUAL to the canonical default
    # — and as a non-canonical spelling of it — must not trace (the host
    # wrapper canonicalizes via ops.resolve.resolve_precision BEFORE the
    # static seam; a spelling variant reaching jit would be the string-typed
    # mu=1 trap)
    streaming.streaming_tango(Y, mz, mw, update_every=UPDATE_EVERY,
                              precision="f32")
    streaming.streaming_tango(Y, mz, mw, update_every=UPDATE_EVERY,
                              precision=" F32 ")
    # the bf16 lane is a REAL second kernel family: exactly one program
    streaming.streaming_tango(Y, mz, mw, update_every=UPDATE_EVERY,
                              precision="bf16")

    # the per-node step-1 entry, warm start + continuation (direct calls:
    # under streaming_tango's trace the inner jit compiles nothing)
    s1 = streaming.streaming_step1(Y[0], mz[0], update_every=UPDATE_EVERY)
    streaming.streaming_step1(Y[0], mz[0], update_every=UPDATE_EVERY)
    streaming.streaming_step1(Y[0], mz[0], update_every=UPDATE_EVERY,
                              state=(s1["Rss"], s1["Rnn"], s1["w"]))

    n = BLOCKS_PER_DISPATCH
    Y2, mz2, mw2 = _inputs(rng, n * T)
    streaming.streaming_tango_scan(Y2, mz2, mw2, update_every=UPDATE_EVERY,
                                   blocks_per_dispatch=n)

    run_batch, run_batch_with_masks = make_batch_runners(
        mask_type="irm1", mu=1.0, policy="local", solver=SOLVER,
        cov_impl=COV_IMPL, n_nodes=K,
    )
    Yb = np.stack([_inputs(rng, T)[0] for _ in range(B)])
    Sb = np.stack([_inputs(rng, T)[0] for _ in range(B)])
    Nb = np.stack([_inputs(rng, T)[0] for _ in range(B)])
    run_batch(Yb, Sb, Nb)
    run_batch(Yb, Sb, Nb)  # cache hit
    Mz = np.stack([_inputs(rng, T)[1] for _ in range(B)])
    run_batch_with_masks(Yb, Sb, Nb, Mz, Mz)

    _chained_workload(rng)

    _train_workload(rng)

    if extra is not None:
        extra(streaming, Y, mz, mw)


def _chained_workload(rng) -> None:
    """The disco-chain programs' share of the budget workload: the
    whole-clip program in its two static shapes (deployment + export),
    the streaming super-tick in warm + continuation form, and the chained
    corpus runner once — with repeat calls and floats passed equal to the
    defaults pinned non-retracing (the mu=1 trap at the chained entry
    points).

    No reference counterpart (module docstring).
    """
    import numpy as np

    from disco_tpu.analysis.trace.programs import CLIP_L, STFT_F, WINDOW_L
    from disco_tpu.enhance import fused
    from disco_tpu.enhance.driver import make_batch_runners

    for entry in (fused.tango_clip_fused, fused.streaming_clip_fused):
        if entry.clear_cache is None:
            raise RuntimeError(
                "budget workload needs cold caches but this jax version "
                "exposes no clear_cache on the chained entry points "
                "(obs.accounting.counted_jit) — update the cache-clearing "
                "seam in budgets._chained_workload"
            )
        entry.clear_cache()

    yt, st, nt = (rng.standard_normal((K, C, CLIP_L)).astype(np.float32)
                  for _ in range(3))
    fused.tango_clip_fused(yt, st, nt, solver="fused-xla", cov_impl=COV_IMPL)
    # cache hits: same shapes; mu passed EQUAL to the 1.0 default is
    # stripped by the traced-float convention
    fused.tango_clip_fused(yt, st, nt, solver="fused-xla", cov_impl=COV_IMPL)
    fused.tango_clip_fused(yt, st, nt, mu=1.0, solver="fused-xla",
                           cov_impl=COV_IMPL)
    # the export-payload program: export is a static — exactly one more
    fused.tango_clip_fused(yt, st, nt, solver="fused-xla", cov_impl=COV_IMPL,
                           export=True)

    t = BLOCKS_PER_DISPATCH * UPDATE_EVERY
    yw = rng.standard_normal((K, C, WINDOW_L)).astype(np.float32)
    mzw = rng.uniform(0.1, 0.9, (K, STFT_F, t)).astype(np.float32)
    out = fused.streaming_clip_fused(
        yw, masks_z=mzw, update_every=UPDATE_EVERY,
        blocks_per_dispatch=BLOCKS_PER_DISPATCH)
    # cache hit, then the continuation program (new carry pytree)
    fused.streaming_clip_fused(
        yw, masks_z=mzw, update_every=UPDATE_EVERY,
        blocks_per_dispatch=BLOCKS_PER_DISPATCH)
    fused.streaming_clip_fused(
        yw, masks_z=mzw, update_every=UPDATE_EVERY,
        blocks_per_dispatch=BLOCKS_PER_DISPATCH, state=out["state"])

    # the chained corpus runner (a fresh counted_jit per factory call —
    # cold by construction, like run_batch above)
    run_batch_chained, _none = make_batch_runners(
        mask_type="irm1", mu=1.0, policy="local", solver="fused-xla",
        cov_impl=COV_IMPL, stft_impl="xla", n_nodes=K, chained=True,
    )
    ytb, stb, ntb = (
        np.stack([rng.standard_normal((K, C, CLIP_L)).astype(np.float32)
                  for _ in range(B)])
        for _ in range(3)
    )
    run_batch_chained(ytb, stb, ntb)
    run_batch_chained(ytb, stb, ntb)  # cache hit


def _train_workload(rng) -> None:
    """The flywheel training lanes' share of the budget workload: exactly
    one program per lane (f32 / 1-device mesh / bf16) for train_step AND
    eval_step, with repeat steps, equal-key factory calls and spelling
    variants pinned non-retracing.

    No reference counterpart (module docstring).
    """
    import numpy as np

    from disco_tpu.analysis.trace.programs import (
        TRAIN_BATCH,
        TRAIN_FREQ,
        TRAIN_WIN,
        _train_mesh,
        _train_model,
    )
    from disco_tpu.nn import training

    # the step-fn factory memoizes across workload runs; clear the compiled
    # caches so a warm process still counts one fresh trace per lane (the
    # budget twin of the streaming entries' clear_cache above)
    training.clear_step_fn_caches()

    model, tx = _train_model()
    x = rng.standard_normal((TRAIN_BATCH, TRAIN_WIN, TRAIN_FREQ)).astype(np.float32)
    y = rng.uniform(0.1, 0.9, x.shape).astype(np.float32)
    state0 = training.create_train_state(model, tx, x[:1], seed=0)

    # lane 1: f32 single-device — repeat steps and an equal-key second
    # factory call trace nothing new
    train_step, eval_step = training.make_step_fns(model, "all")
    s, _ = train_step(state0, x, y)
    s, _ = train_step(s, x, y)
    eval_step(s, x, y)
    eval_step(s, x, y)
    again_t, again_e = training.make_step_fns(model, "all", precision=" F32 ")
    assert again_t is train_step and again_e is eval_step  # memoized key
    again_t(s, x, y)
    again_e(s, x, y)

    # lane 2: the 1-device data-parallel mesh (sharding constraints +
    # donated carry = a different program, once)
    mesh = _train_mesh()
    mt, me = training.make_step_fns(model, "all", mesh=mesh)
    ms = training.replicate_to_mesh(
        training.create_train_state(model, tx, x[:1], seed=0), mesh
    )
    ms, _ = mt(ms, x, y)
    ms, _ = mt(ms, x, y)
    me(ms, x, y)

    # lane 3: bf16 mixed precision — exactly ONE extra program (a bf16
    # batch-stats pytree leaking out of step 1 would make step 2 a second
    # program; the budget pins the f32-accumulator contract behaviorally)
    bt, be = training.make_step_fns(model, "all", precision="bf16")
    bs, _ = bt(state0, x, y)
    bs, _ = bt(bs, x, y)
    be(bs, x, y)


def check_budgets(extra=None) -> tuple:
    """Run the workload and diff the per-label counters against
    :data:`BUDGETS`.  Returns ``(findings, counts)`` — findings empty when
    every label traced exactly its budget.

    No reference counterpart (module docstring).
    """
    from disco_tpu.obs.accounting import recompile_count

    before = {label: recompile_count(label) for label in BUDGETS}
    run_workload(extra=extra)
    counts = {label: recompile_count(label) - before[label] for label in BUDGETS}
    findings = []
    for label, budget in BUDGETS.items():
        n = counts[label]
        if n > budget:
            findings.append(
                f"label {label!r} traced {n} programs, budget {budget}: a "
                "new retrace seam (the mu=1 trap shape — check argument "
                "dtypes and the _float_kw convention at new call sites)"
            )
        elif n < budget:
            findings.append(
                f"label {label!r} traced {n} programs, budget {budget}: the "
                "workload no longer exercises this entry point — a budget "
                "that runs nothing gates nothing (update budgets.py)"
            )
    return findings, counts
