"""Structural fingerprints of traced jaxprs — the identity of a program.

A fingerprint is everything about a traced program that the repo's
contracts care about and nothing that churns for free:

* the **input/output avals** (shape/dtype signature of the traced unit),
* the **primitive multiset** and a **sequence hash** over the depth-first
  walk of every equation (nested ``pjit``/``scan``/``cond`` bodies
  included) — "same value, different program" (the PR-5 traced-float
  convention) shows up here as a different sequence,
* every ``lax.scan``'s ``unroll``/``length`` parameters — the PR-6
  rolled-scan FMA-drift trap is a one-line ``unroll`` diff,
* the presence of ``pure_callback``/``io_callback``/``debug_callback``
  primitives — a hidden host round-trip is a fenced ~80 ms RPC per call on
  the tunnel (CLAUDE.md), so a callback appearing in a hot-path program is
  a performance regression even when the numerics are untouched,
* the **float64/complex128 leaks** (none allowed in the f32 pipeline) and
  the count of no-op ``convert_element_type`` equations (weak-type churn —
  each one is a program-identity hazard at a retrace seam).

Variable names, equation source locations and anything else that differs
between semantically identical traces is deliberately NOT part of the
fingerprint, so goldens survive refactors that do not change the program.

No reference counterpart: the reference repo has no jit and no traced
programs to fingerprint.
"""
from __future__ import annotations

import hashlib
import json

#: bump when the fingerprint schema changes incompatibly — a version
#: mismatch against a golden is reported as "regenerate with --update",
#: not as a program drift
VERSION = 1

#: callback primitives that smuggle a host round-trip into a traced program
CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback")

#: dtypes that must never appear in the f32 pipeline's hot-path programs
_BANNED_DTYPES = ("float64", "complex128")


def _subjaxprs(params: dict):
    """Yield the nested jaxprs of one equation's params (``pjit`` carries a
    ClosedJaxpr under ``jaxpr``; ``scan``/``while``/``cond`` carry
    ClosedJaxprs under ``jaxpr``/``cond_jaxpr``/``body_jaxpr``/
    ``branches``; lists are walked)."""
    for val in params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for sub in vals:
            if hasattr(sub, "jaxpr"):        # ClosedJaxpr
                yield sub.jaxpr
            elif hasattr(sub, "eqns"):       # raw Jaxpr
                yield sub


def _walk(jaxpr, depth, events):
    """Depth-first equation walk: append ``(depth, primitive, params)``."""
    for eqn in jaxpr.eqns:
        events.append((depth, eqn))
        for sub in _subjaxprs(eqn.params):
            _walk(sub, depth + 1, events)


def _aval_str(v) -> str:
    """Stable text form of one variable's aval ('complex64[2,5,8]')."""
    aval = v.aval
    shape = ",".join(str(d) for d in getattr(aval, "shape", ()))
    dtype = getattr(aval, "dtype", None)
    name = str(dtype) if dtype is not None else type(aval).__name__
    weak = "~" if getattr(aval, "weak_type", False) else ""
    return f"{name}{weak}[{shape}]"


def fingerprint_jaxpr(closed_jaxpr) -> dict:
    """Extract the structural fingerprint of one ``ClosedJaxpr``.

    Pure function of the jaxpr object — no tracing, no device, no jax
    import (it only reads attributes), so it is reusable on any jaxpr a
    test already has in hand.

    No reference counterpart (module docstring).
    """
    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
    events: list = []
    _walk(jaxpr, 0, events)

    primitives: dict[str, int] = {}
    scans: list[dict] = []
    callbacks: list[str] = []
    convert_churn = 0
    f64: list[str] = []
    f64_seen: set = set()

    def note_f64(entry: str) -> None:
        if entry not in f64_seen:
            f64_seen.add(entry)
            f64.append(entry)

    # program INPUTS leak too: an f64 invar consumed straight by a
    # convert_element_type never shows in any equation's outputs
    for v in jaxpr.invars:
        if str(getattr(v.aval, "dtype", "")) in _BANNED_DTYPES:
            note_f64(f"invar {_aval_str(v)}")
    seq = hashlib.sha256()
    for depth, eqn in events:
        name = eqn.primitive.name
        primitives[name] = primitives.get(name, 0) + 1
        seq.update(f"{depth}:{name}\n".encode())
        if name == "scan":
            scans.append({
                "depth": depth,
                "unroll": int(eqn.params.get("unroll", 1)),
                "length": int(eqn.params.get("length", 0)),
            })
        if name in CALLBACK_PRIMITIVES:
            callbacks.append(name)
        if name == "convert_element_type":
            in_dt = [getattr(v.aval, "dtype", None) for v in eqn.invars
                     if hasattr(v, "aval")]
            out_dt = [getattr(v.aval, "dtype", None) for v in eqn.outvars]
            if in_dt and out_dt and str(in_dt[0]) == str(out_dt[0]):
                convert_churn += 1  # dtype-preserving: weak-type churn
        for v in eqn.invars:
            # closed-over consts and nested-jaxpr inputs surface here
            if (hasattr(v, "aval")
                    and str(getattr(v.aval, "dtype", "")) in _BANNED_DTYPES):
                note_f64(f"{name} <- {_aval_str(v)}")
        for v in eqn.outvars:
            dt = str(getattr(v.aval, "dtype", ""))
            if dt in _BANNED_DTYPES:
                note_f64(f"{name} -> {_aval_str(v)}")

    return {
        "version": VERSION,
        "in_avals": [_aval_str(v) for v in jaxpr.invars],
        "out_avals": [_aval_str(v) for v in jaxpr.outvars],
        "n_eqns": len(events),
        "primitives": dict(sorted(primitives.items())),
        "sequence_sha256": seq.hexdigest(),
        "scans": scans,
        "callbacks": callbacks,
        "convert_churn": convert_churn,
        "f64": f64,
    }


def fingerprint_fn(fn, args, kwargs=None) -> dict:
    """Trace ``fn`` on abstract inputs (``jax.ShapeDtypeStruct`` pytrees —
    no FLOP runs, no device buffer is touched) and fingerprint the jaxpr.

    No reference counterpart (module docstring).
    """
    import jax

    kwargs = kwargs or {}
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    return fingerprint_jaxpr(closed)


def diff_fingerprints(golden: dict, current: dict) -> list:
    """Readable primitive-level differences, empty when identical.

    The report names WHAT drifted (primitive counts, scan unrolls, avals,
    callbacks, dtype hygiene), so a failing gate points at the change
    instead of just two hashes.

    No reference counterpart (module docstring).
    """
    out: list[str] = []
    if golden.get("version") != current.get("version"):
        return [
            f"fingerprint schema version {golden.get('version')} != "
            f"{current.get('version')}: regenerate goldens with "
            "`disco-trace --update`"
        ]
    for key in ("in_avals", "out_avals"):
        if golden.get(key) != current.get(key):
            out.append(f"{key}: {golden.get(key)} -> {current.get(key)}")
    gp, cp = golden.get("primitives", {}), current.get("primitives", {})
    for prim in sorted(set(gp) | set(cp)):
        a, b = gp.get(prim, 0), cp.get(prim, 0)
        if a != b:
            out.append(f"primitive {prim}: {a} -> {b} ({b - a:+d})")
    if golden.get("scans") != current.get("scans"):
        out.append(f"scans (depth/unroll/length): {golden.get('scans')} -> "
                   f"{current.get('scans')}")
    if golden.get("callbacks") != current.get("callbacks"):
        out.append(
            f"host callbacks: {golden.get('callbacks')} -> "
            f"{current.get('callbacks')} (each is a hidden ~80 ms tunnel RPC)"
        )
    if golden.get("convert_churn") != current.get("convert_churn"):
        out.append(f"dtype-preserving convert_element_type count: "
                   f"{golden.get('convert_churn')} -> {current.get('convert_churn')}"
                   " (weak-type churn)")
    if golden.get("f64") != current.get("f64"):
        out.append(f"float64/complex128 leaks: {golden.get('f64')} -> "
                   f"{current.get('f64')}")
    if (not out and golden.get("sequence_sha256") != current.get("sequence_sha256")):
        out.append(
            "primitive sequence reordered (same multiset, different order): "
            f"{golden.get('sequence_sha256', '')[:12]} -> "
            f"{current.get('sequence_sha256', '')[:12]}"
        )
    if (not out and golden.get("n_eqns") != current.get("n_eqns")):
        out.append(f"n_eqns: {golden.get('n_eqns')} -> {current.get('n_eqns')}")
    return out


def dumps(fp: dict) -> str:
    """Canonical JSON text of a fingerprint (sorted keys, indented — the
    committed golden format, reviewable in a PR diff).

    No reference counterpart (module docstring).
    """
    return json.dumps(fp, indent=2, sort_keys=True) + "\n"
