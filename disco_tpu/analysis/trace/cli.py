"""``disco-trace`` — the program-contract checker's command line.

Exit codes mirror ``disco-lint``: 0 clean, 1 findings, 2 usage error.
Unlike the linter this tool DOES import jax (it traces programs), but it
forces the CPU backend before any device use
(:func:`disco_tpu.analysis.trace.check.ensure_cpu`) so it never claims the
tunneled chip.

``--update`` regenerates the goldens under ``disco_tpu/analysis/golden/``
after an *intended* program change; commit them with a message explaining
WHAT changed in the program and why (doc/source/static_analysis.rst,
"When to run --update").

No reference counterpart: the reference repo has no static analysis.
"""
from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    """The disco-trace argument parser (no reference counterpart)."""
    p = argparse.ArgumentParser(
        prog="disco-trace",
        description=(
            "jaxpr-level program-contract checker: golden fingerprints, "
            "retrace budgets, donation/dtype audits over the canonical "
            "hot-path programs (CPU-only by construction)."
        ),
    )
    p.add_argument("--update", action="store_true",
                   help="regenerate the golden fingerprints instead of "
                        "diffing (audits still run); commit the result")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (json is the machine contract)")
    p.add_argument("--programs", default=None,
                   help="comma-separated program names to check "
                        "(default: all; budgets run only on a full check)")
    p.add_argument("--no-budgets", action="store_true",
                   help="skip the retrace-budget workload (fingerprints and "
                        "audits only)")
    p.add_argument("--list-programs", action="store_true",
                   help="print the program catalog and exit")
    return p


def main(argv=None) -> int:
    """Entry point (console script ``disco-trace`` / ``python -m
    disco_tpu.analysis.trace.cli``).  No reference counterpart."""
    args = build_parser().parse_args(argv)
    from disco_tpu.analysis.trace import check

    if args.list_programs:
        from disco_tpu.analysis.trace.programs import PROGRAMS

        for name, spec in PROGRAMS.items():
            donate = " [donated]" if spec.donate else ""
            print(f"{name:<26} {spec.summary}{donate}")
        return 0

    programs = None
    if args.programs:
        programs = {s.strip() for s in args.programs.split(",") if s.strip()}
    try:
        result = check.run_checks(
            update=args.update,
            programs=programs,
            budgets=not args.no_budgets and programs is None,
        )
    except KeyError as e:
        print(f"disco-trace: {e.args[0]}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(check.format_json(result))
    else:
        print(check.format_text(result))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
