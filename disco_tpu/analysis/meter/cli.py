"""``disco-meter`` — the cost-manifest gate's command line.

Exit codes mirror ``disco-lint``: 0 clean, 1 findings, 2 usage error.
Like ``disco-trace`` this tool imports jax (it traces programs) but
forces the CPU backend before any device use, so it never claims the
tunneled chip.

``--update`` regenerates the cost manifests under
``disco_tpu/analysis/golden/cost/`` after an *intended* cost change;
commit them with a message explaining WHAT moved (flops, HBM traffic,
a fused island) and why (doc/source/observability.rst, "Reading the
roofline").

No reference counterpart: the reference repo has no cost model.
"""
from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    """The disco-meter argument parser (no reference counterpart)."""
    p = argparse.ArgumentParser(
        prog="disco-meter",
        description=(
            "per-program cost observatory: analytic FLOP / HBM-traffic "
            "manifests of the canonical hot-path programs, diffed against "
            "committed goldens with budget and registry-sync enforcement "
            "(CPU-only by construction)."
        ),
    )
    p.add_argument("--update", action="store_true",
                   help="regenerate the cost manifests instead of diffing "
                        "(budgets still run); commit the result")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (json is the machine contract)")
    p.add_argument("--programs", default=None,
                   help="comma-separated program names to meter (default: "
                        "all; registry-sync and cross-budgets run only on "
                        "a full pass)")
    p.add_argument("--list-programs", action="store_true",
                   help="print the program catalog and exit")
    return p


def main(argv=None) -> int:
    """Entry point (console script ``disco-meter`` / ``python -m
    disco_tpu.analysis.meter.cli``).  No reference counterpart."""
    args = build_parser().parse_args(argv)
    from disco_tpu.analysis.meter import check

    if args.list_programs:
        from disco_tpu.analysis.trace.programs import PROGRAMS

        for name, spec in PROGRAMS.items():
            print(f"{name:<26} {spec.summary}")
        return 0

    programs = None
    if args.programs:
        programs = {s.strip() for s in args.programs.split(",") if s.strip()}
    try:
        result = check.run_checks(update=args.update, programs=programs)
    except KeyError as e:
        print(f"disco-meter: {e.args[0]}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(check.format_json(result))
    else:
        print(check.format_text(result))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
