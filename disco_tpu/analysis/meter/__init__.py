"""disco-meter: the per-program cost & roofline observatory.

Static cost accounting for every canonical hot-path program in the
:data:`disco_tpu.analysis.trace.programs.PROGRAMS` catalog: FLOPs, bytes
moved to/from HBM, arithmetic intensity and a peak-live-bytes estimate,
derived from the same forced-CPU abstract tracing the disco-trace gate
already performs — no device work, no chip claim, deterministic on any
host.  The committed cost manifests under ``analysis/golden/cost/`` turn
the fusion arc's central claim ("the fused solve reads the pencils from
HBM once and writes back only the weights") into a hard, regression-gated
assertion, and the roofline join (``disco-obs roofline``) merges these
manifests with measured ``stage_ms`` from any bench record into a
per-stage compute-bound / bandwidth-bound / dispatch-bound verdict.

Modules:

* :mod:`~disco_tpu.analysis.meter.costmodel` — the jaxpr-walking cost
  model (pure function of a traced program; the ``unmodeled`` bucket is
  explicit, never a silent zero).
* :mod:`~disco_tpu.analysis.meter.budgets` — declared per-program
  unmodeled-fraction ceilings and cross-program traffic assertions.
* :mod:`~disco_tpu.analysis.meter.stages` — workload-sized stage programs
  mirroring ``bench.py``'s timed stages, so measured ``stage_ms`` joins a
  cost computed on the SAME program shape.
* :mod:`~disco_tpu.analysis.meter.check` — the ``make meter-check`` gate
  (the fourteenth hermetic gate): manifests diffed against goldens,
  registry sync with the trace catalog, budget enforcement.
* :mod:`~disco_tpu.analysis.meter.cli` — the ``disco-meter`` command line.

No reference counterpart: the reference repo has no cost model and no
performance gates (SURVEY.md §5.1).
"""
