"""Workload-sized stage costs — the roofline join's cost side.

The committed manifests (``analysis/golden/cost/``) are structural: tiny
shapes, good for drift gating, useless for judging a measured
``stage_ms`` against hardware peaks.  This module re-traces the SAME
stage programs ``bench.py`` slope-times — the fused spec+magnitude STFT
over the stacked y/s/n streams, the irm mask stage, step-1, the full
offline tango chain (step-2 reported as full minus step-1, exactly how
``bench.py`` times it), the iSTFT, the fused headline pipeline — at the
*bench workload's* shapes, and costs them with the same jaxpr-walking
model.  Tracing is abstract (``ShapeDtypeStruct`` in, ``jax.eval_shape``
to chain stage output shapes): not one FLOP runs, so calling this inside
a live bench process costs milliseconds and never touches the device.

The streaming-scan and serve lanes get per-window / per-block costs from
the same model (satellite of the meter round: RTF lanes with no flops
had no computable MFU), parameterized on the exact shapes those bench
lanes build.

No reference counterpart: the reference repo has no cost model
(SURVEY.md §5.1).
"""
from __future__ import annotations

import dataclasses

from disco_tpu.analysis.meter import costmodel

#: the stage keys of ``bench.py``'s ``stage_ms`` dict, in pipeline order
#: (``step1_fused_mwf`` and ``chained_clip`` are the disco-chain lanes:
#: the batch-in-lanes fused step-1 twin of ``step1_local_mwf``, and the
#: whole-clip one-program chain)
STAGE_KEYS = ("stft_x3", "masks", "step1_local_mwf", "step1_fused_mwf",
              "step2_exchange_mwf", "istft", "full_pipeline",
              "chained_clip")


@dataclasses.dataclass(frozen=True)
class Workload:
    """One offline bench workload (bench.py's headline defaults: the
    8-node/4-mic north-star config on 10 s clips, batch 16).

    No reference counterpart (module docstring)."""

    batch: int = 16
    dur_s: float = 10.0
    fs: int = 16000
    n_nodes: int = 8
    mics_per_node: int = 4

    @property
    def samples(self) -> int:
        return int(self.dur_s * self.fs)


HEADLINE = Workload()


def _cost(fn, args, program: str) -> dict:
    rep = costmodel.cost_of_fn(fn, args, program=program)
    return {
        "flops": rep["flops"],
        "traffic_bytes": rep["traffic_bytes"],
        "arithmetic_intensity": rep["arithmetic_intensity"],
    }


def _sub(a: dict, b: dict) -> dict:
    """Stage cost as a difference (bench times step-2 as full − step-1).

    No reference counterpart (module docstring)."""
    flops = max(a["flops"] - b["flops"], 0)
    traffic = max(a["traffic_bytes"] - b["traffic_bytes"], 0)
    return {
        "flops": flops,
        "traffic_bytes": traffic,
        "arithmetic_intensity": (
            round(flops / traffic, 6) if traffic else None),
    }


def offline_stage_costs(workload: Workload = HEADLINE,
                        solver: str = "power") -> dict:
    """Cost of each ``stage_ms`` stage at the workload's shapes.

    Mirrors ``bench.py``'s staged jits one for one (bench.py:230-258):
    ``stft_x3`` is the fused spec+magnitude STFT over stacked y/s/n,
    ``step2_exchange_mwf`` is the full-tango cost minus the step-1 cost
    (the same subtraction the timing uses), ``full_pipeline`` is the
    fused headline program.  Returns ``{stage: {flops, traffic_bytes,
    arithmetic_intensity}}`` with all counts covering the WHOLE batch —
    divide by ``workload.batch`` for per-clip figures.

    No reference counterpart (module docstring).
    """
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import istft, stft
    from disco_tpu.core.masks import tf_mask_mag
    from disco_tpu.enhance import compute_z_signals, tango
    from disco_tpu.ops.stft_ops import stft_with_mag

    w = workload
    yb = jax.ShapeDtypeStruct(
        (w.batch, w.n_nodes, w.mics_per_node, w.samples), jnp.float32)

    def f_stft(a, b, c):
        return stft_with_mag(jnp.stack([a, b, c]))

    spec_b, mag_b = jax.eval_shape(f_stft, yb, yb, yb)
    spec1 = jax.ShapeDtypeStruct(spec_b.shape[1:], spec_b.dtype)
    mag1 = jax.ShapeDtypeStruct(mag_b.shape[1:], mag_b.dtype)

    f_mask = jax.vmap(lambda ms, mn: tf_mask_mag(ms[:, 0], mn[:, 0], "irm1"))
    masks_b = jax.eval_shape(f_mask, mag1, mag1)

    f_step1 = jax.vmap(
        lambda Y, S, N, m: compute_z_signals(
            None, None, None, Y=Y, S=S, N=N, masks_z=m)["z_y"])
    # the disco-chain step-1 twin: all K×F pencils as ONE batch-in-lanes
    # fused solve ('fused-xla' pinned — backend resolution of plain
    # 'fused' never changes the modeled structure)
    f_step1_fused = jax.vmap(
        lambda Y, S, N, m: compute_z_signals(
            None, None, None, Y=Y, S=S, N=N, masks_z=m,
            solver="fused-xla")["z_y"])
    f_full = jax.vmap(
        lambda Y, S, N, m: tango(Y, S, N, m, m, policy="local",
                                 solver=solver).yf)
    yf_b = jax.eval_shape(f_full, spec1, spec1, spec1, masks_b)
    f_istft = lambda Z: istft(Z, length=w.samples)   # noqa: E731

    def f_headline(a, b, c):
        def one(y, s, n):
            spec, mag = stft_with_mag(jnp.stack([y, s, n]))
            m = tf_mask_mag(mag[1][:, 0], mag[2][:, 0], "irm1")
            return tango(spec[0], spec[1], spec[2], m, m, policy="local",
                         solver=solver).yf
        return jax.vmap(one)(a, b, c)

    # the whole-clip chained program (enhance/fused.py): the lane bench.py
    # times as rtf_chained_clip / stage_ms.chained_clip
    from disco_tpu.enhance.fused import tango_clip_fused

    f_chained = jax.vmap(
        lambda y, s, n: tango_clip_fused.__wrapped__(y, s, n,
                                                     solver="fused-xla"))

    c_stft = _cost(f_stft, (yb, yb, yb), "stage:stft_x3")
    c_mask = _cost(f_mask, (mag1, mag1), "stage:masks")
    c_step1 = _cost(f_step1, (spec1, spec1, spec1, masks_b), "stage:step1")
    c_step1_fused = _cost(f_step1_fused, (spec1, spec1, spec1, masks_b),
                          "stage:step1_fused")
    c_full = _cost(f_full, (spec1, spec1, spec1, masks_b), "stage:tango_full")
    c_istft = _cost(f_istft, (yf_b,), "stage:istft")
    c_headline = _cost(f_headline, (yb, yb, yb), "stage:full_pipeline")
    c_chained = _cost(f_chained, (yb, yb, yb), "stage:chained_clip")
    return {
        "stft_x3": c_stft,
        "masks": c_mask,
        "step1_local_mwf": c_step1,
        "step1_fused_mwf": c_step1_fused,
        "step2_exchange_mwf": _sub(c_full, c_step1),
        "istft": c_istft,
        "full_pipeline": c_headline,
        "chained_clip": c_chained,
    }


def streaming_scan_cost(dur_s: float = 10.0, fs: int = 16000,
                        n_nodes: int = 4, mics_per_node: int = 4,
                        update_every: int = 4,
                        blocks_per_dispatch: int = 8) -> dict | None:
    """Per-window cost of the scanned super-tick at the bench lane's
    shapes (bench.py:bench_streaming_scan, including its smoke-size block
    shrink); ``None`` when the clip cannot hold the window.  MFU of the
    lane = ``flops / (window wall seconds) / peak``.

    No reference counterpart (module docstring).
    """
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.streaming import (
        initial_stream_state,
        streaming_tango_scan,
    )

    K, C, u = n_nodes, mics_per_node, update_every
    y = jax.ShapeDtypeStruct((K, C, int(dur_s * fs)), jnp.float32)
    Y = jax.eval_shape(stft, y)
    F, T = Y.shape[-2:]
    block = 4 * u
    if T < blocks_per_dispatch * block:
        block = (T // (blocks_per_dispatch * u)) * u
    window = blocks_per_dispatch * block
    if block < u:
        return None
    state = jax.eval_shape(
        lambda: initial_stream_state(K, C, F, update_every=u))
    Yw = jax.ShapeDtypeStruct((K, C, F, window), Y.dtype)
    mw = jax.ShapeDtypeStruct((K, F, window), jnp.float32)
    avail = jax.ShapeDtypeStruct((K, window // u), jnp.float32)

    def run_scan(Yw, mw, st, av):
        return streaming_tango_scan(
            Yw, mw, mw, update_every=u, policy="local", state=st,
            z_avail=av, blocks_per_dispatch=blocks_per_dispatch,
        )["yf"]

    out = _cost(run_scan, (Yw, mw, state, avail), "lane:streaming_scan")
    out.update(window_frames=window, block_frames=block,
               blocks_per_dispatch=blocks_per_dispatch)
    return out


def serve_block_cost(dur_s: float = 4.0, fs: int = 16000,
                     n_nodes: int = 4, mics_per_node: int = 2,
                     update_every: int = 4) -> dict:
    """Per-block cost of the program the serve scheduler dispatches every
    tick (``streaming_tango`` with continuation state) at the serve bench
    lane's session shape (bench.py:bench_serve — Ks=4, Cs=2, u=4,
    block=4·u).  MFU of the lane = ``flops · serve_blocks_per_s / peak``.

    No reference counterpart (module docstring).
    """
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import stft
    from disco_tpu.enhance.streaming import (
        initial_stream_state,
        streaming_tango,
    )

    K, C, u = n_nodes, mics_per_node, update_every
    block = 4 * u
    y = jax.ShapeDtypeStruct((K, C, int(dur_s * fs)), jnp.float32)
    Y = jax.eval_shape(stft, y)
    F = Y.shape[-2]
    state = jax.eval_shape(
        lambda: initial_stream_state(K, C, F, update_every=u))
    Yb = jax.ShapeDtypeStruct((K, C, F, block), Y.dtype)
    mb = jax.ShapeDtypeStruct((K, F, block), jnp.float32)
    avail = jax.ShapeDtypeStruct((K, block // u), jnp.float32)

    def run_block(Yb, mb, st, av):
        return streaming_tango(Yb, mb, mb, update_every=u, policy="local",
                               state=st, z_avail=av)["yf"]

    out = _cost(run_block, (Yb, mb, state, avail), "lane:serve_block")
    out.update(block_frames=block)
    return out


def fused_pipeline_cost(workload: Workload = HEADLINE) -> dict:
    """Whole-batch cost of the headline pipeline on the fused step-2
    solve ('fused-xla' pinned, like the trace golden — the backend
    resolution of plain 'fused' never changes the modeled structure).
    MFU of the bench's ``rtf_fused_solver`` lane = ``flops / dt / peak``.

    No reference counterpart (module docstring).
    """
    return offline_stage_costs(workload, solver="fused-xla")["full_pipeline"]
