"""The meter gate: cost manifests vs goldens + budgets + registry sync.

``make meter-check`` runs :func:`main` (the FOURTEENTH hermetic gate,
right after ``race-check``): every canonical hot-path program
(:data:`~disco_tpu.analysis.trace.programs.PROGRAMS`) is traced on the
same declared abstract inputs the trace gate uses, costed by the
jaxpr-walking model (:mod:`~disco_tpu.analysis.meter.costmodel`), and the
resulting manifest diffed against the golden committed under
``disco_tpu/analysis/golden/cost/``.  On top of the per-program diff:

* **budgets** — unmodeled-traffic ceilings and the cross-program
  fused-vs-eigh HBM inequality (:mod:`~disco_tpu.analysis.meter.budgets`);
  ``--update`` refuses to write a manifest that breaches its own budget,
  so ``git add golden/cost/`` cannot smuggle an unmodeled hot loop in.
* **registry sync** — every program in the trace catalog has a committed
  manifest and every committed manifest names a live program (the DL009
  pattern): a program added without a manifest fails the gate, as does a
  stale manifest for a deleted program.

Hermetic by construction: forced CPU via
:func:`disco_tpu.analysis.trace.check.ensure_cpu`, abstract tracing only —
no FLOP runs, no chip claim, deterministic manifests on any host.

No reference counterpart: the reference repo has no cost model and no CI
gates.
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from disco_tpu.analysis.trace.check import GOLDEN_DIR as _TRACE_GOLDEN_DIR
from disco_tpu.analysis.trace.check import ensure_cpu

#: where the committed cost manifests live (one canonical JSON per program)
GOLDEN_DIR = _TRACE_GOLDEN_DIR / "cost"


@dataclasses.dataclass
class MeterResult:
    """Everything one gate run produced (the JSON reporter's payload).

    ``findings`` are gate-failing dicts with ``program`` (or ``-`` for
    catalog-wide checks), ``check`` (``manifest``/``golden``/``budget``/
    ``cross``/``registry``) and ``message`` — the disco-lint findings
    shape, same as disco-trace.

    No reference counterpart (module docstring).
    """

    findings: list
    reports: dict
    n_programs: int
    updated: list

    @property
    def clean(self) -> bool:
        return not self.findings


def _finding(program: str, check: str, message: str) -> dict:
    return {"program": program, "check": check, "message": message}


def golden_path(name: str) -> Path:
    """The committed cost manifest of one program.

    No reference counterpart (module docstring)."""
    return GOLDEN_DIR / f"{name}.json"


def load_golden(name: str) -> dict | None:
    """Read one committed cost manifest (None when absent).

    No reference counterpart (module docstring)."""
    path = golden_path(name)
    if not path.is_file():
        return None
    return json.loads(path.read_text())


def build_report(spec, costmodel=None) -> dict:
    """Trace one catalog program abstractly and cost it.

    No reference counterpart (module docstring)."""
    from disco_tpu.analysis.meter import costmodel as _cm

    cm = costmodel or _cm
    fn, args, kwargs = spec.build()
    return cm.cost_of_fn(fn, args, kwargs=kwargs, program=spec.name)


def run_checks(update: bool = False, programs=None) -> MeterResult:
    """Run the gate.  ``update=True`` regenerates the manifests instead of
    diffing (budgets still run: a manifest breaching its own unmodeled
    ceiling, or breaking the cross-budget, must not be committable).
    ``programs`` optionally restricts the pass; the registry-sync and
    cross-program checks only run on a full pass (they are catalog-wide
    statements).

    No reference counterpart (module docstring).
    """
    ensure_cpu()

    from disco_tpu.analysis.meter import budgets, costmodel
    from disco_tpu.analysis.trace.programs import PROGRAMS

    findings: list = []
    reports: dict = {}
    updated: list = []

    for name in (programs or ()):
        if name not in PROGRAMS:
            raise KeyError(
                f"unknown program {name!r}; known: {sorted(PROGRAMS)}")
    selected = {
        name: spec for name, spec in PROGRAMS.items()
        if programs is None or name in programs
    }

    for name, spec in selected.items():
        report = build_report(spec)
        reports[name] = report
        budget_msgs = budgets.check_unmodeled(report)
        for msg in budget_msgs:
            findings.append(_finding(name, "budget", msg))
        if update:
            if budget_msgs:
                findings.append(_finding(
                    name, "golden",
                    "refusing to write a manifest that breaches its own "
                    "unmodeled budget (model the primitives, then --update)",
                ))
            else:
                GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
                golden_path(name).write_text(costmodel.dumps(report))
                updated.append(name)
        else:
            golden = load_golden(name)
            if golden is None:
                findings.append(_finding(
                    name, "golden",
                    f"no committed cost manifest at {golden_path(name)} — "
                    "generate one with `disco-meter --update` and commit it",
                ))
            else:
                for line in costmodel.diff_reports(golden, report):
                    findings.append(_finding(name, "manifest", line))

    if programs is None:
        # cross-program theses (fused < eigh) hold on the CURRENT reports:
        # the claim gates the code as it is, not as it was last committed
        for msg in budgets.check_cross(reports):
            findings.append(_finding("-", "cross", msg))
        # registry sync (the DL009 pattern): catalog and manifest dir must
        # name exactly the same set of programs
        committed = {p.stem for p in GOLDEN_DIR.glob("*.json")}
        for name in sorted(set(PROGRAMS) - committed - set(updated)):
            findings.append(_finding(
                name, "registry",
                "program is in the trace catalog but has no cost manifest "
                "under analysis/golden/cost/ — run `disco-meter --update`",
            ))
        for stem in sorted(committed - set(PROGRAMS)):
            findings.append(_finding(
                stem, "registry",
                "stale cost manifest: no such program in the trace catalog "
                f"— delete {golden_path(stem)} or restore the program",
            ))

    return MeterResult(
        findings=findings, reports=reports,
        n_programs=len(selected), updated=updated,
    )


def format_text(result: MeterResult) -> str:
    """Human-readable gate report (one line per program + findings).

    No reference counterpart (module docstring)."""
    lines = []
    bad = {f["program"] for f in result.findings
           if f["check"] in ("manifest", "golden")}
    for name, rep in result.reports.items():
        status = "DRIFT" if name in bad else "ok"
        ai = rep.get("arithmetic_intensity")
        islands = ",".join(rep.get("fused_islands", ())) or "-"
        unmod = (rep.get("unmodeled") or {}).get("traffic_fraction", 0.0)
        lines.append(
            f"manifest {name:<24} {status:>5}  "
            f"{rep['flops']:>12,d} flops  {rep['traffic_bytes']:>11,d} B  "
            f"AI={ai if ai is not None else '-':<8}  "
            f"islands[{islands}]  unmodeled={unmod}"
        )
    if result.updated:
        lines.append("updated manifests: " + ", ".join(result.updated))
    for f in result.findings:
        lines.append(f"FINDING [{f['check']}] {f['program']}: {f['message']}")
    lines.append(
        f"disco-meter: {len(result.findings)} finding(s), "
        f"{result.n_programs} program(s) metered"
    )
    return "\n".join(lines)


def format_json(result: MeterResult) -> str:
    """Machine-readable report — the disco-lint contract shape
    (``clean``/``counts``/``findings``) plus the per-program manifests.

    No reference counterpart (module docstring)."""
    per_check: dict = {}
    for f in result.findings:
        per_check[f["check"]] = per_check.get(f["check"], 0) + 1
    return json.dumps(
        {
            "clean": result.clean,
            "counts": {
                "findings": len(result.findings),
                "programs": result.n_programs,
                "by_check": per_check,
            },
            "findings": result.findings,
            "reports": result.reports,
            "updated": result.updated,
        },
        indent=2,
    )


def main(argv=None) -> int:
    """``python -m disco_tpu.analysis.meter.check`` — the ``make
    meter-check`` entry: full gate, text report, exit 1 on findings.

    No reference counterpart (module docstring)."""
    result = run_checks()
    print(format_text(result))
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
