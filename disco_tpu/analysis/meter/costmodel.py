"""The jaxpr-walking cost model — FLOPs, HBM traffic and live bytes.

A :func:`cost_of_jaxpr` report is a pure function of a traced program
(the same abstract ``ShapeDtypeStruct`` tracing the disco-trace gate
uses: no FLOP runs, no device buffer, no chip claim), so the committed
manifests rebuild bit-identically on any host.  The model is *declared*,
not measured — its value is that it is deterministic, attributable per
primitive class, and moves when (and only when) the program moves:

* **FLOPs** — analytic per-primitive formulas: ``dot_general`` /
  ``conv_general_dilated`` count ``2·M·N·K`` multiply-adds, ``fft``
  counts ``5·N·log2(N)`` per transform, dense linear algebra uses the
  textbook cubics (Cholesky ``n³/3``, ``eigh`` ``12·n³``, triangular
  solve ``n²·m``), elementwise ops count 1 flop per output element
  (transcendentals 10, divisions 4), reductions count one flop per input
  element.  Complex arithmetic scales by the real-flop equivalents
  (add ×2, multiply ×6, division ×20, dot/linalg ×4).
* **HBM traffic** (``traffic_bytes``) — the materialization model: every
  equation reads its operands from and writes its results to HBM once.
  This deliberately ignores XLA fusion (it is an upper bound), EXCEPT for
  **declared fused islands** (:data:`FUSED_UNITS`, matched by inner-jit
  ``pjit`` name, plus any ``pallas_call``): their interior is VMEM-resident
  by construction — the PR-15 fused-solve contract — so an island
  contributes only its boundary operands and results.  ``lax.scan`` body
  traffic is counted **per iteration** (× ``length``), with the carry's
  HBM round-trip counted once per iteration and the ``xs``/``ys`` streams
  counted once in total.
* **Boundary bytes** (``hbm_bytes_in`` / ``hbm_bytes_out``) — the traced
  program's own input/output avals: the traffic floor a perfectly fused
  program cannot go below.
* **Peak live bytes** — a linear-scan liveness estimate over the
  depth-first equation walk (nested bodies inlined): the high-water mark
  of simultaneously live array bytes, an HBM footprint estimate.
* **Unmodeled primitives** are accounted EXPLICITLY: anything outside the
  tables lands in the ``unmodeled`` bucket with its primitive name, count
  and traffic share — never a silent zero.  The meter gate holds that
  share under a declared ceiling (:mod:`disco_tpu.analysis.meter.budgets`).

No reference counterpart: the reference repo has no traced programs and
no cost model (SURVEY.md §5.1).
"""
from __future__ import annotations

import json
import math

#: bump when the report schema or the model conventions change
#: incompatibly — a version mismatch against a committed manifest reports
#: as "regenerate with --update", not as a program drift.  Surfaces in
#: bench records as ``cost_model_version`` so a roofline join never mixes
#: conventions.
VERSION = 1

#: inner-jit (``pjit``) names whose interior is VMEM-resident by contract:
#: the fused rank-1 GEVD-MWF solve (ops/mwf_ops.py) DMAs its pencil tiles
#: HBM->VMEM once and writes back only the filter weights — the PR-15
#: thesis.  The XLA twin is listed too: it is the backend-independent
#: stand-in the gate traces, and the budget it certifies is the pallas
#: kernel's HBM contract.
FUSED_UNITS = ("fused_mwf_xla", "fused_mwf_pallas")

#: primitive classes the per-class breakdown reports (documentation order)
CLASSES = (
    "fft", "dot_general", "linalg", "elementwise", "reduction",
    "gather_scatter", "data_movement", "convert", "random", "unmodeled",
)

# -- primitive tables -------------------------------------------------------
#: zero-flop layout/movement primitives
_MOVEMENT = frozenset((
    "broadcast_in_dim", "reshape", "transpose", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "pad", "squeeze", "expand_dims",
    "rev", "copy", "iota", "stop_gradient", "split", "device_put",
    "opt_barrier", "optimization_barrier", "sharding_constraint",
))

#: dtype-cast primitives (zero flops; the traffic is the point)
_CONVERT = frozenset((
    "convert_element_type", "bitcast_convert_type", "reduce_precision",
))

#: indexed reads/writes (zero flops; address math is free in this model)
_GATHER_SCATTER = frozenset((
    "gather", "scatter", "scatter-add", "scatter_add", "scatter_mul",
    "scatter_min", "scatter_max", "select_and_scatter_add",
))

#: 1-flop-per-element ops (complex: ×2)
_ELEMENTWISE_1 = frozenset((
    "add", "sub", "neg", "abs", "sign", "max", "min", "floor", "ceil",
    "round", "rem", "nextafter", "conj", "real", "imag", "complex",
    "eq", "ne", "lt", "le", "gt", "ge", "and", "or", "xor", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "select_n", "clamp", "is_finite", "copysign", "population_count",
    "clz", "add_any", "square",
))

#: 10-flop-per-element transcendentals (complex: ×2)
_TRANSCENDENTAL = frozenset((
    "exp", "exp2", "log", "log1p", "expm1", "sqrt", "rsqrt", "cbrt",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "erf", "erfc", "erf_inv",
    "logistic", "pow", "lgamma", "digamma",
))

#: one-flop-per-INPUT-element reductions
_REDUCTION = frozenset((
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "cumsum", "cummax",
    "cummin", "cumprod", "cumlogsumexp", "reduce_window_sum",
    "reduce_window_max", "reduce_window_min",
))

#: counter-based RNG kernels: ~100 flops per output element
_RANDOM = frozenset((
    "threefry2x32", "random_bits", "random_seed", "random_wrap",
    "random_fold_in", "random_gamma", "random_clone", "random_split",
    "random_unwrap",
))

#: control primitives: recursed into, no cost of their own
_CONTROL = frozenset((
    "pjit", "closed_call", "core_call", "xla_call", "named_call",
    "custom_jvp_call", "custom_vjp_call", "custom_jvp_call_jaxpr",
    "custom_vjp_call_jaxpr", "custom_lin", "remat", "remat2", "checkpoint",
    "scan", "while", "cond",
))


def _nbytes(v) -> int:
    """Byte size of one variable's aval (0 for abstract tokens).

    No reference counterpart (module docstring)."""
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    itemsize = getattr(dtype, "itemsize", None)
    if shape is None or itemsize is None:
        return 0     # abstract tokens, extended dtypes (RNG keys)
    return int(math.prod(shape)) * int(itemsize)


def _nelems(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", ())
    return int(math.prod(shape)) if shape is not None else 0


def _is_complex(v) -> bool:
    dtype = getattr(getattr(v, "aval", None), "dtype", None)
    return getattr(dtype, "kind", "") == "c"


def _first_shaped(eqn_vars):
    for v in eqn_vars:
        if getattr(getattr(v, "aval", None), "shape", None) is not None:
            return v
    return None


def _dot_general_flops(eqn) -> int:
    """``2·batch·M·N·K`` multiply-add flops of one dot_general (complex ×4).

    No reference counterpart (module docstring)."""
    (contract, batch) = eqn.params["dimension_numbers"]
    (lc, _rc), (lb, _rb) = contract, batch
    lhs, rhs = eqn.invars[0], eqn.invars[1]
    lshape = lhs.aval.shape
    k = math.prod(lshape[d] for d in lc) or 1
    out = _nelems(eqn.outvars[0])
    factor = 4 if (_is_complex(lhs) or _is_complex(rhs)) else 1
    return 2 * out * k * factor


def _conv_flops(eqn) -> int:
    """``2·out·(kernel_spatial·C_in/groups)`` flops of one convolution.

    No reference counterpart (module docstring)."""
    rhs = eqn.invars[1]
    rshape = rhs.aval.shape          # kernel: spatial + (in/groups, out)
    dn = eqn.params.get("dimension_numbers")
    groups = int(eqn.params.get("feature_group_count", 1))
    if dn is not None:
        k_spatial = math.prod(rshape[d] for d in dn.rhs_spec[2:]) or 1
        c_in = rshape[dn.rhs_spec[1]]
    else:                            # fallback: whole kernel volume
        k_spatial, c_in = math.prod(rshape) or 1, 1
    out = _nelems(eqn.outvars[0])
    factor = 4 if _is_complex(rhs) else 1
    return 2 * out * k_spatial * c_in // max(groups, 1) * factor


def _fft_flops(eqn) -> int:
    """``5·N·log2(N)`` per transform over the batch (the classic radix-2
    count; rfft/irfft batches use the larger of the two element counts).

    No reference counterpart (module docstring)."""
    n = math.prod(eqn.params.get("fft_lengths", ())) or 1
    batch = max(_nelems(eqn.invars[0]), _nelems(eqn.outvars[0])) // max(n, 1)
    return int(5 * max(batch, 1) * n * max(math.log2(n), 1.0))


def _linalg_flops(eqn) -> int:
    """Textbook dense-linalg flop cubics per matrix in the batch
    (complex ×4): Cholesky ``n³/3``, eigh ``12·n³``, triangular solve
    ``n²·m``, LU ``2n³/3``, QR ``2mn²``.

    No reference counterpart (module docstring)."""
    name = eqn.primitive.name
    a = _first_shaped(eqn.invars)
    shape = a.aval.shape if a is not None else ()
    factor = 4 if (a is not None and _is_complex(a)) else 1
    if len(shape) < 2:
        return 0
    n, m = shape[-1], shape[-2]
    batch = math.prod(shape[:-2]) or 1
    if name == "cholesky":
        per = n * n * n // 3
    elif name == "eigh":
        per = 12 * n * n * n
    elif name == "triangular_solve":
        b = eqn.invars[1].aval.shape
        per = n * n * (b[-1] if len(b) else 1)
        batch = math.prod(b[:-2]) or 1
    elif name == "lu":
        per = 2 * n * n * n // 3
    elif name in ("qr", "householder_product"):
        per = 2 * m * n * n
    elif name == "svd":
        per = 12 * m * n * n
    else:
        per = 12 * n * n * n
    return batch * per * factor


#: dense-linalg primitives routed through :func:`_linalg_flops`
_LINALG = frozenset((
    "cholesky", "eigh", "triangular_solve", "lu", "qr",
    "householder_product", "svd",
))


def classify(prim_name: str) -> str:
    """Map one primitive name to its cost class (``'unmodeled'`` when the
    model has no entry for it — the explicit-unknowns contract).

    No reference counterpart (module docstring)."""
    if prim_name in _MOVEMENT:
        return "data_movement"
    if prim_name in _CONVERT:
        return "convert"
    if prim_name in _GATHER_SCATTER:
        return "gather_scatter"
    if prim_name in _ELEMENTWISE_1 or prim_name in ("mul", "div",
                                                    "integer_pow"):
        return "elementwise"
    if prim_name in _TRANSCENDENTAL:
        return "elementwise"
    if prim_name in _REDUCTION or prim_name in ("sort", "top_k"):
        return "reduction"
    if prim_name in _RANDOM:
        return "random"
    if prim_name == "fft":
        return "fft"
    if prim_name in ("dot_general", "conv_general_dilated"):
        return "dot_general"
    if prim_name in _LINALG:
        return "linalg"
    return "unmodeled"


def _eqn_flops(eqn) -> int | None:
    """Analytic flops of one (non-control) equation, None when unmodeled.

    No reference counterpart (module docstring)."""
    name = eqn.primitive.name
    out = _first_shaped(eqn.outvars)
    out_elems = _nelems(out) if out is not None else 0
    cplx = 2 if (out is not None and _is_complex(out)) else 1
    if name in _MOVEMENT or name in _CONVERT or name in _GATHER_SCATTER:
        return 0
    if name in _ELEMENTWISE_1:
        return out_elems * cplx
    if name == "mul":
        return out_elems * (6 if cplx == 2 else 1)
    if name == "div":
        return out_elems * (20 if cplx == 2 else 4)
    if name == "integer_pow":
        return out_elems * 2 * cplx
    if name in _TRANSCENDENTAL:
        return out_elems * 10 * cplx
    if name in _REDUCTION:
        inp = _first_shaped(eqn.invars)
        return _nelems(inp) * cplx if inp is not None else 0
    if name in ("sort", "top_k"):
        inp = _first_shaped(eqn.invars)
        n = _nelems(inp) if inp is not None else 0
        return int(n * max(math.log2(max(n, 2)), 1.0))
    if name in _RANDOM:
        return out_elems * 100
    if name == "fft":
        return _fft_flops(eqn)
    if name == "dot_general":
        return _dot_general_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _LINALG:
        return _linalg_flops(eqn)
    return None


def _sub_jaxprs(params: dict):
    """Yield the ClosedJaxpr-like values of one equation's params.

    No reference counterpart (module docstring)."""
    for val in params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for sub in vals:
            if hasattr(sub, "jaxpr") or hasattr(sub, "eqns"):
                yield sub


def _inner(sub):
    return sub.jaxpr if hasattr(sub, "jaxpr") else sub


class _Acc:
    """Accumulator one walk writes into (plain ints throughout so the
    manifests serialize bit-identically).

    No reference counterpart (module docstring)."""

    def __init__(self):
        self.flops: dict[str, int] = {}
        self.traffic: dict[str, int] = {}
        self.unmodeled_prims: dict[str, int] = {}
        self.fused_islands: list[str] = []
        self.while_loops = 0
        self.n_eqns = 0
        self.events: list = []   # (invars, outvars) for the liveness pass

    def add(self, cls: str, flops: int, traffic: int) -> None:
        self.flops[cls] = self.flops.get(cls, 0) + int(flops)
        self.traffic[cls] = self.traffic.get(cls, 0) + int(traffic)

    def merge(self, other: "_Acc", mult: int = 1) -> None:
        for cls, v in other.flops.items():
            self.flops[cls] = self.flops.get(cls, 0) + v * mult
        for cls, v in other.traffic.items():
            self.traffic[cls] = self.traffic.get(cls, 0) + v * mult
        for name, v in other.unmodeled_prims.items():
            self.unmodeled_prims[name] = self.unmodeled_prims.get(name, 0) + v
        self.fused_islands.extend(other.fused_islands)
        self.while_loops += other.while_loops
        self.n_eqns += other.n_eqns
        self.events.extend(other.events)


def _boundary_bytes(eqn) -> int:
    return (sum(_nbytes(v) for v in eqn.invars)
            + sum(_nbytes(v) for v in eqn.outvars))


def _walk(jaxpr, acc: _Acc, fused_units, in_island: bool) -> None:
    """Depth-first cost walk of one jaxpr into ``acc`` (multipliers are
    applied by the caller via :meth:`_Acc.merge`).

    No reference counterpart (module docstring)."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        acc.n_eqns += 1
        acc.events.append((tuple(eqn.invars), tuple(eqn.outvars)))
        if name in _CONTROL or name.startswith("pallas_call"):
            island = (not in_island) and (
                name.startswith("pallas_call")
                or (name == "pjit"
                    and str(eqn.params.get("name", "")) in fused_units)
            )
            if island:
                # VMEM-resident by contract: boundary traffic only; the
                # interior still contributes flops (real work either way)
                acc.add("data_movement", 0, _boundary_bytes(eqn))
                acc.fused_islands.append(
                    str(eqn.params.get("name", name)))
            if name == "while":
                # unknown trip count: body costed once, surfaced in the
                # report so a reader knows the model floor-bounds it
                acc.while_loops += 1
            if name == "cond":
                branches = [_inner(b) for b in eqn.params.get("branches", ())]
                costed = []
                for b in branches:
                    sub = _Acc()
                    _walk(b, sub, fused_units, in_island or island)
                    costed.append(sub)
                if costed:   # worst-case branch models the cond
                    worst = max(
                        costed,
                        key=lambda a: (sum(a.traffic.values()),
                                       sum(a.flops.values())),
                    )
                    acc.merge(worst)
                continue
            mult = 1
            if name == "scan":
                mult = int(eqn.params.get("length", 1))
                if not (in_island or island):
                    # the per-iteration carry round-trip + the streamed
                    # xs/ys (already counted once via the outer operands)
                    n_carry = int(eqn.params.get("num_carry", 0))
                    n_consts = int(eqn.params.get("num_consts", 0))
                    carry = sum(
                        _nbytes(v)
                        for v in eqn.invars[n_consts:n_consts + n_carry])
                    acc.add("data_movement",
                            0, 2 * carry * mult + _boundary_bytes(eqn))
            for sub in _sub_jaxprs(eqn.params):
                body = _Acc()
                _walk(_inner(sub), body, fused_units, in_island or island)
                if island or in_island:
                    # interior of a fused island: flops count, traffic
                    # stays in VMEM by contract
                    body.traffic = {}
                acc.merge(body, mult)
            continue
        flops = _eqn_flops(eqn)
        traffic = 0 if in_island else _boundary_bytes(eqn)
        if flops is None:
            acc.unmodeled_prims[name] = acc.unmodeled_prims.get(name, 0) + 1
            acc.add("unmodeled", 0, traffic)
        else:
            acc.add(classify(name), flops, traffic)


def _peak_live_bytes(jaxpr, events) -> int:
    """Linear-scan liveness high-water mark over the inlined walk.

    No reference counterpart (module docstring)."""
    last_use: dict[int, int] = {}
    size: dict[int, int] = {}

    def see(v, pos):
        if not hasattr(v, "aval") or type(v).__name__ == "Literal":
            return
        key = id(v)
        size[key] = _nbytes(v)
        last_use[key] = pos

    n = len(events)
    for v in tuple(jaxpr.invars) + tuple(jaxpr.constvars):
        see(v, 0)
    for pos, (invars, outvars) in enumerate(events):
        for v in invars:
            see(v, pos)
    for v in jaxpr.outvars:
        see(v, n)
    live: dict[int, int] = {
        id(v): _nbytes(v)
        for v in tuple(jaxpr.invars) + tuple(jaxpr.constvars)
        if hasattr(v, "aval")
    }
    peak = sum(live.values())
    for pos, (invars, outvars) in enumerate(events):
        for v in outvars:
            if hasattr(v, "aval") and type(v).__name__ != "DropVar":
                live[id(v)] = _nbytes(v)
        peak = max(peak, sum(live.values()))
        for v in invars:
            key = id(v)
            if key in live and last_use.get(key, n + 1) <= pos:
                del live[key]
    return int(peak)


def cost_of_jaxpr(closed_jaxpr, fused_units=FUSED_UNITS,
                  program: str = "") -> dict:
    """Cost report of one traced program (the manifest payload).

    Pure function of the jaxpr object — no tracing, no device, no jax
    import (attribute reads only), mirroring
    :func:`disco_tpu.analysis.trace.fingerprint.fingerprint_jaxpr`.

    No reference counterpart (module docstring).
    """
    jaxpr = (closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr")
             else closed_jaxpr)
    acc = _Acc()
    _walk(jaxpr, acc, tuple(fused_units), in_island=False)
    flops = sum(acc.flops.values())
    traffic = sum(acc.traffic.values())
    hbm_in = sum(_nbytes(v) for v in jaxpr.invars)
    hbm_out = sum(_nbytes(v) for v in jaxpr.outvars)
    unmodeled_traffic = acc.traffic.get("unmodeled", 0)
    return {
        "version": VERSION,
        "program": program,
        "flops": int(flops),
        "flops_by_class": {k: v for k, v in sorted(acc.flops.items()) if v},
        "traffic_bytes": int(traffic),
        "traffic_by_class": {
            k: v for k, v in sorted(acc.traffic.items()) if v},
        "hbm_bytes_in": int(hbm_in),
        "hbm_bytes_out": int(hbm_out),
        "peak_live_bytes": _peak_live_bytes(jaxpr, acc.events),
        "arithmetic_intensity": (
            round(flops / traffic, 6) if traffic else None),
        "fused_islands": sorted(set(acc.fused_islands)),
        "while_loops": acc.while_loops,
        "n_eqns": acc.n_eqns,
        "unmodeled": {
            "primitives": dict(sorted(acc.unmodeled_prims.items())),
            "traffic_bytes": int(unmodeled_traffic),
            "traffic_fraction": (
                round(unmodeled_traffic / traffic, 6) if traffic else 0.0),
        },
    }


def cost_of_fn(fn, args, kwargs=None, fused_units=FUSED_UNITS,
               program: str = "") -> dict:
    """Trace ``fn`` on abstract inputs and cost the jaxpr — the
    :func:`~disco_tpu.analysis.trace.fingerprint.fingerprint_fn` twin.

    No reference counterpart (module docstring).
    """
    import jax

    kwargs = kwargs or {}
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    return cost_of_jaxpr(closed, fused_units=fused_units, program=program)


def diff_reports(golden: dict, current: dict) -> list:
    """Readable per-class / per-primitive cost differences, empty when
    identical — the meter gate's failure report names WHAT moved (flops,
    traffic, boundary bytes, unmodeled set), not just two blobs.

    No reference counterpart (module docstring).
    """
    out: list[str] = []
    if golden.get("version") != current.get("version"):
        return [
            f"cost-model version {golden.get('version')} != "
            f"{current.get('version')}: regenerate manifests with "
            "`disco-meter --update`"
        ]
    for key, unit in (("flops", "flops"), ("traffic_bytes", "bytes"),
                      ("hbm_bytes_in", "bytes"), ("hbm_bytes_out", "bytes"),
                      ("peak_live_bytes", "bytes"), ("n_eqns", "eqns"),
                      ("while_loops", "loops")):
        a, b = golden.get(key), current.get(key)
        if a != b:
            rel = f" ({(b - a) / a:+.1%})" if a else ""
            out.append(f"{key}: {a} -> {b} {unit}{rel}")
    for table in ("flops_by_class", "traffic_by_class"):
        ga, cu = golden.get(table, {}), current.get(table, {})
        for cls in sorted(set(ga) | set(cu)):
            a, b = ga.get(cls, 0), cu.get(cls, 0)
            if a != b:
                out.append(f"{table}[{cls}]: {a} -> {b} ({b - a:+d})")
    gu = (golden.get("unmodeled") or {}).get("primitives", {})
    cuu = (current.get("unmodeled") or {}).get("primitives", {})
    for prim in sorted(set(gu) | set(cuu)):
        a, b = gu.get(prim, 0), cuu.get(prim, 0)
        if a != b:
            out.append(f"unmodeled primitive {prim}: {a} -> {b} ({b - a:+d})")
    if golden.get("fused_islands") != current.get("fused_islands"):
        out.append(
            f"fused islands: {golden.get('fused_islands')} -> "
            f"{current.get('fused_islands')} (a lost island re-exposes its "
            "interior traffic to HBM)"
        )
    return out


def dumps(report: dict) -> str:
    """Canonical JSON text of one report (sorted keys, indented — the
    committed manifest format, reviewable in a PR diff).

    No reference counterpart (module docstring).
    """
    return json.dumps(report, indent=2, sort_keys=True) + "\n"
