"""Declared cost budgets the meter gate enforces.

Two kinds of budget, both *declarations reviewed in this file*, not
emergent numbers:

* **Unmodeled-traffic ceilings** — the explicit-unknowns contract.  The
  cost model never silently zeroes a primitive it does not know; the
  unknown's boundary traffic lands in the ``unmodeled`` bucket and this
  module holds that bucket's share of total traffic under a declared
  ceiling per program.  A new primitive drifting into a hot path either
  gets modeled (extend :mod:`~disco_tpu.analysis.meter.costmodel`'s
  tables) or the gate goes red — there is no third path.
* **Cross-program assertions** — relations between programs that encode a
  design thesis as an inequality.  The one that motivated the meter: the
  fused rank-1 GEVD-MWF step-2 chain must model strictly fewer HBM bytes
  than the separate-stage eigh chain (the solve-fusion round's "read the
  pencils once, write back only the weights", held as a hard gate).

No reference counterpart: the reference repo has no cost model
(SURVEY.md §5.1).
"""
from __future__ import annotations

#: default ceiling on ``unmodeled.traffic_fraction`` — today every
#: registered program models to exactly 0.0, so the ceiling mostly guards
#: FUTURE primitives; 5% keeps headroom for a stray cheap unknown without
#: letting a real hot-loop primitive hide.
UNMODELED_FRACTION_MAX = 0.05

#: per-program overrides of :data:`UNMODELED_FRACTION_MAX` (none today;
#: add an entry here — reviewed in the PR diff — to grant a program more
#: unknown headroom)
UNMODELED_OVERRIDES: dict = {}

#: cross-program inequalities: (smaller, larger, report key, thesis).
#: Each asserts ``report[smaller][key] < report[larger][key]`` strictly.
CROSS_BUDGETS = (
    (
        "tango_step2_fused", "tango_step2_eigh", "traffic_bytes",
        "the fused step-2 solve reads the (F,C,C) pencils from HBM once "
        "and writes back only the (F,C) weights — fusing must model "
        "strictly fewer HBM bytes than the separate-stage eigh path",
    ),
    (
        "tango_step1_fused", "tango_step1_eigh", "traffic_bytes",
        "the disco-chain step-1: all K×F local-MWF pencils ride ONE "
        "batch-in-lanes fused solve instead of K vmapped separate-stage "
        "eigh instances — the fused step-1 must model strictly fewer HBM "
        "bytes than the eigh baseline",
    ),
)


def unmodeled_ceiling(program: str) -> float:
    """The declared unmodeled-traffic ceiling of one program.

    No reference counterpart (module docstring)."""
    return float(UNMODELED_OVERRIDES.get(program, UNMODELED_FRACTION_MAX))


def check_unmodeled(report: dict) -> list:
    """Messages when a report's unmodeled bucket breaches its ceiling.

    No reference counterpart (module docstring)."""
    unmodeled = report.get("unmodeled") or {}
    fraction = float(unmodeled.get("traffic_fraction") or 0.0)
    ceiling = unmodeled_ceiling(report.get("program", ""))
    if fraction <= ceiling:
        return []
    prims = unmodeled.get("primitives", {})
    named = ", ".join(f"{k}×{v}" for k, v in sorted(prims.items())) or "?"
    return [
        f"unmodeled traffic fraction {fraction:.4f} exceeds the declared "
        f"ceiling {ceiling:.4f} (primitives: {named}) — model them in "
        "costmodel.py or raise the ceiling in budgets.py (reviewed)"
    ]


def check_cross(reports: dict) -> list:
    """Messages for every violated (or unevaluable) cross-program budget.

    ``reports`` maps program name -> cost report; a budget whose programs
    are missing reports as a finding too — a cross assertion that silently
    stops being evaluated is a gate hole, not a pass.

    No reference counterpart (module docstring)."""
    out: list = []
    for small, large, key, thesis in CROSS_BUDGETS:
        a, b = reports.get(small), reports.get(large)
        if a is None or b is None:
            missing = [n for n, r in ((small, a), (large, b)) if r is None]
            out.append(
                f"cross-budget {small} < {large} on {key}: program(s) "
                f"{', '.join(missing)} missing from the run — the "
                "assertion cannot be evaluated"
            )
            continue
        va, vb = a.get(key), b.get(key)
        if not (isinstance(va, int) and isinstance(vb, int) and va < vb):
            out.append(
                f"cross-budget violated: {small}.{key}={va} is not "
                f"strictly below {large}.{key}={vb} — {thesis}"
            )
    return out
