"""disco_tpu.analysis — AST invariant checking for the repo's own contracts.

The reproduction carries contracts the paper's single-process NumPy
reference never needed — one fenced ~80 ms RPC per dispatch, complex dtypes
that cannot cross the tunnel, atomic-only persistence for crash-safe
resume, a jax-free serve client, registered telemetry kinds and chaos
seams.  Until this package they were enforced by convention and review;
``disco-lint`` turns each into a named rule checked at lint time, gated in
CI via ``make lint-check`` (no jax import anywhere in the linter — the gate
is hermetic and never touches the chip claim).

* :mod:`.registry`     — Rule base class + ``DLnnn`` registry
* :mod:`.rules`        — the fifteen rule implementations (catalog in its docstring)
* :mod:`.suppressions` — ``# disco-lint: disable=... -- justification`` parsing
* :mod:`.registries`   — AST extraction of EVENT_KINDS / SEAMS (no imports)
* :mod:`.runner`       — file collection + the lint engine (:func:`lint_paths`)
* :mod:`.report`       — text / JSON reporters
* :mod:`.cli`          — the ``disco-lint`` console entry

The sibling :mod:`.trace` subpackage (``disco-trace``, ``make
trace-check``) checks the contracts that live BELOW the AST — golden jaxpr
fingerprints, retrace budgets, donation/dtype audits.  It does import jax
(forced to the CPU backend), so nothing in the linter imports it: the
lint gate stays stdlib-only.

No reference counterpart: the reference repo has no static analysis of any
kind (SURVEY.md documents no tooling beyond setup.py).
"""
from disco_tpu.analysis.findings import Finding
from disco_tpu.analysis.registry import RULES, Rule, get_rules, register
from disco_tpu.analysis.runner import (
    DEFAULT_TARGETS,
    LintResult,
    lint_paths,
    lint_source,
    repo_root,
)

__all__ = [
    "DEFAULT_TARGETS",
    "Finding",
    "LintResult",
    "RULES",
    "Rule",
    "get_rules",
    "lint_paths",
    "lint_source",
    "register",
    "repo_root",
]
