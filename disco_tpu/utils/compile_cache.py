"""Persistent XLA compilation cache seam.

The corpus driver compiles one program per length bucket (plus one per
remainder-chunk padded size), and before this seam existed that compile tax
was paid again on EVERY run and every ``--resume`` — minutes of host-side
tracing/lowering before the first chunk dispatches.  JAX ships a persistent
compilation cache (serialized XLA executables keyed on program + flags +
backend) that makes recompiles a disk read; this module is the one place
the framework turns it on, so policy lives in one seam instead of being
sprinkled through drivers and CLIs:

* **Path-configurable**: explicit argument > ``DISCO_TPU_COMPILE_CACHE``
  env var > ``~/.cache/disco_tpu/xla_cache``.
* **Opt-out**: env var (or argument) set to ``0`` / ``off`` / ``none`` /
  ``disabled`` disables it.
* **Off on the axon tunnel unless forced**: the tunneled single-chip
  attachment is a non-standard PJRT plugin whose executable serialization
  support is unknown; the cache stays off there unless a path is given
  explicitly (argument or env var), in which case the caller has opted in.
* **Never fatal**: any failure to enable degrades to "no cache" with a
  ``warning`` obs event — a caching optimization must not break the run it
  was meant to speed up.

No reference counterpart (the reference has no compiled programs to
cache); the seam follows the standard production-JAX recipe
(``jax.config.update("jax_compilation_cache_dir", ...)``).
"""
from __future__ import annotations

import os
import threading

#: Environment override: a cache directory, or 0/off/none/disabled.
ENV_VAR = "DISCO_TPU_COMPILE_CACHE"

_OFF_VALUES = ("0", "off", "none", "disabled", "false")

_lock = threading.Lock()
_state = {"resolved": False, "path": None}


def default_path() -> str:
    """Default on-disk XLA cache location (``~/.cache/disco_tpu/xla_cache``)."""
    return os.path.join(os.path.expanduser("~"), ".cache", "disco_tpu", "xla_cache")


def _tunneled() -> bool:
    from disco_tpu.utils.transfer import _tunneled_attachment

    return _tunneled_attachment()


def ensure_enabled(path: str | bool | None = None) -> str | None:
    """Enable JAX's persistent compilation cache once per process.

    Args:
      path: explicit cache directory; ``False`` (or an off-string) disables;
        ``None`` resolves env var then the default path.

    Returns:
      The active cache directory, or ``None`` when disabled/unavailable.
      Idempotent: later calls return the first resolution (JAX reads the
      config at compile time; flip-flopping it mid-process would shear the
      cache key space for no benefit).
    """
    with _lock:
        if _state["resolved"]:
            return _state["path"]
        _state["resolved"] = True
        _state["path"] = _resolve_and_enable(path)
        return _state["path"]


def _resolve_and_enable(path) -> str | None:
    if path is False:
        return None
    env = os.environ.get(ENV_VAR)
    explicit = path if isinstance(path, str) else env
    if isinstance(explicit, str) and explicit.strip().lower() in _OFF_VALUES:
        return None
    try:
        import jax

        if explicit is None and _tunneled():
            # Unknown serialization support on the tunneled plugin: default
            # off there; an explicit path (arg/env) is the caller's opt-in.
            return None
        cache_dir = explicit or default_path()
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        from disco_tpu.obs import events as obs_events

        obs_events.record("note", stage="compile_cache", path=cache_dir)
        return cache_dir
    except Exception as e:  # pragma: no cover - backend/version specific
        try:
            from disco_tpu.obs import events as obs_events

            obs_events.record(
                "warning", stage="compile_cache",
                reason=f"persistent compilation cache unavailable: "
                       f"{type(e).__name__}: {e}"[:300],
            )
        except Exception:
            pass
        return None


def _reset_for_tests() -> None:
    """Forget the process-wide resolution (test isolation only)."""
    with _lock:
        _state["resolved"] = False
        _state["path"] = None
