"""Deprecated shim — :class:`StageTimer` and :func:`trace_to` moved to
:mod:`disco_tpu.obs.metrics` (the observability subsystem that grew out of
this module).  Import from ``disco_tpu.obs`` instead; this re-export keeps
old call sites working one release."""
from __future__ import annotations

import warnings

from disco_tpu.obs.metrics import StageTimer, trace_to

__all__ = ["StageTimer", "trace_to"]

warnings.warn(
    "disco_tpu.utils.profiling moved to disco_tpu.obs.metrics; "
    "import StageTimer/trace_to from disco_tpu.obs",
    DeprecationWarning,
    stacklevel=2,
)
