"""Tracing / profiling as a first-class utility (SURVEY.md §5.1: the
reference has none — only ad-hoc ``time.clock()`` prints, train.py:96-103).

Two tools:

* :class:`StageTimer` — named wall-clock stages with device synchronisation
  (``block_until_ready`` on demand), accumulating a report dict.  Replaces
  the reference's scattered prints with one structured object.
* :func:`trace_to` — context manager around ``jax.profiler`` trace capture
  for TensorBoard/XProf, gated so it is a no-op when tracing is unavailable.
"""
from __future__ import annotations

import contextlib
import time

import jax


class StageTimer:
    """Accumulate named wall-clock stage timings.

    >>> t = StageTimer()
    >>> with t.stage("stft"):
    ...     pass
    >>> "stft" in t.report()
    True
    """

    def __init__(self, sync: bool = True):
        self.sync = sync
        self.times: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def stage(self, name: str, block_on=None):
        start = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None and self.sync:
                jax.block_until_ready(block_on)
            dt = time.perf_counter() - start
            self.times[name] = self.times.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> dict:
        """{stage: {'total_s', 'calls', 'mean_s'}} sorted by total time."""
        out = {
            k: {"total_s": v, "calls": self.counts[k], "mean_s": v / self.counts[k]}
            for k, v in self.times.items()
        }
        return dict(sorted(out.items(), key=lambda kv: -kv[1]["total_s"]))

    def pretty(self) -> str:
        lines = [f"{k:24s} {v['total_s']:9.4f}s  x{v['calls']:<5d} {v['mean_s']*1e3:9.3f} ms/call"
                 for k, v in self.report().items()]
        return "\n".join(lines)


@contextlib.contextmanager
def trace_to(logdir: str):
    """Capture a jax.profiler trace into ``logdir`` (view with XProf /
    TensorBoard).  No-op (with a note) if the profiler cannot start —
    tracing must never break the pipeline it observes."""
    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # pragma: no cover - backend-specific
        print(f"[profiling] trace unavailable: {e}")
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()
