"""Bounded retry/backoff around the flaky-tunnel seams.

The tunneled single-chip attachment this framework is developed against
(CLAUDE.md environment contract) fails in ways a directly-attached chip
never does: the claim RPC can time out while another process holds the
chip, fenced readbacks occasionally drop, and host<->device transfers can
fail transiently.  The contract's hard rule is that a TPU process must
NEVER be SIGKILLed (a killed holder wedges the remote claim for hours) —
so recovery is always *in-process*: retry the failed call with bounded
exponential backoff under an overall deadline, and if the budget runs out,
raise and let the caller unwind cleanly.

Every retry and recovery is first-class telemetry: each failed attempt
records a ``fault`` event (kind ``transient_error``) and ticks the
``retries`` counter; a success after >= 1 failure records a ``recovery``
event and ticks ``retry_recoveries``; exhausting the budget ticks
``retry_giveups`` — all through ``disco_tpu.obs`` (strict no-op while
recording is disabled), rendered by ``cli/obs.py report``.

The concrete seams wrapped here are the fenced dispatch
(:func:`resilient_fence` around ``disco_tpu.milestones._fence``) and the
complex-safe transfers (:func:`resilient_to_host` /
:func:`resilient_to_device` around ``disco_tpu.utils.transfer``).

No reference counterpart: the reference never leaves one host process, so
transport-layer retries do not exist there.
"""
from __future__ import annotations

import functools
import random
import threading
import time

from disco_tpu.obs import events as _events
from disco_tpu.obs.metrics import REGISTRY as _REGISTRY

_RETRIES = _REGISTRY.counter("retries")
_RECOVERIES = _REGISTRY.counter("retry_recoveries")
_GIVEUPS = _REGISTRY.counter("retry_giveups")
_DEADLINE_HITS = _REGISTRY.counter("dispatch_deadline_hits")


def _transport_errors() -> tuple:
    """Error types a tunnel transport failure can surface as — the
    ``retry_on`` set for the ALWAYS-ON seams (fence, driver/sentinel
    readbacks).  Deliberately excludes TypeError/ValueError and friends: a
    deterministic programming error must raise immediately, not burn the
    backoff budget and pollute the fault log with fake transients."""
    errs: list[type] = [ConnectionError, TimeoutError, OSError]
    try:
        from jax.errors import JaxRuntimeError

        errs.append(JaxRuntimeError)
    except Exception:
        try:  # older jax spells it at the jaxlib layer
            from jaxlib.xla_extension import XlaRuntimeError

            errs.append(XlaRuntimeError)
        except Exception:
            errs.append(RuntimeError)  # last resort: the XLA errors' base
    return tuple(errs)


#: Transport-layer exception types (see :func:`_transport_errors`).
TRANSPORT_ERRORS: tuple = _transport_errors()


class DeadlineExceeded(TimeoutError):
    """The retry budget's wall-clock deadline ran out before a success."""


def call_with_retries(
    fn,
    *args,
    retries: int = 3,
    base_delay_s: float = 0.1,
    backoff: float = 2.0,
    max_delay_s: float = 2.0,
    deadline_s: float | None = None,
    retry_on: type | tuple = Exception,
    label: str | None = None,
    jitter: float = 0.0,
    jitter_seed: int = 0,
    sleep=time.sleep,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying transient failures.

    Args:
      retries: maximum number of RE-tries (so at most ``retries + 1``
        calls).
      base_delay_s / backoff / max_delay_s: exponential backoff
        ``min(base * backoff**i, max)`` between attempts — deterministic by
        default (``jitter=0``), so a seeded run's retry schedule is
        reproducible.
      jitter / jitter_seed: fraction of each backoff delay to SUBTRACT at
        random (``delay * (1 - jitter * u)``, ``u`` drawn from a
        ``random.Random(jitter_seed)`` stream, one draw per sleep, in
        ``[0, 1)``).  Desynchronizes the thundering herd of K parked
        clients all reconnecting after the same outage, while staying
        fully deterministic given the seed (same seed, same failure
        pattern → same schedule) and never exceeding the un-jittered
        delay — deadline accounting stays conservative.  ``jitter`` must
        be in ``[0, 1]``.
      deadline_s: overall wall budget from the first call; if the next
        backoff sleep would cross it, :class:`DeadlineExceeded` is raised
        (chained to the last error) instead of sleeping.
      retry_on: exception type(s) considered transient.  ``KeyboardInterrupt``
        and ``SystemExit`` are never caught (they do not inherit from
        ``Exception``) — an operator abort must unwind immediately, never
        hard-kill (environment contract: no SIGKILL on a TPU process).
      label: telemetry name for the wrapped operation (events/``obs
        report``); defaults to the function's ``__name__``.
      sleep: injection point for tests.

    Returns ``fn``'s value; raises the last error once the budget is spent.
    """
    if retries < 0:
        raise ValueError(f"retries must be >= 0, got {retries}")
    if not 0.0 <= jitter <= 1.0:
        raise ValueError(f"jitter must be in [0, 1], got {jitter}")
    name = label or getattr(fn, "__name__", "call")
    rng = random.Random(jitter_seed) if jitter else None
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            out = fn(*args, **kwargs)
        except retry_on as e:
            attempt += 1
            _RETRIES.inc()
            _events.record(
                "fault", stage=name, fault="transient_error",
                attempt=attempt, error=repr(e),
            )
            if attempt > retries:
                _GIVEUPS.inc()
                raise
            delay = min(base_delay_s * backoff ** (attempt - 1), max_delay_s)
            if rng is not None:
                delay *= 1.0 - jitter * rng.random()
            if deadline_s is not None and (time.monotonic() - t0) + delay > deadline_s:
                _GIVEUPS.inc()
                raise DeadlineExceeded(
                    f"{name}: retry deadline of {deadline_s}s exhausted after "
                    f"{attempt} failed attempt(s); last error: {e!r}"
                ) from e
            sleep(delay)
        else:
            if attempt:
                _RECOVERIES.inc()
                _events.record("recovery", stage=name, attempts=attempt + 1)
            return out


def retrying(**retry_opts):
    """Decorator form of :func:`call_with_retries`::

        @retrying(retries=5, deadline_s=30.0, label="fetch_chunk")
        def fetch_chunk(i): ...

    The wrapped function's kwargs are passed through a closure, NOT merged
    into :func:`call_with_retries`'s namespace — so a decorated function may
    freely take kwargs named ``retries``/``label``/``sleep``/... without
    colliding with the retry options fixed at decoration time.
    """

    def deco(fn):
        opts = dict(retry_opts)
        opts.setdefault("label", getattr(fn, "__name__", "call"))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return call_with_retries(lambda: fn(*args, **kwargs), **opts)

        return wrapper

    return deco


def resilient_fence(x, **retry_opts) -> float:
    """The 1-element host readback that is the only reliable execution
    fence on the tunnel, under caller-chosen retry budgets.  Wraps the raw
    un-retried attempt (``milestones._fence_readback``) — NOT ``_fence``,
    whose own default retry budget would otherwise stack multiplicatively.
    Each attempt ticks the fence counter via the wrapped call itself, so
    the RPC cost model stays honest about retried round-trips."""
    from disco_tpu.milestones import _fence_readback

    retry_opts.setdefault("label", "fence")
    retry_opts.setdefault("retry_on", TRANSPORT_ERRORS)
    return call_with_retries(_fence_readback, x, **retry_opts)


def resilient_to_host(x, **retry_opts):
    """Complex-safe device->host transfer (``utils.transfer.to_host``) under
    bounded retry of transport-layer failures (:data:`TRANSPORT_ERRORS` —
    a dtype/shape bug raises straight through)."""
    from disco_tpu.utils.transfer import to_host

    retry_opts.setdefault("label", "to_host")
    retry_opts.setdefault("retry_on", TRANSPORT_ERRORS)
    return call_with_retries(to_host, x, **retry_opts)


def resilient_to_device(x, **retry_opts):
    """Complex-safe host->device transfer (``utils.transfer.to_device``)
    under bounded retry of transport-layer failures (:data:`TRANSPORT_ERRORS`)."""
    from disco_tpu.utils.transfer import to_device

    retry_opts.setdefault("label", "to_device")
    retry_opts.setdefault("retry_on", TRANSPORT_ERRORS)
    return call_with_retries(to_device, x, **retry_opts)


class DispatchDeadline:
    """Host-only wall-clock watchdog for one dispatch window.

    The tunneled chip can wedge mid-dispatch, and the environment contract
    forbids the classic answer (kill the worker): a SIGKILLed holder wedges
    the remote claim for hours.  So the watchdog never interrupts anything —
    it is a pure ``threading.Timer`` (no jax, safe on any thread) that, when
    the deadline passes with the guarded block still running, marks the
    window **suspect**: flips :attr:`expired`, ticks the
    ``dispatch_deadline_hits`` counter, records a ``fault`` obs event (kind
    ``dispatch_deadline``) and calls the optional ``on_expire`` callback
    (host-only by contract).  The guarded code observes :attr:`expired`
    AFTER its (late) completion and decides what to do — the serve
    scheduler fences via :func:`preflight_probe` and then lets the
    degradation ladder choose retry vs. degrade.

    Usage::

        with DispatchDeadline(2.0, label="serve_tick") as dd:
            ...dispatch + readback...
        if dd.expired:
            ...probe, then degrade...

    No reference counterpart: the reference never has a device that can
    hang (utils/resilience.py module docstring).
    """

    def __init__(self, deadline_s: float, *, label: str = "dispatch",
                 on_expire=None):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = deadline_s
        self.label = label
        self.on_expire = on_expire
        self.expired = False
        self.t0: float | None = None
        self._timer: threading.Timer | None = None

    def _fire(self) -> None:
        # timer thread: host-only telemetry, never touches jax, never kills
        self.expired = True  # disco-race: disable=DR007 -- one-way bool handoff: the timer only stores True; __enter__ resets to False strictly BEFORE arming the timer, and __exit__ cancels before the next window
        _DEADLINE_HITS.inc()
        _events.record(
            "fault", stage=self.label, fault="dispatch_deadline",
            deadline_s=self.deadline_s,
        )
        if self.on_expire is not None:
            try:
                self.on_expire()
            except Exception:
                pass  # a watchdog must never crash the run it watches

    def __enter__(self) -> "DispatchDeadline":
        self.expired = False
        self.t0 = time.monotonic()
        self._timer = threading.Timer(self.deadline_s, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, *exc) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def elapsed_s(self) -> float:
        """Seconds since the guarded window opened (0 before ``__enter__``).

        No reference counterpart (class docstring)."""
        return 0.0 if self.t0 is None else time.monotonic() - self.t0


class PreflightFailed(RuntimeError):
    """The preflight device health probe could not complete a fenced
    round-trip inside its deadline."""


def preflight_probe(deadline_s: float = 60.0, retries: int = 2) -> dict:
    """Bounded-deadline device health probe for long runs.

    A corpus sweep or training run claims the tunneled chip at its first
    jax use and then holds it for hours — if the attachment is wedged (a
    prior holder was killed, the claim RPC hangs), the run discovers it
    only after loading data, tracing programs and burning its own wall
    budget.  The preflight pays one tiny fenced dispatch UP FRONT, under
    :func:`resilient_fence`'s bounded retry and an overall ``deadline_s``,
    so a sick attachment fails in seconds with a clean error instead.

    Returns ``{"ok": True, "dur_s": ..., "platform": ..., "device_count":
    ...}`` on success (the payload of the ``run_start`` obs event); raises
    :class:`PreflightFailed` (chaining the underlying transport error) when
    the round-trip cannot complete — the caller should NOT start the run.
    """
    t0 = time.monotonic()
    try:
        import jax
        import jax.numpy as jnp

        # 1 + 1 through the device: the readback value doubles as a sanity
        # check that the fence actually executed the dispatch.
        val = resilient_fence(
            jnp.ones((1,), jnp.float32) + 1.0,
            retries=retries, deadline_s=deadline_s,
        )
        if val != 2.0:
            raise PreflightFailed(
                f"preflight readback returned {val!r}, expected 2.0 — the "
                f"attachment is returning garbage; do not start the run"
            )
        devs = jax.devices()
        return {
            "ok": True,
            "dur_s": round(time.monotonic() - t0, 6),
            "platform": devs[0].platform,
            "device_count": len(devs),
            "device_kind": devs[0].device_kind,
        }
    except PreflightFailed:
        raise
    except Exception as e:
        raise PreflightFailed(
            f"preflight fenced dispatch failed within {deadline_s}s: {e!r} — "
            f"the device attachment is not healthy; refusing to start the "
            f"long run (recover the claim first, never SIGKILL the holder)"
        ) from e
