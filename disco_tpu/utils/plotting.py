"""Shared room top-view drawing — the one renderer behind
``disco_tpu.sim.geometry.RoomSetup.plot`` (reference ``plot_room``,
room_setups.py:238-253) and ``disco_tpu.enhance.inference.plot_conf``
(reference speech_enhancement/utils.py:141-172).

Object-oriented matplotlib API throughout: the process-global pyplot
backend is never touched, so headless corpus jobs can render thousands of
figures without state leaks.
"""
from __future__ import annotations

import numpy as np


def draw_room_topview(length, width, mics, sources, node_positions, label_offset=1.02):
    """Render a room top view and return the matplotlib Figure.

    Args:
      length, width: room floor dimensions (m).
      mics: (3, n_mics) microphone positions — the pra column layout.
      sources: (n_sources, 3) source positions (rows).
      node_positions: (n_nodes, >=2) per-node label anchor positions
        (node centers, or each node's first mic).
      label_offset: multiplicative offset of the text labels.
    """
    from matplotlib.figure import Figure
    from matplotlib.patches import Rectangle

    mics = np.asarray(mics)
    sources = np.asarray(sources)
    node_positions = np.asarray(node_positions)

    f = Figure()
    ax = f.add_subplot()
    ax.add_patch(Rectangle((0, 0), length, width, fill=False, linewidth=3))
    ax.plot(mics[0, :], mics[1, :], "x", label="mics")
    ax.plot(sources[:, 0], sources[:, 1], "o", label="sources")
    for i_n, c in enumerate(node_positions):
        ax.text(label_offset * c[0], label_offset * c[1], f"Node {i_n + 1}", fontsize=10)
    for i_s, p in enumerate(sources):
        ax.text(label_offset * p[0], label_offset * p[1], f"Source {i_s + 1}", fontsize=10)
    ax.axis("equal")
    ax.set(xlim=(-1, length + 1), ylim=(-1, width + 1))
    ax.legend(loc="upper right", fontsize=8)
    return f
