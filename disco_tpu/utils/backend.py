"""Backend identification that survives plugin platform names.

``jax.default_backend()`` returns the *platform* name, which for TPU
plugins need not be the literal string ``"tpu"`` (the tunneled attachment
in this build environment registers as ``"axon"``).  Code that routes by
hardware class — the MXU DFT-matmul STFT (core/dsp.py), the Mosaic pallas
kernels (beam/filters.py, ops/) — must key off the DEVICE, not the
platform string, or it silently takes the non-TPU path on real TPU
hardware.

No reference counterpart: backend detection is tunnel-deployment
machinery.
"""
from __future__ import annotations

_cached: bool | None = None


def is_tpu() -> bool:
    """True when the default JAX backend drives TPU devices (any platform
    name: 'tpu', plugin names like 'axon', ...).

    The answer is memoized only on success — a transient device-enumeration
    failure must not permanently pin the process to the non-TPU code paths.
    """
    global _cached
    if _cached is not None:
        return _cached
    import jax

    if jax.default_backend() == "tpu":
        _cached = True
        return True
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return False  # transient: do NOT cache
    _cached = "tpu" in kind.lower()
    return _cached
