"""Host <-> device transfer helpers.

Some TPU attachment paths (notably the tunneled single-chip dev backend this
framework is developed against) do not implement complex-dtype host<->device
transfers, while complex math ON device is fully supported.  These helpers
split complex arrays into two real transfers (the real/imag extraction and
the recombination run on the side that supports them), and pass real arrays
straight through.  On standard TPU/CPU backends they are equivalent to
``np.asarray`` / ``jnp.asarray``.

No reference counterpart: the reference never crosses a device boundary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class TunnelTransferError(TypeError):
    """A complex array was about to cross a tunnel attachment raw."""


def _tunneled_attachment() -> bool:
    """True when the default backend is a tunneled plugin attachment (a
    platform name outside the standard set — e.g. the 'axon' single-chip
    tunnel), whose host<->device path cannot move complex dtypes."""
    try:
        platform = jax.default_backend()
    except Exception:
        return False
    return platform not in ("cpu", "gpu", "cuda", "rocm", "tpu", "metal")


def guard_tunnel_complex(x, where: str = "transfer") -> None:
    """Raise :class:`TunnelTransferError` if ``x`` is complex and the
    active attachment cannot transfer complex dtypes.

    The environment contract (CLAUDE.md): complex dtypes cannot cross the
    tunnel — a raw ``np.asarray(device_array)`` / ``jnp.asarray(host_array)``
    on complex data wedges or corrupts the transfer.  Call this at any seam
    that moves raw arrays across the boundary; the sanctioned workaround is
    :func:`to_host` / :func:`to_device` below, which split complex arrays
    into two real transfers.
    """
    if _tunneled_attachment() and (
        np.iscomplexobj(x) or (isinstance(x, jax.Array) and jnp.iscomplexobj(x))
    ):
        raise TunnelTransferError(
            f"{where}: complex dtype {np.asarray(x).dtype if not isinstance(x, jax.Array) else x.dtype} "
            "cannot cross the tunneled TPU attachment (environment contract: "
            "complex dtypes cannot cross the tunnel). Use "
            "disco_tpu.utils.transfer.to_host / to_device, which split complex "
            "arrays into two real transfers."
        )


def to_host(x) -> np.ndarray:
    """Device array -> numpy, complex-safe (two real transfers if needed)."""
    if not isinstance(x, jax.Array):
        return np.asarray(x)
    if jnp.iscomplexobj(x):
        re = np.asarray(jnp.real(x))
        return re + 1j * np.asarray(jnp.imag(x)).astype(re.dtype)
    return np.asarray(x)


@jax.jit
def _combine(re, im):
    return jax.lax.complex(re, im)


def to_device(x) -> jax.Array:
    """Numpy -> device array, complex-safe (combined on device)."""
    if isinstance(x, jax.Array):
        # Already device-resident: return as-is.  ``np.asarray`` here would
        # round-trip the array through the host — for a complex array that
        # is exactly the raw tunnel transfer the environment contract
        # forbids (see :func:`guard_tunnel_complex`).
        return x
    x = np.asarray(x)
    if np.iscomplexobj(x):
        re = np.ascontiguousarray(x.real, dtype=np.float32)
        im = np.ascontiguousarray(x.imag, dtype=np.float32)
        return _combine(jnp.asarray(re), jnp.asarray(im))
    return jnp.asarray(x)


def device_get_tree(tree):
    """Fetch an arbitrary pytree to host in ONE batched ``jax.device_get``.

    The complex-safe, batched replacement for per-leaf ``np.asarray`` /
    :func:`to_host` loops: complex leaves are split into (real, imag) ON
    DEVICE (the tunnel cannot move complex dtypes — environment contract)
    and recombined on host with :func:`to_host` semantics (float32 halves
    → complex64), all leaves travelling in a single ``device_get`` call.
    On the tunneled attachment that is one ~80 ms RPC round instead of one
    per leaf per item — the ``driver.py`` per-clip lazy-slice readback
    this was built to replace cost K×n_real rounds per corpus chunk.

    Host leaves (numpy arrays, scalars, None) pass through untouched.  The
    call is counted once in the fence/RPC accounting
    (``obs.accounting.device_get_tick``) when any leaf actually lives on
    device.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    staged, was_complex, any_device = [], [], False
    for x in leaves:
        if isinstance(x, jax.Array):
            any_device = True
            if jnp.iscomplexobj(x):
                staged.append((jnp.real(x), jnp.imag(x)))
                was_complex.append(True)
                continue
        staged.append(x)
        was_complex.append(False)
    if any_device:
        from disco_tpu.obs import accounting

        accounting.device_get_tick()
    host = jax.device_get(staged)
    out = []
    for h, cplx in zip(host, was_complex):
        if cplx:
            re, im = h
            out.append(re + 1j * im.astype(re.dtype))
        else:
            out.append(h)  # device_get already landed it as numpy
    return jax.tree_util.tree_unflatten(treedef, out)


def prefetch_to_device(iterator, size: int = 2):
    """Overlap host batch preparation and host->device transfer with device
    compute: the loader-parallel layer of SURVEY.md §2.9 (the reference uses
    torch DataLoader workers, train.py:104-105).

    A background thread drains ``iterator`` (host-side numpy work — file
    reads, windowing — overlapping the GIL-released device step), and a
    lookahead deque keeps ``size`` batches already ``to_device``-transferred
    ahead of the consumer (transfers are async, so they run behind the
    in-flight step).  Batches may be arbitrary pytrees of numpy arrays.

    Exceptions from the source iterator are re-raised at the consuming
    site; the feeder thread is a daemon, so abandoning the generator (e.g.
    early-stop mid-epoch) never blocks interpreter exit.
    """
    import collections
    import queue as queue_mod
    import threading

    if size < 1:
        raise ValueError("prefetch_to_device needs size >= 1")

    hostq: "queue_mod.Queue" = queue_mod.Queue(maxsize=size)
    _END = object()

    def feeder():
        try:
            for item in iterator:
                hostq.put(item)
            hostq.put(_END)
        except BaseException as e:  # surfaced at the consumer
            hostq.put(e)

    threading.Thread(target=feeder, daemon=True).start()

    lookahead: "collections.deque" = collections.deque()

    def enqueue(n):
        for _ in range(n):
            item = hostq.get()
            if item is _END:
                return False
            if isinstance(item, BaseException):
                raise item
            lookahead.append(jax.tree_util.tree_map(to_device, item))
        return True

    more = enqueue(size)
    while lookahead:
        yield lookahead.popleft()
        if more:
            more = enqueue(1)
