"""Host <-> device transfer helpers.

Some TPU attachment paths (notably the tunneled single-chip dev backend this
framework is developed against) do not implement complex-dtype host<->device
transfers, while complex math ON device is fully supported.  These helpers
split complex arrays into two real transfers (the real/imag extraction and
the recombination run on the side that supports them), and pass real arrays
straight through.  On standard TPU/CPU backends they are equivalent to
``np.asarray`` / ``jnp.asarray``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def to_host(x) -> np.ndarray:
    """Device array -> numpy, complex-safe (two real transfers if needed)."""
    if not isinstance(x, jax.Array):
        return np.asarray(x)
    if jnp.iscomplexobj(x):
        re = np.asarray(jnp.real(x))
        return re + 1j * np.asarray(jnp.imag(x)).astype(re.dtype)
    return np.asarray(x)


@jax.jit
def _combine(re, im):
    return jax.lax.complex(re, im)


def to_device(x) -> jax.Array:
    """Numpy -> device array, complex-safe (combined on device)."""
    x = np.asarray(x)
    if np.iscomplexobj(x):
        re = np.ascontiguousarray(x.real, dtype=np.float32)
        im = np.ascontiguousarray(x.imag, dtype=np.float32)
        return _combine(jnp.asarray(re), jnp.asarray(im))
    return jnp.asarray(x)
