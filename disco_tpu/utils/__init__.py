from disco_tpu.utils.transfer import (
    TunnelTransferError,
    device_get_tree,
    guard_tunnel_complex,
    prefetch_to_device,
    to_device,
    to_host,
)
from disco_tpu.utils.resilience import (
    TRANSPORT_ERRORS,
    DeadlineExceeded,
    call_with_retries,
    resilient_fence,
    resilient_to_device,
    resilient_to_host,
    retrying,
)
# StageTimer/trace_to live in disco_tpu.obs.metrics since the obs subsystem
# landed; re-exported here (and via the deprecated utils.profiling shim) so
# existing `from disco_tpu.utils import StageTimer` call sites keep working.
from disco_tpu.obs.metrics import StageTimer, trace_to

__all__ = [
    "DeadlineExceeded",
    "StageTimer",
    "TRANSPORT_ERRORS",
    "TunnelTransferError",
    "call_with_retries",
    "device_get_tree",
    "guard_tunnel_complex",
    "prefetch_to_device",
    "resilient_fence",
    "resilient_to_device",
    "resilient_to_host",
    "retrying",
    "to_device",
    "to_host",
    "trace_to",
]
