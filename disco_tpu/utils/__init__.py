from disco_tpu.utils.transfer import prefetch_to_device, to_device, to_host
from disco_tpu.utils.profiling import StageTimer, trace_to

__all__ = ["to_host", "to_device", "prefetch_to_device", "StageTimer", "trace_to"]
