from disco_tpu.utils.transfer import prefetch_to_device, to_device, to_host
# StageTimer/trace_to live in disco_tpu.obs.metrics since the obs subsystem
# landed; re-exported here (and via the deprecated utils.profiling shim) so
# existing `from disco_tpu.utils import StageTimer` call sites keep working.
from disco_tpu.obs.metrics import StageTimer, trace_to

__all__ = ["to_host", "to_device", "prefetch_to_device", "StageTimer", "trace_to"]
