"""Fused rank-1 GEVD-MWF solve: one VMEM-resident cov→whiten→Jacobi→filter
program.

The step-2 exchange MWF is the measured MFU wall after the covariance fold
(BENCH_r05: ``step2_exchange_mwf`` 115.9 ms of a 190 ms pipeline): the
batched rank-1 GEVD solve still runs as separate XLA programs — diagonal
load, Cholesky whiten (two triangular solves), the eigendecomposition and
the rank-1 filter formation each materialize their (F, C, C) intermediates
to HBM between fusion boundaries, while the *useful* output is only the
(F, C) filter weights.  SURVEY §7 anticipated exactly this kernel; ROADMAP
item 1 names it the remaining lever toward "MFU >= 15% and step-2 under
40 ms".

:func:`fused_mwf_pallas` runs the WHOLE solve chain as one pallas program:
each grid step DMAs a lane tile of (C, C) Hermitian pencils (Rss, Rnn)
HBM->VMEM once and performs

    scale-normalize -> diagonal-load -> Cholesky(Rnn) -> whiten
    A = L⁻¹ Rss L⁻ᴴ -> fixed-sweep cyclic Jacobi -> dominant eigenpair
    -> back-substitute q₁ = L⁻ᴴ u₁ -> W = q₁ · λ/(λ+μ) · (Q⁻¹)₀₀

entirely in VMEM, writing back ONLY the (..., C) filter weights W and the
GEVD selection vector t1 — the whitened matrix, the rotation states and
the eigenvector planes never touch HBM.  The layout and the rotation
schedule are :mod:`disco_tpu.ops.eigh_ops`'s batch-in-lanes formulation
(matrix element (p, q) is a full lane vector of pencils; scatter-free
masked writes), the filter algebra is :func:`disco_tpu.beam.filters.gevd_mwf`'s
Cholesky-whitened closed form (reference se_utils/internal_formulas.py:56-73,
Serizel et al. 2014), and the triangular factor work runs element-wise on
lane vectors (statically unrolled over C <= 16 — no scatter, no gather).

:func:`fused_mwf_xla` is the same algorithm as plain XLA ops for off-TPU
backends (whiten via ``beam.filters._whitened``, eigendecomposition via
``eigh_ops.eigh_jacobi``): same math, ordinary fusion.  Both sit behind
:func:`rank1_gevd_fused` and the shared ``ops.resolve`` policy seam
(``impl='auto' | 'xla' | 'pallas'``, :data:`MWF_IMPL_ENV` escape hatch),
reachable from every pipeline entry point as the ``solver='fused'`` /
``'fused-xla'`` / ``'fused-pallas'`` specs of the
:func:`disco_tpu.beam.filters.rank1_gevd` dispatch table.

``precision='bf16'`` extends the PR-9 compute lane into the solve: the
Hermitian pencil planes are rounded to bfloat16 at the HBM->VMEM boundary
(halving the fused program's only HBM read), while EVERY in-VMEM iteration
— whitening, rotations, back-substitution — accumulates in float32.
Gated like the covariance lane by documented looser oracle tolerances and
an SDR-within-0.1-dB pin (tests/test_mwf_ops.py).

Parity: pinned against the float64 NumPy oracle
(``tests/reference_impls.intern_filter_np`` type 'gevd' rank 1) across
C in {4..11} including near-degenerate warm-up covariances, and against
``gevd_mwf(rank=1)``; the NaN-sanitize guard matches ``gevd_mwf``'s
(degenerate bins fall back to the e1 pass-through selector, or surface as
non-finite under ``sanitize=False`` so the streaming ffill hold keeps the
previous block's filter).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from disco_tpu.ops.eigh_ops import _lane_rotation, _pairs, default_sweeps
from disco_tpu.ops.resolve import compute_dtype, resolve_impl, resolve_precision

#: Environment escape hatch for the fused-solve kernel selection:
#: ``DISCO_TPU_MWF_IMPL=xla`` (or ``pallas``) overrides the ``'auto'``
#: resolution wherever a caller selected the ``'fused'`` solver spec.
MWF_IMPL_ENV = "DISCO_TPU_MWF_IMPL"


def resolve_mwf_impl(impl: str = "auto") -> str:
    """Resolve a fused-solve ``impl`` knob to a concrete kernel choice —
    the MWF twin of ``resolve_cov_impl``/``resolve_stft_impl``, backed by
    the SAME shared policy (:func:`disco_tpu.ops.resolve.resolve_impl`):
    ``'auto'`` is the fused pallas kernel on real TPU backends and the XLA
    formulation elsewhere, with :data:`MWF_IMPL_ENV` as the operator
    escape hatch.

    No reference counterpart: kernel selection is a TPU-port concern — the
    reference solves every (node, freq) pencil one way only
    (``scipy.linalg.eig``, internal_formulas.py:56-73).
    """
    return resolve_impl(impl, MWF_IMPL_ENV)


def _bf16_round(x):
    """Round a float plane through bfloat16 — the solve's ``precision='bf16'``
    input quantization (module docstring).  Lives in ops/ because precision
    casts are an ops concern (disco-lint DL012).

    No reference counterpart (module docstring)."""
    return x.astype(jnp.bfloat16).astype(jnp.float32)


# ------------------------------------------------------------- XLA twin
@partial(jax.jit, static_argnames=("sweeps", "precision"))
def fused_mwf_xla(Rss: jnp.ndarray, Rnn: jnp.ndarray, mu: float = 1.0,
                  sweeps: int | None = None, precision: str = "f32"):
    """The fused solve's XLA formulation: identical algorithm chain
    (scale-normalize -> load -> Cholesky whiten -> fixed-sweep Jacobi ->
    dominant eigenpair -> rank-1 filter) as ordinary fused XLA ops — the
    off-TPU twin behind :func:`rank1_gevd_fused`.

    The rank-1 'gevd' branch of reference internal_formulas.py:56-73 in
    the Cholesky-whitened form of :func:`disco_tpu.beam.filters.gevd_mwf`,
    restricted to the dominant eigenpair (ascending Jacobi output — the
    last column).

    Returns:
      (W, t1): filter and GEVD selection vector, each (..., C), UNsanitized
      (degenerate bins carry non-finite values; :func:`rank1_gevd_fused`
      owns the e1 fallback policy).
    """
    from jax.scipy.linalg import solve_triangular

    from disco_tpu.beam.filters import EIG_CEIL, EIG_FLOOR, _whitened
    from disco_tpu.ops.eigh_ops import eigh_jacobi

    Rss = jnp.asarray(Rss)
    Rnn = jnp.asarray(Rnn)
    if resolve_precision(precision) == "bf16":
        Rss = jax.lax.complex(_bf16_round(jnp.real(Rss)), _bf16_round(jnp.imag(Rss)))
        Rnn = jax.lax.complex(_bf16_round(jnp.real(Rnn)), _bf16_round(jnp.imag(Rnn)))
    L, A = _whitened(Rss, Rnn)
    lam, U = eigh_jacobi(A, sweeps=sweeps)  # ascending
    lam1 = jnp.clip(lam[..., -1], EIG_FLOOR, EIG_CEIL)
    u1 = U[..., :, -1]
    # q1 = L^-H u1 ; (Q^-1)[0, 0] = conj(u1[0] * L[0, 0]) (L lower-tri)
    q1 = solve_triangular(L.conj().swapaxes(-1, -2), u1[..., None], lower=False)[..., 0]
    qinv00 = jnp.conj(u1[..., 0] * L[..., 0, 0])
    g = (lam1 / (lam1 + mu)).astype(q1.dtype)
    W = q1 * (g * qinv00)[..., None]
    t1 = q1 * qinv00[..., None]
    return W, t1


# ---------------------------------------------------------- pallas kernel
#
# Layout: BATCH IN LANES (the eigh_ops round-5 lesson) — a block is
# (C, C, tile): pencil element (i, j) IS a full (tile,)-lane vector, every
# rotation is natively-shaped VPU work, and the triangular-factor math runs
# element-wise on lane vectors with ALL loops statically unrolled over
# C <= 16 (static python indices — no scatter, no gather, no Mosaic-less
# primitives).  The (C, C, tile) whitened/rotation/eigenvector planes live
# and die in VMEM; only the (C, tile) filter planes are stored.


def _elem_cholesky(Nr, Ni, load, C):
    """Element-wise complex Cholesky of the loaded noise pencil batch:
    ``L[(i, j)]`` lane-vector dicts (re, im) with ``(i >= j)``, statically
    unrolled (C <= 16).  A non-PSD pencil produces NaN via ``sqrt`` of a
    negative — the same signal ``jnp.linalg.cholesky`` emits, so the
    sanitize/ffill guards downstream see identical semantics.

    The Cholesky step of reference internal_formulas.py:56-73's GEVD in
    the whitened form of ``beam.filters._whitened``.
    """
    Lr: dict = {}
    Li: dict = {}
    inv_diag: dict = {}
    for j in range(C):
        d = Nr[j, j] + load
        for k in range(j):
            d = d - (Lr[(j, k)] * Lr[(j, k)] + Li[(j, k)] * Li[(j, k)])
        ljj = jnp.sqrt(d)  # NaN for non-PSD -> sanitize path downstream
        inv = 1.0 / ljj
        Lr[(j, j)] = ljj
        Li[(j, j)] = jnp.zeros_like(ljj)
        inv_diag[j] = inv
        for i in range(j + 1, C):
            ar = Nr[i, j]
            ai = Ni[i, j]
            for k in range(j):
                # A[i, j] - sum_k L[i, k] conj(L[j, k])
                ar = ar - (Lr[(i, k)] * Lr[(j, k)] + Li[(i, k)] * Li[(j, k)])
                ai = ai - (Li[(i, k)] * Lr[(j, k)] - Lr[(i, k)] * Li[(j, k)])
            Lr[(i, j)] = ar * inv
            Li[(i, j)] = ai * inv
    return Lr, Li, inv_diag


def _elem_whiten(Sr, Si, Lr, Li, inv_diag, C):
    """Element-wise whitening ``A = L⁻¹ Rss L⁻ᴴ`` (re-hermitized), as two
    statically-unrolled forward substitutions on lane vectors; returns
    element dicts ``A[(i, j)]``.

    The whitening step of ``beam.filters._whitened`` (reference
    internal_formulas.py:56-73 via Cholesky instead of ``scipy.linalg.eig``).
    """
    # forward solve L B = Rss, rows of B as (C, tile) arrays
    Br: list = []
    Bi: list = []
    for i in range(C):
        rr = Sr[i]
        ri = Si[i]
        for k in range(i):
            lr = Lr[(i, k)][None]
            li = Li[(i, k)][None]
            rr = rr - (lr * Br[k] - li * Bi[k])
            ri = ri - (lr * Bi[k] + li * Br[k])
        inv = inv_diag[i][None]
        Br.append(rr * inv)
        Bi.append(ri * inv)
    # forward solve L M = B^H (element level), then A = M^H re-hermitized
    Mr: dict = {}
    Mi: dict = {}
    for i in range(C):
        for j in range(C):
            rr = Br[j][i]       # B^H[i, j] = conj(B[j, i])
            ri = -Bi[j][i]
            for k in range(i):
                rr = rr - (Lr[(i, k)] * Mr[(k, j)] - Li[(i, k)] * Mi[(k, j)])
                ri = ri - (Lr[(i, k)] * Mi[(k, j)] + Li[(i, k)] * Mr[(k, j)])
            inv = inv_diag[i]
            Mr[(i, j)] = rr * inv
            Mi[(i, j)] = ri * inv
    # A = M^H, re-hermitized: A[i, j] = (conj(M[j, i]) + M[i, j]) / 2
    Ar: dict = {}
    Ai: dict = {}
    for i in range(C):
        for j in range(C):
            Ar[(i, j)] = 0.5 * (Mr[(j, i)] + Mr[(i, j)])
            Ai[(i, j)] = 0.5 * (Mi[(i, j)] - Mi[(j, i)])
    return Ar, Ai


def _rows_to_plane(rows, C):
    """Stack C (C, tile) row vectors into a (C, C, tile) plane by masked
    selects against a leading-dim iota (scatter-free — the eigh_ops
    broadcast-write idiom).

    No reference counterpart (module docstring)."""
    row_idx = jax.lax.broadcasted_iota(jnp.int32, (C, 1, 1), 0)
    plane = jnp.zeros((C,) + rows[0].shape, rows[0].dtype)
    for i in range(C):
        plane = jnp.where(row_idx == i, rows[i][None], plane)
    return plane


def _elems_to_rows(elems, C):
    """Assemble C (C, tile) rows from a ``{(i, j): (tile,)}`` element dict
    by masked selects (scatter-free).

    No reference counterpart (module docstring)."""
    col_idx = jax.lax.broadcasted_iota(jnp.int32, (C, 1), 0)
    rows = []
    for i in range(C):
        row = jnp.zeros((C,) + elems[(i, 0)].shape, elems[(i, 0)].dtype)
        for j in range(C):
            row = jnp.where(col_idx == j, elems[(i, j)][None], row)
        rows.append(row)
    return rows


def _mwf_kernel(ssr_ref, ssi_ref, nnr_ref, nni_ref, mu_ref,
                wr_ref, wi_ref, t1r_ref, t1i_ref, *, C, sweeps, eps, loading,
                lam_floor, lam_ceil):
    """One lane tile: the WHOLE rank-1 GEVD-MWF solve in VMEM, single HBM
    round trip — inputs are the (C, C, tile) pencil planes (+ the (tile,)
    mu lane), outputs only the (C, tile) filter/selection planes.

    The chain (module docstring) mirrors ``beam.filters.gevd_mwf`` at
    rank 1 (reference internal_formulas.py:56-73): scale-normalize ->
    diagonal-load -> element-wise Cholesky -> element-wise whiten ->
    fixed-sweep cyclic Jacobi (eigh_ops' lanes-layout rotation schedule,
    ``fori_loop`` over sweeps) -> unrolled dominant-eigenpair select ->
    back-substitution -> filter formation.
    """
    f32 = jnp.float32
    Sr = ssr_ref[...].astype(f32)  # (C, C, tile); no-op cast in the f32 lane
    Si = ssi_ref[...].astype(f32)
    Nr = nnr_ref[...].astype(f32)
    Ni = nni_ref[...].astype(f32)
    mu = mu_ref[0]                 # (tile,)

    # -- joint scale normalization (filters._whitened: filter-invariant,
    # keeps warm-up ~1e-12 covariances inside f32 iteration range)
    tr = Nr[0, 0]
    for c in range(1, C):
        tr = tr + Nr[c, c]
    tr = tr * (1.0 / C)
    scale = (1.0 / jnp.maximum(tr, np.float32(np.finfo(np.float32).smallest_normal)))[None, None]
    Sr = Sr * scale
    Si = Si * scale
    Nr = Nr * scale
    Ni = Ni * scale

    # -- relative diagonal loading (filters._load_diag)
    tr2 = Nr[0, 0]
    for c in range(1, C):
        tr2 = tr2 + Nr[c, c]
    load = loading * (tr2 * (1.0 / C)) + np.float32(np.finfo(np.float32).tiny)

    Lr, Li, inv_diag = _elem_cholesky(Nr, Ni, load, C)
    Ael_r, Ael_i = _elem_whiten(Sr, Si, Lr, Li, inv_diag, C)
    Ar = _rows_to_plane(_elems_to_rows(Ael_r, C), C)
    Ai = _rows_to_plane(_elems_to_rows(Ael_i, C), C)

    # -- fixed-sweep cyclic Jacobi with eigenvector accumulation (the
    # eigh_ops lanes-layout schedule, intermediates VMEM-resident)
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (C, C, 1), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (C, C, 1), 1)
    ).astype(f32)
    Vr = jnp.broadcast_to(eye, Ar.shape)
    Vi = jnp.zeros_like(Ar)

    def one_sweep(_, carry):
        Ar, Ai, Vr, Vi = carry
        for p, q in _pairs(C):
            Ar, Ai, Vr, Vi = _lane_rotation(Ar, Ai, Vr, Vi, p, q, eps)
        return Ar, Ai, Vr, Vi

    Ar, Ai, Vr, Vi = jax.lax.fori_loop(0, sweeps, one_sweep, (Ar, Ai, Vr, Vi))

    # -- dominant eigenpair: unrolled running max over the converged
    # diagonal (no sort — rank 1 needs only the top pair)
    lam = jnp.sum(Ar * eye, axis=1)  # (C, tile)
    best = lam[0]
    ur, ui = Vr[:, 0], Vi[:, 0]      # (C, tile)
    for c in range(1, C):
        better = lam[c] > best
        best = jnp.where(better, lam[c], best)
        ur = jnp.where(better[None], Vr[:, c], ur)
        ui = jnp.where(better[None], Vi[:, c], ui)
    lam1 = jnp.clip(best, lam_floor, lam_ceil)

    # -- back-substitution q1 = L^-H u1 (L^H upper-triangular, unrolled)
    qr: dict = {}
    qi: dict = {}
    for i in reversed(range(C)):
        rr = ur[i]
        ri = ui[i]
        for k in range(i + 1, C):
            # L^H[i, k] = conj(L[k, i])
            lr, li = Lr[(k, i)], -Li[(k, i)]
            rr = rr - (lr * qr[k] - li * qi[k])
            ri = ri - (lr * qi[k] + li * qr[k])
        inv = inv_diag[i]            # L[i, i] real
        qr[i] = rr * inv
        qi[i] = ri * inv

    # -- filter formation: (Q^-1)[0,0] = conj(u1[0] L[0,0]); W = q1 g qinv00
    qinv_r = ur[0] * Lr[(0, 0)]
    qinv_i = -ui[0] * Lr[(0, 0)]
    g = lam1 / (lam1 + mu)
    cr = g * qinv_r
    ci = g * qinv_i
    w_re = [qr[i] * cr - qi[i] * ci for i in range(C)]
    w_im = [qr[i] * ci + qi[i] * cr for i in range(C)]
    t_re = [qr[i] * qinv_r - qi[i] * qinv_i for i in range(C)]
    t_im = [qr[i] * qinv_i + qi[i] * qinv_r for i in range(C)]

    row_1d = jax.lax.broadcasted_iota(jnp.int32, (C, 1), 0)

    def stack_c(lanes):
        out = jnp.zeros((C,) + mu.shape, f32)
        for i in range(C):
            out = jnp.where(row_1d == i, lanes[i][None], out)
        return out

    wr_ref[...] = stack_c(w_re)
    wi_ref[...] = stack_c(w_im)
    t1r_ref[...] = stack_c(t_re)
    t1i_ref[...] = stack_c(t_im)


@partial(jax.jit, static_argnames=("sweeps", "tile", "interpret", "precision"))
def fused_mwf_pallas(Rss: jnp.ndarray, Rnn: jnp.ndarray, mu: float = 1.0,
                     sweeps: int | None = None, tile: int = 512,
                     interpret: bool = False, precision: str = "f32"):
    """:func:`fused_mwf_xla` as ONE pallas program (module docstring): the
    pencil tile is read HBM->VMEM once, the whole whiten/Jacobi/filter
    chain runs in VMEM, and only the (..., C) filter planes are written
    back.

    Args:
      Rss, Rnn: (..., C, C) hermitian PSD pencils, complex64 or float32;
        batch dims are flattened into the LANE dim in tiles of ``tile``
        pencils per grid step (``tile`` a multiple of 128).
      mu: speech-distortion tradeoff (traced — one program per shape
        bucket, not per mu).
      sweeps: Jacobi sweep count; None -> ``eigh_ops.default_sweeps``.
      interpret: pallas interpreter mode (CPU correctness tests; the
        Mosaic lowering is TPU-only).
      precision: 'f32' (default) or 'bf16' — the pencil planes cross
        HBM->VMEM as bfloat16 and are converted once on read; every
        in-VMEM iteration stays float32 (module docstring; gated by the
        documented looser oracle tolerances).

    Returns:
      (W, t1): filter and GEVD selection vector, each (..., C) complex64,
      UNsanitized (see :func:`rank1_gevd_fused`).

    The rank-1 'gevd' branch of reference internal_formulas.py:56-73 as a
    single fused device program.
    """
    from jax.experimental import pallas as pl

    Rss = jnp.asarray(Rss)
    Rnn = jnp.asarray(Rnn)
    C = Rss.shape[-1]
    if sweeps is None:
        sweeps = default_sweeps(C)
    batch_shape = Rss.shape[:-2]
    dt = compute_dtype(precision)

    def planes(R):
        # (..., C, C) -> lanes layout (C, C, B); bf16 lane quantizes here
        re = jnp.real(R).astype(dt).reshape((-1, C, C)).transpose(1, 2, 0)
        im = jnp.imag(R).astype(dt).reshape((-1, C, C)).transpose(1, 2, 0)
        return re, im

    Sr, Si = planes(Rss)
    Nr, Ni = planes(Rnn)
    B = Sr.shape[-1]
    n_tiles = -(-B // tile)
    pad = n_tiles * tile - B
    if pad:
        # identity-pencil padding keeps the padded solves well-conditioned
        eye = jnp.broadcast_to(jnp.eye(C, dtype=dt)[:, :, None], (C, C, pad))
        zero = jnp.zeros((C, C, pad), dt)
        Sr = jnp.concatenate([Sr, eye], axis=-1)
        Si = jnp.concatenate([Si, zero], axis=-1)
        Nr = jnp.concatenate([Nr, eye], axis=-1)
        Ni = jnp.concatenate([Ni, zero], axis=-1)
    mu_lane = jnp.full((1, n_tiles * tile), mu, jnp.float32)
    eps = float(np.finfo(np.float32).tiny ** 0.5)

    from disco_tpu.beam.filters import DIAG_LOADING, EIG_CEIL, EIG_FLOOR

    wr, wi, t1r, t1i = pl.pallas_call(
        partial(_mwf_kernel, C=C, sweeps=sweeps, eps=eps,
                loading=float(DIAG_LOADING),
                lam_floor=np.float32(EIG_FLOOR), lam_ceil=np.float32(EIG_CEIL)),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((C, tile), lambda i: (0, i)),
            pl.BlockSpec((C, tile), lambda i: (0, i)),
            pl.BlockSpec((C, tile), lambda i: (0, i)),
            pl.BlockSpec((C, tile), lambda i: (0, i)),
        ],
        out_shape=[jax.ShapeDtypeStruct((C, n_tiles * tile), jnp.float32)] * 4,
        interpret=interpret,
    )(Sr, Si, Nr, Ni, mu_lane)
    W = jax.lax.complex(wr, wi)[:, :B].transpose(1, 0).reshape(batch_shape + (C,))
    t1 = jax.lax.complex(t1r, t1i)[:, :B].transpose(1, 0).reshape(batch_shape + (C,))
    return W, t1


def rank1_gevd_fused(Rss, Rnn, mu: float = 1.0, impl: str = "auto",
                     sweeps: int | None = None, precision: str = "f32",
                     sanitize: bool = True, interpret: bool | None = None):
    """The fused rank-1 GEVD-MWF solve with implementation dispatch — the
    ``solver='fused*'`` target of :func:`disco_tpu.beam.filters.rank1_gevd`
    (reference internal_formulas.py:56-73 at rank 1).

    ``impl`` resolves through the shared ``ops.resolve`` policy
    (:func:`resolve_mwf_impl`: 'auto' = pallas on real TPUs, xla
    elsewhere, :data:`MWF_IMPL_ENV` override); ``interpret=None`` resolves
    to the pallas interpreter off-TPU.  ``sanitize`` matches
    ``gevd_mwf``'s degenerate-bin policy: non-finite filters (near-singular
    pencils past the diagonal loading) fall back to the e1 pass-through
    selector; ``sanitize=False`` surfaces them for callers with their own
    fallback (the streaming ffill hold).
    """
    impl = resolve_mwf_impl(impl)
    if impl == "pallas":
        if interpret is None:
            from disco_tpu.utils.backend import is_tpu

            interpret = not is_tpu()
        W, t1 = fused_mwf_pallas(Rss, Rnn, mu=mu, sweeps=sweeps,
                                 interpret=interpret, precision=precision)
    else:
        W, t1 = fused_mwf_xla(Rss, Rnn, mu=mu, sweeps=sweeps,
                              precision=precision)
    if not sanitize:
        return W, t1
    e1 = jnp.zeros_like(W).at[..., 0].set(1.0)
    ok = (jnp.isfinite(W.real) & jnp.isfinite(W.imag)).all(-1, keepdims=True)
    return jnp.where(ok, W, e1), jnp.where(ok, t1, e1)
