"""TPU-native STFT kernels.

The analysis filterbank is the second-hottest op of the whole framework
(SURVEY.md §3 hot-loop summary: ~60 librosa STFT/ISTFT calls per clip in the
reference).  On TPU the rFFT lowering is not the fast path for a 512-point
transform — the MXU is.  Two implementations:

* :func:`stft_matmul` — XLA formulation: the 50%-overlap framing is two
  shifted views of the hop-chunked signal (no gather), and the DFT is two
  (T, 512) @ (512, 257) real matmuls against precomputed cos/sin matrices
  with ``precision='float32'``.  ~1.5x faster than ``jnp.fft.rfft`` on TPU
  at 3e-7 relative error (exact integer-mod angles).
* :func:`stft_pallas` — the same computation as one fused pallas kernel:
  signal chunks are DMA'd HBM->VMEM per frame tile, frames/window/DFT all
  happen in VMEM, and the framed intermediate never exists in HBM.

``disco_tpu.core.dsp.stft`` dispatches to the matmul path on TPU backends
automatically; the pallas kernel is opt-in (``impl='pallas'``).
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

N_FFT, N_HOP = 512, 256


@functools.lru_cache(maxsize=8)
def dft_matrices(n_fft: int = N_FFT):
    """(n_fft, n_fft//2+1) cos/sin DFT matrices with exact integer-mod
    angles (float64 host precompute, cast to f32).  Returned as NUMPY so the
    cache never holds trace-bound constants (safe to call under any jit)."""
    k = np.arange(n_fft // 2 + 1, dtype=np.int64)[:, None]
    n = np.arange(n_fft, dtype=np.int64)[None, :]
    ang = -2.0 * np.pi * ((k * n) % n_fft) / n_fft
    return np.cos(ang).T.astype(np.float32), np.sin(ang).T.astype(np.float32)


def _hann(n_fft, dtype=jnp.float32):
    k = jnp.arange(n_fft, dtype=dtype)
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * k / n_fft)


def _chunked(x, n_fft, hop):
    """Reflect-pad for a centered STFT and return (chunks (B, T+1, hop),
    n_frames, batch_shape).  Requires hop == n_fft // 2 (the framework's
    512/256 convention): frame t is then [chunk_t ‖ chunk_{t+1}]."""
    assert n_fft == 2 * hop, "matmul/pallas STFT assumes 50% overlap (n_fft == 2*hop)"
    x = jnp.asarray(x)
    pad = n_fft // 2
    bs = x.shape[:-1]
    L = x.shape[-1]
    xp = jnp.pad(x.reshape((-1, L)), ((0, 0), (pad, pad)), mode="reflect")
    n_frames = 1 + (xp.shape[-1] - n_fft) // hop
    A = xp[:, : (n_frames + 1) * hop].reshape(xp.shape[0], -1, hop)
    return A, n_frames, bs


@partial(jax.jit, static_argnames=("n_fft", "hop"))
def stft_matmul(x: jnp.ndarray, n_fft: int = N_FFT, hop: int = N_HOP) -> jnp.ndarray:
    """Centered STFT as two MXU matmuls (see module docstring).  Identical
    conventions and output layout to ``disco_tpu.core.dsp.stft``."""
    A, n_frames, bs = _chunked(x, n_fft, hop)
    frames = jnp.concatenate([A[:, :-1], A[:, 1:]], axis=-1)  # (B, T, n_fft)
    wf = frames * _hann(n_fft, frames.dtype)
    Dre, Dim = (jnp.asarray(d) for d in dft_matrices(n_fft))
    spec = jax.lax.complex(
        jnp.matmul(wf, Dre, precision="float32"),
        jnp.matmul(wf, Dim, precision="float32"),
    )
    return jnp.swapaxes(spec, -1, -2).reshape(bs + (n_fft // 2 + 1, n_frames))


# --------------------------------------------------------------- pallas path
def _stft_kernel(a0_ref, a1_ref, dre_ref, dim_ref, win_ref, re_ref, im_ref):
    """One (batch, frame-tile) program: frames assembled from the two
    shifted chunk views in VMEM, windowed, DFT'd on the MXU."""
    frames = jnp.concatenate([a0_ref[0], a1_ref[0]], axis=-1)  # (TILE_T, n_fft)
    wf = frames * win_ref[:]
    re_ref[0] = jnp.dot(wf, dre_ref[:], precision="float32", preferred_element_type=jnp.float32)
    im_ref[0] = jnp.dot(wf, dim_ref[:], precision="float32", preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("n_fft", "hop", "tile_t", "interpret"))
def stft_pallas(
    x: jnp.ndarray,
    n_fft: int = N_FFT,
    hop: int = N_HOP,
    tile_t: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused pallas STFT (frame + window + DFT in VMEM, grid over
    (batch, frame tiles)).  Same output as :func:`stft_matmul`.

    The framed (B, T, 512) intermediate never touches HBM: each grid step
    reads a (tile_t + 1, hop) chunk strip and writes (tile_t, 257) re/im.
    ``interpret=True`` runs the kernel in the pallas interpreter (CPU
    correctness tests).
    """
    from jax.experimental import pallas as pl

    A, n_frames, bs = _chunked(x, n_fft, hop)
    B = A.shape[0]
    n_freq = n_fft // 2 + 1
    # pad frame count to a tile multiple; the two 50%-shifted chunk views
    # (frame t = [chunk_t ‖ chunk_{t+1}]) are passed separately because
    # BlockSpec index maps address whole blocks (no overlapping strips).
    n_tiles = -(-n_frames // tile_t)
    rows_needed = n_tiles * tile_t + 1
    A = jnp.pad(A, ((0, 0), (0, rows_needed - A.shape[1]), (0, 0)))
    A0 = A[:, :-1]
    A1 = A[:, 1:]
    Dre, Dim = (jnp.asarray(d) for d in dft_matrices(n_fft))
    win = _hann(n_fft)

    re, im = pl.pallas_call(
        _stft_kernel,
        grid=(B, n_tiles),
        in_specs=[
            pl.BlockSpec((1, tile_t, hop), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, tile_t, hop), lambda b, t: (b, t, 0)),
            pl.BlockSpec((n_fft, n_freq), lambda b, t: (0, 0)),
            pl.BlockSpec((n_fft, n_freq), lambda b, t: (0, 0)),
            pl.BlockSpec((n_fft,), lambda b, t: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile_t, n_freq), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, tile_t, n_freq), lambda b, t: (b, t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, n_tiles * tile_t, n_freq), jnp.float32),
            jax.ShapeDtypeStruct((B, n_tiles * tile_t, n_freq), jnp.float32),
        ],
        interpret=interpret,
    )(A0, A1, Dre, Dim, win)
    spec = jax.lax.complex(re, im)[:, :n_frames]
    return jnp.swapaxes(spec, -1, -2).reshape(bs + (n_freq, n_frames))


@functools.lru_cache(maxsize=8)
def idft_matrices(n_fft: int = N_FFT):
    """(n_fft//2+1, n_fft) inverse-rDFT matrices: ``x = re @ A + im @ B``
    for a conjugate-symmetric spectrum (exact integer-mod angles, float64
    host precompute).  Returned as numpy (see dft_matrices)."""
    assert n_fft % 2 == 0, "idft_matrices assumes even n_fft (real Nyquist bin)"
    n_freq = n_fft // 2 + 1
    k = np.arange(n_freq, dtype=np.int64)[:, None]
    n = np.arange(n_fft, dtype=np.int64)[None, :]
    ang = 2.0 * np.pi * ((k * n) % n_fft) / n_fft
    # weights: DC and Nyquist count once, middle bins twice (conj symmetry)
    w = np.full((n_freq, 1), 2.0)
    w[0] = w[-1] = 1.0
    A = (w * np.cos(ang) / n_fft).astype(np.float32)
    B = (-w * np.sin(ang) / n_fft).astype(np.float32)
    return A, B


@partial(jax.jit, static_argnames=("length", "n_fft", "hop"))
def istft_matmul(spec: jnp.ndarray, length: int, n_fft: int = N_FFT, hop: int = N_HOP) -> jnp.ndarray:
    """Inverse centered STFT as two MXU matmuls + the 50%-overlap chunk-add
    (no scatter): the synthesis dual of :func:`stft_matmul`, with squared-
    window OLA normalization identical to ``disco_tpu.core.dsp.istft``.
    """
    assert n_fft == 2 * hop, "matmul ISTFT assumes 50% overlap (n_fft == 2*hop)"
    spec = jnp.asarray(spec)
    batch_shape = spec.shape[:-2]
    n_freq, n_frames = spec.shape[-2:]
    assert n_freq == n_fft // 2 + 1, (n_freq, n_fft)
    pad = n_fft // 2

    A, B = (jnp.asarray(d) for d in idft_matrices(n_fft))
    sp = jnp.swapaxes(spec.reshape((-1, n_freq, n_frames)), -1, -2)  # (B, T, F)
    frames = (
        jnp.matmul(jnp.real(sp), A, precision="float32")
        + jnp.matmul(jnp.imag(sp), B, precision="float32")
    )  # (B, T, n_fft)
    win = _hann(n_fft, frames.dtype)
    frames = frames * win

    # OLA via the chunk trick: output chunk c = frames[c][:hop] + frames[c-1][hop:]
    first = frames[..., :hop]  # (B, T, hop)
    second = frames[..., hop:]
    total_chunks = n_frames + 1
    y = jnp.zeros((frames.shape[0], total_chunks, hop), frames.dtype)
    y = y.at[:, :n_frames].add(first)
    y = y.at[:, 1:].add(second)
    y = y.reshape(frames.shape[0], total_chunks * hop)

    # squared-window normalization (identical accumulation in chunk form)
    w2_first = (win**2)[:hop]
    w2_second = (win**2)[hop:]
    wss = jnp.zeros(total_chunks * hop, frames.dtype)
    wss = wss.reshape(total_chunks, hop).at[:n_frames].add(w2_first).at[1:].add(w2_second).reshape(-1)
    tiny = jnp.finfo(frames.dtype).tiny
    y = jnp.where(wss > tiny, y / jnp.where(wss > tiny, wss, 1.0), y)

    y = y[:, pad : pad + length]
    out_pad = length - y.shape[-1]
    if out_pad > 0:
        y = jnp.pad(y, ((0, 0), (0, out_pad)))
    return y.reshape(batch_shape + (length,)).astype(jnp.float32)
