"""TPU-native STFT kernels.

The analysis filterbank is the second-hottest op of the whole framework
(SURVEY.md §3 hot-loop summary: ~60 librosa STFT/ISTFT calls per clip in the
reference).  On TPU the rFFT lowering is not the fast path for a 512-point
transform — the MXU is.  Two implementations:

* :func:`stft_matmul` — XLA formulation: the 50%-overlap framing is two
  shifted views of the hop-chunked signal (no gather), and the DFT is two
  (T, 512) @ (512, 257) real matmuls against precomputed cos/sin matrices
  with ``precision='float32'``.  ~1.5x faster than ``jnp.fft.rfft`` on TPU
  at 3e-7 relative error (exact integer-mod angles).
* :func:`stft_pallas` — the same computation as one fused pallas kernel:
  signal chunks are DMA'd HBM->VMEM per frame tile, frames/window/DFT all
  happen in VMEM, and the framed intermediate never exists in HBM.  With
  ``with_mag=True`` the kernel ALSO emits the magnitude spectrogram,
  computed from the re/im planes while they are still VMEM-resident — the
  separate ``jnp.abs`` pass (one more HBM read of the full spec) that the
  mask stage otherwise pays never happens.

``disco_tpu.core.dsp.stft`` dispatches to the matmul path on TPU backends
automatically; the pallas kernel is opt-in (``impl='pallas'``).  The fused
spec+magnitude entry point :func:`stft_with_mag` has its own
``resolve_stft_impl`` auto/xla/pallas seam (mirroring
``ops.cov_ops.resolve_cov_impl``; ``DISCO_TPU_STFT_IMPL`` env escape
hatch) plus the ``precision`` lane of :mod:`disco_tpu.ops.resolve`: under
``'bf16'`` the DFT matmuls run with bf16 operands and float32 accumulators
(``preferred_element_type``) — opt-in, gated by the documented looser
oracle tolerances in tests/test_ops.py.
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from disco_tpu.ops.resolve import compute_dtype, resolve_impl, resolve_precision

N_FFT, N_HOP = 512, 256

#: Environment escape hatch for the fused STFT kernel selection:
#: ``DISCO_TPU_STFT_IMPL=xla`` (or ``pallas``) overrides the ``'auto'``
#: resolution everywhere callers left ``stft_impl`` at its default.
STFT_IMPL_ENV = "DISCO_TPU_STFT_IMPL"


def resolve_stft_impl(impl: str = "auto") -> str:
    """Resolve a ``stft_impl`` knob to a concrete kernel choice — the STFT
    twin of ``ops.cov_ops.resolve_cov_impl``, backed by the SAME shared
    policy (:func:`disco_tpu.ops.resolve.resolve_impl`): ``'auto'`` is the
    fused pallas kernel on real TPU backends and the XLA formulation
    elsewhere, with :data:`STFT_IMPL_ENV` as the operator escape hatch.

    No reference counterpart: kernel selection is a TPU-port concern — the
    reference computes every STFT one way only (librosa, tango.py:335).
    """
    return resolve_impl(impl, STFT_IMPL_ENV)


@functools.lru_cache(maxsize=8)
def dft_matrices(n_fft: int = N_FFT):
    """(n_fft, n_fft//2+1) cos/sin DFT matrices with exact integer-mod
    angles (float64 host precompute, cast to f32).  Returned as NUMPY so the
    cache never holds trace-bound constants (safe to call under any jit)."""
    k = np.arange(n_fft // 2 + 1, dtype=np.int64)[:, None]
    n = np.arange(n_fft, dtype=np.int64)[None, :]
    ang = -2.0 * np.pi * ((k * n) % n_fft) / n_fft
    return np.cos(ang).T.astype(np.float32), np.sin(ang).T.astype(np.float32)


def _hann(n_fft, dtype=jnp.float32):
    k = jnp.arange(n_fft, dtype=dtype)
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * k / n_fft)


def _chunked(x, n_fft, hop):
    """Reflect-pad for a centered STFT and return (chunks (B, T+1, hop),
    n_frames, batch_shape).  Requires hop == n_fft // 2 (the framework's
    512/256 convention): frame t is then [chunk_t ‖ chunk_{t+1}]."""
    assert n_fft == 2 * hop, "matmul/pallas STFT assumes 50% overlap (n_fft == 2*hop)"
    x = jnp.asarray(x)
    pad = n_fft // 2
    bs = x.shape[:-1]
    L = x.shape[-1]
    xp = jnp.pad(x.reshape((-1, L)), ((0, 0), (pad, pad)), mode="reflect")
    n_frames = 1 + (xp.shape[-1] - n_fft) // hop
    A = xp[:, : (n_frames + 1) * hop].reshape(xp.shape[0], -1, hop)
    return A, n_frames, bs


@partial(jax.jit, static_argnames=("n_fft", "hop", "precision"))
def stft_matmul(
    x: jnp.ndarray, n_fft: int = N_FFT, hop: int = N_HOP, precision: str = "f32"
) -> jnp.ndarray:
    """Centered STFT as two MXU matmuls (see module docstring).  Identical
    conventions and output layout to ``disco_tpu.core.dsp.stft``.
    ``precision='bf16'`` runs the DFT matmuls with bf16 operands and f32
    accumulators (the opt-in compute lane; default unchanged)."""
    A, n_frames, bs = _chunked(x, n_fft, hop)
    frames = jnp.concatenate([A[:, :-1], A[:, 1:]], axis=-1)  # (B, T, n_fft)
    wf = frames * _hann(n_fft, frames.dtype)
    Dre, Dim = (jnp.asarray(d) for d in dft_matrices(n_fft))
    if resolve_precision(precision) == "bf16":
        dt = compute_dtype(precision)
        spec = jax.lax.complex(
            jnp.matmul(wf.astype(dt), Dre.astype(dt), preferred_element_type=jnp.float32),
            jnp.matmul(wf.astype(dt), Dim.astype(dt), preferred_element_type=jnp.float32),
        )
    else:
        spec = jax.lax.complex(
            jnp.matmul(wf, Dre, precision="float32"),
            jnp.matmul(wf, Dim, precision="float32"),
        )
    return jnp.swapaxes(spec, -1, -2).reshape(bs + (n_fft // 2 + 1, n_frames))


# --------------------------------------------------------------- pallas path
def _stft_kernel(a0_ref, a1_ref, dre_ref, dim_ref, win_ref, re_ref, im_ref, *rest):
    """One (batch, frame-tile) program: frames assembled from the two
    shifted chunk views in VMEM, windowed, DFT'd on the MXU.  The chunk
    views and DFT matrices arrive pre-cast to the precision lane's compute
    dtype (bf16 under ``precision='bf16'``); the dots accumulate in f32
    either way.  With a trailing ``mag_ref`` the magnitude is computed from
    the re/im tiles while they are still VMEM-resident and stored as a
    third output — the downstream ``jnp.abs`` HBM pass never happens."""
    frames = jnp.concatenate([a0_ref[0], a1_ref[0]], axis=-1)  # (TILE_T, n_fft)
    wf = frames * win_ref[:].astype(frames.dtype)
    # f32 lane: pinned float32 MXU passes (the pre-fusion program, bit-
    # compatible); bf16 lane: operand dtype IS the precision request, the
    # preferred_element_type keeps the accumulator f32
    kw = (dict(precision="float32") if frames.dtype == jnp.float32 else {})
    re = jnp.dot(wf, dre_ref[:], preferred_element_type=jnp.float32, **kw)
    im = jnp.dot(wf, dim_ref[:], preferred_element_type=jnp.float32, **kw)
    re_ref[0] = re
    im_ref[0] = im
    if rest:
        rest[0][0] = jnp.sqrt(re * re + im * im)


@partial(jax.jit, static_argnames=("n_fft", "hop", "tile_t", "interpret",
                                   "precision", "with_mag"))
def stft_pallas(
    x: jnp.ndarray,
    n_fft: int = N_FFT,
    hop: int = N_HOP,
    tile_t: int = 128,
    interpret: bool = False,
    precision: str = "f32",
    with_mag: bool = False,
):
    """Fused pallas STFT (frame + window + DFT in VMEM, grid over
    (batch, frame tiles)).  Same output as :func:`stft_matmul`.

    The framed (B, T, 512) intermediate never touches HBM: each grid step
    reads a (tile_t + 1, hop) chunk strip and writes (tile_t, 257) re/im.
    ``interpret=True`` runs the kernel in the pallas interpreter (CPU
    correctness tests).  ``with_mag=True`` additionally emits the magnitude
    spectrogram (computed in VMEM — see :func:`_stft_kernel`) and returns
    ``(spec, mag)``; ``precision='bf16'`` feeds the DFT dots bf16 operands
    with f32 accumulation.
    """
    from jax.experimental import pallas as pl

    A, n_frames, bs = _chunked(x, n_fft, hop)
    B = A.shape[0]
    n_freq = n_fft // 2 + 1
    # pad frame count to a tile multiple; the two 50%-shifted chunk views
    # (frame t = [chunk_t ‖ chunk_{t+1}]) are passed separately because
    # BlockSpec index maps address whole blocks (no overlapping strips).
    n_tiles = -(-n_frames // tile_t)
    rows_needed = n_tiles * tile_t + 1
    A = jnp.pad(A, ((0, 0), (0, rows_needed - A.shape[1]), (0, 0)))
    dt = compute_dtype(precision)
    A0 = A[:, :-1].astype(dt)
    A1 = A[:, 1:].astype(dt)
    Dre, Dim = (jnp.asarray(d).astype(dt) for d in dft_matrices(n_fft))
    win = _hann(n_fft)

    n_out = 3 if with_mag else 2
    out = pl.pallas_call(
        _stft_kernel,
        grid=(B, n_tiles),
        in_specs=[
            pl.BlockSpec((1, tile_t, hop), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, tile_t, hop), lambda b, t: (b, t, 0)),
            pl.BlockSpec((n_fft, n_freq), lambda b, t: (0, 0)),
            pl.BlockSpec((n_fft, n_freq), lambda b, t: (0, 0)),
            pl.BlockSpec((n_fft,), lambda b, t: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile_t, n_freq), lambda b, t: (b, t, 0)),
        ] * n_out,
        out_shape=[
            jax.ShapeDtypeStruct((B, n_tiles * tile_t, n_freq), jnp.float32),
        ] * n_out,
        interpret=interpret,
    )(A0, A1, Dre, Dim, win)
    re, im = out[0], out[1]
    spec = jax.lax.complex(re, im)[:, :n_frames]
    spec = jnp.swapaxes(spec, -1, -2).reshape(bs + (n_freq, n_frames))
    if not with_mag:
        return spec
    mag = jnp.swapaxes(out[2][:, :n_frames], -1, -2).reshape(bs + (n_freq, n_frames))
    return spec, mag


def stft_with_mag(
    x: jnp.ndarray,
    n_fft: int = N_FFT,
    hop: int = N_HOP,
    impl: str = "auto",
    precision: str = "f32",
    interpret: bool | None = None,
):
    """Fused STFT returning ``(spec, mag)`` for ALL leading-axis channels in
    one pass — the analysis stage of the enhancement hot path (the three
    y/s/n streams stack on a leading axis and transform together), emitting
    both the complex spec and the magnitude the mask stage consumes so the
    separate ``stft`` + ``jnp.abs`` round-trips disappear.

    Implementation seam (``resolve_stft_impl``, mirroring
    ``ops.cov_ops.resolve_cov_impl``; ``DISCO_TPU_STFT_IMPL`` env escape
    hatch):

    * 'xla': ``disco_tpu.core.dsp.stft``'s backend-auto path (rFFT off-TPU,
      MXU matmul on TPU — bit-identical to the pre-fusion pipeline at the
      default precision) + ``jnp.abs``; XLA fuses the abs when traced
      inside a larger program.
    * 'pallas': :func:`stft_pallas` ``with_mag=True`` — framing, window,
      DFT and magnitude all in VMEM; the framed intermediate and the
      spec re-read for ``abs`` never touch HBM.

    ``precision='bf16'`` (ops.resolve lane) runs the DFT matmuls with bf16
    operands and f32 accumulators; on the 'xla' lane this selects the
    matmul formulation (rFFT has no bf16 form).

    No reference counterpart: the reference computes STFTs and magnitudes
    in separate per-channel librosa calls (tango.py:335-337) — fusing them
    is a TPU-port concern.
    """
    impl = resolve_stft_impl(impl)
    precision = resolve_precision(precision)
    if impl == "pallas":
        if interpret is None:
            from disco_tpu.utils.backend import is_tpu

            interpret = not is_tpu()
        return stft_pallas(x, n_fft, hop, interpret=interpret,
                           precision=precision, with_mag=True)
    spec = stft_fused(x, n_fft, hop, impl=impl, precision=precision)
    return spec, jnp.abs(spec)


def stft_fused(
    x: jnp.ndarray,
    n_fft: int = N_FFT,
    hop: int = N_HOP,
    impl: str = "auto",
    precision: str = "f32",
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Spec-only twin of :func:`stft_with_mag` — the same
    ``resolve_stft_impl``/``precision`` seams for callers whose masks are
    computed in-program (the corpus batch runners compute oracle masks
    inside the jitted chunk program, so emitting a magnitude here would be
    a dead output).

    No reference counterpart (see :func:`stft_with_mag`).
    """
    impl = resolve_stft_impl(impl)
    precision = resolve_precision(precision)
    if impl == "pallas":
        if interpret is None:
            from disco_tpu.utils.backend import is_tpu

            interpret = not is_tpu()
        return stft_pallas(x, n_fft, hop, interpret=interpret, precision=precision)
    if precision == "bf16":
        return stft_matmul(x, n_fft, hop, precision=precision)
    from disco_tpu.core.dsp import stft

    return stft(x, n_fft, hop)


@functools.lru_cache(maxsize=8)
def idft_matrices(n_fft: int = N_FFT):
    """(n_fft//2+1, n_fft) inverse-rDFT matrices: ``x = re @ A + im @ B``
    for a conjugate-symmetric spectrum (exact integer-mod angles, float64
    host precompute).  Returned as numpy (see dft_matrices)."""
    assert n_fft % 2 == 0, "idft_matrices assumes even n_fft (real Nyquist bin)"
    n_freq = n_fft // 2 + 1
    k = np.arange(n_freq, dtype=np.int64)[:, None]
    n = np.arange(n_fft, dtype=np.int64)[None, :]
    ang = 2.0 * np.pi * ((k * n) % n_fft) / n_fft
    # weights: DC and Nyquist count once, middle bins twice (conj symmetry)
    w = np.full((n_freq, 1), 2.0)
    w[0] = w[-1] = 1.0
    A = (w * np.cos(ang) / n_fft).astype(np.float32)
    B = (-w * np.sin(ang) / n_fft).astype(np.float32)
    return A, B


@partial(jax.jit, static_argnames=("length", "n_fft", "hop"))
def istft_matmul(spec: jnp.ndarray, length: int, n_fft: int = N_FFT, hop: int = N_HOP) -> jnp.ndarray:
    """Inverse centered STFT as two MXU matmuls + the 50%-overlap chunk-add
    (no scatter): the synthesis dual of :func:`stft_matmul`, with squared-
    window OLA normalization identical to ``disco_tpu.core.dsp.istft``.
    """
    assert n_fft == 2 * hop, "matmul ISTFT assumes 50% overlap (n_fft == 2*hop)"
    spec = jnp.asarray(spec)
    batch_shape = spec.shape[:-2]
    n_freq, n_frames = spec.shape[-2:]
    assert n_freq == n_fft // 2 + 1, (n_freq, n_fft)
    pad = n_fft // 2

    A, B = (jnp.asarray(d) for d in idft_matrices(n_fft))
    sp = jnp.swapaxes(spec.reshape((-1, n_freq, n_frames)), -1, -2)  # (B, T, F)
    frames = (
        jnp.matmul(jnp.real(sp), A, precision="float32")
        + jnp.matmul(jnp.imag(sp), B, precision="float32")
    )  # (B, T, n_fft)
    win = _hann(n_fft, frames.dtype)
    frames = frames * win

    # OLA via the chunk trick: output chunk c = frames[c][:hop] + frames[c-1][hop:]
    first = frames[..., :hop]  # (B, T, hop)
    second = frames[..., hop:]
    total_chunks = n_frames + 1
    y = jnp.zeros((frames.shape[0], total_chunks, hop), frames.dtype)
    y = y.at[:, :n_frames].add(first)
    y = y.at[:, 1:].add(second)
    y = y.reshape(frames.shape[0], total_chunks * hop)

    # squared-window normalization (identical accumulation in chunk form)
    w2_first = (win**2)[:hop]
    w2_second = (win**2)[hop:]
    wss = jnp.zeros(total_chunks * hop, frames.dtype)
    wss = wss.reshape(total_chunks, hop).at[:n_frames].add(w2_first).at[1:].add(w2_second).reshape(-1)
    tiny = jnp.finfo(frames.dtype).tiny
    y = jnp.where(wss > tiny, y / jnp.where(wss > tiny, wss, 1.0), y)

    y = y[:, pad : pad + length]
    out_pad = length - y.shape[-1]
    if out_pad > 0:
        y = jnp.pad(y, ((0, 0), (0, out_pad)))
    return y.reshape(batch_shape + (length,)).astype(jnp.float32)
