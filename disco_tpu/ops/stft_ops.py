"""TPU-native STFT kernels.

The analysis filterbank is the second-hottest op of the whole framework
(SURVEY.md §3 hot-loop summary: ~60 librosa STFT/ISTFT calls per clip in the
reference).  On TPU the rFFT lowering is not the fast path for a 512-point
transform — the MXU is.  Two implementations:

* :func:`stft_matmul` — XLA formulation: the 50%-overlap framing is two
  shifted views of the hop-chunked signal (no gather), and the DFT is two
  (T, 512) @ (512, 257) real matmuls against precomputed cos/sin matrices
  with ``precision='float32'``.  ~1.5x faster than ``jnp.fft.rfft`` on TPU
  at 3e-7 relative error (exact integer-mod angles).
* :func:`stft_pallas` — the same computation as one fused pallas kernel:
  signal chunks are DMA'd HBM->VMEM per frame tile, frames/window/DFT all
  happen in VMEM, and the framed intermediate never exists in HBM.

``disco_tpu.core.dsp.stft`` dispatches to the matmul path on TPU backends
automatically; the pallas kernel is opt-in (``impl='pallas'``).
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

N_FFT, N_HOP = 512, 256


@functools.lru_cache(maxsize=8)
def dft_matrices(n_fft: int = N_FFT):
    """(n_fft, n_fft//2+1) cos/sin DFT matrices with exact integer-mod
    angles (float64 host precompute, cast to f32).  Returned as NUMPY so the
    cache never holds trace-bound constants (safe to call under any jit)."""
    k = np.arange(n_fft // 2 + 1, dtype=np.int64)[:, None]
    n = np.arange(n_fft, dtype=np.int64)[None, :]
    ang = -2.0 * np.pi * ((k * n) % n_fft) / n_fft
    return np.cos(ang).T.astype(np.float32), np.sin(ang).T.astype(np.float32)


def _hann(n_fft, dtype=jnp.float32):
    k = jnp.arange(n_fft, dtype=dtype)
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * k / n_fft)


def _chunked(x, n_fft, hop):
    """Reflect-pad for a centered STFT and return (chunks (B, T+1, hop),
    n_frames, batch_shape).  Requires hop == n_fft // 2 (the framework's
    512/256 convention): frame t is then [chunk_t ‖ chunk_{t+1}]."""
    assert n_fft == 2 * hop, "matmul/pallas STFT assumes 50% overlap (n_fft == 2*hop)"
    x = jnp.asarray(x)
    pad = n_fft // 2
    bs = x.shape[:-1]
    L = x.shape[-1]
    xp = jnp.pad(x.reshape((-1, L)), ((0, 0), (pad, pad)), mode="reflect")
    n_frames = 1 + (xp.shape[-1] - n_fft) // hop
    A = xp[:, : (n_frames + 1) * hop].reshape(xp.shape[0], -1, hop)
    return A, n_frames, bs


@partial(jax.jit, static_argnames=("n_fft", "hop"))
def stft_matmul(x: jnp.ndarray, n_fft: int = N_FFT, hop: int = N_HOP) -> jnp.ndarray:
    """Centered STFT as two MXU matmuls (see module docstring).  Identical
    conventions and output layout to ``disco_tpu.core.dsp.stft``."""
    A, n_frames, bs = _chunked(x, n_fft, hop)
    frames = jnp.concatenate([A[:, :-1], A[:, 1:]], axis=-1)  # (B, T, n_fft)
    wf = frames * _hann(n_fft, frames.dtype)
    Dre, Dim = (jnp.asarray(d) for d in dft_matrices(n_fft))
    spec = jax.lax.complex(
        jnp.matmul(wf, Dre, precision="float32"),
        jnp.matmul(wf, Dim, precision="float32"),
    )
    return jnp.swapaxes(spec, -1, -2).reshape(bs + (n_fft // 2 + 1, n_frames))


# --------------------------------------------------------------- pallas path
def _stft_kernel(a0_ref, a1_ref, dre_ref, dim_ref, win_ref, re_ref, im_ref):
    """One (batch, frame-tile) program: frames assembled from the two
    shifted chunk views in VMEM, windowed, DFT'd on the MXU."""
    frames = jnp.concatenate([a0_ref[0], a1_ref[0]], axis=-1)  # (TILE_T, n_fft)
    wf = frames * win_ref[:]
    re_ref[0] = jnp.dot(wf, dre_ref[:], precision="float32", preferred_element_type=jnp.float32)
    im_ref[0] = jnp.dot(wf, dim_ref[:], precision="float32", preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("n_fft", "hop", "tile_t", "interpret"))
def stft_pallas(
    x: jnp.ndarray,
    n_fft: int = N_FFT,
    hop: int = N_HOP,
    tile_t: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused pallas STFT (frame + window + DFT in VMEM, grid over
    (batch, frame tiles)).  Same output as :func:`stft_matmul`.

    The framed (B, T, 512) intermediate never touches HBM: each grid step
    reads a (tile_t + 1, hop) chunk strip and writes (tile_t, 257) re/im.
    ``interpret=True`` runs the kernel in the pallas interpreter (CPU
    correctness tests).
    """
    from jax.experimental import pallas as pl

    A, n_frames, bs = _chunked(x, n_fft, hop)
    B = A.shape[0]
    n_freq = n_fft // 2 + 1
    # pad frame count to a tile multiple; the two 50%-shifted chunk views
    # (frame t = [chunk_t ‖ chunk_{t+1}]) are passed separately because
    # BlockSpec index maps address whole blocks (no overlapping strips).
    n_tiles = -(-n_frames // tile_t)
    rows_needed = n_tiles * tile_t + 1
    A = jnp.pad(A, ((0, 0), (0, rows_needed - A.shape[1]), (0, 0)))
    A0 = A[:, :-1]
    A1 = A[:, 1:]
    Dre, Dim = (jnp.asarray(d) for d in dft_matrices(n_fft))
    win = _hann(n_fft)

    re, im = pl.pallas_call(
        _stft_kernel,
        grid=(B, n_tiles),
        in_specs=[
            pl.BlockSpec((1, tile_t, hop), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, tile_t, hop), lambda b, t: (b, t, 0)),
            pl.BlockSpec((n_fft, n_freq), lambda b, t: (0, 0)),
            pl.BlockSpec((n_fft, n_freq), lambda b, t: (0, 0)),
            pl.BlockSpec((n_fft,), lambda b, t: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile_t, n_freq), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, tile_t, n_freq), lambda b, t: (b, t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, n_tiles * tile_t, n_freq), jnp.float32),
            jax.ShapeDtypeStruct((B, n_tiles * tile_t, n_freq), jnp.float32),
        ],
        interpret=interpret,
    )(A0, A1, Dre, Dim, win)
    spec = jax.lax.complex(re, im)[:, :n_frames]
    return jnp.swapaxes(spec, -1, -2).reshape(bs + (n_freq, n_frames))
