"""Batched small-hermitian eigendecomposition via fixed-sweep cyclic Jacobi.

The GEVD filter bank solves ~(batch x node x 257) eigenproblems of tiny
hermitian matrices (C <= 16: mics-per-node, or mics + K-1 compressed
channels).  XLA's general ``eigh`` is the measured dominant cost of the
TANGO pipeline on TPU (262 of 289 ms per 16-clip batch — see README
roofline).  SURVEY.md §7 step 2 anticipated this: "consider a pallas
batched small-hermitian-eig kernel if vmap(eigh) underperforms".

Two implementations of the same algorithm:

* :func:`eigh_jacobi` — pure-XLA: a statically-unrolled cyclic-by-rows
  Jacobi sweep schedule.  Every batch element rotates the same (p, q) pair
  in lockstep, so each rotation is a handful of batched row/column
  elementwise updates (VPU work, no MXU, no data-dependent control flow) —
  exactly the shape XLA compiles well.  Runs on any backend.
* :func:`eigh_jacobi_pallas` — the same schedule as one pallas kernel:
  a tile of matrices is DMA'd HBM->VMEM once, ALL sweeps run in VMEM, and
  the eigenpairs are written back once — the intermediate rotation states
  never touch HBM.

Accuracy: Jacobi converges quadratically; at the pipeline's matrix sizes
(C <= 11: mics-per-node up to mics + K-1 stacked channels) ``sweeps=8``
reaches f32 machine-precision off-diagonal mass (tested against
``np.linalg.eigh`` in tests/test_eigh_ops.py).  Eigenvalues are returned
ASCENDING with their eigenvectors, matching ``jnp.linalg.eigh``.

Complex matrices are processed as re/im float32 planes internally (the
pallas TPU lowering has no complex support), with the rotation phase
carried explicitly.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _pairs(C: int):
    """Cyclic-by-rows sweep schedule: all (p, q), p < q."""
    return [(p, q) for p in range(C - 1) for q in range(p + 1, C)]


def default_sweeps(C: int) -> int:
    """Size-adaptive sweep count reaching f32 machine-precision residuals
    with margin (measured, round 3): C=4 converges by sweep 4 (residual
    5e-7), C=11 by sweep 6 (1e-6) with sweep 5 borderline (8e-4).  The
    pipeline's step-1 matrices are C<=5, so the adaptive default halves
    the dominant-stage rotation count there vs the old fixed 8."""
    if C <= 5:
        return 5
    if C <= 12:
        return 7
    return 8


def _rotation(app, aqq, apq_re, apq_im, eps):
    """Jacobi rotation (c, sigma_re, sigma_im) zeroing the (p, q) entry.

    All inputs are (..., ) real batches.  sigma = s * e^{i phi} with
    phi = arg(A[p, q]); identity rotation where |A[p, q]| < eps.
    """
    mag = jnp.sqrt(apq_re * apq_re + apq_im * apq_im)
    small = mag < eps
    mag_safe = jnp.where(small, 1.0, mag)
    # t = tan(theta): smaller root of t^2 + 2 tau t - 1 = 0,
    # tau = (aqq - app) / (2 |apq|)
    tau = (aqq - app) / (2.0 * mag_safe)
    rt = jnp.sqrt(1.0 + tau * tau)
    t = jnp.where(tau >= 0, 1.0 / (tau + rt), 1.0 / (tau - rt))
    c = 1.0 / jnp.sqrt(1.0 + t * t)
    s = t * c
    phase_re = apq_re / mag_safe
    phase_im = apq_im / mag_safe
    c = jnp.where(small, 1.0, c)
    sig_re = jnp.where(small, 0.0, s * phase_re)
    sig_im = jnp.where(small, 0.0, s * phase_im)
    return c, sig_re, sig_im


def _apply_rotation(Ar, Ai, Vr, Vi, p, q, eps):
    """One (p, q) rotation on re/im planes: A <- G^H A G, V <- V G.

    Shapes: (..., C, C).  p, q are static ints.  Scatter-free: rows and
    columns are READ with static slices but WRITTEN back as broadcast
    selects against constant one-hot masks over the whole (C, C) plane —
    the ``.at[].set()`` formulation lowers to scatter, which Mosaic lacks
    (round-3 solver_ab on real TPU: "Unimplemented primitive in Pallas TPU
    lowering ... scatter"), while masked selects are plain VPU work.  At
    the pipeline's C <= 11 the full-plane select costs about the same as
    the row write it replaces; XLA constant-folds the masks either way.
    """
    C = Ar.shape[-1]
    c, sr, si = _rotation(
        Ar[..., p, p], Ar[..., q, q], Ar[..., p, q], Ai[..., p, q], eps
    )
    c = c[..., None]
    sr = sr[..., None]
    si = si[..., None]

    # one-hot (C, C) masks from 2-D iota — NOT materialized numpy constants,
    # which pallas kernels may not capture (and 1-D iota has no Mosaic
    # lowering; jnp.eye is itself iota-based, hence kernel-safe)
    row_idx = jax.lax.broadcasted_iota(jnp.int32, (C, C), 0)
    col_idx = jax.lax.broadcasted_iota(jnp.int32, (C, C), 1)
    row_p, row_q = row_idx == p, row_idx == q
    col_p, col_q = col_idx == p, col_idx == q

    def put_rows(M, new_p, new_q):
        return jnp.where(row_p, new_p[..., None, :],
                         jnp.where(row_q, new_q[..., None, :], M))

    def put_cols(M, new_p, new_q):
        return jnp.where(col_p, new_p[..., :, None],
                         jnp.where(col_q, new_q[..., :, None], M))

    # rows: (G^H A)[p] = c A[p] - sigma A[q];  (G^H A)[q] = conj(sigma) A[p] + c A[q]
    rp_r, rp_i = Ar[..., p, :], Ai[..., p, :]
    rq_r, rq_i = Ar[..., q, :], Ai[..., q, :]
    new_p_r = c * rp_r - (sr * rq_r - si * rq_i)
    new_p_i = c * rp_i - (sr * rq_i + si * rq_r)
    new_q_r = (sr * rp_r + si * rp_i) + c * rq_r
    new_q_i = (sr * rp_i - si * rp_r) + c * rq_i
    Ar = put_rows(Ar, new_p_r, new_q_r)
    Ai = put_rows(Ai, new_p_i, new_q_i)

    # cols: (M G)[:, p] = c M[:, p] - conj(sigma) M[:, q];  (M G)[:, q] = sigma M[:, p] + c M[:, q]
    cp_r, cp_i = Ar[..., :, p], Ai[..., :, p]
    cq_r, cq_i = Ar[..., :, q], Ai[..., :, q]
    new_cp_r = c * cp_r - (sr * cq_r + si * cq_i)
    new_cp_i = c * cp_i - (sr * cq_i - si * cq_r)
    new_cq_r = (sr * cp_r - si * cp_i) + c * cq_r
    new_cq_i = (sr * cp_i + si * cp_r) + c * cq_i
    Ar = put_cols(Ar, new_cp_r, new_cq_r)
    Ai = put_cols(Ai, new_cp_i, new_cq_i)

    # eigenvectors: V <- V G (same column update)
    vp_r, vp_i = Vr[..., :, p], Vi[..., :, p]
    vq_r, vq_i = Vr[..., :, q], Vi[..., :, q]
    new_vp_r = c * vp_r - (sr * vq_r + si * vq_i)
    new_vp_i = c * vp_i - (sr * vq_i - si * vq_r)
    new_vq_r = (sr * vp_r - si * vp_i) + c * vq_r
    new_vq_i = (sr * vp_i + si * vp_r) + c * vq_i
    Vr = put_cols(Vr, new_vp_r, new_vq_r)
    Vi = put_cols(Vi, new_vp_i, new_vq_i)
    return Ar, Ai, Vr, Vi


def _sweep_body(Ar, Ai, Vr, Vi, C: int, sweeps: int, eps: float):
    """The sweep schedule shared by both backends: the (p, q) pair loop is
    statically unrolled (static slice indices — no gathers), the identical
    outer sweeps run under ``fori_loop`` to keep the program size at one
    sweep."""

    def one_sweep(_, carry):
        Ar, Ai, Vr, Vi = carry
        for p, q in _pairs(C):
            Ar, Ai, Vr, Vi = _apply_rotation(Ar, Ai, Vr, Vi, p, q, eps)
        return Ar, Ai, Vr, Vi

    return jax.lax.fori_loop(0, sweeps, one_sweep, (Ar, Ai, Vr, Vi))


def _sort_eigpairs(lam, Vr, Vi):
    """Ascending eigenvalue order + matching eigenvector columns."""
    order = jnp.argsort(lam, axis=-1)
    lam = jnp.take_along_axis(lam, order, axis=-1)
    Vr = jnp.take_along_axis(Vr, order[..., None, :], axis=-1)
    Vi = jnp.take_along_axis(Vi, order[..., None, :], axis=-1)
    return lam, Vr, Vi


def _sorted_eigpairs(Ar, Vr, Vi):
    """Ascending eigenvalues from the converged diagonal + matching
    eigenvector columns."""
    return _sort_eigpairs(jnp.diagonal(Ar, axis1=-2, axis2=-1), Vr, Vi)


@partial(jax.jit, static_argnames=("sweeps",))
def eigh_jacobi(A: jnp.ndarray, sweeps: int | None = None):
    """Batched hermitian eigendecomposition, ascending (like jnp.linalg.eigh).

    Args:
      A: (..., C, C) hermitian, complex64 or float32.
      sweeps: fixed cyclic sweep count; None -> :func:`default_sweeps` (size-
        adaptive, f32 machine precision with margin; 8 covers C <= 16).

    Returns:
      (lam, V): eigenvalues (..., C) float32 ascending, eigenvectors
      (..., C, C) with columns matching lam; complex64 V for complex input.
    """
    A = jnp.asarray(A)
    C = A.shape[-1]
    if sweeps is None:
        sweeps = default_sweeps(C)
    complex_in = jnp.iscomplexobj(A)
    Ar = jnp.real(A).astype(jnp.float32)
    Ai = jnp.imag(A).astype(jnp.float32) if complex_in else jnp.zeros_like(Ar)
    eye = jnp.broadcast_to(jnp.eye(C, dtype=jnp.float32), Ar.shape)
    Vr = eye
    Vi = jnp.zeros_like(Ar)
    eps = float(np.finfo(np.float32).tiny ** 0.5)

    Ar, Ai, Vr, Vi = _sweep_body(Ar, Ai, Vr, Vi, C, sweeps, eps)
    lam, Vr, Vi = _sorted_eigpairs(Ar, Vr, Vi)
    V = jax.lax.complex(Vr, Vi) if complex_in else Vr
    return lam, V


# --------------------------------------------------------------- pallas path
#
# Layout: BATCH IN LANES.  The round-4 kernel tiled as (tile, C, C) — the
# matrix dims sat in the (sublane, lane) position, so every rotation was a
# C<=11-lane op on a 128-lane VPU plus a relayout, and the real Mosaic
# compile ran away (round-5 probe: >9.5 min without finishing, while
# trivial kernels compile in ~1-3 s on the same attachment).  Here a block
# is (C, C, tile): matrix element (p, q) IS a full (tile,)-lane vector of
# batch elements, every rotation is a handful of natively-shaped
# elementwise (C, C, tile) / (tile,) VPU ops, and the p/q row-column
# writes are broadcast selects against LEADING-dim iota masks (no scatter,
# no lane-dim relayout).


def _lane_rotation(Ar, Ai, Vr, Vi, p, q, eps):
    """One (p, q) rotation in the lanes layout: arrays are (C, C, tile),
    matrix indices lead, the batch fills the lane dim.  A <- G^H A G,
    V <- V G, same math as :func:`_apply_rotation` (rows/cols swapped into
    leading dims)."""
    C = Ar.shape[0]
    c, sr, si = _rotation(Ar[p, p], Ar[q, q], Ar[p, q], Ai[p, q], eps)  # (tile,)

    row_p = jax.lax.broadcasted_iota(jnp.int32, (C, 1, 1), 0) == p
    row_q = jax.lax.broadcasted_iota(jnp.int32, (C, 1, 1), 0) == q
    col_p = jax.lax.broadcasted_iota(jnp.int32, (1, C, 1), 1) == p
    col_q = jax.lax.broadcasted_iota(jnp.int32, (1, C, 1), 1) == q

    def put_rows(M, new_p, new_q):
        return jnp.where(row_p, new_p[None], jnp.where(row_q, new_q[None], M))

    def put_cols(M, new_p, new_q):
        return jnp.where(col_p, new_p[:, None], jnp.where(col_q, new_q[:, None], M))

    # rows: (G^H A)[p] = c A[p] - sigma A[q];  (G^H A)[q] = conj(sigma) A[p] + c A[q]
    rp_r, rp_i = Ar[p], Ai[p]  # (C, tile)
    rq_r, rq_i = Ar[q], Ai[q]
    new_p_r = c * rp_r - (sr * rq_r - si * rq_i)
    new_p_i = c * rp_i - (sr * rq_i + si * rq_r)
    new_q_r = (sr * rp_r + si * rp_i) + c * rq_r
    new_q_i = (sr * rp_i - si * rp_r) + c * rq_i
    Ar = put_rows(Ar, new_p_r, new_q_r)
    Ai = put_rows(Ai, new_p_i, new_q_i)

    # cols: (M G)[:, p] = c M[:, p] - conj(sigma) M[:, q];  (M G)[:, q] = sigma M[:, p] + c M[:, q]
    cp_r, cp_i = Ar[:, p], Ai[:, p]  # (C, tile)
    cq_r, cq_i = Ar[:, q], Ai[:, q]
    new_cp_r = c * cp_r - (sr * cq_r + si * cq_i)
    new_cp_i = c * cp_i - (sr * cq_i - si * cq_r)
    new_cq_r = (sr * cp_r - si * cp_i) + c * cq_r
    new_cq_i = (sr * cp_i + si * cp_r) + c * cq_i
    Ar = put_cols(Ar, new_cp_r, new_cq_r)
    Ai = put_cols(Ai, new_cp_i, new_cq_i)

    # eigenvectors: V <- V G (same column update)
    vp_r, vp_i = Vr[:, p], Vi[:, p]
    vq_r, vq_i = Vr[:, q], Vi[:, q]
    new_vp_r = c * vp_r - (sr * vq_r + si * vq_i)
    new_vp_i = c * vp_i - (sr * vq_i - si * vq_r)
    new_vq_r = (sr * vp_r - si * vp_i) + c * vq_r
    new_vq_i = (sr * vp_i + si * vp_r) + c * vq_i
    Vr = put_cols(Vr, new_vp_r, new_vq_r)
    Vi = put_cols(Vi, new_vp_i, new_vq_i)
    return Ar, Ai, Vr, Vi


def _eigh_kernel(ar_ref, ai_ref, lam_ref, vr_ref, vi_ref, *, C, sweeps, eps):
    """One lane tile: all sweeps in VMEM, single HBM round-trip.  Emits the
    UNSORTED converged diagonal + eigenvector planes — the argsort/gather of
    ``_sorted_eigpairs`` has no Mosaic lowering, so ordering happens in
    plain XLA after the pallas_call.  The diagonal is extracted as a masked
    sublane reduction (``sum(A * I, axis=1)``) rather than ``jnp.diagonal``,
    whose gather Mosaic also lacks."""
    Ar = ar_ref[...]  # (C, C, tile)
    Ai = ai_ref[...]
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (C, C, 1), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (C, C, 1), 1)
    ).astype(jnp.float32)
    Vr = jnp.broadcast_to(eye, Ar.shape)
    Vi = jnp.zeros_like(Ar)

    def one_sweep(_, carry):
        Ar, Ai, Vr, Vi = carry
        for p, q in _pairs(C):
            Ar, Ai, Vr, Vi = _lane_rotation(Ar, Ai, Vr, Vi, p, q, eps)
        return Ar, Ai, Vr, Vi

    Ar, Ai, Vr, Vi = jax.lax.fori_loop(0, sweeps, one_sweep, (Ar, Ai, Vr, Vi))
    lam_ref[...] = jnp.sum(Ar * eye, axis=1)  # (C, tile)
    vr_ref[...] = Vr
    vi_ref[...] = Vi


@partial(jax.jit, static_argnames=("sweeps", "tile", "interpret"))
def eigh_jacobi_pallas(A: jnp.ndarray, sweeps: int | None = None, tile: int = 512, interpret: bool = False):
    """:func:`eigh_jacobi` as one fused pallas kernel (see module docstring
    and the batch-in-lanes layout note above).

    Args:
      A: (..., C, C) hermitian complex64/float32; batch dims are flattened
        into the LANE dim in tiles of ``tile`` matrices per grid step
        (``tile`` should be a multiple of 128).
      interpret: run in the pallas interpreter (CPU correctness tests).
    """
    from jax.experimental import pallas as pl

    A = jnp.asarray(A)
    C = A.shape[-1]
    if sweeps is None:
        sweeps = default_sweeps(C)
    batch_shape = A.shape[:-2]
    complex_in = jnp.iscomplexobj(A)
    # (B, C, C) -> lanes layout (C, C, B)
    Ar = jnp.real(A).astype(jnp.float32).reshape((-1, C, C)).transpose(1, 2, 0)
    Ai = (
        jnp.imag(A).astype(jnp.float32).reshape((-1, C, C)).transpose(1, 2, 0)
        if complex_in
        else jnp.zeros_like(Ar)
    )
    B = Ar.shape[-1]
    n_tiles = -(-B // tile)
    pad = n_tiles * tile - B
    if pad:
        # identity padding keeps the padded matrices well-conditioned
        eye = jnp.broadcast_to(jnp.eye(C, dtype=jnp.float32)[:, :, None], (C, C, pad))
        Ar = jnp.concatenate([Ar, eye], axis=-1)
        Ai = jnp.concatenate([Ai, jnp.zeros((C, C, pad), jnp.float32)], axis=-1)
    eps = float(np.finfo(np.float32).tiny ** 0.5)

    lam, Vr, Vi = pl.pallas_call(
        partial(_eigh_kernel, C=C, sweeps=sweeps, eps=eps),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((C, tile), lambda i: (0, i)),
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
            pl.BlockSpec((C, C, tile), lambda i: (0, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, n_tiles * tile), jnp.float32),
            jax.ShapeDtypeStruct((C, C, n_tiles * tile), jnp.float32),
            jax.ShapeDtypeStruct((C, C, n_tiles * tile), jnp.float32),
        ],
        interpret=interpret,
    )(Ar, Ai)
    # back to batch-major, then sort outside the kernel (no Mosaic sort)
    lam = lam[:, :B].transpose(1, 0)
    Vr = Vr[:, :, :B].transpose(2, 0, 1)
    Vi = Vi[:, :, :B].transpose(2, 0, 1)
    lam, Vr, Vi = _sort_eigpairs(lam, Vr, Vi)
    lam = lam.reshape(batch_shape + (C,))
    Vr = Vr.reshape(batch_shape + (C, C))
    Vi = Vi.reshape(batch_shape + (C, C))
    V = jax.lax.complex(Vr, Vi) if complex_in else Vr
    return lam, V
