"""Fused masked spatial covariances as one pallas kernel.

The TANGO steps estimate speech/noise covariances by materializing the
masked STFT copies ``s_hat = m * Y`` and ``n_hat = (1 - m) * Y`` and then
contracting each over frames (reference tango.py:347-364;
``beam.covariance.masked_covariances``).  On TPU that costs HBM round
trips for two full (C, F, T) complex intermediates — written once and
read back by the covariance matmuls — while the covariances themselves
are tiny ((F, C, C), ~100 KB).  The round-2 roofline named this traffic
the next lever after the eigensolve (VERDICT round-2 #3).

:func:`masked_cov_pallas` computes BOTH covariances in one kernel pass:
each grid step DMAs a (C, Fb, T) block of Y (planar re/im) plus its mask
block into VMEM once and emits only the (Fb, C, C) covariance blocks —
the masked copies never exist in HBM.  The math per frequency bin is

    Rss[c, d] = (1/T) sum_t m_t^2      Y[c, t] conj(Y[d, t])
    Rnn[c, d] = (1/T) sum_t (1 - m_t)^2 Y[c, t] conj(Y[d, t])

evaluated hermitian-triangle-wise as elementwise products + SUBLANE-axis
reductions over frames-major (T, Fb) planes (VPU work; no tiny-matmul MXU
padding waste) — see ``_cov_kernel``'s layout note.  Each per-bin result
is born as an (Fb,) lane vector, so output layout inside the kernel is
(C, C, Fb) and every store is a contiguous lane store; the host
transposes the tiny result to the (..., F, C, C) convention.

:func:`masked_covariances_fused` dispatches 'xla' / 'pallas' so callers can
pick per backend; parity is pinned in tests/test_ops.py against
``beam.covariance.masked_covariances`` and the float64 oracle.  Since the
hot-path fusion round the 'xla' lane is the FOLDED einsum
(:func:`masked_covariances_folded`): the mask weights are contracted inside
the covariance einsum (masked rank-1 updates), so even off-TPU the masked
spectrogram copies never exist as program values.  Both lanes additionally
support PER-CHANNEL masks ((..., C, F, T) — the step-2 stacked
``[local mics ‖ z]`` layout where each channel carries its own mask, e.g.
the 'distant' policy) and the ``precision='bf16'`` compute lane
(:mod:`disco_tpu.ops.resolve`): bf16 multiply inner loops, f32
accumulators, gated by the documented looser oracle tolerances.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from disco_tpu.beam.covariance import masked_covariances
from disco_tpu.ops.resolve import compute_dtype, resolve_impl, resolve_precision


def _cov_kernel(yr_ref, yi_ref, m_ref, ssr_ref, ssi_ref, nnr_ref, nni_ref, *, C, inv_t):
    """One (C, Tb, Fb) block: both masked covariances, hermitian triangle,
    ACCUMULATED over the innermost (frame-tile) grid axis.

    Layout note: the frame reduction runs over the SUBLANE axis
    (frames-major (Tb, Fb) planes, ``axis=0``) so each per-bin result is
    born as a lane vector and every store below is a native contiguous
    lane store.  The frame axis is additionally TILED (grid axis 2, with
    the output block's index map ignoring it, so the covariance block
    stays VMEM-resident and accumulates across frame tiles): an untiled
    10 s clip at the step-2 stack width is a ~14 MB input block — past
    the ~16 MB VMEM budget, which is how the round-3/4 full-pipeline
    compiles died (tpu_compile_helper crash, BENCH_r03/r04
    covfused_error) while the round-5 short-clip probe compiled fine in
    ~1 s (exp/probe_mosaic_r5.json: every ladder construct AND the full
    kernel at T=130 pass on real Mosaic)."""
    t_idx = pl.program_id(2)

    @pl.when(t_idx == 0)
    def _init():
        ssr_ref[...] = jnp.zeros_like(ssr_ref)
        ssi_ref[...] = jnp.zeros_like(ssi_ref)
        nnr_ref[...] = jnp.zeros_like(nnr_ref)
        nni_ref[...] = jnp.zeros_like(nni_ref)

    m = m_ref[0]  # (Tb, Fb)
    ws = (m * m) * inv_t
    one_m = 1.0 - m
    wn = (one_m * one_m) * inv_t
    for c in range(C):
        xr_c, xi_c = yr_ref[0, c], yi_ref[0, c]  # (Tb, Fb)
        for d in range(c, C):
            xr_d, xi_d = yr_ref[0, d], yi_ref[0, d]
            # Y_c conj(Y_d): re = rc rd + ic id, im = ic rd - rc id
            prr = xr_c * xr_d + xi_c * xi_d
            pii = xi_c * xr_d - xr_c * xi_d
            ss_re = jnp.sum(ws * prr, axis=0)  # (Fb,) lane vector
            ss_im = jnp.sum(ws * pii, axis=0)
            nn_re = jnp.sum(wn * prr, axis=0)
            nn_im = jnp.sum(wn * pii, axis=0)
            ssr_ref[0, c, d, :] += ss_re
            ssi_ref[0, c, d, :] += ss_im
            nnr_ref[0, c, d, :] += nn_re
            nni_ref[0, c, d, :] += nn_im
            if d != c:  # hermitian mirror
                ssr_ref[0, d, c, :] += ss_re
                ssi_ref[0, d, c, :] += -ss_im
                nnr_ref[0, d, c, :] += nn_re
                nni_ref[0, d, c, :] += -nn_im


def _cov_kernel_chan(yr_ref, yi_ref, m_ref, ssr_ref, ssi_ref, nnr_ref, nni_ref, *, C, inv_t):
    """Per-CHANNEL-mask variant of :func:`_cov_kernel` — the step-2 stacked
    ``[local mics ‖ z]`` layout where every channel carries its own mask
    (the 'distant' mask-for-z policy: producer masks on the z channels,
    the consumer mask on the local mics).  Same layout/accumulation scheme;
    the pair weight is ``m_c * m_d`` (speech) / ``(1-m_c)(1-m_d)`` (noise)
    instead of the shared ``m^2`` / ``(1-m)^2``."""
    t_idx = pl.program_id(2)

    @pl.when(t_idx == 0)
    def _init():
        ssr_ref[...] = jnp.zeros_like(ssr_ref)
        ssi_ref[...] = jnp.zeros_like(ssi_ref)
        nnr_ref[...] = jnp.zeros_like(nnr_ref)
        nni_ref[...] = jnp.zeros_like(nni_ref)

    for c in range(C):
        xr_c, xi_c = yr_ref[0, c], yi_ref[0, c]  # (Tb, Fb)
        m_c = m_ref[0, c]
        for d in range(c, C):
            xr_d, xi_d = yr_ref[0, d], yi_ref[0, d]
            m_d = m_ref[0, d]
            ws = (m_c * m_d) * inv_t
            wn = ((1.0 - m_c) * (1.0 - m_d)) * inv_t
            # Y_c conj(Y_d): re = rc rd + ic id, im = ic rd - rc id
            prr = xr_c * xr_d + xi_c * xi_d
            pii = xi_c * xr_d - xr_c * xi_d
            ss_re = jnp.sum(ws * prr, axis=0)  # (Fb,) lane vector
            ss_im = jnp.sum(ws * pii, axis=0)
            nn_re = jnp.sum(wn * prr, axis=0)
            nn_im = jnp.sum(wn * pii, axis=0)
            ssr_ref[0, c, d, :] += ss_re
            ssi_ref[0, c, d, :] += ss_im
            nnr_ref[0, c, d, :] += nn_re
            nni_ref[0, c, d, :] += nn_im
            if d != c:  # hermitian mirror
                ssr_ref[0, d, c, :] += ss_re
                ssi_ref[0, d, c, :] += -ss_im
                nnr_ref[0, d, c, :] += nn_re
                nni_ref[0, d, c, :] += -nn_im


@partial(jax.jit, static_argnames=("f_tile", "t_tile", "interpret", "precision"))
def masked_cov_pallas(
    y: jnp.ndarray, mask: jnp.ndarray, f_tile: int = 128, t_tile: int = 256,
    interpret: bool = False, precision: str = "f32",
):
    """Speech/noise covariances from a mixture and TF mask, fused.

    Drop-in for ``beam.covariance.masked_covariances`` (same semantics,
    reference tango.py:347-364): Y is read from HBM exactly once and the
    masked copies never touch HBM.

    Args:
      y: (..., C, F, T) complex64 mixture STFT.
      mask: (..., F, T) float mask, broadcast over channels — or
        (..., C, F, T) PER-CHANNEL masks (the step-2 stacked layout under
        the 'distant' policy), routed to :func:`_cov_kernel_chan` with pair
        weights ``m_c m_d`` / ``(1-m_c)(1-m_d)``.
      f_tile: frequency bins per grid step (F is zero-padded to a multiple).
        Mosaic requires the covariance blocks' trailing dim to be a multiple
        of 128 (measured on TPU v5e: f_tile=8 is rejected at lowering), so
        the default is 128.
      t_tile: frames per grid step (T is zero-padded to a multiple; zero
        frames contribute zero to both sums, so padding is exact).  Bounds
        VMEM per grid step at ~2*C*f_tile*t_tile*4 bytes (~2.9 MB at the
        C=11 step-2 stack) regardless of clip length — the untiled kernel
        blew the ~16 MB VMEM budget at 10 s clips, which is where the
        round-3/4 on-device compile crashes came from.
      interpret: pallas interpreter mode (CPU correctness tests).
      precision: 'f32' (default, the pre-existing program) or 'bf16' — the
        Y planes are fed to the kernel in bf16, so the elementwise products
        of the inner loop run at bf16 while the mask weights and the
        sublane reductions accumulate in f32 (``ops.resolve`` lane; gated
        by the documented looser oracle tolerances).

    Returns:
      (Rss, Rnn), each (..., F, C, C) complex64.
    """
    y = jnp.asarray(y)
    mask = jnp.asarray(mask, jnp.float32)
    *lead, C, F, T = y.shape
    chan = mask.ndim == y.ndim  # per-channel masks carry the C axis
    B = 1
    for n in lead:
        B *= n
    dt = compute_dtype(precision)
    # frames-major planes: the kernel reduces over sublanes (see
    # _cov_kernel's layout note) — transpose costs one HBM pass of Y, still
    # far below the three masked-copy round trips the einsum path pays
    yr = jnp.real(y).astype(dt).reshape(B, C, F, T).transpose(0, 1, 3, 2)
    yi = jnp.imag(y).astype(dt).reshape(B, C, F, T).transpose(0, 1, 3, 2)
    if chan:
        m = (
            jnp.broadcast_to(mask, tuple(lead) + (C, F, T))
            .reshape(B, C, F, T)
            .transpose(0, 1, 3, 2)
        )
    else:
        m = (
            jnp.broadcast_to(mask, tuple(lead) + (F, T))
            .reshape(B, F, T)
            .transpose(0, 2, 1)
        )

    n_ft = -(-F // f_tile)
    Fp = n_ft * f_tile
    n_tt = -(-T // t_tile)
    Tp = n_tt * t_tile
    if Fp != F or Tp != T:
        pad = ((0, 0), (0, 0), (0, Tp - T), (0, Fp - F))
        yr, yi = jnp.pad(yr, pad), jnp.pad(yi, pad)
        mpad = pad if chan else ((0, 0), (0, Tp - T), (0, Fp - F))
        m = jnp.pad(m, mpad)

    # NOTE on shard_map: pallas_call's vma handling is incomplete in this
    # jax version (its interpreter rejects even correctly-annotated
    # out_shapes with "dynamic_slice requires varying manual axes to
    # match"), so the shard_map caller (parallel/mesh.py) disables
    # check_vma for the pallas cov variant instead of annotating here.
    out_struct = jax.ShapeDtypeStruct((B, C, C, Fp), jnp.float32)

    kernel = _cov_kernel_chan if chan else _cov_kernel
    m_spec = (
        pl.BlockSpec((1, C, t_tile, f_tile), lambda b, f, t: (b, 0, t, f))
        if chan
        else pl.BlockSpec((1, t_tile, f_tile), lambda b, f, t: (b, t, f))
    )
    # frame tiles innermost: the output block's index map ignores t, so the
    # (1, C, C, f_tile) accumulator stays VMEM-resident across the sweep
    out = pl.pallas_call(
        partial(kernel, C=C, inv_t=1.0 / T),
        grid=(B, n_ft, n_tt),
        in_specs=[
            pl.BlockSpec((1, C, t_tile, f_tile), lambda b, f, t: (b, 0, t, f)),
            pl.BlockSpec((1, C, t_tile, f_tile), lambda b, f, t: (b, 0, t, f)),
            m_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
        ],
        out_shape=[out_struct] * 4,
        interpret=interpret,
    )(yr, yi, m)
    ssr, ssi, nnr, nni = (o[..., :F] for o in out)
    Rss = jax.lax.complex(ssr, ssi).transpose(0, 3, 1, 2)
    Rnn = jax.lax.complex(nnr, nni).transpose(0, 3, 1, 2)
    shape = tuple(lead) + (F, C, C)
    return Rss.reshape(shape), Rnn.reshape(shape)


# ------------------------------------------------------- folded XLA einsums
def _weighted_cov_shared(y, w, precision: str):
    """``R[..., f, c, d] = (1/T) sum_t w[..., f, t] y_c conj(y_d)`` with the
    frame weights contracted IN the einsum — the masked copies of the
    materializing path (``beam.covariance``) never exist as program values.

    No reference counterpart: the reference materializes the masked copies
    (tango.py:347-348); folding is the TPU HBM-traffic optimization.
    """
    T = y.shape[-1]
    if resolve_precision(precision) == "bf16":
        dt = compute_dtype(precision)
        yr, yi = jnp.real(y).astype(dt), jnp.imag(y).astype(dt)
        w16 = w.astype(dt)
        pe = dict(preferred_element_type=jnp.float32)
        re = (jnp.einsum("...ft,...cft,...dft->...fcd", w16, yr, yr, **pe)
              + jnp.einsum("...ft,...cft,...dft->...fcd", w16, yi, yi, **pe))
        im = (jnp.einsum("...ft,...cft,...dft->...fcd", w16, yi, yr, **pe)
              - jnp.einsum("...ft,...cft,...dft->...fcd", w16, yr, yi, **pe))
        return jax.lax.complex(re, im) / T
    cov = jnp.einsum("...ft,...cft,...dft->...fcd", w, y, jnp.conj(y),
                     precision=jax.lax.Precision.HIGHEST)
    return cov / T


def _weighted_cov_chan(y, m, precision: str):
    """Per-channel-mask fold: ``R[..., f, c, d] = (1/T) sum_t m_c m_d
    y_c conj(y_d)`` with ``m`` shaped (..., C, F, T).

    No reference counterpart (see :func:`_weighted_cov_shared`).
    """
    T = y.shape[-1]
    if resolve_precision(precision) == "bf16":
        dt = compute_dtype(precision)
        yr, yi = jnp.real(y).astype(dt), jnp.imag(y).astype(dt)
        m16 = m.astype(dt)
        pe = dict(preferred_element_type=jnp.float32)
        sub = "...cft,...dft,...cft,...dft->...fcd"
        re = (jnp.einsum(sub, m16, m16, yr, yr, **pe)
              + jnp.einsum(sub, m16, m16, yi, yi, **pe))
        im = (jnp.einsum(sub, m16, m16, yi, yr, **pe)
              - jnp.einsum(sub, m16, m16, yr, yi, **pe))
        return jax.lax.complex(re, im) / T
    cov = jnp.einsum("...cft,...dft,...cft,...dft->...fcd", m, m, y, jnp.conj(y),
                     precision=jax.lax.Precision.HIGHEST)
    return cov / T


def outer_acc_bf16(w, x):
    """``sum_t w_t x_t x_t^H`` over a (u, F, D) complex stream with bf16
    multiplies and f32 accumulators (planar re/im) — the streaming
    covariance tail accumulation of ``enhance/streaming._block_covariances``
    under the bf16 lane.  Lives here because precision casts are an ops/
    concern (disco-lint DL012): callers request a lane through the
    ``precision=`` seam and never spell dtype literals themselves.

    The exponential-smoothing estimator this accelerates is reference
    se_utils/internal_formulas.py:84-103; the bf16 lane itself has no
    reference counterpart.
    """
    xr = jnp.real(x).astype(jnp.bfloat16)
    xi = jnp.imag(x).astype(jnp.bfloat16)
    w16 = w.astype(jnp.bfloat16)
    pe = dict(preferred_element_type=jnp.float32)
    re = (jnp.einsum("t,tfc,tfd->fcd", w16, xr, xr, **pe)
          + jnp.einsum("t,tfc,tfd->fcd", w16, xi, xi, **pe))
    im = (jnp.einsum("t,tfc,tfd->fcd", w16, xi, xr, **pe)
          - jnp.einsum("t,tfc,tfd->fcd", w16, xr, xi, **pe))
    return jax.lax.complex(re, im)


def weighted_cov_folded(y, mask, precision: str = "f32"):
    """ONE covariance of the mask-applied stack without materializing it:
    the generalized masked-rank-1-update accumulator behind
    :func:`masked_covariances_folded`.

    ``mask`` is (..., F, T) (shared over channels) or (..., C, F, T)
    (per-channel — the step-2 stacked ``[local mics ‖ z]`` layouts where
    each channel carries its own mask, e.g. the 'none' policy's
    ``[(1-m) · Y ‖ zn]`` noise stack expressed as masks ``[(1-m) ‖ 1]``
    over ``[Y ‖ zn]``).  ``precision='bf16'`` runs the contraction with
    bf16 operands in planar re/im form with f32 accumulators.

    The mask->covariance stage of reference tango.py:347-364, re-associated
    so the masked spectrogram copies never exist.
    """
    y = jnp.asarray(y)
    mask = jnp.asarray(mask, jnp.float32)
    if mask.ndim == y.ndim:
        return _weighted_cov_chan(y, mask, precision)
    return _weighted_cov_shared(y, mask * mask, precision)


def masked_covariances_folded(y, mask, precision: str = "f32"):
    """Speech/noise covariance pair with the TF mask folded into the
    accumulation — the XLA twin of :func:`masked_cov_pallas` (same
    semantics as ``beam.covariance.masked_covariances``, reference
    tango.py:347-364, to f32 re-association roundoff): ``Rss`` weights by
    the mask, ``Rnn`` by its complement, and neither ``m*Y`` nor
    ``(1-m)*Y`` is ever a program value.  Accepts shared (..., F, T) or
    per-channel (..., C, F, T) masks like the pallas kernel.
    """
    y = jnp.asarray(y)
    mask = jnp.asarray(mask, jnp.float32)
    return (
        weighted_cov_folded(y, mask, precision),
        weighted_cov_folded(y, 1.0 - mask, precision),
    )


#: Environment escape hatch for the default covariance kernel selection:
#: ``DISCO_TPU_COV_IMPL=xla`` (or ``pallas``) overrides the ``'auto'``
#: resolution everywhere the callers left ``cov_impl`` at its default.
COV_IMPL_ENV = "DISCO_TPU_COV_IMPL"


def resolve_cov_impl(impl: str = "auto") -> str:
    """Resolve a ``cov_impl`` knob to a concrete kernel choice.

    ``'auto'`` (the pipeline default since the round-6 promotion —
    ``rtf_covfused`` 6829 vs 6735 default in BENCH_r05) resolves to the
    fused pallas kernel on real TPU backends and to the einsum path
    everywhere else (off-TPU the pallas interpreter is a correctness tool,
    not a fast path), with the :data:`COV_IMPL_ENV` env var as the
    operator escape hatch.  Explicit ``'xla'``/``'pallas'`` pass through
    untouched.  Resolution happens when a program is *traced* (``cov_impl``
    is a static jit argument), so flipping the env var mid-process does not
    retrace already-compiled buckets.

    No reference counterpart: kernel selection is a TPU-port concern — the
    reference computes its covariances one way only (numpy einsum,
    tango.py:347-364, the stage both kernels implement).  Backed by the
    shared resolution policy (:func:`disco_tpu.ops.resolve.resolve_impl`)
    since the STFT seam landed, so ``cov_impl='auto'`` and
    ``stft_impl='auto'`` can never resolve differently on one backend.
    """
    return resolve_impl(impl, COV_IMPL_ENV)


def masked_covariances_fused(y, mask, impl: str = "xla", interpret: bool | None = None,
                             precision: str = "f32"):
    """Masked speech/noise covariance pair with implementation dispatch —
    the mask->covariance stage of reference tango.py:347-364.

    'xla': the FOLDED einsum (:func:`masked_covariances_folded`) — since
    the hot-path fusion round this lane no longer materializes the masked
    copies either (the materializing reference formulation survives as
    ``beam.covariance.masked_covariances``, which the perf-check parity
    gate pins this path against); 'pallas': single fused read of Y
    (:func:`masked_cov_pallas`).  Both accept shared (..., F, T) or
    per-channel (..., C, F, T) masks and the ``precision`` lane.
    ``interpret=None`` resolves to the pallas interpreter off-TPU (the
    Mosaic lowering is TPU-only) — the one place this decision lives.
    """
    if impl == "xla":
        return masked_covariances_folded(y, mask, precision=precision)
    if impl == "pallas":
        if interpret is None:
            from disco_tpu.utils.backend import is_tpu

            interpret = not is_tpu()
        return masked_cov_pallas(y, mask, interpret=interpret, precision=precision)
    raise ValueError(f"unknown cov impl {impl!r}; expected 'xla' or 'pallas'")
