"""Fused masked spatial covariances as one pallas kernel.

The TANGO steps estimate speech/noise covariances by materializing the
masked STFT copies ``s_hat = m * Y`` and ``n_hat = (1 - m) * Y`` and then
contracting each over frames (reference tango.py:347-364;
``beam.covariance.masked_covariances``).  On TPU that costs HBM round
trips for two full (C, F, T) complex intermediates — written once and
read back by the covariance matmuls — while the covariances themselves
are tiny ((F, C, C), ~100 KB).  The round-2 roofline named this traffic
the next lever after the eigensolve (VERDICT round-2 #3).

:func:`masked_cov_pallas` computes BOTH covariances in one kernel pass:
each grid step DMAs a (C, Fb, T) block of Y (planar re/im) plus its mask
block into VMEM once and emits only the (Fb, C, C) covariance blocks —
the masked copies never exist in HBM.  The math per frequency bin is

    Rss[c, d] = (1/T) sum_t m_t^2      Y[c, t] conj(Y[d, t])
    Rnn[c, d] = (1/T) sum_t (1 - m_t)^2 Y[c, t] conj(Y[d, t])

evaluated hermitian-triangle-wise as elementwise products + SUBLANE-axis
reductions over frames-major (T, Fb) planes (VPU work; no tiny-matmul MXU
padding waste) — see ``_cov_kernel``'s layout note.  Each per-bin result
is born as an (Fb,) lane vector, so output layout inside the kernel is
(C, C, Fb) and every store is a contiguous lane store; the host
transposes the tiny result to the (..., F, C, C) convention.

:func:`masked_covariances_fused` dispatches 'xla' (the einsum path) /
'pallas' so callers can pick per backend; parity is pinned in
tests/test_ops.py against ``beam.covariance.masked_covariances``.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from disco_tpu.beam.covariance import masked_covariances


def _cov_kernel(yr_ref, yi_ref, m_ref, ssr_ref, ssi_ref, nnr_ref, nni_ref, *, C, inv_t):
    """One (C, Tb, Fb) block: both masked covariances, hermitian triangle,
    ACCUMULATED over the innermost (frame-tile) grid axis.

    Layout note: the frame reduction runs over the SUBLANE axis
    (frames-major (Tb, Fb) planes, ``axis=0``) so each per-bin result is
    born as a lane vector and every store below is a native contiguous
    lane store.  The frame axis is additionally TILED (grid axis 2, with
    the output block's index map ignoring it, so the covariance block
    stays VMEM-resident and accumulates across frame tiles): an untiled
    10 s clip at the step-2 stack width is a ~14 MB input block — past
    the ~16 MB VMEM budget, which is how the round-3/4 full-pipeline
    compiles died (tpu_compile_helper crash, BENCH_r03/r04
    covfused_error) while the round-5 short-clip probe compiled fine in
    ~1 s (exp/probe_mosaic_r5.json: every ladder construct AND the full
    kernel at T=130 pass on real Mosaic)."""
    t_idx = pl.program_id(2)

    @pl.when(t_idx == 0)
    def _init():
        ssr_ref[...] = jnp.zeros_like(ssr_ref)
        ssi_ref[...] = jnp.zeros_like(ssi_ref)
        nnr_ref[...] = jnp.zeros_like(nnr_ref)
        nni_ref[...] = jnp.zeros_like(nni_ref)

    m = m_ref[0]  # (Tb, Fb)
    ws = (m * m) * inv_t
    one_m = 1.0 - m
    wn = (one_m * one_m) * inv_t
    for c in range(C):
        xr_c, xi_c = yr_ref[0, c], yi_ref[0, c]  # (Tb, Fb)
        for d in range(c, C):
            xr_d, xi_d = yr_ref[0, d], yi_ref[0, d]
            # Y_c conj(Y_d): re = rc rd + ic id, im = ic rd - rc id
            prr = xr_c * xr_d + xi_c * xi_d
            pii = xi_c * xr_d - xr_c * xi_d
            ss_re = jnp.sum(ws * prr, axis=0)  # (Fb,) lane vector
            ss_im = jnp.sum(ws * pii, axis=0)
            nn_re = jnp.sum(wn * prr, axis=0)
            nn_im = jnp.sum(wn * pii, axis=0)
            ssr_ref[0, c, d, :] += ss_re
            ssi_ref[0, c, d, :] += ss_im
            nnr_ref[0, c, d, :] += nn_re
            nni_ref[0, c, d, :] += nn_im
            if d != c:  # hermitian mirror
                ssr_ref[0, d, c, :] += ss_re
                ssi_ref[0, d, c, :] += -ss_im
                nnr_ref[0, d, c, :] += nn_re
                nni_ref[0, d, c, :] += -nn_im


@partial(jax.jit, static_argnames=("f_tile", "t_tile", "interpret"))
def masked_cov_pallas(
    y: jnp.ndarray, mask: jnp.ndarray, f_tile: int = 128, t_tile: int = 256, interpret: bool = False
):
    """Speech/noise covariances from a mixture and TF mask, fused.

    Drop-in for ``beam.covariance.masked_covariances`` (same semantics,
    reference tango.py:347-364): Y is read from HBM exactly once and the
    masked copies never touch HBM.

    Args:
      y: (..., C, F, T) complex64 mixture STFT.
      mask: (..., F, T) float mask, broadcast over channels.
      f_tile: frequency bins per grid step (F is zero-padded to a multiple).
        Mosaic requires the covariance blocks' trailing dim to be a multiple
        of 128 (measured on TPU v5e: f_tile=8 is rejected at lowering), so
        the default is 128.
      t_tile: frames per grid step (T is zero-padded to a multiple; zero
        frames contribute zero to both sums, so padding is exact).  Bounds
        VMEM per grid step at ~2*C*f_tile*t_tile*4 bytes (~2.9 MB at the
        C=11 step-2 stack) regardless of clip length — the untiled kernel
        blew the ~16 MB VMEM budget at 10 s clips, which is where the
        round-3/4 on-device compile crashes came from.
      interpret: pallas interpreter mode (CPU correctness tests).

    Returns:
      (Rss, Rnn), each (..., F, C, C) complex64.
    """
    y = jnp.asarray(y)
    *lead, C, F, T = y.shape
    B = 1
    for n in lead:
        B *= n
    # frames-major planes: the kernel reduces over sublanes (see
    # _cov_kernel's layout note) — transpose costs one HBM pass of Y, still
    # far below the three masked-copy round trips the einsum path pays
    yr = jnp.real(y).astype(jnp.float32).reshape(B, C, F, T).transpose(0, 1, 3, 2)
    yi = jnp.imag(y).astype(jnp.float32).reshape(B, C, F, T).transpose(0, 1, 3, 2)
    m = (
        jnp.broadcast_to(jnp.asarray(mask, jnp.float32), tuple(lead) + (F, T))
        .reshape(B, F, T)
        .transpose(0, 2, 1)
    )

    n_ft = -(-F // f_tile)
    Fp = n_ft * f_tile
    n_tt = -(-T // t_tile)
    Tp = n_tt * t_tile
    if Fp != F or Tp != T:
        pad = ((0, 0), (0, 0), (0, Tp - T), (0, Fp - F))
        yr, yi = jnp.pad(yr, pad), jnp.pad(yi, pad)
        m = jnp.pad(m, ((0, 0), (0, Tp - T), (0, Fp - F)))

    # NOTE on shard_map: pallas_call's vma handling is incomplete in this
    # jax version (its interpreter rejects even correctly-annotated
    # out_shapes with "dynamic_slice requires varying manual axes to
    # match"), so the shard_map caller (parallel/mesh.py) disables
    # check_vma for the pallas cov variant instead of annotating here.
    out_struct = jax.ShapeDtypeStruct((B, C, C, Fp), jnp.float32)

    # frame tiles innermost: the output block's index map ignores t, so the
    # (1, C, C, f_tile) accumulator stays VMEM-resident across the sweep
    out = pl.pallas_call(
        partial(_cov_kernel, C=C, inv_t=1.0 / T),
        grid=(B, n_ft, n_tt),
        in_specs=[
            pl.BlockSpec((1, C, t_tile, f_tile), lambda b, f, t: (b, 0, t, f)),
            pl.BlockSpec((1, C, t_tile, f_tile), lambda b, f, t: (b, 0, t, f)),
            pl.BlockSpec((1, t_tile, f_tile), lambda b, f, t: (b, t, f)),
        ],
        out_specs=[
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
            pl.BlockSpec((1, C, C, f_tile), lambda b, f, t: (b, 0, 0, f)),
        ],
        out_shape=[out_struct] * 4,
        interpret=interpret,
    )(yr, yi, m)
    ssr, ssi, nnr, nni = (o[..., :F] for o in out)
    Rss = jax.lax.complex(ssr, ssi).transpose(0, 3, 1, 2)
    Rnn = jax.lax.complex(nnr, nni).transpose(0, 3, 1, 2)
    shape = tuple(lead) + (F, C, C)
    return Rss.reshape(shape), Rnn.reshape(shape)


#: Environment escape hatch for the default covariance kernel selection:
#: ``DISCO_TPU_COV_IMPL=xla`` (or ``pallas``) overrides the ``'auto'``
#: resolution everywhere the callers left ``cov_impl`` at its default.
COV_IMPL_ENV = "DISCO_TPU_COV_IMPL"


def resolve_cov_impl(impl: str = "auto") -> str:
    """Resolve a ``cov_impl`` knob to a concrete kernel choice.

    ``'auto'`` (the pipeline default since the round-6 promotion —
    ``rtf_covfused`` 6829 vs 6735 default in BENCH_r05) resolves to the
    fused pallas kernel on real TPU backends and to the einsum path
    everywhere else (off-TPU the pallas interpreter is a correctness tool,
    not a fast path), with the :data:`COV_IMPL_ENV` env var as the
    operator escape hatch.  Explicit ``'xla'``/``'pallas'`` pass through
    untouched.  Resolution happens when a program is *traced* (``cov_impl``
    is a static jit argument), so flipping the env var mid-process does not
    retrace already-compiled buckets.

    No reference counterpart: kernel selection is a TPU-port concern — the
    reference computes its covariances one way only (numpy einsum,
    tango.py:347-364, the stage both kernels implement).
    """
    if impl != "auto":
        if impl not in ("xla", "pallas"):
            raise ValueError(f"unknown cov impl {impl!r}; expected 'auto', 'xla' or 'pallas'")
        return impl
    import os

    env = os.environ.get(COV_IMPL_ENV, "").strip().lower()
    if env:
        if env not in ("xla", "pallas"):
            raise ValueError(f"{COV_IMPL_ENV}={env!r}: expected 'xla' or 'pallas'")
        return env
    from disco_tpu.utils.backend import is_tpu

    return "pallas" if is_tpu() else "xla"


def masked_covariances_fused(y, mask, impl: str = "xla", interpret: bool | None = None):
    """Masked speech/noise covariance pair with implementation dispatch —
    the mask->covariance stage of reference tango.py:347-364.

    'xla': einsum via materialized masked copies (``beam.covariance``);
    'pallas': single fused read of Y (:func:`masked_cov_pallas`).
    ``interpret=None`` resolves to the pallas interpreter off-TPU (the
    Mosaic lowering is TPU-only) — the one place this decision lives.
    """
    if impl == "xla":
        return masked_covariances(y, mask)
    if impl == "pallas":
        if interpret is None:
            from disco_tpu.utils.backend import is_tpu

            interpret = not is_tpu()
        return masked_cov_pallas(y, mask, interpret=interpret)
    raise ValueError(f"unknown cov impl {impl!r}; expected 'xla' or 'pallas'")
