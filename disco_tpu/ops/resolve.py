"""Shared kernel-implementation and precision resolution seams.

Every fused kernel family in :mod:`disco_tpu.ops` exposes the same knob
shape: an ``impl`` argument taking ``'auto' | 'xla' | 'pallas'`` with a
``DISCO_TPU_*_IMPL`` environment escape hatch, where ``'auto'`` resolves to
the fused pallas kernel on real TPU backends and to the XLA formulation
everywhere else (off-TPU the pallas interpreter is a correctness tool, not
a fast path).  Before this module each family hand-rolled that resolution
(``ops.cov_ops.resolve_cov_impl`` was the template); now the policy lives
ONCE, so ``cov_impl="auto"`` and ``stft_impl="auto"`` can never resolve
differently on the same backend — pinned by tests/test_ops.py.

The ``precision`` seam (``'f32'`` default, ``'bf16'`` opt-in) is resolved
here too: :func:`resolve_precision` is the one place the token is
validated/normalized, so every kernel family and every jit static argument
sees the SAME canonical string — a non-canonical spelling reaching a
``static_argnames`` seam would trace a duplicate program per call site
(the PR-6 ``mu=1`` retrace trap, this time with strings), which the
retrace-budget gate (``disco_tpu.analysis.trace.budgets``) holds exact.

No reference counterpart: kernel selection and mixed-precision lanes are
TPU-port concerns — the reference computes everything one way only
(float64 numpy).
"""
from __future__ import annotations

import os

#: the concrete kernel choices every ``impl`` seam resolves to
IMPL_CHOICES = ("xla", "pallas")

#: the compute-precision lanes of the fused kernels: ``'f32'`` (default,
#: full float32) or ``'bf16'`` (bf16 multiply inner loops with float32
#: accumulators — gated by the documented looser oracle tolerances)
PRECISIONS = ("f32", "bf16")


def resolve_impl(impl: str, env_var: str) -> str:
    """Resolve an ``impl`` knob (``'auto'``/``'xla'``/``'pallas'``) to a
    concrete kernel choice with the shared auto policy.

    ``'auto'`` resolves to ``'pallas'`` on real TPU backends and ``'xla'``
    elsewhere, with ``env_var`` (e.g. ``DISCO_TPU_COV_IMPL``) as the
    operator escape hatch.  Explicit choices pass through after validation.
    Resolution happens when a program is *traced* (``impl`` knobs are
    static jit arguments), so flipping the env var mid-process does not
    retrace already-compiled buckets.

    No reference counterpart (module docstring).
    """
    if impl != "auto":
        if impl not in IMPL_CHOICES:
            raise ValueError(
                f"unknown impl {impl!r}; expected 'auto' or one of {IMPL_CHOICES}"
            )
        return impl
    env = os.environ.get(env_var, "").strip().lower()
    if env:
        if env not in IMPL_CHOICES:
            raise ValueError(f"{env_var}={env!r}: expected one of {IMPL_CHOICES}")
        return env
    from disco_tpu.utils.backend import is_tpu

    return "pallas" if is_tpu() else "xla"


def resolve_precision(precision: str) -> str:
    """Validate/normalize a ``precision`` token to its canonical form
    (``'f32'`` or ``'bf16'``).

    Callers holding a jit ``static_argnames`` precision seam MUST pass the
    canonical string (this function's output): two spellings of the same
    lane would be two static values and therefore two traced programs —
    the string-typed twin of the ``mu=1`` retrace trap, held exact by the
    retrace-budget gate.

    No reference counterpart (module docstring).
    """
    p = str(precision).strip().lower()
    if p not in PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r}; expected one of {PRECISIONS}"
        )
    return p


def check_canonical_precision(precision: str) -> str:
    """Require an ALREADY-canonical precision token — the guard for
    directly-jitted entry points whose ``precision`` is a static argument
    (``enhance.tango.tango``/``tango_step1``/``tango_step2``).

    Unlike :func:`resolve_precision` this does not normalize: a
    normalization *inside* the traced body runs after the jit cache key is
    formed, so every spelling variant would silently trace (and compile) a
    duplicate program — the string-typed ``mu=1`` retrace trap.  Raising at
    trace time turns the trap into a loud error; host-side wrappers that
    accept user input (the CLI, the driver, ``streaming_tango``)
    canonicalize with :func:`resolve_precision` BEFORE the static seam.

    No reference counterpart (module docstring).
    """
    if precision not in PRECISIONS:
        raise ValueError(
            f"precision {precision!r} is not canonical; static jit seams must "
            f"see exactly one spelling per lane — pass one of {PRECISIONS} "
            "(canonicalize user input with resolve_precision first)"
        )
    return precision


def compute_dtype(precision: str):
    """The matmul/accumulation *input* dtype of a precision lane (the
    accumulator stays float32 in both lanes — ``preferred_element_type``).

    No reference counterpart (module docstring).
    """
    import jax.numpy as jnp

    return jnp.bfloat16 if resolve_precision(precision) == "bf16" else jnp.float32
