from disco_tpu.ops.eigh_ops import eigh_jacobi, eigh_jacobi_pallas
from disco_tpu.ops.mwf_ops import (
    fused_mwf_pallas,
    fused_mwf_xla,
    rank1_gevd_fused,
    resolve_mwf_impl,
)
from disco_tpu.ops.resolve import resolve_precision
from disco_tpu.ops.stft_ops import (
    dft_matrices,
    idft_matrices,
    istft_matmul,
    resolve_stft_impl,
    stft_fused,
    stft_matmul,
    stft_pallas,
    stft_with_mag,
)

__all__ = [
    "dft_matrices",
    "eigh_jacobi",
    "eigh_jacobi_pallas",
    "fused_mwf_pallas",
    "fused_mwf_xla",
    "idft_matrices",
    "istft_matmul",
    "rank1_gevd_fused",
    "resolve_mwf_impl",
    "resolve_precision",
    "resolve_stft_impl",
    "stft_fused",
    "stft_matmul",
    "stft_pallas",
    "stft_with_mag",
]
