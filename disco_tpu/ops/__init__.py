from disco_tpu.ops.stft_ops import dft_matrices, idft_matrices, istft_matmul, stft_matmul, stft_pallas

__all__ = ["dft_matrices", "idft_matrices", "istft_matmul", "stft_matmul", "stft_pallas"]
