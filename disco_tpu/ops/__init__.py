from disco_tpu.ops.eigh_ops import eigh_jacobi, eigh_jacobi_pallas
from disco_tpu.ops.stft_ops import dft_matrices, idft_matrices, istft_matmul, stft_matmul, stft_pallas

__all__ = [
    "dft_matrices",
    "eigh_jacobi",
    "eigh_jacobi_pallas",
    "idft_matrices",
    "istft_matmul",
    "stft_matmul",
    "stft_pallas",
]
