from disco_tpu.ops.stft_ops import dft_matrices, stft_matmul, stft_pallas

__all__ = ["dft_matrices", "stft_matmul", "stft_pallas"]
