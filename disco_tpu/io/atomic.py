"""Atomic artifact I/O: tmp-file + fsync + ``os.replace`` writers and cheap
integrity probes.

Every artifact the long-running entry points persist — WAVs, ``.npy``/
``.npz`` arrays, result pickles, flax msgpack checkpoints — historically
went straight to its final path, so a crash or preemption mid-write left a
truncated file at the *done* location.  The existence-only idempotency
guards (pre-PR-3 ``enhance/driver.py``, ``datagen/disco.py``) then trusted
that file forever: the unit was never redone and the corpus silently
carried a corrupt artifact.  On this hardware the stakes are higher than
usual — the environment contract forbids SIGKILLing a TPU-holding process
(CLAUDE.md), so runs are expected to be *interrupted and resumed*, not
killed and restarted from scratch.

The writers here give the crash-safety invariant every resume path relies
on: **the final path either holds the complete artifact or does not exist**.
The payload is written to a same-directory temp file, flushed and fsynced,
then ``os.replace``d over the destination (atomic on POSIX within one
filesystem); the directory entry is fsynced best-effort so the rename
itself survives a power loss.  A crash at any point leaves at most a
``*.tmp.*`` litter file, never a truncated artifact.

The probes are the matching read side: cheap self-validating loads that
distinguish "done" from "truncated" for each artifact family, used by the
verified-resume checks (``disco_tpu.runs.ledger``) and by the
validate-before-skip idempotency guards.  :func:`file_digest` provides the
stronger sidecar-digest form the run ledger records per artifact.

No reference counterpart: the reference writes everything in place and its
restart story is "delete the partial output by hand" (SURVEY.md §5.3).
"""
from __future__ import annotations

import contextlib
import hashlib
import io as _io
import os
import pickle
import struct
import zipfile
from pathlib import Path

import numpy as np

from disco_tpu.io.audio import write_wav as _write_wav_raw

#: Suffix pattern of the temp files :func:`atomic_write` creates.  A
#: ``*.tmp.<pid>`` file is by construction an abandoned partial write,
#: never a finished artifact; :func:`remove_tmp_litter` (called by the
#: verified-resume paths) deletes survivors of a REAL crash — a process
#: death between ``open`` and ``os.replace`` skips the in-process cleanup
#: that an exception unwind runs.
TMP_SUFFIX = ".tmp"


def remove_tmp_litter(root) -> list:
    """Delete abandoned atomic-write temp files under ``root`` (recursive);
    returns the removed paths.  Only exact ``<name>.tmp.<pid>`` shapes are
    touched, and each is deleted best-effort — litter cleanup must never
    break the resume doing it."""
    root = Path(root)
    removed: list[str] = []
    if not root.is_dir():
        return removed
    for p in root.rglob(f"*{TMP_SUFFIX}.*"):
        stem, _, pid = p.name.rpartition(".")
        if not stem.endswith(TMP_SUFFIX) or not pid.isdigit():
            continue
        with contextlib.suppress(OSError):
            p.unlink()
            removed.append(str(p))
    return removed


def _fsync_dir(path: Path) -> None:
    """Best-effort fsync of a directory entry so a rename survives power
    loss.  Some filesystems refuse O_RDONLY dir fsync — degrade silently;
    the rename is still atomic against process crashes either way."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path, mode: str = "wb", **open_kwargs):
    """Context manager yielding a file handle whose contents appear at
    ``path`` atomically on successful exit.

    ``open_kwargs`` forward to :func:`open` (text-mode writers need e.g.
    ``newline=""`` for the csv module).

    Writes go to ``<path>.tmp.<pid>`` in the same directory (same
    filesystem, so the final ``os.replace`` is atomic), are flushed and
    fsynced, then renamed over ``path``.  On ANY exception the temp file is
    removed and ``path`` is untouched — a crashed writer can never leave a
    truncated artifact at the final location.

    The ``mid_write`` chaos seam (``disco_tpu.runs.chaos``) fires after the
    payload is written but before the rename: an injected crash there
    proves the invariant the chaos gate asserts — tmp litter, complete
    final tree.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.parent / f"{path.name}{TMP_SUFFIX}.{os.getpid()}"
    fh = open(tmp, mode, **open_kwargs)
    try:
        yield fh
        fh.flush()
        os.fsync(fh.fileno())
        fh.close()
        from disco_tpu.runs import chaos

        chaos.tick("mid_write", path=str(path))
        os.replace(tmp, path)
        _fsync_dir(path.parent)
    except BaseException:
        with contextlib.suppress(OSError):
            fh.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def write_bytes_atomic(path, data: bytes) -> Path:
    """Atomic ``Path.write_bytes`` (the flax msgpack checkpoint writer)."""
    with atomic_write(path) as fh:
        fh.write(data)
    return Path(path)


def write_wav_atomic(path, data, fs, subtype: str = "FLOAT") -> Path:
    """Atomic :func:`disco_tpu.io.audio.write_wav`: the RIFF container is
    encoded into memory, then placed with the tmp+fsync+replace protocol —
    a reader can never observe a header without its data chunk."""
    buf = _io.BytesIO()
    _write_wav_raw(buf, data, fs, subtype=subtype)
    return write_bytes_atomic(path, buf.getvalue())


def save_npy_atomic(path, arr, allow_pickle: bool = False) -> Path:
    """Atomic ``np.save``.  Unlike ``np.save(path, ...)``, the final name is
    exactly ``path`` with a ``.npy`` suffix ensured (np.save's own
    append-suffix behavior, made explicit so callers know the artifact
    name they must verify)."""
    path = Path(path)
    if path.suffix != ".npy":
        path = path.with_name(path.name + ".npy")
    with atomic_write(path) as fh:
        np.save(fh, arr, allow_pickle=allow_pickle)
    return path


def savez_atomic(path, **arrays) -> Path:
    """Atomic ``np.savez`` (the per-epoch loss-history artifact)."""
    path = Path(path)
    if path.suffix != ".npz":
        path = path.with_name(path.name + ".npz")
    with atomic_write(path) as fh:
        np.savez(fh, **arrays)
    return path


def dump_pickle_atomic(path, obj, protocol=pickle.HIGHEST_PROTOCOL) -> Path:
    """Atomic ``pickle.dump`` (the per-RIR OIM results dicts)."""
    with atomic_write(path) as fh:
        pickle.dump(obj, fh, protocol=protocol)
    return Path(path)


# -- integrity probes --------------------------------------------------------
def probe_wav(path) -> bool:
    """True iff ``path`` is a structurally complete WAV: RIFF/WAVE magic,
    a parsable fmt chunk, and a data chunk whose declared size fits inside
    the file.  Reads only the chunk headers — O(#chunks), not O(bytes)."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(12)
            if len(head) < 12:
                return False
            riff, _size, wave = struct.unpack("<4sI4s", head)
            if riff != b"RIFF" or wave != b"WAVE":
                return False
            end = os.fstat(fh.fileno()).st_size
            saw_fmt = saw_data = False
            while True:
                chead = fh.read(8)
                if len(chead) < 8:
                    break
                cid, csize = struct.unpack("<4sI", chead)
                if fh.tell() + csize > end:
                    return False  # declared chunk runs past EOF: truncated
                if cid == b"fmt ":
                    saw_fmt = True
                elif cid == b"data":
                    saw_data = True
                fh.seek(csize + (csize % 2), 1)
            return saw_fmt and saw_data
    except OSError:
        return False


def probe_npy(path) -> bool:
    """True iff ``path`` is a complete ``.npy``.

    Public-API only (no ``np.lib.format`` internals, which have changed
    shape across numpy versions): a memory-mapped ``np.load`` validates the
    header and refuses a payload shorter than the (shape, dtype) promise
    without reading the data.  Object arrays (the ``allow_pickle`` infos
    files) cannot be mapped and fall back to a full validating load — as
    does any mmap refusal, so an unmappable-but-intact file still probes
    True and a truncated one still probes False."""
    try:
        arr = np.load(path, mmap_mode="r", allow_pickle=False)
        del arr
        return True
    except Exception:
        try:
            np.load(path, allow_pickle=True)
            return True
        except Exception:
            return False


def probe_npz(path) -> bool:
    """True iff ``path`` is a complete ``.npz``: the zip central directory
    is intact and every member CRC-checks (``zipfile.testzip``)."""
    try:
        with zipfile.ZipFile(path) as zf:
            return zf.testzip() is None
    except Exception:
        return False


def probe_pickle(path) -> bool:
    """True iff ``path`` unpickles to completion.  Full load — the OIM
    result dicts this guards are a few KB, so "cheap" holds; a truncated
    stream raises inside ``pickle`` and reads as not-done."""
    try:
        with open(path, "rb") as fh:
            pickle.load(fh)
        return True
    except Exception:
        return False


def probe_msgpack(path) -> bool:
    """True iff ``path`` parses as a complete flax-serialization msgpack
    stream (structure only — shape compatibility with a concrete TrainState
    is the loader's job, see ``nn.training.load_checkpoint``)."""
    try:
        from flax import serialization

        serialization.msgpack_restore(Path(path).read_bytes())
        return True
    except Exception:
        return False


#: Probe dispatch by suffix (:func:`probe_artifact`).
_PROBES = {
    ".wav": probe_wav,
    ".npy": probe_npy,
    ".npz": probe_npz,
    ".p": probe_pickle,
    ".pkl": probe_pickle,
    ".pickle": probe_pickle,
    ".msgpack": probe_msgpack,
}


def probe_artifact(path) -> bool:
    """Self-validating existence check: True iff ``path`` exists AND its
    format-specific probe passes.  Unknown suffixes degrade to a non-empty
    existence check (still strictly stronger than ``Path.exists``)."""
    path = Path(path)
    try:
        if not path.is_file():
            return False
        probe = _PROBES.get(path.suffix.lower())
        if probe is None:
            return path.stat().st_size > 0
        return probe(path)
    except OSError:
        return False


def file_digest(path, algo: str = "sha256") -> str:
    """Sidecar digest of a finished artifact, ``"sha256:<hex>"`` — what the
    run ledger records per artifact and re-checks on verified resume."""
    h = hashlib.new(algo)
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return f"{algo}:{h.hexdigest()}"


def verify_digest(path, digest: str) -> bool:
    """True iff ``path`` exists and hashes to ``digest`` (same algo)."""
    try:
        algo = digest.split(":", 1)[0]
        return file_digest(path, algo) == digest
    except (OSError, ValueError):
        return False
