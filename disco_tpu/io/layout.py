"""The dataset file layout — the inter-layer contract of the reference.

The reference's layers communicate through a conventional directory/naming
scheme rather than Python objects (SURVEY.md §1; reference
post_generator.py:133-166, dnn/utils.py:110-138, tango.py:75-110,
get_z_signals.py:324-359).  This module is the single source of truth for
those paths, so generated corpora are drop-in compatible both ways:

    {root}/{scenario}/{train|val|test}/
        wav_original/{dry,cnv}/{target,noise}/{rir}_S-{s}[_{noise}]_Ch-{c}.wav
        wav_processed/{snrdir}/{target,noise,mixture}/...
        stft_processed/{raw,normed/abs}/{snrdir}/{...}/...npy
        mask_processed/{snrdir}/{rir}_{noise}_Ch-{c}.npy
        stft_z/{zfile}/{raw,normed/abs}/{snrdir}/{zs_hat,zn_hat}/{rir}_{noise}_Node-{k}.npy
        log/snrs/dry/{snrdir}/{rir}_{noise}.npy
        log/infos/{rir}.npy
"""
from __future__ import annotations

import dataclasses
import os
from pathlib import Path


def snr_dirname(snr_range) -> str:
    """'0-6'-style directory name from an SNR range (post_generator.py:66-68)."""
    return f"{snr_range[0]}-{snr_range[1]}"


@dataclasses.dataclass(frozen=True)
class DatasetLayout:
    """Path factory for one (root, scenario, case) corpus slice."""

    root: str
    scenario: str  # 'random' | 'living' | 'meeting' | 'meetit'
    case: str  # 'train' | 'val' | 'test'

    @property
    def base(self) -> Path:
        return Path(self.root) / self.scenario / self.case

    # -- wav_original (dataset generation output) --------------------------
    def wav_original(self, kind: str, source: str, rir: int, s: int, ch: int, noise: str | None = None) -> Path:
        """kind: 'dry'|'cnv'; source: 'target'|'noise'; 1-based source id ``s``
        and channel ``ch``; noise-type tag for noise files."""
        tag = f"{rir}_S-{s}" + (f"_{noise}" if noise else "") + f"_Ch-{ch}.wav"
        return self.base / "wav_original" / kind / source / tag

    def dry_source(self, source: str, rir: int, s: int, noise: str | None = None) -> Path:
        """Dry source wav — no channel suffix: {rir}_S-{s}[_{noise}].wav
        (convolve_signals.py:305-310)."""
        tag = f"{rir}_S-{s}" + (f"_{noise}" if noise else "") + ".wav"
        return self.base / "wav_original" / "dry" / source / tag

    def cnv_image(self, source: str, rir: int, s: int, ch: int, noise: str | None = None) -> Path:
        """Convolved image wav: {rir}_S-{s}[_{noise}]_Ch-{ch}.wav
        (convolve_signals.py:312-325)."""
        return self.wav_original("cnv", source, rir, s, ch, noise=noise)

    # -- wav_processed / stft_processed / mask_processed (mixing output) ---
    def wav_processed(self, snr_range, source: str, rir: int, ch: int, noise: str | None = None) -> Path:
        tag = f"{rir}" + (f"_{noise}" if noise else "") + f"_Ch-{ch}.wav"
        return self.base / "wav_processed" / snr_dirname(snr_range) / source / tag

    def stft_processed(self, snr_range, source: str, rir: int, ch: int, noise: str | None = None, normed: bool = False) -> Path:
        tag = f"{rir}" + (f"_{noise}" if noise else "") + f"_Ch-{ch}.npy"
        sub = ("normed", "abs") if normed else ("raw",)
        return self.base.joinpath("stft_processed", *sub, snr_dirname(snr_range), source, tag)

    def mask_processed(self, snr_range, rir: int, ch: int, noise: str) -> Path:
        return self.base / "mask_processed" / snr_dirname(snr_range) / f"{rir}_{noise}_Ch-{ch}.npy"

    # -- stft_z (compressed-signal exports for CRNN training) --------------
    def stft_z(self, zfile: str, snr_range, zsig: str, rir: int, node: int, noise: str, normed: bool = False) -> Path:
        """zsig: 'zs_hat' | 'zn_hat'; 1-based node index."""
        sub = ("normed", "abs") if normed else ("raw",)
        return self.base.joinpath(
            "stft_z", zfile, *sub, snr_dirname(snr_range), zsig, f"{rir}_{noise}_Node-{node}.npy"
        )

    # -- logs --------------------------------------------------------------
    def snr_log(self, snr_range, rir: int, noise: str) -> Path:
        return self.base / "log" / "snrs" / "dry" / snr_dirname(snr_range) / f"{rir}_{noise}.npy"

    def infos(self, rir: int) -> Path:
        return self.base / "log" / "infos" / f"{rir}.npy"

    def ensure_dir(self, path: Path) -> Path:
        os.makedirs(path.parent, exist_ok=True)
        return path


def case_of_rir(rir: int, n_samples=(10000, 1000, 1000)) -> str:
    """train/val/test split from a 1-based RIR id against cumulative sample
    counts (post_generator.py:49-64)."""
    cum = [sum(n_samples[: i + 1]) for i in range(len(n_samples))]
    assert 0 < rir <= cum[-1], f"rir should be between 1 and {cum[-1]}"
    return "train" if rir <= cum[0] else "val" if rir <= cum[1] else "test"
