"""WAV I/O with soundfile-compatible float semantics.

The reference reads/writes audio through ``soundfile``/libsndfile
(e.g. tango.py:95-109,605-608): integer PCM is returned as float in
[-1, 1), float files pass through.  libsndfile is not in this image, so the
same contract is provided over ``scipy.io.wavfile``.
"""
from __future__ import annotations

import numpy as np

_PCM_SCALE = {np.dtype(np.int16): 2**15, np.dtype(np.int32): 2**31}


def read_wav(path, dtype=np.float32):
    """Read a WAV file as float in [-1, 1), shape (n_samples,) or
    (n_samples, n_channels).  Returns (signal, fs) — note the (signal, fs)
    order of soundfile.read, which the reference relies on."""
    import scipy.io.wavfile

    fs, data = scipy.io.wavfile.read(str(path))
    if data.dtype in _PCM_SCALE:
        data = data.astype(dtype) / _PCM_SCALE[data.dtype]
    elif data.dtype == np.uint8:  # 8-bit WAV is unsigned
        data = (data.astype(dtype) - 128.0) / 128.0
    else:
        data = data.astype(dtype)
    return data, fs


def write_wav(path, data, fs):
    """Write float audio in [-1, 1) as a float32 WAV (the reference writes
    float via soundfile; float32 WAV preserves that exactly)."""
    import scipy.io.wavfile

    scipy.io.wavfile.write(str(path), int(fs), np.asarray(data, np.float32))
