"""WAV I/O with soundfile-compatible semantics, implemented natively.

The reference reads/writes audio through ``soundfile``/libsndfile
(e.g. tango.py:95-109,605-608): integer PCM is returned as float in [-1, 1),
float files pass through.  libsndfile is not in this image, and
``scipy.io.wavfile`` cannot read or write 24-bit PCM — which real DISCO
corpora written by other tools may use (VERDICT round-1 missing #4) — so the
RIFF container and the PCM codecs are implemented here directly: 8-bit
unsigned, 16/24/32-bit signed PCM and 32/64-bit float, plus
WAVE_FORMAT_EXTENSIBLE headers, for both read and write.
"""
from __future__ import annotations

import struct

import numpy as np

WAVE_FORMAT_PCM = 0x0001
WAVE_FORMAT_IEEE_FLOAT = 0x0003
WAVE_FORMAT_EXTENSIBLE = 0xFFFE

#: write_wav subtypes, named as soundfile names them
SUBTYPES = ("PCM_16", "PCM_24", "PCM_32", "FLOAT", "DOUBLE")


def _decode(raw: bytes, fmt_code: int, bits: int, dtype):
    """Raw data-chunk bytes -> float array in [-1, 1) (PCM) or passthrough
    (float formats)."""
    if fmt_code == WAVE_FORMAT_IEEE_FLOAT:
        src = np.frombuffer(raw, np.float32 if bits == 32 else np.float64)
        return src.astype(dtype)
    if fmt_code != WAVE_FORMAT_PCM:
        raise ValueError(f"unsupported WAV format code 0x{fmt_code:04x}")
    if bits == 8:  # 8-bit WAV is unsigned
        x = np.frombuffer(raw, np.uint8).astype(dtype)
        return (x - 128.0) / 128.0
    if bits == 16:
        return np.frombuffer(raw, "<i2").astype(dtype) / 2.0**15
    if bits == 24:
        b = np.frombuffer(raw, np.uint8).reshape(-1, 3).astype(np.int32)
        x = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16)
        x = (x ^ 0x800000) - 0x800000  # sign-extend 24 -> 32 bits
        return x.astype(dtype) / 2.0**23
    if bits == 32:
        return np.frombuffer(raw, "<i4").astype(dtype) / 2.0**31
    raise ValueError(f"unsupported PCM bit depth {bits}")


def read_wav(path, dtype=np.float32):
    """Read a WAV file as float in [-1, 1), shape (n_samples,) or
    (n_samples, n_channels).  Returns (signal, fs) — note the (signal, fs)
    order of soundfile.read, which the reference relies on."""
    with open(path, "rb") as fh:
        riff, _size, wave = struct.unpack("<4sI4s", fh.read(12))
        if riff != b"RIFF" or wave != b"WAVE":
            raise ValueError(f"{path}: not a RIFF/WAVE file")
        fmt_code = bits = fs = n_ch = None
        data = None
        while True:
            head = fh.read(8)
            if len(head) < 8:
                break
            cid, csize = struct.unpack("<4sI", head)
            if cid == b"fmt ":
                fmt = fh.read(csize)
                fmt_code, n_ch, fs, _byterate, _align, bits = struct.unpack("<HHIIHH", fmt[:16])
                if fmt_code == WAVE_FORMAT_EXTENSIBLE:
                    # sub-format GUID's leading 16 bits carry the real code
                    fmt_code = struct.unpack("<H", fmt[24:26])[0]
            elif cid == b"data":
                data = fh.read(csize)
            else:
                fh.seek(csize, 1)
            if csize % 2:  # RIFF chunks are word-aligned
                fh.seek(1, 1)
        if fmt_code is None or data is None:
            raise ValueError(f"{path}: missing fmt/data chunk")
    x = _decode(data, fmt_code, bits, dtype)
    if n_ch > 1:
        x = x.reshape(-1, n_ch)
    return x, fs


def _encode(x: np.ndarray, subtype: str) -> tuple[bytes, int, int]:
    """Float audio -> (raw bytes, format code, bits per sample)."""
    if subtype == "FLOAT":
        return np.asarray(x, "<f4").tobytes(), WAVE_FORMAT_IEEE_FLOAT, 32
    if subtype == "DOUBLE":
        return np.asarray(x, "<f8").tobytes(), WAVE_FORMAT_IEEE_FLOAT, 64
    # libsndfile clips PCM writes to full scale; the post-round clip keeps
    # rounding at the positive rail from overflowing the integer width
    x = np.clip(np.asarray(x, np.float64), -1.0, 1.0)
    if subtype == "PCM_16":
        v = np.clip((x * 2.0**15).round(), -(2**15), 2**15 - 1)
        return v.astype("<i2").tobytes(), WAVE_FORMAT_PCM, 16
    if subtype == "PCM_32":
        v = np.clip((x * 2.0**31).round(), -(2**31), 2**31 - 1)
        return v.astype("<i4").tobytes(), WAVE_FORMAT_PCM, 32
    if subtype == "PCM_24":
        v = np.clip((x * 2.0**23).round(), -(2**23), 2**23 - 1).astype(np.int32) & 0xFFFFFF
        b = np.empty((v.size, 3), np.uint8)
        b[:, 0] = v & 0xFF
        b[:, 1] = (v >> 8) & 0xFF
        b[:, 2] = (v >> 16) & 0xFF
        return b.tobytes(), WAVE_FORMAT_PCM, 24
    raise ValueError(f"unknown subtype {subtype!r}; one of {SUBTYPES}")


def write_wav(path, data, fs, subtype: str = "FLOAT"):
    """Write float audio in [-1, 1) as WAV.  ``subtype`` selects the sample
    format (soundfile naming): 'FLOAT' (default — preserves the reference's
    float writes exactly), 'DOUBLE', or 'PCM_16'/'PCM_24'/'PCM_32'.

    ``path`` may also be an open binary file object (the atomic writer in
    ``disco_tpu.io.atomic`` encodes into memory, then renames into place).
    """
    data = np.asarray(data)
    n_ch = 1 if data.ndim == 1 else data.shape[1]
    raw, fmt_code, bits = _encode(data.reshape(-1), subtype)
    align = n_ch * bits // 8

    def emit(fh):
        fh.write(struct.pack("<4sI4s", b"RIFF", 36 + len(raw) + (len(raw) % 2), b"WAVE"))
        fh.write(struct.pack("<4sIHHIIHH", b"fmt ", 16, fmt_code, n_ch,
                             int(fs), int(fs) * align, align, bits))
        fh.write(struct.pack("<4sI", b"data", len(raw)))
        fh.write(raw)
        if len(raw) % 2:
            fh.write(b"\x00")

    if hasattr(path, "write"):
        emit(path)
    else:
        with open(path, "wb") as fh:
            emit(fh)
