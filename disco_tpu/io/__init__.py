from disco_tpu.io.audio import read_wav, write_wav
from disco_tpu.io.layout import DatasetLayout

__all__ = ["read_wav", "write_wav", "DatasetLayout"]
