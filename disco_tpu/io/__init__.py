from disco_tpu.io.audio import read_wav, write_wav
from disco_tpu.io.fastwav import read_wavs_batch
from disco_tpu.io.layout import DatasetLayout

__all__ = ["read_wav", "read_wavs_batch", "write_wav", "DatasetLayout"]
