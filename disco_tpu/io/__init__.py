from disco_tpu.io.atomic import (
    atomic_write,
    dump_pickle_atomic,
    file_digest,
    probe_artifact,
    save_npy_atomic,
    savez_atomic,
    verify_digest,
    write_bytes_atomic,
    write_wav_atomic,
)
from disco_tpu.io.audio import read_wav, write_wav
from disco_tpu.io.fastwav import read_wavs_batch
from disco_tpu.io.layout import DatasetLayout

__all__ = [
    "DatasetLayout",
    "atomic_write",
    "dump_pickle_atomic",
    "file_digest",
    "probe_artifact",
    "read_wav",
    "read_wavs_batch",
    "save_npy_atomic",
    "savez_atomic",
    "verify_digest",
    "write_bytes_atomic",
    "write_wav",
    "write_wav_atomic",
]
