"""ctypes bindings for the native threaded WAV batch reader.

The C++ library (``disco_tpu/native/fastwav.cpp``) decodes a whole batch of
mono corpus wavs with a thread pool — the per-RIR ~48-file ingest of
``zexport.load_node_signals`` (reference get_z_signals.py:44-92) in one
call.  Built on demand with g++ (cached next to the source); degrades
gracefully to the pure-Python ``disco_tpu.io.audio.read_wav`` loop when no
compiler is available, with identical decoded samples (same PCM scaling).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from disco_tpu.io.audio import read_wav

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "fastwav.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libfastwav.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib():
    """The loaded shared library, building it on first use; None if
    unavailable (no compiler / unsupported platform)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Rebuild when the source is newer; a prebuilt .so without the
        # source (installed package) is used as-is.
        have_src = os.path.exists(_SRC)
        stale = (
            not os.path.exists(_LIB)
            or (have_src and os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        )
        if stale and (not have_src or not _build()):
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        # fixed-width int64 on both sides of the ABI: the numpy buffers are
        # int64 and C 'long' is 32-bit on LLP64 platforms (ADVICE round 2)
        lib.fast_read_wavs.restype = ctypes.c_int
        lib.fast_read_wavs.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native fastwav library is built and loadable."""
    return get_lib() is not None


def _python_fallback(paths):
    sigs, fss = [], []
    for p in paths:
        x, fs = read_wav(p)
        if x.ndim != 1:
            raise RuntimeError(f"fastwav: {p!r} is not mono")
        sigs.append(np.asarray(x, np.float32))
        fss.append(fs)
    lens = {len(x) for x in sigs}
    if len(lens) != 1:
        raise RuntimeError(f"fastwav: ragged batch, lengths {sorted(lens)}")
    if len(set(fss)) != 1:
        raise RuntimeError(f"fastwav: mixed sample rates {sorted(set(fss))}")
    return np.stack(sigs), fss[0]


def read_wavs_batch(paths, n_threads: int | None = None):
    """Decode many equal-length mono wavs into one (n, L) float32 array.

    Returns (signals, fs).  All files must be mono, the same length and the
    same sample rate — the corpus per-RIR contract; a RuntimeError names
    the offending file otherwise.  Threaded native decode when the library
    is available, else a sequential Python loop with identical samples.
    """
    paths = [os.fspath(p) for p in paths]
    if not paths:
        raise ValueError("read_wavs_batch: empty path list")
    lib = get_lib()
    if lib is None:
        return _python_fallback(paths)

    # probe the first file for the batch geometry (python decoder: shares
    # the failure modes users see on truly broken files)
    x0, fs0 = read_wav(paths[0])
    if x0.ndim != 1:
        raise RuntimeError(f"fastwav: {paths[0]!r} is not mono")
    L = len(x0)
    n = len(paths)
    out = np.empty((n, L), np.float32)
    lens = np.zeros(n, np.int64)
    fss = np.zeros(n, np.int32)
    fail = np.zeros(1, np.int64)
    if n_threads is None:
        n_threads = min(32, os.cpu_count() or 4)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    rc = lib.fast_read_wavs(
        c_paths,
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        L,
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        fss.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        n_threads,
        fail.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        bad = int(fail[0])
        raise RuntimeError(
            f"fastwav: failed reading {paths[bad]!r} (unsupported format, "
            "multichannel, or IO error)"
        )
    if not (lens == L).all():
        bad = int(np.flatnonzero(lens != L)[0])
        raise RuntimeError(
            f"fastwav: ragged batch — {paths[bad]!r} has {int(lens[bad])} "
            f"samples, expected {L}"
        )
    if not (fss == fs0).all():
        bad = int(np.flatnonzero(fss != fs0)[0])
        raise RuntimeError(
            f"fastwav: mixed sample rates — {paths[bad]!r} at {int(fss[bad])} Hz, "
            f"expected {fs0}"
        )
    return out, fs0
