"""Multichannel Wiener filters: SDW-MWF, rank-1 MWF and rank-constrained
GEVD-MWF.

Capability parity with reference ``se_utils/internal_formulas.py:31-81``
(`intern_filter` with types 'mwf', 'r1-mwf', 'gevd'), following Serizel et al.
2014's low-rank GEVD-MWF formulation.  The reference calls
``scipy.linalg.eig(Rxx, Rnn)`` once per (node, freq) bin inside Python loops;
TPUs have no complex non-hermitian generalized eigensolver, and don't need
one: both matrices are hermitian PSD, so the generalized problem is solved by
Cholesky whitening + ``eigh``:

    L = chol(Rnn + δI),   A = L⁻¹ Rxx L⁻ᴴ,   (λ, U) = eigh(A),   Q = L⁻ᴴ U

with ``Q⁻¹ = Uᴴ Lᴴ`` so the first column of ``Q⁻¹`` is
``conj(U[0, :] * L[0, 0])`` in closed form (L lower-triangular) — no matrix
inversion.  Everything is batched over arbitrary leading axes (node, freq,
room, ...) so the whole filter bank is a handful of fused batched linalg calls
instead of ``K × 257`` interpreted eigendecompositions.
"""
from __future__ import annotations

import re
from functools import partial

import jax
import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

from disco_tpu.core.mathx import FLOAT64_EPS

# Eigenvalue clamp range of the reference (internal_formulas.py:6-7,59-62):
# float64 machine epsilon and 1e6.
EIG_FLOOR = FLOAT64_EPS
EIG_CEIL = 1e6
# Relative diagonal loading guaranteeing the Cholesky factorization exists in
# f32 even for near-singular noise covariances (the reference instead relies
# on scipy's non-hermitian solver tolerating them).
DIAG_LOADING = 1e-6


def get_filter_type(name: str):
    """Parse a filter spec like 'gevd', 'rank2-gevd', 'rank12-gevd',
    'gevd-power', 'r1-mwf', 'mwf' (internal_formulas.py:10-28):
    returns (type, rank)."""
    if name == "gevd-power":
        return "gevd-power", 1
    if "gevd" in name:
        if "-" in name:
            m = re.fullmatch(r"rank(\d+)-gevd", name)
            if m is None:
                raise ValueError(
                    f"malformed GEVD filter spec {name!r}; expected 'gevd', 'rankN-gevd' or 'gevd-power'"
                )
            return "gevd", int(m.group(1))
        return "gevd", "full"
    return name, None


def _load_diag(R: jnp.ndarray, rel: float = DIAG_LOADING) -> jnp.ndarray:
    C = R.shape[-1]
    tr = jnp.trace(R, axis1=-2, axis2=-1).real / C
    eye = jnp.eye(C, dtype=R.dtype)
    return R + (rel * tr[..., None, None] + jnp.finfo(R.real.dtype).tiny) * eye


def _whitened(Rxx: jnp.ndarray, Rnn: jnp.ndarray):
    """Shared GEVD prologue: (L, A) with ``L = chol(Rnn + loading)`` and
    ``A = L^-1 Rxx L^-H`` re-hermitized.

    Joint scale normalization first: (Rxx, Rnn) -> (sRxx, sRnn) leaves the
    filter and t1 exactly invariant (L scales by sqrt(s), Q by 1/sqrt(s),
    qinv0 by sqrt(s); the generalized eigenvalues are unchanged), but keeps
    the Cholesky/eigh iterations in float32 range for near-zero
    covariances — required on TPU where warm-up-phase streaming
    covariances are ~1e-12."""
    C = Rnn.shape[-1]
    tr_n = jnp.trace(Rnn, axis1=-2, axis2=-1).real[..., None, None] / C
    scale = 1.0 / jnp.maximum(tr_n, jnp.finfo(Rnn.real.dtype).smallest_normal)
    Rxx = Rxx * scale
    Rnn = Rnn * scale
    L = jnp.linalg.cholesky(_load_diag(Rnn))
    Li_Rxx = solve_triangular(L, Rxx, lower=True)
    A = solve_triangular(L, Li_Rxx.conj().swapaxes(-1, -2), lower=True).conj().swapaxes(-1, -2)
    return L, 0.5 * (A + A.conj().swapaxes(-1, -2))  # re-hermitize vs roundoff


@partial(jax.jit, static_argnames=("rank", "sanitize", "eigh_impl", "sweeps"))
def gevd_mwf(Rxx: jnp.ndarray, Rnn: jnp.ndarray, mu: float = 1.0, rank=1,
             sanitize: bool = True, eigh_impl: str = "xla", sweeps: int | None = None):
    """Rank-``rank`` GEVD-MWF (the 'gevd' branch of internal_formulas.py:56-73).

    Args:
      Rxx: speech covariance, (..., C, C) hermitian.
      Rnn: noise covariance, (..., C, C) hermitian.
      mu: speech-distortion tradeoff.
      rank: int rank constraint, or 'full'.
      sanitize: replace non-finite filters (degenerate bins) with the e1
        pass-through selector.  Pass False when the caller has its own
        fallback policy (e.g. the streaming pipeline keeps the previous
        block's filter instead).
      eigh_impl: the batched hermitian eigensolver — 'xla'
        (``jnp.linalg.eigh``), 'jacobi' (fixed-sweep cyclic Jacobi,
        ``disco_tpu.ops.eigh_ops.eigh_jacobi``) or 'jacobi-pallas' (the
        same schedule as one fused VMEM kernel).
      sweeps: Jacobi sweep count for the 'jacobi'/'jacobi-pallas' impls
        (static; ignored by 'xla').  None -> the size-adaptive
        ``eigh_ops.default_sweeps``.

    Returns:
      (W, t1): filter (..., C) and the GEVD reference-selection vector
      ``t1 = Q[:, 0] * (Q⁻¹)[0, 0]`` (..., C).
    """
    C = Rxx.shape[-1]
    L, A = _whitened(Rxx, Rnn)
    if eigh_impl == "xla":
        lam, U = jnp.linalg.eigh(A)  # ascending
    elif eigh_impl == "jacobi":
        from disco_tpu.ops.eigh_ops import eigh_jacobi

        lam, U = eigh_jacobi(A, sweeps=sweeps)
    elif eigh_impl == "jacobi-pallas":
        from disco_tpu.ops.eigh_ops import eigh_jacobi_pallas

        from disco_tpu.utils.backend import is_tpu

        # interpret off-TPU: the Mosaic lowering is TPU-only, and the
        # interpreter makes the branch testable on any backend.  Keyed off
        # the device kind, not the platform string — plugin platforms
        # (e.g. the tunneled 'axon' attachment) are real TPUs.
        lam, U = eigh_jacobi_pallas(A, sweeps=sweeps, interpret=not is_tpu())
    else:
        raise ValueError(
            f"unknown eigh_impl {eigh_impl!r}; expected 'xla', 'jacobi' or 'jacobi-pallas'"
        )
    lam = lam[..., ::-1]
    U = U[..., ::-1]
    lam = jnp.clip(lam, EIG_FLOOR, EIG_CEIL)

    # Q = L⁻ᴴ U ; (Q⁻¹)[i, 0] = conj(U[0, i] * L[0, 0])
    Q = solve_triangular(L.conj().swapaxes(-1, -2), U, lower=False)
    qinv_col0 = jnp.conj(U[..., 0, :] * L[..., 0:1, 0])

    gains = lam / (lam + mu)
    if rank != "full":
        keep = jnp.arange(C) < rank
        gains = jnp.where(keep, gains, 0.0)
    W = jnp.einsum("...ci,...i->...c", Q, gains.astype(Q.dtype) * qinv_col0)
    t1 = Q[..., :, 0] * qinv_col0[..., 0:1]
    if not sanitize:
        return W, t1
    # Degenerate-bin guard: if the f32 Cholesky/eigh emitted non-finite
    # values for a bin (near-singular noise stats survive the diagonal
    # loading only up to hardware precision), fall back to the e1 selector —
    # pass the reference channel through rather than poisoning the clip.
    e1 = jnp.zeros_like(W).at[..., 0].set(1.0)
    ok = jnp.isfinite(W.real) & jnp.isfinite(W.imag)
    ok = ok.all(axis=-1, keepdims=True)
    W = jnp.where(ok, W, e1)
    t1 = jnp.where(ok, t1, e1)
    return W, t1


@partial(jax.jit, static_argnames=("iters", "sanitize"))
def gevd_mwf_power(Rxx: jnp.ndarray, Rnn: jnp.ndarray, mu: float = 1.0, iters: int = 12,
                   sanitize: bool = True):
    """Rank-1 GEVD-MWF via power iteration on the whitened matrix.

    The rank-1 filter needs ONLY the dominant whitened eigenpair:
    ``W = q1 * g1 * (Q^-1)[0,0]`` with ``q1 = L^-H u1`` and ``(Q^-1)[0,0] =
    conj(u1[0] L[0,0])`` — so the full batched ``eigh`` (QR iterations,
    the serial bottleneck of the TPU pipeline) can be replaced by ``iters``
    matvecs.  Accuracy equals ``gevd_mwf(rank=1)`` to f32 roundoff wherever
    the speech field has a clear dominant direction (measured ~2e-7 on
    rank-1 scenes; bins with a weak eigengap converge more slowly but carry
    small Wiener gains).  Since round 4 this is the OFFLINE PIPELINE
    DEFAULT (tango/driver/mesh solver defaults), flipped on the round-3
    on-device A/B (exp/tpu_validation_r3.jsonl solver_ab: 6722x RTF vs
    eigh's 4833x at 49 dB output agreement, <=0.1 dB pinned SDR delta);
    ``rank1_gevd``'s own default stays 'eigh' (reference-bit-matching
    primitive), and streaming keeps 'eigh' (weak warm-up eigengaps).
    """
    C = Rxx.shape[-1]
    L, A = _whitened(Rxx, Rnn)

    # Derived from A (not a fresh constant) so the scan carry keeps A's
    # device-varying type under shard_map — a replicated init would fail the
    # carry typecheck on a node-sharded mesh.
    v = jnp.zeros_like(A[..., 0]) + 1.0 / jnp.sqrt(C)

    def body(v, _):
        w = jnp.einsum("...cd,...d->...c", A, v)
        return w / jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True),
                               jnp.finfo(A.real.dtype).tiny), None

    v, _ = jax.lax.scan(body, v, None, length=iters)
    lam = jnp.clip(
        jnp.real(jnp.einsum("...c,...cd,...d->...", jnp.conj(v), A, v)),
        EIG_FLOOR, EIG_CEIL,
    )
    q1 = solve_triangular(L.conj().swapaxes(-1, -2), v[..., None], lower=False)[..., 0]
    qinv00 = jnp.conj(v[..., 0] * L[..., 0, 0])
    g = (lam / (lam + mu)).astype(q1.dtype)
    W = q1 * (g * qinv00)[..., None]
    t1 = q1 * qinv00[..., None]
    if not sanitize:
        return W, t1
    e1 = jnp.zeros_like(W).at[..., 0].set(1.0)
    ok = (jnp.isfinite(W.real) & jnp.isfinite(W.imag)).all(-1, keepdims=True)
    return jnp.where(ok, W, e1), jnp.where(ok, t1, e1)


# THE solver-spec grammar, re-exported from its stdlib-only home
# (disco_tpu/solver_spec.py — jax-free so the serve client and argparse
# can validate specs without importing jax; this module keeps the names
# because the dispatch table is the grammar's primary consumer).
from disco_tpu.solver_spec import (  # noqa: E402  (dataflow grouping)
    FUSED_IMPLS as _FUSED_IMPLS,
)
from disco_tpu.solver_spec import (  # noqa: E402,F401  (re-export)
    RANK1_SOLVERS,
    parse_solver_spec,
)


def rank1_gevd(Rss, Rnn, mu: float = 1.0, solver: str = "eigh", sanitize: bool = True,
               precision: str = "f32"):
    """Rank-1 GEVD-MWF by solver spec — THE dispatch table shared by the
    offline TANGO steps, the streaming refreshes and ``intern_filter``:

    * ``'eigh'`` — batched eigendecomposition (:func:`gevd_mwf` at rank 1);
      bit-matches the reference semantics.
    * ``'power'`` / ``'power:N'`` — dominant-eigenpair power iteration
      (:func:`gevd_mwf_power`, N iterations, default 12).  Same filter to
      f32 roundoff on offline frame-mean covariances at a fraction of the
      eigensolve cost; streaming warm-up covariances with weak eigengaps
      need ``power:N`` with larger N (see tests/test_streaming.py).
    * ``'jacobi'`` / ``'jacobi-pallas'`` (optionally ``':N'`` for an
      explicit sweep count; default size-adaptive, eigh_ops.default_sweeps)
      — fixed-sweep cyclic Jacobi full eigendecomposition
      (``disco_tpu.ops.eigh_ops``), as a statically unrolled XLA schedule
      or one fused VMEM pallas kernel (the eigensolve alone; whiten and
      filter formation stay separate XLA stages).
    * ``'fused'`` / ``'fused-xla'`` / ``'fused-pallas'`` (optionally
      ``':N'`` Jacobi sweeps) — the WHOLE solve chain (scale-normalize ->
      diagonal-load -> Cholesky whiten -> fixed-sweep Jacobi -> rank-1
      back-substitution -> filter weights) as one VMEM-resident program
      (``disco_tpu.ops.mwf_ops.rank1_gevd_fused``): the (F, C, C)
      intermediates never touch HBM and only the (F, C) weights are
      written back.  ``'fused'`` resolves per backend through the shared
      ``ops.resolve`` policy (pallas on real TPUs, XLA elsewhere;
      ``DISCO_TPU_MWF_IMPL`` escape hatch); the explicit suffixes pin the
      lane.  The only solver family that consumes ``precision``:
      ``'bf16'`` quantizes the pencil planes at the HBM->VMEM boundary
      with every in-VMEM iteration in f32 (documented looser tolerances,
      tests/test_mwf_ops.py).

    ``precision`` is ignored by the non-fused solvers (their programs are
    pinned bit-identical by the trace goldens).
    """
    base, n = parse_solver_spec(solver)
    if base == "eigh":
        return gevd_mwf(Rss, Rnn, mu=mu, rank=1, sanitize=sanitize)
    if base in _FUSED_IMPLS:
        from disco_tpu.ops.mwf_ops import rank1_gevd_fused

        return rank1_gevd_fused(Rss, Rnn, mu=mu, impl=_FUSED_IMPLS[base],
                                sweeps=n, precision=precision, sanitize=sanitize)
    if base in ("jacobi", "jacobi-pallas"):
        return gevd_mwf(Rss, Rnn, mu=mu, rank=1, sanitize=sanitize, eigh_impl=base, sweeps=n)
    if n is None:
        return gevd_mwf_power(Rss, Rnn, mu=mu, sanitize=sanitize)
    return gevd_mwf_power(Rss, Rnn, mu=mu, iters=n, sanitize=sanitize)


def solver_lane_info(spec: str) -> dict:
    """Resolved provenance of a solver spec for bench records: the parsed
    base/N plus the CONCRETE kernel implementation the spec runs on this
    backend (post-``ops.resolve`` for the fused family) — so a bench
    record distinguishes 'jacobi' XLA from pallas from the fused kernel
    without re-running.

    No reference counterpart: bench provenance is a TPU-port concern.
    """
    base, n = parse_solver_spec(spec)
    if base in _FUSED_IMPLS:
        from disco_tpu.ops.mwf_ops import resolve_mwf_impl

        impl = resolve_mwf_impl(_FUSED_IMPLS[base])
    elif base in ("jacobi-pallas",):
        impl = "pallas"
    else:  # eigh / power / jacobi are XLA formulations
        impl = "xla"
    return {"spec": spec, "base": base, "n": n, "impl": impl}


@jax.jit
def r1_mwf(Rxx: jnp.ndarray, Rnn: jnp.ndarray, mu: float = 1.0):
    """Rank-1 SDW-MWF (the 'r1-mwf' branch of internal_formulas.py:45-54):
    project Rxx onto its dominant eigenpair, then ``W = P[:, 0]/(μ + tr P)``
    with ``P = Rnn⁻¹ Rxx₁``."""
    lam, V = jnp.linalg.eigh(0.5 * (Rxx + Rxx.conj().swapaxes(-1, -2)))
    vmax = V[..., :, -1]
    lmax = jnp.abs(lam[..., -1])
    Rxx1 = lmax[..., None, None] * jnp.einsum("...c,...d->...cd", vmax, jnp.conj(vmax))
    P = jnp.linalg.solve(_load_diag(Rnn), Rxx1)
    tr = jnp.trace(P, axis1=-2, axis2=-1)
    return P[..., :, 0] / (mu + tr[..., None])


@jax.jit
def mwf(Rxx: jnp.ndarray, Rnn: jnp.ndarray):
    """Plain MWF (the 'mwf' branch of internal_formulas.py:74-76):
    ``W = (Rxx + Rnn)⁻¹ Rxx e1``."""
    return jnp.linalg.solve(_load_diag(Rxx + Rnn), Rxx)[..., :, 0]


def intern_filter(Rxx, Rnn, mu: float = 1.0, ftype: str = "r1-mwf", rank="full"):
    """Dispatching wrapper mirroring the reference ``intern_filter`` surface
    (internal_formulas.py:31-81), including its defaults (type 'r1-mwf',
    rank 'Full').  Returns (W, t1); t1 is the e1 selector for non-GEVD types,
    as in the reference."""
    if ftype == "gevd":
        return gevd_mwf(Rxx, Rnn, mu=mu, rank=rank)
    if ftype == "gevd-power":
        if rank != 1:
            raise ValueError("the 'gevd-power' solver is rank-1 only; pass rank=1")
        return rank1_gevd(Rxx, Rnn, mu=mu, solver="power")
    C = Rxx.shape[-1]
    t1 = jnp.zeros(Rxx.shape[:-2] + (C,), Rxx.dtype).at[..., 0].set(1.0)
    if ftype == "r1-mwf":
        return r1_mwf(Rxx, Rnn, mu=mu), t1
    if ftype == "mwf":
        return mwf(Rxx, Rnn), t1
    raise AttributeError("Unknown filter reference")
