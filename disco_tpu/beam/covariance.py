"""Spatial covariance estimation.

The reference accumulates per-frame outer products in nested Python loops over
(freq, frame) (tango.py:357-364,433-440) and has a separate online
exponential-smoothing variant (se_utils/internal_formulas.py:84-103).  Here
both are single einsum contractions, batched over any leading axes — on TPU the
(C,T)x(T,C) contraction per frequency bin lands on the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


from functools import partial


@partial(jax.jit, static_argnames=("axis_name",))
def frame_mean_covariance(
    a: jnp.ndarray, b: jnp.ndarray | None = None, axis_name: str | None = None
) -> jnp.ndarray:
    """Frame-averaged spatial covariance.

    Args:
      a: STFT stack, shape (..., C, F, T).
      b: optional second stack for cross-covariance (defaults to ``a``).
      axis_name: when the frame axis is sharded over a mesh axis (sequence
        parallelism, SURVEY.md §5.7), pass that axis name — local partial
        sums are ``psum``-reduced so every shard gets the global mean.

    Returns:
      (..., F, C, C) complex covariance: ``mean_t a[...,c,f,t] conj(b[...,d,f,t])``
      — the offline frame-mean estimator of reference tango.py:357-364.
    """
    b = a if b is None else b
    T = a.shape[-1]
    # HIGHEST precision: the TPU default (bf16 operands) accumulates ~1e-2
    # relative error over the frame reduction, which can leave the noise
    # covariance numerically indefinite — Cholesky in the GEVD then emits
    # NaN bins (observed on hardware at C+K-1 = 5 stacked channels).
    cov = jnp.einsum("...cft,...dft->...fcd", a, jnp.conj(b),
                     precision=jax.lax.Precision.HIGHEST)
    if axis_name is not None:
        cov = jax.lax.psum(cov, axis_name)
        T = T * jax.lax.psum(1, axis_name)
    return cov / T


@jax.jit
def masked_covariances(y: jnp.ndarray, mask: jnp.ndarray):
    """Speech/noise covariances from a mixture and a TF mask.

    The reference forms ``s_hat = m * y`` and ``n_hat = (1-m) * y`` per channel
    (tango.py:347-348) then frame-averages outer products.  Fused here.

    Args:
      y: mixture STFT, (..., C, F, T).
      mask: real TF mask, (..., F, T) — broadcast over channels.

    Returns:
      (Rss, Rnn), each (..., F, C, C).
    """
    m = mask[..., None, :, :]
    s_hat = m * y
    n_hat = (1.0 - m) * y
    return frame_mean_covariance(s_hat), frame_mean_covariance(n_hat)


@jax.jit
def smoothed_covariance(
    R: jnp.ndarray, x: jnp.ndarray, lambda_cor: float = 0.95, mask=None
) -> jnp.ndarray:
    """One step of exponential smoothing ``R <- λR + (1-λ)[m] x xᴴ`` — the
    online/streaming estimator of internal_formulas.py:84-103, for frame-by-
    frame operation (scan over frames in a streaming pipeline).

    Args:
      R: previous estimate, (..., C, C).
      x: current frame, (..., C).
      mask: optional scalar/broadcastable mask weight applied to the update.
    """
    upd = jnp.einsum("...c,...d->...cd", x, jnp.conj(x))
    if mask is not None:
        upd = mask[..., None, None] * upd
    return lambda_cor * R + (1.0 - lambda_cor) * upd
