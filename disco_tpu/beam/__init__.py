from disco_tpu.beam.covariance import (
    frame_mean_covariance,
    masked_covariances,
    smoothed_covariance,
)
from disco_tpu.beam.filters import (
    get_filter_type,
    mwf,
    r1_mwf,
    gevd_mwf,
    gevd_mwf_power,
    rank1_gevd,
    intern_filter,
)

__all__ = [
    "frame_mean_covariance",
    "masked_covariances",
    "smoothed_covariance",
    "get_filter_type",
    "mwf",
    "r1_mwf",
    "gevd_mwf",
    "gevd_mwf_power",
    "rank1_gevd",
    "intern_filter",
]
