"""Dynamic scenes: piecewise-stationary trajectories with crossfaded RIRs.

SURVEY §L2 names moving sources and time-varying node positions as the
scenario axis the static corpus never exercises.  The image-source model is
only defined for a frozen geometry, so a moving scene is approximated the
way perceptual RIR interpolation does it: the trajectory is sampled at K
segment waypoints, each segment gets its own static RIR, and the per-segment
wet signals are blended with raised-cosine crossfades at the segment
boundaries — piecewise-stationary acoustics with no hard switching clicks.

The whole engine is ONE compiled program: the K segment RIRs are a ``vmap``
over the existing :func:`disco_tpu.sim.ism.shoebox_rir` lattice scatter, the
K convolutions one batched rFFT, and the blend a ``lax.scan`` over segments
(explicit ``unroll=1`` — the DL011 bit-exactness discipline: scan order is
the summation order) accumulating weighted segment streams into the output.

``make scene-check`` pins the continuity property: the crossfaded mixture's
worst boundary-sample jump is bounded by the in-segment jump scale, while a
hard-switched blend (crossfade 0) shows the click.

No reference counterpart: the reference corpus is static rooms only
(``gen_disco/convolve_signals.py``; SURVEY §L2 gap list).
"""
from __future__ import annotations

from functools import partial

import numpy as np

from disco_tpu.obs.accounting import counted_jit


def piecewise_trajectory(start, end, n_segments: int) -> np.ndarray:
    """(K, 3) segment waypoints linearly interpolating start → end (the
    midpoint of each segment — a constant-velocity walk sampled at segment
    centers).

    No reference counterpart (module docstring)."""
    start = np.asarray(start, np.float32)
    end = np.asarray(end, np.float32)
    if n_segments < 1:
        raise ValueError(f"n_segments must be >= 1, got {n_segments}")
    frac = (np.arange(n_segments, dtype=np.float32) + 0.5) / n_segments
    return start[None, :] + frac[:, None] * (end - start)[None, :]


def segment_weights(n_samples: int, n_segments: int, crossfade: int):
    """(K, n_samples) float32 blend weights: segment k owns samples
    ``[k*seg, (k+1)*seg)`` with a raised-cosine handover of ``crossfade``
    samples centered on each interior boundary.  Rows sum to 1 everywhere
    (constant-power-sum crossfade in the amplitude domain, the overlap-add
    complement convention).

    Host-side numpy: the weights depend only on static shapes, so they are
    a compile-time constant of the dynamic program.

    No reference counterpart (module docstring)."""
    if n_segments < 1:
        raise ValueError(f"n_segments must be >= 1, got {n_segments}")
    seg = n_samples / float(n_segments)
    t = np.arange(n_samples, dtype=np.float64)
    w = np.zeros((n_segments, n_samples), np.float64)
    half = max(int(crossfade), 0) / 2.0
    for k in range(n_segments):
        lo, hi = k * seg, (k + 1) * seg
        if half == 0:
            w[k] = (t >= lo) & (t < hi) if k < n_segments - 1 else (t >= lo)
            continue
        # Ramp up across [lo-half, lo+half) (skipped at the first segment),
        # down across [hi-half, hi+half) (skipped at the last).
        up = np.clip((t - (lo - half)) / (2 * half), 0.0, 1.0) if k > 0 else np.ones_like(t)
        dn = np.clip(((hi + half) - t) / (2 * half), 0.0, 1.0) if k < n_segments - 1 else np.ones_like(t)
        ramp_up = 0.5 - 0.5 * np.cos(np.pi * up)
        ramp_dn = 0.5 - 0.5 * np.cos(np.pi * dn)
        w[k] = ramp_up * ramp_dn
    w /= np.maximum(w.sum(0, keepdims=True), 1e-12)
    return w.astype(np.float32)


@counted_jit(label="dynamic_scene",
             static_argnames=("n_segments", "crossfade", "max_order", "rir_len", "fs"))
def _dynamic_scene_program(room_dim, src_path, mic_path, alpha, dry,
                           n_segments: int, crossfade: int,
                           max_order: int, rir_len: int, fs: int):
    """The one compiled dynamic-scene program — see
    :func:`dynamic_scene_mixture`.

    No reference counterpart (module docstring)."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.sim.ism import fft_convolve, shoebox_rir

    L = dry.shape[-1]
    # K segment RIRs in one lattice-scatter batch: (K, M, rir_len).
    rirs = jax.vmap(
        lambda src, mc: shoebox_rir(room_dim, src, mc, alpha,
                                    max_order=max_order, rir_len=rir_len, fs=fs)
    )(src_path, mic_path)
    # Each segment hears the WHOLE dry signal through its frozen room —
    # (K, M, L) — and the blend picks each segment's window.  Convolving
    # full-length (vs per-segment slices) is what makes the crossfade
    # click-free: both sides of a boundary carry the same source material.
    wet = fft_convolve(dry[None, None, :], rirs, out_len=L)
    weights = jnp.asarray(segment_weights(L, n_segments, crossfade))  # (K, L)

    def blend_step(acc, seg):
        wet_k, w_k = seg
        # scan, not a vmap-sum: the accumulation order is the segment order,
        # bit-stable across K (DL011 — the continuity bound is asserted to
        # tolerance, the crash-resume tree to identity).
        return acc + wet_k * w_k[None, :], None

    out, _ = jax.lax.scan(blend_step, jnp.zeros_like(wet[0]), (wet, weights),
                          unroll=1)
    return {"mixture": out, "rirs": rirs}


def dynamic_scene_mixture(room_dim, src_path, mics, alpha, dry, *,
                          crossfade: int = 512, max_order: int = 20,
                          rir_len: int = 4096, fs: int = 16000,
                          mic_path=None) -> dict:
    """Nonstationary mixture of one moving scene, in ONE dispatch.

    Args:
      room_dim: (3,) room dimensions.
      src_path: (K, 3) per-segment source waypoints
        (:func:`piecewise_trajectory`); K = number of stationary segments.
      mics: (M, 3) static mic positions — or pass ``mic_path`` (K, M, 3)
        for time-varying node positions (SURVEY §L2's second moving axis).
      alpha: wall energy absorption.
      dry: (L,) dry source signal.
      crossfade: boundary handover width in samples (0 = hard switch —
        the click the gate's continuity leg measures against).

    Returns numpy ``{"mixture": (M, L), "rirs": (K, M, rir_len)}`` via one
    batched readback.
    """
    import jax.numpy as jnp

    from disco_tpu.utils.transfer import device_get_tree

    src_path = np.asarray(src_path, np.float32)
    K = int(src_path.shape[0])
    if mic_path is None:
        mic_path = np.broadcast_to(np.asarray(mics, np.float32)[None], (K,) + np.shape(mics))
    mic_path = np.ascontiguousarray(mic_path, np.float32)
    out = _dynamic_scene_program(
        jnp.asarray(room_dim, jnp.float32), jnp.asarray(src_path),
        jnp.asarray(mic_path), jnp.float32(alpha),
        jnp.asarray(dry, jnp.float32),
        n_segments=K, crossfade=int(crossfade),
        max_order=int(max_order), rir_len=int(rir_len), fs=int(fs),
    )
    return device_get_tree(out)


def boundary_jumps(mixture: np.ndarray, n_segments: int) -> np.ndarray:
    """Max |x[t] - x[t-1]| across any channel AT each interior segment
    boundary — the discontinuity statistic the scene-check continuity leg
    bounds (a hard-switched blend clicks exactly there).

    No reference counterpart (module docstring)."""
    x = np.asarray(mixture)
    L = x.shape[-1]
    seg = L / float(n_segments)
    jumps = []
    for k in range(1, int(n_segments)):
        t = int(round(k * seg))
        if 1 <= t < L:
            jumps.append(float(np.max(np.abs(x[..., t] - x[..., t - 1]))))
    return np.asarray(jumps)
