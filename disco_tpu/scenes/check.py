"""``make scene-check`` — the batched scenario-factory gate (seventeenth gate).

Proves the scenes subsystem end to end, hermetically (CPU backend forced by
the Makefile, compile cache off, ONE jax process, zero SIGKILLs):

1. **Oracle parity**: the batched ISM engine
   (:func:`~disco_tpu.sim.ism.shoebox_rirs_batched`) matches an
   independent loop-based float64 NumPy Allen & Berkley oracle (inlined
   below, the same physics as ``tests/reference_impls.shoebox_rir_np``)
   per (scene, source, mic) at relative error < 2e-4.
2. **Batched = per-scene**: the (B,) scene axis is pure vmap — batched
   RIRs match B independent :func:`~disco_tpu.sim.ism.shoebox_rirs`
   dispatches in the same ``(max_order, rir_len)`` bucket bit-for-bit
   (atol 1e-6; identical program, different batching).
3. **One dispatch per batch + retrace budget**: simulating a B=8 scene
   batch is exactly ONE batched readback (fence accounting, the ISSUE's
   acceptance criterion), and the ``scene_batch`` program retraces
   exactly once per distinct bucket — a second same-bucket batch adds
   ZERO recompiles.
4. **Dynamic continuity**: crossfaded segment weights sum to one
   everywhere, and on a smooth (sine) dry signal the worst boundary jump
   of a crossfaded moving-source mixture is under half the hard-switch
   (crossfade=0) jump of the same trajectory — the overlap-add crossfade
   demonstrably removes the segment-boundary click.
5. **Chaos crash-and-resume**: a :class:`~disco_tpu.runs.chaos.ChaosCrash`
   at the ``between_scenes`` seam inside ``disco-gen --batched`` dies like
   a process death; the resumed run (same seed) completes the corpus and
   the artifact tree is **byte-identical** to an uninterrupted run — the
   per-scene ``(seed, rir_id, stream)`` reseeding discipline at work.
6. **SceneStream determinism + verified resume**: the training feed's
   (seed, epoch) batch stream is deterministic, a RunLedger-armed epoch
   replays to zero duplicate scene batches, and a chaos crash at the
   ``between_scene_batches`` seam resumes to exactly the missing batches
   (crashed + resumed == uninterrupted).

No reference counterpart: the reference pre-generates its corpus to disk
with per-scene pyroomacoustics loops and has no on-line scenario factory
(SURVEY.md §0, gen_disco/convolve_signals.py).
"""
from __future__ import annotations

import json
import sys
import tempfile
from pathlib import Path

FS = 16000

#: oracle-parity bound: float32 engine vs float64 loop oracle, relative
#: l2 error per RIR (tests/test_sim.py pins the per-scene path at 1e-4;
#: the batched engine shares its kernel, measured ~2e-5 on this workload).
ORACLE_RTOL = 2e-4

#: dynamic-continuity bound: the crossfaded boundary jump must be under
#: this fraction of the hard-switch jump on the same (sine-dry) scene —
#: measured ~0.1 on the gate workload, 0.5 leaves margin while still
#: failing if the crossfade stops doing its job.
CROSSFADE_JUMP_RATIO = 0.5


def _oracle_rir_np(room_dim, source, mic, alpha, max_order, rir_len,
                   fs=FS, c=343.0, fdl=81):
    """Loop-based float64 Allen & Berkley shoebox ISM oracle — independent
    of disco_tpu.sim (no jax, no shared helpers; the same physics as the
    tests/reference_impls.py oracle that pins the per-scene kernel):
    sum-order truncation, uniform sqrt(1-alpha) wall reflection,
    1/(4 pi d) spreading, windowed-sinc fractional delays.

    Reference counterpart: pyroomacoustics libroom conventions as used by
    gen_disco/convolve_signals.py:84-99 (SURVEY.md §L1)."""
    import numpy as np

    room_dim = np.asarray(room_dim, np.float64)
    source = np.asarray(source, np.float64)
    mic = np.asarray(mic, np.float64)
    beta = np.sqrt(max(1.0 - float(alpha), 0.0))
    half = fdl // 2
    rir = np.zeros(rir_len)
    N = max_order
    for n in range(-N, N + 1):
        for l in range(-N, N + 1):  # noqa: E741 — ISM lattice convention
            for m in range(-N, N + 1):
                for u in (0, 1):
                    for v in (0, 1):
                        for w in (0, 1):
                            n_refl = (abs(n - u) + abs(n) + abs(l - v)
                                      + abs(l) + abs(m - w) + abs(m))
                            if n_refl > N:
                                continue
                            img = np.array([
                                (1 - 2 * u) * source[0] + 2 * n * room_dim[0],
                                (1 - 2 * v) * source[1] + 2 * l * room_dim[1],
                                (1 - 2 * w) * source[2] + 2 * m * room_dim[2],
                            ])
                            d = max(np.linalg.norm(img - mic), 1e-3)
                            amp = beta ** n_refl / (4 * np.pi * d)
                            delay = d * fs / c
                            t0 = int(np.floor(delay))
                            frac = delay - t0
                            for tap in range(-half, half + 1):
                                t = t0 + tap
                                if 0 <= t < rir_len:
                                    arg = tap - frac
                                    win = 0.5 * (1 + np.cos(np.pi * arg / (half + 1)))
                                    rir[t] += amp * np.sinc(arg) * win
    return rir


def _check_oracle_parity(failures: list) -> dict:
    """Experiment 1: batched engine vs the inlined float64 oracle."""
    import numpy as np

    from disco_tpu.sim import shoebox_rirs_batched

    max_order, rir_len = 2, 1024
    dims = np.array([[4.0, 3.0, 2.5], [5.5, 4.0, 3.0]], np.float32)
    srcs = np.array([[[1.0, 1.2, 1.1]], [[1.5, 2.0, 1.4]]], np.float32)
    mics = np.array([[[2.5, 2.0, 1.3], [3.0, 1.0, 1.2]],
                     [[3.5, 2.5, 1.5], [4.0, 3.0, 1.8]]], np.float32)
    alphas = np.array([0.35, 0.5], np.float32)
    got = np.asarray(shoebox_rirs_batched(dims, srcs, mics, alphas,
                                          max_order=max_order,
                                          rir_len=rir_len))
    worst = 0.0
    for b in range(2):
        for mi in range(2):
            want = _oracle_rir_np(dims[b], srcs[b, 0], mics[b, mi],
                                  alphas[b], max_order, rir_len)
            err = float(np.linalg.norm(got[b, 0, mi] - want)
                        / np.linalg.norm(want))
            worst = max(worst, err)
            if err > ORACLE_RTOL:
                failures.append(
                    f"oracle: batched RIR (scene {b}, mic {mi}) off the "
                    f"float64 oracle by rel {err:g} > {ORACLE_RTOL:g}"
                )
    return {"oracle_rel_err": worst}


def _check_batched_equals_per_scene(failures: list) -> dict:
    """Experiment 2: the (B,) axis is pure vmap — batched == per-scene."""
    import numpy as np

    from disco_tpu.scenes import draw_scene_batch, scene_batch_bucket, simulate_scene_batch
    from disco_tpu.sim import shoebox_rirs

    rng = np.random.default_rng(41)
    batch = draw_scene_batch(rng, 3, duration_s=0.5,
                             setup_overrides={"n_sensors_per_node": (2, 2)})
    max_order, rir_len = scene_batch_bucket(batch, max_order=4)
    out = simulate_scene_batch(batch, max_order=4)
    worst = 0.0
    for b in range(batch.n_scenes):
        single = np.asarray(shoebox_rirs(
            batch.room_dims[b], batch.sources[b], batch.mics[b],
            float(batch.alphas[b]), max_order=max_order, rir_len=rir_len))
        err = float(np.abs(out["rirs"][b] - single).max())
        worst = max(worst, err)
        if err > 1e-6:
            failures.append(
                f"vmap-parity: scene {b} batched RIRs differ from the "
                f"per-scene dispatch by {err:g} > 1e-6"
            )
    # the factory's derived products are finite and the mask is a mask
    for k in ("noisy", "clean", "mag_noisy"):
        if not np.isfinite(out[k]).all():
            failures.append(f"vmap-parity: non-finite values in {k!r}")
    if not (np.all(out["mask"] >= 0) and np.all(out["mask"] <= 1)):
        failures.append("vmap-parity: IRM mask left [0, 1]")
    return {"vmap_max_abs_err": worst, "bucket_rir_len": rir_len}


def _check_dispatch_budget(failures: list) -> dict:
    """Experiment 3: one readback per batch, one retrace per bucket."""
    import numpy as np

    from disco_tpu.obs.accounting import device_get_count, recompile_count
    from disco_tpu.scenes import draw_scene_batch, simulate_scene_batch

    rng = np.random.default_rng(43)
    overrides = {"n_sensors_per_node": (2, 2)}

    g0, r0 = device_get_count(), recompile_count("scene_batch")
    first = draw_scene_batch(rng, 8, duration_s=0.5, setup_overrides=overrides)
    simulate_scene_batch(first, max_order=2)
    gets_first = device_get_count() - g0
    if gets_first != 1:
        failures.append(
            f"dispatch: a B=8 scene batch cost {gets_first} batched "
            "readbacks — the acceptance criterion is exactly ONE"
        )
    retraces_first = recompile_count("scene_batch") - r0
    if retraces_first != 1:
        failures.append(
            f"dispatch: first B=8 batch retraced {retraces_first}×, "
            "expected exactly 1 (a fresh bucket compiles once)"
        )
    # same bucket again: zero recompiles, still one readback each
    g1, r1 = device_get_count(), recompile_count("scene_batch")
    again = draw_scene_batch(rng, 8, duration_s=0.5, setup_overrides=overrides)
    simulate_scene_batch(again, max_order=2)
    if recompile_count("scene_batch") - r1 != 0:
        failures.append(
            f"dispatch: a same-bucket batch retraced "
            f"{recompile_count('scene_batch') - r1}× — the bucket policy "
            "failed to reuse the compiled program"
        )
    if device_get_count() - g1 != 1:
        failures.append("dispatch: second batch broke the one-readback rule")
    # a different bucket (B changes the traced shape): exactly one more
    r2 = recompile_count("scene_batch")
    small = draw_scene_batch(rng, 4, duration_s=0.5, setup_overrides=overrides)
    simulate_scene_batch(small, max_order=2)
    if recompile_count("scene_batch") - r2 != 1:
        failures.append(
            f"dispatch: a new (B=4) bucket retraced "
            f"{recompile_count('scene_batch') - r2}×, expected exactly 1"
        )
    return {"readbacks_per_batch": gets_first,
            "retraces_total": recompile_count("scene_batch") - r0}


def _check_dynamic_continuity(failures: list) -> dict:
    """Experiment 4: crossfade weights + boundary continuity on a sine dry."""
    import numpy as np

    from disco_tpu.scenes import (
        boundary_jumps,
        dynamic_scene_mixture,
        piecewise_trajectory,
        segment_weights,
    )

    n_seg, L = 5, FS // 2
    w = segment_weights(L, n_seg, crossfade=512)
    colsum = np.abs(w.sum(axis=0) - 1.0).max()
    if colsum > 1e-6:
        failures.append(
            f"dynamic: crossfade weights sum off unity by {colsum:g} — "
            "overlap-add would rescale the mixture"
        )
    hard = segment_weights(L, n_seg, crossfade=0)
    if not np.array_equal(np.unique(hard), [0.0, 1.0]):
        failures.append("dynamic: crossfade=0 weights are not a hard switch")

    t = np.arange(L) / FS
    dry = np.sin(2 * np.pi * 440 * t).astype(np.float32)
    path = piecewise_trajectory([1.0, 1.0, 1.5], [3.0, 2.0, 1.5], n_seg)
    mics = np.asarray([[2.0, 1.5, 1.0], [2.2, 1.5, 1.0]], np.float32)
    room = [4.0, 3.0, 2.5]

    def jump(crossfade):
        out = dynamic_scene_mixture(room, path, mics, 0.3, dry,
                                    crossfade=crossfade, max_order=2,
                                    rir_len=2048)
        if not np.isfinite(out["mixture"]).all():
            failures.append(f"dynamic: non-finite mixture at crossfade={crossfade}")
        return float(boundary_jumps(out["mixture"], n_seg).max())

    j_cross, j_hard = jump(512), jump(0)
    if j_cross > CROSSFADE_JUMP_RATIO * j_hard:
        failures.append(
            f"dynamic: crossfaded boundary jump {j_cross:g} is not under "
            f"{CROSSFADE_JUMP_RATIO} × the hard-switch jump {j_hard:g} — "
            "the crossfade is not removing the segment click"
        )
    return {"jump_crossfade": j_cross, "jump_hard_switch": j_hard}


def _raw_corpus(root: Path):
    """Tiny synthetic LibriSpeech-shaped raw corpus (the tests/test_datagen.py
    recipe): two 6 s envelope-gated 'speech' files + one 8 s noise file,
    written atomically so the chaos legs never see torn inputs."""
    import numpy as np

    from disco_tpu.io.atomic import write_wav_atomic

    rng = np.random.default_rng(0)
    speech = []
    for spk in ("7", "8"):
        f = root / "LibriSpeech" / spk / "1" / f"{spk}-1-0001.wav"
        t = np.arange(6 * FS) / FS
        env = (np.sin(2 * np.pi * 1.1 * t + float(spk)) > -0.2).astype(np.float64)
        write_wav_atomic(f, 0.3 * env * rng.standard_normal(len(t)), FS)
        speech.append(str(f))
    nf = root / "noises" / "n0.wav"
    write_wav_atomic(nf, 0.2 * rng.standard_normal(8 * FS), FS)
    return speech, [str(nf)]


def _signal_setup(speech, noise):
    import numpy as np

    from disco_tpu.sim import SpeechAndNoiseSetup

    return SpeechAndNoiseSetup(
        target_list=speech, talkers_list=speech, noises_dict={"fs": noise},
        duration_range=(5, 10), var_tar=10 ** (-23 / 10),
        snr_dry_range=[[0, 0]],
        snr_cnv_range=(-60, 60),  # wide gate: the tiny corpus must not redraw forever
        min_delta_snr=-1,
        rng=np.random.default_rng(3),
    )


def _run_batched_gen(out_root: Path, speech, noise, crash_after=None) -> list:
    """One ``disco-gen --batched`` run against the mini corpus; optionally
    chaos-crashed at the between_scenes seam then resumed."""
    import numpy as np

    from disco_tpu.datagen import generate_disco_rirs_batched
    from disco_tpu.io import DatasetLayout
    from disco_tpu.runs import chaos

    layout = DatasetLayout(str(out_root / "dataset"), "random", "test")
    ledger = str(out_root / "ledger.jsonl")
    kw = dict(max_order=2, batch=2, ledger=ledger, resume=True, seed=17)
    if crash_after is not None:
        chaos.configure("between_scenes", after=crash_after)
        try:
            generate_disco_rirs_batched(
                "random", "test", 1, 4, _signal_setup(speech, noise), layout,
                rng=np.random.default_rng(5), **kw)
            return ["CRASH-NEVER-FIRED"]
        except chaos.ChaosCrash:
            pass
        finally:
            chaos.disable()
    done = generate_disco_rirs_batched(
        "random", "test", 1, 4, _signal_setup(speech, noise), layout,
        rng=np.random.default_rng(5 if crash_after is None else 999), **kw)
    return done


def _check_datagen_chaos_resume(failures: list, scratch: Path) -> dict:
    """Experiment 5: byte-identical crash-and-resume of disco-gen --batched."""
    from disco_tpu.runs.check import _trees_identical

    speech, noise = _raw_corpus(scratch / "corpus")
    a, b = scratch / "uninterrupted", scratch / "crashed"
    a.mkdir()
    b.mkdir()
    done_plain = _run_batched_gen(a, speech, noise)
    if done_plain != [1, 2, 3, 4]:
        failures.append(f"datagen: uninterrupted run generated {done_plain}, "
                        "expected [1, 2, 3, 4]")
    done_resumed = _run_batched_gen(b, speech, noise, crash_after=2)
    if done_resumed == ["CRASH-NEVER-FIRED"]:
        failures.append("datagen: the between_scenes chaos crash never fired")
    elif set(done_resumed) & {1, 2}:
        failures.append(
            f"datagen: the resumed run regenerated ledger-done scenes "
            f"{sorted(set(done_resumed) & {1, 2})} — verified resume broken"
        )
    _trees_identical(a / "dataset", b / "dataset", failures, "datagen")
    return {"scenes_resumed": len(done_resumed)}


def _check_stream(failures: list, scratch: Path) -> dict:
    """Experiment 6: SceneStream determinism, ledger resume, chaos seam."""
    import numpy as np

    from disco_tpu.runs import chaos
    from disco_tpu.scenes import SceneStream

    def stream():
        return SceneStream(seed=7, scenes_per_batch=2, batches_per_epoch=2,
                           duration_s=0.5, max_order=2, win_len=4,
                           setup_overrides={"n_sensors_per_node": (2, 2)})

    full = list(stream().batches(8, epoch=0))
    twin = list(stream().batches(8, epoch=0))
    if not full:
        failures.append("stream: the feed yielded no training batches")
    if len(full) != len(twin) or not all(
        np.array_equal(xa, xb) and np.array_equal(ya, yb)
        for (xa, ya), (xb, yb) in zip(full, twin)
    ):
        failures.append("stream: the (seed, epoch) batch stream is not deterministic")
    geom = stream().peek_geometry()
    if full and full[0][0].shape[-1] != geom["n_freq"]:
        failures.append(
            f"stream: batch feature dim {full[0][0].shape[-1]} != "
            f"peek_geometry n_freq {geom['n_freq']}"
        )

    # ledger-armed epoch replays to zero duplicate scene batches
    led = scratch / "stream_ledger.jsonl"
    first = list(stream().batches(8, epoch=0, ledger=led))
    if len(first) != len(full):
        failures.append("stream: the ledger-armed epoch differs from the bare one")
    again = list(stream().batches(8, epoch=0, ledger=led))
    if again:
        failures.append(
            f"stream: a completed epoch replayed {len(again)} batches — "
            "verified resume must skip every consumed scene batch"
        )

    # chaos at the batch seam: crash, then resume to exactly the rest
    led2 = scratch / "stream_ledger_chaos.jsonl"
    chaos.configure("between_scene_batches", after=1)
    got: list = []
    try:
        for xy in stream().batches(8, epoch=0, ledger=led2):
            got.append(xy)
        failures.append("stream: the between_scene_batches crash never fired")
    except chaos.ChaosCrash:
        pass
    finally:
        chaos.disable()
    rest = list(stream().batches(8, epoch=0, ledger=led2))
    combined = got + rest
    if len(combined) != len(full) or not all(
        np.array_equal(xa, xb) and np.array_equal(ya, yb)
        for (xa, ya), (xb, yb) in zip(combined, full)
    ):
        failures.append(
            f"stream: crashed ({len(got)}) + resumed ({len(rest)}) batches "
            f"!= the uninterrupted epoch ({len(full)}) — the scene-batch "
            "resume unit is not seamless"
        )
    return {"batches_per_epoch": len(full),
            "batches_after_crash": len(rest)}


def main(argv=None) -> int:
    """Run the scenario-factory gate (``make scene-check``); exit 1 on failure.

    No reference counterpart (module docstring)."""
    import os

    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    from disco_tpu import obs

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        obs_log = tmp / "scene_check.jsonl"
        with obs.recording(obs_log):
            obs.write_manifest(tool="scene-check")
            oracle = _check_oracle_parity(failures)
            vmapped = _check_batched_equals_per_scene(failures)
            dispatch = _check_dispatch_budget(failures)
            dynamic = _check_dynamic_continuity(failures)
            datagen = _check_datagen_chaos_resume(failures, tmp)
            streamed = _check_stream(failures, tmp)
            obs.record("counters", **obs.REGISTRY.snapshot())
        events = obs.read_events(obs_log)  # schema-validating read

        scene_stages = {e.get("stage") for e in events
                        if e["kind"] == "scene"}
        if "scenes" not in scene_stages:
            failures.append("event log missing SceneStream scene events "
                            "(stage='scenes')")
        if "datagen" not in scene_stages:
            failures.append("event log missing batched-datagen scene events "
                            "(stage='datagen')")
        if not any(e["kind"] == "run_resume" for e in events):
            failures.append("event log missing the datagen run_resume event")

    if failures:
        for f in failures:
            print(f"scene-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "scene_check": "ok",
        "oracle_rel_err": oracle["oracle_rel_err"],
        "vmap_max_abs_err": vmapped["vmap_max_abs_err"],
        "readbacks_per_batch": dispatch["readbacks_per_batch"],
        "retraces_total": dispatch["retraces_total"],
        "jump_crossfade": dynamic["jump_crossfade"],
        "jump_hard_switch": dynamic["jump_hard_switch"],
        "scenes_resumed": datagen["scenes_resumed"],
        "stream_batches_per_epoch": streamed["batches_per_epoch"],
        "jax_processes": 1,
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
