"""disco_tpu.scenes — the batched on-device scenario factory.

Three layers (module docstrings carry the detail):

* :mod:`disco_tpu.scenes.batched` — B rooms × S sources × M mics simulated
  as ONE compiled program (RIRs, convolution, SNR mixing, STFT, mask).
* :mod:`disco_tpu.scenes.dynamic` — piecewise-stationary moving-source /
  moving-node scenes with crossfaded segment RIRs.
* :mod:`disco_tpu.scenes.stream` — the SceneStream training feed
  (ShardDataset-shaped; plugs into ``flywheel.fit`` and the resident
  trainer).

``make scene-check`` (:mod:`disco_tpu.scenes.check`) is the subsystem's
hermetic gate.

No reference counterpart: the reference simulates scenes one at a time on
the host (SURVEY.md §0; gen_disco/convolve_signals.py).
"""
from disco_tpu.scenes.batched import (
    BATCH_QUANTUM,
    SceneBatch,
    draw_scene_batch,
    noise_gain_for_snr,
    scene_batch_bucket,
    simulate_scene_batch,
    synthetic_dry_pair,
)
from disco_tpu.scenes.dynamic import (
    boundary_jumps,
    dynamic_scene_mixture,
    piecewise_trajectory,
    segment_weights,
)
from disco_tpu.scenes.stream import SceneStream, unit_scene_batch

__all__ = [
    "BATCH_QUANTUM",
    "SceneBatch",
    "SceneStream",
    "boundary_jumps",
    "draw_scene_batch",
    "dynamic_scene_mixture",
    "noise_gain_for_snr",
    "piecewise_trajectory",
    "scene_batch_bucket",
    "segment_weights",
    "simulate_scene_batch",
    "synthetic_dry_pair",
    "unit_scene_batch",
]
