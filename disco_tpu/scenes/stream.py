"""SceneStream: the scenario factory feeding training directly.

The flywheel's training input so far is tapped serve traffic
(:class:`~disco_tpu.flywheel.dataset.ShardDataset`): real, but rate-limited
by what the server happens to serve — the PR 18 resident trainer can starve
when traffic is thin.  SceneStream is the other leg: training batches
simulated on demand by the batched scenario factory
(:mod:`disco_tpu.scenes.batched`), one compiled dispatch per scene batch,
windowed into EXACTLY the ``(x, y)`` convention the training stack consumes
(``x`` = reference-mic magnitude STFT window ``(win_len, F)``, ``y`` = the
matching IRM mask window — the ``nn/data.DiscoDataset`` item shape,
reference dnn/data/datasets.py:102-162).

The production contract mirrors ``ShardDataset`` deliberately — same
``batches`` / ``batch_fn`` / ``peek_geometry`` surface — so
``flywheel.fit`` and the resident trainer take either feed unchanged:

* **Deterministic seeded draws** — scene batch ``i`` of epoch ``e`` is
  drawn from ``default_rng([seed, e, i])``: two runs with one seed see
  identical scenes, geometry, SNRs and window order.
* **Ledger resume** — each scene batch is a
  ``scene_batch:<epoch>:<i>`` ledger unit; on resume,
  ``verified_done`` skips batches that were already simulated AND
  consumed, so a crashed training run never re-trains on half an epoch.
* **Chaos seam** — ``between_scene_batches`` ticks after each scene
  batch's windows are fully yielded (the factory's clean boundary),
  drilled by ``make scene-check``'s crash-and-resume leg.
* **Observability** — one ``scene`` obs event per simulated batch and
  ``scene_batches`` / ``scenes_simulated`` counters.

Module import stays jax-free (disco-lint DL005): the factory program loads
lazily on the first simulated batch.

No reference counterpart: the reference pre-generates its corpus to disk
and trains offline (dnn/utils.py:74-140); an on-demand simulated feed is
TPU-port-only.
"""
from __future__ import annotations

import numpy as np

from disco_tpu.obs import events as obs_events
from disco_tpu.obs.metrics import REGISTRY as obs_registry

#: STFT geometry of the factory's analysis stage (ops.stft_ops convention).
_N_FFT, _N_HOP = 512, 256


def unit_scene_batch(epoch: int, index: int) -> str:
    """Ledger work-unit id of one simulated scene batch in one epoch.

    No reference counterpart (module docstring)."""
    return f"scene_batch:{int(epoch)}:{int(index)}"


class SceneStream:
    """On-demand simulated training batches from the batched scene factory.

    Args:
      seed: base seed of every deterministic draw.
      scenes_per_batch: B — scenes simulated per factory dispatch.
      batches_per_epoch: scene batches per epoch (the epoch's size knob —
        an on-demand corpus has no natural directory size).
      duration_s: dry-signal duration per scene.
      scenario: geometry sampler name (``sim.make_setup``).
      snr_range: per-scene SNR draw range (``snr_cnv_range`` convention).
      max_order: ISM reflection order (reference uses 20; hermetic gates
        pass a small order).
      win_len / win_hop: training window length/hop in STFT frames.
      setup_overrides: ``make_setup`` keyword overrides (small rooms /
        few mics for gates).
      dry_fn: ``(rng, n_samples) -> (target, noise)`` dry-signal source;
        default is the hermetic synthetic pair
        (:func:`disco_tpu.scenes.batched.synthetic_dry_pair`) — plug a
        ``sim.signals`` corpus setup in for real material.

    No reference counterpart (module docstring).
    """

    def __init__(self, *, seed: int = 0, scenes_per_batch: int = 8,
                 batches_per_epoch: int = 4, duration_s: float = 1.0,
                 scenario: str = "random", snr_range: tuple = (-5.0, 10.0),
                 max_order: int = 20, fs: int = 16000, win_len: int = 8,
                 win_hop: int | None = None, setup_overrides: dict | None = None,
                 dry_fn=None):
        if scenes_per_batch < 1:
            raise ValueError(f"scenes_per_batch must be >= 1, got {scenes_per_batch}")
        if batches_per_epoch < 1:
            raise ValueError(f"batches_per_epoch must be >= 1, got {batches_per_epoch}")
        if win_len < 1:
            raise ValueError(f"win_len must be >= 1, got {win_len}")
        self.seed = int(seed)
        self.scenes_per_batch = int(scenes_per_batch)
        self.batches_per_epoch = int(batches_per_epoch)
        self.duration_s = float(duration_s)
        self.scenario = str(scenario)
        self.snr_range = tuple(snr_range)
        self.max_order = int(max_order)
        self.fs = int(fs)
        self.win_len = int(win_len)
        self.win_hop = int(win_hop) if win_hop else int(win_len)
        self.setup_overrides = dict(setup_overrides or {})
        self.dry_fn = dry_fn

    # -- factory calls -------------------------------------------------------
    def _rng(self, epoch: int, index: int) -> np.random.Generator:
        """Per-(epoch, batch) rng — the determinism anchor: the draw
        depends only on (seed, epoch, index), never on consumption
        history, so a resumed epoch reproduces its remaining batches
        exactly (the ``ShardDataset._shard_rng`` discipline)."""
        return np.random.default_rng([self.seed, int(epoch), int(index)])

    def simulate_batch(self, epoch: int, index: int) -> dict:
        """Draw + simulate scene batch ``index`` of ``epoch``: ONE compiled
        factory dispatch, one batched readback (see
        :func:`disco_tpu.scenes.batched.simulate_scene_batch`).

        No reference counterpart (module docstring)."""
        from disco_tpu.scenes.batched import draw_scene_batch, simulate_scene_batch

        rng = self._rng(epoch, index)
        batch = draw_scene_batch(
            rng, self.scenes_per_batch, scenario=self.scenario,
            duration_s=self.duration_s, snr_range=self.snr_range, fs=self.fs,
            setup_overrides=self.setup_overrides, dry_fn=self.dry_fn,
        )
        out = simulate_scene_batch(batch, max_order=self.max_order, fs=self.fs)
        obs_registry.counter("scene_batches").inc()
        obs_registry.counter("scenes_simulated").inc(batch.n_scenes)
        obs_events.record(
            "scene", stage="scenes", epoch=int(epoch), index=int(index),
            n_scenes=batch.n_scenes, scenario=self.scenario,
            rir_len=int(out["rirs"].shape[-1]), max_order=self.max_order,
        )
        return out

    # -- windowing -----------------------------------------------------------
    def _windows(self, out: dict, epoch: int, index: int, shuffle: bool = True):
        """(xs, ys) window stacks of one simulated batch, in the batch's
        deterministic per-epoch order when ``shuffle`` (the window
        permutation draws from the SAME per-(epoch, index) stream as the
        scene draw, after it — one rng, one replayable sequence)."""
        mag, mask = out["mag_noisy"], out["mask"]  # (B, F, T)
        B, _F, T = mag.shape
        xs, ys = [], []
        for b in range(B):
            for t0 in range(0, T - self.win_len + 1, self.win_hop):
                # (F, win) -> (win, F): the DiscoDataset item convention
                xs.append(mag[b, :, t0:t0 + self.win_len].T.astype(np.float32))
                ys.append(mask[b, :, t0:t0 + self.win_len].T.astype(np.float32))
        if not xs:
            return None
        if not shuffle:
            return np.stack(xs), np.stack(ys)
        order = np.random.default_rng(
            [self.seed, int(epoch), int(index), 1]).permutation(len(xs))
        return (np.stack([xs[i] for i in order]),
                np.stack([ys[i] for i in order]))

    # -- the batch stream ----------------------------------------------------
    def batches(self, batch_size: int, *, epoch: int = 0, shuffle: bool = True,
                ledger=None, drop_last: bool = True, recent: int | None = None):
        """Yield ``(x, y)`` numpy batches for one epoch — the
        :meth:`ShardDataset.batches` contract, scene batches standing in
        for shards: batches never cross a scene-batch boundary, ``ledger``
        arms per-scene-batch verified resume (simulated-and-consumed
        batches are skipped on replay), ``recent`` is accepted for feed
        interchangeability and ignored (an on-demand factory has no
        backlog to window).

        No reference counterpart (module docstring).
        """
        from disco_tpu.runs import chaos as run_chaos
        from disco_tpu.runs.ledger import RunLedger

        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        own_ledger = ledger is not None and not isinstance(ledger, RunLedger)
        if own_ledger:
            ledger = RunLedger(ledger)
        try:
            done: set = set()
            if ledger is not None:
                done, _requeued = ledger.verified_done()
            for index in range(self.batches_per_epoch):
                unit = unit_scene_batch(epoch, index)
                if unit in done:
                    continue
                if ledger is not None:
                    ledger.mark_in_flight(unit)
                out = self.simulate_batch(epoch, index)
                windows = self._windows(out, epoch, index, shuffle=shuffle)
                if windows is None:
                    if ledger is not None:
                        ledger.mark_done(unit, n_windows=0)
                    continue
                xs, ys = windows
                n = len(xs)
                for start in range(0, n, batch_size):
                    if drop_last and start + batch_size > n:
                        break
                    yield xs[start:start + batch_size], ys[start:start + batch_size]
                if ledger is not None:
                    # no artifacts: the scenes live only in the yielded
                    # batches, so the done record is the consumption marker
                    ledger.mark_done(unit, n_windows=n)
                run_chaos.tick("between_scene_batches", epoch=int(epoch),
                               index=int(index))
        finally:
            if own_ledger:
                ledger.close()

    def batch_fn(self, batch_size: int, *, shuffle: bool = True,
                 ledger=None, drop_last: bool = True):
        """A ``fit``-compatible zero-arg epoch-iterator callable with
        ``set_start_epoch(n)`` — byte-for-byte the
        :meth:`ShardDataset.batch_fn` resume contract (see its docstring
        for why the epoch counter must restart at the resumed epoch).

        No reference counterpart (module docstring).
        """
        from disco_tpu.runs.ledger import RunLedger

        if ledger is not None and not isinstance(ledger, RunLedger):
            ledger = RunLedger(ledger)
        state = {"epoch": 0}

        def make():
            epoch = state["epoch"]
            state["epoch"] += 1
            return self.batches(batch_size, epoch=epoch, shuffle=shuffle,
                                ledger=ledger, drop_last=drop_last)

        def set_start_epoch(epoch: int) -> None:
            state["epoch"] = int(epoch)

        make.set_start_epoch = set_start_epoch
        return make

    def peek_geometry(self) -> dict:
        """Feed geometry without simulating anything — what sizes the model
        (the :func:`~disco_tpu.flywheel.dataset.peek_geometry` surface):
        the factory's shapes are known statically from its STFT convention
        (centered 512/256 frames: ``T = 1 + L//hop``).

        No reference counterpart (module docstring)."""
        L = int(round(self.duration_s * self.fs))
        return {
            "n_nodes": 1,
            "mics_per_node": None,  # per-scenario; the feed trains on mic 0
            "n_freq": _N_FFT // 2 + 1,
            "block_frames": 1 + L // _N_HOP,
        }
