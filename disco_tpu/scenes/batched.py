"""Batched scenario factory: B rooms simulated in ONE dispatched program.

The reference simulates scenes one at a time — one ``pra.ShoeBox`` per room,
per-channel ``np.convolve`` loops (``gen_disco/convolve_signals.py:84-99,
161``).  The per-scene TPU port (``datagen/disco.py:simulate_scene``) already
fuses one scene into one launch, but on the tunneled attachment every fenced
dispatch costs a fixed ~80 ms RPC (CLAUDE.md), so a 100k-scene corpus at one
dispatch per scene is ~2.2 hours of pure RPC before any compute.  This
module batches the SCENE axis:

* :func:`scene_batch_bucket` picks ONE static ``(max_order, rir_len)``
  bucket for a whole batch (the coarse-quantum application of the canonical
  :func:`disco_tpu.sim.ism.rir_bucket` policy), so B rooms compile to one
  program per bucket instead of one per room;
* :func:`simulate_scene_batch` runs the whole factory — B × S × M image-
  source RIRs, dry→wet FFT convolution, SNR-scaled mixing, reference-mic
  STFT magnitudes and the IRM training mask — as ONE ``counted_jit``
  program (label ``scene_batch``; ``make scene-check`` pins exactly one
  retrace per bucket and exactly ONE batched readback per call).

Everything host-facing travels back through
``utils.transfer.device_get_tree`` — one fenced RPC per scene batch,
however many leaves.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np

from disco_tpu.obs.accounting import counted_jit
from disco_tpu.sim.ism import rir_bucket

#: Coarse rir_len rounding for batch buckets: nearby scene batches land in
#: the same compiled program (the per-scene path uses 256; one program per
#: 2048-sample band keeps the retrace budget countable on one hand).
BATCH_QUANTUM = 2048


@dataclasses.dataclass
class SceneBatch:
    """Host-side parameters of one scene batch (everything the compiled
    factory consumes, as numpy).

    Shapes: B scenes × S sources (index 0 = target, 1 = noise, the
    two-source DISCO convention of convolve_signals.py:216-282) × M mics.

    No reference counterpart: the reference has no batched scene axis
    (module docstring).
    """

    room_dims: np.ndarray  # (B, 3) float32
    sources: np.ndarray    # (B, S, 3) float32
    mics: np.ndarray       # (B, M, 3) float32
    alphas: np.ndarray     # (B,) float32 wall energy absorption
    betas: np.ndarray      # (B,) float32 RT60 seconds (bucket sizing)
    dry: np.ndarray        # (B, S, L) float32 dry source signals
    noise_gains: np.ndarray  # (B,) float32 linear gain applied to the wet noise
    snr_db: np.ndarray     # (B,) float32 the sampled per-scene SNR (metadata)

    @property
    def n_scenes(self) -> int:
        return int(self.room_dims.shape[0])


def scene_batch_bucket(batch: SceneBatch, max_order: int = 20,
                       fs: int = 16000, quantum: int = BATCH_QUANTUM) -> tuple[int, int]:
    """The shared static ``(max_order, rir_len)`` bucket of one batch.

    Delegates per scene to the canonical :func:`disco_tpu.sim.ism.rir_bucket`
    policy (room-dim-aware order clamp included) and takes the max
    ``rir_len`` over the batch — every scene's tail fits, and the coarse
    ``quantum`` bounds how many distinct programs a corpus run can compile.

    No reference counterpart (module docstring).
    """
    rir_len = 0
    for b in range(batch.n_scenes):
        _, n = rir_bucket(float(batch.betas[b]), batch.room_dims[b],
                          max_order=max_order, fs=fs, quantum=quantum)
        rir_len = max(rir_len, n)
    return max_order, rir_len


def noise_gain_for_snr(target: np.ndarray, noise: np.ndarray, snr_db: float) -> float:
    """Linear gain scaling ``noise`` so that ``rms(target)/rms(gain*noise)``
    hits ``snr_db`` (the dry-domain analogue of
    ``core.sigproc.increase_to_snr``'s energy balance, reference
    sigproc_utils.py:28-55 — the factory applies the gain to the WET noise
    inside the compiled program, so it must be a plain scalar)."""
    pt = float(np.mean(np.square(target))) + 1e-12
    pn = float(np.mean(np.square(noise))) + 1e-12
    return float(np.sqrt(pt / pn) * 10.0 ** (-float(snr_db) / 20.0))


@counted_jit(label="scene_batch", static_argnames=("max_order", "rir_len", "fs"))
def _scene_batch_program(room_dims, sources, mics, alphas, dry, noise_gains,
                         max_order: int, rir_len: int, fs: int):
    """The one compiled factory program — see :func:`simulate_scene_batch`.

    No reference counterpart (module docstring)."""
    import jax.numpy as jnp

    from disco_tpu.core.masks import tf_mask_mag
    from disco_tpu.ops.stft_ops import stft_with_mag
    from disco_tpu.sim.ism import fft_convolve, shoebox_rirs_batched

    L = dry.shape[-1]
    rirs = shoebox_rirs_batched(room_dims, sources, mics, alphas,
                                max_order=max_order, rir_len=rir_len, fs=fs)
    # (B, S, M, L): every dry source convolved with its RIRs to every mic.
    wet = fft_convolve(dry[:, :, None, :], rirs, out_len=L)
    clean = wet[:, 0]                                    # (B, M, L)
    noise = wet[:, 1] * noise_gains[:, None, None]       # (B, M, L)
    noisy = clean + noise
    # Reference-mic analysis (mic 0 is the node's reference channel, the
    # ShardDataset ref_mic convention): one fused STFT over the three
    # stacked streams, then the IRM1 training target.
    stack = jnp.stack([noisy[:, 0], clean[:, 0], noise[:, 0]])  # (3, B, L)
    _spec, mag = stft_with_mag(stack)                     # (3, B, F, T)
    mask = tf_mask_mag(mag[1], mag[2], mask_type="irm1")  # (B, F, T)
    return {
        "rirs": rirs,
        "noisy": noisy,
        "clean": clean,
        "mag_noisy": mag[0],
        "mask": mask,
    }


def simulate_scene_batch(batch: SceneBatch, max_order: int = 20,
                         fs: int = 16000, quantum: int = BATCH_QUANTUM,
                         rir_len: int | None = None) -> dict:
    """Simulate one scene batch in ONE device dispatch + ONE batched readback.

    The compiled equivalent of B sequential reference scene simulations
    (``gen_disco/convolve_signals.py:216-282`` per scene): batched ISM RIRs,
    batched FFT convolution, SNR mixing, reference-mic STFT magnitudes and
    the IRM mask target, all in one ``counted_jit`` program.  The result
    pytree crosses the boundary through ``device_get_tree`` — one fenced
    RPC — so simulating a B≥8 batch is exactly one RIR-engine dispatch
    (the ``make scene-check`` fence-accounting criterion).

    Returns a dict of numpy arrays: ``rirs (B,S,M,rir_len)``,
    ``noisy/clean (B,M,L)``, ``mag_noisy (B,F,T)``, ``mask (B,F,T)``.
    """
    import jax.numpy as jnp

    from disco_tpu.utils.transfer import device_get_tree

    if rir_len is None:
        max_order, rir_len = scene_batch_bucket(batch, max_order=max_order,
                                                fs=fs, quantum=quantum)
    out = _scene_batch_program(
        jnp.asarray(batch.room_dims, jnp.float32),
        jnp.asarray(batch.sources, jnp.float32),
        jnp.asarray(batch.mics, jnp.float32),
        jnp.asarray(batch.alphas, jnp.float32),
        jnp.asarray(batch.dry, jnp.float32),
        jnp.asarray(batch.noise_gains, jnp.float32),
        max_order=max_order, rir_len=rir_len, fs=fs,
    )
    return device_get_tree(out)


def synthetic_dry_pair(rng: np.random.Generator, n_samples: int,
                       fs: int = 16000) -> tuple[np.ndarray, np.ndarray]:
    """A hermetic (target, noise) dry pair: speech-shaped amplitude-modulated
    noise vs stationary noise — the corpus-free stand-in the scene-check
    gate and SceneStream's synthetic mode use (real runs plug
    ``sim.signals.SpeechAndNoiseSetup`` corpora in instead; the modulation
    mimics the syllabic envelope that makes VAD/SNR gating meaningful).

    No reference counterpart: the reference always reads LibriSpeech
    (convolve_signals.py:32-81).
    """
    t = np.arange(n_samples, dtype=np.float64) / fs
    carrier = rng.standard_normal(n_samples)
    # ~4 Hz syllabic envelope with a random phase, floored so silence is
    # quiet-but-nonzero (fw-SNR needs energy in every band).
    env = 0.55 + 0.45 * np.sin(2 * np.pi * 4.0 * t + rng.uniform(0, 2 * np.pi))
    target = (carrier * env).astype(np.float32)
    noise = rng.standard_normal(n_samples).astype(np.float32)
    target /= max(float(np.std(target)), 1e-9)
    noise /= max(float(np.std(noise)), 1e-9)
    return target, noise


def draw_scene_batch(rng: np.random.Generator, n_scenes: int, *,
                     scenario: str = "random", duration_s: float = 1.0,
                     snr_range: tuple = (-5.0, 10.0), fs: int = 16000,
                     setup_overrides: dict | None = None,
                     dry_fn=None) -> SceneBatch:
    """Draw one :class:`SceneBatch`: geometry by the SURVEY §L2 rejection
    samplers (``sim.make_setup`` — same constraints as the reference
    room_setups.py), dry signals from ``dry_fn`` (default
    :func:`synthetic_dry_pair`), per-scene SNR uniform in ``snr_range``
    (the ``snr_cnv_range`` convention, convolve_signals.py:404-409).

    All scenes in a batch share the scenario's fixed sensor layout, so the
    (B, S, M) stacking is rectangular by construction.
    """
    from disco_tpu.sim import make_setup

    sampler = make_setup(scenario, rng=rng, **(setup_overrides or {}))
    L = int(round(duration_s * fs))
    dry_fn = dry_fn or (lambda r, n: synthetic_dry_pair(r, n, fs=fs))

    dims, srcs, mics, alphas, betas, drys, gains, snrs = [], [], [], [], [], [], [], []
    for _ in range(int(n_scenes)):
        cfg = sampler.create_room_setup()
        target, noise = dry_fn(rng, L)
        snr_db = float(rng.uniform(*snr_range))
        dims.append(np.asarray(cfg.room_dim, np.float32))
        srcs.append(np.asarray(cfg.source_positions[:2], np.float32))
        mics.append(np.asarray(cfg.mic_positions.T, np.float32))
        alphas.append(np.float32(cfg.alpha))
        betas.append(np.float32(cfg.beta))
        drys.append(np.stack([target, noise]).astype(np.float32))
        gains.append(np.float32(noise_gain_for_snr(target, noise, snr_db)))
        snrs.append(np.float32(snr_db))
    return SceneBatch(
        room_dims=np.stack(dims), sources=np.stack(srcs), mics=np.stack(mics),
        alphas=np.asarray(alphas, np.float32), betas=np.asarray(betas, np.float32),
        dry=np.stack(drys), noise_gains=np.asarray(gains, np.float32),
        snr_db=np.asarray(snrs, np.float32),
    )
