"""disco_tpu — a TPU-native (JAX/XLA/pallas/pjit) framework for distributed
microphone-array speech enhancement and separation.

Re-designed from scratch with the capabilities of the nfurnon/disco reference
(see SURVEY.md): room simulation of ad-hoc microphone arrays, DNN time-frequency
mask estimation, and two-step DANSE-style distributed rank-1 GEVD-MWF
beamforming ("TANGO") — with rooms, nodes, frequency bins and STFT frames
treated as array axes on a TPU mesh instead of Python loops.

Subpackages
-----------
core       DSP kernels: STFT/ISTFT filterbank, TF masks, VAD, math utilities,
           metrics (incl. native STOI), misc/yaml helpers
ops        MXU matmul STFT/ISTFT kernels + fused pallas STFT
beam       spatial covariance estimation + MWF / rank-1 MWF / GEVD-MWF filters
enhance    TANGO two-step pipeline (offline + streaming), separation, z export,
           the per-RIR results driver
parallel   mesh topology + shard_map node/frame-parallel execution
           (z = all_gather over ICI; psum'd covariances for frame sharding),
           multi-host hybrid ICI/DCN meshes
nn         Flax CRNN mask estimator, training engine, corpus datasets,
           native C++ fast loader
sim        room geometry sampling, batched image-source RIRs, FFT convolution
datagen    DISCO/MEETIT corpus generation, mixing pass, downloaders
io         wav / npy I/O and the dataset file layout
cli        argparse entry points (disco-gen / -mix / -tango / -train / -obs ...)
obs        structured run telemetry: JSONL event log + manifest, metrics
           registry, fence/RPC + recompile accounting, numerics sentinels
utils      complex-safe host<->device transfer
milestones the five BASELINE benchmark configurations
"""

__version__ = "0.1.0"
