"""disco_tpu — a TPU-native (JAX/XLA/pallas/pjit) framework for distributed
microphone-array speech enhancement and separation.

Re-designed from scratch with the capabilities of the nfurnon/disco reference
(see SURVEY.md): room simulation of ad-hoc microphone arrays, DNN time-frequency
mask estimation, and two-step DANSE-style distributed rank-1 GEVD-MWF
beamforming ("TANGO") — with rooms, nodes, frequency bins and STFT frames
treated as array axes on a TPU mesh instead of Python loops.

Subpackages
-----------
core      DSP kernels: STFT/ISTFT filterbank, TF masks, VAD, math utilities, metrics
beam      spatial covariance estimation + MWF / rank-1 MWF / GEVD-MWF filters
enhance   the TANGO two-step distributed enhancement pipeline
parallel  mesh topology + shard_map node-parallel execution (z = all_gather over ICI)
nn        Flax CRNN mask estimator + training engine
sim       room geometry sampling, batched image-source RIRs, FFT convolution
io        wav / npy I/O and the dataset file layout
"""

__version__ = "0.1.0"
