"""Native (C++) components, built on demand with g++ — see fastloader.cpp
and disco_tpu/nn/fastload.py for the bindings."""
