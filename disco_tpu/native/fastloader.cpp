// Threaded .npy corpus loader — the native data-path component.
//
// The reference's DiscoDataset.load_data (datasets.py:71-87) np.load()s
// every |STFT| of the corpus into one RAM array, single-threaded in Python
// — minutes of wall clock for the 11k-RIR training corpus.  This library
// does the same work with a C++ thread pool: each worker parses the .npy
// header, freads the payload, and writes the magnitude (for complex64
// inputs) or |value| (for float32 inputs) into its slot of one
// preallocated float32 buffer, zero-padded to max_frames columns.
//
// ABI (ctypes, see disco_tpu/nn/fastload.py):
//   int fast_load_abs(const char** paths, int n_paths,
//                     float* out, long slot_elems,
//                     long n_freq, long max_frames, long skip_cols,
//                     int n_threads, long* out_frames)
// skip_cols: leading STFT frames dropped from every file (the reference
// drops the first second of lead silence, datasets.py:81).
// returns 0 on success, else 1 + the index of the first failing file is
// written to out_frames[n_paths] (caller allocates n_paths + 1 longs).
//
// Build: g++ -O3 -shared -fPIC -pthread fastloader.cpp -o libfastloader.so

#include <atomic>
#include <cmath>
#include <complex>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct NpyInfo {
  bool ok = false;
  bool is_complex = false;  // '<c8' vs '<f4'
  long rows = 0, cols = 0;
  long data_offset = 0;
};

NpyInfo parse_npy_header(FILE* f) {
  NpyInfo info;
  unsigned char magic[8];
  if (fread(magic, 1, 8, f) != 8) return info;
  if (memcmp(magic, "\x93NUMPY", 6) != 0) return info;
  int major = magic[6];
  uint32_t header_len = 0;
  if (major == 1) {
    unsigned char b[2];
    if (fread(b, 1, 2, f) != 2) return info;
    header_len = b[0] | (b[1] << 8);
    info.data_offset = 10 + header_len;
  } else {
    unsigned char b[4];
    if (fread(b, 1, 4, f) != 4) return info;
    header_len = b[0] | (b[1] << 8) | (b[2] << 16) | ((uint32_t)b[3] << 24);
    info.data_offset = 12 + header_len;
  }
  std::string hdr(header_len, '\0');
  if (fread(&hdr[0], 1, header_len, f) != header_len) return info;

  if (hdr.find("'fortran_order': True") != std::string::npos) return info;
  if (hdr.find("'<c8'") != std::string::npos) {
    info.is_complex = true;
  } else if (hdr.find("'<f4'") == std::string::npos) {
    return info;  // only complex64 / float32 supported
  }
  size_t sp = hdr.find("'shape':");
  if (sp == std::string::npos) return info;
  size_t lp = hdr.find('(', sp), rp = hdr.find(')', sp);
  if (lp == std::string::npos || rp == std::string::npos) return info;
  std::string shape = hdr.substr(lp + 1, rp - lp - 1);
  long dims[2] = {0, 0};
  int nd = 0;
  const char* p = shape.c_str();
  while (*p && nd < 2) {
    while (*p == ' ' || *p == ',') p++;
    if (*p < '0' || *p > '9') break;
    dims[nd++] = strtol(p, const_cast<char**>(&p), 10);
  }
  if (nd != 2) return info;
  info.rows = dims[0];
  info.cols = dims[1];
  info.ok = true;
  return info;
}

bool load_one(const char* path, float* slot, long n_freq, long max_frames,
              long skip_cols, long* n_frames_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  NpyInfo info = parse_npy_header(f);
  if (!info.ok || info.rows != n_freq) {
    fclose(f);
    return false;
  }
  long avail = info.cols > skip_cols ? info.cols - skip_cols : 0;
  long cols = avail < max_frames ? avail : max_frames;
  if (fseek(f, info.data_offset, SEEK_SET) != 0) {
    fclose(f);
    return false;
  }
  const long elem = info.is_complex ? 8 : 4;
  std::vector<unsigned char> row(info.cols * elem);
  for (long r = 0; r < info.rows; ++r) {
    if (fread(row.data(), 1, row.size(), f) != row.size()) {
      fclose(f);
      return false;
    }
    float* dst = slot + r * max_frames;
    if (info.is_complex) {
      const float* src = reinterpret_cast<const float*>(row.data()) + 2 * skip_cols;
      for (long c = 0; c < cols; ++c) {
        const float re = src[2 * c], im = src[2 * c + 1];
        dst[c] = std::sqrt(re * re + im * im);
      }
    } else {
      const float* src = reinterpret_cast<const float*>(row.data()) + skip_cols;
      for (long c = 0; c < cols; ++c) dst[c] = std::fabs(src[c]);
    }
    // zero-pad the tail (buffer arrives uninitialised)
    for (long c = cols; c < max_frames; ++c) dst[c] = 0.0f;
  }
  fclose(f);
  *n_frames_out = cols;
  return true;
}

}  // namespace

extern "C" int fast_load_abs(const char** paths, int n_paths, float* out,
                             long slot_elems, long n_freq, long max_frames,
                             long skip_cols, int n_threads, long* out_frames) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int> next(0);
  std::atomic<long> first_fail(-1);

  auto worker = [&]() {
    while (true) {
      int i = next.fetch_add(1);
      if (i >= n_paths || first_fail.load() >= 0) break;
      long nf = 0;
      if (!load_one(paths[i], out + (long)i * slot_elems, n_freq, max_frames, skip_cols, &nf)) {
        long expect = -1;
        first_fail.compare_exchange_strong(expect, i);
        break;
      }
      out_frames[i] = nf;
    }
  };

  std::vector<std::thread> pool;
  const int nt = n_threads < n_paths ? n_threads : (n_paths ? n_paths : 1);
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();

  if (first_fail.load() >= 0) {
    out_frames[n_paths] = first_fail.load();
    return 1;
  }
  return 0;
}
