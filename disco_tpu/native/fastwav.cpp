// Threaded WAV batch reader — the native audio ingest component.
//
// Corpus-scale enhancement reads ~48 mono PCM wavs per RIR
// (zexport.load_node_signals; reference get_z_signals.py:44-92 does the
// same through soundfile, one python call per channel).  At the measured
// >1000x real-time enhancement rate the sequential Python decode loop, not
// the TPU, bounds corpus wall-clock — this library decodes a whole batch
// with a C++ thread pool instead, one file per task, writing float32
// samples in [-1, 1) straight into the caller's preallocated buffer.
//
// Decoding matches disco_tpu/io/audio.py exactly: RIFF/WAVE with PCM
// 8 (unsigned) / 16 / 24 / 32-bit and IEEE float 32/64, plus
// WAVE_FORMAT_EXTENSIBLE headers.  MONO files only — the corpus layout is
// one channel per file; anything else fails the file and the Python
// wrapper raises RuntimeError naming it (the pure-Python path is used only
// when this library is unavailable, not as a per-file retry).
//
// ABI (ctypes, see disco_tpu/io/fastwav.py):
//   int fast_read_wavs(const char** paths, int n_paths,
//                      float* out, int64_t slot_samples,
//                      int64_t* out_len, int* out_fs,
//                      int n_threads, int64_t* fail_idx)
// Each file i is decoded into out[i*slot_samples : (i+1)*slot_samples],
// truncated to slot_samples, zero-padded past its true length (written to
// out_len[i]); out_fs[i] is the sample rate.  Returns 0 on success, else 1
// with fail_idx[0] = index of the first failing file.
//
// Build: g++ -O3 -shared -fPIC -pthread fastwav.cpp -o libfastwav.so

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint16_t kPcm = 0x0001;
constexpr uint16_t kFloat = 0x0003;
constexpr uint16_t kExtensible = 0xFFFE;

uint32_t rd32(const unsigned char* p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) | ((uint32_t)p[3] << 24);
}
uint16_t rd16(const unsigned char* p) { return p[0] | (p[1] << 8); }

bool read_one(const char* path, float* slot, int64_t slot_samples,
              int64_t* len_out, int* fs_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  unsigned char hdr[12];
  if (fread(hdr, 1, 12, f) != 12 || memcmp(hdr, "RIFF", 4) != 0 ||
      memcmp(hdr + 8, "WAVE", 4) != 0) {
    fclose(f);
    return false;
  }
  // file size bounds every chunk-size field: a corrupt size would
  // otherwise drive a multi-GB resize whose bad_alloc escapes the worker
  // thread and aborts the process
  if (fseek(f, 0, SEEK_END) != 0) {
    fclose(f);
    return false;
  }
  const int64_t file_size = ftell(f);
  fseek(f, 12, SEEK_SET);
  uint16_t fmt_code = 0, n_ch = 0, bits = 0;
  uint32_t fs = 0;
  bool have_fmt = false;
  std::vector<unsigned char> data;
  // chunk scan (word-aligned, as in audio.py read_wav)
  unsigned char ch[8];
  while (fread(ch, 1, 8, f) == 8) {
    uint32_t sz = rd32(ch + 4);
    if ((int64_t)sz > file_size - ftell(f)) {
      fclose(f);
      return false;
    }
    if (memcmp(ch, "fmt ", 4) == 0) {
      std::vector<unsigned char> fmt(sz);
      if (fread(fmt.data(), 1, sz, f) != sz || sz < 16) {
        fclose(f);
        return false;
      }
      fmt_code = rd16(&fmt[0]);
      n_ch = rd16(&fmt[2]);
      fs = rd32(&fmt[4]);
      bits = rd16(&fmt[14]);
      if (fmt_code == kExtensible) {
        // real code = first 2 bytes of the SubFormat GUID at offset 24
        if (sz < 26) {
          fclose(f);
          return false;
        }
        fmt_code = rd16(&fmt[24]);
      }
      have_fmt = true;
    } else if (memcmp(ch, "data", 4) == 0) {
      data.resize(sz);
      if (fread(data.data(), 1, sz, f) != sz) {
        fclose(f);
        return false;
      }
    } else {
      if (fseek(f, sz, SEEK_CUR) != 0) break;
    }
    if (sz & 1) fseek(f, 1, SEEK_CUR);  // chunks are word-aligned
    if (have_fmt && !data.empty()) break;
  }
  fclose(f);
  if (!have_fmt || data.empty() || n_ch != 1) return false;

  const int64_t bytes_per = bits / 8;
  if (bytes_per == 0) return false;
  const int64_t n = (int64_t)(data.size() / bytes_per);
  const int64_t m = n < slot_samples ? n : slot_samples;
  const unsigned char* p = data.data();

  if (fmt_code == kFloat && bits == 32) {
    memcpy(slot, p, m * 4);
  } else if (fmt_code == kFloat && bits == 64) {
    const double* src = reinterpret_cast<const double*>(p);
    for (int64_t i = 0; i < m; ++i) slot[i] = (float)src[i];
  } else if (fmt_code == kPcm && bits == 8) {
    for (int64_t i = 0; i < m; ++i) slot[i] = ((float)p[i] - 128.0f) / 128.0f;
  } else if (fmt_code == kPcm && bits == 16) {
    const int16_t* src = reinterpret_cast<const int16_t*>(p);
    for (int64_t i = 0; i < m; ++i) slot[i] = (float)src[i] / 32768.0f;
  } else if (fmt_code == kPcm && bits == 24) {
    for (int64_t i = 0; i < m; ++i) {
      int32_t v = p[3 * i] | (p[3 * i + 1] << 8) | (p[3 * i + 2] << 16);
      v = (v ^ 0x800000) - 0x800000;  // sign-extend 24 -> 32
      slot[i] = (float)v / 8388608.0f;
    }
  } else if (fmt_code == kPcm && bits == 32) {
    const int32_t* src = reinterpret_cast<const int32_t*>(p);
    for (int64_t i = 0; i < m; ++i) slot[i] = (float)((double)src[i] / 2147483648.0);
  } else {
    return false;
  }
  for (int64_t i = m; i < slot_samples; ++i) slot[i] = 0.0f;
  *len_out = n;
  *fs_out = (int)fs;
  return true;
}

}  // namespace

extern "C" int fast_read_wavs(const char** paths, int n_paths, float* out,
                              int64_t slot_samples, int64_t* out_len, int* out_fs,
                              int n_threads, int64_t* fail_idx) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int> next(0);
  std::atomic<int64_t> first_fail(-1);

  auto worker = [&]() {
    while (true) {
      int i = next.fetch_add(1);
      if (i >= n_paths || first_fail.load() >= 0) break;
      int64_t len = 0;
      int fs = 0;
      bool ok = false;
      try {
        ok = read_one(paths[i], out + (int64_t)i * slot_samples, slot_samples, &len, &fs);
      } catch (...) {
        ok = false;  // e.g. bad_alloc — must not escape the thread
      }
      if (!ok) {
        int64_t expect = -1;
        first_fail.compare_exchange_strong(expect, i);
        break;
      }
      out_len[i] = len;
      out_fs[i] = fs;
    }
  };

  std::vector<std::thread> pool;
  const int nt = n_threads < n_paths ? n_threads : (n_paths ? n_paths : 1);
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();

  if (first_fail.load() >= 0) {
    fail_idx[0] = first_fail.load();
    return 1;
  }
  return 0;
}
