"""TANGO — the two-step DANSE-style distributed rank-1 GEVD-MWF pipeline.

Capability parity with reference ``speech_enhancement/tango.py:252-457``
(``offline_tango``), re-designed TPU-first:

* The reference runs ``for i_nod / for f in 257 / for t in frames`` Python
  loops with a scipy ``eig`` per (node, freq) bin.  Here each step is a pure
  function over a whole node's (C, F, T) STFT block — covariances are one
  einsum, the 257 GEVDs are one batched Cholesky-whitened ``eigh`` — and the
  node axis is either ``vmap``ed (single device) or sharded over a mesh with
  the z-exchange as an ``all_gather`` (see ``disco_tpu.parallel``).
* The "network transport" of the reference is ``concatenate_signals``
  (tango.py:142-155): node k filters ``[y_k ‖ z_{j<k} ‖ z_{j>k}]``.  The same
  ascending-skip-k ordering is reproduced by :func:`others_index`.
* The step-2 mask-for-z policy matrix (tango.py:396-429) is implemented for
  'local', 'none'/None, 'distant', 'compressed', 'use_oracle_refs',
  'use_oracle_zs'.  (The reference's 'use_oracle_sigs' branch is
  shape-inconsistent as shipped — it concatenates (C, F, T) blocks where
  (F, T) streams are expected, so the subsequent ``np.inner`` cannot run; its
  evident intent is covered by 'use_oracle_refs'.)

Masks are *inputs* here (shape (K, F, T)): oracle masks come from
:func:`oracle_masks`, CRNN masks from ``disco_tpu.nn`` — keeping this module
independent of the mask source and fully jittable.

Fault tolerance (no reference counterpart — the reference assumes every z
arrives intact): ``tango``/``tango_step2`` accept an optional availability
mask over the exchanged z channels (``z_mask``/``z_avail``).  Unavailable
channels are excluded from the step-2 MWF by jittable channel masking —
their stat and application channels are zeroed via a NaN-safe select and
the noise covariance gets trace-relative diagonal loading on the excluded
channels, which decouples them from the GEVD exactly (their generalized
eigenvalue collapses to the clamp floor, so the rank-1 filter assigns them
~zero gain and the surviving channels see precisely the K-1-subset
problem; pinned against the subset float64 oracle in tests/test_fault.py).
With every other node unavailable this degrades to local-only beamforming
on the node's own mics.  A finiteness guard at the exchange seam
additionally excludes any node whose z carries non-finite values
(``z_nan`` injects exactly that fault for testing — see
``disco_tpu.fault``).  With ``z_mask=None`` and ``z_nan=None`` (the
defaults) every code path is byte-identical to the fault-free pipeline.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from disco_tpu.beam.covariance import frame_mean_covariance
from disco_tpu.beam.filters import rank1_gevd
from disco_tpu.core.masks import tf_mask
from disco_tpu.ops.resolve import check_canonical_precision
from disco_tpu.solver_spec import is_fused_spec

Policy = str | None
_POLICIES = ("local", "none", "distant", "compressed", "use_oracle_refs", "use_oracle_zs")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TangoResult:
    """Outputs of the two-step pipeline, all (K, F, T) complex unless noted —
    the 9-tuple of reference tango.py:457."""

    yf: jnp.ndarray  # filtered mixture (the enhanced signal)
    sf: jnp.ndarray  # filter applied to clean speech (for metrics)
    nf: jnp.ndarray  # filter applied to clean noise (for metrics)
    z_y: jnp.ndarray  # compressed mixture (the exchanged signal)
    z_s: jnp.ndarray  # speech component of z
    z_n: jnp.ndarray  # noise component of z
    zn: jnp.ndarray  # compressed-noise estimate y_ref - z_y
    masks_z: jnp.ndarray  # step-1 masks
    mask_w: jnp.ndarray  # step-2 masks

    def tree_flatten(self):
        # Not dataclasses.astuple — that deep-copies every array leaf.
        return tuple(getattr(self, f.name) for f in dataclasses.fields(self)), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def others_index(K: int) -> np.ndarray:
    """(K, K-1) static index matrix: row k lists all nodes j != k ascending —
    the concatenation order of reference tango.py:142-155."""
    return np.stack([[j for j in range(K) if j != k] for k in range(K)])


def oracle_masks(S: jnp.ndarray, N: jnp.ndarray, mask_type: str = "irm1", ref_mic: int = 0) -> jnp.ndarray:
    """Oracle TF masks at each node's reference mic: (K, C, F, T) -> (K, F, T)
    (the irm/ibm/iam branch of tango.py:189-211)."""
    return tf_mask(S[:, ref_mic], N[:, ref_mic], mask_type)


def _masked_cov_pair(X, mask, cov_impl: str, frame_axis, precision: str = "f32"):
    """(Rss, Rnn) of ``mask * X`` / ``(1-mask) * X`` — the shared
    mask->covariance stage of both steps, routed by ``cov_impl``:

    * 'auto' (the default since the round-6 promotion): the fused pallas
      kernel on real TPU backends, the folded einsum elsewhere —
      ``ops.cov_ops.resolve_cov_impl``, ``DISCO_TPU_COV_IMPL`` env escape
      hatch.  Parity stays gated by the float64 oracles in
      tests/reference_impls.py and tests/test_ops.py.
    * 'xla': the FOLDED einsum (``ops.cov_ops.masked_covariances_folded``,
      since the hot-path fusion round): mask weights contracted inside the
      covariance accumulation, so the masked spectrogram copies are never
      program values even off-TPU.
    * 'pallas': the fused single-read kernel (ops.cov_ops) — the masked
      copies never touch HBM (round-2 verdict #3).

    ``mask`` is (F, T) shared or (C, F, T) per-channel (the step-2 stacked
    layout under the 'distant'/'none' policies); ``precision`` is the
    ops.resolve compute lane ('f32' default, 'bf16' opt-in).  Sequence
    parallelism (``frame_axis``) falls back to the materializing einsum —
    the psum needs ``frame_mean_covariance``'s axis_name plumbing — and
    supports shared masks only (the one caller shape that existed before
    the fold).
    """
    if cov_impl == "auto":
        from disco_tpu.ops.cov_ops import resolve_cov_impl

        cov_impl = resolve_cov_impl(cov_impl)
    if frame_axis is None:
        from disco_tpu.ops.cov_ops import masked_covariances_fused

        return masked_covariances_fused(X, mask, impl=cov_impl, precision=precision)
    m = mask[None] if mask.ndim == X.ndim - 1 else mask
    Rss = frame_mean_covariance(m * X, axis_name=frame_axis)
    Rnn = frame_mean_covariance((1.0 - m) * X, axis_name=frame_axis)
    return Rss, Rnn


# ------------------------------------------------------------------ step 1
def _step1_covariances(Y, S, N, mask_z, oracle_stats: bool, frame_axis,
                       cov_impl: str, precision: str):
    """The covariance stage of step 1 at ONE node: (F, C, C) (Rss, Rnn)
    pencils from the masked mixture (or the oracle S/N stats).  Factored
    out of :func:`tango_step1` so :func:`tango` can vmap THIS stage alone
    over the node axis and hand the stacked (K, F, C, C) pencils to a
    single batch-in-lanes fused solve (same ops, same order — the
    composition in ``tango_step1`` traces the identical program).

    Reference counterpart: the covariance half of tango.py:326-349.
    """
    if oracle_stats:
        Rss = frame_mean_covariance(S, axis_name=frame_axis)  # (F, C, C)
        Rnn = frame_mean_covariance(N, axis_name=frame_axis)
        return Rss, Rnn
    return _masked_cov_pair(Y, mask_z, cov_impl, frame_axis, precision)


def _step1_apply(w, t1, Y, S, N, ref_mic: int = 0):
    """The filter-application stage of step 1 at ONE node: (F, C) weights →
    the compressed (F, T) exchange streams (the other factored half of
    :func:`tango_step1` — see :func:`_step1_covariances`).

    Reference counterpart: the ``np.inner`` applications of
    tango.py:361-374.
    """
    z_y = jnp.einsum("fc,cft->ft", jnp.conj(w), Y)
    z_s = jnp.einsum("fc,cft->ft", jnp.conj(w), S)
    z_n = jnp.einsum("fc,cft->ft", jnp.conj(w), N)
    z_t1_s = jnp.einsum("fc,cft->ft", t1, S)  # np.inner(t1, ·): no conjugate
    z_t1_n = jnp.einsum("fc,cft->ft", t1, N)
    zn = Y[ref_mic] - z_y
    return {"z_y": z_y, "z_s": z_s, "z_n": z_n, "zn": zn, "z_t1_s": z_t1_s, "z_t1_n": z_t1_n}


@partial(jax.jit, static_argnames=("oracle_stats", "ref_mic", "frame_axis", "solver",
                                   "cov_impl", "precision"))
def tango_step1(
    Y, S, N, mask_z, mu: float = 1.0, oracle_stats: bool = False, ref_mic: int = 0,
    frame_axis: str | None = None, solver: str = "power", cov_impl: str = "auto",
    precision: str = "f32",
):
    """Step 1 at ONE node: local rank-1 GEVD-MWF -> compressed signals.

    This is the per-node unit that ``vmap``s over the node axis on one device
    and runs under ``shard_map`` on a node-sharded mesh (tango.py:326-377).

    Args:
      Y, S, N: (C, F, T) complex STFTs of mixture / speech / noise.
      mask_z: (F, T) step-1 mask at the reference mic.
      oracle_stats: the 'use_oracle_' step-1 branch (tango.py:345-349) —
        covariances from the true S/N instead of masked Y.
      precision: the ops.resolve compute lane of the masked-covariance
        accumulation — 'f32' (default, the pre-existing program) or 'bf16'
        (bf16 multiplies, f32 accumulators; gated by the documented looser
        oracle tolerances in tests/test_tango.py).  With a ``'fused*'``
        solver the lane extends into the solve itself (bf16 pencil planes
        at the HBM->VMEM boundary, f32 in-VMEM iterations —
        ops/mwf_ops.py); the other solver families ignore it.

    Returns:
      dict with z_y/z_s/z_n/zn (F, T) and t1-projected references
      z_t1_s/z_t1_n (F, T) (the ``z_gevd_*`` diagnostics of tango.py:372-374).
    """
    precision = check_canonical_precision(precision)
    Rss, Rnn = _step1_covariances(Y, S, N, mask_z, oracle_stats, frame_axis,
                                  cov_impl, precision)
    w, t1 = rank1_gevd(Rss, Rnn, mu=mu, solver=solver, precision=precision)  # (F, C) each
    return _step1_apply(w, t1, Y, S, N, ref_mic)


# ------------------------------------------------------------------ step 2
def _masked_select(z_oth, a_oth):
    """Zero the unavailable z channels of a gathered (K-1, F, T) stack.

    ``jnp.where`` (a select), NOT multiplication: a corrupted stream can
    carry NaN/Inf, and ``0 * nan`` is ``nan`` — the select guarantees an
    excluded channel contributes exact zeros no matter what it holds.
    """
    return jnp.where(a_oth[:, None, None] > 0, z_oth, jnp.zeros((), z_oth.dtype))


def _regularize_excluded(Rnn, n_mics: int, a_oth):
    """Trace-relative diagonal loading on the EXCLUDED z channels of a
    (F, D, D) noise covariance (D = n_mics + K - 1).

    A zeroed channel leaves a zero row/column in both covariances; loading
    its Rnn diagonal entry (Rss stays zero) decouples it exactly: the
    whitened matrix becomes block-diagonal with a zero block, the channel's
    generalized eigenvalue hits the EIG_FLOOR clamp, and its Wiener gain is
    ~0 — the surviving channels solve precisely the subset MWF.  Scaled by
    the mean Rnn diagonal so the loading conditions the Cholesky at any
    signal level (warm-up streaming covariances are ~1e-12).
    """
    D = Rnn.shape[-1]
    reg = jnp.concatenate([jnp.zeros(n_mics), 1.0 - (a_oth > 0)]).astype(Rnn.real.dtype)
    tr = jnp.trace(Rnn, axis1=-2, axis2=-1).real / D
    load = jnp.maximum(tr, jnp.finfo(tr.dtype).tiny)[..., None] * reg
    return Rnn + load[..., None] * jnp.eye(D, dtype=Rnn.dtype)


def finite_z_guard(z_y):
    """(K,) availability flags from finiteness of the exchanged streams: a
    node whose compressed signal carries any non-finite value is treated as
    unavailable (the z-exchange seam's corruption detector).  Jittable —
    runs inside the step-2 program, so the sharded paths get it too."""
    fin = jnp.isfinite(z_y.real) & jnp.isfinite(z_y.imag)
    return fin.all(axis=(-2, -1)).astype(z_y.real.dtype)


def _z_stats(policy: Policy, mask_w_k, all_z, all_masks_w, all_S_ref, all_N_ref, mask_type):
    """Speech/noise statistic versions of the exchanged z streams, per the
    mask-for-z policy matrix (tango.py:396-429).  Returns (K, F, T) stat
    arrays indexed by *source* node (the consumer selects its 'others')."""
    z_y = all_z["z_y"]
    if policy == "local":
        # Consumer-side mask: node k's own mask_w on every incoming z
        # (tango.py:418-420 with z_for_rs left unmasked).
        return mask_w_k[None] * z_y, (1.0 - mask_w_k)[None] * z_y
    if policy is None or policy == "none":
        # Unmasked z for speech stats, the zn = y_ref - z estimate for noise
        # (tango.py:421-424).
        return z_y, all_z["zn"]
    if policy == "distant":
        # Producer-side mask: each z_j masked with node j's own mask_w
        # (tango.py:398-400).
        return all_masks_w * z_y, (1.0 - all_masks_w) * z_y
    if policy == "compressed":
        # Mask estimated on the compressed signal itself (tango.py:401-405).
        mc = tf_mask(all_z["z_s"], all_z["z_n"], mask_type)
        return mc * z_y, (1.0 - mc) * z_y
    if policy == "use_oracle_refs":
        # Oracle ref-mic clean components in place of z (tango.py:406-408).
        return all_S_ref, all_N_ref
    if policy == "use_oracle_zs":
        # True speech/noise components of z (tango.py:409-411).
        return all_z["z_s"], all_z["z_n"]
    raise ValueError(f"unknown mask_for_z policy {policy!r}; expected one of {_POLICIES}")


@partial(jax.jit, static_argnames=("policy", "ref_mic", "mask_type", "frame_axis",
                                   "solver", "cov_impl", "precision"))
def tango_step2(
    Y,
    S,
    N,
    mask_w_k,
    k,
    all_z,
    all_masks_w,
    all_S_ref,
    all_N_ref,
    mu: float = 1.0,
    policy: Policy = "local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    frame_axis: str | None = None,
    solver: str = "power",
    cov_impl: str = "auto",
    precision: str = "f32",
    z_avail=None,
):
    """Step 2 at ONE node k: global rank-1 GEVD-MWF on ``[y_k ‖ z_{j≠k}]``
    (tango.py:380-455).

    Args:
      Y, S, N: (C, F, T) local STFTs of node k.
      mask_w_k: (F, T) step-2 mask of node k.
      k: scalar node index (traced — under shard_map it is ``axis_index``).
      all_z: dict of (K, F, T) gathered step-1 outputs from ALL nodes —
        the product of the z-exchange (all_gather over the node axis).
      all_masks_w: (K, F, T) gathered step-2 masks (for the 'distant' policy).
      all_S_ref / all_N_ref: (K, F, T) gathered ref-mic clean components
        (for the 'use_oracle_refs' policy).
      precision: ops.resolve compute lane of the covariance accumulation
        and, under the ``'fused*'`` solver family, of the GEVD solve
        ('f32' default / 'bf16' opt-in — see :func:`tango_step1`).
      z_avail: optional (K,) availability of the exchanged streams as seen
        by THIS consumer (1 = arrived intact).  Unavailable channels are
        excluded from the MWF (module docstring); None (default) is the
        fault-free fast path, byte-identical to the original pipeline.

    Covariance fusion (the hot-path fusion round): the 'local', 'distant'
    and 'none' policies all express their statistic stacks as per-channel
    masks over the SAME stacked streams, so their covariances run as
    masked rank-1 updates (``_masked_cov_pair`` / ``weighted_cov_folded``)
    and the masked spectrograms are never materialized — 'local' shares
    one mask across the stack, 'distant' carries producer masks on the z
    channels, 'none' is ``[m ‖ 1]`` over ``[Y ‖ z]`` for speech and
    ``[(1-m) ‖ 1]`` over ``[Y ‖ zn]`` for noise (two single-cov folds:
    the two stacks differ, so the pair kernel does not apply).  The
    remaining policies ('compressed', the oracle ones) substitute genuinely
    different signals and keep the materializing path.

    Returns:
      (yf, sf, nf): (F, T) filtered mixture / speech / noise at node k.
    """
    precision = check_canonical_precision(precision)
    K = all_z["z_y"].shape[0]
    C = Y.shape[0]
    # Ascending j != k (dynamic k — shard_map passes a traced axis_index).
    oth = jnp.arange(K - 1) + (jnp.arange(K - 1) >= k)
    if z_avail is None:
        sel = lambda v: v[oth]
    else:
        a_oth = z_avail[oth]  # (K-1,) availability of this node's others
        sel = lambda v: _masked_select(v[oth], a_oth)

    in_y = jnp.concatenate([Y, sel(all_z["z_y"])], axis=0)  # (C+K-1, F, T)
    fold_ok = frame_axis is None  # sequence parallelism keeps the psum path
    if policy == "local":
        # 'local' masks every stacked channel — own mics AND incoming z's —
        # with node k's own mask (tango.py:418-420), i.e. the whole stat
        # stack is one masked covariance of [Y ‖ z_{j≠k}]: the fused
        # single-read kernel applies to the full C+K-1 stack.
        Rss, Rnn = _masked_cov_pair(in_y, mask_w_k, cov_impl, frame_axis, precision)
    elif policy == "distant" and fold_ok:
        # Producer-side masks per z channel, consumer mask on the local
        # mics (tango.py:398-400): one per-channel mask stack over in_y —
        # the zeroing select on unavailable z commutes with the real mask
        # multiply, so folding is exact under faults too.
        chan_mask = jnp.concatenate(
            [jnp.broadcast_to(mask_w_k[None], (C,) + mask_w_k.shape),
             all_masks_w[oth]], axis=0,
        )
        Rss, Rnn = _masked_cov_pair(in_y, chan_mask, cov_impl, frame_axis, precision)
    elif policy in (None, "none") and fold_ok:
        from disco_tpu.ops.cov_ops import weighted_cov_folded

        ones = jnp.ones((K - 1,) + mask_w_k.shape, mask_w_k.dtype)
        m_c = jnp.broadcast_to(mask_w_k[None], (C,) + mask_w_k.shape)
        Rss = weighted_cov_folded(
            in_y, jnp.concatenate([m_c, ones], axis=0), precision
        )
        in_zn = jnp.concatenate([Y, sel(all_z["zn"])], axis=0)
        Rnn = weighted_cov_folded(
            in_zn, jnp.concatenate([1.0 - m_c, ones], axis=0), precision
        )
    else:
        zs_stat_all, zn_stat_all = _z_stats(
            policy, mask_w_k, all_z, all_masks_w, all_S_ref, all_N_ref, mask_type
        )
        m = mask_w_k[None]
        stat_s = jnp.concatenate([m * Y, sel(zs_stat_all)], axis=0)  # (C+K-1, F, T)
        stat_n = jnp.concatenate([(1.0 - m) * Y, sel(zn_stat_all)], axis=0)
        Rss = frame_mean_covariance(stat_s, axis_name=frame_axis)
        Rnn = frame_mean_covariance(stat_n, axis_name=frame_axis)
    if z_avail is not None:
        Rnn = _regularize_excluded(Rnn, C, a_oth)
    w, _ = rank1_gevd(Rss, Rnn, mu=mu, solver=solver, precision=precision)  # (F, C+K-1)

    in_s = jnp.concatenate([S, sel(all_z["z_s"])], axis=0)
    in_n = jnp.concatenate([N, sel(all_z["z_n"])], axis=0)
    yf = jnp.einsum("fc,cft->ft", jnp.conj(w), in_y)
    sf = jnp.einsum("fc,cft->ft", jnp.conj(w), in_s)
    nf = jnp.einsum("fc,cft->ft", jnp.conj(w), in_n)
    return yf, sf, nf


# ------------------------------------------------------------- full pipeline
@partial(jax.jit, static_argnames=("policy", "ref_mic", "mask_type",
                                   "oracle_step1_stats", "solver", "cov_impl",
                                   "precision"))
def tango(
    Y,
    S,
    N,
    masks_z,
    mask_w,
    mu: float = 1.0,
    policy: Policy = "local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    oracle_step1_stats: bool = False,
    solver: str = "power",
    cov_impl: str = "auto",
    precision: str = "f32",
    z_mask=None,
    z_nan=None,
) -> TangoResult:
    """The full two-step pipeline on one device: ``vmap`` over the node axis,
    z-exchange by plain indexing (the in-process ``concatenate_signals`` of
    the reference).  For the mesh-sharded version see
    ``disco_tpu.parallel.tango_sharded`` — both are bit-identical.

    Args:
      Y, S, N: (K, C, F, T) complex STFT stacks.
      masks_z, mask_w: (K, F, T) step-1 / step-2 masks.
      z_mask: optional availability of the exchanged z streams — (K,) per
        source node, or (K, K) with row k = what consumer k received
        (asymmetric link loss).  Unavailable streams are excluded from the
        step-2 MWF (module docstring); at K-1 = 0 available streams a node
        degrades to local-only beamforming on its own mics.
      z_nan: optional (K,) flags — corrupt node k's exchanged streams to
        NaN after step 1 (fault injection at the exchange seam,
        ``disco_tpu.fault``).  Activating either fault input also arms the
        finiteness guard: any node whose z carries non-finite values is
        excluded, injected or not.

    Batched use: ``jax.vmap(tango, in_axes=(0, 0, 0, 0, 0))`` over a rooms
    axis — rooms, nodes, freq and frames are all array axes.

    ``precision``: ops.resolve compute lane of both steps' covariance
    accumulations ('f32' default — the pre-existing program — or 'bf16'
    with f32 accumulators, gated by the documented looser oracle
    tolerances; tests/test_tango.py).  Must be the CANONICAL token: this
    entry point is jitted directly, so a spelling variant normalized here
    would already have keyed a duplicate program — it raises instead
    (``ops.resolve.check_canonical_precision``; callers holding user input
    canonicalize with ``resolve_precision`` first, as the CLI/driver do).
    """
    precision = check_canonical_precision(precision)
    if is_fused_spec(solver):
        # Step-1 fused solve, batched across K×F (the step-1 fusion round):
        # vmapping the whole of tango_step1 over the node axis would run K
        # separate fused-solve instances, each padding its F pencils to a
        # full lane tile (~half the lanes dead at F=257, tile=512).  The
        # fused kernels are batch-polymorphic — ``planes()`` flattens every
        # leading axis into lanes (ops/mwf_ops.py) — so instead the
        # covariance stage alone vmaps to stacked (K, F, C, C) pencils and
        # ALL K·F step-1 solves run as ONE batch-in-lanes VMEM-resident
        # program through the same dispatch table.  Identical math, one
        # program instead of K; parity pinned in tests/test_mwf_ops.py.
        Rss, Rnn = jax.vmap(
            lambda y, s, n, m: _step1_covariances(
                y, s, n, m, oracle_step1_stats, None, cov_impl, precision)
        )(Y, S, N, masks_z)
        w1, t1 = rank1_gevd(Rss, Rnn, mu=mu, solver=solver, precision=precision)
        all_z = jax.vmap(partial(_step1_apply, ref_mic=ref_mic))(w1, t1, Y, S, N)
    else:
        step1 = jax.vmap(
            lambda y, s, n, m: tango_step1(
                y, s, n, m, mu=mu, oracle_stats=oracle_step1_stats, ref_mic=ref_mic,
                solver=solver, cov_impl=cov_impl, precision=precision,
            )
        )
        all_z = step1(Y, S, N, masks_z)

    K = Y.shape[0]
    if z_nan is not None:
        # Injection at the exchange seam: every stream the corrupted node
        # would have sent turns NaN, exactly what a garbled packet looks
        # like to the consumers (the guard below must catch it).
        bad = (jnp.asarray(z_nan) > 0)[:, None, None]
        nanc = jnp.full((), jnp.nan + 1j * jnp.nan, all_z["z_y"].dtype)
        all_z = {key: jnp.where(bad, nanc, val) for key, val in all_z.items()}
    if z_mask is None and z_nan is None:
        step2 = jax.vmap(
            lambda y, s, n, mw, k: tango_step2(
                y, s, n, mw, k, all_z, mask_w, S[:, ref_mic], N[:, ref_mic],
                mu=mu, policy=policy, ref_mic=ref_mic, mask_type=mask_type,
                solver=solver, cov_impl=cov_impl, precision=precision,
            ),
            in_axes=(0, 0, 0, 0, 0),
        )
        yf, sf, nf = step2(Y, S, N, mask_w, jnp.arange(K))
    else:
        fin = finite_z_guard(all_z["z_y"])  # (K,) corruption detector
        if z_mask is None:
            avail = jnp.broadcast_to(fin[None, :], (K, K))
        else:
            zm = jnp.asarray(z_mask, Y.real.dtype)
            zm = jnp.broadcast_to(zm, (K, K)) if zm.ndim == 1 else zm
            avail = zm * fin[None, :]  # rows = consumer, cols = source
        step2 = jax.vmap(
            lambda y, s, n, mw, k, za: tango_step2(
                y, s, n, mw, k, all_z, mask_w, S[:, ref_mic], N[:, ref_mic],
                mu=mu, policy=policy, ref_mic=ref_mic, mask_type=mask_type,
                solver=solver, cov_impl=cov_impl, precision=precision, z_avail=za,
            ),
            in_axes=(0, 0, 0, 0, 0, 0),
        )
        yf, sf, nf = step2(Y, S, N, mask_w, jnp.arange(K), avail)
    return TangoResult(
        yf=yf, sf=sf, nf=nf,
        z_y=all_z["z_y"], z_s=all_z["z_s"], z_n=all_z["z_n"], zn=all_z["zn"],
        masks_z=masks_z, mask_w=mask_w,
    )
