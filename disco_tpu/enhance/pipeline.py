"""Corpus throughput engine: overlapped prefetch / dispatch / readback.

The batched corpus driver (:func:`disco_tpu.enhance.driver.
enhance_rirs_batched`) historically ran its three phases strictly in
sequence — load a chunk's wavs from disk, dispatch the jitted batch to the
device, read the results back and score — so the device idled during disk
I/O and the host idled during compute.  BENCH_r05 puts the per-clip
pipeline at thousands of times realtime *on device*; corpus wall-clock was
dominated by everything around the dispatch.  This module provides the two
overlap primitives the driver now composes:

* :class:`ChunkPrefetcher` — a double-buffered background loader: while the
  device runs chunk N, a daemon thread loads and pads chunk N+1 (wav
  decode, numpy padding, ledger ``in_flight`` marks and the ``chunk_load``
  chaos seam all run *with the work*, on the loader thread, so crash-safe
  resume semantics are preserved — an interrupted prefetched chunk is
  simply in_flight-but-not-done and is redone on resume).  The loader does
  host-only work (no jax), so it never contends for the device.
* :func:`fetch_chunk_host` — ONE batched, complex-safe ``jax.device_get``
  of everything a chunk's scoring needs (per-clip time-domain outputs,
  step-1/2 masks, exported z streams).  The per-clip
  ``tree_map(lambda x: x[i])`` lazy slices this replaces crossed the
  tunnel K×n_real times per chunk at a fixed ~80 ms RPC each
  (CLAUDE.md); the batched fetch crosses once.

Observability: each chunk records a ``chunk_pipeline`` stage event (with
the prefetch stall it paid as an attr), ``fetch_chunk_host`` a
``chunk_readback`` stage event, and the ``prefetch_stall_ms`` /
``readback_ms`` / ``overlap_efficiency`` gauges (plus stall/readback
histograms and the ``chunks_pipelined`` / ``chunk_readbacks`` counters)
land in every ``counters`` snapshot, so ``disco-obs report`` and the
``corpus_clips_per_s`` bench lane can regress the overlap itself.

No reference counterpart: the reference enhances clips one at a time in a
Python loop (SURVEY.md §5.5); this is the layer that turns a fast kernel
into a fast corpus run.
"""
from __future__ import annotations

import queue as queue_mod
import threading
import time
from dataclasses import dataclass

import numpy as np

from disco_tpu.obs import events as obs_events
from disco_tpu.obs.metrics import REGISTRY as obs_registry

#: Scoring backpressure: at most this many chunks of pending scoring
#: futures are kept in flight before the dispatch thread blocks on the
#: oldest.  2 (not 1, the pre-engine ``drain()`` bound) lets chunk N-1's
#: scoring overlap chunk N's dispatch AND chunk N+1's prefetch without
#: unbounded host memory growth.
MAX_PENDING_CHUNKS = 2


@dataclass
class LoadedChunk:
    """One corpus chunk, loaded and padded, ready to dispatch."""

    bucket: int          # padded clip length Lp (the compile bucket)
    chunk: list          # [(rir, out_path, layout), ...] — n_real entries
    sigs: list           # per-clip load_input_signals tuples (y, s, n, ...)
    ys: np.ndarray       # (B, K, C, Lp) padded mixture stack (B >= n_real)
    ss: np.ndarray       # (B, K, C, Lp) padded target stack
    ns: np.ndarray       # (B, K, C, Lp) padded noise stack
    n_real: int          # real clips in the batch (the rest is pad)

    @property
    def clip_lengths(self) -> list:
        """True (unpadded) length per real clip — what ISTFT trims to."""
        return [self.sigs[i][0].shape[-1] for i in range(self.n_real)]


_END = object()


class ChunkPrefetcher:
    """Double-buffered background chunk loader.

    Iterating yields ``(LoadedChunk, stall_s)`` where ``stall_s`` is how
    long the consumer waited for the chunk — the number that tells you
    whether disk I/O or the device is the bottleneck (``stall_s ≈ 0`` means
    the prefetch fully hid the load behind the previous chunk's compute).

    ``depth`` bounds lookahead: with the default 2, at most one chunk sits
    ready in the queue while a second is being loaded — double buffering,
    so host memory holds at most ``depth`` chunks beyond the one being
    consumed.  Exceptions from the loader (including
    :class:`~disco_tpu.runs.chaos.ChaosCrash`, a ``BaseException`` — an
    injected crash must kill the run exactly like a process death) are
    re-raised at the consuming site, and ``stop_requested`` (the graceful
    SIGTERM/SIGINT flag of ``disco_tpu.runs.interrupt``) is polled between
    chunks so an interrupted run stops marking new work ``in_flight``.

    Always :meth:`close` in a ``finally``: a consumer that unwinds
    mid-iteration (chaos crash, scoring error) would otherwise leave the
    loader thread blocked on a full queue.  After ``close`` the loader
    starts no new chunk (the stop flag is checked before every load, and a
    chunk's ledger marks are written before its wav reads begin), so the
    only residue a loader caught MID-load can emit is finishing that one
    read — if it outlives the join timeout, ``close`` says so loudly (a
    ``warning`` obs event + ``prefetch_orphaned`` counter) instead of
    silently abandoning it.
    """

    def __init__(self, work, load_chunk, depth: int = 2, stop_requested=None):
        if depth < 2:
            raise ValueError(f"ChunkPrefetcher needs depth >= 2 (double buffering), got {depth}")
        # a generator stays lazy and is drained ON the loader thread — the
        # training batch feed (nn.training._prefetch_host_batches) does its
        # numpy prep inside next(), which is exactly the work to offload;
        # finite lists are still snapshotted against caller mutation
        self._work = work if hasattr(work, "__next__") else list(work)
        self._load = load_chunk
        self._stop = threading.Event()
        self._stop_requested = stop_requested or (lambda: False)
        # depth - 1 queued + 1 being loaded = depth chunks of lookahead
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=depth - 1)
        self._thread = threading.Thread(
            target=self._run, name="disco-chunk-prefetch", daemon=True
        )
        self._thread.start()

    def _put(self, item) -> bool:
        """Blocking put that stays responsive to :meth:`close`."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def _run(self):
        try:
            for work_item in self._work:
                if self._stop.is_set() or self._stop_requested():
                    break
                loaded = self._load(*work_item)
                if not self._put(loaded):
                    return
            self._put(_END)
        except BaseException as e:  # ChaosCrash included — re-raised at get()
            self._put(e)

    def __iter__(self):
        while True:
            t0 = time.perf_counter()
            item = self._q.get()
            stall_s = time.perf_counter() - t0
            if item is _END:
                self._thread.join(timeout=5.0)
                return
            if isinstance(item, BaseException):
                raise item
            yield item, stall_s

    def close(self, join_timeout: float = 5.0) -> bool:
        """Stop the loader and release it: set the stop flag, drain the
        queue (unblocking a pending put) and join.  Idempotent.

        Returns True when the loader actually exited.  A loader stuck
        inside one slow chunk read cannot observe the flag mid-call; it
        will start nothing new afterwards, but if it outlives the timeout
        that is recorded (warning event + counter), never swallowed — a
        caller resuming in-process deserves to know a stale read is still
        draining."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue_mod.Empty:
                break
        self._thread.join(timeout=join_timeout)
        if self._thread.is_alive():
            obs_registry.counter("prefetch_orphaned").inc()
            obs_events.record(
                "warning", stage="chunk_load",
                reason="prefetch loader still inside a chunk read after "
                       f"close({join_timeout:g}s); it will exit after that "
                       "read without starting new work",
            )
            return False
        return True


def fetch_chunk_host(res_b, clip_lengths, n_real: int) -> dict:
    """Move one chunk's scoring inputs to host in ONE batched device_get.

    The time-domain conversion happens here, on device, one clip at a time
    with exactly the shapes and static lengths the sequential path uses
    (``istft(res.yf[i], length=L_i)``) — bit-identical outputs by
    construction, queued asynchronously with no readback between clips.
    Then the whole payload — six time-domain arrays per clip, the step-1/2
    masks and the exported z streams for the real clips — crosses the
    host boundary as a single complex-safe
    :func:`~disco_tpu.utils.transfer.device_get_tree` call.

    This replaces the K×n_real lazy per-clip readbacks of the pre-engine
    driver (``tree_map(lambda x: x[i])`` slices materialized one
    ``np.asarray`` at a time inside scoring — see ``chunk_readbacks`` /
    ``device_get_batches`` in the counters snapshot for the accounting).

    Args:
      res_b: batched :class:`~disco_tpu.enhance.tango.TangoResult`
        (leaves ``(B, K, F, T)``), device-resident.
      clip_lengths: true (unpadded) sample length per real clip.
      n_real: number of real clips (pad clips are never fetched).

    Returns:
      dict with ``td`` (list of per-clip 6-tuples ``(sh_t, szh_t, sf_t,
      nf_t, szf_t, nzf_t)``, each ``(K, L_i)`` float32 numpy), ``masks_z``
      / ``mask_w`` (``(n_real, K, F, T)`` float numpy) and ``z_y``
      (``(n_real, K, F, T)`` complex64 numpy).
    """
    from disco_tpu.core.dsp import istft
    from disco_tpu.utils.transfer import device_get_tree

    with obs_events.stage("chunk_readback", n_clips=n_real):
        td = []
        for i in range(n_real):
            L = int(clip_lengths[i])
            td.append(tuple(
                istft(z[i], length=L)
                for z in (res_b.yf, res_b.z_y, res_b.sf, res_b.nf, res_b.z_s, res_b.z_n)
            ))
        t0 = time.perf_counter()
        host = device_get_tree({
            "td": td,
            "masks_z": res_b.masks_z[:n_real],
            "mask_w": res_b.mask_w[:n_real],
            "z_y": res_b.z_y[:n_real],
        })
        dt_ms = (time.perf_counter() - t0) * 1e3
    obs_registry.gauge("readback_ms").set(dt_ms)
    obs_registry.histogram("readback_ms").observe(dt_ms)
    obs_registry.counter("chunk_readbacks").inc()
    return host


def fetch_chained_host(out_b, clip_lengths, n_real: int) -> dict:
    """Chained-lane twin of :func:`fetch_chunk_host`: the ``run_batch_chained``
    runner already converted every clip to time domain *inside* the chained
    program (its export payload carries six (B, K, Lp) stacks), so this
    fetch only moves the payload across in ONE batched ``device_get_tree``
    and trims each clip's bucket padding to its true length on host (numpy
    views — the trim is not a device crossing).  Returns the same dict
    shape as :func:`fetch_chunk_host`.

    The trimmed streams are the chained program's own ISTFTs of the padded
    clip, sliced — not a per-clip ``istft(length=L_i)`` — so chained chunk
    artifacts are parity-matched to the staged path at the documented
    chained tolerance (``enhance.fused``), not bit-identical.

    No reference counterpart (module docstring).
    """
    from disco_tpu.utils.transfer import device_get_tree

    with obs_events.stage("chunk_readback", n_clips=n_real, chained=True):
        t0 = time.perf_counter()
        host = device_get_tree({
            "td": tuple(a[:n_real] for a in out_b["td"]),
            "masks_z": out_b["masks_z"][:n_real],
            "mask_w": out_b["mask_w"][:n_real],
            "z_y": out_b["z_y"][:n_real],
        })
        dt_ms = (time.perf_counter() - t0) * 1e3
    obs_registry.gauge("readback_ms").set(dt_ms)
    obs_registry.histogram("readback_ms").observe(dt_ms)
    obs_registry.counter("chunk_readbacks").inc()
    td_stacks = host["td"]
    host["td"] = [
        tuple(a[i][..., : int(clip_lengths[i])] for a in td_stacks)
        for i in range(n_real)
    ]
    return host


def note_chunk_overlap(stall_s: float, busy_s: float) -> None:
    """Record one chunk's overlap economics: the stall the dispatch loop
    paid waiting for the prefetcher and the busy time it then spent, folded
    into the ``prefetch_stall_ms`` / ``overlap_efficiency`` gauges (last
    chunk) and the stall histogram (whole run).  ``overlap_efficiency`` is
    busy/(busy+stall): 1.0 means the prefetch fully hid the load."""
    stall_ms = stall_s * 1e3
    obs_registry.gauge("prefetch_stall_ms").set(stall_ms)
    obs_registry.histogram("prefetch_stall_ms").observe(stall_ms)
    total = busy_s + stall_s
    obs_registry.gauge("overlap_efficiency").set(busy_s / total if total > 0 else 1.0)
    obs_registry.counter("chunks_pipelined").inc()
