"""Streaming (frame-recursive) TANGO — the online mode the reference has
machinery for but never wires in.

The reference ships an exponential-smoothing covariance estimator
(``spatial_correlation_matrix``, se_utils/internal_formulas.py:84-103) as the
*online* alternative to the offline frame-mean used by tango, but no caller
ever uses it (SURVEY.md §2.2/§5.7).  Here it becomes a first-class pipeline
with fixed per-frame latency and O(1) covariance state.

Warm start: the reference recursion takes "the previous estimation of Rxx"
as input and — having no caller — never defines the initial state.  This
module initializes ``R0 = 1e-6 * I`` (a tiny isotropic loading): after t
frames the state is ``lam^t * R0 + (1-lam) * sum lam^(t-i) x_i x_i^H``,
i.e. the reference recursion exactly, plus an exponentially-vanishing
regularizer whose only role is keeping the very first GEVD refreshes
well-posed (the refresh guard below skips them anyway if ill-conditioned).
The per-frame update itself — ``R <- lam R + (1-lam) (m x)(m x)^H`` with
the mask fused into the stream — matches internal_formulas.py:84-103 with
``M`` pre-multiplied, as its docstring describes.

TPU-first structure: the naive formulation (a ``lax.scan`` over frames with
the GEVD refresh under ``lax.cond``) is what a line-by-line port would write,
but complex ``eigh`` inside XLA control flow is unsupported on TPU and
serializes the eigendecompositions even where it runs.  Instead the stream is
processed in blocks of ``update_every`` frames: one scan carries the smoothed
covariances and *emits a covariance checkpoint per block* (the recursion over
the intra-block frames is unrolled in closed form as a single weighted
einsum — an MXU contraction), then ALL refresh-point GEVDs run as one
batched top-level ``eigh``, and the per-block filters are applied to their
frames with one more einsum.  Numerically identical to the naive recursion;
compiles and batches everywhere.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from disco_tpu.beam.filters import rank1_gevd
from disco_tpu.enhance.tango import others_index
from disco_tpu.obs.accounting import counted_jit
from disco_tpu.ops.resolve import resolve_precision

#: Default filter-refresh block length (frames).  Shared with the driver's
#: fault wiring: a streaming availability mask is per-block, so the block
#: count B = ceil(T / update_every) must agree between the fault plan and
#: this module's reshape.
DEFAULT_UPDATE_EVERY = 4

#: Signature defaults of the traced float parameters, named so callers that
#: need bit-reproducibility (``disco_tpu.serve.scheduler``) can mirror the
#: canonical calling convention: jax.jit applies an OMITTED default at trace
#: time (a weak f64 Python constant, folded once), while a PASSED float is a
#: traced f32 input computed at runtime — e.g. ``0.99 ** 3`` then differs in
#: the last ulp between the two, and the warm-up GEVD refreshes run on
#: near-degenerate covariances where one ulp flips the ffill hold guard and
#: diverges the stream.  Same value, different program: omit when equal.
DEFAULT_LAMBDA_COR = 0.99
DEFAULT_MU = 1.0


def _outer(x):
    """(..., F, D) frame -> (..., F, D, D) outer product."""
    return jnp.einsum("...fc,...fd->...fcd", x, jnp.conj(x),
                      precision=jax.lax.Precision.HIGHEST)


def initial_stream_state(n_nodes: int, n_mics: int, n_freq: int,
                         update_every: int = DEFAULT_UPDATE_EVERY,
                         ref_mic: int = 0, dtype=None):
    """The explicit warm-start continuation state of :func:`streaming_tango`
    as a host (numpy) pytree — exactly the state the ``state=None`` /
    ``z_avail=None`` defaults materialize internally (``R0 = 1e-6 I``
    covariances, the ref-mic one-hot filter seed, an empty last-good-z hold
    carry), so ``streaming_tango(..., state=initial_stream_state(...),
    z_avail=ones)`` is bit-identical to the default first call (pinned in
    tests/test_serve.py).

    The online enhancement service (``disco_tpu.serve``) needs the state in
    this explicit form from block 0: every session then carries a uniform,
    serializable pytree (``disco_tpu.serve.session.save_session_state``)
    instead of a ``None``-until-first-block special case.

    Returns a dict with ``step1``/``step2`` ``(Rss, Rnn, w)`` triples
    (leading node axis, matching the vmapped per-node streams) and the
    ``hold`` carries for the ``z_y``/``zn`` exchanged streams.
    """
    import numpy as np

    dtype = np.complex64 if dtype is None else np.dtype(dtype)
    K, C, F, u = int(n_nodes), int(n_mics), int(n_freq), int(update_every)
    D2 = C + K - 1  # step-2 stacks [local mics ‖ K-1 exchanged z's]
    eps = 1e-6

    def cov_w(D):
        R = np.broadcast_to(eps * np.eye(D, dtype=dtype), (K, F, D, D)).copy()
        w = np.zeros((K, F, D), dtype)
        w[..., ref_mic] = 1.0
        return R, w

    R1, w1 = cov_w(C)
    R2, w2 = cov_w(D2)

    def hold_carry():
        return (np.zeros((K, F, u), dtype), np.zeros((K,), bool))

    return {
        "step1": (R1, R1.copy(), w1),
        "step2": (R2, R2.copy(), w2),
        "hold": {"z_y": hold_carry(), "zn": hold_carry()},
    }


def _block_covariances(XSb, XNb, lam, Rss0=None, Rnn0=None, precision: str = "f32"):
    """Scan over frame blocks, emitting the refresh-point covariances.
    ``Rss0``/``Rnn0`` seed the recursion (continuation state from a previous
    chunk); default is the documented warm start.

    The refresh covariance of block b is the smoothed estimate *after the
    block's first frame* — exactly where the naive per-frame recursion
    ``R <- lam R + (1-lam) x x^H`` (the reference's
    ``spatial_correlation_matrix``, internal_formulas.py:84-103, with its
    mask fused into the stream) refreshes its filter.  The remaining u-1
    frames advance the carry in closed form:
    ``R_end = lam^(u-1) R_refresh + (1-lam) sum_i lam^(u-1-i) x_i x_i^H``.

    Args:
      XSb: (B, u, F, D) speech-statistic frame blocks (already masked /
        policy-shaped — see ``_stream_stats``).
      XNb: (B, u, F, D) noise-statistic frame blocks.
      lam: smoothing factor.
      precision: ops.resolve compute lane of the intra-block accumulation
        einsum — 'f32' (default: the pre-existing program, bit-identical)
        or 'bf16' (planar re/im contraction with bf16 operands and f32
        accumulators; the rank-1 refresh outer product stays f32 — it is
        one frame, and the GEVD warm-up conditions on it).

    Returns:
      ((Rss_end, Rnn_end), (Rss_ref, Rnn_ref)) with ref shapes (B, F, D, D).
    """
    B, u, F, D = XSb.shape
    eps = 1e-6
    if Rss0 is None:
        Rss0 = jnp.broadcast_to(eps * jnp.eye(D, dtype=XSb.dtype), (F, D, D))
    if Rnn0 is None:
        Rnn0 = jnp.broadcast_to(eps * jnp.eye(D, dtype=XSb.dtype), (F, D, D))
    # weights lam^(u-1-i) for intra-block frames i = 1..u-1
    tail_w = lam ** jnp.arange(u - 2, -1, -1, dtype=jnp.float32) if u > 1 else None
    bf16 = resolve_precision(precision) == "bf16"

    def acc_tail(x):  # (u-1, F, D) -> sum_t w_t x_t x_t^H, (F, D, D)
        if not bf16:
            return jnp.einsum("t,tfc,tfd->fcd", tail_w, x, jnp.conj(x),
                              precision=jax.lax.Precision.HIGHEST)
        # the bf16 planar accumulator lives in ops/ — precision casts are
        # an ops concern (DL012), this module only routes the lane
        from disco_tpu.ops.cov_ops import outer_acc_bf16

        return outer_acc_bf16(tail_w, x)

    def body(carry, inp):
        Rss, Rnn = carry
        xs, xn = inp  # (u, F, D) each
        Rss_r = lam * Rss + (1.0 - lam) * _outer(xs[0])
        Rnn_r = lam * Rnn + (1.0 - lam) * _outer(xn[0])
        if u > 1:
            acc_s = acc_tail(xs[1:])
            acc_n = acc_tail(xn[1:])
            Rss_e = lam ** (u - 1) * Rss_r + (1.0 - lam) * acc_s
            Rnn_e = lam ** (u - 1) * Rnn_r + (1.0 - lam) * acc_n
        else:
            Rss_e, Rnn_e = Rss_r, Rnn_r
        return (Rss_e, Rnn_e), (Rss_r, Rnn_r)

    # unroll=1 (explicit, DL011): this recursion runs identically inside the
    # per-block program and the scanned super-tick body, so its rolled form
    # cancels in the bit-exactness comparison — rolled is the deliberate
    # choice (smaller program, no parity exposure).
    return jax.lax.scan(body, (Rss0, Rnn0), (XSb, XNb), unroll=1)


def _stream_filter(X, XS, XN, lam, u, mu, ref: int = 0, extras=None, init_state=None,
                   solver: str = "eigh", precision: str = "f32"):
    """One node's streaming filter over a (T, F, D) frame stream.

    ``X`` is the stream the filter is APPLIED to; ``XS``/``XN`` are the
    speech/noise statistic streams driving the smoothed covariances (for the
    plain 'local' policy these are ``m*X`` and ``(1-m)*X``; other policies
    shape the z channels differently — see ``_stream_stats``).

    ``ref``: channel selected by the warm-up / skipped-refresh fallback
    filter (the node's reference mic).  ``extras``: optional list of
    (T, F, D) streams filtered with the same per-block filters (clean-
    component diagnostics).

    Returns (out (T, F), w_last (F, D), Rss_end, Rnn_end, filtered_extras).
    """
    T, F, D = X.shape
    pad = (-T) % u
    if pad:
        zpad = jnp.zeros((pad, F, D), X.dtype)
        X = jnp.concatenate([X, zpad])
        XS = jnp.concatenate([XS, zpad])
        XN = jnp.concatenate([XN, zpad])
    B = X.shape[0] // u
    Xb = X.reshape(B, u, F, D)

    Rss0, Rnn0, w_seed = (None, None, None) if init_state is None else init_state
    (Rss_e, Rnn_e), (Rss_ref, Rnn_ref) = _block_covariances(
        XS.reshape(B, u, F, D), XN.reshape(B, u, F, D), lam, Rss0, Rnn0,
        precision=precision,
    )
    if pad:
        # Padded zero frames only decay the carry (R <- lam R); undo so the
        # returned continuation state is the true end-of-stream estimate.
        undo = lam ** (-pad)
        Rss_e = Rss_e * undo
        Rnn_e = Rnn_e * undo
    # ALL refresh GEVDs at once: one batched top-level solve over (B, F)
    # bins.  sanitize=False: a degenerate refresh must surface as non-finite
    # so the ffill guard below keeps the PREVIOUS block's filter (the
    # adaptive-beamforming fallback) instead of the solvers' e1 selector,
    # which would silently switch the stream to channel 0.
    w = jax.vmap(
        lambda a, b: rank1_gevd(a, b, mu=mu, solver=solver, sanitize=False,
                                precision=precision)[0]
    )(Rss_ref, Rnn_ref)  # (B, F, D)
    # An ill-conditioned refresh (warm-up covariances can make the stacked
    # [mics ‖ z] channels nearly dependent; TPU f32 eigh then returns
    # non-finite) is SKIPPED: keep the previous block's filter — the standard
    # adaptive-beamforming guard.  Falls back to the ref-mic selector before
    # the first good refresh (or to the previous chunk's final filter when
    # continuing).
    e_ref = jnp.zeros((F, D), w.dtype).at[:, ref].set(1.0) if w_seed is None else w_seed

    def ffill(prev, wb):
        ok = jnp.isfinite(wb.real) & jnp.isfinite(wb.imag)
        ok = ok.all(axis=-1, keepdims=True)
        wb = jnp.where(ok, wb, prev)
        return wb, wb

    # unroll=1 (explicit, DL011): same in both gated paths — see
    # _block_covariances.
    _, w = jax.lax.scan(ffill, e_ref, w, unroll=1)
    out = jnp.einsum("bfd,bufd->buf", jnp.conj(w), Xb).reshape(B * u, F)[:T]
    if extras is not None:
        # Apply the SAME per-block filters to auxiliary streams (clean
        # speech/noise components) — the diagnostics of the offline path
        # (sf/nf), produced by the one online filter.
        filtered = []
        for E in extras:
            Ep = jnp.concatenate([E, jnp.zeros((pad, F, D), E.dtype)]) if pad else E
            Eb = Ep.reshape(B, u, F, D)
            filtered.append(jnp.einsum("bfd,bufd->buf", jnp.conj(w), Eb).reshape(B * u, F)[:T])
        return out, w[-1], Rss_e, Rnn_e, filtered
    return out, w[-1], Rss_e, Rnn_e, []


# counted_jit: same semantics as jax.jit, plus a jit_trace event per fresh
# trace (new static args / shapes) so online-mode retraces are visible in
# `obs report` — per-chunk deployment with drifting chunk lengths is exactly
# the recompile trap this counter exists to catch.
@partial(counted_jit, label="streaming_step1",
         static_argnames=("update_every", "ref_mic", "with_diagnostics", "solver",
                          "precision"))
def streaming_step1(
    Y,
    mask_z,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    S=None,
    N=None,
    with_diagnostics: bool = False,
    state=None,
    solver: str = "eigh",
    precision: str = "f32",
):
    """Streaming local MWF at one node: recursive covariance smoothing with a
    filter refresh every ``update_every`` frames.

    Args:
      Y: (C, F, T) complex mixture STFT.
      mask_z: (F, T) step-1 mask.
      S, N: optional clean component STFTs — with ``with_diagnostics=True``
        the same online filter is applied to them, yielding z_s/z_n (the
        filter-on-clean diagnostics of the offline path).
      state: optional (Rss, Rnn, w) continuation state from a previous
        chunk's output — true chunk-by-chunk online processing.  When the
        previous chunk's frame count is a multiple of ``update_every``, the
        chained result is numerically identical to processing the whole
        stream at once (pinned in tests/test_streaming.py).

    Returns:
      dict with z_y (F, T) compressed stream, zn (F, T) = y_ref - z, the
      final (Rss, Rnn, w) state for continuation, and z_s/z_n when
      diagnostics are requested.
    """
    def tfc(a):
        return jnp.moveaxis(a, -1, 0).swapaxes(-1, -2)  # (C,F,T) -> (T,F,C)

    extras = [tfc(S), tfc(N)] if with_diagnostics else None
    X = tfc(Y)
    M = mask_z.T[..., None]  # (T, F, 1) broadcast over channels
    z, w, Rss, Rnn, extra_out = _stream_filter(
        X, M * X, (1.0 - M) * X, lambda_cor, update_every, mu, ref=ref_mic, extras=extras,
        init_state=state, solver=solver, precision=precision,
    )
    z_y = z.T
    out = {"z_y": z_y, "zn": Y[ref_mic] - z_y, "Rss": Rss, "Rnn": Rnn, "w": w}
    if with_diagnostics:
        out["z_s"], out["z_n"] = extra_out[0].T, extra_out[1].T
    return out


def hold_last_good(z, avail, update_every: int, fallback=None, carry=None,
                   return_carry: bool = False):
    """Last-good-z hold over refresh blocks: the degraded-mode delivery
    policy for transient link loss (``disco_tpu.fault``).

    The exchanged stream is processed in blocks of ``update_every`` frames
    (the filter-refresh granularity of this module).  A block whose z was
    not delivered (``avail[k, b] == 0``) is bridged with the most recent
    delivered block's frames — the standard hold policy of adaptive
    beamformers under packet loss.  Blocks lost before ANY delivery fall
    back to the matching ``fallback`` block (the producer's ``zn = y_ref -
    z`` noise estimate in the pipeline wiring) or, with ``fallback=None``,
    keep their original frames (used for the diagnostic streams, which are
    held only once a good block exists).

    ``jnp.where`` selects throughout, so a lost block full of NaN can never
    leak into the output.

    Args:
      z: (K, F, T) exchanged stream.
      avail: (K, B) per-block availability, B = ceil(T / update_every).
      fallback: optional (K, F, T) stream substituted for leading losses.
      carry: optional ``(last_block, seen)`` continuation state from a
        previous chunk's ``return_carry=True`` call — chunked runs then
        bridge a loss at a chunk boundary with the PREVIOUS chunk's last
        good block, exactly like the unchunked run.
      return_carry: also return the end-of-stream ``(last_block, seen)``.

    Returns:
      (K, F, T) held stream — and the carry when ``return_carry``.
    """
    K, F, T = z.shape
    u = update_every
    pad = (-T) % u
    B = (T + pad) // u
    avail = jnp.asarray(avail)
    if avail.ndim == 1:  # (K,) shorthand: constant over blocks
        avail = avail[:, None]
    avail = jnp.broadcast_to(avail, (K, B))

    def blocks(a):  # (K, F, T) -> (B, K, F, u)
        ap = jnp.pad(a, ((0, 0), (0, 0), (0, pad))) if pad else a
        return jnp.moveaxis(ap.reshape(K, F, B, u), 2, 0)

    zb = blocks(z)
    fb = blocks(fallback) if fallback is not None else zb
    ok = (avail > 0).T  # (B, K)

    def step(carry, inp):
        last, seen = carry  # (K, F, u) last emitted block, (K,) any-good flag
        blk, fblk, a = inp
        subst = jnp.where(seen[:, None, None], last, fblk)
        out = jnp.where(a[:, None, None], blk, subst)
        return (out, seen | a), out

    init = (jnp.zeros_like(zb[0]), jnp.zeros(K, bool)) if carry is None else carry
    # unroll=1 (explicit, DL011): pure jnp.where selects — no FMA to
    # reassociate — and identical in both gated paths.
    carry_out, held = jax.lax.scan(step, init, (zb, fb, ok), unroll=1)
    out = jnp.moveaxis(held, 0, 2).reshape(K, F, B * u)[..., :T]
    return (out, carry_out) if return_carry else out


def _stream_stats(Y, all_z, zn, mask_w, oth, policy):
    """Step-2 speech/noise statistic streams per node under the mask-for-z
    policy — the streaming mirror of the offline ``_z_stats``
    (tango.py:396-429 semantics):

    - 'local':   consumer mask m_k on local mics AND every incoming z.
    - 'distant': producer mask m_j on z_j; consumer mask on local mics.
    - 'none'/None: z unmasked for speech stats, the producer's zn stream
      (y_ref - z) for noise stats; consumer mask on local mics.

    Returns (XS, XN): (K, C+K-1, F, T) stacked statistic streams.
    """
    m = mask_w[:, None]  # (K, 1, F, T)
    y_s, y_n = m * Y, (1.0 - m) * Y
    z_oth = all_z[oth]  # (K, K-1, F, T)
    if policy == "local":
        zs_stat = mask_w[:, None] * z_oth
        zn_stat = (1.0 - mask_w)[:, None] * z_oth
    elif policy is None or policy == "none":
        zs_stat = z_oth
        zn_stat = zn[oth]
    elif policy == "distant":
        mw_oth = mask_w[oth]  # producer masks, (K, K-1, F, T)
        zs_stat = mw_oth * z_oth
        zn_stat = (1.0 - mw_oth) * z_oth
    else:
        raise ValueError(
            f"streaming mask-for-z policy {policy!r} not supported; "
            "one of 'local', 'distant', 'none' (other policies are offline-only)"
        )
    return (
        jnp.concatenate([y_s, zs_stat], axis=1),
        jnp.concatenate([y_n, zn_stat], axis=1),
    )


def _streaming_tango_body(
    Y,
    masks_z,
    mask_w,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    S=None,
    N=None,
    with_diagnostics: bool = False,
    policy: str | None = "local",
    state=None,
    solver: str = "eigh",
    z_avail=None,
    precision: str = "f32",
):
    """The one-block state transition of :func:`streaming_tango` — the
    traced computation, shared verbatim with the :func:`streaming_tango_scan`
    scan body so the scanned path is the per-block program by construction
    (the serve scheduler already proved a *restructured* program — the
    vmapped megabatch — diverges through the warm-up GEVD + ffill hold).
    ``precision`` routes BOTH steps' covariance accumulations through the
    ops.resolve compute lane here, in the one shared body, so the scanned
    super-tick, the per-block path and the serve scheduler can never run
    different kernels for the same lane."""
    K, C, F, T = Y.shape
    st1_in, st2_in = (None, None) if state is None else (state["step1"], state["step2"])
    step1 = jax.vmap(
        lambda y, m, s, n, st: streaming_step1(
            y, m, lambda_cor=lambda_cor, update_every=update_every, mu=mu, ref_mic=ref_mic,
            S=s, N=n, with_diagnostics=with_diagnostics, state=st, solver=solver,
            precision=precision,
        ),
        in_axes=(0, 0, 0, 0, 0 if st1_in is not None else None),
    )
    s_in = S if with_diagnostics else Y
    n_in = N if with_diagnostics else Y
    s1 = step1(Y, masks_z, s_in, n_in, st1_in)
    all_z = s1["z_y"]  # (K, F, T)
    zn = s1["zn"]
    z_s, z_n = (s1["z_s"], s1["z_n"]) if with_diagnostics else (None, None)
    hold_state = None
    if z_avail is not None:
        # Degraded-mode delivery: lost/stale blocks reuse the last good z
        # (zn-estimate fallback before the first delivery); zn and the
        # diagnostic streams are held with the same availability so every
        # downstream statistic describes the stream the consumer actually
        # used.  The per-stream hold carries ride the continuation state so
        # a loss at a chunk boundary is bridged with the previous chunk's
        # last good block, exactly like the unchunked run.
        hin = (state or {}).get("hold", {}) or {}
        all_z, h_zy = hold_last_good(all_z, z_avail, update_every, fallback=zn,
                                     carry=hin.get("z_y"), return_carry=True)
        zn, h_zn = hold_last_good(zn, z_avail, update_every,
                                  carry=hin.get("zn"), return_carry=True)
        hold_state = {"z_y": h_zy, "zn": h_zn}
        if with_diagnostics:
            z_s, h_zs = hold_last_good(z_s, z_avail, update_every,
                                       carry=hin.get("z_s"), return_carry=True)
            z_n, h_znn = hold_last_good(z_n, z_avail, update_every,
                                        carry=hin.get("z_n"), return_carry=True)
            hold_state["z_s"] = h_zs
            hold_state["z_n"] = h_znn

    oth = jnp.asarray(others_index(K))  # (K, K-1)

    def stack_streams(base, z_streams):
        return jnp.concatenate([base, z_streams[oth]], axis=1)  # (K, C+K-1, F, T)

    def ktfd(a):
        return jnp.moveaxis(a, -1, 1).swapaxes(-1, -2)  # (K, D, F, T) -> (K, T, F, D)

    X = ktfd(stack_streams(Y, all_z))
    XS, XN = _stream_stats(Y, all_z, zn, mask_w, oth, policy)
    XS, XN = ktfd(XS), ktfd(XN)
    if with_diagnostics:
        Xs = ktfd(stack_streams(S, z_s))
        Xn = ktfd(stack_streams(N, z_n))
        stream2 = jax.vmap(
            lambda x, xs_st, xn_st, xs, xn, st: _stream_filter(
                x, xs_st, xn_st, lambda_cor, update_every, mu, ref=ref_mic, extras=[xs, xn],
                init_state=st, solver=solver, precision=precision,
            ),
            in_axes=(0, 0, 0, 0, 0, 0 if st2_in is not None else None),
        )
        yf, w2, Rss2, Rnn2, (sf, nf) = stream2(X, XS, XN, Xs, Xn, st2_in)
        out_state = {"step1": (s1["Rss"], s1["Rnn"], s1["w"]),
                     "step2": (Rss2, Rnn2, w2)}
        if hold_state is not None:
            out_state["hold"] = hold_state
        return {
            "yf": jnp.moveaxis(yf, 1, -1),
            "sf": jnp.moveaxis(sf, 1, -1),
            "nf": jnp.moveaxis(nf, 1, -1),
            "z_y": all_z,
            "zn": zn,
            "z_s": z_s,
            "z_n": z_n,
            "state": out_state,
        }
    stream2 = jax.vmap(
        lambda x, xs_st, xn_st, st: _stream_filter(
            x, xs_st, xn_st, lambda_cor, update_every, mu, ref=ref_mic, init_state=st,
            solver=solver, precision=precision,
        )[:4],
        in_axes=(0, 0, 0, 0 if st2_in is not None else None),
    )
    yf, w2, Rss2, Rnn2 = stream2(X, XS, XN, st2_in)  # yf (K, T, F)
    out_state = {"step1": (s1["Rss"], s1["Rnn"], s1["w"]),
                 "step2": (Rss2, Rnn2, w2)}
    if hold_state is not None:
        out_state["hold"] = hold_state
    return {
        "yf": jnp.moveaxis(yf, 1, -1),
        "z_y": all_z,
        "zn": zn,
        "state": out_state,
    }


def _float_kw(lambda_cor, mu):
    """Forward the traced floats ONLY when they differ from the signature
    defaults — the canonical calling convention (module docstring): jax.jit
    folds an omitted default at trace time but traces a passed value, and
    the two programs differ in the last ulp where the warm-up GEVD amplifies
    it.  The host-side wrappers below must not turn every omitted default
    into a passed value."""
    kw = {}
    if not (isinstance(lambda_cor, float) and lambda_cor == DEFAULT_LAMBDA_COR):
        kw["lambda_cor"] = lambda_cor
    if not (isinstance(mu, float) and mu == DEFAULT_MU):
        kw["mu"] = mu
    return kw


def _chaos_between_blocks(state):
    """Fire the ``between_blocks`` chaos seam on a chunk-continuation entry
    — host-side, OUTSIDE the jitted program, so it fires on every
    continuation call (a tick inside the traced function would fire only at
    trace time and silently skip every cached call)."""
    if state is not None:
        from disco_tpu.runs import chaos as _chaos

        _chaos.tick("between_blocks")


@partial(counted_jit, label="streaming_tango",
         static_argnames=("update_every", "ref_mic", "with_diagnostics", "policy",
                          "solver", "precision"))
def _streaming_tango_jit(
    Y,
    masks_z,
    mask_w,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    S=None,
    N=None,
    with_diagnostics: bool = False,
    policy: str | None = "local",
    state=None,
    solver: str = "eigh",
    z_avail=None,
    precision: str = "f32",
):
    """The jitted :func:`_streaming_tango_body` (the public
    :func:`streaming_tango` wrapper adds the host-side chaos seam)."""
    return _streaming_tango_body(
        Y, masks_z, mask_w, lambda_cor=lambda_cor, update_every=update_every,
        mu=mu, ref_mic=ref_mic, S=S, N=N, with_diagnostics=with_diagnostics,
        policy=policy, state=state, solver=solver, z_avail=z_avail,
        precision=precision,
    )


def streaming_tango(
    Y,
    masks_z,
    mask_w,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    S=None,
    N=None,
    with_diagnostics: bool = False,
    policy: str | None = "local",
    state=None,
    solver: str = "eigh",
    z_avail=None,
    precision: str = "f32",
):
    """Full two-step streaming TANGO over all nodes (mixture-only by
    default: the deployment path needs no oracle S/N).

    Step 1 streams per node (vmapped); the z-exchange is array indexing on
    one device (an all_gather over 'node' when mesh-sharded); step 2 streams
    the stacked [y_k ‖ z_{j≠k}] under the 'local', 'distant' or 'none'
    mask-for-z policy (see :func:`_stream_stats`; the oracle policies are
    offline-only features).

    Args:
      Y: (K, C, F, T) mixture STFTs.
      masks_z, mask_w: (K, F, T) step-1 / step-2 masks.
      S, N: optional (K, C, F, T) clean components; with
        ``with_diagnostics=True`` the SAME online filters are applied to
        them, yielding sf/nf/z_s/z_n — every diagnostic then describes the
        one deployed filter (no second offline pass).
      state: optional continuation state (the previous chunk's returned
        ``state``) — chunk-by-chunk online deployment of BOTH steps; exact
        across refresh-block-aligned boundaries (tests/test_streaming.py).
      z_avail: optional per-block availability of the exchanged streams —
        (K, B) with B = ceil(T / update_every), or (K,) broadcast over
        blocks.  Lost/stale blocks are bridged by :func:`hold_last_good`
        (previous good block, falling back to the producer's ``zn``
        estimate before the first delivery); the diagnostic streams are
        held with the same availability.  The hold carries ride the
        returned ``state`` (key ``"hold"``), so chunked continuation —
        pass per-chunk masks — bridges a chunk-boundary loss with the
        previous chunk's last good block, matching the unchunked run
        across refresh-block-aligned boundaries.  None (default) is the
        fault-free path, byte-identical to before.
      precision: ops.resolve compute lane of the covariance accumulations
        ('f32' default — the pre-existing program, bit-identical — or
        'bf16' opt-in).  Canonicalized here (``resolve_precision``) before
        it reaches the static-argument seam, so spelling variants of the
        same lane can never trace duplicate programs (the string-typed
        mu=1 trap; retrace budgets stay exact).

    Returns:
      dict with yf (K, F, T) enhanced outputs, z_y/zn (K, F, T) streams,
      a ``state`` entry for continuation, and sf/nf/z_s/z_n when
      diagnostics are requested.

    Crash safety: a chunked deployment loop is exactly the shape the
    crash-safe runs layer (``disco_tpu.runs``) targets — the returned
    ``state`` is the continuation checkpoint, so a caller persisting it
    atomically per chunk (``disco_tpu.io.atomic``) can resume a killed
    stream at the last chunk boundary.  The ``between_blocks`` chaos seam
    fires at each chunk-continuation entry (host-side, outside jit) so
    ``make chaos-check``-style tests can interrupt a chunked run at the
    boundary.
    """
    _chaos_between_blocks(state)
    return _streaming_tango_jit(
        Y, masks_z, mask_w, update_every=update_every, ref_mic=ref_mic,
        S=S, N=N, with_diagnostics=with_diagnostics, policy=policy,
        state=state, solver=solver, z_avail=z_avail,
        precision=resolve_precision(precision),
        **_float_kw(lambda_cor, mu),
    )


#: the jit plumbing of the wrapped program, for callers that re-jit it with
#: different options (the serve scheduler's donated off-CPU step uses
#: ``__wrapped__``) or inspect the cache (tests, counted_jit accounting)
streaming_tango.jitted = _streaming_tango_jit.jitted
streaming_tango.lower = _streaming_tango_jit.lower
streaming_tango.clear_cache = _streaming_tango_jit.clear_cache
streaming_tango.__wrapped__ = _streaming_tango_jit.__wrapped__


@partial(counted_jit, label="streaming_tango_scan",
         static_argnames=("blocks_per_dispatch", "update_every", "ref_mic",
                          "with_diagnostics", "policy", "solver", "precision"))
def _streaming_tango_scan_jit(
    Y,
    masks_z,
    mask_w,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    S=None,
    N=None,
    with_diagnostics: bool = False,
    policy: str | None = "local",
    state=None,
    solver: str = "eigh",
    z_avail=None,
    blocks_per_dispatch: int = 1,
    precision: str = "f32",
):
    """Device-resident super-tick: ``blocks_per_dispatch`` refresh-aligned
    streaming blocks per dispatch, via ``lax.scan`` over the per-block state
    transition.

    On the tunneled attachment every fenced dispatch pays a fixed ~80 ms RPC
    round-trip (CLAUDE.md), so a per-block host loop is pure dispatch
    overhead once the on-device per-frame latency beats the frame budget
    (BENCH_r03–r05: ``streaming_rtf`` flat at 18.9× while offline RTF nearly
    doubled).  This driver moves the block recursion on device: one program
    runs N blocks back to back, so one fenced readback amortizes over N
    blocks instead of gating each one.

    Bit-exactness contract: the scan body is
    :func:`_streaming_tango_body` — the *identical* per-block computation
    :func:`streaming_tango` traces, with the same carry pytree as
    ``initial_stream_state``/``state=`` and the same ``z_avail`` hold
    semantics (a lost block is bridged identically inside a super-tick and
    across its edges, because the hold carries ride the scan carry exactly
    as they ride the returned ``state`` between per-block calls).  Pinned by
    ``tests/test_streaming.py`` and the hermetic ``make stream-check`` gate;
    a restructured program (the vmapped megabatch) is exactly what the serve
    scheduler measured diverging (~1.0 rel err through the GEVD warm-up +
    ffill hold), so the scan body being the per-block program is the load-
    bearing design decision, not an implementation detail.  The scan runs
    with ``unroll=N`` for the same reason: a *rolled* while-loop body
    compiles with different FMA/fusion choices than the standalone per-block
    program (measured ~2e-6 step-1 drift on CPU, amplified to ~3e-2 through
    the warm-up GEVD), while the unrolled bodies compile exactly like the
    standalone program — still ONE dispatch, which is the whole point.

    Args:
      Y: (K, C, F, T) mixture STFTs with ``T = blocks_per_dispatch * Tc``
        and ``Tc`` a multiple of ``update_every`` — N equal refresh-aligned
        blocks.  Streams that don't divide evenly fall back to the per-block
        path for the remainder (the serve scheduler and ``bench.py`` do
        exactly that).
      masks_z, mask_w: (K, F, T) step-1 / step-2 masks.
      state: optional continuation carry (same pytree as
        :func:`streaming_tango`); ``None`` materializes
        :func:`initial_stream_state` — bit-identical to the per-block
        default first call (pinned in tests/test_serve.py).
      z_avail: optional (K, B) availability over ALL ``B = T //
        update_every`` refresh blocks of the window (or (K,) broadcast);
        sliced per scanned block into exactly the columns the per-block
        path would receive.
      blocks_per_dispatch: N, the super-tick width (static: one compiled
        program per N).

    Returns:
      the :func:`streaming_tango` dict — yf/z_y/zn (K, F, T) stitched over
      the N blocks, plus the end-of-window ``state`` (and the diagnostics
      when requested).
    """
    n = int(blocks_per_dispatch)
    if n < 1:
        raise ValueError(f"blocks_per_dispatch must be >= 1, got {blocks_per_dispatch}")
    K, C, F, T = Y.shape
    u = update_every
    if T % n:
        raise ValueError(
            f"streaming_tango_scan: T={T} frames does not split into "
            f"blocks_per_dispatch={n} equal blocks (run the remainder through "
            "the per-block path)"
        )
    Tc = T // n
    if Tc % u:
        raise ValueError(
            f"streaming_tango_scan: per-dispatch block length {Tc} must be a "
            f"multiple of update_every={u} (refresh-aligned blocks)"
        )
    if with_diagnostics and (S is None or N is None):
        raise ValueError("with_diagnostics=True needs S and N")
    if state is None:
        state = jax.tree_util.tree_map(
            jnp.asarray,
            initial_stream_state(K, C, F, update_every=u, ref_mic=ref_mic,
                                 dtype=Y.dtype),
        )

    carry = {"step1": state["step1"], "step2": state["step2"]}
    hold_keys = ("z_y", "zn") + (("z_s", "z_n") if with_diagnostics else ())
    if z_avail is not None:
        # Pre-fill any missing hold carry with the zero seed
        # hold_last_good(carry=None) would materialize — bit-identical, and
        # it keeps the scan carry structure fixed across iterations.
        hin = (state.get("hold") or {}) if isinstance(state, dict) else {}
        carry["hold"] = {
            key: hin[key] if hin.get(key) is not None
            else (jnp.zeros((K, F, u), Y.dtype), jnp.zeros((K,), bool))
            for key in hold_keys
        }

    def chunk(a):  # (..., T) -> (n, ..., Tc) leading scan axis
        a = jnp.asarray(a)
        return jnp.moveaxis(a.reshape(a.shape[:-1] + (n, Tc)), -2, 0)

    xs = {"Y": chunk(Y), "mz": chunk(masks_z), "mw": chunk(mask_w)}
    if with_diagnostics:
        xs["S"], xs["N"] = chunk(S), chunk(N)
    if z_avail is not None:
        Bc = Tc // u
        za = jnp.asarray(z_avail)
        if za.ndim == 1:
            za = jnp.broadcast_to(za[:, None], (K, n * Bc))
        if za.shape != (K, n * Bc):
            raise ValueError(
                f"z_avail shape {za.shape} does not cover the window: "
                f"expected ({K}, {n * Bc}) refresh-block columns"
            )
        xs["za"] = jnp.moveaxis(za.reshape(K, n, Bc), 1, 0)  # (n, K, Bc)

    def body(c, x):
        st = {"step1": c["step1"], "step2": c["step2"]}
        if "hold" in c:
            st["hold"] = c["hold"]
        out = _streaming_tango_body(
            x["Y"], x["mz"], x["mw"], lambda_cor=lambda_cor, update_every=u,
            mu=mu, ref_mic=ref_mic, S=x.get("S"), N=x.get("N"),
            with_diagnostics=with_diagnostics, policy=policy, state=st,
            solver=solver, z_avail=x.get("za"), precision=precision,
        )
        st_out = out.pop("state")
        c_out = {"step1": st_out["step1"], "step2": st_out["step2"]}
        if "hold" in st_out:
            c_out["hold"] = st_out["hold"]
        return c_out, out

    carry_out, ys = jax.lax.scan(body, carry, xs, unroll=n)

    def unchunk(a):  # (n, K, F, Tc) -> (K, F, n * Tc)
        return jnp.moveaxis(a, 0, -2).reshape(a.shape[1:-1] + (T,))

    out = {key: unchunk(val) for key, val in ys.items()}
    out_state = {"step1": carry_out["step1"], "step2": carry_out["step2"]}
    if "hold" in carry_out:
        out_state["hold"] = carry_out["hold"]
    out["state"] = out_state
    return out


def streaming_tango_scan(
    Y,
    masks_z,
    mask_w,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    S=None,
    N=None,
    with_diagnostics: bool = False,
    policy: str | None = "local",
    state=None,
    solver: str = "eigh",
    z_avail=None,
    blocks_per_dispatch: int = 1,
    precision: str = "f32",
):
    """Host entry of the scanned super-tick driver — fires the
    ``between_blocks`` chaos seam on every chunk-continuation call (outside
    the jitted program) and mirrors the canonical traced-float convention,
    then dispatches :func:`_streaming_tango_scan_jit` (see its docstring
    for the full contract).

    No direct reference counterpart: the reference never wires its online
    estimator (se_utils/internal_formulas.py:84-103, the recursion
    :func:`streaming_tango` deploys) into any driver, and dispatch-RPC
    amortization is a concern of this port's tunneled-TPU deployment only.
    """
    _chaos_between_blocks(state)
    return _streaming_tango_scan_jit(
        Y, masks_z, mask_w, update_every=update_every, ref_mic=ref_mic,
        S=S, N=N, with_diagnostics=with_diagnostics, policy=policy,
        state=state, solver=solver, z_avail=z_avail,
        blocks_per_dispatch=blocks_per_dispatch,
        precision=resolve_precision(precision),
        **_float_kw(lambda_cor, mu),
    )


streaming_tango_scan.jitted = _streaming_tango_scan_jit.jitted
streaming_tango_scan.lower = _streaming_tango_scan_jit.lower
streaming_tango_scan.clear_cache = _streaming_tango_scan_jit.clear_cache
streaming_tango_scan.__wrapped__ = _streaming_tango_scan_jit.__wrapped__
