"""Whole-clip chained TANGO — the entire enhancement chain as ONE program.

The staged offline driver (``enhance.driver.enhance_rir``) dispatches the
clip as a sequence of separately jitted programs — fused STFT, mask
estimation, the two-step ``tango`` pipeline, then six ISTFTs at persist
time — and every stage boundary materializes full (K, F, T) spectrogram
stacks to HBM and, on the tunneled attachment, pays a fenced ~80 ms RPC
per dispatch (CLAUDE.md).  This module chains

    stft_with_mag -> tf_mask_mag -> folded covariances -> fused step-1
    -> z-exchange -> fused step-2 -> istft

into one jitted program per clip (:func:`tango_clip_fused`) and one per
streaming super-tick (:func:`streaming_clip_fused`, built on the shared
:func:`~disco_tpu.enhance.streaming._streaming_tango_body` factoring via
``streaming_tango_scan``): the only arrays that ever cross the program
boundary are the time-domain inputs and outputs — plus the masks / z
streams when exporting, and the continuation state of the streaming twin,
all declared program I/O.  XLA then fuses across the former stage seams
and no (K, F, T)-shaped intermediate escapes to the output avals (pinned
by the committed disco-trace goldens, tests/test_trace.py).

Bit-exactness: the chained program traces the SAME stage functions in the
same order as the staged path, so the spectral pipeline itself is the
identical computation — but the chained CLIP output is not guaranteed
bit-equal to the staged driver's persisted wavs (XLA may fuse across the
former dispatch boundaries and reassociate differently), and the
streaming twin's per-window STFT sees each super-tick window's own
reflect padding instead of the full clip's.  Parity is pinned at
documented tolerances in tests/test_fused_clip.py; see
doc/source/performance.rst ("Chaining the clip") for when each path is
and isn't bit-exact.

Defaults: this module is opt-in everywhere (driver ``chained=...``, CLI
``--chained``); the staged path and its defaults are untouched.

No reference counterpart: the reference enhances one clip per process
through Python-loop stages (tango.py:460-641) and has no program
boundary to fuse across.
"""
from __future__ import annotations

from functools import partial

import jax.numpy as jnp

from disco_tpu.core.dsp import istft
from disco_tpu.enhance.streaming import (
    DEFAULT_LAMBDA_COR,
    DEFAULT_MU,
    DEFAULT_UPDATE_EVERY,
    _chaos_between_blocks,
    _float_kw,
    streaming_tango_scan,
)
from disco_tpu.enhance.tango import oracle_masks, tango
from disco_tpu.obs.accounting import counted_jit
from disco_tpu.ops.resolve import check_canonical_precision, resolve_precision
from disco_tpu.ops.stft_ops import stft_with_mag


def _clip_oracle_masks(spec, mag, mask_type: str, ref_mic: int):
    """(K, F, T) oracle step masks from the fused STFT's outputs: the
    irm/ibm families consume the magnitudes the one STFT program already
    emitted (``tf_mask_mag`` — no second ``abs`` pass over the spectra);
    the iam family needs the complex sum and falls back to the spectral
    entry point.  Reference counterpart: the mask branch of tango.py:189-211
    (via :func:`~disco_tpu.enhance.tango.oracle_masks`).
    """
    if mask_type[:-1] in ("irm", "ibm"):
        from disco_tpu.core.masks import tf_mask_mag

        return tf_mask_mag(mag[1][:, ref_mic], mag[2][:, ref_mic], mask_type)
    return oracle_masks(spec[1], spec[2], mask_type, ref_mic=ref_mic)


@partial(counted_jit, label="tango_clip_fused",
         static_argnames=("policy", "ref_mic", "mask_type",
                          "oracle_step1_stats", "solver", "cov_impl",
                          "stft_impl", "precision", "export"))
def _tango_clip_fused_jit(
    y,
    s,
    n,
    masks_z=None,
    mask_w=None,
    mu: float = 1.0,
    policy: str | None = "local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    oracle_step1_stats: bool = False,
    solver: str = "fused",
    cov_impl: str = "auto",
    stft_impl: str = "auto",
    precision: str = "f32",
    export: bool = False,
):
    """The jitted :func:`tango_clip_fused` (the public wrapper canonicalizes
    the precision token and applies the traced-float convention)."""
    precision = check_canonical_precision(precision)
    L = y.shape[-1]
    # ONE fused spec+magnitude program over the stacked y/s/n streams; the
    # masks consume the emitted magnitudes in the same program.
    spec, mag = stft_with_mag(jnp.stack([y, s, n]), impl=stft_impl,
                              precision=precision)
    Y, S, N = spec[0], spec[1], spec[2]
    if masks_z is None:
        masks_z = _clip_oracle_masks(spec, mag, mask_type, ref_mic)
    if mask_w is None:
        mask_w = masks_z
    res = tango(Y, S, N, masks_z, mask_w, mu=mu, policy=policy,
                ref_mic=ref_mic, mask_type=mask_type,
                oracle_step1_stats=oracle_step1_stats, solver=solver,
                cov_impl=cov_impl, precision=precision)
    if not export:
        return istft(res.yf, length=L)
    # The export payload is exactly what the driver's scoring half needs
    # (_persist_and_score's time_domain + masks/z contract): six
    # time-domain streams through ONE stacked ISTFT, plus the (K, F, T)
    # masks and the exported z — all declared program outputs.
    td = istft(jnp.stack([res.yf, res.z_y, res.sf, res.nf, res.z_s, res.z_n]),
               length=L)
    return {
        "td": tuple(td[i] for i in range(6)),
        "masks_z": res.masks_z,
        "mask_w": res.mask_w,
        "z_y": res.z_y,
    }


def tango_clip_fused(
    y,
    s,
    n,
    masks_z=None,
    mask_w=None,
    mu: float = 1.0,
    policy: str | None = "local",
    ref_mic: int = 0,
    mask_type: str = "irm1",
    oracle_step1_stats: bool = False,
    solver: str = "fused",
    cov_impl: str = "auto",
    stft_impl: str = "auto",
    precision: str = "f32",
    export: bool = False,
):
    """The whole offline clip — STFT, masks, both MWF steps, ISTFT — as ONE
    jitted program: one dispatch (one fenced ~80 ms RPC on the tunneled
    attachment) per clip, with no inter-stage HBM round-trip beyond the
    declared program I/O.

    Args:
      y, s, n: (K, C, L) float time-domain mixture / speech / noise node
        signals (the processed dataset layout of the staged driver).
      masks_z, mask_w: optional (K, F, T) step-1 / step-2 masks as traced
        program inputs (the CRNN path); ``None`` (default) computes oracle
        masks of ``mask_type`` *inside* the program from the fused STFT's
        magnitudes, and ``mask_w=None`` reuses ``masks_z`` exactly as the
        staged oracle driver does.
      solver: rank-1 GEVD-MWF solver spec (``beam.filters.rank1_gevd``).
        Defaults to ``'fused'`` — the chained program exists to compose
        with the batch-in-lanes fused solve; any spec in the grammar is
        accepted (the 'eigh' chain is the meter baseline).
      cov_impl / stft_impl / precision: the shared ops.resolve seams,
        routed to every stage exactly as the staged path routes them.
      export: ``False`` (default, the deployment program) returns only the
        (K, L) enhanced time-domain signal; ``True`` returns the scoring
        payload — ``td`` (the 6-tuple of (K, L) ISTFTs: yf, z_y, sf, nf,
        z_s, z_n), ``masks_z``/``mask_w`` and the complex ``z_y`` export —
        matching ``driver._persist_and_score``'s contract.

    Reference counterpart: the full per-clip flow of
    ``offline_tango``/``main`` (tango.py:460-641), collapsed from staged
    Python phases into one traced program (module docstring).
    """
    kw = {} if (isinstance(mu, float) and mu == 1.0) else {"mu": mu}
    return _tango_clip_fused_jit(
        y, s, n, masks_z, mask_w, policy=policy, ref_mic=ref_mic,
        mask_type=mask_type, oracle_step1_stats=oracle_step1_stats,
        solver=solver, cov_impl=cov_impl, stft_impl=stft_impl,
        precision=resolve_precision(precision), export=export, **kw,
    )


tango_clip_fused.jitted = _tango_clip_fused_jit.jitted
tango_clip_fused.lower = _tango_clip_fused_jit.lower
tango_clip_fused.clear_cache = _tango_clip_fused_jit.clear_cache
tango_clip_fused.__wrapped__ = _tango_clip_fused_jit.__wrapped__


@partial(counted_jit, label="streaming_clip_fused",
         static_argnames=("update_every", "ref_mic", "mask_type", "policy",
                          "solver", "blocks_per_dispatch", "stft_impl",
                          "precision"))
def _streaming_clip_fused_jit(
    y,
    s=None,
    n=None,
    masks_z=None,
    mask_w=None,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    mask_type: str = "irm1",
    policy: str | None = "local",
    state=None,
    solver: str = "eigh",
    z_avail=None,
    blocks_per_dispatch: int = 1,
    stft_impl: str = "auto",
    precision: str = "f32",
):
    """The jitted :func:`streaming_clip_fused` (the public wrapper adds the
    host-side chaos seam and the traced-float convention)."""
    precision = check_canonical_precision(precision)
    L = y.shape[-1]
    if masks_z is None:
        if s is None or n is None:
            raise ValueError(
                "streaming_clip_fused: either pass masks_z explicitly or "
                "provide s and n for in-program oracle masks"
            )
        spec, mag = stft_with_mag(jnp.stack([y, s, n]), impl=stft_impl,
                                  precision=precision)
        Y = spec[0]
        masks_z = _clip_oracle_masks(spec, mag, mask_type, ref_mic)
    else:
        Y = stft_with_mag(y, impl=stft_impl, precision=precision)[0]
    if mask_w is None:
        mask_w = masks_z
    # The scan machinery of streaming_tango_scan, inlined into THIS trace
    # (__wrapped__ is the raw function): the per-block state transition is
    # the shared _streaming_tango_body, so the spectral pipeline inside
    # this program is the per-block streaming program by construction.
    out = streaming_tango_scan.__wrapped__(
        Y, masks_z, mask_w, lambda_cor=lambda_cor, update_every=update_every,
        mu=mu, ref_mic=ref_mic, policy=policy, state=state, solver=solver,
        z_avail=z_avail, blocks_per_dispatch=blocks_per_dispatch,
        precision=precision,
    )
    return {"yf": istft(out["yf"], length=L), "state": out["state"]}


def streaming_clip_fused(
    y,
    s=None,
    n=None,
    masks_z=None,
    mask_w=None,
    lambda_cor: float = DEFAULT_LAMBDA_COR,
    update_every: int = DEFAULT_UPDATE_EVERY,
    mu: float = DEFAULT_MU,
    ref_mic: int = 0,
    mask_type: str = "irm1",
    policy: str | None = "local",
    state=None,
    solver: str = "eigh",
    z_avail=None,
    blocks_per_dispatch: int = 1,
    stft_impl: str = "auto",
    precision: str = "f32",
):
    """One streaming super-tick — window STFT, masks, the scanned N-block
    two-step streaming pipeline, ISTFT — as ONE jitted program: the
    time-domain window goes in, the enhanced time-domain window and the
    continuation ``state`` come out, and nothing else crosses the program
    boundary.

    Built on the same ``_streaming_tango_body`` factoring as
    ``streaming_tango``/``streaming_tango_scan``: the scan body inside
    this program IS the per-block streaming program (the load-bearing
    bit-exactness contract of the scanned driver — see
    ``streaming_tango_scan``'s docstring), so the spectral pipeline
    matches the staged streaming path exactly.  The *window* STFT is where
    the twin differs: each super-tick window is transformed with its own
    centered reflect padding, so the first/last frames of a window differ
    from a full-clip STFT's — the documented chained-vs-staged boundary
    tolerance (module docstring).

    Args:
      y: (K, C, Lw) time-domain window with ``1 + Lw // hop`` STFT frames
        splitting into ``blocks_per_dispatch`` refresh-aligned blocks
        (``streaming_tango_scan``'s frame contract; e.g. Lw = 1792 gives
        T = 8 = 2 blocks x update_every 4 at the defaults).
      s, n: optional (K, C, Lw) clean components for in-program oracle
        masks of ``mask_type``; alternatively pass ``masks_z`` (and
        optionally ``mask_w``) explicitly as (K, F, T) program inputs.
      state: optional continuation carry from the previous super-tick's
        returned ``state`` (same pytree as ``streaming_tango``); the
        ``between_blocks`` chaos seam fires on continuation entry exactly
        like the staged wrappers.
      solver / precision: the shared dispatch seams — a ``'fused*'`` spec
        runs every refresh GEVD batch through the fused solve.
      z_avail: optional (K,) or (K, n_refresh) float availability of the
        exchanged z streams, routed to the scan's fault path unchanged
        (``streaming_tango_scan``) — the serve scheduler's per-session
        fault plans reach the chained lane through this.

    Returns:
      dict with ``yf`` (K, Lw) enhanced time-domain window and ``state``
      for the next super-tick.

    No direct reference counterpart: the reference never wires its online
    estimator into any driver (see ``streaming_tango_scan``), and
    dispatch-RPC amortization is a tunneled-TPU concern.
    """
    _chaos_between_blocks(state)
    return _streaming_clip_fused_jit(
        y, s, n, masks_z, mask_w, update_every=update_every, ref_mic=ref_mic,
        mask_type=mask_type, policy=policy, state=state, solver=solver,
        z_avail=z_avail, blocks_per_dispatch=blocks_per_dispatch,
        stft_impl=stft_impl, precision=resolve_precision(precision),
        **_float_kw(lambda_cor, mu),
    )


streaming_clip_fused.jitted = _streaming_clip_fused_jit.jitted
streaming_clip_fused.lower = _streaming_clip_fused_jit.lower
streaming_clip_fused.clear_cache = _streaming_clip_fused_jit.clear_cache
streaming_clip_fused.__wrapped__ = _streaming_clip_fused_jit.__wrapped__
