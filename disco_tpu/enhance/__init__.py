from disco_tpu.enhance.inference import (
    crnn_mask,
    get_frames_to_pad,
    get_z_for_mask,
    normalization,
    pcen,
    plot_conf,
    prepare_data,
    reshape_mask,
    vad_mask,
)
from disco_tpu.enhance.tango import (
    TangoResult,
    finite_z_guard,
    oracle_masks,
    others_index,
    tango,
    tango_step1,
    tango_step2,
)
from disco_tpu.enhance.fused import streaming_clip_fused, tango_clip_fused
from disco_tpu.enhance.separation import separate_sources, separate_with_masks
from disco_tpu.enhance.streaming import (hold_last_good, initial_stream_state,
                                          streaming_step1, streaming_tango,
                                          streaming_tango_scan)
from disco_tpu.enhance.zexport import compute_z_signals, export_z

__all__ = [
    "TangoResult",
    "finite_z_guard",
    "hold_last_good",
    "oracle_masks",
    "tango",
    "tango_step1",
    "tango_step2",
    "others_index",
    "crnn_mask",
    "get_frames_to_pad",
    "get_z_for_mask",
    "normalization",
    "pcen",
    "plot_conf",
    "prepare_data",
    "reshape_mask",
    "vad_mask",
    "compute_z_signals",
    "export_z",
    "initial_stream_state",
    "streaming_clip_fused",
    "streaming_step1",
    "streaming_tango",
    "streaming_tango_scan",
    "tango_clip_fused",
    "separate_sources",
    "separate_with_masks",
]
