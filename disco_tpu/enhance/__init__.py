from disco_tpu.enhance.tango import (
    TangoResult,
    oracle_masks,
    tango,
    tango_step1,
    tango_step2,
    others_index,
)

__all__ = [
    "TangoResult",
    "oracle_masks",
    "tango",
    "tango_step1",
    "tango_step2",
    "others_index",
]
