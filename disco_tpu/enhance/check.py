"""``make perf-check`` — the corpus-throughput-engine gate.

Runs the chaos-check miniature corpus through BOTH corpus drivers and
asserts the acceptance contract of the overlapped
prefetch/dispatch/readback engine (``disco_tpu.enhance.pipeline``):

1. **Byte-identical artifacts**: the pipelined engine's artifact tree is
   byte-for-byte the sequential path's tree — overlap changes scheduling,
   never math (the engine's ISTFTs run with the sequential path's exact
   shapes and the batched readback is a lossless transfer).
2. **Ledger equivalence**: a pipelined run with a ledger replays to the
   same per-unit end states (every unit ``done``) with the same artifact
   digests as the byte-identical tree implies.
3. **One batched readback per chunk**: the ``device_get_batches`` /
   ``chunk_readbacks`` accounting counters advance once per chunk —
   K×n_real per-clip readbacks are gone — and the overlap gauges
   (``prefetch_stall_ms`` et al.) are recorded.
4. **Bench contract**: ``bench.py`` still prints exactly ONE JSON line on
   stdout, carrying the ``corpus_clips_per_s`` corpus-mode metric plus —
   since the hot-path fusion round — the ``stft_impl``/``precision``
   active-kernel fields and the bf16 error-reporting lane (the fields
   ``disco-obs compare`` gates on).
5. **Fused-path parity**: the DEFAULT hot-path kernels (the folded
   covariance einsum, the fused spec+magnitude STFT, and their pallas
   twins in interpret mode) are asserted against the UNFUSED reference
   formulations (``beam.covariance.masked_covariances``, ``dsp.stft`` +
   ``abs``) at the committed tolerances on every CI run — the default
   path can never silently drift from the materializing math it replaced.

Runs on the CPU backend; wired into ``make test`` alongside ``obs-check``,
``fault-check`` and ``chaos-check``.

No reference counterpart: this is the corpus-engine CI gate (``make
perf-check``); the reference repo has no CI tooling at all.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path


def _enhance(corpus, out_root, **kw):
    from disco_tpu.enhance.driver import enhance_rirs_batched
    from disco_tpu.runs.check import NOISE, RIRS, SNR_RANGE
    from disco_tpu.runs.check import C as MINI_C
    from disco_tpu.runs.check import K as MINI_K

    return enhance_rirs_batched(
        str(corpus), "living", list(RIRS), NOISE, snr_range=SNR_RANGE,
        out_root=str(out_root), save_fig=False, bucket=8192, max_batch=2,
        n_nodes=MINI_K, mics_per_node=MINI_C, score_workers=2, **kw,
    )


def _check_bench_one_line(failures: list) -> dict | None:
    """Run bench.py at smoke size and assert the ONE-JSON-line stdout
    contract with the new corpus fields present."""
    root = Path(__file__).resolve().parents[2]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "BENCH_BATCH": "2",
        "BENCH_DUR_S": "0.5",
        "BENCH_ITERS": "2",
        "BENCH_CORPUS_CLIPS": "2",
        # pinned (not inherited): an exported =0 would null the scan lane
        # this gate asserts, and a large N cannot fit the 0.5 s smoke clip
        "BENCH_BLOCKS_PER_DISPATCH": "4",
        "BENCH_SERVE_SESSIONS": "2",
        "BENCH_SERVE_DUR_S": "1.0",
        # flywheel lanes at smoke size: the gate asserts presence, the
        # numbers only need to be measured, not representative
        "BENCH_TRAIN_STEPS": "2",
        "BENCH_TRAIN_BATCH": "2",
        "BENCH_TAP_BLOCKS": "8",
        # scenario-factory lane at smoke size (low ISM order + short dry
        # clips: the gate asserts the field and the one-dispatch-per-batch
        # contract, not TPU-representative throughput)
        "BENCH_SCENE_BATCHES": "2",
        "BENCH_SCENE_B": "4",
        "BENCH_SCENE_DUR_S": "0.5",
        "BENCH_SCENE_ORDER": "2",
        # pinned: an exported =0 would null the promotion lane this gate
        # asserts (the lane's one rollout IS its smoke size)
        "BENCH_PROMOTE": "1",
        "BENCH_NP_DUR_S": "0",  # skip the minutes-long float64 baseline
        # 900 s starved the smoke bench on a 1-core host: bench_jax alone
        # measured 644 s there, and this gate's own compile-cache=off
        # (inherited by the subprocess) makes the full run land past 900.
        # Host speed must not decide the gate — the in-bench watchdog
        # still catches a genuine wedge, just with 1-core headroom.
        "BENCH_WATCHDOG_S": "1800",
    }
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=root, env=env,
        capture_output=True, text=True, timeout=1800,
    )
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    if proc.returncode != 0:
        failures.append(
            f"bench: exited {proc.returncode}; stdout={proc.stdout[-300:]!r} "
            f"stderr={proc.stderr[-300:]!r}"
        )
        return None
    if len(lines) != 1:
        failures.append(f"bench: stdout must be exactly ONE JSON line, got {len(lines)}")
        return None
    try:
        rec = json.loads(lines[0])
    except json.JSONDecodeError as e:
        failures.append(f"bench: stdout line is not JSON: {e}")
        return None
    if not isinstance(rec.get("corpus_clips_per_s"), (int, float)):
        failures.append(
            f"bench: corpus_clips_per_s missing/null in the record "
            f"(corpus_error={rec.get('corpus_error')!r})"
        )
    if not isinstance((rec.get("corpus_pipeline") or {}).get("prefetch_stall_ms"),
                      (int, float)):
        failures.append("bench: corpus_pipeline.prefetch_stall_ms missing/null")
    for key in ("serve_blocks_per_s", "serve_p95_ms"):
        if not isinstance(rec.get(key), (int, float)):
            failures.append(
                f"bench: {key} missing/null in the record "
                f"(serve_error={rec.get('serve_error')!r})"
            )
    for key in ("streaming_rtf_scan", "streaming_rtf_block", "dispatches_per_block"):
        if not isinstance(rec.get(key), (int, float)):
            failures.append(
                f"bench: {key} missing/null in the record "
                f"(streaming_scan_error={rec.get('streaming_scan_error')!r})"
            )
    for key, err_key in (("train_steps_per_s", "train_error"),
                         ("tap_blocks_per_s", "tap_error"),
                         # the scenario-factory lane: one compiled program
                         # + one batched readback per scene batch
                         ("scenes_per_s", "scene_error"),
                         # the live-flywheel lane: complete tap->train->
                         # publish->promote generations must close on a
                         # loopback server and be measured
                         ("tap_to_promotion_ms", "promote_error"),
                         ("flywheel_generations", "promote_error"),
                         ("model_promotions", "promote_error")):
        if not isinstance(rec.get(key), (int, float)):
            failures.append(
                f"bench: {key} missing/null in the record "
                f"({err_key}={rec.get(err_key)!r})"
            )
    # the causal-tracing lane: the field must be measured, and the
    # DISABLED seam must be a measured no-op (strict-no-op contract of
    # obs.trace — a sub-microsecond attribute check; 2 µs leaves CI-load
    # headroom without admitting real work on the hot path)
    if not isinstance(rec.get("span_overhead_ns"), (int, float)):
        failures.append(
            f"bench: span_overhead_ns missing/null in the record "
            f"(span_error={rec.get('span_error')!r})"
        )
    else:
        disabled_ns = (rec.get("span_stats") or {}).get("disabled_ns")
        if not isinstance(disabled_ns, (int, float)) or disabled_ns > 2000.0:
            failures.append(
                f"bench: tracing-disabled span seam cost {disabled_ns!r} ns "
                "— the strict-no-op contract is broken (must be ~0)"
            )
    for key, allowed in (("stft_impl", ("xla", "pallas")),
                         ("precision", ("f32", "bf16"))):
        if rec.get(key) not in allowed:
            failures.append(f"bench: {key} missing/invalid in the record: "
                            f"{rec.get(key)!r} (expected one of {allowed})")
    if not isinstance(rec.get("bf16_max_rel_err"), (int, float)):
        failures.append(
            f"bench: bf16_max_rel_err missing/null in the record "
            f"(bf16_error={rec.get('bf16_error')!r})"
        )
    # the fused-solve lane (solve-fusion round): measured, and its resolved
    # provenance recorded so records distinguish jacobi XLA from pallas
    # from the fused kernel without re-running
    if not isinstance(rec.get("rtf_fused_solver"), (int, float)):
        failures.append(
            f"bench: rtf_fused_solver missing/null in the record "
            f"(fused_error={rec.get('fused_error')!r})"
        )
    # the disco-chain lanes: the whole-clip chained program and the fused
    # step-1 stage lane must both be measured, with their stage_ms rows
    # present (the error fields say WHY when they are not)
    for key, err_key in (("rtf_chained_clip", "chained_clip_error"),
                         ("rtf_fused_step1", "fused_step1_error")):
        if not isinstance(rec.get(key), (int, float)):
            failures.append(
                f"bench: {key} missing/null in the record "
                f"({err_key}={rec.get(err_key)!r})"
            )
    for key in ("chained_clip", "step1_fused_mwf"):
        if not isinstance((rec.get("stage_ms") or {}).get(key), (int, float)):
            failures.append(f"bench: stage_ms.{key} missing/null in the record")
    lanes = rec.get("solver_lanes") or {}
    for lane_key in ("rtf", "rtf_eigh_solver", "rtf_jacobi_solver",
                     "rtf_fused_solver", "rtf_fused_step1",
                     "rtf_chained_clip"):
        lane = lanes.get(lane_key) or {}
        if lane.get("impl") not in ("xla", "pallas"):
            failures.append(
                f"bench: solver_lanes[{lane_key!r}].impl missing/invalid: "
                f"{lane.get('impl')!r} (expected 'xla' or 'pallas')"
            )
    # the roofline join (meter round): every timed stage must carry its
    # modeled MFU and HBM GB/s, the lanes their attributed flops, and the
    # record the cost-model version the join was computed under — a
    # silent meter failure would strip disco-obs compare's per-stage
    # regression lanes from the NEXT baseline
    if not isinstance(rec.get("cost_model_version"), int):
        failures.append(
            f"bench: cost_model_version missing/null in the record "
            f"(meter_error={rec.get('meter_error')!r})"
        )
    for table in ("mfu_by_stage", "hbm_gbps_by_stage"):
        got = rec.get(table)
        if not isinstance(got, dict) or not got:
            failures.append(
                f"bench: {table} missing/empty in the record "
                f"(meter_error={rec.get('meter_error')!r})"
            )
        else:
            missing = sorted(set(rec.get("stage_ms") or {}) - set(got))
            if missing:
                failures.append(
                    f"bench: {table} lacks timed stage(s) {missing}")
    lane_mfu = rec.get("lane_mfu")
    if not isinstance(lane_mfu, dict) or not (
            {"streaming_scan", "serve", "fused_solver"} <= set(lane_mfu)):
        failures.append(
            f"bench: lane_mfu missing/incomplete in the record: "
            f"{lane_mfu!r} (meter_error={rec.get('meter_error')!r})"
        )
    if not isinstance(rec.get("workload"), dict):
        failures.append("bench: workload missing/null in the record")
    return rec


def _check_fused_parity(failures: list) -> None:
    """Fused-vs-unfused parity at the kernel seams (acceptance item 5):
    the DEFAULT path's folded/fused kernels against the materializing
    reference formulations they replaced, on a fixed random case."""
    import numpy as np

    from disco_tpu.beam.covariance import masked_covariances
    from disco_tpu.core.dsp import stft
    from disco_tpu.ops.cov_ops import masked_cov_pallas, masked_covariances_folded
    from disco_tpu.ops.stft_ops import stft_with_mag

    rng = np.random.default_rng(42)
    x = rng.standard_normal((2, 3, 12000)).astype("float32")
    spec_ref = np.asarray(stft(x))
    mag_ref = np.abs(spec_ref)
    scale = np.max(mag_ref)
    for impl in ("xla", "pallas"):
        spec, mag = stft_with_mag(x, impl=impl, interpret=True)
        # disco-lint: disable=DL002 -- hermetic CPU gate: interpret-mode/CPU arrays, no tunnel crossing to batch
        spec, mag = np.asarray(spec), np.asarray(mag)
        err_s = np.max(np.abs(spec - spec_ref)) / scale
        err_m = np.max(np.abs(mag - mag_ref)) / scale
        if err_s > 1e-5 or err_m > 1e-5:
            failures.append(
                f"fused parity: stft_with_mag[{impl}] drifted from "
                f"dsp.stft+abs (spec {err_s:.2e}, mag {err_m:.2e} > 1e-5)"
            )

    C, F, T = 4, 33, 50
    y = (rng.standard_normal((C, F, T)) + 1j * rng.standard_normal((C, F, T))
         ).astype(np.complex64)
    m = rng.random((F, T)).astype(np.float32)
    Rss_ref_d, Rnn_ref_d = masked_covariances(y, m)
    Rss_ref, Rnn_ref = np.asarray(Rss_ref_d), np.asarray(Rnn_ref_d)
    scale_r = max(np.max(np.abs(Rss_ref)), np.max(np.abs(Rnn_ref)))
    for name, fn in (
        ("folded-xla", lambda: masked_covariances_folded(y, m)),
        ("pallas", lambda: masked_cov_pallas(y, m, t_tile=16, f_tile=8,
                                             interpret=True)),
    ):
        Rss, Rnn = fn()
        # disco-lint: disable=DL002 -- hermetic CPU gate: interpret-mode/CPU arrays, no tunnel crossing to batch
        Rss, Rnn = np.asarray(Rss), np.asarray(Rnn)
        err = max(np.max(np.abs(Rss - Rss_ref)),
                  np.max(np.abs(Rnn - Rnn_ref))) / scale_r
        if err > 1e-4:
            failures.append(
                f"fused parity: masked covariance [{name}] drifted from the "
                f"materializing einsum ({err:.2e} > 1e-4 max rel)"
            )

    # fused rank-1 GEVD-MWF solve (ops/mwf_ops.py, the solve-fusion round):
    # both lanes (XLA twin + pallas kernel in interpret mode) against the
    # separate-stage eigensolve path they replace, through THE dispatch
    # table — the solver specs are the sanctioned selection seam (DL016)
    from disco_tpu.beam.filters import rank1_gevd

    Rnn_pd = Rnn_ref + 0.05 * scale_r * np.eye(C, dtype=np.complex64)
    w_ref, t1_ref = rank1_gevd(Rss_ref, Rnn_pd, solver="eigh")
    w_ref, t1_ref = np.asarray(w_ref), np.asarray(t1_ref)
    wscale = np.linalg.norm(w_ref)
    for spec in ("fused-xla", "fused-pallas"):
        w, t1 = rank1_gevd(Rss_ref, Rnn_pd, solver=spec)
        # disco-lint: disable=DL002 -- hermetic CPU gate: interpret-mode/CPU arrays, no tunnel crossing to batch
        w, t1 = np.asarray(w), np.asarray(t1)
        err = max(np.linalg.norm(w - w_ref), np.linalg.norm(t1 - t1_ref)) / wscale
        if err > 1e-3:
            failures.append(
                f"fused parity: rank1_gevd[{spec}] drifted from the eigh "
                f"solve ({err:.2e} > 1e-3 rel l2)"
            )

    # step-1 batch-in-lanes fused solve (the disco-chain round): BOTH fused
    # lanes through compute_z_signals' solver spec — all K×F pencils ride
    # ONE rank1_gevd call through THE dispatch table — against the
    # reference-bit-matching eigh step-1 path
    from disco_tpu.enhance.zexport import compute_z_signals

    Ks, Cs, L1 = 2, 3, 12000
    y1 = rng.standard_normal((Ks, Cs, L1)).astype(np.float32)
    s1 = rng.standard_normal((Ks, Cs, L1)).astype(np.float32)
    n1 = rng.standard_normal((Ks, Cs, L1)).astype(np.float32)
    z_ref = np.asarray(compute_z_signals(y1, s1, n1, solver="eigh")["z_y"])
    zscale = np.max(np.abs(z_ref))
    for spec in ("fused-xla", "fused-pallas"):
        # disco-lint: disable=DL002 -- hermetic CPU gate: interpret-mode/CPU arrays, no tunnel crossing to batch
        z = np.asarray(compute_z_signals(y1, s1, n1, solver=spec)["z_y"])
        err = np.max(np.abs(z - z_ref)) / zscale
        if err > 1e-3:
            failures.append(
                f"fused parity: compute_z_signals[{spec}] step-1 z_y drifted "
                f"from the eigh solve ({err:.2e} > 1e-3 max rel)"
            )


def main(argv=None) -> int:
    # Hermetic gate: no persistent compile-cache writes under ~/.cache from
    # CI (the bench subprocess inherits this too); an explicit env value
    # still wins.
    """Run the corpus-throughput gate (``make perf-check``); exit 1 on failure."""
    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    from disco_tpu import obs
    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.runs import RunLedger
    from disco_tpu.runs.check import _mini_corpus, _trees_identical

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        corpus = _mini_corpus(tmp / "dataset")
        obs_log = tmp / "perf_check.jsonl"
        with obs.recording(obs_log):
            obs.write_manifest(tool="perf-check")

            # -- sequential reference (the --no-pipeline escape hatch) ------
            seq = tmp / "sequential"
            res_seq = _enhance(corpus, seq, pipeline=False)

            # -- pipelined engine, with a ledger ----------------------------
            pipe, led = tmp / "pipelined", tmp / "ledger.jsonl"
            gets0 = device_get_count()
            res_pipe = _enhance(corpus, pipe, pipeline=True, ledger=str(led))
            n_chunks = device_get_count() - gets0

            if set(res_seq) != set(res_pipe):
                failures.append(
                    f"result keys differ: sequential={sorted(res_seq)} "
                    f"pipelined={sorted(res_pipe)}"
                )
            _trees_identical(seq, pipe, failures, "pipelined parity")

            # 2 clips at max_batch=2 = exactly one chunk → one batched get
            if n_chunks != 1:
                failures.append(
                    f"expected ONE batched device_get for the single chunk, "
                    f"counted {n_chunks}"
                )
            gauges = obs.REGISTRY.snapshot()["gauges"]
            for g in ("prefetch_stall_ms", "readback_ms", "overlap_efficiency"):
                if gauges.get(g) is None:
                    failures.append(f"overlap gauge {g!r} was not recorded")

            # every unit done in the ledger (and verified against digests)
            done, requeued = RunLedger(led).verified_done(requeue=False)
            if len(done) != len(res_pipe) or requeued:
                failures.append(
                    f"ledger not clean after pipelined run: done={sorted(done)} "
                    f"requeued={requeued}"
                )
            obs.record("counters", **obs.REGISTRY.snapshot())
        events = obs.read_events(obs_log)  # schema-validating read
        if not any(e["kind"] == "stage_end" and e["stage"] == "chunk_pipeline"
                   for e in events):
            failures.append("event log missing the chunk_pipeline stage event")

    _check_fused_parity(failures)
    bench_rec = _check_bench_one_line(failures)

    if failures:
        for f in failures:
            print(f"perf-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "perf_check": "ok",
        "corpus_clips_per_s": bench_rec.get("corpus_clips_per_s"),
        "prefetch_stall_ms": bench_rec.get("corpus_pipeline", {}).get("prefetch_stall_ms"),
        "readback_ms": bench_rec.get("corpus_pipeline", {}).get("readback_ms"),
        "overlap_efficiency": bench_rec.get("corpus_pipeline", {}).get("overlap_efficiency"),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
