"""``make stream-check`` — the device-resident super-tick gate.

Hermetic (CPU backend, compile cache off, one JAX process, no sockets, no
SIGKILLs) check of the scanned multi-block streaming driver
(:func:`disco_tpu.enhance.streaming.streaming_tango_scan`) against its
acceptance contract:

1. **Scan parity**: a stream driven through scanned super-ticks
   (``blocks_per_dispatch`` = N refresh-aligned blocks per dispatch) is
   **bit-identical** to the per-block host loop — fault-free AND under a
   ``z_avail`` plan whose losses span super-tick edges (the hold carries
   ride the scan carry), including the continuation state, a ``state=``
   handoff mid-stream, and a non-multiple-of-N tail served by the
   per-block fallback.
2. **Readback-count invariant**: over a serve scheduler run with
   ``blocks_per_super_tick=N``, the batched-readback accounting
   (``device_get_batches``) advances once per super-tick — fenced
   dispatches per delivered block ≤ 1/N plus the per-block ragged tail —
   and every delivered block is byte-identical to the per-block scheduler
   path.

Wired into ``make test`` alongside ``obs-check``/``fault-check``/
``chaos-check``/``perf-check``/``serve-check``.

No reference counterpart: the reference has no streaming deployment to
gate.
"""
# disco-lint: file-disable=DL002 -- the per-block host loop IS this gate's oracle: per-item readbacks on hermetic CPU are the reference semantics the scan must match, not a tunnel cost
from __future__ import annotations

import json
import sys

K, C, U = 4, 2, 4
BLOCK = 2 * U       # serve-style block_frames
N_SUPER = 4         # blocks per scanned dispatch


def _scene(seed=7, L=30000):
    import numpy as np

    from disco_tpu.core.dsp import stft

    rng = np.random.default_rng(seed)
    Y = np.asarray(stft(rng.standard_normal((K, C, L)).astype(np.float32)))
    F, T = Y.shape[-2:]
    m = rng.uniform(0.05, 0.95, size=(K, F, T)).astype(np.float32)
    return Y, m


def per_block_reference(Y, m, *, block, update_every, state, plan=None):
    """The per-block host loop every scan-parity gate compares against
    (the serve dispatch shape): explicit ``state=`` continuation,
    per-block ``z_avail`` availability columns.  THE bit-exactness oracle —
    tests/test_streaming.py imports it rather than re-implementing it, so
    the per-block calling convention is pinned in exactly one place.

    No reference counterpart: the reference has no streaming driver to
    chunk (see the disco_tpu.enhance.streaming module docstring); this
    loop is the port's own per-block deployment shape, restated as an
    oracle."""
    import numpy as np

    from disco_tpu.enhance.streaming import streaming_tango

    K, T = Y.shape[0], Y.shape[-1]
    per = block // update_every
    outs = []
    for i in range(T // block):
        lo, hi = i * block, (i + 1) * block
        avail = (np.ones((K, per), np.float32) if plan is None
                 else plan[:, i * per:(i + 1) * per])
        o = streaming_tango(Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi],
                            update_every=update_every, state=state,
                            z_avail=avail)
        state = o["state"]
        outs.append(np.asarray(o["yf"]))
    return np.concatenate(outs, axis=-1), state


def _per_block(Y, m, plan=None):
    from disco_tpu.enhance.streaming import initial_stream_state

    F = Y.shape[-2]
    return per_block_reference(
        Y, m, block=BLOCK, update_every=U, plan=plan,
        state=initial_stream_state(K, C, F, update_every=U),
    )


def _check_scan_parity(failures: list) -> dict:
    import numpy as np

    from disco_tpu.enhance.streaming import (
        initial_stream_state,
        streaming_tango,
        streaming_tango_scan,
    )

    Y, m = _scene()
    F, T = Y.shape[-2:]
    n_blocks = T // BLOCK
    window = N_SUPER * BLOCK
    nw = n_blocks // N_SUPER
    per = BLOCK // U
    cols = window // U

    # a fault plan with losses inside a window, across a super-tick edge,
    # and before the first delivery (zn fallback)
    plan = np.ones((K, n_blocks * per), np.float32)
    plan[1, cols - 2:cols + 3] = 0
    plan[3, 0:2] = 0
    plan[2, 5:6] = 0

    for label, p in (("fault-free", None), ("faulted", plan)):
        ref, ref_state = _per_block(Y, m, plan=p)
        state = initial_stream_state(K, C, F, update_every=U)
        outs = []
        for w in range(nw):
            lo, hi = w * window, (w + 1) * window
            avail = (np.ones((K, cols), np.float32) if p is None
                     else p[:, w * cols:(w + 1) * cols])
            o = streaming_tango_scan(
                Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi], update_every=U,
                state=state, z_avail=avail, blocks_per_dispatch=N_SUPER,
            )
            state = o["state"]
            outs.append(np.asarray(o["yf"]))
        # non-multiple-of-N tail: per-block fallback continues the state
        for i in range(nw * N_SUPER, n_blocks):
            lo, hi = i * BLOCK, (i + 1) * BLOCK
            avail = (np.ones((K, per), np.float32) if p is None
                     else p[:, i * per:(i + 1) * per])
            o = streaming_tango(Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi],
                                update_every=U, state=state, z_avail=avail)
            state = o["state"]
            outs.append(np.asarray(o["yf"]))
        got = np.concatenate(outs, axis=-1)
        if not np.array_equal(got, ref):
            failures.append(
                f"scan parity ({label}): scanned+tail output differs from the "
                f"per-block loop (max abs diff {np.abs(got - ref).max():g})"
            )
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(ref_state)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                failures.append(
                    f"scan parity ({label}): continuation state diverged"
                )
                break
    return {"blocks": n_blocks, "super_ticks": nw, "tail_blocks": n_blocks - nw * N_SUPER}


def _check_readback_invariant(failures: list) -> dict:
    """Serve scheduler with super-ticks: device_get_batches == super-ticks,
    fenced readbacks per block ≤ 1/N + the ragged/partial tail, outputs
    byte-identical to the per-block scheduler."""
    import numpy as np

    from disco_tpu.obs.accounting import device_get_count
    from disco_tpu.serve.scheduler import Scheduler
    from disco_tpu.serve.session import SessionConfig

    Y, m = _scene(seed=11)
    F, T = Y.shape[-2:]
    cfg = SessionConfig(n_nodes=K, mics_per_node=C, n_freq=F,
                        block_frames=BLOCK, update_every=U)
    n_blocks = -(-T // BLOCK)

    def run(sched):
        s = sched.open_session(cfg)
        outs = {}
        gets0 = device_get_count()
        i = 0
        while i < n_blocks:
            for _ in range(sched.blocks_per_super_tick):
                if i < n_blocks:
                    lo, hi = i * BLOCK, min((i + 1) * BLOCK, T)
                    sched.push_block(s, i, Y[..., lo:hi], m[..., lo:hi], m[..., lo:hi])
                    i += 1
            for _s, seq, yf, _lat in sched.tick():
                outs[seq] = yf
        while sched.pending_blocks():
            for _s, seq, yf, _lat in sched.tick():
                outs[seq] = yf
        if len(outs) != n_blocks:
            failures.append(f"scheduler delivered {len(outs)}/{n_blocks} blocks")
            return None, 0
        return (np.concatenate([outs[i] for i in range(n_blocks)], axis=-1),
                device_get_count() - gets0)

    ref, gets_block = run(Scheduler(max_sessions=2, max_queue_blocks=2 * N_SUPER))
    got, gets_scan = run(Scheduler(max_sessions=2, max_queue_blocks=2 * N_SUPER,
                                   blocks_per_super_tick=N_SUPER))
    if ref is None or got is None:
        return {}
    if not np.array_equal(got, ref):
        failures.append(
            "super-tick scheduler output differs from the per-block scheduler "
            f"(max abs diff {np.abs(got - ref).max():g})"
        )
    full = n_blocks - 1 if T % BLOCK else n_blocks
    expected = full // N_SUPER + (full % N_SUPER) + (1 if T % BLOCK else 0)
    if gets_scan > expected:
        failures.append(
            f"readback invariant: {gets_scan} batched readbacks for {n_blocks} "
            f"blocks at N={N_SUPER} (expected <= {expected}: one per super-tick "
            "plus the per-block tail)"
        )
    if gets_scan >= gets_block:
        failures.append(
            f"super-ticks did not reduce readbacks: {gets_scan} vs "
            f"{gets_block} per-block"
        )
    return {"blocks": n_blocks, "readbacks_per_block_path": gets_block,
            "readbacks_supertick_path": gets_scan}


def main(argv=None) -> int:
    """Run the super-tick gate (``make stream-check``); exit 1 on failure."""
    import os

    os.environ.setdefault("DISCO_TPU_COMPILE_CACHE", "off")
    failures: list[str] = []
    parity = _check_scan_parity(failures)
    readback = _check_readback_invariant(failures)
    if failures:
        for f in failures:
            print(f"stream-check FAIL: {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "stream_check": "ok",
        "blocks_per_dispatch": N_SUPER,
        **{f"parity_{k}": v for k, v in parity.items()},
        **readback,
        "jax_processes": 1,
        "sigkills_issued": 0,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
