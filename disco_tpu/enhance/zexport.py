"""Z-signal export — step 1 only, batch-producing the compressed signals
that become CRNN training inputs (reference
speech_enhancement/get_z_signals.py:213-359).

The reference re-runs tango's step 1 per node in Python loops and saves, per
node, ``zs_hat`` (the compressed mixture estimate z) and ``zn_hat``
(y_ref − z), each raw + |·| "normed", under
``stft_z/{zfile}/{raw,normed/abs}/{snrdir}/...`` — idempotently per RIR.
Here step 1 is the jitted ``vmap``ed :func:`disco_tpu.enhance.tango_step1`;
the file contract is identical.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from disco_tpu.core.dsp import stft
from disco_tpu.enhance.tango import oracle_masks, tango_step1
from disco_tpu.utils import device_get_tree
from disco_tpu.io.atomic import save_npy_atomic
from disco_tpu.io.layout import DatasetLayout, case_of_rir


def compute_z_signals(
    y, s, n, masks_z=None, mask_type: str = "irm1", mu: float = 1.0, oracle_stats: bool = False,
    Y=None, S=None, N=None, solver: str = "power", cov_impl: str = "auto",
    precision: str = "f32",
):
    """Step 1 over all nodes: (K, C, L) time signals → dict of (K, F, T)
    z streams (reference get_z_signals.py:213-317, vectorized).

    ``masks_z`` may be given explicitly (K, F, T) — e.g. CRNN-estimated —
    else oracle masks of ``mask_type`` are computed from S and N.  With
    explicit masks, ``s``/``n`` may be None (the clean-component streams
    z_s/z_n then come out zero; export_z does not save them).  Precomputed
    STFTs may be passed as ``Y``/``S``/``N`` to skip the transform.

    ``solver``/``cov_impl``/``precision`` route to the step-1 solve and
    covariance stages exactly as in :func:`disco_tpu.enhance.tango.tango`
    (defaults unchanged — 'power'/'auto'/'f32').  A ``'fused*'`` solver
    spec runs ALL K×F step-1 pencils as ONE batch-in-lanes fused solve
    (the step-1 fusion round) instead of K vmapped per-node instances;
    this is the step-1 lane ``bench.py`` times as ``rtf_fused_step1``.
    """
    from disco_tpu.enhance.tango import _step1_apply, _step1_covariances
    from disco_tpu.beam.filters import rank1_gevd
    from disco_tpu.ops.resolve import check_canonical_precision
    from disco_tpu.solver_spec import is_fused_spec

    precision = check_canonical_precision(precision)
    Y = stft(jnp.asarray(y)) if Y is None else jnp.asarray(Y)
    if S is None:
        S = stft(jnp.asarray(s)) if s is not None else jnp.zeros_like(Y)
    if N is None:
        N = stft(jnp.asarray(n)) if n is not None else jnp.zeros_like(Y)
    if masks_z is None:
        if s is None or n is None:
            raise ValueError("either pass masks_z explicitly or provide s and n for oracle masks")
        masks_z = oracle_masks(S, N, mask_type)
    if is_fused_spec(solver):
        # the K×F batch-in-lanes seam of enhance.tango.tango: one fused
        # solve over the stacked pencils, covariance/apply stages vmapped
        Rss, Rnn = jax.vmap(
            lambda yk, sk, nk, mk: _step1_covariances(
                yk, sk, nk, mk, oracle_stats, None, cov_impl, precision)
        )(Y, S, N, jnp.asarray(masks_z))
        w1, t1 = rank1_gevd(Rss, Rnn, mu=mu, solver=solver, precision=precision)
        out = jax.vmap(_step1_apply)(w1, t1, Y, S, N)
    else:
        step1 = jax.vmap(lambda yk, sk, nk, mk: tango_step1(
            yk, sk, nk, mk, mu=mu, oracle_stats=oracle_stats, solver=solver,
            cov_impl=cov_impl, precision=precision))
        out = step1(Y, S, N, jnp.asarray(masks_z))
    out["masks_z"] = masks_z
    return out


def _node_paths(layout, rir, noise_tag, snr_range, n_nodes, mics_per_node, source):
    return [
        layout.wav_processed(snr_range, source, rir, 1 + node * mics_per_node + c, noise=noise_tag)
        for node in range(n_nodes)
        for c in range(mics_per_node)
    ]


def load_node_signals(layout: DatasetLayout, rir: int, noise: str, snr_range, n_nodes: int = 4, mics_per_node: int = 4):
    """Read processed mixture/target/noise wavs into (K, C, L) arrays
    (reference get_z_signals.py:44-92).  All 3 x K x C channel files are
    decoded in ONE threaded native batch (``disco_tpu.io.fastwav``) — the
    per-RIR ingest that otherwise bounds corpus wall-clock at >1000x
    real-time enhancement rates."""
    from disco_tpu.io.fastwav import read_wavs_batch

    # targets are saved without a noise tag; mixture/noise carry it
    # (postgen.save_data, reference post_generator.py:133-150)
    paths = (
        _node_paths(layout, rir, noise, snr_range, n_nodes, mics_per_node, "mixture")
        + _node_paths(layout, rir, None, snr_range, n_nodes, mics_per_node, "target")
        + _node_paths(layout, rir, noise, snr_range, n_nodes, mics_per_node, "noise")
    )
    sigs, _fs = read_wavs_batch(paths)
    y, s, n = sigs.reshape(3, n_nodes, mics_per_node, -1)
    return y, s, n


def load_mixture_signals(layout: DatasetLayout, rir: int, noise: str, snr_range, n_nodes: int = 4, mics_per_node: int = 4):
    """Mixture-only variant of :func:`load_node_signals` for mask-supplied
    exports (no oracle masks needed → no target/noise reads)."""
    from disco_tpu.io.fastwav import read_wavs_batch

    paths = _node_paths(layout, rir, noise, snr_range, n_nodes, mics_per_node, "mixture")
    sigs, _fs = read_wavs_batch(paths)
    return sigs.reshape(n_nodes, mics_per_node, -1)


def export_z(
    root: str,
    scenario: str,
    rir: int,
    noise: str,
    snr_range=(0, 6),
    zfile: str = "oracle",
    mask_type: str = "irm1",
    masks_z=None,
    masks_fn=None,
    n_nodes: int = 4,
    mics_per_node: int = 4,
    force: bool = False,
) -> bool:
    """Export z's for one RIR; returns False if already done (idempotency
    guard of reference get_z_signals.py:328-331, with the reference's
    missing-'.npy' stale-check bug fixed per SURVEY.md §7).

    ``masks_fn``: optional callable (K, C, F, T) mixture STFT -> (K, F, T)
    step-1 masks (the CRNN path of reference get_z_signals.py:95-120);
    ``masks_z`` passes them precomputed.  With neither, oracle masks of
    ``mask_type`` are used.
    """
    layout = DatasetLayout(root, scenario, case_of_rir(rir))
    done_marker = layout.stft_z(zfile, snr_range, "zn_hat", rir, n_nodes, noise, normed=True)
    if done_marker.exists() and not force:
        return False

    if masks_z is None and masks_fn is None:
        y, s, n = load_node_signals(layout, rir, noise, snr_range, n_nodes, mics_per_node)
    else:  # explicit masks: the 32 target/noise wav reads are not needed
        y, s, n = load_mixture_signals(layout, rir, noise, snr_range, n_nodes, mics_per_node), None, None
    Y = None
    if masks_fn is not None and masks_z is None:
        Y = stft(jnp.asarray(y))
        masks_z = masks_fn(Y)
    out = compute_z_signals(y, s, n, masks_z=masks_z, mask_type=mask_type, Y=Y)
    # ONE batched complex-safe device_get for both exported stream stacks —
    # the same single-readback-per-batch contract as the corpus engine's
    # fetch_chunk_host (separate per-stream to_host crossings each paid a
    # full tunnel round-trip).
    zs, zn = device_get_tree((out["z_y"], out["zn"]))
    zs = np.asarray(zs).astype("complex64")  # zs_hat = compressed mixture
    zn = np.asarray(zn).astype("complex64")  # zn_hat = y_ref − z

    for k in range(n_nodes):
        for zsig, arr in (("zs_hat", zs[k]), ("zn_hat", zn[k])):
            raw = layout.stft_z(zfile, snr_range, zsig, rir, k + 1, noise, normed=False)
            save_npy_atomic(layout.ensure_dir(raw), arr)
            normed = layout.stft_z(zfile, snr_range, zsig, rir, k + 1, noise, normed=True)
            save_npy_atomic(layout.ensure_dir(normed), np.abs(arr))
    return True
