"""Inference glue: shape STFT streams into CRNN batches and back into masks
(reference speech_enhancement/utils.py:13-138, tango.py:158-249).

Host-side numpy prep (windowing, normalization) feeding ONE batched jitted
forward pass — the reference's per-window torch loop
(speech_enhancement/utils.py:118-131) becomes a single
``sliding_window_view`` + one model.apply over all windows.

PCEN is implemented natively (the reference calls librosa.pcen,
speech_enhancement/utils.py:61-64): per-channel IIR smoothing with the
standard librosa coefficient mapping from ``time_constant``, then the
(E/(eps+M)^gain + bias)^power − bias^power compression.
"""
from __future__ import annotations

import functools

import numpy as np
import scipy.signal

import jax
import jax.numpy as jnp

from disco_tpu.core.masks import vad_oracle_batch
from disco_tpu.utils import to_host

STFT_MIN, STFT_MAX = 1e-6, 1e3  # utils.py:7
FS = 16000
N_FFT = 512
N_HOP = 256
FRAMES_LOST = 6  # utils.py:10 — conv-cropped frames of the canonical CRNN


def get_frames_to_pad(in_len: int, output_frames: str, out_len: int | None = None) -> tuple[int, int]:
    """(left, right) zero-frames so the selected output frame lines up with
    the first input frame (reference utils.py:13-33)."""
    out_len = in_len if out_len is None else out_len
    if output_frames == "mid":
        return int(np.floor(in_len / 2)), int(np.floor(in_len / 2))
    if output_frames == "last":
        selected = (in_len + out_len) // 2
        return selected - 1, in_len - selected
    if output_frames == "all":
        return 0, 0
    raise ValueError("output_frames should be 'mid', 'last' or 'all'")


def pcen(
    S,
    sr: int = FS,
    hop_length: int = N_HOP,
    gain: float = 0.98,
    bias: float = 2.0,
    power: float = 0.5,
    time_constant: float = 0.400,
    eps: float = 1e-6,
    axis: int = -1,
):
    """Per-channel energy normalization over the frame axis — native
    equivalent of the librosa.pcen call at reference utils.py:61-64."""
    S = np.asarray(S, dtype=np.float64)
    t_frames = time_constant * sr / float(hop_length)
    b = (np.sqrt(1 + 4 * t_frames**2) - 1) / (2 * t_frames**2)
    zi = (1 - b) * np.expand_dims(S.take(0, axis=axis), axis)
    M, _ = scipy.signal.lfilter([b], [1, b - 1], S, axis=axis, zi=zi)
    smooth = np.exp(-gain * (np.log(eps) + np.log1p(M / eps)))
    return (S * smooth + bias) ** power - bias**power


def normalization(x, norm_type: str | None = None, axis: int = 0):
    """Inference-time feature normalization (reference utils.py:36-66):
    None | 'scale_to_unit_norm' | 'scale_to_1' (q99) | 'center_and_scale'
    | 'pcen'.  Input may be complex; output is a normalized magnitude."""
    x = np.clip(np.abs(x), STFT_MIN, STFT_MAX)
    if norm_type == "pcen":
        return pcen(x * 2**31)
    if norm_type == "scale_to_unit_norm":
        x_norm = np.linalg.norm(x, axis=axis, keepdims=True)
    elif norm_type == "scale_to_1":
        x_norm = np.quantile(x, 0.99, axis=axis, keepdims=True)
    elif norm_type == "center_and_scale":
        x = x - np.mean(x, axis=axis, keepdims=True)
        x_norm = np.std(x, axis=axis, keepdims=True)
    else:
        return x
    return x / x_norm


def prepare_data(
    y_data,
    three_d_tensor: bool,
    z_data=None,
    win_len: int = 21,
    win_hop: int = 1,
    frame_to_pred: str = "last",
    norm_type: str | None = None,
    frames_lost: int = FRAMES_LOST,
):
    """(F, T) stream(s) → (n_windows, …) model input batch
    (reference utils.py:69-138): normalize, pad so the predicted frame
    covers every original frame, slide ``win_len`` windows with hop
    ``win_hop``, stack z channels on the channel axis (3-D CRNN) or the
    frequency axis (2-D RNN).  Vectorized: no Python loop over windows."""
    chans = [normalization(y_data, norm_type=norm_type, axis=1)]
    if z_data is not None:
        chans += [normalization(z, norm_type=norm_type, axis=1) for z in z_data]

    pad = get_frames_to_pad(win_len, frame_to_pred, out_len=win_len - frames_lost)
    stacked = np.stack([np.pad(c, ((0, 0), pad)) for c in chans])  # (C, F, Tp)
    # (C, F, Tp) → windows (n, C, T=win_len, F)
    wins = np.lib.stride_tricks.sliding_window_view(stacked, win_len, axis=-1)
    wins = wins[:, :, ::win_hop]  # (C, F, n, win_len)
    out = np.ascontiguousarray(np.transpose(wins, (2, 0, 3, 1)), dtype=np.float32)
    if not three_d_tensor:
        n, c, t, f = out.shape
        out = np.ascontiguousarray(np.transpose(out, (0, 2, 1, 3))).reshape(n, t, c * f)
    return out


def reshape_mask(mask_stack, output_frame: str = "last"):
    """Stacked per-window model outputs → one (F, T) mask
    (reference tango.py:228-240)."""
    if output_frame == "last":
        out = mask_stack[:, -1, :]
    elif output_frame == "mid":
        win_len = mask_stack.shape[1]
        out = mask_stack[:, int(np.floor(win_len / 2)), :]
    elif output_frame == "all":
        raise NotImplementedError("'all' inference reshaping is not implemented (as in the reference)")
    else:
        raise ValueError("output_frame should be 'last' or 'mid'")
    return np.squeeze(out).T


def get_z_for_mask(z_s, z_n, k: int, nb_nodes: int = 4, z_sigs="zs_hat"):
    """Select/reorder exchanged z streams for the NN input at node k
    (reference tango.py:158-186): a single z kind drops the local node; the
    zs&zn pair interleaves [zs_j, zn_j, …] then drops the local pair."""
    if z_sigs in ("zs_hat", "zn_hat"):
        z_in = np.asarray(z_s if z_sigs == "zs_hat" else z_n)
        keep = [j for j in range(nb_nodes) if j != k]
        return z_in[keep]
    z_s, z_n = np.asarray(z_s), np.asarray(z_n)
    inter = np.empty((2 * nb_nodes,) + z_s.shape[1:], z_s.dtype)
    inter[0::2] = z_s
    inter[1::2] = z_n
    keep = [j for j in range(2 * nb_nodes) if j not in (2 * k, 2 * k + 1)]
    return inter[keep]


def crnn_mask(
    Y,
    model,
    variables,
    z=None,
    win_len: int = 21,
    frame_to_pred: str = "last",
    norm_type: str | None = None,
    three_d_tensor: bool = True,
):
    """CRNN inference path of reference get_mask (tango.py:211-215): one
    batched jitted forward over all sliding windows → (F, T) mask.

    Args:
      Y: (F, T) complex mixture STFT at the node's reference mic.
      model / variables: flax CRNN and its params/batch_stats.
      z: optional list/array of (F, T) compressed streams from other nodes.
    """
    frames_lost = win_len - model.conv_output_hw()[0]
    x = prepare_data(
        to_host(Y),
        three_d_tensor,
        z_data=None if z is None else list(z),
        win_len=win_len,
        win_hop=1,
        frame_to_pred=frame_to_pred,
        norm_type=norm_type,
        frames_lost=frames_lost,
    )
    m_stack = _jitted_apply(model)(variables, jnp.asarray(x))
    return reshape_mask(np.asarray(m_stack), frame_to_pred)


def crnn_masks_batched(
    Ys,
    model,
    variables,
    zs=None,
    win_len: int = 21,
    frame_to_pred: str = "last",
    norm_type: str | None = None,
    three_d_tensor: bool = True,
    max_windows_per_call: int = 16384,
):
    """Masks for MANY streams in few large device forwards.

    The per-node Python loop the round-1 driver used (K sequential
    ``crnn_mask`` calls with host round-trips, VERDICT weak #4) becomes:
    host-side window prep per stream (cheap numpy), the streams' windows
    concatenated and pushed through ``model.apply`` in slices of at most
    ``max_windows_per_call`` (whole streams per slice, so peak host/device
    memory stays bounded at corpus batch sizes — 16 clips x 4 nodes x 10 s
    would otherwise materialize ~7 GB of windows at once), then a
    per-stream reshape.  Streams must share (F, T) — guaranteed within a
    clip and within a length bucket of the corpus driver.

    Args:
      Ys: (B, F, T) complex mixture STFTs (B = nodes, or clips x nodes).
      zs: optional (B, n_z, F, T) exchanged streams per entry.

    Returns:
      (B, F, T) float masks.
    """
    frames_lost = win_len - model.conv_output_hw()[0]

    def prep(i):
        return prepare_data(
            to_host(Ys[i]),
            three_d_tensor,
            z_data=None if zs is None else list(to_host(zs[i])),
            win_len=win_len,
            win_hop=1,
            frame_to_pred=frame_to_pred,
            norm_type=norm_type,
            frames_lost=frames_lost,
        )

    B = len(Ys)
    x0 = prep(0)
    n_win = x0.shape[0]
    streams_per_call = max(1, max_windows_per_call // n_win)
    apply_fn = _jitted_apply(model)
    masks = []
    for lo in range(0, B, streams_per_call):
        xs = [x0 if i == 0 else prep(i) for i in range(lo, min(lo + streams_per_call, B))]
        m_all = np.asarray(apply_fn(variables, jnp.asarray(np.concatenate(xs, 0))))
        masks += [
            reshape_mask(m_all[j * n_win : (j + 1) * n_win], frame_to_pred)
            for j in range(len(xs))
        ]
    return np.stack(masks)


@functools.lru_cache(maxsize=32)
def _jitted_apply(model):
    """One compiled forward per model instance (flax modules are hashable) —
    keeps repeated per-node/per-clip crnn_mask calls on a cached XLA
    executable instead of op-by-op dispatch."""
    return jax.jit(lambda variables, x: model.apply(variables, x, train=False))


def vad_mask(ts, n_freq: int, n_frames: int):
    """'ivad' mask: oracle VAD spread across frequencies
    (reference tango.py:216-222)."""
    vad = np.asarray(vad_oracle_batch(jnp.asarray(ts), win_len=N_FFT, win_hop=N_HOP))
    vad = vad[::N_HOP]
    m = np.zeros((n_freq, n_frames), "float32")
    m[:, : len(vad)] = np.tile(vad[: n_frames], (n_freq, 1))
    return m


def plot_conf(infos, mics_per_node=(4, 4, 4, 4), return_fig=False):
    """Room top-view plot from saved generation infos
    (reference utils.py:141-172).  Built on the object-oriented matplotlib
    API so the process-global pyplot backend is never touched."""
    from matplotlib.figure import Figure
    from matplotlib.patches import Rectangle

    f = Figure()
    ax = f.add_subplot()
    ax.add_patch(Rectangle((0, 0), infos["room"]["length"], infos["room"]["width"], fill=False, linewidth=3))
    ax.plot(infos["mics"][0, :], infos["mics"][1, :], "x")
    ax.plot(infos["sources"][:, 0], infos["sources"][:, 1], "x")
    ax.axis("equal")
    cums = np.cumsum([0] + list(mics_per_node))
    for i_n in range(len(mics_per_node)):
        ax.text(1.05 * infos["mics"][0, cums[i_n]], 1.05 * infos["mics"][1, cums[i_n]], f"Node {i_n + 1}", fontsize=10)
    for i_s in range(np.shape(infos["sources"])[0]):
        ax.text(1.05 * infos["sources"][i_s, 0], 1.05 * infos["sources"][i_s, 1], f"Source {i_s + 1}", fontsize=10)
    ax.set(xlim=(-1, infos["room"]["length"] + 1), ylim=(-1, infos["room"]["width"] + 1))
    if return_fig:
        return f
