"""Inference glue: shape STFT streams into CRNN batches and back into masks
(reference speech_enhancement/utils.py:13-138, tango.py:158-249).

Two paths replace the reference's per-window torch loop
(speech_enhancement/utils.py:118-131):

* :func:`crnn_mask` — host-side numpy prep (``sliding_window_view``) + one
  jitted forward per stream; the simple single-stream entry point.
* :func:`crnn_masks_batched` — the production path: normalization, window
  gathering and the model forwards all run ON DEVICE in one jitted program
  per batch, with the CRNN's conv stack hoisted to the full stream
  (``CRNN.__call__`` stream mode) so convs run once instead of once per
  window.  Nothing but the final masks crosses the host boundary — on the
  tunneled single-chip attachment (~45 MB/s) data movement, not compute,
  dominates mask estimation.

PCEN is implemented natively (the reference calls librosa.pcen,
speech_enhancement/utils.py:61-64): per-channel IIR smoothing with the
standard librosa coefficient mapping from ``time_constant``, then the
(E/(eps+M)^gain + bias)^power − bias^power compression.  PCEN normalization
is host-only; the batched path falls back to per-stream prep for it.
"""
from __future__ import annotations

import functools

import numpy as np
import scipy.signal

import jax
import jax.numpy as jnp

from disco_tpu.core.masks import vad_oracle_batch
from disco_tpu.utils import to_host

STFT_MIN, STFT_MAX = 1e-6, 1e3  # utils.py:7
FS = 16000
N_FFT = 512
N_HOP = 256
FRAMES_LOST = 6  # utils.py:10 — conv-cropped frames of the canonical CRNN


def get_frames_to_pad(in_len: int, output_frames: str, out_len: int | None = None) -> tuple[int, int]:
    """(left, right) zero-frames so the selected output frame lines up with
    the first input frame (reference utils.py:13-33)."""
    out_len = in_len if out_len is None else out_len
    if output_frames == "mid":
        return int(np.floor(in_len / 2)), int(np.floor(in_len / 2))
    if output_frames == "last":
        selected = (in_len + out_len) // 2
        return selected - 1, in_len - selected
    if output_frames == "all":
        return 0, 0
    raise ValueError("output_frames should be 'mid', 'last' or 'all'")


def pcen(
    S,
    sr: int = FS,
    hop_length: int = N_HOP,
    gain: float = 0.98,
    bias: float = 2.0,
    power: float = 0.5,
    time_constant: float = 0.400,
    eps: float = 1e-6,
    axis: int = -1,
):
    """Per-channel energy normalization over the frame axis — native
    equivalent of the librosa.pcen call at reference utils.py:61-64."""
    S = np.asarray(S, dtype=np.float64)
    t_frames = time_constant * sr / float(hop_length)
    b = (np.sqrt(1 + 4 * t_frames**2) - 1) / (2 * t_frames**2)
    zi = (1 - b) * np.expand_dims(S.take(0, axis=axis), axis)
    M, _ = scipy.signal.lfilter([b], [1, b - 1], S, axis=axis, zi=zi)
    smooth = np.exp(-gain * (np.log(eps) + np.log1p(M / eps)))
    return (S * smooth + bias) ** power - bias**power


def normalization(x, norm_type: str | None = None, axis: int = 0):
    """Inference-time feature normalization (reference utils.py:36-66):
    None | 'scale_to_unit_norm' | 'scale_to_1' (q99) | 'center_and_scale'
    | 'pcen'.  Input may be complex; output is a normalized magnitude."""
    x = np.clip(np.abs(x), STFT_MIN, STFT_MAX)
    if norm_type == "pcen":
        return pcen(x * 2**31)
    if norm_type == "scale_to_unit_norm":
        x_norm = np.linalg.norm(x, axis=axis, keepdims=True)
    elif norm_type == "scale_to_1":
        x_norm = np.quantile(x, 0.99, axis=axis, keepdims=True)
    elif norm_type == "center_and_scale":
        x = x - np.mean(x, axis=axis, keepdims=True)
        x_norm = np.std(x, axis=axis, keepdims=True)
    else:
        return x
    return x / x_norm


def prepare_data(
    y_data,
    three_d_tensor: bool,
    z_data=None,
    win_len: int = 21,
    win_hop: int = 1,
    frame_to_pred: str = "last",
    norm_type: str | None = None,
    frames_lost: int = FRAMES_LOST,
):
    """(F, T) stream(s) → (n_windows, …) model input batch
    (reference utils.py:69-138): normalize, pad so the predicted frame
    covers every original frame, slide ``win_len`` windows with hop
    ``win_hop``, stack z channels on the channel axis (3-D CRNN) or the
    frequency axis (2-D RNN).  Vectorized: no Python loop over windows."""
    chans = [normalization(y_data, norm_type=norm_type, axis=1)]
    if z_data is not None:
        chans += [normalization(z, norm_type=norm_type, axis=1) for z in z_data]

    pad = get_frames_to_pad(win_len, frame_to_pred, out_len=win_len - frames_lost)
    stacked = np.stack([np.pad(c, ((0, 0), pad)) for c in chans])  # (C, F, Tp)
    # (C, F, Tp) → windows (n, C, T=win_len, F)
    wins = np.lib.stride_tricks.sliding_window_view(stacked, win_len, axis=-1)
    wins = wins[:, :, ::win_hop]  # (C, F, n, win_len)
    out = np.ascontiguousarray(np.transpose(wins, (2, 0, 3, 1)), dtype=np.float32)
    if not three_d_tensor:
        n, c, t, f = out.shape
        out = np.ascontiguousarray(np.transpose(out, (0, 2, 1, 3))).reshape(n, t, c * f)
    return out


def reshape_mask(mask_stack, output_frame: str = "last"):
    """Stacked per-window model outputs → one (F, T) mask
    (reference tango.py:228-240)."""
    if output_frame == "last":
        out = mask_stack[:, -1, :]
    elif output_frame == "mid":
        win_len = mask_stack.shape[1]
        out = mask_stack[:, int(np.floor(win_len / 2)), :]
    elif output_frame == "all":
        raise NotImplementedError("'all' inference reshaping is not implemented (as in the reference)")
    else:
        raise ValueError("output_frame should be 'last' or 'mid'")
    return np.squeeze(out).T


def get_z_for_mask(z_s, z_n, k: int, nb_nodes: int = 4, z_sigs="zs_hat"):
    """Select/reorder exchanged z streams for the NN input at node k
    (reference tango.py:158-186): a single z kind drops the local node; the
    zs&zn pair interleaves [zs_j, zn_j, …] then drops the local pair."""
    if z_sigs in ("zs_hat", "zn_hat"):
        z_in = np.asarray(z_s if z_sigs == "zs_hat" else z_n)
        keep = [j for j in range(nb_nodes) if j != k]
        return z_in[keep]
    z_s, z_n = np.asarray(z_s), np.asarray(z_n)
    inter = np.empty((2 * nb_nodes,) + z_s.shape[1:], z_s.dtype)
    inter[0::2] = z_s
    inter[1::2] = z_n
    keep = [j for j in range(2 * nb_nodes) if j not in (2 * k, 2 * k + 1)]
    return inter[keep]


def crnn_mask(
    Y,
    model,
    variables,
    z=None,
    win_len: int = 21,
    frame_to_pred: str = "last",
    norm_type: str | None = None,
    three_d_tensor: bool = True,
):
    """CRNN inference path of reference get_mask (tango.py:211-215): one
    batched jitted forward over all sliding windows → (F, T) mask.

    Args:
      Y: (F, T) complex mixture STFT at the node's reference mic.
      model / variables: flax CRNN and its params/batch_stats.
      z: optional list/array of (F, T) compressed streams from other nodes.
    """
    frames_lost = win_len - model.conv_output_hw()[0]
    x = prepare_data(
        to_host(Y),
        three_d_tensor,
        z_data=None if z is None else list(z),
        win_len=win_len,
        win_hop=1,
        frame_to_pred=frame_to_pred,
        norm_type=norm_type,
        frames_lost=frames_lost,
    )
    m_stack = _jitted_apply(model)(variables, jnp.asarray(x))
    return reshape_mask(np.asarray(m_stack), frame_to_pred)


def normalization_device(x, norm_type: str | None = None, axis: int = -1):
    """Jittable mirror of :func:`normalization` over (..., F, T) arrays —
    the host version is applied per (F, T) stream with axis=1 (the time
    axis), so the device default is axis=-1 ('pcen' excluded — its IIR
    smoother runs host-side)."""
    x = jnp.clip(jnp.abs(x), STFT_MIN, STFT_MAX)
    if norm_type is None:
        return x
    if norm_type == "scale_to_unit_norm":
        return x / jnp.linalg.norm(x, axis=axis, keepdims=True)
    if norm_type == "scale_to_1":
        return x / jnp.quantile(x, 0.99, axis=axis, keepdims=True)
    if norm_type == "center_and_scale":
        x = x - jnp.mean(x, axis=axis, keepdims=True)
        return x / jnp.std(x, axis=axis, keepdims=True)
    raise ValueError(f"norm_type {norm_type!r} has no device implementation (pcen is host-only)")


def crnn_masks_batched(
    Ys,
    model,
    variables,
    zs=None,
    win_len: int = 21,
    frame_to_pred: str = "last",
    norm_type: str | None = None,
    three_d_tensor: bool = True,
):
    """Masks for MANY streams, fully device-resident — one launch.

    The per-node Python loop the round-1 driver used (K sequential
    ``crnn_mask`` calls with host round-trips, VERDICT weak #4) becomes one
    jitted program: normalization, sliding-window gathering, and the model
    forwards all run on device, with the conv stack hoisted to the full
    stream for CRNN models (see ``CRNN.__call__`` stream mode).  Nothing
    but the final (B, F, T) masks crosses the host boundary — on a
    tunneled chip (~45 MB/s) shipping prepared windows made the batched
    path *slower* than the per-clip loop; shipping nothing is ~10x better
    than shipping magnitudes.  Streams must share (F, T) — guaranteed
    within a clip and within a length bucket of the corpus driver.

    Args:
      Ys: (B, F, T) complex mixture STFTs (B = nodes, or clips x nodes) —
        device or host arrays.
      zs: optional (B, n_z, F, T) exchanged streams per entry.

    Returns:
      (B, F, T) float masks, on device (``np.asarray`` them if needed).
    """
    if frame_to_pred == "all":
        raise NotImplementedError("'all' inference reshaping is not implemented (as in the reference)")
    if norm_type == "pcen":  # host-only IIR: fall back to per-stream prep
        # ONE batched complex-safe device_get for the whole stream stack
        # (and the exchanged z's) BEFORE the per-stream loop — the loop's
        # crnn_mask(to_host(Ys[i])) calls were B separate tunnel crossings,
        # the same per-item lazy-readback anti-pattern the corpus engine's
        # fetch_chunk_host replaced in the driver.
        from disco_tpu.utils.transfer import device_get_tree

        Ys_h, zs_h = device_get_tree((Ys, zs))
        return np.stack([
            crnn_mask(Ys_h[i], model, variables,
                      z=None if zs_h is None else list(zs_h[i]),
                      win_len=win_len, frame_to_pred=frame_to_pred,
                      norm_type=norm_type, three_d_tensor=three_d_tensor)
            for i in range(len(Ys_h))
        ])
    frames_lost = win_len - model.conv_output_hw()[0]
    pad = get_frames_to_pad(win_len, frame_to_pred, out_len=win_len - frames_lost)
    B = len(Ys)
    # group streams per map step: big enough forwards to feed the MXU (a
    # lone stream's GRU steps are tiny matmuls), small enough that one
    # group's window tensor bounds memory
    group = max(1, min(B, 8))
    padded_B = -(-B // group) * group
    run = _jitted_sliding_masks(model, win_len, frame_to_pred, group,
                                tuple(pad), norm_type, padded_B - B, zs is None)
    Ys = jnp.asarray(Ys)
    return run(variables, Ys, None if zs is None else jnp.asarray(zs))[:B]


def _conv_stream_safe(model) -> bool:
    """True iff hoisting the model's conv stack to the full stream is exact:
    the time axis must see no padding, stride 1, and no pooling — then the
    full-stream conv output is the concatenation of per-window outputs.
    Non-canonical CRNN configs (time padding/stride/pooling) and conv-free
    models fall back to the per-window branch."""
    if not hasattr(model, "cnn_filters"):
        return False
    from disco_tpu.nn.bricks import _pair, broadcast_arg, spec_per_layer

    n = len(model.cnn_filters)
    pads = [_pair(p) for p in broadcast_arg(model.conv_padding, n)]
    strides = [_pair(s) for s in spec_per_layer(model.conv_strides, n)]
    pools = [_pair(k) for k in spec_per_layer(model.pool_kernels, n)]
    return all(p[0] == 0 for p in pads) and all(s[0] == 1 for s in strides) and all(
        k[0] == 1 for k in pools
    )


@functools.lru_cache(maxsize=64)
def _jitted_sliding_masks(model, win_len: int, frame_to_pred: str, group: int,
                          pad: tuple, norm_type: str | None, n_fill: int,
                          no_z: bool):
    """One compiled device-resident mask program per (model, window, group)
    configuration: normalize the complex streams, pad frames, gather
    windows, apply the model over ``group`` streams at a time, keep the
    predicted frame — all inside one jit, with ``lax.map`` over stream
    groups bounding peak memory.  ``n_fill`` duplicate streams pad B to a
    multiple of ``group`` (dropped by the caller).

    The lru_cache is load-bearing for throughput, not a micro-optimization:
    without it every call builds a fresh ``jax.jit`` wrapper, so every
    corpus batch re-traces and re-lowers the full mask program (measured on
    the round-3 hardware A/B as the batched path running 4x SLOWER than the
    per-clip loop purely on host-side tracing time — the XLA executable
    cache only saves the final compile step).  All key arguments are
    hashable: flax modules hash by structure, the rest are static config."""

    streaming = _conv_stream_safe(model)  # CRNN: convs hoisted to full stream

    @jax.jit
    def run(variables, Ys, zs):  # Ys (B, F, T) complex, zs (B, n_z, F, T)|None
        if no_z:
            chans = Ys[:, None]  # (B, 1, F, T)
        else:
            chans = jnp.concatenate([Ys[:, None], zs], axis=1)  # (B, C, F, T)
        mags = normalization_device(chans, norm_type, axis=-1)
        mags = jnp.pad(mags, ((0, 0), (0, 0), (0, 0), pad)).astype(jnp.float32)
        if n_fill:
            mags = jnp.concatenate([mags, jnp.repeat(mags[-1:], n_fill, axis=0)])
        Bt, C, F, Tp = mags.shape
        T = Tp - win_len + 1

        def one(mag_g):  # (group, C, F, Tp)
            if streaming:
                # convs once over the full streams, GRU/FF per gathered
                # post-conv window (exact — the conv stack has no time
                # padding; see CRNN.__call__ stream mode)
                out = model.apply(variables, mag_g, train=False, stream=True)
                # (G, T, win_out, F)
                sel = out.shape[2] - 1 if frame_to_pred == "last" else out.shape[2] // 2
                return jnp.transpose(out[:, :, sel, :], (0, 2, 1))  # (G, F, T)
            idx = jnp.arange(T)[:, None] + jnp.arange(win_len)[None, :]
            wins = mag_g[:, :, :, idx]  # (G, C, F, T, win)
            x = jnp.transpose(wins, (0, 3, 1, 4, 2)).reshape(group * T, C, win_len, F)
            out = model.apply(variables, x, train=False)  # (G*T, win_out, F)
            sel = out.shape[1] - 1 if frame_to_pred == "last" else out.shape[1] // 2
            return jnp.transpose(out[:, sel, :].reshape(group, T, F), (0, 2, 1))  # (G, F, T)

        grouped = mags.reshape(Bt // group, group, C, F, Tp)
        return jax.lax.map(one, grouped).reshape(Bt, F, T)

    return run


@functools.lru_cache(maxsize=32)
def _jitted_apply(model):
    """One compiled forward per model instance (flax modules are hashable) —
    keeps repeated per-node/per-clip crnn_mask calls on a cached XLA
    executable instead of op-by-op dispatch."""
    return jax.jit(lambda variables, x: model.apply(variables, x, train=False))


def vad_mask(ts, n_freq: int, n_frames: int):
    """'ivad' mask: oracle VAD spread across frequencies
    (reference tango.py:216-222)."""
    vad = np.asarray(vad_oracle_batch(jnp.asarray(ts), win_len=N_FFT, win_hop=N_HOP))
    vad = vad[::N_HOP]
    m = np.zeros((n_freq, n_frames), "float32")
    m[:, : len(vad)] = np.tile(vad[: n_frames], (n_freq, 1))
    return m


def plot_conf(infos, mics_per_node=(4, 4, 4, 4), return_fig=False):
    """Room top-view plot from saved generation infos
    (reference utils.py:141-172) — node labels anchored at each node's
    first mic.  Shares the renderer with ``sim.geometry.RoomSetup.plot``
    (``disco_tpu.utils.plotting.draw_room_topview``)."""
    from disco_tpu.utils.plotting import draw_room_topview

    cums = np.cumsum([0] + list(mics_per_node))[:-1]
    node_anchors = np.asarray(infos["mics"])[:2, cums].T  # (n_nodes, 2)
    f = draw_room_topview(
        infos["room"]["length"], infos["room"]["width"], infos["mics"],
        infos["sources"], node_anchors, label_offset=1.05,
    )
    if return_fig:
        return f
