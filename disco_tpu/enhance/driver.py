"""The flagship per-RIR enhancement entry point: load a dataset sample, run
the two-step TANGO pipeline on device, evaluate, save.

Capability parity with reference ``speech_enhancement/tango.py:460-641``
(``main``): same idempotency guard, same results-directory layout
(``results/{scenario}/{dset}/{save_dir}/{WAV,MASK,OIM,STFT/z,FIG}``), same
pickled ``results_tango_* / results_mwf_*`` dicts with the same keys, so
reference-side aggregation scripts read the outputs unchanged.

Both BSS metric families are written to the OIM pickles: the ``sdr_*`` /
``sir_*`` / ``sar_*`` keys carry the 512-tap filtered-projection values of
``core.bss.bss_eval_sources`` — the same metric as mir_eval's
``bss_eval_sources`` that the reference calls (tango.py:552-567), so the
numbers are paper-table comparable — and the ``si_sdr_*`` / ``si_sir_*`` /
``si_sar_*`` keys carry the scale-invariant Le Roux decomposition
(``core.metrics.si_bss``).  STOI is the native implementation in
``core.metrics.stoi``.
"""
from __future__ import annotations

import contextlib
import os
import pickle
import threading
import time
from functools import lru_cache, partial
from pathlib import Path

import numpy as np

_FIG_LOCK = threading.Lock()  # see the save_fig block in _persist_and_score

from disco_tpu.core.bss import BssEval
from disco_tpu.core.dsp import istft
from disco_tpu.core.metrics import fw_sd, fw_snr, si_bss, stoi
from disco_tpu.enhance.tango import TangoResult, oracle_masks, tango
from disco_tpu.enhance.zexport import load_node_signals
from disco_tpu.io.atomic import (
    dump_pickle_atomic,
    probe_npy,
    probe_pickle,
    save_npy_atomic,
    write_wav_atomic,
)
from disco_tpu.io.audio import read_wav
from disco_tpu.io.layout import DatasetLayout, case_of_rir, snr_dirname
from disco_tpu.obs import accounting as obs_accounting
from disco_tpu.obs import events as obs_events
from disco_tpu.obs import sentinels as obs_sentinels
from disco_tpu.obs.metrics import REGISTRY as obs_registry
from disco_tpu.runs import chaos as run_chaos
from disco_tpu.runs import interrupt as run_interrupt
from disco_tpu.runs.ledger import RunLedger, unit_rir
from disco_tpu.utils import TRANSPORT_ERRORS, call_with_retries, device_get_tree


def _record_degraded(fault_plan, streaming: bool = False, **attrs):
    """Record the pipeline's degraded-mode entry for one clip: a
    ``degraded`` obs event naming what was lost plus the ``degraded_clips``
    counter.  No-op when the plan injects nothing (an all-defaults spec)."""
    if fault_plan is None or not fault_plan.any_fault():
        return
    obs_registry.counter("degraded_clips").inc()
    if not obs_events.enabled():
        return
    if streaming:
        lost = fault_plan.avail_streaming < 1.0
        obs_events.record(
            "degraded", stage="mwf", mode="streaming",
            n_blocks_held=int(lost.sum()),
            nodes=np.flatnonzero(lost.any(axis=1)).tolist(),
            **attrs,
        )
    else:
        excluded = np.flatnonzero(fault_plan.avail_offline < 1.0).tolist()
        obs_events.record(
            "degraded", stage="mwf", mode="offline",
            n_streams_excluded=len(excluded), nodes=excluded,
            nan_nodes=np.flatnonzero(fault_plan.z_nan).tolist(),
            **attrs,
        )


def load_input_signals(layout: DatasetLayout, rir: int, noise: str, snr_range, n_nodes=4, mics_per_node=4):
    """Processed node signals + dry references + logged SNRs (reference
    tango.py:55-111)."""
    y, s, n = load_node_signals(layout, rir, noise, snr_range, n_nodes, mics_per_node)
    s_dry, fs = read_wav(layout.dry_source("target", rir, 1))
    n_dry, _ = read_wav(layout.dry_source("noise", rir, 2, noise=noise))
    snr_path = layout.snr_log(snr_range, rir, noise)
    if snr_path.exists():
        rnd_snrs = np.load(snr_path)
    else:
        # Degraded input, made visible: the per-node logged SNRs land in
        # every OIM pickle as snr_in_raw, so a silent zeros substitution
        # poisons downstream aggregation invisibly.  The counter and the
        # warning event surface it in `disco-obs report`.
        rnd_snrs = np.zeros(n_nodes)
        obs_registry.counter("snr_sidecar_missing").inc()
        obs_events.record(
            "warning", stage="load_input", rir=rir, noise=noise,
            reason="SNR sidecar missing; substituting zeros for snr_in_raw",
            path=str(snr_path),
        )
    return y, s, n, s_dry, n_dry, fs, rnd_snrs


def dset_of_rir(rir: int) -> str:
    """Results-tree split: train for rir <= 11000, test above
    (reference tango.py:41-45)."""
    return "train" if rir <= 11000 else "test"


def results_root(scenario: str, dset: str, save_dir: str) -> Path:
    """Results tree root for one (scenario, dset, save_dir) run."""
    return Path("results") / scenario / dset / save_dir


def _clip_done(out: Path, rir, noise: str) -> bool:
    """Validated idempotency probe for one enhanced RIR: both OIM pickles
    (the last artifacts ``_persist_and_score`` writes) must exist AND
    unpickle to completion.  Replaces the existence-only guard that trusted
    truncated files forever — a crash mid-run now reads as not-done and the
    clip is redone (atomic writes make the redo safe)."""
    for kind in ("mwf", "tango"):
        p = out / "OIM" / f"results_{kind}_{rir}_{noise}.p"
        if not probe_pickle(p):
            if p.exists():
                obs_registry.counter("corrupt_artifacts_detected").inc()
                obs_events.record(
                    "warning", stage="skip_probe", rir=rir, noise=noise,
                    reason="existing OIM pickle failed its integrity probe; "
                           "re-enhancing this clip", path=str(p),
                )
            return False
    return True


def clip_artifacts(out: Path, rir, noise: str, snr_range, n_nodes: int) -> list:
    """The canonical artifact paths of one enhanced RIR — what the run
    ledger digests into a ``done`` record and re-verifies on resume.  The
    best-effort FIG render is deliberately absent (plotting may legally
    fail)."""
    paths = [
        out / "OIM" / f"results_tango_{rir}_{noise}.p",
        out / "OIM" / f"results_mwf_{rir}_{noise}.p",
    ]
    zdir = out / "STFT" / "z" / "raw" / snr_dirname(snr_range)
    for k in range(n_nodes):
        tag = f"{noise}_Node-{k + 1}"
        paths += [
            out / "WAV" / str(rir) / f"{stem}-{tag}.wav"
            for stem in ("in_mix", "out_mix", "mid_z", "in_noi", "out_noi", "in_tar", "out_tar")
        ]
        paths += [
            out / "MASK" / str(rir) / f"step1_{tag}.npy",
            out / "MASK" / str(rir) / f"step2_{tag}.npy",
            zdir / f"{rir}_{tag}.npy",
        ]
    return paths


#: Keys of the per-node metric dicts below — the degraded-mode NaN fill
#: must produce exactly this set so `stack_keys` can stack healthy and
#: corrupted nodes together (pinned by tests/test_fault.py).
_NODE_METRIC_KEYS = (
    "sdr_cnv", "sir_cnv", "sar_cnv", "sdr_dry", "sir_dry", "sar_dry",
    "sdr_in_cnv", "sir_in_cnv", "sdr_in_dry", "sir_in_dry", "sar_in_dry",
    "si_sdr_cnv", "si_sir_cnv", "si_sar_cnv",
    "si_sdr_dry", "si_sir_dry", "si_sar_dry",
    "si_sdr_in_cnv", "si_sir_in_cnv",
    "si_sdr_in_dry", "si_sir_in_dry", "si_sar_in_dry",
    "delta_stoi_cnv", "delta_stoi_dry",
    "snr_out", "snr_in_cnv", "snr_in_dry", "fw_sd_cnv", "fw_sd_dry",
)


def _node_metrics_pair(y0, s0, n0, sh_t, szh_t, s_dry, n_dry, sf_t, nf_t,
                       szf_t, nzf_t, fs, sl, proj_dry, bss_filt_len=512):
    """All metric variants for one node's two enhanced outputs — ``sh_t``
    (full TANGO) and ``szh_t`` (step-1/MWF) — against the dry and convolved
    references (tango.py:545-593).  Returns (tango_dict, mwf_dict).

    Both outputs share the references, so the 512-tap BSS projectors (the
    dominant eval cost: a (2*512)^2 Gram factorization each) are reused for
    every estimate — the dry one (``proj_dry``, node-independent) is built
    once per RIR by the caller, the convolved one once per node here — and
    the input-side metrics are computed once instead of per-output.  The
    filtered-projection family is emitted under the reference's key names,
    the scale-invariant family under ``si_*``."""
    refs_dry = np.stack((s_dry[sl], n_dry[sl]), axis=1)
    refs_cnv = np.stack((s0[sl], n0[sl]), axis=1)
    proj_cnv = BssEval(refs_cnv.T, bss_filt_len)

    # input-side metrics: identical for both outputs
    sdr_in_dry, sir_in_dry, sar_in_dry = proj_dry.score(y0[sl])
    sdr_in_cnv, sir_in_cnv, _ = proj_cnv.score(y0[sl])
    si_sdr_in_dry, si_sir_in_dry, si_sar_in_dry = si_bss(y0[sl], refs_dry, 0)
    si_sdr_in_cnv, si_sir_in_cnv, _ = si_bss(y0[sl], refs_cnv, 0)
    stoi_in = stoi(s0[sl], y0[sl], fs)
    stoi_in_dry = stoi(s_dry[sl], y0[sl], fs)
    _, fw_snr_in_cnv, _ = fw_snr(s0[sl], n0[sl], fs)
    _, fw_snr_in_dry, _ = fw_snr(s_dry[sl], n_dry[sl], fs)

    def one_output(est, s_filt, n_filt):
        if not np.isfinite(est[sl]).all():
            # Degraded mode (disco_tpu.fault): a corrupted/NaN stream — e.g.
            # the saved MWF output of a NaN-z node, whose enhanced TANGO
            # output is still fine — scores as NaN metrics, never a crash
            # (the 512-tap BSS projector's cho_solve rejects non-finite
            # input with a raw ValueError otherwise).
            return dict.fromkeys(_NODE_METRIC_KEYS, float("nan"))
        sdr_dry, sir_dry, sar_dry = proj_dry.score(est[sl])
        sdr_cnv, sir_cnv, sar_cnv = proj_cnv.score(est[sl])
        si_sdr_dry, si_sir_dry, si_sar_dry = si_bss(est[sl], refs_dry, 0)
        si_sdr_cnv, si_sir_cnv, si_sar_cnv = si_bss(est[sl], refs_cnv, 0)
        stoi_out = stoi(s0[sl], est[sl], fs)
        stoi_out_dry = stoi(s_dry[sl], est[sl], fs)
        _, fw_snr_out, _ = fw_snr(s_filt[sl], n_filt[sl], fs)
        _, fsd_cnv, _ = fw_sd(s_filt[sl], s0[sl], fs)
        _, fsd_dry, _ = fw_sd(s_filt[sl], s_dry[sl], fs)
        return {
            "sdr_cnv": sdr_cnv, "sir_cnv": sir_cnv, "sar_cnv": sar_cnv,
            "sdr_dry": sdr_dry, "sir_dry": sir_dry, "sar_dry": sar_dry,
            "sdr_in_cnv": sdr_in_cnv, "sir_in_cnv": sir_in_cnv,
            "sdr_in_dry": sdr_in_dry, "sir_in_dry": sir_in_dry, "sar_in_dry": sar_in_dry,
            "si_sdr_cnv": si_sdr_cnv, "si_sir_cnv": si_sir_cnv, "si_sar_cnv": si_sar_cnv,
            "si_sdr_dry": si_sdr_dry, "si_sir_dry": si_sir_dry, "si_sar_dry": si_sar_dry,
            "si_sdr_in_cnv": si_sdr_in_cnv, "si_sir_in_cnv": si_sir_in_cnv,
            "si_sdr_in_dry": si_sdr_in_dry, "si_sir_in_dry": si_sir_in_dry, "si_sar_in_dry": si_sar_in_dry,
            "delta_stoi_cnv": stoi_out - stoi_in, "delta_stoi_dry": stoi_out_dry - stoi_in_dry,
            "snr_out": fw_snr_out, "snr_in_cnv": fw_snr_in_cnv, "snr_in_dry": fw_snr_in_dry,
            "fw_sd_cnv": fsd_cnv, "fw_sd_dry": fsd_dry,
        }

    return one_output(sh_t, sf_t, nf_t), one_output(szh_t, szf_t, nzf_t)


def estimate_masks(Y, S, N, models, mask_type: str, n_nodes: int, mu: float = 1.0,
                   z_sigs: str = "zs_hat", mags=None):
    """Step-1 and step-2 masks, oracle or CRNN (reference tango.py:189-225,
    387-394).  ``models`` is a 2-list; each entry is None (oracle) or a
    ``(flax_module, variables)`` pair.  The step-2 CRNN consumes the local
    reference channel plus the exchanged z streams, so step 1 runs first to
    produce them (the staged flow of reference main:497-503).  All node
    forwards run as ONE batched device call per step
    (:func:`disco_tpu.enhance.inference.crnn_masks_batched`).

    ``mags``: optional ``(mag_S, mag_N)`` (K, C, F, T) magnitude
    spectrograms from the fused STFT (``ops.stft_ops.stft_with_mag``) —
    the irm/ibm oracle masks then consume them directly instead of
    recomputing ``abs`` over the complex spectra (the magnitude the fused
    kernel already emitted); the iam family needs the complex sum and
    falls back to the spectra."""
    import jax.numpy as jnp

    if mags is not None and mask_type[:-1] in ("irm", "ibm"):
        from disco_tpu.core.masks import tf_mask_mag

        oracle = tf_mask_mag(mags[0][:, 0], mags[1][:, 0], mask_type)
    else:
        oracle = oracle_masks(S, N, mask_type)
    Y = jnp.asarray(Y)
    if models[0] is None:
        masks_z = oracle
    else:
        from disco_tpu.enhance.inference import crnn_masks_batched

        model, variables = models[0]
        masks_z = jnp.asarray(crnn_masks_batched(Y[:, 0], model, variables))
    if models[1] is None:
        mask_w = oracle
    else:
        from disco_tpu.enhance.inference import crnn_masks_batched
        from disco_tpu.enhance.zexport import compute_z_signals

        out = compute_z_signals(None, None, None, Y=Y, S=S, N=N, masks_z=masks_z, mu=mu)
        zs = _z_for_mask_device(out["z_y"], out["zn"], n_nodes, z_sigs)
        model, variables = models[1]
        mask_w = jnp.asarray(crnn_masks_batched(Y[:, 0], model, variables, zs=zs))
    return masks_z, mask_w


def _z_for_mask_device(z_y, zn, n_nodes: int, z_sigs: str):
    """Device-resident mirror of inference.get_z_for_mask for ALL nodes at
    once: (K, F, T) z streams -> (K, n_z, F, T) per-node NN inputs, with no
    host round-trip (the tunneled chip moves ~45 MB/s; z streams for a
    16-clip batch are ~130 MB)."""
    import jax.numpy as jnp

    from disco_tpu.enhance.tango import others_index

    oth = jnp.asarray(others_index(n_nodes))  # (K, K-1)
    if z_sigs in ("zs_hat", "zn_hat"):
        z_in = jnp.asarray(z_y if z_sigs == "zs_hat" else zn)
        return z_in[oth]
    z_y, zn = jnp.asarray(z_y), jnp.asarray(zn)
    inter = jnp.stack([z_y, zn], axis=1).reshape((2 * n_nodes,) + z_y.shape[1:])
    keep = jnp.asarray([
        [j for j in range(2 * n_nodes) if j not in (2 * k, 2 * k + 1)]
        for k in range(n_nodes)
    ])
    return inter[keep]



def _persist_and_score(
    out: Path, layout: DatasetLayout, rir: int, noise: str, snr_range,
    y, s, n, s_dry, n_dry, fs, rnd_snrs, res, L: int, T_true: int,
    n_nodes: int, save_fig: bool, bss_filt_len: int = 512,
    time_domain=None,
):
    """Per-RIR second half of the reference main (tango.py:528-639): ISTFT
    back to time, every metric variant, and the WAV/MASK/OIM/STFT-z/FIG
    results tree.  Shared by the single-RIR and batched drivers.

    ``time_domain``: optional precomputed ``(sh_t, szh_t, sf_t, nf_t,
    szf_t, nzf_t)`` host arrays — the pipelined corpus engine converts the
    whole chunk on device and reads it back in ONE batched ``device_get``
    (:func:`disco_tpu.enhance.pipeline.fetch_chunk_host`), so scoring must
    not pay a per-clip ISTFT + readback again.  ``res`` then only needs its
    ``masks_z`` / ``mask_w`` / ``z_y`` leaves (host-resident)."""
    if time_domain is not None:
        # disco-lint: disable=DL002 -- time_domain arrays are host-resident by contract (fetch_chunk_host already landed them); np.asarray here is a no-op guard
        sh_t, szh_t, sf_t, nf_t, szf_t, nzf_t = (np.asarray(a) for a in time_domain)
        # host-resident per the contract above; slice on host
        masks_z_h, mask_w_h, z_y_h = res.masks_z, res.mask_w, res.z_y
    else:
        with obs_events.stage("istft", rir=rir):
            # All six ISTFTs queue ON DEVICE, then the whole scoring payload
            # (time-domain stacks + masks + the complex z export) crosses the
            # tunnel in ONE batched complex-safe readback under the same
            # transport-retry budget the old per-leaf resilient_to_host had
            # (the per-node slice loop below used to pay 2K extra fenced
            # crossings per clip — the anti-pattern disco-lint DL002 pins).
            host = call_with_retries(
                device_get_tree,
                {
                    "td": tuple(
                        istft(z, length=L)
                        for z in (res.yf, res.z_y, res.sf, res.nf, res.z_s, res.z_n)
                    ),
                    "masks_z": res.masks_z,
                    "mask_w": res.mask_w,
                    "z_y": res.z_y,
                },
                retry_on=TRANSPORT_ERRORS,
                label="persist_readback",
            )
        sh_t, szh_t, sf_t, nf_t, szf_t, nzf_t = host["td"]
        masks_z_h, mask_w_h, z_y_h = host["masks_z"], host["mask_w"], host["z_y"]
    obs_sentinels.check_finite("istft_out", sh_t, stage="istft")
    # score_persist covers the whole tail of the function (node loop,
    # pickles, best-effort figure); ExitStack reuses the shared `stage`
    # implementation without reindenting the tail.  Closed on the success
    # path below — a crashed clip aborts the run, so losing its stage_end
    # is acceptable telemetry, not a leak (the recorder flushes per event).
    _score_stage = contextlib.ExitStack()
    _score_stage.enter_context(obs_events.stage("score_persist", rir=rir, noise=noise))

    for sub in ("WAV", "MASK", "OIM", "FIG"):
        os.makedirs(out / sub, exist_ok=True)
    (out / "WAV" / str(rir)).mkdir(exist_ok=True)
    (out / "MASK" / str(rir)).mkdir(exist_ok=True)
    zdir = out / "STFT" / "z" / "raw" / snr_dirname(snr_range)
    os.makedirs(zdir, exist_ok=True)

    # first second (lead silence) skipped; lengths are node-independent,
    # so the slice and the dry-reference projector are per-RIR
    min_len = min(len(y[0, 0]), sh_t.shape[-1], len(s_dry), len(n_dry))
    sl = slice(fs, min_len)
    proj_dry = BssEval(np.stack((s_dry[sl], n_dry[sl])), bss_filt_len)

    per_node_tango, per_node_mwf = [], []
    for k in range(n_nodes):
        y0, s0, n0 = y[k, 0], s[k, 0], n[k, 0]
        tango_d, mwf_d = _node_metrics_pair(
            y0, s0, n0, sh_t[k], szh_t[k], s_dry, n_dry,
            sf_t[k], nf_t[k], szf_t[k], nzf_t[k], fs, sl, proj_dry,
            bss_filt_len=bss_filt_len,
        )
        per_node_tango.append(tango_d)
        per_node_mwf.append(mwf_d)

        tag = f"{noise}_Node-{k + 1}"
        # atomic (tmp+fsync+rename, io.atomic): a crash mid-persist leaves
        # the final paths either complete or absent, never truncated — the
        # invariant the verified-resume probes rely on
        write_wav_atomic(out / "WAV" / str(rir) / f"in_mix-{tag}.wav", y0, fs)
        write_wav_atomic(out / "WAV" / str(rir) / f"out_mix-{tag}.wav", sh_t[k], fs)
        write_wav_atomic(out / "WAV" / str(rir) / f"mid_z-{tag}.wav", szh_t[k], fs)
        write_wav_atomic(out / "WAV" / str(rir) / f"in_noi-{tag}.wav", n0, fs)
        write_wav_atomic(out / "WAV" / str(rir) / f"out_noi-{tag}.wav", nf_t[k], fs)
        write_wav_atomic(out / "WAV" / str(rir) / f"in_tar-{tag}.wav", s0, fs)
        write_wav_atomic(out / "WAV" / str(rir) / f"out_tar-{tag}.wav", sf_t[k], fs)
        save_npy_atomic(out / "MASK" / str(rir) / f"step1_{tag}", masks_z_h[k, :, :T_true])
        save_npy_atomic(out / "MASK" / str(rir) / f"step2_{tag}", mask_w_h[k, :, :T_true])
        # z export: already on host via the single batched readback above —
        # slicing here is numpy, not a per-node tunnel crossing
        save_npy_atomic(zdir / f"{rir}_{tag}", z_y_h[k, :, :T_true])

    def stack_keys(dicts):
        return {k: np.array([d[k] for d in dicts]) for k in dicts[0]}

    results = {"snr_in_raw": rnd_snrs, **stack_keys(per_node_tango)}
    resultsz = {"snr_in_raw": rnd_snrs, **stack_keys(per_node_mwf)}
    dump_pickle_atomic(out / "OIM" / f"results_tango_{rir}_{noise}.p", results)
    dump_pickle_atomic(out / "OIM" / f"results_mwf_{rir}_{noise}.p", resultsz)

    if save_fig:
        infos_path = layout.infos(rir)
        # validated, not just exists(): a truncated infos .npy from a
        # crashed datagen run would otherwise be trusted here forever
        if probe_npy(infos_path):
            try:
                from disco_tpu.enhance.inference import plot_conf

                # One figure at a time: the OO matplotlib API avoids pyplot's
                # main-thread requirement, but first-render font-cache builds
                # and the Agg rasterizer are not re-entrant — scoring may run
                # on a thread pool (enhance_rirs_batched score_workers).  The
                # unregistered Figure needs no pyplot close; it is GC'd.
                with _FIG_LOCK:
                    fig = plot_conf(np.load(infos_path, allow_pickle=True).item(), return_fig=True)
                    fig.savefig(out / "FIG" / f"{rir}.png")
            except Exception:
                pass  # plotting is best-effort observability, never fatal
    _score_stage.close()
    obs_registry.counter("clips_enhanced").inc()
    if obs_events.enabled():
        obs_events.record("clip", rir=rir, noise=noise, n_nodes=n_nodes,
                          sdr_cnv_mean=float(np.mean(results["sdr_cnv"])))
    run_chaos.tick("between_clips", rir=rir)
    return results


def enhance_rir(
    root: str,
    scenario: str,
    rir: int,
    noise: str,
    save_dir: str = "tango",
    snr_range=(0, 6),
    mask_type: str = "irm1",
    policy: str = "local",
    models=(None, None),
    mu: float = 1.0,
    n_nodes: int = 4,
    mics_per_node: int = 4,
    out_root: str | None = None,
    force: bool = False,
    save_fig: bool = True,
    streaming: bool = False,
    bucket: int = 0,
    z_sigs: str = "zs_hat",
    solver: str | None = None,
    cov_impl: str = "auto",
    stft_impl: str = "auto",
    precision: str = "f32",
    chained: bool = False,
    fault_spec=None,
    ledger=None,
):
    """Enhance one RIR end-to-end and persist everything (reference
    tango.py:460-641).  ``models``: per-step CRNN params or None for the
    oracle masks of ``mask_type``.  ``streaming=True`` runs the
    frame-recursive online pipeline (exponential-smoothing covariances,
    block filter refresh) instead of the offline frame-mean one.

    ``chained=True`` runs the whole offline clip — STFT, oracle masks, both
    MWF steps, the six scoring ISTFTs — as ONE dispatched program
    (:func:`disco_tpu.enhance.fused.tango_clip_fused` with ``export=True``)
    followed by ONE batched readback, instead of the staged
    stft/masks/mwf/istft dispatch sequence.  Offline oracle lane only:
    ``streaming``, CRNN ``models`` and ``fault_spec`` are rejected (the
    chained program computes masks in-program and has no z-exchange host
    seam).  ``solver=None`` then resolves to ``'fused'`` — the chained
    program exists to compose with the batch-in-lanes fused solve.

    ``ledger``: optional :class:`disco_tpu.runs.RunLedger` (or path) —
    the clip's in_flight/done transitions and artifact digests are
    recorded for verified resume (``disco_tpu.runs.ledger``).  All artifact
    writes are atomic (``disco_tpu.io.atomic``), and the idempotency skip
    validates the existing OIM pickles instead of trusting bare existence —
    a truncated artifact from a crashed run is re-enhanced, never returned.

    ``fault_spec``: optional ``disco_tpu.fault.FaultSpec`` (or dict/path
    accepted by ``load_fault_spec``) — inject the seeded fault scenario at
    the z-exchange seam and run the pipeline in degraded mode: offline,
    unavailable/corrupted streams are excluded from the step-2 MWF;
    streaming, lost blocks are bridged by the last-good-z hold.  Every
    injected fault and the degraded-mode entry are recorded as obs
    events/counters.  ``None`` (default) leaves the pipeline byte-identical
    to the fault-free path.

    ``solver=None`` resolves per mode: 'power' offline (measured fastest
    at SDR parity — round-3 solver_ab, exp/tpu_validation_r3.jsonl) but
    'eigh' in streaming mode, whose warm-up covariances have weak
    eigengaps that the 12-iteration power default cannot resolve
    (tests/test_streaming.py pins ~power:96 for eigh-level quality there).

    ``stft_impl`` / ``precision``: the fused-hot-path seams
    (``ops.stft_ops.resolve_stft_impl`` / ``ops.resolve``).  The y/s/n
    analysis STFTs run as ONE fused spec+magnitude program over the
    stacked streams (three fenced dispatches collapse to one on the
    tunneled attachment, and the irm/ibm oracle masks consume the emitted
    magnitudes); ``precision='bf16'`` opts the STFT matmuls and both
    pipelines' covariance accumulations into the bf16 compute lane.

    Returns the tango results dict, or None when the RIR was already
    processed (idempotency)."""
    if chained:
        if streaming:
            raise ValueError(
                "chained=True is the offline whole-clip lane; the streaming "
                "chained twin (enhance.fused.streaming_clip_fused) lives "
                "behind the serve scheduler's time-domain sessions"
            )
        if models != (None, None):
            raise ValueError(
                "chained=True computes oracle masks in-program; the CRNN "
                "mask lane needs host STFTs and stays on the staged path"
            )
        if fault_spec is not None:
            raise ValueError(
                "chained=True has no z-exchange host seam to inject faults "
                "at; run fault scenarios on the staged path"
            )
    if solver is None:
        solver = "fused" if chained else ("eigh" if streaming else "power")
    import jax.numpy as jnp

    from disco_tpu.ops.stft_ops import stft_with_mag

    out = Path(out_root) if out_root is not None else results_root(scenario, dset_of_rir(rir), save_dir)
    if not force and _clip_done(out, rir, noise):
        return None
    if ledger is not None and not isinstance(ledger, RunLedger):
        ledger = RunLedger(ledger)
    if ledger is not None:
        ledger.mark_in_flight(unit_rir(rir, noise))

    layout = DatasetLayout(root, scenario, case_of_rir(rir))
    with obs_events.stage("load_input", rir=rir, noise=noise):
        y, s, n, s_dry, n_dry, fs, rnd_snrs = load_input_signals(
            layout, rir, noise, snr_range, n_nodes, mics_per_node
        )
    L = y.shape[-1]
    if bucket:
        from disco_tpu.core.dsp import bucket_length

        Lp = bucket_length(L, bucket)
        pad = ((0, 0), (0, 0), (0, Lp - L))
        y_in, s_in, n_in = np.pad(y, pad), np.pad(s, pad), np.pad(n, pad)
    else:
        y_in, s_in, n_in = y, s, n

    from disco_tpu.core.dsp import n_stft_frames

    T_true = n_stft_frames(L)  # saved masks/z trimmed to the true frames
    if chained:
        # TangoResult is re-imported here because the streaming branch's
        # local import below makes the name function-local
        from disco_tpu.enhance.fused import tango_clip_fused
        from disco_tpu.enhance.tango import TangoResult

        # The whole clip rides ONE dispatched program (one fenced ~80 ms
        # RPC on the tunneled attachment) and the full scoring payload —
        # six time-domain streams, both masks, the z export — crosses back
        # in ONE batched readback; the staged stft/masks/mwf/istft stages
        # above and below never run.
        with obs_events.stage("mwf", rir=rir, mode="chained", solver=solver):
            host = call_with_retries(
                device_get_tree,
                tango_clip_fused(
                    jnp.asarray(y_in), jnp.asarray(s_in), jnp.asarray(n_in),
                    mu=mu, policy=policy, mask_type=mask_type, solver=solver,
                    cov_impl=cov_impl, stft_impl=stft_impl,
                    precision=precision, export=True,
                ),
                retry_on=TRANSPORT_ERRORS,
                label="chained_readback",
            )
        # bucket padding is trimmed on host (numpy views, no extra crossing)
        td = tuple(a[..., :L] for a in host["td"])
        obs_sentinels.check_finite("mwf_yf", td[0], stage="mwf")
        res = TangoResult(
            yf=None, sf=None, nf=None, z_y=host["z_y"],
            z_s=None, z_n=None, zn=None,
            masks_z=host["masks_z"], mask_w=host["mask_w"],
        )
        out_results = _persist_and_score(
            out, layout, rir, noise, snr_range, y, s, n, s_dry, n_dry, fs,
            rnd_snrs, res, L, T_true, n_nodes, save_fig, time_domain=td,
        )
        if ledger is not None:
            ledger.mark_done(
                unit_rir(rir, noise),
                clip_artifacts(out, rir, noise, snr_range, n_nodes),
            )
        if obs_events.enabled():
            obs_events.record("counters", **obs_registry.snapshot())
        return out_results
    with obs_events.stage("stft", rir=rir):
        # ONE fused spec+magnitude program over the stacked y/s/n streams
        # (was three separate stft dispatches + an abs pass in the mask
        # program — on the tunneled attachment each dispatch is a fenced
        # ~80 ms RPC)
        spec, mag = stft_with_mag(jnp.asarray(np.stack([y_in, s_in, n_in])),
                                  impl=stft_impl, precision=precision)
        Y, S, N = spec[0], spec[1], spec[2]
    obs_sentinels.check_finite("stft_Y", Y, stage="stft")
    with obs_events.stage("masks", rir=rir):
        masks_z, mask_w = estimate_masks(Y, S, N, models, mask_type, n_nodes,
                                         mu=mu, z_sigs=z_sigs,
                                         mags=(mag[1], mag[2]))
    obs_sentinels.check_finite("masks", (masks_z, mask_w), stage="masks")

    fault_plan = None
    if fault_spec is not None:
        from disco_tpu.enhance.streaming import DEFAULT_UPDATE_EVERY
        from disco_tpu.fault import plan_faults

        T_frames = Y.shape[-1]
        n_blocks = -(-T_frames // DEFAULT_UPDATE_EVERY) if streaming else 1
        fault_plan = plan_faults(fault_spec, n_nodes, n_blocks)
        fault_plan.record(mode="streaming" if streaming else "offline")
        _record_degraded(fault_plan, rir=rir, streaming=streaming)
        if not fault_plan.any_fault():
            # The seeded plan drew nothing: stay on the fault-free fast
            # path (no guard, no masked step-2 program, no extra jit entry)
            fault_plan = None
    if streaming:
        # The online pipeline implements the 'local'/'distant'/'none'
        # mask-for-z policies; the oracle policies are offline-only.
        if policy not in ("local", "distant", "none", None):
            raise ValueError(
                f"streaming mode implements the 'local'/'distant'/'none' "
                f"mask-for-z policies; got {policy!r}"
            )
        if cov_impl not in ("xla", "auto"):
            # the online estimator is exponential smoothing, not a frame
            # mean — the fused offline kernel does not apply; reject an
            # EXPLICIT pallas request rather than silently compare xla
            # against itself in an A/B ('auto' just means "pipeline
            # default", which for streaming is its own estimator)
            raise ValueError(
                f"streaming mode uses the smoothed-covariance estimator; "
                f"cov_impl={cov_impl!r} applies to the offline pipeline only"
            )
        from disco_tpu.enhance.tango import TangoResult
        from disco_tpu.enhance.streaming import streaming_tango

        with obs_events.stage("mwf", rir=rir, mode="streaming", solver=solver):
            st = streaming_tango(Y, masks_z, mask_w, mu=mu, S=S, N=N,
                                 with_diagnostics=True, policy=policy, solver=solver,
                                 precision=precision,
                                 z_avail=None if fault_plan is None
                                 else fault_plan.avail_streaming)
        # ONE filter everywhere: every saved wav, mask, z and metric below
        # describes the online beamformer (sf/nf come from the same
        # per-block filters applied to the clean components).
        res = TangoResult(
            yf=st["yf"], sf=st["sf"], nf=st["nf"],
            z_y=st["z_y"], z_s=st["z_s"], z_n=st["z_n"], zn=st["zn"],
            masks_z=masks_z, mask_w=mask_w,
        )
    else:
        with obs_events.stage("mwf", rir=rir, mode="offline", solver=solver):
            if fault_plan is None:
                res = tango(Y, S, N, masks_z, mask_w, mu=mu, policy=policy,
                            mask_type=mask_type, solver=solver, cov_impl=cov_impl,
                            precision=precision)
            else:
                res = tango(Y, S, N, masks_z, mask_w, mu=mu, policy=policy,
                            mask_type=mask_type, solver=solver, cov_impl=cov_impl,
                            precision=precision,
                            z_mask=fault_plan.avail_offline,
                            z_nan=fault_plan.z_nan if fault_plan.z_nan.any() else None)
    obs_sentinels.check_finite("mwf_yf", res.yf, stage="mwf")

    out_results = _persist_and_score(
        out, layout, rir, noise, snr_range, y, s, n, s_dry, n_dry, fs,
        rnd_snrs, res, L, T_true, n_nodes, save_fig,
    )
    if ledger is not None:
        ledger.mark_done(
            unit_rir(rir, noise),
            clip_artifacts(out, rir, noise, snr_range, n_nodes),
        )
    if obs_events.enabled():
        obs_events.record("counters", **obs_registry.snapshot())
    return out_results


def aggregate_results(oim_dir, kind: str = "tango", noise: str | None = None):
    """Collect per-RIR pickles into one dict of stacked arrays — the
    aggregation the reference leaves to the user (SURVEY.md §5.5)."""
    from disco_tpu.core.miscx import concatenate_dicts

    oim_dir = Path(oim_dir)
    pattern = f"results_{kind}_*"
    dicts = []
    for p in sorted(oim_dir.glob(pattern)):
        if noise is not None and not p.stem.endswith(f"_{noise}"):
            continue
        with open(p, "rb") as fh:
            d = pickle.load(fh)
        dicts.append({k: np.atleast_1d(v) for k, v in d.items()})
    if not dicts:
        return {}
    return concatenate_dicts(dicts)


@lru_cache(maxsize=8)
def _jitted_step1_2d(mu: float):
    """One jitted (batch, node)-vmapped step-1 program per mu.  Cached at
    module level so repeated corpus batches reuse the traced program — a
    fresh ``jax.jit`` per batch re-traces everything (see the round-3 note
    on ``inference._jitted_sliding_masks``)."""
    from disco_tpu.enhance.tango import tango_step1

    import jax

    return obs_accounting.counted_jit(
        jax.vmap(jax.vmap(lambda y, s, n, m: tango_step1(y, s, n, m, mu=mu))),
        label="step1_2d",
    )


def _batched_masks(Yb, Sb, Nb, models, mask_type, mu, n_nodes, z_sigs):
    """Step-1/step-2 masks for a WHOLE clip batch: the (B, K) node forwards
    of each CRNN step run as one concatenated device call
    (:func:`disco_tpu.enhance.inference.crnn_masks_batched`); oracle steps
    stay vmapped on device.  Returns (Mz, Mw), each (B, K, F, T)."""
    import jax
    import jax.numpy as jnp

    from disco_tpu.enhance.inference import crnn_masks_batched

    B, K, _, F, T = Yb.shape
    oracle = jax.vmap(lambda S, N: oracle_masks(S, N, mask_type))(Sb, Nb)
    refs = None
    if models[0] is not None or models[1] is not None:
        refs = jnp.asarray(Yb)[:, :, 0].reshape(B * K, F, T)
    if models[0] is None:
        Mz = oracle
    else:
        model, variables = models[0]
        Mz = jnp.asarray(crnn_masks_batched(refs, model, variables)).reshape(B, K, F, T)
    if models[1] is None:
        Mw = oracle
    else:
        out = _jitted_step1_2d(mu)(Yb, Sb, Nb, Mz)
        zs = jax.vmap(lambda zy, zn: _z_for_mask_device(zy, zn, n_nodes, z_sigs))(
            out["z_y"], out["zn"]
        ).reshape(B * K, -1, F, T)
        model, variables = models[1]
        Mw = jnp.asarray(crnn_masks_batched(refs, model, variables, zs=zs)).reshape(B, K, F, T)
    return Mz, Mw


def make_batch_runners(
    *,
    mask_type: str = "irm1",
    mu: float = 1.0,
    policy: str = "local",
    solver: str = "power",
    cov_impl: str = "auto",
    precision: str = "f32",
    z_mask_arr=None,
    z_nan_arr=None,
    n_nodes: int = 4,
    mesh=None,
    chained: bool = False,
    stft_impl: str = "auto",
):
    """Build the per-chunk batch programs of :func:`enhance_rirs_batched`:
    ``(run_batch, run_batch_with_masks)`` over (B, K, C, F, T) STFT stacks
    (oracle masks computed in-program vs. masks passed in).

    ``chained=True`` instead returns ``(run_batch_chained, None)``: one
    jitted program over (B, K, C, L) *time-domain* stacks that vmaps the
    whole chained clip (:func:`disco_tpu.enhance.fused.tango_clip_fused`
    with ``export=True`` — STFT, oracle masks, both MWF steps and the six
    scoring ISTFTs all inside the program), so a chunk's former
    stft + masks + mwf dispatch sequence collapses to ONE launch.
    Single-device oracle lane only (``mesh``/fault masks rejected);
    ``stft_impl`` feeds the in-program STFT and is ignored otherwise.

    Hoisted out of :func:`enhance_rirs_batched` so the corpus driver and the
    program-contract checker (``disco_tpu.analysis.trace``) construct the
    SAME jitted entry points — the golden-fingerprint gate traces exactly
    what the driver dispatches, not a re-implementation.

    Single-device (``mesh=None``): one ``counted_jit`` per runner — each
    length bucket (and each remainder-chunk padded size) traces a fresh
    program, visible in `obs report` via the ``run_batch`` /
    ``run_batch_with_masks`` labels.  The (Yb, Sb, Nb) STFT stacks are
    donated off-CPU: they are rebuilt per chunk and never touched after
    dispatch, so XLA can reuse their HBM for the outputs instead of
    doubling the footprint (CPU ignores donation with a warning per
    program — skip it there).  With a ``mesh``, the runners route through
    ``disco_tpu.parallel.tango_batch_sharded`` instead.

    No reference counterpart: the reference enhances one clip per process
    (tango.py:460-641) and has no batched corpus driver.
    """
    import jax
    import jax.numpy as jnp

    from disco_tpu.ops.resolve import resolve_precision

    precision = resolve_precision(precision)
    if chained:
        if mesh is not None:
            raise ValueError(
                "chained batch runners are a single-device lane; mesh runs "
                "stay on the staged STFT-stack runners"
            )
        if z_mask_arr is not None or z_nan_arr is not None:
            raise ValueError(
                "chained batch runners have no z-exchange host seam; run "
                "fault scenarios on the staged path"
            )
        from disco_tpu.enhance.fused import tango_clip_fused

        donate = (0, 1, 2) if jax.default_backend() != "cpu" else ()

        @obs_accounting.counted_jit(label="run_batch_chained",
                                    donate_argnums=donate)
        def run_batch_chained(yb, sb, nb):
            def one(y, s, n):
                return tango_clip_fused.__wrapped__(
                    y, s, n, mu=mu, policy=policy, mask_type=mask_type,
                    solver=solver, cov_impl=cov_impl, stft_impl=stft_impl,
                    precision=precision, export=True,
                )

            return jax.vmap(one)(yb, sb, nb)

        return run_batch_chained, None
    if mesh is not None:
        if precision != "f32":
            # the sharded runners have no precision plumbing yet — reject
            # loudly instead of silently running the f32 kernels under a
            # bf16 request
            raise ValueError(
                "precision='bf16' is a single-device lane; mesh runs are f32"
            )
        from disco_tpu.parallel import tango_batch_sharded

        # jitted ONCE (not per chunk — a fresh lambda per call would defeat
        # the jit cache and re-compile the mask program every chunk)
        oracle_mask_fn = obs_accounting.counted_jit(
            jax.vmap(partial(oracle_masks, mask_type=mask_type)), label="oracle_masks_batched"
        )

        def run_batch_with_masks(Yb, Sb, Nb, Mz, Mw):
            zmb = znb = None
            if z_mask_arr is not None:
                B = Yb.shape[0]
                zmb = jnp.broadcast_to(jnp.asarray(z_mask_arr), (B, n_nodes))
                if z_nan_arr is not None:
                    znb = jnp.broadcast_to(jnp.asarray(z_nan_arr), (B, n_nodes))
            return tango_batch_sharded(
                Yb, Sb, Nb, Mz, Mw, mesh, mu=mu, policy=policy,
                mask_type=mask_type, solver=solver, cov_impl=cov_impl,
                z_mask_b=zmb, z_nan_b=znb,
            )

        def run_batch(Yb, Sb, Nb):
            Mb = oracle_mask_fn(Sb, Nb)
            return run_batch_with_masks(Yb, Sb, Nb, Mb, Mb)

        return run_batch, run_batch_with_masks

    donate = (0, 1, 2) if jax.default_backend() != "cpu" else ()

    @obs_accounting.counted_jit(label="run_batch", donate_argnums=donate)
    def run_batch(Yb, Sb, Nb):
        def one(Y, S, N):
            m = oracle_masks(S, N, mask_type)
            return tango(Y, S, N, m, m, mu=mu, policy=policy, mask_type=mask_type,
                         solver=solver, cov_impl=cov_impl, precision=precision,
                         z_mask=z_mask_arr, z_nan=z_nan_arr)

        return jax.vmap(one)(Yb, Sb, Nb)

    @obs_accounting.counted_jit(label="run_batch_with_masks", donate_argnums=donate)
    def run_batch_with_masks(Yb, Sb, Nb, Mz, Mw):
        def one(Y, S, N, mz, mw):
            return tango(Y, S, N, mz, mw, mu=mu, policy=policy, mask_type=mask_type,
                         solver=solver, cov_impl=cov_impl, precision=precision,
                         z_mask=z_mask_arr, z_nan=z_nan_arr)

        return jax.vmap(one)(Yb, Sb, Nb, Mz, Mw)

    return run_batch, run_batch_with_masks


def enhance_rirs_batched(
    root: str,
    scenario: str,
    rirs,
    noise: str,
    save_dir: str = "tango",
    snr_range=(0, 6),
    mask_type: str = "irm1",
    policy: str = "local",
    mu: float = 1.0,
    n_nodes: int = 4,
    mics_per_node: int = 4,
    out_root: str | None = None,
    force: bool = False,
    save_fig: bool = True,
    bucket: int = 8192,
    max_batch: int = 16,
    models=(None, None),
    z_sigs: str = "zs_hat",
    solver: str | None = None,
    cov_impl: str = "auto",
    stft_impl: str = "auto",
    precision: str = "f32",
    score_workers: int = 4,
    mesh=None,
    chained: bool = False,
    fault_spec=None,
    ledger=None,
    resume: bool = False,
    pipeline: bool = True,
    compile_cache=None,
):
    """Corpus-scale enhancement: many RIRs per jitted launch.

    Single-clip launches on a tunneled/remote TPU pay a fixed per-call
    latency that dominates the compute (measured ~70 ms vs ~2 ms of actual
    work per clip); batching 16 clips into one ``vmap``ed program is ~10x
    higher throughput.  RIRs are grouped by bucketed length (one compiled
    program per bucket), enhanced with oracle masks of ``mask_type`` or —
    when ``models`` carries (module, variables) pairs — with CRNN masks
    whose per-clip, per-node forwards are batched into one device call per
    step per chunk, then scored/persisted per RIR exactly like
    :func:`enhance_rir`.

    ``score_workers``: per-RIR scoring (_persist_and_score — the 512-tap
    BSS Gram factorizations, STOI and fw metrics dominate host CPU) runs in
    a thread pool so chunk N's metrics overlap chunk N+1's decode + device
    launch; pending futures are bounded at two chunks
    (``pipeline.MAX_PENDING_CHUNKS`` — memory bound without blocking the
    dispatch thread on every previous chunk), and 1 means inline scoring.
    The metric math is identical either way.

    ``fault_spec``: optional fault scenario (``disco_tpu.fault``) — the
    same seeded plan (offline semantics: per-node availability + NaN
    corruption at the z-exchange) applies to every clip in the run, so a
    corpus sweep measures degradation under a FIXED network condition.

    ``mesh``: optional (batch, node) ``jax.sharding.Mesh`` — each chunk
    then runs as ``disco_tpu.parallel.tango_batch_sharded`` (clips over
    'batch', nodes over 'node', GSPMD-placed collectives) instead of the
    single-device vmap; ``max_batch`` must be divisible by the mesh's
    'batch' size and ``n_nodes`` by its 'node' size.  Results are
    identical (tests/test_driver.py).

    ``ledger`` / ``resume``: the crash-safe run contract
    (``disco_tpu.runs``).  ``ledger`` (a :class:`~disco_tpu.runs.RunLedger`
    or path) records per-clip in_flight/done transitions with artifact
    digests; with ``resume=True`` the ledger's done entries are *verified*
    against those digests before being skipped and corrupt/missing units
    are requeued.  With a ledger but ``resume=False`` its done records are
    trusted as-recorded (no re-hash, no duplicate catch-up appends) —
    ``--resume`` is the digest-verified path.  Without a ledger the skip
    probe still validates the existing OIM pickles (``_clip_done``)
    instead of trusting existence.
    A graceful stop (SIGTERM/SIGINT via ``disco_tpu.runs.interrupt``)
    finishes the in-flight chunk, drains scoring, flushes the ledger and
    returns the partial results — the run is then resumable.

    ``pipeline``: the corpus throughput engine
    (``disco_tpu.enhance.pipeline``) — on by default.  A background
    prefetcher loads and pads chunk N+1 while the device runs chunk N
    (ledger ``in_flight`` marks and the ``chunk_load``/``pre_dispatch``
    chaos seams move with the work, preserving crash-safe resume), the
    jitted batch inputs are donated to halve HBM churn, and each chunk's
    results come back in ONE batched complex-safe ``device_get`` instead
    of K×n_real lazy per-clip readbacks.  Artifacts are byte-identical to
    the sequential path (``make perf-check`` gates this); ``pipeline=False``
    (CLI ``--no-pipeline``) is the escape hatch.

    ``compile_cache``: persistent XLA compilation cache
    (``disco_tpu.utils.compile_cache``) so per-bucket programs compile once
    across runs/resumes instead of once per process.  ``None`` resolves the
    ``DISCO_TPU_COMPILE_CACHE`` env var then the default path (off on the
    tunneled attachment unless explicitly pointed at a directory);
    ``False`` disables; a string is the cache directory.

    ``chained``: each chunk rides ONE dispatched program over the raw
    (B, K, C, L) time stacks (the ``run_batch_chained`` runner — STFT,
    oracle masks, both MWF steps and the scoring ISTFTs in-program) and
    ONE batched readback, instead of the staged fused-STFT + batch-runner
    sequence.  Offline oracle lane only: CRNN ``models``, ``mesh`` and
    ``fault_spec`` are rejected, exactly as in :func:`enhance_rir`;
    ``solver=None`` then resolves to ``'fused'``.

    Returns {rir: results dict} for the RIRs actually processed
    (already-done ones are skipped — same idempotency contract).
    """
    if chained:
        if models != (None, None):
            raise ValueError(
                "chained=True computes oracle masks in-program; the CRNN "
                "mask lane needs host STFTs and stays on the staged path"
            )
        if mesh is not None:
            raise ValueError(
                "chained=True is a single-device lane; mesh runs stay on "
                "the staged STFT-stack runners"
            )
        if fault_spec is not None:
            raise ValueError(
                "chained=True has no z-exchange host seam to inject faults "
                "at; run fault scenarios on the staged path"
            )
    if solver is None:
        # offline default, measured (round-3 solver_ab); the chained lane
        # exists to compose with the batch-in-lanes fused solve
        solver = "fused" if chained else "power"
    import jax
    import jax.numpy as jnp

    from disco_tpu.core.dsp import bucket_length, n_stft_frames
    from disco_tpu.ops.stft_ops import stft_fused
    from disco_tpu.utils import compile_cache as _compile_cache

    _compile_cache.ensure_enabled(compile_cache)

    fault_plan = None
    z_mask_arr = z_nan_arr = None
    if fault_spec is not None:
        from disco_tpu.fault import plan_faults

        fault_plan = plan_faults(fault_spec, n_nodes, 1)
        fault_plan.record(mode="offline")
        if fault_plan.any_fault():
            z_mask_arr = fault_plan.avail_offline
            z_nan_arr = fault_plan.z_nan if fault_plan.z_nan.any() else None
        else:  # nothing drawn: keep every chunk on the fault-free fast path
            fault_plan = None

    out_base = out_root  # per-RIR dset split resolved below

    if ledger is not None and not isinstance(ledger, RunLedger):
        ledger = RunLedger(ledger)
    ledger_done: set = set()
    if resume:
        # A REAL crash (process death, not an exception unwind) can leave
        # abandoned *.tmp.<pid> partial writes; sweep them before probing.
        from disco_tpu.io.atomic import remove_tmp_litter

        roots = {
            str(Path(out_base) if out_base is not None
                else results_root(scenario, dset_of_rir(r), save_dir))
            for r in rirs
        }
        litter = [p for root in sorted(roots) for p in remove_tmp_litter(root)]
        if litter:
            obs_events.record(
                "warning", stage="resume",
                reason=f"removed {len(litter)} abandoned temp file(s) from a "
                       f"crashed writer", files=litter[:20],
            )
    requeued_units: set = set()
    if ledger is not None and resume:
        # Verified resume: done entries are re-checked against their
        # artifact digests; corrupt/missing units are requeued (loudly) and
        # fall through to the index pass below for re-enhancement.
        ledger_done, requeued = ledger.verified_done()
        requeued_units = set(requeued)
        obs_events.record(
            "run_resume", stage="enhance", ledger=str(ledger.path),
            n_done=len(ledger_done), n_requeued=len(requeued),
            requeued=sorted(requeued),
        )
    elif ledger is not None:
        # No verification requested: trust the ledger's own done records so
        # a plain rerun with --ledger neither re-hashes the done corpus nor
        # appends a duplicate catch-up line per clip (--resume is the
        # digest-verified path).
        ledger_done = {
            u for u, rec in ledger.replay().items() if rec["state"] == "done"
        }

    # -- index pass: group pending RIRs by bucketed length. Only ONE channel
    # is read here to learn the clip length; full audio is loaded per chunk
    # below, so corpus-scale runs never hold the whole split in RAM.
    groups: dict[int, list] = {}
    for rir in rirs:
        out = Path(out_base) if out_base is not None else results_root(scenario, dset_of_rir(rir), save_dir)
        if not force:
            if unit_rir(rir, noise) in ledger_done:
                continue
            # A unit the verified resume just REQUEUED must actually be
            # redone: its digest-level damage (e.g. a deleted WAV) may not
            # show in the pickle-only _clip_done probe, and "requeued" means
            # never trusted — the atomic re-enhance regenerates everything.
            if unit_rir(rir, noise) not in requeued_units and _clip_done(out, rir, noise):
                # Complete on disk but absent from (or unverified by) the
                # ledger — e.g. a crash landed between the final artifact
                # rename and the done append.  Catch the ledger up so the
                # next resume verifies by digest instead of re-probing.
                # (Membership in ledger_done was already ruled out above.)
                if ledger is not None:
                    ledger.mark_done(
                        unit_rir(rir, noise),
                        clip_artifacts(out, rir, noise, snr_range, n_nodes),
                        recovered="complete artifacts found without a done record",
                    )
                continue
        layout = DatasetLayout(root, scenario, case_of_rir(rir))
        probe = layout.wav_processed(snr_range, "mixture", rir, 1, noise=noise)
        if not probe.exists():
            continue
        L = len(read_wav(probe)[0])
        Lp = bucket_length(L, bucket) if bucket else L
        groups.setdefault(Lp, []).append((rir, out, layout))

    run_batch, run_batch_with_masks = make_batch_runners(
        mask_type=mask_type, mu=mu, policy=policy, solver=solver,
        cov_impl=cov_impl, precision=precision,
        z_mask_arr=z_mask_arr, z_nan_arr=z_nan_arr,
        n_nodes=n_nodes, mesh=mesh, chained=chained, stft_impl=stft_impl,
    )

    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from disco_tpu.enhance.pipeline import (
        MAX_PENDING_CHUNKS,
        ChunkPrefetcher,
        LoadedChunk,
        fetch_chained_host,
        fetch_chunk_host,
        note_chunk_overlap,
    )

    # Flat work list: one entry per (bucket, chunk) launch, in the same
    # bucket-then-offset order the sequential loop always used.
    work_items = [
        (Lp, items[start : start + max_batch])
        for Lp, items in groups.items()
        for start in range(0, len(items), max_batch)
    ]

    all_results = {}
    # Scoring backpressure: one future-list per chunk, bounded at
    # MAX_PENDING_CHUNKS (=2) chunks in flight — chunk N-1's scoring
    # overlaps chunk N's dispatch and chunk N+1's prefetch, instead of the
    # old drain() blocking the dispatch thread on every previous chunk
    # before the next could even load.
    pending_chunks: deque = deque()
    stopping = False  # graceful interruption: wind down between chunks

    def drain_chunks(bound: int = 0):
        while len(pending_chunks) > bound:
            for rir_, fut in pending_chunks.popleft():
                all_results[rir_] = fut.result()

    def score_unit(score_fn, rir_, out_):
        """One clip's scoring + ledger completion (runs on a worker)."""
        r = score_fn()
        if ledger is not None:
            ledger.mark_done(
                unit_rir(rir_, noise),
                clip_artifacts(out_, rir_, noise, snr_range, n_nodes),
            )
        return r

    def load_chunk(Lp, chunk):
        """Load + pad one chunk — host-only work (wav decode, numpy
        padding, ledger marks, chaos seams).  Runs on the prefetch thread
        in pipelined mode, inline otherwise; identical either way, so the
        two paths share crash/resume semantics by construction."""
        if ledger is not None:
            for rir, _out, _layout in chunk:
                ledger.mark_in_flight(unit_rir(rir, noise), bucket=Lp)
        run_chaos.tick("chunk_load", bucket=Lp, n_clips=len(chunk))
        with obs_events.stage("chunk_load", n_clips=len(chunk), bucket=Lp):
            sigs = [
                load_input_signals(layout, rir, noise, snr_range, n_nodes, mics_per_node)
                for rir, _, layout in chunk
            ]
        ys, ss, ns = [], [], []
        for (y, s, n, *_rest) in sigs:
            pad = ((0, 0), (0, 0), (0, Lp - y.shape[-1]))
            ys.append(np.pad(y, pad))
            ss.append(np.pad(s, pad))
            ns.append(np.pad(n, pad))
        # Remainder chunks pad to the next power of two, not to
        # max_batch (round-2 verdict #9: repeating clip 0 up to
        # 15/16 of a launch was discarded work on small splits).
        # Cost model: at most log2(max_batch) extra compiled
        # programs per length bucket, <2x padding waste vs up to
        # max_batch-x before.  Mesh runs keep the full batch — the
        # chunk size must stay divisible by the mesh 'batch' axis.
        n_real = len(ys)
        tail = max_batch if mesh is not None else min(
            max_batch, 1 << max(n_real - 1, 0).bit_length()
        )
        while len(ys) < tail:
            ys.append(ys[0]); ss.append(ss[0]); ns.append(ns[0])
        return LoadedChunk(Lp, chunk, sigs, np.stack(ys), np.stack(ss),
                           np.stack(ns), n_real)

    def dispatch_chunk(lc):
        """STFT + jitted batch launch (main thread — the only jax user).
        chunk_enhance wall time is dispatch-side only (jit returns before
        the device finishes); the recompile events and the fence deltas in
        score_persist carry the device-side story."""
        run_chaos.tick("pre_dispatch", bucket=lc.bucket, n_clips=lc.n_real)
        with obs_events.stage("chunk_enhance", n_clips=lc.n_real,
                              bucket=lc.bucket, batch=len(lc.ys)):
            if chained:
                # the whole chunk as ONE program over the raw time stacks:
                # STFT, masks, both MWF steps and the scoring ISTFTs are
                # inside run_batch_chained — nothing to stage here
                return run_batch(jnp.asarray(lc.ys), jnp.asarray(lc.ss),
                                 jnp.asarray(lc.ns))
            # one fused STFT program over the stacked y/s/n chunk (was
            # three separate stft dispatches); the batch runners compute
            # masks in-program, so the spec-only fused entry applies
            spec = stft_fused(
                jnp.asarray(np.stack([lc.ys, lc.ss, lc.ns])),
                impl=stft_impl, precision=precision,
            )
            Yb, Sb, Nb = spec[0], spec[1], spec[2]
            if models == (None, None):
                return run_batch(Yb, Sb, Nb)
            Mz, Mw = _batched_masks(Yb, Sb, Nb, models, mask_type, mu, n_nodes, z_sigs)
            return run_batch_with_masks(Yb, Sb, Nb, Mz, Mw)

    def submit_scoring(lc, res_b=None, host=None):
        """Queue (or run inline) one chunk's per-clip scoring.  Pipelined
        mode passes ``host`` (the single batched readback of
        ``fetch_chunk_host``); the sequential path passes the device
        ``res_b`` and scores from lazy per-clip slices as before."""
        futs = []
        for i in range(lc.n_real):
            rir, out, layout = lc.chunk[i]
            y, s, n, s_dry, n_dry, fs, rnd_snrs = lc.sigs[i]
            _record_degraded(fault_plan, rir=rir)
            L = y.shape[-1]
            if host is not None:
                res_i = TangoResult(
                    yf=None, sf=None, nf=None,
                    z_y=host["z_y"][i], z_s=None, z_n=None, zn=None,
                    masks_z=host["masks_z"][i], mask_w=host["mask_w"][i],
                )
                td_i = host["td"][i]
            else:
                res_i = jax.tree_util.tree_map(lambda x: x[i], res_b)
                td_i = None
            score = partial(
                _persist_and_score,
                out, layout, rir, noise, snr_range, y, s, n, s_dry, n_dry,
                fs, rnd_snrs, res_i, L, n_stft_frames(L), n_nodes, save_fig,
                time_domain=td_i,
            )
            if score_workers <= 1:
                all_results[rir] = score_unit(score, rir, out)
            else:
                futs.append((rir, ex.submit(score_unit, score, rir, out)))
        if futs:
            pending_chunks.append(futs)
            drain_chunks(MAX_PENDING_CHUNKS)

    with ThreadPoolExecutor(max_workers=max(score_workers, 1)) as ex:
        if pipeline:
            prefetcher = ChunkPrefetcher(
                work_items, load_chunk, stop_requested=run_interrupt.stop_requested
            )
            n_done_chunks = 0
            try:
                for lc, stall_s in prefetcher:
                    if run_interrupt.stop_requested():
                        # Graceful stop: the prefetcher stops feeding, no
                        # new chunk is dispatched; in-flight scoring drains
                        # below, its done records land in the ledger, and
                        # the partial results return — resumable by
                        # construction (prefetched-but-undone chunks are
                        # in_flight in the ledger, so resume redoes them).
                        stopping = True
                        break
                    t0 = time.perf_counter()
                    with obs_events.stage("chunk_pipeline", n_clips=lc.n_real,
                                          bucket=lc.bucket,
                                          stall_ms=round(stall_s * 1e3, 3)):
                        res_b = dispatch_chunk(lc)
                        fetch = fetch_chained_host if chained else fetch_chunk_host
                        host = fetch(res_b, lc.clip_lengths, lc.n_real)
                        submit_scoring(lc, host=host)
                    note_chunk_overlap(stall_s, time.perf_counter() - t0)
                    n_done_chunks += 1
                # The PREFETCHER can also be the one that observes a stop
                # (it polls the flag before each load and then ends the
                # stream): the loop above then exits normally with work
                # items never loaded.  That is still a partial run — the
                # resume note below must fire either way.
                if n_done_chunks < len(work_items):
                    stopping = True
            finally:
                prefetcher.close()
        else:
            for Lp, chunk in work_items:
                if run_interrupt.stop_requested():
                    stopping = True
                    break
                lc = load_chunk(Lp, chunk)
                res_b = dispatch_chunk(lc)
                if chained:
                    # the chained payload is a whole-chunk export dict, not
                    # a sliceable TangoResult — score from the same single
                    # batched readback the pipelined path uses
                    submit_scoring(
                        lc,
                        host=fetch_chained_host(res_b, lc.clip_lengths,
                                                lc.n_real),
                    )
                else:
                    submit_scoring(lc, res_b=res_b)
        drain_chunks()
    if stopping:
        obs_events.record(
            "note", stage="enhance",
            reason="graceful stop: partial corpus run; rerun with resume=True "
                   "(--resume) to continue",
            n_done=len(all_results),
        )
    if obs_events.enabled():
        obs_events.record("counters", **obs_registry.snapshot())
    return all_results
