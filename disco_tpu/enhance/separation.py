"""Source separation over the distributed array — the MEETIT use case
(reference gen_meetit + ICASSP 2021 setup, SURVEY.md §0 pillar 3).

The reference generates per-node per-source IRMs (gen_meetit
convolve_signals.py:166-189) and separates by running the same two-step
MWF machinery once per source.  Here that is a first-class API: one
``vmap`` over the source axis of the jitted TANGO pipeline — sources,
nodes, frequencies and frames are all array axes.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from disco_tpu.core.masks import tf_mask
from disco_tpu.enhance.tango import tango


@partial(jax.jit, static_argnames=("policy", "mask_type", "ref_mic"))
def separate_sources(Y, S_imgs, mu: float = 1.0, policy="distant", mask_type: str = "irm1", ref_mic: int = 0):
    """Oracle-mask separation: extract every source at every node.

    Args:
      Y: (K, C, F, T) mixture STFTs.
      S_imgs: (n_src, K, C, F, T) per-source image STFTs (sum = Y's signal
        part); source s's interference is ``Y - S_imgs[s]``.

    Returns:
      (n_src, K, F, T) complex estimates: source s as extracted by node k.
    """
    def one(S):
        N = Y - S
        m = tf_mask(S[:, ref_mic], N[:, ref_mic], mask_type)
        return tango(Y, S, N, m, m, mu=mu, policy=policy, ref_mic=ref_mic, mask_type=mask_type).yf

    return jax.vmap(one)(S_imgs)


@partial(jax.jit, static_argnames=("policy", "mask_type", "ref_mic"))
def separate_with_masks(Y, masks, mu: float = 1.0, policy="distant", mask_type: str = "irm1", ref_mic: int = 0):
    """Mask-driven separation (deployment path — no oracle images needed).

    Args:
      Y: (K, C, F, T) mixture STFTs.
      masks: (n_src, K, F, T) per-source per-node TF masks (e.g. CRNN
        estimates, or the saved MEETIT IRMs).

    Returns:
      (n_src, K, F, T) complex per-source estimates.
    """
    if policy not in ("local", "none", "distant", None):
        # oracle/compressed policies need clean components, which the
        # mask-only path replaces with zeros (-> NaN/degenerate statistics)
        raise ValueError(
            f"separate_with_masks supports policies 'local'/'none'/'distant'; got {policy!r}"
        )
    Z = jnp.zeros_like(Y)

    def one(m):
        return tango(Y, Z, Z, m, m, mu=mu, policy=policy, ref_mic=ref_mic, mask_type=mask_type).yf

    return jax.vmap(one)(masks)
