"""Opt-in numerics watchdogs at stage boundaries.

Round-2's hardware-correctness lesson (README "hardware-correctness note"):
TPU bf16 matmul accumulation left frame-mean covariances indefinite and
poisoned step-2 GEVDs with NaN bins that CPU tests never saw — the failure
mode was *silent propagation*.  :func:`check_finite` is the guard the
pipeline calls at its stage seams (post-STFT, post-mask, post-MWF,
post-ISTFT in ``enhance/driver.py``): when recording is enabled it pulls the
tensor to host, and on any non-finite value records a ``sentinel`` event
naming the offending stage with tensor stats, instead of letting the NaN
surface three stages later as a mysteriously zero metric.

Strictly opt-in: with the recorder disabled (the default) each check is one
attribute read — in particular it does NOT force a device sync, so the
jitted pipeline's async dispatch is untouched.  When enabled, each checked
tree leaf costs one host readback (counted as a fence — on the tunnel that
is the ~80 ms unit of cost, which is why these live at clip-level stage
boundaries and not inside kernels).

No reference counterpart: the reference lets NaNs propagate silently.
"""
from __future__ import annotations

import numpy as np

from disco_tpu.obs import accounting as _accounting
from disco_tpu.obs import events as _events
from disco_tpu.obs import metrics as _metrics

_CHECKS = _metrics.REGISTRY.counter("sentinel_checks")
_TRIPS = _metrics.REGISTRY.counter("sentinel_trips")


def _narrow_float(dtype: np.dtype) -> bool:
    """True for sub-f32 float dtypes whose reductions must NOT run in
    their own arithmetic: f16 (kind 'f') and the ml_dtypes extension
    floats bf16/f8 (kind 'V' — numpy exposes registered custom dtypes as
    void-kind).  Ints/bools/f32/f64 pass through untouched."""
    return (dtype.kind in ("f", "V")) and dtype.itemsize < 4


def _leaf_stats(arr: np.ndarray) -> dict:
    """Summary stats of one host array, split finite / non-finite.  Complex
    input: ``np.isfinite`` is False if either component is non-finite, and
    magnitude stats are reported on ``abs``.  Narrow floats (bf16/f16 —
    the PR-9 ``precision='bf16'`` compute lane) are upcast to float32
    BEFORE reduction: the stats must use f32 accumulators, not inherit the
    checked tensor's 8-bit-mantissa arithmetic (a bf16 mean over a long
    tensor is itself wrong-but-plausible — exactly what a sentinel exists
    to rule out)."""
    mag = np.abs(arr) if np.iscomplexobj(arr) else arr
    if _narrow_float(mag.dtype):
        mag = mag.astype(np.float32)
    finite = np.isfinite(mag)
    n_bad = int(arr.size - finite.sum())
    stats = {
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "n_nonfinite": n_bad,
        "frac_nonfinite": n_bad / arr.size if arr.size else 0.0,
        "n_nan": int(np.isnan(mag).sum()),
        "n_inf": int(np.isinf(mag).sum()),
    }
    if finite.any():
        fm = mag[finite]
        stats["finite_absmax"] = float(np.max(np.abs(fm)))
        stats["finite_mean"] = float(np.mean(fm))
    return stats


def check_finite(name: str, tree, stage: str | None = None,
                 precision: str | None = None) -> bool:
    """Record a ``sentinel`` event for every non-finite leaf of ``tree``.

    Args:
      name: what is being checked ("stft_Y", "mwf_yf", ...).
      tree: array / pytree of arrays (device or host).
      stage: pipeline stage to attribute a trip to (defaults to ``name``).
      precision: the ACTIVE compute-lane precision ("f32"/"bf16" —
        ``ops.resolve``); carried in the sentinel event's attrs so a trip
        under the opt-in bf16 lane (PR 9) is attributable to the lane, not
        misread as an f32 pipeline bug.

    Returns True when every leaf is finite (always True when NO event sink
    is live — neither the JSONL recorder nor the flight ring
    (``events.active()``); the check is skipped entirely, so the default
    pipeline's async dispatch is untouched.  Observability must never
    change pipeline behavior: this *records*, it does not raise).  A check
    that tripped also triggers ONE flight-recorder dump when a dump dir is
    armed (``obs.flight`` — the non-finite tensor's recent causal context
    is exactly what the post-mortem needs).
    """
    if not _events.active():
        return True
    import jax

    from disco_tpu.utils.resilience import resilient_to_host

    ok = True
    tripped: list[str] = []
    leaves = jax.tree_util.tree_leaves(tree)
    for i, leaf in enumerate(leaves):
        # Device arrays: to_host (complex dtypes cannot cross the Axon tunnel
        # directly, CLAUDE.md) under bounded retry — a watchdog readback
        # dropped by the tunnel must not kill the run it observes.  The
        # readback is fenced — count it: two round-trips for complex
        # (to_host splits into real+imag transfers, utils/transfer.py), one
        # for real.  Host arrays are free: checking them must not inflate
        # the RPC estimate.
        if isinstance(leaf, jax.Array):
            arr = np.asarray(resilient_to_host(leaf, label="sentinel_readback"))
            _accounting.fence_tick(2 if np.iscomplexobj(arr) else 1)
        else:
            arr = np.asarray(leaf)
        _CHECKS.inc()
        mag = np.abs(arr) if np.iscomplexobj(arr) else arr
        if _narrow_float(mag.dtype):
            mag = mag.astype(np.float32)  # f32 accumulators for bf16/f16 lanes
        if not np.isfinite(mag).all():
            ok = False
            _TRIPS.inc()
            leaf_name = name if len(leaves) == 1 else f"{name}[{i}]"
            tripped.append(leaf_name)
            extra = {"precision": precision} if precision is not None else {}
            _events.record(
                "sentinel",
                stage=stage or name,
                name=leaf_name,
                **extra,
                **_leaf_stats(arr),
            )
    if tripped:
        # ONE dump per check, after the loop: a fully-diverged pytree must
        # not serialize the ring once per leaf on the very path that just
        # detected numerical distress
        from disco_tpu.obs import flight as _flight

        _flight.auto_dump(
            "sentinel",
            reason=f"non-finite {', '.join(tripped)} at {stage or name}",
        )
    return ok
