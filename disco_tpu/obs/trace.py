"""Causal tracing: end-to-end spans from a client block to its tap shard.

The serve/soak/flywheel stack (PRs 5-12) retries, quarantines, parks,
degrades and taps traffic — but until this module the telemetry was flat:
when a session landed in QUARANTINED or a soak campaign flagged a slow
tick, no event said *which* client block, *which* scheduler tick and
*which* dispatch/readback caused it.  This module is the missing causal
spine: a ``trace_id``/``span_id``/``parent_id`` triple is minted at client
block submission (:func:`root`), carried in the ``block`` protocol frame
(``frame["trace"]`` — absent for pre-span clients, which are served
unchanged), and advanced one hop at a time (:func:`span`) through

    client_block → enqueue → dispatch → readback → deliver → tap
                                                           → train_batch

Each hop is one ``span`` obs event (kind registered in
:data:`~disco_tpu.obs.events.EVENT_KINDS`) whose ``stage`` names the hop
(the closed set :data:`SPAN_STAGES` — disco-lint DL014 checks call-site
literals against it) and whose attrs carry ``trace``/``span``/``parent``
plus per-hop attribution (queue wait at dispatch, readback duration,
delivery latency).  ``disco-obs trace <log> <trace_id>`` renders the chain
as a waterfall; :func:`chain` is the reconstruction primitive the
``scope-check`` gate uses to prove every delivered frame has a complete
causal chain.

Contract (the :class:`~disco_tpu.obs.events.Recorder` discipline): the
process-global :class:`Tracer` is a **strict no-op while disabled** — every
entry point returns after one attribute check, so the serve hot path pays
nothing (``bench.py`` measures this as ``span_overhead_ns``).  When
enabled, spans flow to the JSONL event log (if recording is on) and to the
flight recorder ring (:mod:`disco_tpu.obs.flight`, if armed) — either sink
alone works.  This module is **stdlib-only** (no jax, no numpy): the
numpy-only serve client mints ids through it, so it carries the client
purity contract (disco-lint DL005).

No reference counterpart: the reference has no serving layer and no
telemetry of any kind (SURVEY.md §5.1); the span model follows the
industry-standard distributed-tracing triple (OpenTelemetry-style
trace/span/parent) sized down to the repo's dependency-free JSONL log.
"""
from __future__ import annotations

import dataclasses
import threading
import time
import uuid

from disco_tpu.obs import events as _events

#: The closed set of span stages (hop names).  disco-lint rule DL014 checks
#: every ``span("<stage>", ...)`` / ``root("<stage>")`` string literal
#: against this registry — a typo'd hop would otherwise break every chain
#: reconstruction that expects the canonical hop names.  Extend
#: deliberately: ``disco-obs trace`` orders its waterfall by this sequence.
SPAN_STAGES = frozenset(
    {
        "client_block",  # root: one input block submitted by a serve client
        "enqueue",       # scheduler accepted the block into a session queue
        "dispatch",      # the block's device program was queued (per super-tick group)
        "readback",      # the tick's ONE batched readback brought it host-side
        "deliver",       # the enhanced block was handed to the connection writer
        "tap",           # the corpus tap spooled the delivered tuple
        "train_batch",   # a ShardDataset read the tapped record into training windows
        "promote_stage",   # root of a promotion rollout: candidate staged
        "promote_canary",  # canary sessions assigned onto the candidate
        "promote_gate",    # the SDR/SLO gate verdict was computed
        "promote_swap",    # the rollout's terminal swap (promote or rollback)
    }
)

#: Canonical hop order for waterfall rendering and chain validation (the
#: serve chain; ``train_batch`` happens in a later process and is ordered
#: last when present).
STAGE_ORDER = ("client_block", "enqueue", "dispatch", "readback", "deliver",
               "tap", "train_batch",
               # the promotion-rollout chain is its own waterfall (a rollout,
               # not a block, is the traced unit — promote/controller.py)
               "promote_stage", "promote_canary", "promote_gate",
               "promote_swap")

#: Bound on tracked in-flight spans (the ``status`` frame's inflight
#: section); beyond it new entries are dropped, never an error.
MAX_INFLIGHT = 4096


@dataclasses.dataclass(frozen=True)
class SpanCtx:
    """One trace's moving head: the trace id plus the id of the most recent
    hop (the parent of the next hop).  Immutable — every hop returns an
    advanced copy, so a failed dispatch's retry re-advances from the same
    parent instead of chaining onto the failed attempt.

    No reference counterpart (module docstring)."""

    trace: str
    span: str

    def to_wire(self) -> dict:
        """The protocol-frame / shard-record representation."""
        return {"trace": self.trace, "span": self.span}


def new_id() -> str:
    """A fresh 64-bit hex span/trace id (uuid4-derived — unique across the
    client and server processes that share one trace).

    No reference counterpart (module docstring)."""
    return uuid.uuid4().hex[:16]


def from_wire(d) -> SpanCtx | None:
    """Validate a wire-decoded ``frame["trace"]`` dict into a
    :class:`SpanCtx`; None for absent/malformed headers (a pre-span client
    MUST be served unchanged, so a bad header degrades to untraced, never
    raises).

    No reference counterpart (module docstring)."""
    if not isinstance(d, dict):
        return None
    trace, span = d.get("trace"), d.get("span")
    if not isinstance(trace, str) or not isinstance(span, str):
        return None
    if not trace or not span or len(trace) > 64 or len(span) > 64:
        return None
    return SpanCtx(trace=trace, span=span)


class Tracer:
    """Process-global span sink (the :class:`~disco_tpu.obs.events.Recorder`
    contract): strict no-op while disabled, one attribute check per call.

    When enabled, each hop records a ``span`` event through the obs
    recorder (sideband JSONL when recording is on, flight ring when the
    flight recorder is armed — :mod:`disco_tpu.obs.events` fans out) and
    maintains the bounded in-flight table the serve ``status`` frame
    reports.
    """

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        #: {key: {"trace", "stage", "session", "seq", "t"}} — blocks whose
        #: chain has started but not reached ``deliver`` yet
        self._inflight: dict = {}
        self.spans_recorded = 0

    def enable(self) -> None:
        with self._lock:
            self._inflight.clear()
            self.enabled = True

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self._inflight.clear()

    # -- span recording ------------------------------------------------------
    def root(self, stage: str = "client_block", **attrs) -> SpanCtx | None:
        """Mint a new trace and record its root span (parent null).  None
        while disabled — callers thread the None through and every later
        hop no-ops."""
        if not self.enabled:
            return None
        ctx = SpanCtx(trace=new_id(), span=new_id())
        self._record(stage, ctx, parent=None, **attrs)
        return ctx

    def span(self, stage: str, ctx: SpanCtx | None, **attrs) -> SpanCtx | None:
        """Record one hop: mints a child span id under ``ctx`` and returns
        the advanced context.  No-op (returns ``ctx`` unchanged) while
        disabled or when ``ctx`` is None (an untraced block)."""
        if not self.enabled or ctx is None:
            return ctx
        child = SpanCtx(trace=ctx.trace, span=new_id())
        self._record(stage, child, parent=ctx.span, **attrs)
        return child

    def record_span(self, stage: str, ctx: SpanCtx | None, *,
                    parent: str | None, **attrs) -> None:
        """Record a hop for an ALREADY-minted context (mint-then-commit:
        the corpus tap mints its span id into the shard record first and
        records the event only once the spool accepted the block — a
        dropped block must never log a hop it did not take)."""
        if not self.enabled or ctx is None:
            return
        self._record(stage, ctx, parent=parent, **attrs)

    def _record(self, stage: str, ctx: SpanCtx, parent: str | None, **attrs):
        with self._lock:
            # spans flow in from the I/O thread (client block arrival), the
            # dispatch thread (enqueue/dispatch/readback hops) and main —
            # += alone drops counts exactly like the metrics Counter would
            self.spans_recorded += 1
        _events.record("span", stage=stage, trace=ctx.trace, span=ctx.span,
                       parent=parent, **attrs)

    # -- in-flight table (the status frame's live view) ----------------------
    def inflight_begin(self, key, ctx: SpanCtx | None, stage: str,
                       **info) -> None:
        """Track one block's chain as in flight (bounded; overflow drops).

        No reference counterpart (module docstring)."""
        if not self.enabled or ctx is None:
            return
        with self._lock:
            if len(self._inflight) >= MAX_INFLIGHT and key not in self._inflight:
                return
            self._inflight[key] = {"trace": ctx.trace, "stage": stage,
                                   "t": time.time(), **info}

    def inflight_update(self, key, stage: str) -> None:
        """Advance an in-flight block's current stage.

        No reference counterpart (module docstring)."""
        if not self.enabled:
            return
        with self._lock:
            entry = self._inflight.get(key)
            if entry is not None:
                entry["stage"] = stage

    def inflight_end(self, key) -> None:
        """The block reached delivery: drop it from the live table.

        No reference counterpart (module docstring)."""
        if not self.enabled:
            return
        with self._lock:
            self._inflight.pop(key, None)

    def inflight_snapshot(self, limit: int = 32) -> dict:
        """{"count", "oldest_s", "spans": [...]} — the ``status`` frame's
        inflight section (``spans`` capped at ``limit`` oldest-first).

        No reference counterpart (module docstring)."""
        now = time.time()
        with self._lock:
            entries = sorted(self._inflight.items(), key=lambda kv: kv[1]["t"])
        spans = [
            {"key": list(k) if isinstance(k, tuple) else k,
             "age_s": round(now - v["t"], 6),
             **{kk: vv for kk, vv in v.items() if kk != "t"}}
            for k, v in entries[:limit]
        ]
        return {
            "count": len(entries),
            "oldest_s": round(now - entries[0][1]["t"], 6) if entries else None,
            "spans": spans,
        }


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-global :class:`Tracer`.

    No reference counterpart (module docstring)."""
    return _TRACER


def enabled() -> bool:
    """True while causal tracing is on.

    No reference counterpart (module docstring)."""
    return _TRACER.enabled


def enable() -> None:
    """Turn on causal tracing process-wide (``disco-serve --trace``,
    the scope-check gate).

    No reference counterpart (module docstring)."""
    _TRACER.enable()


def disable() -> None:
    """Turn causal tracing off (back to the strict no-op contract).

    No reference counterpart (module docstring)."""
    _TRACER.disable()


def root(stage: str = "client_block", **attrs) -> SpanCtx | None:
    """Module-level :meth:`Tracer.root` on the process-global tracer.

    No reference counterpart (module docstring)."""
    return _TRACER.root(stage, **attrs)


def span(stage: str, ctx: SpanCtx | None, **attrs) -> SpanCtx | None:
    """Module-level :meth:`Tracer.span` on the process-global tracer.

    No reference counterpart (module docstring)."""
    return _TRACER.span(stage, ctx, **attrs)


def record_span(stage: str, ctx: SpanCtx | None, *, parent: str | None,
                **attrs) -> None:
    """Module-level :meth:`Tracer.record_span` on the process-global
    tracer (the mint-then-commit form).

    No reference counterpart (module docstring)."""
    _TRACER.record_span(stage, ctx, parent=parent, **attrs)


# -- reconstruction (the jax-free reader side: cli/obs.py, scope-check) ------
def spans_of(events: list, trace_id: str) -> list:
    """Every ``span`` event of one trace, in record order.

    No reference counterpart (module docstring)."""
    return [e for e in events
            if e.get("kind") == "span" and e["attrs"].get("trace") == trace_id]


def trace_ids(events: list) -> list:
    """Distinct trace ids in first-appearance order (the ``disco-obs trace
    <log>`` listing).

    No reference counterpart (module docstring)."""
    seen: dict = {}
    for e in events:
        if e.get("kind") == "span":
            seen.setdefault(e["attrs"].get("trace"), None)
    return [t for t in seen if t]


def chain(events: list, trace_id: str, *, end_stage: str | None = None) -> list:
    """Reconstruct one trace's causal chain by walking ``parent`` links
    backward from its terminal span; returns the spans root-first.

    ``end_stage`` picks the terminal hop explicitly (e.g. ``"deliver"`` for
    the serve chain, ``"tap"`` when the corpus tap ran); default: the last
    recorded span of the trace.  Spans off the main path — a failed
    dispatch attempt whose retry re-chained from the same parent — are
    left out by construction: the walk only follows the surviving links.
    Raises :class:`ValueError` when a parent link is dangling (a broken
    chain must fail loudly — scope-check turns this into a gate failure).

    No reference counterpart (module docstring).
    """
    spans = spans_of(events, trace_id)
    if not spans:
        raise ValueError(f"trace {trace_id!r}: no span events")
    by_id = {e["attrs"]["span"]: e for e in spans}
    if end_stage is not None:
        tails = [e for e in spans if e["stage"] == end_stage]
        if not tails:
            raise ValueError(
                f"trace {trace_id!r}: no {end_stage!r} span — the chain "
                f"never reached its terminal hop "
                f"(stages seen: {sorted({e['stage'] for e in spans})})"
            )
        tail = tails[-1]
    else:
        tail = spans[-1]
    path = [tail]
    seen = {tail["attrs"]["span"]}
    while path[-1]["attrs"].get("parent") is not None:
        parent = path[-1]["attrs"]["parent"]
        if parent not in by_id:
            if path[-1]["stage"] in ("enqueue", "train_batch"):
                # legitimate cross-process chain heads: an enqueue span's
                # parent is the client's root (it lives in the CLIENT
                # process's log), and a train_batch span's parent is the
                # tap span (it lives in the SERVER process's log) — a
                # single-process log starts its view of the trace here
                break
            raise ValueError(
                f"trace {trace_id!r}: span {path[-1]['attrs']['span']} names "
                f"parent {parent} but no such span was recorded — broken chain"
            )
        if parent in seen:
            raise ValueError(f"trace {trace_id!r}: parent cycle at {parent}")
        seen.add(parent)
        path.append(by_id[parent])
    return list(reversed(path))


def verify_chain(events: list, trace_id: str, *, require: tuple,
                 end_stage: str | None = None) -> list:
    """:func:`chain` plus a stage-coverage assertion: the reconstructed
    path must visit every stage in ``require`` (order-checked against
    :data:`STAGE_ORDER`).  Returns the chain; raises :class:`ValueError`
    with the missing/misordered hops named — the scope-check failure shape.

    No reference counterpart (module docstring).
    """
    path = chain(events, trace_id, end_stage=end_stage or (require[-1] if require else None))
    stages = [e["stage"] for e in path]
    missing = [s for s in require if s not in stages]
    if missing:
        raise ValueError(
            f"trace {trace_id!r}: chain missing hop(s) {missing} "
            f"(got {stages})"
        )
    order = [STAGE_ORDER.index(s) for s in stages if s in STAGE_ORDER]
    if order != sorted(order):
        raise ValueError(
            f"trace {trace_id!r}: hops out of causal order: {stages}"
        )
    return path


def render_waterfall(events: list, trace_id: str, width: int = 40) -> str:
    """The ``disco-obs trace`` waterfall: one line per hop with its offset
    from the root span, per-hop attribution (queue wait / readback duration
    / delivery latency) and a proportional bar.

    No reference counterpart (module docstring).
    """
    path = chain(events, trace_id)
    t0 = path[0]["t"]
    t_end = max(e["t"] for e in path)
    total = max(t_end - t0, 1e-9)
    lines = [f"trace {trace_id}  ({len(path)} hops, "
             f"{total * 1e3:.2f} ms client-to-tail)"]
    lines.append(f"{'hop':<14}{'+ms':>10}  {'attribution':<28} waterfall")
    for e in path:
        off = e["t"] - t0
        a = e["attrs"]
        attribution = ""
        for key, label in (("wait_ms", "queue-wait"), ("readback_ms", "readback"),
                           ("latency_ms", "latency"), ("dur_ms", "dur")):
            if a.get(key) is not None:
                attribution += f"{label}={a[key]:.2f}ms "
        if a.get("failed"):
            attribution += f"FAILED: {a.get('error', '?')} "
        if a.get("tick") is not None:
            attribution += f"tick={a['tick']} "
        pos = int(off / total * (width - 1))
        bar = "." * pos + "#"
        lines.append(f"{e['stage']:<14}{off * 1e3:>10.2f}  {attribution:<28} {bar}")
    sess = next((e["attrs"].get("session") for e in path
                 if e["attrs"].get("session") is not None), None)
    seq = next((e["attrs"].get("seq") for e in path
                if e["attrs"].get("seq") is not None), None)
    if sess is not None:
        lines.append(f"session={sess}  seq={seq}")
    return "\n".join(lines)
